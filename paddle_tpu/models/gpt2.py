"""GPT-2-style decoder-only LM (BASELINE.json config 5: "ERNIE / GPT-2
345M (TP+DP on TPU mesh via DistributeTranspiler->GSPMD)").

Pre-LN causal transformer: x + attn(ln(x)), x + ffn(ln(x)); by default a
GELU MLP, learned positions, untied LM head — with modern-decoder
options on GPT2Config: n_kv_head (grouped-query attention), use_rotary
(RoPE instead of the position table), use_swiglu (gated SiLU FFN:
ffn_gate.w/ffn_up.w replace ffn_in.w), tie_embeddings (logits reuse
emb.w; no softmax_out.w exists).  Attention always goes through the
fused_attention op with causal=True — no [T, T] mask tensor ever exists
in the program (the op's flash kernel runs under FLAGS_use_pallas, fused
XLA otherwise).  Parameter names reuse the transformer TP patterns
(mha_[qkv].w / mha_o.w / ffn_in.w or ffn_gate.w+ffn_up.w / ffn_out.w /
emb.w / softmax_out.w) so `parallel.transformer_tp_rules` shards every
option combination unchanged on a {dp, mp} mesh.
"""

import numpy as np

from .. import layers, unique_name
from ..initializer import Normal
from ..param_attr import ParamAttr

__all__ = [
    "GPT2Config",
    "gpt2_lm",
    "gpt2_lm_program",
    "gpt2_logits_program",
    "greedy_generate",
    "greedy_generate_cached",
    "beam_generate_cached",
    "sample_generate_cached",
    "gpt2_decode_step_program",
    "gpt2_ragged_step_program",
    "prefill_cached_chunked",
    "speculative_generate_cached",
    "speculative_sample_generate_cached",
    "beam_generate",
    "make_fake_lm_batch",
]


class GPT2Config:
    """gpt2-small shape defaults (345M config: d_model=1024, n_layer=24,
    n_head=16); subclass to shrink for tests."""

    vocab_size = 50257
    n_ctx = 1024
    d_model = 768
    n_layer = 12
    n_head = 12
    n_kv_head = None  # < n_head enables grouped-query attention (MQA at 1)
    use_rotary = False  # RoPE on q/k instead of the learned position table
    use_swiglu = False  # gated SiLU FFN (2/3 width) instead of gelu MLP
    ffn_multiple_of = 1  # round the SwiGLU hidden up (128/256 aligns
    # the lane dim and keeps TP divisibility; 1 = exact 2/3 sizing)
    tie_embeddings = False  # output logits reuse emb.w (x @ emb.w^T)
    dropout = 0.1
    recompute = False  # rematerialize each block's activations in backward
    # which parallel.partition_rules family table shards this model's
    # persistables (weights AND the serving slot-pool caches) on a
    # tensor-parallel mesh — ServingEngine(mesh=...) resolves it
    partition_family = "gpt2"


def _pa(base, std=0.02):
    return ParamAttr(
        name=unique_name.generate(base), initializer=Normal(0.0, std)
    )


def _attn(x, hp, is_test, cache=None):
    """Causal self-attention via the shared transformer block (same graph,
    same mha_* param names, one fused-path implementation to maintain).
    With `cache`, x is the single current token and causality comes from
    the cache's <=pos mask instead of the causal flag."""
    from . import transformer as tfm

    return tfm.multi_head_attention(
        x, x, x, None, hp.d_model, hp.n_head, dropout_rate=0.0,
        is_test=is_test, fused=True, causal=cache is None, cache=cache,
        n_kv_head=getattr(hp, "n_kv_head", None),
        rotary=getattr(hp, "use_rotary", False),
    )


def _block(x, hp, is_test, cache=None):
    """One decoder block — the SAME function builds the training graph and
    the KV-cached decode step, so the parameter-creation order (and with
    it, weight sharing by name) holds by construction."""
    a = _attn(layers.layer_norm(x, begin_norm_axis=2), hp, is_test, cache)
    if hp.dropout and not is_test:
        a = layers.dropout(a, hp.dropout, is_test=is_test)
    x = layers.elementwise_add(x, a)
    ln = layers.layer_norm(x, begin_norm_axis=2)
    if getattr(hp, "use_swiglu", False):
        # SwiGLU: silu(xW_g) * xW_u -> W_out, hidden at 2/3 of 4*d so
        # the parameter count matches the gelu MLP (the standard sizing)
        hid = int(4 * hp.d_model * 2 // 3)
        mult = int(getattr(hp, "ffn_multiple_of", 1) or 1)
        hid = ((hid + mult - 1) // mult) * mult
        gate = layers.fc(ln, size=hid, num_flatten_dims=2,
                         act="swish", bias_attr=False,
                         param_attr=_pa("ffn_gate.w"))
        up = layers.fc(ln, size=hid, num_flatten_dims=2, bias_attr=False,
                       param_attr=_pa("ffn_up.w"))
        h = layers.elementwise_mul(gate, up)
    else:
        h = layers.fc(
            ln, size=4 * hp.d_model, num_flatten_dims=2, act="gelu",
            param_attr=_pa("ffn_in.w"), bias_attr=_pa("ffn_in.b"),
        )
    h = layers.fc(h, size=hp.d_model, num_flatten_dims=2,
                  param_attr=_pa("ffn_out.w"))
    if hp.dropout and not is_test:
        h = layers.dropout(h, hp.dropout, is_test=is_test)
    return layers.elementwise_add(x, h)


def _tied_logits(x, hp, emb_name):
    """Output projection: x @ emb.w^T when tie_embeddings (saves the
    [vocab, d] output matrix and couples input/output token geometry),
    else a separate softmax_out.w."""
    if getattr(hp, "tie_embeddings", False):
        from .. import framework

        w = framework.default_main_program().global_block().var(emb_name)
        return layers.matmul(x, w, transpose_y=True)
    return layers.fc(x, size=hp.vocab_size, num_flatten_dims=2,
                     bias_attr=False, param_attr=_pa("softmax_out.w"))


def gpt2_lm(ids, hp=GPT2Config, is_test=False):
    """[B, T] token ids -> [B, T, vocab] next-token logits."""
    emb_attr = _pa("emb.w")
    tok = layers.embedding(
        ids, size=[hp.vocab_size, hp.d_model], param_attr=emb_attr
    )
    if getattr(hp, "use_rotary", False):
        x = tok  # positions enter via RoPE on q/k inside attention
    else:
        pos_table = layers.create_parameter(
            shape=[hp.n_ctx, hp.d_model], dtype="float32",
            attr=_pa("pos_emb.w", 0.01)
        )
        T = ids.shape[1]
        pos = layers.slice(pos_table, axes=[0], starts=[0], ends=[T])
        x = layers.elementwise_add(tok, pos, axis=1)
    if hp.dropout and not is_test:
        x = layers.dropout(x, hp.dropout, is_test=is_test)
    for _ in range(hp.n_layer):
        if getattr(hp, "recompute", False) and not is_test:
            x = layers.recompute(lambda h: _block(h, hp, is_test), x)
        else:
            x = _block(x, hp, is_test)
    x = layers.layer_norm(x, begin_norm_axis=2)
    return _tied_logits(x, hp, emb_attr.name)


def gpt2_lm_program(hp=GPT2Config, seq_len=128, lr=3e-4, is_test=False,
                    use_bf16=False, mesh=None):
    """Build (main, startup, feeds, [loss, token_count]) for causal-LM
    training.  Feeds: ids/labels [B, T] int64, loss_weight [B, T] float.

    Built under unique_name.guard(): parameter names are deterministic, so
    a logits program built later in the same process shares weights with
    this one through the scope by name (the train->generate workflow).

    `mesh` stamps the program for GSPMD tensor-parallel training: the
    gpt2-family rule table lifted to training names (grads + Adam
    moments shard like their param — ZeRO-style sharded optimizer
    state), batch feeds over the mesh's dp axis.  No model edits — the
    executor's _run_spmd path picks the stamp up."""
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup), unique_name.guard():
        ids = layers.data("ids", shape=[seq_len], dtype="int64")
        lbl = layers.data("labels", shape=[seq_len], dtype="int64")
        w = layers.data("loss_weight", shape=[seq_len], dtype="float32")

        logits = gpt2_lm(ids, hp, is_test)
        cost = layers.softmax_with_cross_entropy(
            logits, layers.unsqueeze(lbl, [2])
        )
        cost = layers.elementwise_mul(cost, layers.unsqueeze(w, [2]))
        tokens = layers.reduce_sum(w)
        # epsilon guard: an all-pad batch yields loss 0, never 0/0 NaN
        loss = layers.elementwise_div(
            layers.reduce_sum(cost), layers.clip(tokens, 1e-5, 1e30)
        )

        # logits-free fused cross-entropy (the [B, T, V] f32 logits
        # tensor never reaches HBM under FLAGS_use_pallas) + the
        # matmul-epilogue layer for the FFN/residual-LN chains — both
        # BEFORE minimize so grads differentiate through the fused ops
        from ..transpiler.pass_registry import apply_pass

        apply_pass(main, "linear_xent_fuse_pass")
        apply_pass(main, "matmul_epilogue_fuse_pass")
        if use_bf16:
            apply_pass(main, "bf16_amp_pass")
        # HBM-budgeted remat (FLAGS_hbm_budget_bytes; no-op when unset);
        # the flag is a per-device budget, so a mesh scales it
        from ..transpiler.remat import maybe_remat

        maybe_remat(main, loss, is_test, mesh=mesh)
        if not is_test:
            fluid.optimizer.Adam(learning_rate=lr).minimize(loss)

    if mesh is not None:
        from ..parallel.partition_rules import (annotate_spmd,
                                                train_partition_rules_for)

        annotate_spmd(main, mesh, train_partition_rules_for(
            getattr(hp, "partition_family", "gpt2")))
    return main, startup, ["ids", "labels", "loss_weight"], [loss, tokens]


def make_fake_lm_batch(batch_size, seq_len, hp=GPT2Config, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(1, hp.vocab_size, (batch_size, seq_len + 1)).astype("int64")
    return {
        "ids": ids[:, :-1],
        "labels": ids[:, 1:],
        "loss_weight": np.ones((batch_size, seq_len), "float32"),
    }


def gpt2_logits_program(hp=GPT2Config, seq_len=128):
    """Inference program fetching the full [B, T, vocab] logits (the
    decode-step workhorse: static shapes, one compile for any prompt
    length <= seq_len)."""
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup), unique_name.guard():
        ids = layers.data("ids", shape=[seq_len], dtype="int64")
        logits = gpt2_lm(ids, hp, is_test=True)
    return main, startup, ["ids"], [logits]


def gpt2_decode_step_program(hp=GPT2Config, batch=1, t_max=None, width=1,
                             cache_dtype="float32"):
    """KV-cached decode step (the incremental-decoding engine the
    reference's beam-search cache plumbing approximates):

        feeds:  step_ids [B, W] int64, pos [1] int64
                (+ pos_vec [W] int64 when W > 1: positions pos..pos+W-1)
        fetch:  next-token logits — [B, vocab] (W == 1) or
                [B, W, vocab] (W > 1; row i predicts position pos+i+1)
        state:  per-layer kcache/vcache [B, H, T_max, Dh] persistable vars

    cache_dtype="bfloat16" halves decode's dominant HBM tenant (writes
    cast in seq_cache_write; attention math promotes back to f32).
    width == 1 is the classic one-token step: O(T_max * d) per token.
    width > 1 is the CHUNKED step (prefill / speculative verify): one
    dispatch writes W cache slots and scores W positions with
    offset-causal attention (fused_attention qstart) — prompt prefill
    drops from P dispatches to ceil(P/W) MXU-shaped ones.  The cache
    vars live donated in HBM and the step compiles ONCE.  Returns
    (main, cache_startup, feeds, fetches, cache_names); run
    `cache_startup` to (re)zero the caches before each generation.
    Built under unique_name.guard(), so weights are shared by name with
    gpt2_lm_program / gpt2_logits_program built in the same process."""
    import paddle_tpu as fluid

    t_max = t_max or hp.n_ctx
    assert t_max <= hp.n_ctx, (
        "t_max %d exceeds the position table n_ctx %d" % (t_max, hp.n_ctx))
    width = int(width)
    assert 1 <= width <= t_max, (width, t_max)
    dh = hp.d_model // hp.n_head
    main = fluid.Program()
    cache_startup = fluid.Program()  # ONLY cache zeroing lands here
    throwaway_startup = fluid.Program()  # param inits (weights come from
    # the training/logits program's startup via shared names)
    cache_names = []
    with fluid.program_guard(main, throwaway_startup), unique_name.guard():
        # static batch: the caches are [batch, ...] state, so the whole
        # step graph keeps concrete shapes (one compile, no DYN dims)
        ids = layers.data("step_ids", shape=[batch, width], dtype="int64",
                          append_batch_size=False)
        pos = layers.data("pos", shape=[1], dtype="int64",
                          append_batch_size=False)
        pos_vec = None
        if width > 1:
            pos_vec = layers.data("pos_vec", shape=[width], dtype="int64",
                                  append_batch_size=False)
        emb_attr = _pa("emb.w")
        tok = layers.embedding(
            ids, size=[hp.vocab_size, hp.d_model], param_attr=emb_attr
        )  # [B, W, D] (W == 1 squeezes in the lookup)
        tok = layers.reshape(tok, shape=[batch, width, hp.d_model])
        if getattr(hp, "use_rotary", False):
            x = tok  # RoPE rotates q/k by position inside cached attention
        else:
            pos_table = layers.create_parameter(
                shape=[hp.n_ctx, hp.d_model], dtype="float32",
                attr=_pa("pos_emb.w", 0.01),
            )
            if width == 1:
                pos_row = layers.reshape(layers.gather(pos_table, pos),
                                         shape=[1, 1, hp.d_model])
                x = layers.elementwise_add(tok, pos_row)
            else:
                pos_rows = layers.gather(pos_table, pos_vec)  # [W, D]
                x = layers.elementwise_add(tok, pos_rows, axis=1)
        from .decode_cache import add_cache_zero_fills, create_kv_caches

        blk = main.global_block()
        n_kv = getattr(hp, "n_kv_head", None) or hp.n_head
        kv_caches, cache_names = create_kv_caches(
            blk, "gpt2", hp.n_layer, batch, n_kv, t_max, dh,
            dtype=cache_dtype)
        add_cache_zero_fills(
            cache_startup,
            [(n, (batch, n_kv, t_max, dh)) for n in cache_names],
            dtype=cache_dtype)
        for cache in kv_caches:
            cache["pos"] = pos
            if pos_vec is not None:
                cache["pos_vec"] = pos_vec
            x = _block(x, hp, is_test=True, cache=cache)
        x = layers.layer_norm(x, begin_norm_axis=2)
        logits = _tied_logits(x, hp, emb_attr.name)
        if width == 1:
            logits = layers.reshape(logits, shape=[batch, hp.vocab_size])
        feeds = ["step_ids", "pos"] + (["pos_vec"] if pos_vec is not None
                                       else [])
        # PR 11 closed-gap: the matmul-epilogue fuse bundle now rewrites
        # DECODE programs too (fc bias+act, SwiGLU diamonds, residual-LN
        # pairs -> the fused ops / pallas kernels).  Row-independent
        # kernels keep the serving exactness contract intact; the fetch
        # is protected so no fuse can fold it away.
        _apply_decode_epilogue_passes(main, logits)
    return main, cache_startup, feeds, [logits], cache_names


def gpt2_ragged_step_program(hp=GPT2Config, batch=4, t_max=None, width=8,
                             cache_dtype="float32", cache_prefix="gpt2"):
    """The continuous-batching serving step (serving/engine.py's ONE
    compiled program): width-W decode over a POOL of `batch` slots where
    every slot sits at its own position.

        feeds:  step_ids   [B, W] int64 — per-slot token columns (a
                           prefilling slot carries a prompt chunk, a
                           decoding slot its current token in column 0,
                           a free slot padding)
                pos_rows   [B] int64 — each slot's global write/query
                           base position (qstart)
                width_rows [B] int64 — how many of the W columns are
                           REAL for each slot (1 for decode, chunk len
                           for prefill, 0 for free slots); columns
                           beyond it are never written to the cache
                pos_mat    [B, W] int64 — per-slot absolute positions
                           pos_rows[b] + i (clipped into the position
                           table) for the position embedding / RoPE
        fetch:  logits [B, W, vocab] — row b column i predicts position
                pos_rows[b] + i + 1 for that slot's request
        state:  the SAME per-layer gpt2_{k,v}cache_* persistables as
                gpt2_decode_step_program (shared scope, shared names);
                `cache_prefix` renames them — a DRAFT model's step
                program sharing the target's scope (self-draft
                speculation) must keep its own KV pool

    Cache writes go through slot_cache_write (per-row position + width,
    out-of-width columns dropped) and attention masks per-row offset-
    causal (fused_attention vector qstart), so ONE dispatch interleaves
    prompt prefill for newly admitted requests with single-token decode
    for in-flight ones — occupancy changes only change feed VALUES,
    never shapes: the step compiles exactly once.  Exactness: row b's
    logits are bit-identical to the same request running solo in the
    same program (row-independent math; masked lanes contribute exact
    zeros), which is the serving engine's per-request contract.
    Returns (main, cache_startup, feeds, fetches, cache_names)."""
    import paddle_tpu as fluid

    t_max = t_max or hp.n_ctx
    assert t_max <= hp.n_ctx, (
        "t_max %d exceeds the position table n_ctx %d" % (t_max, hp.n_ctx))
    width = int(width)
    assert 1 <= width <= t_max, (width, t_max)
    dh = hp.d_model // hp.n_head
    main = fluid.Program()
    cache_startup = fluid.Program()
    throwaway_startup = fluid.Program()
    with fluid.program_guard(main, throwaway_startup), unique_name.guard():
        ids = layers.data("step_ids", shape=[batch, width], dtype="int64",
                          append_batch_size=False)
        pos_rows = layers.data("pos_rows", shape=[batch], dtype="int64",
                               append_batch_size=False)
        width_rows = layers.data("width_rows", shape=[batch], dtype="int64",
                                 append_batch_size=False)
        pos_mat = layers.data("pos_mat", shape=[batch, width],
                              dtype="int64", append_batch_size=False)
        emb_attr = _pa("emb.w")
        tok = layers.embedding(
            ids, size=[hp.vocab_size, hp.d_model], param_attr=emb_attr
        )
        tok = layers.reshape(tok, shape=[batch, width, hp.d_model])
        if getattr(hp, "use_rotary", False):
            x = tok  # RoPE rotates q/k by pos_mat inside cached attention
        else:
            pos_table = layers.create_parameter(
                shape=[hp.n_ctx, hp.d_model], dtype="float32",
                attr=_pa("pos_emb.w", 0.01),
            )
            pos_emb = layers.gather(pos_table, pos_mat)  # [B, W, D]
            x = layers.elementwise_add(tok, pos_emb)
        from .decode_cache import add_cache_zero_fills, create_kv_caches

        blk = main.global_block()
        n_kv = getattr(hp, "n_kv_head", None) or hp.n_head
        kv_caches, cache_names = create_kv_caches(
            blk, cache_prefix, hp.n_layer, batch, n_kv, t_max, dh,
            dtype=cache_dtype)
        add_cache_zero_fills(
            cache_startup,
            [(n, (batch, n_kv, t_max, dh)) for n in cache_names],
            dtype=cache_dtype)
        for cache in kv_caches:
            cache["pos_rows"] = pos_rows
            cache["width_rows"] = width_rows
            if getattr(hp, "use_rotary", False):
                cache["pos_mat"] = pos_mat
            x = _block(x, hp, is_test=True, cache=cache)
        x = layers.layer_norm(x, begin_norm_axis=2)
        logits = _tied_logits(x, hp, emb_attr.name)
        # the continuous-batching step gets the same matmul-epilogue
        # bundle as the classic decode step (PR 11's "training programs
        # only" limit closed); per-row kernels preserve pooled == solo
        _apply_decode_epilogue_passes(main, logits)
    feeds = ["step_ids", "pos_rows", "width_rows", "pos_mat"]
    return main, cache_startup, feeds, [logits], cache_names


def _apply_decode_epilogue_passes(main, logits):
    """Apply the matmul-epilogue fuse bundle to a decode/serving step
    program, protecting the logits fetch (a fuse deletes every
    intermediate of its chain; the fetch must survive)."""
    from ..transpiler.pass_registry import apply_pass

    prev = tuple(getattr(main, "_protected_fetch_names", ()) or ())
    main._protected_fetch_names = tuple(
        dict.fromkeys(prev + (logits.name,)))
    apply_pass(main, "matmul_epilogue_fuse_pass")


def _prefill_cached(exe, step_main, fetches, ids):
    """Feed the prompt one token at a time (filling the caches); returns
    the logits after the last prompt token (they predict position p)."""
    logits = None
    for t in range(ids.shape[1]):
        (logits,) = exe.run(
            step_main,
            feed={"step_ids": ids[:, t:t + 1],
                  "pos": np.array([t], "int64")},
            fetch_list=fetches,
        )
    return logits


def _speculative_core(
        exe, tgt_step_main, tgt_cache_startup, tgt_step_fetch,
        tgt_wide_main, tgt_wide_fetch, spec_k,
        draft_step_main, draft_cache_startup, draft_step_fetch,
        prompt_ids, max_new_tokens, draft_scope,
        target_pick, draft_pick, resolve_round):
    """Shared speculative round machinery (greedy and sampling variants
    plug in their token rules):

    - target_pick(logits [B, V]) -> [B] token (prefill / capacity-tail)
    - draft_pick(logits [B, V]) -> ([B] token, aux) — aux rides to the
      resolver (the sampling variant records the draft's filtered probs)
    - resolve_round(wl [B, spec_k, V], drafts, aux) ->
      (accepted token list, cur [B], j) — wl row i is the target
      distribution at position pos+i+1 conditioned on chunk[:, :i+1];
      j tokens were accepted, `cur` goes to position pos+j+1

    Round shape: the draft proposes k = spec_k-1 tokens one-step-at-a-
    time, ONE width-spec_k target dispatch scores anchor+drafts, the
    resolver keeps the longest valid prefix.  Rollback is free by
    construction: rejected tokens' K/V sit beyond the accepted position,
    never attended (<=pos masking) and overwritten before first use.
    Near cache capacity the tail falls back to one-token target steps (a
    fixed-width verify write would clamp onto valid slots)."""
    from ..core.scope import global_scope
    from .decode_cache import probe_cache_len, validate_cached_call

    prompt_ids = np.asarray(prompt_ids, "int64")
    b, p = prompt_ids.shape
    spec_k = int(spec_k)
    if spec_k < 2:
        raise ValueError(
            "speculative decoding needs spec_k >= 2 (the wide verify "
            "program needs width > 1; spec_k == 1 is just the plain "
            "cached generator)")
    validate_cached_call(tgt_step_main, "gpt2", "step_ids", b, p,
                         max_new_tokens)
    t_max = probe_cache_len(tgt_wide_main, "gpt2")
    step_t_max = probe_cache_len(tgt_step_main, "gpt2")
    if t_max != step_t_max:
        raise ValueError(
            "speculative decode: wide program cache length %d != step "
            "program's %d — both must address the SAME cache"
            % (t_max, step_t_max))
    from .decode_cache import probe_cache_dtype

    wd = probe_cache_dtype(tgt_wide_main, "gpt2")
    sd = probe_cache_dtype(tgt_step_main, "gpt2")
    if wd != sd:
        raise ValueError(
            "speculative decode: wide program cache dtype %s != step "
            "program's %s — build both with the same cache_dtype"
            % (wd, sd))
    draft_scope = draft_scope if draft_scope is not None else global_scope()

    def run_draft(main, feed, fetches):
        return exe.run(main, feed=feed, fetch_list=fetches,
                       scope=draft_scope)

    # prefill BOTH caches with the prompt; target via its wide program
    exe.run(tgt_cache_startup)
    run_draft(draft_cache_startup, {}, [])
    tgt_logits = prefill_cached_chunked(
        exe, tgt_wide_main, tgt_wide_fetch, prompt_ids, spec_k, t_max)
    for t in range(p):
        run_draft(
            draft_step_main,
            feed={"step_ids": prompt_ids[:, t:t + 1],
                  "pos": np.array([t], "int64")},
            fetches=draft_step_fetch)

    out = [prompt_ids[:, i] for i in range(p)]
    # batch rows advance in lockstep on the SLOWEST row's acceptance —
    # every row's tokens stay valid under its own rule regardless
    cur = target_pick(tgt_logits)  # token @ position p
    pos = p
    proposals = accepted_total = rounds = 0
    while pos < p + max_new_tokens:
        out.append(cur)
        if pos + 1 >= p + max_new_tokens:
            break
        if pos + spec_k > t_max:
            # capacity tail: one-token target steps
            (tl,) = exe.run(
                tgt_step_main,
                feed={"step_ids": cur[:, None],
                      "pos": np.array([pos], "int64")},
                fetch_list=tgt_step_fetch)
            cur = target_pick(tl)
            pos += 1
            continue
        k = min(spec_k - 1, p + max_new_tokens - pos - 2)
        drafts, aux = [], []
        (dl,) = run_draft(
            draft_step_main,
            feed={"step_ids": cur[:, None], "pos": np.array([pos], "int64")},
            fetches=draft_step_fetch)
        for i in range(k):
            tok, a = draft_pick(dl)
            drafts.append(tok)
            aux.append(a)
            (dl,) = run_draft(
                draft_step_main,
                feed={"step_ids": tok[:, None],
                      "pos": np.array([pos + 1 + i], "int64")},
                fetches=draft_step_fetch)
        # ONE target dispatch scores cur + the k draft tokens: row i is
        # the target distribution at position pos+i+1
        chunk = np.stack([cur] + drafts, axis=1)
        if chunk.shape[1] < spec_k:
            chunk = np.pad(chunk, ((0, 0), (0, spec_k - chunk.shape[1])))
        (wl,) = exe.run(
            tgt_wide_main,
            feed={"step_ids": chunk,
                  "pos": np.array([pos], "int64"),
                  "pos_vec": np.minimum(
                      np.arange(pos, pos + spec_k, dtype="int64"),
                      t_max - 1)},
            fetch_list=tgt_wide_fetch)
        rounds += 1
        proposals += k
        acc, cur, j = resolve_round(np.asarray(wl), drafts, aux)
        out.extend(acc)
        accepted_total += j
        pos = pos + 1 + j
    tokens = np.stack(out, axis=1)[:, :p + max_new_tokens]
    stats = {
        "rounds": rounds,
        "proposed": proposals,
        "accepted": accepted_total,
        "accept_rate": (accepted_total / proposals) if proposals else 1.0,
    }
    return tokens, stats


def speculative_generate_cached(
        exe, tgt_step_main, tgt_cache_startup, tgt_step_fetch,
        tgt_wide_main, tgt_wide_fetch, spec_k,
        draft_step_main, draft_cache_startup, draft_step_fetch,
        prompt_ids, max_new_tokens, draft_scope=None):
    """Speculative GREEDY decoding: the resolver keeps the longest
    prefix where every batch row's draft equals the target's argmax,
    then takes the target's bonus/correction token.  Output is EXACTLY
    the target's own greedy_generate_cached sequence for any draft —
    the draft only changes how many target dispatches it takes
    (>= 1 + ceil(new/(k+1)) at full acceptance vs `new`).
    Beyond-reference (the reference era predates speculative decoding);
    the standard TPU serving recipe for dispatch-bound decode.
    draft_scope: the draft model's own fluid.Scope (separate weights +
    caches); defaults to the CURRENT scope (self-draft).  Returns
    (tokens [B, P+new], accept_stats dict)."""

    def target_pick(logits):
        return np.asarray(logits).argmax(-1).astype("int64")

    def draft_pick(logits):
        return np.asarray(logits).argmax(-1).astype("int64"), None

    def resolve(wl, drafts, aux):
        # the shared resolver rule (decode_cache.greedy_accept_len) —
        # the serving engine's in-pool rounds resolve with the same one
        from .decode_cache import greedy_accept_len

        tgt_next = wl.argmax(-1).astype("int64")  # [B, spec_k]
        j = greedy_accept_len(tgt_next, drafts)
        # bonus (all accepted) or correction (first mismatch)
        return list(drafts[:j]), tgt_next[:, j], j

    return _speculative_core(
        exe, tgt_step_main, tgt_cache_startup, tgt_step_fetch,
        tgt_wide_main, tgt_wide_fetch, spec_k,
        draft_step_main, draft_cache_startup, draft_step_fetch,
        prompt_ids, max_new_tokens, draft_scope,
        target_pick, draft_pick, resolve)


def speculative_sample_generate_cached(
        exe, tgt_step_main, tgt_cache_startup, tgt_step_fetch,
        tgt_wide_main, tgt_wide_fetch, spec_k,
        draft_step_main, draft_cache_startup, draft_step_fetch,
        prompt_ids, max_new_tokens, temperature=1.0, top_k=0, top_p=1.0,
        seed=None, draft_scope=None):
    """Speculative SAMPLING (the rejection-sampling scheme): the draft
    proposes d ~ p_d, accepted with prob min(1, p_t(d)/p_d(d)); on
    rejection the token re-samples from normalize(max(p_t - p_d, 0)).
    The output distribution is EXACTLY the target's filtered sampling
    distribution (same temperature/top_k/top_p applied to both models'
    logits) for ANY draft.  A round stops at the first index where ANY
    batch row rejects — earlier acceptances stand (valid draws
    regardless of other rows); at the stop index accepted rows keep
    their draft token and rejected rows draw the residual.  Returns
    (tokens [B, P+new], accept_stats dict)."""
    from .decode_cache import filtered_probs, residual_probs, sample_rows

    rng = np.random.RandomState(seed)
    b = np.asarray(prompt_ids).shape[0]

    def probs(logits):
        return filtered_probs(logits, temperature, top_k, top_p)

    def target_pick(logits):
        return sample_rows(probs(logits), rng)

    def draft_pick(logits):
        pd = probs(logits)
        return sample_rows(pd, rng), pd

    def resolve(wl, drafts, aux):
        j, acc = 0, []
        while j < len(drafts):
            pt = probs(wl[:, j])
            pd = aux[j]
            d = drafts[j]
            ratio = (np.take_along_axis(pt, d[:, None], 1).reshape(-1)
                     / np.maximum(
                         np.take_along_axis(pd, d[:, None], 1).reshape(-1),
                         1e-12))
            reject = rng.rand(b) > ratio
            if not reject.any():
                acc.append(d)
                j += 1
                continue
            # stop: rejected rows draw the shared residual rule
            # (decode_cache.residual_probs — the serving engine's keyed
            # resolver computes the same distribution); accepted rows
            # keep d (a valid draw regardless of others)
            repl = sample_rows(residual_probs(pt, pd), rng)
            return acc, np.where(reject, repl, d).astype("int64"), j
        # every draft accepted: bonus from the target's last row
        return acc, sample_rows(probs(wl[:, len(drafts)]), rng), j

    return _speculative_core(
        exe, tgt_step_main, tgt_cache_startup, tgt_step_fetch,
        tgt_wide_main, tgt_wide_fetch, spec_k,
        draft_step_main, draft_cache_startup, draft_step_fetch,
        prompt_ids, max_new_tokens, draft_scope,
        target_pick, draft_pick, resolve)


def _dispatch_prefill(exe, step_main, fetches, ids, prefill):
    """Prefill the caches with `ids`: chunked through the wide program
    when `prefill` = (wide_main, wide_fetches, width[, t_max]) is given,
    one-token steps otherwise.  The wide program's cache length and
    static batch are VALIDATED here — a wrong t_max would let the
    chunked writes clamp onto valid slots, and a beam path needs the
    wide program built with batch = B * beam_size."""
    if prefill is None:
        return _prefill_cached(exe, step_main, fetches, ids)
    from .decode_cache import probe_cache_len

    from .decode_cache import probe_cache_dtype

    wm, wf, width = prefill[0], prefill[1], int(prefill[2])
    t_max = probe_cache_len(wm, "gpt2")
    step_t_max = probe_cache_len(step_main, "gpt2")
    if t_max != step_t_max:
        raise ValueError(
            "prefill wide program cache length %d != the step program's "
            "%d — both must address the SAME cache capacity or the "
            "chunked writes land on wrong slots" % (t_max, step_t_max))
    wd, sd = probe_cache_dtype(wm, "gpt2"), probe_cache_dtype(step_main,
                                                             "gpt2")
    if wd != sd:
        raise ValueError(
            "prefill wide program cache dtype %s != the step program's "
            "%s — build both with the same cache_dtype" % (wd, sd))
    if len(prefill) > 3 and int(prefill[3]) != t_max:
        raise ValueError(
            "prefill t_max %d does not match the wide program's cache "
            "length %d" % (int(prefill[3]), t_max))
    ids_var = wm.global_block().var("step_ids")
    wb, ww = int(ids_var.shape[0]), int(ids_var.shape[1])
    if ww != width:
        raise ValueError(
            "prefill width %d != the wide program's step_ids width %d"
            % (width, ww))
    if wb != ids.shape[0]:
        raise ValueError(
            "prefill wide program batch %d != %d rows to prefill (beam "
            "paths need the wide program built with batch = B * "
            "beam_size)" % (wb, ids.shape[0]))
    return prefill_cached_chunked(exe, wm, wf, ids, width, t_max)


def prefill_cached_chunked(exe, wide_main, wide_fetches, ids, width,
                           t_max):
    """Fill the caches with the prompt in ceil(P/W) width-W dispatches
    (gpt2_decode_step_program(width=W)) instead of P one-token steps;
    returns the logits predicting position P (identical to one-token
    prefill).  The last chunk re-anchors to t_max - W when it would
    write past the cache (rewriting earlier slots with the same tokens
    is idempotent); pad rows beyond the prompt land in slots the
    generation loop overwrites before ever attending them."""
    from .decode_cache import run_chunked_ids

    ids = np.asarray(ids, "int64")
    _b, p = ids.shape
    logits = last_c0 = None
    for c0, lg in run_chunked_ids(exe, wide_main, wide_fetches, ids,
                                  width, t_max, "step_ids",
                                  has_pos_vec=True):
        logits, last_c0 = lg, c0
    return logits[:, (p - 1) - last_c0]


def greedy_generate_cached(exe, step_main, cache_startup, fetches,
                           prompt_ids, max_new_tokens, prefill=None):
    """Greedy decoding through the KV-cached step program: prefill fills
    the caches from the prompt, then each new token costs one
    O(T_max * d) step.  Matches greedy_generate token-for-token.
    prefill: optional (wide_main, wide_fetches, width, t_max) from
    gpt2_decode_step_program(width=W) — chunked prefill in ceil(P/W)
    dispatches instead of P."""
    from .decode_cache import validate_cached_call

    prompt_ids = np.asarray(prompt_ids, "int64")
    b, p = prompt_ids.shape
    validate_cached_call(step_main, "gpt2", "step_ids", b, p,
                         max_new_tokens)
    exe.run(cache_startup)  # (re)zero the caches for this generation
    out = [prompt_ids[:, i] for i in range(p)]
    logits = _dispatch_prefill(exe, step_main, fetches, prompt_ids,
                               prefill)
    for t in range(p, p + max_new_tokens):
        nxt = np.asarray(logits).argmax(axis=-1).astype("int64")
        out.append(nxt)
        if t + 1 >= p + max_new_tokens:
            break
        (logits,) = exe.run(
            step_main,
            feed={"step_ids": nxt[:, None], "pos": np.array([t], "int64")},
            fetch_list=fetches,
        )
    return np.stack(out, axis=1)


def _prompt_buffer(main, prompt_ids, max_new_tokens, pad_id):
    """Shared decode prologue: validate the prompt against the program's
    width and left-align it in a pad-filled [B, T] buffer."""
    T = int(main.global_block().vars["ids"].shape[1])
    prompt_ids = np.asarray(prompt_ids, "int64")
    b, p = prompt_ids.shape
    assert p >= 1, "empty prompt: seed generation with at least a BOS token"
    assert p + max_new_tokens <= T, (
        "program seq_len %d < prompt %d + new %d" % (T, p, max_new_tokens)
    )
    buf = np.full((b, T), pad_id, "int64")
    buf[:, :p] = prompt_ids
    return buf, p


def greedy_generate(exe, main, fetches, prompt_ids, max_new_tokens,
                    pad_id=0):
    """Greedy decoding on a fixed-shape logits program: the prompt is
    right-padded to the program's T, each step feeds the updated ids and
    reads the logits at the last real position.  One XLA compile total
    (static shapes); causal masking makes the padded tail invisible.

    prompt_ids: [B, P] int64.  Returns [B, P + max_new_tokens] int64.
    """
    buf, p = _prompt_buffer(main, prompt_ids, max_new_tokens, pad_id)
    cur = p
    for _ in range(max_new_tokens):
        (logits,) = exe.run(main, feed={"ids": buf}, fetch_list=fetches)
        nxt = np.asarray(logits)[:, cur - 1, :].argmax(axis=-1)
        buf[:, cur] = nxt
        cur += 1
    return buf[:, :cur]


def beam_generate(exe, main, fetches, prompt_ids, max_new_tokens,
                  beam_size=4, eos_id=None, pad_id=0, length_penalty=0.0):
    """Beam-search decoding on the same fixed-shape logits program as
    greedy_generate.  Returns (ids [B, T_out], scores [B])."""
    from ..contrib.decoder.beam_search_decoder import full_sequence_beam_search

    buf, p = _prompt_buffer(main, prompt_ids, max_new_tokens, pad_id)

    def logits_fn(rows, cur):
        (logits,) = exe.run(main, feed={"ids": rows}, fetch_list=fetches)
        return np.asarray(logits)[:, cur - 1, :]

    return full_sequence_beam_search(
        logits_fn, buf, p, beam_size, p + max_new_tokens,
        eos_id if eos_id is not None else -1, pad_id, length_penalty,
    )


def beam_generate_cached(exe, step_main, cache_startup, fetches, prompt_ids,
                         max_new_tokens, beam_size=4, eos_id=None, pad_id=0,
                         length_penalty=0.0, prefill=None):
    """Beam-search decoding through the KV-cached step program: the step
    program must be built with batch = B * beam_size; surviving beams'
    caches shuffle via a gather/assign reorder program each step (the
    reference's beam-search cache plumbing).  prefill: optional
    (wide_main, wide_fetches, width, t_max) chunked prompt prefill —
    the wide program must ALSO be built with batch = B * beam_size.
    Returns (ids [B, T_out], scores [B])."""
    from ..contrib.decoder.beam_search_decoder import incremental_beam_search
    from .decode_cache import (
        make_cache_reorder_program,
        validate_cached_call,
    )

    prompt_ids = np.asarray(prompt_ids, "int64")
    b, p = prompt_ids.shape
    validate_cached_call(step_main, "gpt2", "step_ids", b, p,
                         max_new_tokens, beams=beam_size)
    sb = step_main.global_block()
    r = b * beam_size
    cache_shapes = [
        (n, v.shape, v.dtype) for n, v in sb.vars.items()
        if n.startswith(("gpt2_kcache_", "gpt2_vcache_"))
    ]
    reorder = make_cache_reorder_program(cache_shapes, r)

    exe.run(cache_startup)
    rep = np.repeat(prompt_ids, beam_size, axis=0)
    logits = _dispatch_prefill(exe, step_main, fetches, rep, prefill)

    def step_fn(tokens, pos):
        (lg,) = exe.run(step_main,
                        feed={"step_ids": tokens,
                              "pos": np.array([pos], "int64")},
                        fetch_list=fetches)
        return lg

    def reorder_fn(rows):
        exe.run(reorder, feed={"parents": rows.astype("int64")},
                fetch_list=[])

    return incremental_beam_search(
        step_fn, reorder_fn, logits, prompt_ids, p, beam_size,
        p + max_new_tokens, eos_id if eos_id is not None else -1, pad_id,
        length_penalty)


def sample_generate_cached(exe, step_main, cache_startup, fetches,
                           prompt_ids, max_new_tokens, temperature=1.0,
                           top_k=0, top_p=1.0, seed=None, eos_id=None,
                           pad_id=0, prefill=None):
    """Stochastic decoding through the KV-cached step: temperature
    scaling, top-k and/or nucleus (top-p) filtering, seeded numpy
    sampling.  top_k=1 reduces to greedy.  prefill: optional
    (wide_main, wide_fetches, width, t_max) — chunked prompt prefill in
    ceil(P/W) dispatches.  Returns [B, P + new] int64."""
    from .decode_cache import sample_from_logits, validate_cached_call

    prompt_ids = np.asarray(prompt_ids, "int64")
    b, p = prompt_ids.shape
    validate_cached_call(step_main, "gpt2", "step_ids", b, p,
                         max_new_tokens)
    rng = np.random.RandomState(seed)
    exe.run(cache_startup)
    logits = _dispatch_prefill(exe, step_main, fetches, prompt_ids,
                               prefill)
    out = [prompt_ids[:, i] for i in range(p)]
    done = np.zeros(b, bool)
    for t in range(p, p + max_new_tokens):
        nxt = sample_from_logits(logits, rng, temperature, top_k, top_p)
        if eos_id is not None:
            nxt = np.where(done, pad_id, nxt)
            done |= nxt == eos_id
        out.append(nxt)
        if t + 1 >= p + max_new_tokens or (eos_id is not None and done.all()):
            break
        (logits,) = exe.run(step_main, feed={
            "step_ids": nxt[:, None], "pos": np.array([t], "int64")},
            fetch_list=fetches)
    # early all-eos exit: pad to the documented [B, P + new] width
    while len(out) < p + max_new_tokens:
        out.append(np.full(b, pad_id, "int64"))
    return np.stack(out, axis=1)
