"""SE-ResNeXt (benchmark/fluid/models/se_resnext.py analog).

Grouped 3x3 convolutions (cardinality) + squeeze-and-excitation blocks;
depth 50 with [3,4,6,3] stages.  Grouped conv lowers to one XLA conv with
feature_group_count — MXU-friendly, no per-group unrolling.
"""

from .. import layers


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1, act=None,
                  is_test=False):
    conv = layers.conv2d(
        input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=(filter_size - 1) // 2,
        groups=groups,
        act=None,
        bias_attr=False,
    )
    return layers.batch_norm(conv, act=act, is_test=is_test)


def squeeze_excitation(input, num_channels, reduction_ratio=16):
    pool = layers.pool2d(input, pool_type="avg", global_pooling=True)
    squeeze = layers.fc(pool, size=max(1, num_channels // reduction_ratio), act="relu")
    excitation = layers.fc(squeeze, size=num_channels, act="sigmoid")
    # scale channels: [N,C,H,W] * [N,C] broadcast on axis 0
    return layers.elementwise_mul(input, excitation, axis=0)


def shortcut(input, ch_out, stride, is_test=False):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, is_test=is_test)
    return input


def bottleneck_block(input, num_filters, stride, cardinality=32,
                     reduction_ratio=16, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu", is_test=is_test)
    conv1 = conv_bn_layer(
        conv0, num_filters, 3, stride=stride, groups=cardinality, act="relu",
        is_test=is_test,
    )
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, act=None, is_test=is_test)
    scaled = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = shortcut(input, num_filters * 2, stride, is_test=is_test)
    return layers.relu(layers.elementwise_add(short, scaled))


def se_resnext(input, class_dim=1000, depth=50, cardinality=32,
               reduction_ratio=16, is_test=False, stages=None,
               num_filters=None):
    if stages is None:
        assert depth in (50, 101, 152)
        stages = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}[depth]
    num_filters = num_filters or [128, 256, 512, 1024]

    conv = conv_bn_layer(input, 64, 7, stride=2, act="relu", is_test=is_test)
    conv = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1,
                         pool_type="max")
    for stage, count in enumerate(stages):
        for i in range(count):
            conv = bottleneck_block(
                conv,
                num_filters[stage],
                stride=2 if i == 0 and stage != 0 else 1,
                cardinality=cardinality,
                reduction_ratio=reduction_ratio,
                is_test=is_test,
            )
    pool = layers.pool2d(conv, pool_type="avg", global_pooling=True)
    drop = layers.dropout(pool, dropout_prob=0.2, is_test=is_test)
    return layers.fc(drop, size=class_dim, act="softmax")
