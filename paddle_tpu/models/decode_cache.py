"""Shared KV-cache scaffolding for incremental decode (used by gpt2's
decode step and the transformer's seq2seq decode programs): per-layer
cache variable creation, the zeroing program, and capacity probing."""

import numpy as np

from .. import layers

__all__ = ["create_kv_caches", "add_cache_zero_fills", "probe_cache_len",
           "make_cache_reorder_program", "validate_cached_call",
           "probe_cache_dtype", "run_chunked_ids", "sample_from_logits",
           "filtered_probs", "sample_rows", "make_slot_reset_program",
           "fold_in_seed", "sample_rows_keyed", "filtered_probs_rows",
           "make_row_copy_program", "greedy_accept_len", "residual_probs",
           "spec_key", "spec_propose_keyed", "spec_accept_keyed",
           "spec_token_keyed"]


def create_kv_caches(block, prefix, n_layer, batch, n_head, t_max, dh,
                     dtype="float32"):
    """Create per-layer persistable [batch, n_head, t_max, dh] K/V cache
    vars named `<prefix>_{k,v}cache_<layer>`.  Returns (per-layer cache
    dicts without 'pos', all names).  dtype="bfloat16" halves decode's
    dominant HBM tenant (seq_cache_write casts on write; attention
    math promotes back to f32)."""
    caches, names = [], []
    for li in range(n_layer):
        cache = {}
        for nm in ("k", "v"):
            cname = "%s_%scache_%d" % (prefix, nm, li)
            cache[nm] = block.create_var(
                name=cname, shape=[batch, n_head, t_max, dh],
                dtype=dtype, persistable=True)
            names.append(cname)
        caches.append(cache)
    return caches, names


def add_cache_zero_fills(zero_program, named_shapes, dtype="float32"):
    """Append fill_constant ops zeroing each (name, shape) persistable
    into `zero_program` (run it to reset decode state per generation)."""
    import paddle_tpu as fluid

    with fluid.program_guard(zero_program, fluid.Program()):
        blk = zero_program.global_block()
        for cname, shape in named_shapes:
            layers.fill_constant(
                list(shape), dtype, 0.0,
                out=blk.create_var(name=cname, shape=list(shape),
                                   dtype=dtype, persistable=True))


def make_slot_reset_program(named_shapes, batch, dtype="float32"):
    """add_cache_zero_fills generalized to PER-SLOT resets (the serving
    pool's admission step): a program multiplying every named [B, ...]
    persistable cache by the fed `slot_keep` [B] row mask — 1.0 keeps a
    slot's rows, 0.0 zeroes them for the incoming request.  ONE compiled
    program covers every subset of slots (the mask is a feed, so
    admission churn never retraces).  named_shapes entries: (name,
    shape) or (name, shape, dtype) — per-var dtype overrides `dtype`
    (bf16 caches reset in bf16)."""
    import paddle_tpu as fluid

    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        keep = layers.data("slot_keep", shape=[batch], dtype="float32",
                           append_batch_size=False)
        blk = prog.global_block()
        for entry in named_shapes:
            cname, shape = entry[0], entry[1]
            vdtype = entry[2] if len(entry) > 2 else dtype
            assert int(shape[0]) == batch, (cname, shape, batch)
            cvar = blk.create_var(name=cname, shape=list(shape),
                                  dtype=vdtype, persistable=True)
            masked = layers.elementwise_mul(cvar, keep, axis=0)
            if str(vdtype) != "float32":
                # the f32 mask promotes the product; cast back so the
                # persistable keeps its declared dtype (bf16 caches
                # must stay bf16 — assign does not cast)
                masked = layers.cast(masked, str(vdtype))
            blk.append_op("assign", inputs={"X": [masked]},
                          outputs={"Out": [cvar]})
    return prog


def probe_cache_len(step_main, prefix):
    """The decode capacity (cache time axis) of a step program."""
    for n, v in step_main.global_block().vars.items():
        if n.startswith(prefix + "_kcache_"):
            return int(v.shape[2])
    raise ValueError("no %s_kcache_* vars in the step program" % prefix)


def probe_cache_dtype(step_main, prefix):
    """The declared cache dtype of a step program (programs sharing one
    scope's cache vars must agree, or writes silently land in whichever
    dtype the executed startup created)."""
    for n, v in step_main.global_block().vars.items():
        if n.startswith(prefix + "_kcache_"):
            return str(v.dtype)
    raise ValueError("no %s_kcache_* vars in the step program" % prefix)


def make_cache_reorder_program(named_shapes, batch):
    """Program that gathers every named persistable cache along its batch
    axis by the fed `parents` [batch] row ids and assigns it back — the
    beam-search cache-shuffling step (run with fetch_list=[]).
    named_shapes entries: (name, shape) or (name, shape, dtype)."""
    import paddle_tpu as fluid

    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        parents = layers.data("parents", shape=[batch], dtype="int64",
                              append_batch_size=False)
        blk = prog.global_block()
        for entry in named_shapes:
            cname, shape = entry[0], entry[1]
            dtype = entry[2] if len(entry) > 2 else "float32"
            cvar = blk.create_var(name=cname, shape=list(shape),
                                  dtype=dtype, persistable=True)
            g = layers.gather(cvar, parents)
            blk.append_op("assign", inputs={"X": [g]},
                          outputs={"Out": [cvar]})
    return prog


def make_row_copy_program(named_pairs, n_dst, dtype="float32"):
    """make_slot_reset_program generalized to CROSS-POOL row copies (the
    prefix-cache load/store step): for every (src_name, src_shape,
    dst_name, dst_shape) pair, gather `n_dst` rows of the [R, ...] src
    persistable by the fed `copy_src_rows` ids and lerp them into the
    [n_dst, ...] dst persistable under the fed `copy_take` / `copy_keep`
    [n_dst] row masks (callers pass keep = 1 - take; take=0 rows keep
    dst bytes untouched).  ONE compiled program covers every row
    assignment — the ids and masks are feeds, so admission churn and
    prefix registration never retrace.  Pair entries may append a
    per-pair dtype overriding `dtype` (bf16 caches copy in bf16: the
    f32 masks promote, the cast restores)."""
    import paddle_tpu as fluid

    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        rows = layers.data("copy_src_rows", shape=[n_dst], dtype="int64",
                           append_batch_size=False)
        take = layers.data("copy_take", shape=[n_dst], dtype="float32",
                           append_batch_size=False)
        keep = layers.data("copy_keep", shape=[n_dst], dtype="float32",
                           append_batch_size=False)
        blk = prog.global_block()
        for entry in named_pairs:
            src_name, src_shape, dst_name, dst_shape = entry[:4]
            vdtype = entry[4] if len(entry) > 4 else dtype
            assert int(dst_shape[0]) == n_dst, (dst_name, dst_shape, n_dst)
            assert list(src_shape[1:]) == list(dst_shape[1:]), (
                src_name, src_shape, dst_name, dst_shape)
            src = blk.create_var(name=src_name, shape=list(src_shape),
                                 dtype=vdtype, persistable=True)
            dst = blk.create_var(name=dst_name, shape=list(dst_shape),
                                 dtype=vdtype, persistable=True)
            g = layers.gather(src, rows)
            mixed = layers.elementwise_add(
                layers.elementwise_mul(g, take, axis=0),
                layers.elementwise_mul(dst, keep, axis=0))
            if str(vdtype) != "float32":
                mixed = layers.cast(mixed, str(vdtype))
            blk.append_op("assign", inputs={"X": [mixed]},
                          outputs={"Out": [dst]})
    return prog


def validate_cached_call(step_main, prefix, ids_var, batch, prompt_len,
                         new_tokens, beams=1):
    """Shared prologue checks for every cached-decode entry point: a
    non-empty prompt, the step program's static batch, and the cache
    capacity bound (the last generated token is never fed back, hence
    the +1)."""
    assert prompt_len >= 1, (
        "empty prompt: seed generation with at least a BOS token")
    step_b = int(step_main.global_block().vars[ids_var].shape[0])
    assert batch * beams == step_b, (
        "prompt batch %d x beams %d != decode program's static batch %d"
        % (batch, beams, step_b))
    t_cache = probe_cache_len(step_main, prefix)
    assert prompt_len + new_tokens <= t_cache + 1, (
        "prompt %d + new %d exceeds cache length %d"
        % (prompt_len, new_tokens, t_cache))
    return t_cache


def run_chunked_ids(exe, main, fetches, ids, width, t_max, ids_feed,
                    has_pos_vec):
    """Shared chunk driver for the width-W cached programs (gpt2 prefill
    and seq2seq force-decode): yields (c0, chunk_logits) per dispatch.
    The last chunk re-anchors to t_max - W when it would write past the
    cache (rewriting identical slots is idempotent) and short chunks
    zero-pad (pad rows' K/V land in slots overwritten before first
    attention; pad output rows are the caller's to ignore)."""
    ids = np.asarray(ids, "int64")
    _b, T = ids.shape
    width = int(width)
    starts = list(range(0, T, width)) or [0]
    if starts[-1] + width > t_max:
        starts[-1] = max(0, t_max - width)
    for c0 in starts:
        chunk = ids[:, c0:c0 + width]
        if chunk.shape[1] < width:
            chunk = np.pad(chunk, ((0, 0), (0, width - chunk.shape[1])))
        feed = {ids_feed: chunk, "pos": np.array([c0], "int64")}
        if has_pos_vec:
            feed["pos_vec"] = np.minimum(
                np.arange(c0, c0 + width, dtype="int64"), t_max - 1)
        (lg,) = exe.run(main, feed=feed, fetch_list=fetches)
        yield c0, np.asarray(lg)


def filtered_probs(logits, temperature=1.0, top_k=0, top_p=1.0):
    """[B, V] -> the temperature / top-k / nucleus filtered probability
    rows that sample_from_logits draws from (exposed separately for the
    speculative-sampling accept/residual math)."""
    lg = np.asarray(logits, np.float64) / max(temperature, 1e-6)
    if top_k:
        k_eff = min(int(top_k), lg.shape[-1])  # top_k >= vocab: no-op
        kth = np.sort(lg, axis=-1)[:, -k_eff][:, None]
        lg = np.where(lg < kth, -np.inf, lg)
    probs = np.exp(lg - lg.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    if top_p < 1.0:
        order = np.argsort(-probs, axis=-1)
        sorted_p = np.take_along_axis(probs, order, -1)
        keep_sorted = np.cumsum(sorted_p, -1) - sorted_p < top_p
        keep = np.zeros_like(probs, bool)
        np.put_along_axis(keep, order, keep_sorted, -1)
        probs = np.where(keep, probs, 0.0)
        probs /= probs.sum(-1, keepdims=True)
    return probs


def sample_rows(probs, rng):
    """Categorical draw per row of a [B, V] probability matrix."""
    return np.array([rng.choice(probs.shape[-1], p=probs[i])
                     for i in range(probs.shape[0])], "int64")


def sample_from_logits(logits, rng, temperature=1.0, top_k=0, top_p=1.0):
    """Temperature / top-k / nucleus (top-p) filtered categorical sampling
    shared by the gpt2 and transformer samplers.  logits [B, V] -> [B]."""
    return sample_rows(
        filtered_probs(logits, temperature, top_k, top_p), rng)


# ---------------------------------------------------------------------------
# per-request keyed sampling (the continuous-batching exactness enabler)
# ---------------------------------------------------------------------------
# sample_rows draws every row from ONE shared rng stream, so a request's
# sample at step t depends on its slot index and on how many neighbors
# drew before it — under admission churn the same request would sample
# differently.  The keyed variants below make each draw a PURE FUNCTION
# of (request seed, request step): fold_in_seed mixes the pair into an
# independent 32-bit key (splitmix64 finalizer — the numpy analog of
# jax.random.fold_in) and the row draws from its own RandomState.  A
# request's sample stream is then identical whether it runs solo or
# shares a pool with any neighbors, admitted at any time.

_SPLITMIX_GAMMA = 0x9E3779B97F4A7C15


def _splitmix64(z):
    z = (z + _SPLITMIX_GAMMA) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return z ^ (z >> 31)


def fold_in_seed(seed, step):
    """Derive the 32-bit rng key for (request seed, request step) —
    deterministic, order-free, neighbor-free.  Both inputs pass through
    the full-width splitmix finalizer BEFORE combining, so every bit of
    an arbitrary-width seed lands in the key (a shift-based combine
    would silently alias seeds differing only in high bits)."""
    m64 = 0xFFFFFFFFFFFFFFFF
    z = _splitmix64(_splitmix64(int(seed) & m64)
                    ^ _splitmix64((int(step) & m64) ^ _SPLITMIX_GAMMA))
    return int(z & 0xFFFFFFFF)


def sample_rows_keyed(probs, seeds, steps):
    """Categorical draw per row of a [B, V] probability matrix where row
    i draws from RandomState(fold_in_seed(seeds[i], steps[i])) — the
    vectorized-per-row twin of sample_rows whose output is independent
    of batch composition and slot order."""
    probs = np.asarray(probs)
    seeds = np.asarray(seeds).reshape(-1)
    steps = np.asarray(steps).reshape(-1)
    out = np.empty(probs.shape[0], "int64")
    for i in range(probs.shape[0]):
        rng = np.random.RandomState(fold_in_seed(seeds[i], steps[i]))
        out[i] = rng.choice(probs.shape[-1], p=probs[i])
    return out


def filtered_probs_rows(logits, temperatures, top_ks, top_ps):
    """filtered_probs with PER-ROW sampling params (heterogeneous
    requests sharing one serving dispatch), VECTORIZED: one pass over
    the whole [N, V] block instead of PR 9's per-row python loop (the
    documented "loops per row; vectorize if pools grow" limit).

    Bit-exactness contract: every row's output is BIT-IDENTICAL to
    ``filtered_probs(logits[i:i+1], t[i], k[i], p[i])`` — the same
    float64 op sequence runs elementwise, and the top-k / top-p stages
    apply only to the subset of rows whose solo run would enter those
    branches (a ``where`` with an all-false mask still perturbs nothing,
    but the solo path's SKIPPED renormalization must be skipped here
    too).  top_k must be >= 0 (0 = off), as everywhere else.
    ``tests/test_serving.py`` pins the row-loop equivalence."""
    lg = np.asarray(logits, np.float64).copy()
    n, v = lg.shape
    t = np.array([max(float(x), 1e-6) for x in temperatures], np.float64)
    lg /= t[:, None]
    ks = np.array([int(x) for x in top_ks])
    kr = np.nonzero(ks)[0]
    if kr.size:
        k_eff = np.minimum(ks[kr], v)  # top_k >= vocab: no-op
        srt = np.sort(lg[kr], axis=-1)
        kth = np.take_along_axis(srt, (v - k_eff)[:, None], -1)
        lg[kr] = np.where(lg[kr] < kth, -np.inf, lg[kr])
    probs = np.exp(lg - lg.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    ps = np.array([float(x) for x in top_ps], np.float64)
    pr = np.nonzero(ps < 1.0)[0]
    if pr.size:
        sub = probs[pr]
        order = np.argsort(-sub, axis=-1)
        sorted_p = np.take_along_axis(sub, order, -1)
        keep_sorted = np.cumsum(sorted_p, -1) - sorted_p < ps[pr][:, None]
        keep = np.zeros_like(sub, bool)
        np.put_along_axis(keep, order, keep_sorted, -1)
        sub = np.where(keep, sub, 0.0)
        sub /= sub.sum(-1, keepdims=True)
        probs[pr] = sub
    return probs


# ---------------------------------------------------------------------------
# speculative-decoding resolver primitives (shared by solo and pooled)
# ---------------------------------------------------------------------------
# gpt2's solo speculative loops and the serving engine's in-pool rounds
# resolve draft-vs-target with the SAME math, hoisted here.  The greedy
# rule (longest draft==argmax prefix) and the residual distribution are
# direct refactors of the former inline closures — bit-identical.
#
# The KEYED accept rule is the pooled twin of solo rejection sampling:
# every sub-draw (draft proposal, accept uniform, residual pick) is
# keyed by (request seed, stream tag, GLOBAL token index), so the token
# emitted at index t is a pure function of (seed, t, token prefix) —
# independent of round boundaries, batch neighbors, admission order,
# and failover replay restarts.  The price of that purity: a fully
# accepted round emits NO free bonus token (the bonus has no draft
# proposal, so it would leak round structure into the stream).  Greedy
# keeps its bonus — argmax is already prefix-pure.

_SPEC_TAG_DRAFT = 0x5D01
_SPEC_TAG_ACCEPT = 0x5D02
_SPEC_TAG_RESID = 0x5D03


def greedy_accept_len(tgt_next, drafts):
    """Longest prefix j such that every batch row's draft token equals
    the target argmax at every position < j.  tgt_next [B, K] int64,
    drafts: list of [B] arrays (may be shorter than K)."""
    j = 0
    while j < len(drafts) and bool((drafts[j] == tgt_next[:, j]).all()):
        j += 1
    return j


def residual_probs(pt, pd):
    """The rejection-sampling residual normalize(max(pt - pd, 0)) per
    row ([..., V] in, same shape out); degenerate rows (pt <= pd
    everywhere, residual mass ~0) fall back to pt."""
    resid = np.maximum(np.asarray(pt, np.float64)
                       - np.asarray(pd, np.float64), 0.0)
    rs = resid.sum(-1, keepdims=True)
    return np.where(rs > 1e-12, resid / np.maximum(rs, 1e-12), pt)


def spec_key(seed, tag, step):
    """Key for ONE speculative sub-draw at global token index `step`:
    a distinct fold_in_seed stream per tag, so the three draws at one
    index are independent of each other and none collides with the
    plain sampler's fold_in_seed(seed, step) stream."""
    return fold_in_seed(fold_in_seed(seed, tag), step)


def spec_propose_keyed(pd_row, seed, step):
    """The draft proposal at global token index `step`: one categorical
    draw from the filtered draft row, keyed — re-derivable anywhere."""
    rng = np.random.RandomState(spec_key(seed, _SPEC_TAG_DRAFT, step))
    return int(rng.choice(pd_row.shape[-1], p=pd_row))


def spec_accept_keyed(d, pt_row, pd_row, seed, step):
    """Resolve proposal `d` at global token index `step` against the
    filtered target row: accept with probability min(1, pt[d]/pd[d]),
    else draw the residual.  Returns (token, accepted).  Output
    distribution is exactly the target row (standard per-token
    rejection sampling)."""
    u = np.random.RandomState(
        spec_key(seed, _SPEC_TAG_ACCEPT, step)).rand()
    ratio = float(pt_row[d]) / max(float(pd_row[d]), 1e-12)
    if u <= ratio:
        return int(d), True
    resid = residual_probs(pt_row[None, :], pd_row[None, :])[0]
    rng = np.random.RandomState(spec_key(seed, _SPEC_TAG_RESID, step))
    return int(rng.choice(resid.shape[-1], p=resid)), False


def spec_token_keyed(pt_row, pd_row, seed, step):
    """Propose + resolve in one call — the per-index token rule used
    wherever a round structure is NOT available (first token after
    prefill, capacity-tail width-1 steps).  Identical composition to a
    round's propose-then-accept, so streams never fork on path."""
    d = spec_propose_keyed(pd_row, seed, step)
    return spec_accept_keyed(d, pt_row, pd_row, seed, step)
