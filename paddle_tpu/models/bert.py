"""BERT-base pretraining model (BASELINE.json config 3: "BERT-base
pretraining (fluid ops, Pallas fused attention, DP)").

Encoder-only transformer built from the same blocks (and the same
TP-rule-compatible parameter names) as models/transformer.py: token +
position + segment embeddings -> N post-LN encoder layers -> masked-LM
head over every position (masked positions selected by a weight feed — the
static-shape TPU form of the gather-based MLM head) + next-sentence head
on the [CLS] vector.  hp.fused_attn routes attention through the
fused/flash kernel with the rank-1 key-padding bias.
"""

import numpy as np

from .. import layers, unique_name
from ..initializer import Normal
from ..param_attr import ParamAttr
from . import transformer as tfm

__all__ = ["BertConfig", "bert_encoder", "bert_pretrain_program", "make_fake_bert_batch"]


class BertConfig:
    """bert-base shape defaults; subclass to shrink for tests."""

    vocab_size = 30522
    type_vocab_size = 2
    max_position = 512
    d_model = 768
    d_inner_hid = 3072
    n_head = 12
    n_layer = 12
    dropout = 0.1
    fused_attn = False
    recompute = False  # rematerialize each encoder layer in backward
    label_smooth_eps = 0.0  # encoder reuses tfm blocks; unused here
    partition_family = "bert"


def _emb_table(name):
    return ParamAttr(
        name=unique_name.generate(name), initializer=Normal(0.0, 0.02)
    )


def bert_encoder(src_ids, seg_ids, attn_bias, hp, is_test=False, kpad_bias=None):
    """[B, T] ids -> [B, T, d_model] sequence output."""
    tok = layers.embedding(
        src_ids, size=[hp.vocab_size, hp.d_model],
        param_attr=_emb_table("emb.w"),
    )
    seg = layers.embedding(
        seg_ids, size=[hp.type_vocab_size, hp.d_model],
        param_attr=_emb_table("seg_emb.w"),
    )
    # learned position table (BERT uses trained positions, not sinusoids)
    pos_table = layers.create_parameter(
        shape=[hp.max_position, hp.d_model],
        dtype="float32",
        attr=_emb_table("pos_emb.w"),
    )
    seq_len = src_ids.shape[1]
    pos = layers.slice(pos_table, axes=[0], starts=[0], ends=[seq_len])
    x = layers.elementwise_add(
        layers.elementwise_add(tok, seg), pos, axis=1
    )
    x = layers.layer_norm(x, begin_norm_axis=2)
    if hp.dropout and not is_test:
        x = layers.dropout(x, hp.dropout, is_test=is_test)
    for _ in range(hp.n_layer):
        if getattr(hp, "recompute", False) and not is_test:
            x = layers.recompute(
                lambda h: tfm.encoder_layer(
                    h, attn_bias, hp, is_test, kpad_bias=kpad_bias
                ),
                x,
            )
        else:
            x = tfm.encoder_layer(x, attn_bias, hp, is_test,
                                  kpad_bias=kpad_bias)
    return x


def bert_pretrain_program(hp=BertConfig, seq_len=128, lr=1e-4, is_test=False,
                          use_bf16=False, mesh=None):
    """Build (main, startup, feeds, [total, mlm, nsp]) for MLM+NSP
    pretraining.  Feeds:
      src_ids/seg_ids [B, T] int64; input_mask [B, T] float (1 = real);
      mlm_labels [B, T] int64 (label at masked slots, anything elsewhere);
      mlm_weight [B, T] float (1 at masked slots);
      nsp_label [B, 1] int64.
    """
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_ids", shape=[seq_len], dtype="int64")
        seg = layers.data("seg_ids", shape=[seq_len], dtype="int64")
        mask = layers.data("input_mask", shape=[seq_len], dtype="float32")
        mlm_lbl = layers.data("mlm_labels", shape=[seq_len], dtype="int64")
        mlm_w = layers.data("mlm_weight", shape=[seq_len], dtype="float32")
        nsp_lbl = layers.data("nsp_label", shape=[1], dtype="int64")

        # additive key bias from the mask: 0 at real tokens, -1e9 at pads
        kpad = layers.scale(mask, scale=1e9, bias=-1e9)
        kpad.stop_gradient = True
        if getattr(hp, "fused_attn", False):
            attn_bias, kpad_bias = None, kpad
        else:
            attn_bias = layers.unsqueeze(layers.unsqueeze(kpad, [1]), [1])
            kpad_bias = None

        enc = bert_encoder(src, seg, attn_bias, hp, is_test, kpad_bias)

        # masked-LM head: transform + vocab logits at EVERY position,
        # loss weighted to the masked slots (static shapes; the gather
        # form of the original would be dynamic)
        mlm_h = layers.fc(enc, size=hp.d_model, num_flatten_dims=2,
                          act="gelu", param_attr=_emb_table("mlm_trans.w"))
        mlm_h = layers.layer_norm(mlm_h, begin_norm_axis=2)
        mlm_logits = layers.fc(
            mlm_h, size=hp.vocab_size, num_flatten_dims=2, bias_attr=False,
            param_attr=_emb_table("softmax_out.w"),
        )
        mlm_cost = layers.softmax_with_cross_entropy(
            mlm_logits, layers.unsqueeze(mlm_lbl, [2])
        )
        mlm_cost = layers.elementwise_mul(mlm_cost, layers.unsqueeze(mlm_w, [2]))
        # epsilon-guarded denominator: a batch with zero masked slots must
        # yield loss 0, not 0/0 = NaN poisoning every weight
        denom = layers.clip(layers.reduce_sum(mlm_w), 1e-5, 1e30)
        mlm_loss = layers.elementwise_div(
            layers.reduce_sum(mlm_cost), denom
        )

        # next-sentence head on [CLS] (position 0)
        cls = layers.squeeze(layers.slice(enc, axes=[1], starts=[0], ends=[1]), [1])
        pooled = layers.fc(cls, size=hp.d_model, act="tanh",
                           param_attr=_emb_table("pooler.w"))
        nsp_logits = layers.fc(pooled, size=2,
                               param_attr=_emb_table("nsp.w"))
        nsp_loss = layers.mean(
            layers.softmax_with_cross_entropy(nsp_logits, nsp_lbl)
        )
        total = layers.elementwise_add(mlm_loss, nsp_loss)

        # logits-free MLM loss (the [B, T, V] f32 logits never reach HBM
        # under FLAGS_use_pallas) + matmul-epilogue fusions, applied
        # before minimize so grads differentiate through the fused ops
        from ..transpiler.pass_registry import apply_pass

        apply_pass(main, "linear_xent_fuse_pass")
        apply_pass(main, "matmul_epilogue_fuse_pass")
        if use_bf16:
            apply_pass(main, "bf16_amp_pass")
        # HBM-budgeted remat (FLAGS_hbm_budget_bytes; no-op when unset);
        # the flag is a per-device budget, so a mesh scales it
        from ..transpiler.remat import maybe_remat

        maybe_remat(main, total, is_test, mesh=mesh)
        if not is_test:
            fluid.optimizer.Adam(learning_rate=lr).minimize(total)

    if mesh is not None:
        # GSPMD training stamp: bert-family rules lifted to training
        # names (grads + Adam moments shard like their param), batch
        # feeds over the mesh's dp axis
        from ..parallel.partition_rules import (annotate_spmd,
                                                train_partition_rules_for)

        annotate_spmd(main, mesh, train_partition_rules_for(
            getattr(hp, "partition_family", "bert")))
    feeds = ["src_ids", "seg_ids", "input_mask", "mlm_labels", "mlm_weight",
             "nsp_label"]
    return main, startup, feeds, [total, mlm_loss, nsp_loss]


def make_fake_bert_batch(batch_size, seq_len, hp=BertConfig, seed=0,
                         mask_frac=0.15):
    rng = np.random.RandomState(seed)
    src = rng.randint(3, hp.vocab_size, (batch_size, seq_len)).astype("int64")
    lens = rng.randint(seq_len // 2, seq_len + 1, (batch_size,))
    mask = (np.arange(seq_len)[None, :] < lens[:, None]).astype("float32")
    seg_split = rng.randint(1, seq_len, (batch_size,))
    seg = (np.arange(seq_len)[None, :] >= seg_split[:, None]).astype("int64")
    mlm_w = (rng.rand(batch_size, seq_len) < mask_frac).astype("float32") * mask
    mlm_w[:, 0] = 1.0  # guarantee at least one masked slot per row
    labels = src.copy()
    src = np.where(mlm_w > 0, 1, src)  # [MASK] id = 1
    nsp = rng.randint(0, 2, (batch_size, 1)).astype("int64")
    return {
        "src_ids": src, "seg_ids": seg, "input_mask": mask,
        "mlm_labels": labels, "mlm_weight": mlm_w, "nsp_label": nsp,
    }
