"""paddle_tpu.incubate — the experimental/advanced surface.

The reference snapshot (Fluid ~1.x, late 2018) predates the fleet /
incubate API; this package is where the TPU-native capabilities that
later Paddle generations homed under `paddle.incubate` live, re-exported
from their implementation modules:

- gradient merge / accumulation     -> GradientMergeOptimizer
- sequence/context parallelism      -> ring_attention, ulysses_attention
- expert parallelism                -> switch_moe (top-1/top-2 GShard)
- pipeline parallelism              -> pipeline (GPipe + 1F1B schedules)
- ZeRO-1/3 parameter sharding       -> zero1_rules / zero3_rules
- mixed precision                   -> rewrite_bf16 / rewrite_fp16
- high-level trainer w/ checkpoints -> paddle_tpu.contrib.trainer
- distributed roles/transpile       -> paddle_tpu.transpiler +
                                       paddle_tpu.distributed
"""

from ..contrib.mixed_precision import rewrite_bf16, rewrite_fp16
from ..optimizer import GradientMergeOptimizer
from ..parallel import moe, pipeline, ring, sharding, ulysses
from ..parallel.sharding import zero1_rules, zero3_rules
from ..parallel.moe import switch_moe
from ..parallel.ring import ring_attention
from ..parallel.ulysses import ulysses_attention

__all__ = [
    "GradientMergeOptimizer",
    "rewrite_bf16",
    "rewrite_fp16",
    "ring_attention",
    "ulysses_attention",
    "switch_moe",
    "moe",
    "pipeline",
    "ring",
    "ulysses",
    "sharding",
    "zero1_rules",
    "zero3_rules",
]
