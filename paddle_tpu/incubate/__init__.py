"""paddle_tpu.incubate — namespace parity.

The reference snapshot (Fluid ~1.x, late 2018) predates the fleet /
incubate API surface; this package exists so `import paddle_tpu.incubate`
resolves for forward-compatible user code. The capabilities that later
moved here already live elsewhere in this framework:

- high-level trainer with checkpointing  -> paddle_tpu.contrib.trainer
- distributed roles/transpile           -> paddle_tpu.transpiler +
                                           paddle_tpu.distributed
- mixed precision                       -> paddle_tpu.contrib.mixed_precision
"""
