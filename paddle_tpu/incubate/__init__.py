"""paddle_tpu.incubate"""
