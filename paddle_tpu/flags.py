"""Runtime flag registry (the gflags surface, SURVEY §5.6).

The reference defines ~31 gflags in C++ and exports an allowlist to Python
via __init__.py __bootstrap__ (:85) -> core.init_gflags.  Here the registry
is the single source of truth; values load from the environment at import:

* `FLAGS_<name>=value` env vars (the reference's exact contract), or
* `PADDLE_TPU_FLAGS="--name=value --other=v"` batch form.

Wired flags: check_nan_inf (executor fetch scan), benchmark (per-run
timing log), rpc_deadline / max_retry (RPC client), enable_rpc_profiler
(RecordEvent spans around RPC calls), heartbeat_interval /
eviction_deadline (trainer liveness + pserver barrier eviction,
docs/FAULT_TOLERANCE.md), async_journal / async_staleness_bound /
sparse_hot_rows / sparse_hot_ttl (durable async sparse: write-ahead
journal, bounded staleness, trainer-side hot-row prefetch cache —
docs/FAULT_TOLERANCE.md "Durable async sparse").  The remaining knobs
are accepted
for script compatibility and are no-ops under XLA (their help text says
so) — memory budgeting belongs to PJRT and fusion to the compiler.

Liveness-pair validation: eviction_deadline must exceed
heartbeat_interval, or every healthy trainer would miss its own liveness
deadline between beats (a self-evicting job).  The registry validates the
pair at load time and on set_flags(), warning and CLAMPING the deadline
to 3x the interval instead of silently configuring a broken job.

Self-healing knobs that are NOT FLAGS_: the supervisor restart policy
(--supervise / --max-restarts / --restart-window / --restart-backoff /
--ckpt-dir) is per-launch CLI surface on paddle_tpu.distributed.launch,
and pserver incarnation numbers are minted automatically per start
(persisted next to the checkpoint) — see docs/FAULT_TOLERANCE.md.
"""

import os

__all__ = ["DEFINE_flag", "get_flag", "set_flags", "flag_items"]

_flags = {}


class _Flag:
    __slots__ = ("name", "value", "default", "help")

    def __init__(self, name, default, help):
        self.name = name
        self.default = default
        self.value = default
        self.help = help


def _coerce(default, raw):
    if isinstance(default, bool):
        return str(raw).lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


def DEFINE_flag(name, default, help=""):
    f = _Flag(name, default, help)
    env = os.environ.get("FLAGS_" + name)
    if env is not None:
        f.value = _coerce(default, env)
    _flags[name] = f
    return f


def get_flag(name):
    return _flags[name].value


def set_flags(mapping):
    """dict name->value, applied with type coercion (init_gflags analog)."""
    for name, value in mapping.items():
        key = name[len("FLAGS_"):] if name.startswith("FLAGS_") else name
        if key not in _flags:
            raise KeyError("unknown flag %s (known: %s)" % (key, sorted(_flags)))
        f = _flags[key]
        f.value = _coerce(f.default, value)
    _validate_liveness_pair()


def _validate_liveness_pair():
    """eviction_deadline <= heartbeat_interval configures a SELF-EVICTING
    job: a healthy trainer goes 'silent' for one full interval between
    beats, so the deadline must comfortably exceed it.  Warn and clamp
    to 3x the interval (one lost beat + scheduling slack) rather than
    letting the misconfiguration eat the cluster at the first barrier."""
    if "eviction_deadline" not in _flags or "heartbeat_interval" not in _flags:
        return  # registry still loading
    hb = _flags["heartbeat_interval"].value
    ev = _flags["eviction_deadline"]
    if hb > 0 and ev.value <= hb:
        import sys

        clamped = 3.0 * float(hb)
        sys.stderr.write(
            "WARNING: FLAGS_eviction_deadline=%.3g <= "
            "FLAGS_heartbeat_interval=%.3g would evict healthy trainers "
            "between beats; clamping eviction_deadline to %.3g\n"
            % (ev.value, hb, clamped))
        ev.value = clamped


def flag_items():
    return {name: f.value for name, f in sorted(_flags.items())}


def _parse_batch_env():
    batch = os.environ.get("PADDLE_TPU_FLAGS", "")
    for tok in batch.split():
        if tok.startswith("--") and "=" in tok:
            k, v = tok[2:].split("=", 1)
            if k in _flags:
                f = _flags[k]
                f.value = _coerce(f.default, v)


# ---- the reference's knob surface (CMakeLists/bootstrap allowlist) -------
DEFINE_flag("check_nan_inf", False,
            "scan every fetched value for NaN/Inf and raise (operator.cc:688)")
DEFINE_flag("benchmark", False, "log wall time of every Executor.run")
DEFINE_flag("eager_delete_tensor_gb", -1.0,
            "compat no-op: XLA frees temps inside the step; rw state is "
            "donated unconditionally")
DEFINE_flag("fraction_of_gpu_memory_to_use", 0.92,
            "HBM budget fraction: forwarded to the XLA client allocator "
            "(memory.apply_memory_fraction) when set via FLAGS_... env "
            "before the first backend init")
DEFINE_flag("init_allocated_mem", False, "compat no-op under XLA")
DEFINE_flag("free_idle_memory", False, "compat no-op under XLA")
DEFINE_flag("paddle_num_threads", 1, "compat no-op (XLA owns threading)")
DEFINE_flag("dist_threadpool_size", 0,
            "compat no-op (pserver threads are per-connection)")
DEFINE_flag("rpc_deadline", 180000, "RPC timeout in ms (grpc deadline)")
DEFINE_flag("max_retry", 30, "RPC connect retries")
DEFINE_flag("heartbeat_interval", 2.0,
            "trainer->pserver liveness heartbeat period in seconds; a "
            "background sender starts with the first pserver RPC "
            "(0 disables heartbeats and therefore eviction)")
DEFINE_flag("eviction_deadline", 20.0,
            "seconds without any contact (heartbeat or verb) after which "
            "a heartbeat-tracked trainer is declared dead and evicted "
            "from the sync round — pending barriers re-evaluate against "
            "the surviving live set instead of hanging forever")
DEFINE_flag("enable_rpc_profiler", False, "RecordEvent spans around RPC")
DEFINE_flag("comm_bucket_bytes", 4 * 1024 * 1024,
            "size cap (bytes) for coalesced grad/param buckets in pserver "
            "mode: DistributeTranspiler groups small blocks into buckets "
            "and each bucket ships as ONE rpc frame per pserver instead "
            "of one round trip per variable (0 restores the legacy "
            "per-variable send/recv ops)")
DEFINE_flag("comm_wire_dtype", "float32",
            "wire dtype for dense bucket grads and fetched params on the "
            "pserver path: 'float32' (default — byte-identical legacy "
            "frames, bit-exact dist-vs-local parity) or 'bfloat16' (halves "
            "comm bytes; the trainer casts grads at the RPC boundary and "
            "the pserver casts fetched params in its replies, both "
            "decompressed back to the original dtype at decode).  The "
            "transpiler stamps the value into the bucket plan so both "
            "ends agree; the legacy per-variable ops "
            "(FLAGS_comm_bucket_bytes=0) always ship full precision")
DEFINE_flag("comm_grad_int8", False,
            "int8 + error-feedback wire compression for dense bucket "
            "grads (quarter-size frames): each block ships as int8 with a "
            "per-block scale, the quantization residual is kept "
            "TRAINER-side and added into the same block's grad next "
            "round, so the quantization error is corrected over time "
            "instead of accumulating (an approximation — see "
            "docs/PERFORMANCE.md).  Applies to grads only; fetched "
            "params follow FLAGS_comm_wire_dtype")
DEFINE_flag("ps_fused_apply", True,
            "pserver sync rounds apply the optimizer with ONE jitted "
            "fused call per (optimizer, dtype) group of shard blocks "
            "(blocks padded + stacked, lr read once per round) instead "
            "of one executor program run per block; shard programs the "
            "fuser cannot prove equivalent fall back to the per-block "
            "path automatically (0 disables the fused path entirely)")
DEFINE_flag("async_journal", True,
            "async pserver mode: append every applied sparse chunk / dense "
            "bucket to a crc-framed, fsync'd write-ahead journal next to "
            "the checkpoint (rotated at each snapshot).  A restarted "
            "incarnation replays journal-after-snapshot, so an async "
            "restart loses ZERO applied updates; corrupt/truncated tail "
            "records are skipped cold with a counter, like corrupt "
            "snapshots.  Needs a checkpoint dir; 0 restores the old "
            "lose-since-last-checkpoint behavior")
DEFINE_flag("async_staleness_bound", 0,
            "async pserver mode: park pushes/prefetches from a trainer "
            "whose logical clock (its per-table send_sparse seq tokens) "
            "runs more than this many steps ahead of the slowest live "
            "peer, releasing when the laggard catches up or departs "
            "(eviction/complete frees the bound).  0 = unbounded — the "
            "pre-bound fire-and-forget behavior")
DEFINE_flag("sparse_hot_rows", 0,
            "async pserver mode: trainer-side hot-row cache capacity (rows "
            "per table) for distributed-lookup prefetch.  Hits skip the "
            "prefetch RPC; pushed grads update the cached copy through "
            "the table's own optimizer rule (sgd mirrors exactly), and "
            "entries refresh from the server every "
            "FLAGS_sparse_hot_ttl steps so multi-trainer drift is "
            "corrected instead of accumulating.  Only engages where the "
            "mirror is exact: sgd, constant lr, uncompressed f32 sparse "
            "wire (a bf16 wire means the server applies DECODED grads "
            "the client does not hold).  0 disables the cache")
DEFINE_flag("sparse_hot_ttl", 8,
            "steps a hot-row cache entry may serve before it must be "
            "re-fetched from its pserver (the drift-correction refresh "
            "for FLAGS_sparse_hot_rows)")
DEFINE_flag("elastic_replan", True,
            "elastic autoscaling (docs/FAULT_TOLERANCE.md): trainers "
            "re-derive their bucket/shard plan at runtime (transpiler."
            "derive_plan over the program-carried plan spec) when a "
            "pserver mints a new plan epoch — membership changed "
            "durably — correcting the baked 1/N grad scale to the live "
            "world and fencing stale-epoch frames like stale "
            "incarnations.  For an unchanged world the re-derived plan "
            "is bit-identical to the transpile-time plan and the "
            "correction is exactly 1.0 (skipped), so static jobs are "
            "unaffected.  0 pins the transpile-time plan forever (the "
            "pre-elastic behavior: a dead trainer leaves the job "
            "under-scaled, an added one cannot contribute)")
DEFINE_flag("comm_inflight", 4,
            "window of in-flight bucket RPCs per pserver endpoint: bucket "
            "N+1 serializes and sends while bucket N is on the wire; "
            "send_barrier / the next recv drains the window (1 = fully "
            "serial, the pre-pipelining behavior)")
DEFINE_flag("feed_prefetch", 2,
            "depth of the reader.feed_prefetch double buffer: batch N+1 "
            "is device_put on a background thread while step N computes "
            "(0 disables staging; the decorator passes batches through)")
DEFINE_flag("cudnn_deterministic", False,
            "compat; XLA compilation is deterministic already")
DEFINE_flag("use_mkldnn", False, "compat no-op (XLA owns fusion)")
DEFINE_flag("use_pallas", False,
            "dispatch hot ops (attention, layer_norm) to the Pallas "
            "kernel library instead of plain XLA lowerings")
DEFINE_flag("flash_block_q", 0,
            "flash-attention q-block rows (0 = the kernel default 128); "
            "on-chip sweep knob: a multiple of 128 (or the full q "
            "length) that divides the q sequence length — the Mosaic "
            "minor-dim rule for the lse/delta specs (invalid values "
            "raise)")
DEFINE_flag("flash_block_k", 0,
            "flash-attention k-block columns (0 = default 128); a "
            "multiple of 128 (or the full k length) dividing the k "
            "sequence length")
DEFINE_flag("kernel_tune_cache", "",
            "path of the persisted per-(kernel, shape-bucket, dtype, "
            "device kind) block-size tuning cache consulted by every "
            "pallas_call site (ops/kernel_tuning.py): searched decisions "
            "are written back atomically so later processes dispatch "
            "without searching.  Empty = in-memory only for this process")
DEFINE_flag("kernel_autotune", True,
            "allow the measured block-size search at the first "
            "real-device dispatch of a (kernel, shape-bucket) the tuning "
            "cache has not seen (synthetic operands, standalone jit — "
            "compile-time work).  0 = consult-only: misses seed the "
            "heuristic default and never search (the CI regime, with a "
            "pinned FLAGS_kernel_tune_cache).  Interpret-mode (CPU) runs "
            "never search regardless — their timings are meaningless")
DEFINE_flag("hbm_budget_bytes", 0,
            "peak-activation HBM budget (bytes) for the rematerialization "
            "pass (transpiler.remat): model builders partition the forward "
            "program into checkpoint segments at detected layer boundaries "
            "and greedily mark segments for recompute (jax.checkpoint) "
            "until the traced fwd+bwd peak-activation estimate "
            "(utils.memory_analysis) fits the budget.  Marked segments "
            "recompute the SAME ops in backward, so losses are "
            "bit-identical to the unremat program.  0 disables the pass "
            "(the builders' hp.recompute knob still remats every layer "
            "unconditionally)")
DEFINE_flag("program_tune_cache", "",
            "path of the persisted per-(program-signature, shape-bucket, "
            "device kind) PROGRAM knob decision cache consulted by "
            "transpiler.autotune.tune(): searched decisions (AMP on/off, "
            "remat segments, prng impl, steps-per-dispatch window) are "
            "written back atomically so later processes apply the tuned "
            "configuration without re-searching — same bucketing "
            "discipline as FLAGS_kernel_tune_cache.  Empty = in-memory "
            "only for this process")
DEFINE_flag("program_autotune", True,
            "allow transpiler.autotune.tune() to SEARCH (clone the "
            "program per candidate knob setting, jit, and time synthetic "
            "steps) on a decision-cache miss.  0 = consult-only: misses "
            "return the all-defaults decision and never time anything "
            "(the CI regime, with a pinned FLAGS_program_tune_cache)")
DEFINE_flag("check_program", False,
            "static program verification (analysis.verify_program): "
            "apply_pass re-verifies the program after EVERY registry "
            "pass (verified-in => verified-out, the TVM pass-infra "
            "contract) and the executor verifies each program version "
            "once before its first compile — an ill-formed program "
            "fails loudly at the pass boundary with the pass and the "
            "offending op named, instead of at JAX trace time (or "
            "silently, the PR 12 half-applied-fold bug class).  ON in "
            "tests/CI (conftest + scripts/ci.sh arm it); OFF by default "
            "in production hot paths — disabled, the check is a single "
            "flag read, zero per-step cost")
DEFINE_flag("prng_impl", "threefry",
            "JAX PRNG for in-program randomness (dropout, *_random, "
            "sampling): 'threefry' (default; splittable counter stream, "
            "exact back-compat) or 'rbg' (TPU hardware generator — much "
            "cheaper mask generation in dropout-heavy models; different "
            "stream, same distribution).  Toggling recompiles.")
DEFINE_flag("tpu_bf16_matmul", False,
            "reserved: AMP is the explicit contrib.mixed_precision."
            "rewrite_bf16() program rewrite, not a global flag yet")

_parse_batch_env()
_validate_liveness_pair()
