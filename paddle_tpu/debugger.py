"""Program introspection: pretty-printer + graphviz dumps
(python/paddle/fluid/debugger.py + net_drawer.py analogs)."""

__all__ = ["pprint_program_codes", "pprint_block_codes", "draw_block_graphviz"]


def _fmt_var(block, name):
    v = block._find_var_recursive(name)
    if v is None:
        return name
    return "%s[%s,%s]" % (name, "x".join(str(d) for d in (v.shape or [])), v.dtype)


def pprint_block_codes(block, show_backward=True):
    """One line per op: outs = op_type(ins) {attrs}."""
    lines = []
    for op in block.ops:
        role = op.attrs.get("op_role", "forward")
        if not show_backward and role in ("backward", "optimize"):
            continue
        outs = ", ".join(
            _fmt_var(block, n) for names in op.outputs.values() for n in names
        )
        ins = ", ".join(
            _fmt_var(block, n) for names in op.inputs.values() for n in names
        )
        attrs = {
            k: v
            for k, v in op.attrs.items()
            if not k.startswith("__") and k not in ("op_role", "op_role_var")
        }
        attr_str = (" {%s}" % ", ".join("%s=%r" % kv for kv in sorted(attrs.items()))) if attrs else ""
        lines.append("%s = %s(%s)%s  # %s" % (outs or "_", op.type, ins, attr_str, role))
    return "\n".join(lines)


def pprint_program_codes(program, show_backward=True):
    out = []
    for i, block in enumerate(program.blocks):
        out.append("// block %d (parent %d)" % (block.idx, block.parent_idx))
        out.append(pprint_block_codes(block, show_backward))
    return "\n".join(out)


def draw_block_graphviz(block, highlights=None, path="./graph.dot"):
    """Emit a graphviz dot file: op nodes (boxes) + var nodes (ellipses),
    edges by def/use (net_drawer.py / graph_viz_pass analog).  Edge
    iteration is the shared ``analysis.graph.block_edges`` walk."""
    from .analysis.graph import block_edges

    highlights = set(highlights or ())
    lines = ["digraph G {", "  rankdir=TB;"]
    var_ids = {}

    def vid(name):
        if name not in var_ids:
            var_ids[name] = "var_%d" % len(var_ids)
            color = ' style=filled fillcolor="lightcoral"' if name in highlights else ""
            lines.append(
                '  %s [label="%s" shape=ellipse%s];'
                % (var_ids[name], _fmt_var(block, name), color)
            )
        return var_ids[name]

    for i, op, in_names, out_names in block_edges(block):
        op_id = "op_%d" % i
        lines.append(
            '  %s [label="%s" shape=box style=filled fillcolor="lightblue"];'
            % (op_id, op.type)
        )
        for n in in_names:
            lines.append("  %s -> %s;" % (vid(n), op_id))
        for n in out_names:
            lines.append("  %s -> %s;" % (op_id, vid(n)))
    lines.append("}")
    text = "\n".join(lines)
    with open(path, "w") as f:
        f.write(text)
    return text
