"""Model save/load (python/paddle/fluid/io.py analog).

The reference emits save/load ops into programs (save_op.cc); here
persistence is a host-side operation over the scope (values are pulled from
HBM and written as .npy files; the serialized Program is JSON).  API parity:
save/load_vars/params/persistables (io.py:89,204,252) and
save/load_inference_model (io.py:544,674).
"""

import json
import os

import numpy as np
import jax

from . import framework
from .executor import global_scope
from .framework import Parameter, Program

# Serialized-program format version (framework.proto:24 `Version` +
# framework/version.h analog).  Bump on incompatible __model__ layout
# changes; the loader accepts every version <= current and refuses newer
# ones (IsProgramVersionSupported semantics).  Version history:
#   0 — pre-versioning era (no "version" field in __model__)
#   1 — adds the version field itself
PROGRAM_FORMAT_VERSION = 1


def is_program_version_supported(version):
    return 0 <= int(version) <= PROGRAM_FORMAT_VERSION

__all__ = [
    "save_vars",
    "save_params",
    "save_persistables",
    "load_vars",
    "load_params",
    "load_persistables",
    "save_inference_model",
    "load_inference_model",
    "get_program_persistable_vars",
]


def _is_persistable(var):
    return var.persistable


def get_program_persistable_vars(program):
    return [v for v in program.list_vars() if v.persistable]


def _save_var(dirname, name, value):
    path = os.path.join(dirname, name.replace("/", "%2F"))
    np.save(path + ".npy", np.asarray(jax.device_get(value)))


def _load_var(dirname, name):
    path = os.path.join(dirname, name.replace("/", "%2F") + ".npy")
    return np.load(path)


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None, filename=None, scope=None):
    if main_program is None:
        main_program = framework.default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars() if predicate is None or predicate(v)]
    os.makedirs(dirname, exist_ok=True)
    scope = scope if scope is not None else global_scope()
    if filename is not None:
        blob = {}
        for v in vars:
            val = scope.find_var(v.name)
            if val is None:
                continue
            blob[v.name] = np.asarray(jax.device_get(val))
        np.savez(os.path.join(dirname, filename), **blob)
        return
    for v in vars:
        val = scope.find_var(v.name)
        if val is None:
            continue
        _save_var(dirname, v.name, val)


def save_params(executor, dirname, main_program=None, filename=None, scope=None):
    if main_program is None:
        main_program = framework.default_main_program()
    save_vars(
        executor,
        dirname,
        main_program,
        vars=[v for v in main_program.list_vars() if isinstance(v, Parameter)],
        filename=filename,
        scope=scope,
    )


def save_persistables(executor, dirname, main_program=None, filename=None, scope=None):
    if main_program is None:
        main_program = framework.default_main_program()
    save_vars(
        executor,
        dirname,
        main_program,
        vars=get_program_persistable_vars(main_program),
        filename=filename,
        scope=scope,
    )


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None, filename=None, scope=None):
    if main_program is None:
        main_program = framework.default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars() if predicate is None or predicate(v)]
    scope = scope if scope is not None else global_scope()
    if filename is not None:
        blob = np.load(os.path.join(dirname, filename))
        for v in vars:
            if v.name in blob:
                scope.set(v.name, blob[v.name])
        return
    for v in vars:
        try:
            scope.set(v.name, _load_var(dirname, v.name))
        except FileNotFoundError:
            pass


def load_params(executor, dirname, main_program=None, filename=None, scope=None):
    if main_program is None:
        main_program = framework.default_main_program()
    load_vars(
        executor,
        dirname,
        main_program,
        vars=[v for v in main_program.list_vars() if isinstance(v, Parameter)],
        filename=filename,
        scope=scope,
    )


def load_persistables(executor, dirname, main_program=None, filename=None, scope=None):
    if main_program is None:
        main_program = framework.default_main_program()
    load_vars(
        executor,
        dirname,
        main_program,
        vars=get_program_persistable_vars(main_program),
        filename=filename,
        scope=scope,
    )


def save_inference_model(
    dirname,
    feeded_var_names,
    target_vars,
    executor,
    main_program=None,
    model_filename=None,
    params_filename=None,
    export_for_deployment=True,
    scope=None,
    model_format="json",
):
    """Prune to the inference slice + save program & params (io.py:544).

    `model_format`: "json" (human-readable, default) or "pb" — the binary
    protobuf ProgramDesc (native/desc.proto), validated by the C++ codec
    when available.  The loader sniffs the format, so consumers are
    format-agnostic."""
    if main_program is None:
        main_program = framework.default_main_program()
    os.makedirs(dirname, exist_ok=True)
    pruned = main_program.clone(for_test=True)._prune(target_vars)
    feed_names = list(feeded_var_names)
    fetch_names = [
        t.name if isinstance(t, framework.Variable) else t for t in target_vars
    ]
    path = os.path.join(dirname, model_filename or "__model__")
    if model_format == "pb":
        from . import desc_codec

        data = desc_codec.program_to_bytes(pruned, feed_names, fetch_names)
        ok, msg = desc_codec.native_validate(data)
        if ok is False:  # None = native codec unavailable, skip the check
            raise RuntimeError("binary __model__ failed validation: " + msg)
        with open(path, "wb") as f:
            f.write(data)
    elif model_format == "json":
        meta = {
            "version": PROGRAM_FORMAT_VERSION,
            "program": pruned.to_json(),
            "feed_names": feed_names,
            "fetch_names": fetch_names,
        }
        with open(path, "w") as f:
            json.dump(meta, f)
    else:
        raise ValueError("model_format must be 'json' or 'pb', got %r" % model_format)
    save_persistables(executor, dirname, pruned, filename=params_filename, scope=scope)
    return fetch_names


def load_inference_model(dirname, executor, model_filename=None, params_filename=None, scope=None):
    from . import desc_codec

    path = os.path.join(dirname, model_filename or "__model__")
    with open(path, "rb") as f:
        raw = f.read()
    if desc_codec.looks_like_pb(raw):
        program, feed_names, fetch_names = desc_codec.model_from_bytes(raw)
    else:
        meta = json.loads(raw.decode("utf-8"))
        version = meta.get("version", 0)  # pre-versioning models load as v0
        if not is_program_version_supported(version):
            raise RuntimeError(
                "saved model format version %s is newer than this build "
                "supports (<= %d) — upgrade paddle_tpu to load it"
                % (version, PROGRAM_FORMAT_VERSION)
            )
        program = Program.from_json(meta["program"])
        feed_names, fetch_names = meta["feed_names"], meta["fetch_names"]
    load_persistables(executor, dirname, program, filename=params_filename, scope=scope)
    fetch_vars = [program.global_block().var(n) for n in fetch_names]
    return program, feed_names, fetch_vars
