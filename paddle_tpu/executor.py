"""User-facing Executor (python/paddle/fluid/executor.py analog).

``Executor(place).run(program, feed={...}, fetch_list=[...])`` keeps the
reference's contract (executor.py:374) but executes by compiling the program
block to one XLA computation (see core/trace.py) instead of interpreting ops.
Feed dict entries become function arguments; fetch vars become outputs; no
feed/fetch ops or feed-variable side channel are needed.
"""

import threading

import numpy as np
import jax
import jax.numpy as jnp

from . import framework
from .core import scope as scope_mod
from .core.trace import ExecutionCache
from .places import CPUPlace, default_place
from .profiler import RecordEvent

__all__ = ["Executor", "global_scope", "scope_guard"]

global_scope = scope_mod.global_scope
scope_guard = scope_mod.scope_guard

_FAST_MISS = object()  # sentinel: fast-path preconditions broke, go slow

# jax threads an ordered-io-callback TOKEN from each dispatch into the
# next, resharding it onto the new computation's devices — and in this
# jax, resharding a 1-device token onto a multi-device mesh (or back)
# trips a PjRt layout CHECK and aborts the process.  Ordered-effect
# tokens are per-thread (dispatch.RuntimeTokenSet is a threading.local),
# so track each thread's last dispatch topology and DRAIN its tokens when
# the topology changes: a pure synchronization point (every prior
# callback completes before the new regime's first one runs), after which
# the next dispatch mints a fresh token with the right sharding.  This is
# what lets the collective (mesh) trainer and the pserver (single-device)
# paths coexist in one process — the hybrid parity tests run both.
_token_regime = threading.local()


def _ensure_token_regime(key):
    prev = getattr(_token_regime, "key", None)
    if prev == key:
        return
    if prev is not None:
        # jax-private surface: absent (or reshaped) on newer jax builds,
        # where tokens are topology-safe and no drain is needed — degrade
        # to the old no-drain behavior rather than crash every run
        try:
            from jax._src import dispatch as _jax_dispatch

            tokens = getattr(_jax_dispatch, "runtime_tokens", None)
        except ImportError:  # pragma: no cover - jax internals moved
            tokens = None
        if tokens is not None:
            try:
                tokens.block_until_ready()  # also clears
            except Exception:
                try:
                    tokens.clear()
                except Exception:  # pragma: no cover - API drift
                    pass
    _token_regime.key = key


def as_numpy(value):
    """Fetch result -> numpy (executor.py:66 analog)."""
    from .lod import LoDTensor

    if isinstance(value, LoDTensor):
        return value
    if isinstance(value, (list, tuple)):
        return [as_numpy(v) for v in value]
    return np.asarray(jax.device_get(value))


def _dtype_kind(dt):
    """numpy kind with bfloat16/ml_dtypes ('V') treated as float."""
    if str(dt) == "bfloat16":
        return "f"
    k = np.dtype(str(dt)).kind
    return "f" if k == "V" else k


class Executor:
    def __init__(self, place=None):
        self.place = place if place is not None else default_place()
        self._cache = ExecutionCache()
        self._step = 0
        self._key_cache = {}
        self._closed = False
        # steady-state run() memo: (program, feed-keys, fetches, scope) ->
        # everything the slow path re-derives per step (compiled
        # executable, feed spec, state classification).  See run().
        self._run_cache = {}
        self._host_feed_ms = 0.0  # cumulative feed-upload wall time

    @property
    def host_feed_ms(self):
        """Cumulative milliseconds run() spent staging feeds onto the
        device (the host_feed_ms bench counter)."""
        return self._host_feed_ms

    @property
    def compile_count(self):
        """How many distinct program traces this executor has compiled —
        the serving engine's no-retrace contract is asserted against
        this: a continuous-batching step must compile ONCE, and then
        hold the steady-state memo across every occupancy change (slots
        going live/free change feed values, never feed signatures)."""
        return self._cache.compile_count

    def _commit_state(self, n, v, device, scope):
        """Normalize state to a COMMITTED on-device array.  Startup
        outputs are uncommitted (no committed inputs feed them) while
        train feeds are device_put -> committed; without this the first
        train run flips every param to committed and the jit cache
        misses, silently COMPILING THE WHOLE PROGRAM TWICE (minutes
        through a TPU tunnel).  Committed same-device arrays pass through
        untouched; numpy state (checkpoint loads) uploads once — the
        device array is written back to the scope so read-only weights
        are not re-uploaded per step."""
        if isinstance(v, jax.Array):
            if getattr(v, "committed", True) and device in v.devices():
                return v
        elif not isinstance(v, np.ndarray):
            return v
        arr = jax.device_put(v, device)
        scope.set(n, arr)
        return arr

    def _rng_base(self, program):
        # base key derives from the program's seed (per-program, so
        # main_program.random_seed is honored even after the startup run).
        # FLAGS_prng_impl=rbg swaps the generator for the TPU-cheap
        # hardware RBG (typed key so fold_in/bernoulli work unchanged);
        # the default stays raw threefry for exact stream back-compat.
        from .flags import get_flag

        seed = int(program.random_seed)
        impl = get_flag("prng_impl")
        base = self._key_cache.get((seed, impl))
        if base is None:
            s = seed if seed != 0 else 90157
            if impl == "threefry":
                base = jax.random.PRNGKey(s)
            else:
                base = jax.random.key(s, impl=impl)
            self._key_cache[(seed, impl)] = base
        return base

    def _rng_key(self, program):
        # folding in the step counter advances streams across runs.  The
        # fold is jitted: eagerly it binds ~6 primitives of host dispatch
        # per step (profiled at ~1ms on CPU — comparable to the whole
        # compiled step for small models); jitted it is one cached-
        # executable dispatch.  The step rides in as a fixed-dtype array
        # so every step hits the same executable.
        fold = getattr(self, "_fold_fn", None)
        if fold is None:
            fold = self._fold_fn = jax.jit(
                lambda k, s: jax.random.fold_in(k, s))
        key = fold(self._rng_base(program), np.uint32(self._step))
        self._step += 1
        return key

    def _prepare_feed(self, program, feed, device):
        """device_put feeds with the LoDTensor padded+lengths expansion
        and the kind-level dtype guard (DataFeeder enforce analog) —
        shared by run() and run_loop()."""
        from .lod import LoDTensor

        feed_arrays = {}
        for name, value in feed.items():
            if isinstance(value, LoDTensor):
                # ragged feed: pass the padded data; expose lengths as
                # `<name>@SEQ_LEN` if the program wants them
                feed_arrays[name] = jax.device_put(
                    jnp.asarray(value.data), device)
                feed_arrays[name + "@SEQ_LEN"] = jax.device_put(
                    jnp.asarray(value.seq_lens()), device
                )
                continue
            arr = jnp.asarray(value)
            var = program.global_block()._find_var_recursive(name)
            if var is not None and var.dtype:
                # kind-level check (int vs float vs bool): silently
                # flooring float ids into an embedding lookup is the
                # classic garbage-in bug the reference's DataFeeder
                # enforce guards against; width-only differences
                # (int32/int64, f32/f64) stay allowed
                want = _dtype_kind(var.dtype)
                got = _dtype_kind(arr.dtype)
                if want != got and {want, got} != {"i", "u"}:
                    raise TypeError(
                        "feed '%s' has dtype %s but the program declares "
                        "%s — cast the feed (DataFeeder does this) or fix "
                        "the data layer dtype" % (name, arr.dtype, var.dtype)
                    )
            feed_arrays[name] = jax.device_put(arr, device)
        return feed_arrays

    def run(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        feed_var_name="feed",
        fetch_var_name="fetch",
        scope=None,
        return_numpy=True,
        use_program_cache=True,
    ):
        if self._closed:
            raise RuntimeError("Executor is closed")
        if program is None:
            program = framework.default_main_program()
        if scope is None:
            scope = global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_names = [
            v.name if isinstance(v, framework.Variable) else str(v) for v in fetch_list
        ]
        # collective-mode program (DistributeTranspiler mode="collective"
        # stamps program._collective): the step runs under shard_map over
        # a dp mesh so its c_allreduce_* ops lower to real collectives
        coll = getattr(program, "_collective", None)
        if coll is not None:
            return self._run_collective(program, feed, fetch_names, scope,
                                        return_numpy, coll)
        # pipeline-stamped program (transpiler.pipeline.pipeline_program):
        # the stage-sliced schedule runs as one jitted shard_map step over
        # the dp×mp×pp mesh, params/optimizer state packed per-stage
        pp = getattr(program, "_pipeline", None)
        if pp is not None:
            return self._run_pipeline(program, feed, fetch_names, scope,
                                      return_numpy, pp)
        # GSPMD-stamped program (parallel.partition_rules.annotate_spmd):
        # persistables place per the partition-rule table and the traced
        # step jits with those shardings — the tensor-parallel serving
        # pool's execution path
        spmd = getattr(program, "_spmd", None)
        if spmd is not None:
            return self._run_spmd(program, feed, fetch_names, scope,
                                  return_numpy, spmd)
        # steady-state fast path: everything the slow path re-derives per
        # step — the listen_and_serv/reader op scans, per-feed var lookup
        # + dtype-kind guard, the sorted feed-signature tuple, and the
        # compile-cache hash — is memoized per (program version,
        # feed-keys, fetches, scope).  The memo only validates that each
        # feed still matches the recorded (shape, dtype); any surprise
        # falls back to the full path, which refreshes the memo.
        fast_key = (id(program), program._version, id(scope),
                    tuple(fetch_names), tuple(sorted(feed)))
        entry = self._run_cache.get(fast_key)
        if entry is not None:
            out = self._run_fast(entry, program, feed, fetch_names, scope,
                                 return_numpy)
            if out is not _FAST_MISS:
                return out
        return self._run_slow(program, feed, fetch_names, scope,
                              return_numpy, fast_key)

    def _run_fast(self, entry, program, feed, fetch_names, scope,
                  return_numpy):
        from .flags import get_flag

        if (bool(get_flag("use_pallas")),
                get_flag("prng_impl")) != entry["flags"]:
            return _FAST_MISS  # lowering flags flipped: recompile path
        device = entry["device"]
        spec = entry["feed_spec"]
        import time as _time

        t0 = _time.perf_counter()
        feed_arrays = {}
        with RecordEvent("feed_upload", cat="feed"):
            for name, value in feed.items():
                want = spec.get(name)
                shape = getattr(value, "shape", None)
                dtype = getattr(value, "dtype", None)
                if (want is None or shape is None or dtype is None
                        or (tuple(shape), str(dtype)) != want):
                    return _FAST_MISS
                if isinstance(value, jax.Array):
                    if (getattr(value, "committed", True)
                            and device in value.devices()):
                        feed_arrays[name] = value  # pre-staged (prefetch)
                    else:
                        feed_arrays[name] = jax.device_put(value, device)
                elif isinstance(value, np.ndarray):
                    feed_arrays[name] = jax.device_put(value, device)
                else:
                    return _FAST_MISS  # LoDTensor / list feeds: slow path
        self._host_feed_ms += (_time.perf_counter() - t0) * 1e3
        compiled = entry["compiled"]
        traced = compiled.traced
        ro_state = {}
        for n in traced.ro_names:
            ro_state[n] = self._commit_state(n, scope.find_var(n), device,
                                             scope)
        rw_state = {}
        for n in traced.rw_names:
            rw_state[n] = self._commit_state(n, scope.find_var(n), device,
                                             scope)
        return self._finish_run(compiled, feed_arrays, ro_state, rw_state,
                                program, fetch_names, scope, return_numpy)

    def _maybe_verify_program(self, program, feed, fetch_names, scope):
        """Verify-before-first-run (FLAGS_check_program): the program
        verifies statically before its first compile — a malformed
        program fails with an attributable diagnostic instead of a
        trace-time error (or a silent miscompile).  The verdict depends
        on the run's feeds, fetches (the DCE mask scopes checks to the
        ops that will trace) AND scope (scope-resident names count as
        defined), so the memo keys on all four; flag off is one flag
        read."""
        from .flags import get_flag

        if not get_flag("check_program"):
            return
        vkey = (program._version, tuple(sorted(feed)),
                tuple(fetch_names), id(scope))
        seen = getattr(program, "_verified_keys", None)
        if seen is not None and vkey in seen:
            return
        from .analysis import check_program as _check_program

        _check_program(
            program, scope=scope, feeds=list(feed),
            fetches=fetch_names, dce_fetches=fetch_names)
        if seen is None or len(seen) > 64:
            seen = set()
        seen.add(vkey)
        program._verified_keys = seen

    def _run_slow(self, program, feed, fetch_names, scope, return_numpy,
                  fast_key):
        # pserver program: block on the listen_and_serv service loop
        # (ListenAndServOp::RunImpl analog) instead of compiling
        if any(
            op.type == "listen_and_serv" for op in program.global_block().ops
        ):
            from .distributed.ps_server import run_pserver

            run_pserver(program, scope, self)
            return []

        self._maybe_verify_program(program, feed, fetch_names, scope)

        device = self.place.jax_device()
        import time as _time

        t0 = _time.perf_counter()
        with RecordEvent("feed_upload", cat="feed"):
            feed_arrays = self._prepare_feed(program, feed, device)
        self._host_feed_ms += (_time.perf_counter() - t0) * 1e3

        # in-program readers: satisfy `read` op outputs from the staged
        # device queue (create_py_reader/double_buffer analog — host IO
        # happens here at the executor boundary, not inside the XLA step)
        readers = getattr(program, "_py_readers", None)
        if readers:
            for op in program.global_block().ops:
                if op.type != "read":
                    continue
                state = readers[op.attrs["reader_name"]]
                batch = state.next_feed()  # raises EOFException at end
                for n in op.outputs["Out"]:
                    key_name = n if n in batch else None
                    if key_name is None:
                        # dict batches may use positional order
                        key_name = state.out_names[op.outputs["Out"].index(n)]
                    val = batch[key_name]
                    feed_arrays[n] = (
                        val
                        if hasattr(val, "devices") or hasattr(val, "device")
                        else jax.device_put(jnp.asarray(val), device)
                    )

        feed_sig = tuple(
            sorted((n, tuple(a.shape), str(a.dtype)) for n, a in feed_arrays.items())
        )
        compiled = self._cache.get(program, 0, feed_sig, fetch_names, scope)
        traced = compiled.traced

        ro_state = {}
        for n in traced.ro_names:
            ro_state[n] = self._commit_state(n, scope.find_var(n), device,
                                             scope)
        rw_state = {}
        for n in traced.rw_names:
            rw_state[n] = self._commit_state(n, scope.find_var(n), device,
                                             scope)

        # memoize for the steady-state fast path — only shapes the fast
        # path can fully re-validate (plain array feeds, no reader ops)
        if not readers and all(
            isinstance(v, (np.ndarray, jax.Array)) for v in feed.values()
        ):
            from .flags import get_flag

            # spec records the RAW feed's (shape, dtype) — a float64
            # numpy feed canonicalizes to f32 on staging, and matching
            # against the staged dtype would miss the fast path on every
            # step (device_put canonicalizes identically on both paths)
            self._run_cache[fast_key] = {
                "compiled": compiled,
                "device": device,
                "feed_spec": {
                    n: (tuple(v.shape), str(v.dtype))
                    for n, v in feed.items()
                },
                "flags": (bool(get_flag("use_pallas")),
                          get_flag("prng_impl")),
            }

        return self._finish_run(compiled, feed_arrays, ro_state, rw_state,
                                program, fetch_names, scope, return_numpy)

    def _finish_run(self, compiled, feed_arrays, ro_state, rw_state,
                    program, fetch_names, scope, return_numpy):
        from .flags import get_flag

        _ensure_token_regime(("flat", self.place.jax_device().id))
        key = self._rng_key(program)
        import time as _time

        t0 = _time.time()
        with RecordEvent("executor_run"):
            fetches, new_state = compiled(feed_arrays, ro_state, rw_state, key)
        if get_flag("benchmark"):
            # FLAGS_benchmark contract: per-run timing log with a device
            # barrier so the number is real
            jax.block_until_ready(fetches if fetches else list(new_state.values()))
            print("[benchmark] run %.3f ms" % ((_time.time() - t0) * 1e3))

        for n, v in new_state.items():
            scope.set(n, v)

        if get_flag("check_nan_inf"):
            # FLAGS_check_nan_inf contract (operator.cc:688): raise on any
            # non-finite fetched value, naming the variable.  Materialize
            # once and reuse for the return (no double device_get).
            np_fetches = [np.asarray(jax.device_get(f)) for f in fetches]
            for name, arr in zip(fetch_names, np_fetches):
                if arr.dtype.kind == "i" or arr.dtype.kind == "b":
                    continue
                try:
                    finite = np.isfinite(arr)  # works for f16/f32 AND
                    # ml_dtypes bfloat16 (whose dtype.kind is 'V')
                except TypeError:
                    continue
                if not finite.all():
                    raise RuntimeError(
                        "NaN/Inf detected in fetched var '%s'" % name
                    )
            if return_numpy:
                return np_fetches

        if return_numpy:
            return [as_numpy(f) for f in fetches]
        return list(fetches)

    # ---- GSPMD (tensor-parallel mesh) run path --------------------------
    def _spmd_state_sharding(self, program, mesh, rules, name, scope):
        """Placement for one state var: rule-table spec with the scalar/
        rank/divisibility guards, shape taken from the scope value when
        present, else the program's var declaration (fresh persistables
        a startup program is about to create)."""
        val = scope.find_var(name)
        shape = getattr(val, "shape", None)
        if shape is None:
            var = program.global_block()._find_var_recursive(name)
            shape = tuple(var.shape) if var is not None else None
        return rules.sharding_for(mesh, name, shape)

    def _run_spmd(self, program, feed, fetch_names, scope, return_numpy,
                  spmd):
        """Run a GSPMD-stamped program: ONE traced step jitted with the
        partition-rule table's in/out shardings — XLA's SPMD partitioner
        emits the collectives (qkv/ffn all-reduces, vocab-sharded logits
        merge) while the KV slot-pool persistables live SHARDED in HBM
        (heads axis: pool bytes/device drop ~1/N).  Mesh-aware lowerings
        (fused_attention's vector-QStart pallas kernel under shard_map,
        slot_cache_write's sharding constraints) bind through the
        spmd_lowering context during the trace.

        The serving engine's two PR 9 contracts survive unchanged:
        occupancy churn changes feed VALUES only (one compile per feed
        signature, counted in compile_count like every other path), and
        row math stays row-independent under sharding (heads-axis splits
        never mix slots), so pooled == solo bit-for-bit."""
        import time as _time

        from jax.sharding import NamedSharding, PartitionSpec

        from .flags import get_flag

        mesh, rules = spmd["mesh"], spmd["rules"]
        self._maybe_verify_program(program, feed, fetch_names, scope)
        repl = NamedSharding(mesh, PartitionSpec())

        # training rule tables name a dp axis: batch feeds shard their
        # leading dim over it (the GSPMD global-view batch — the traced
        # per-batch loss mean IS the PR 6 allreduce-mean, emitted by the
        # partitioner instead of an explicit c_allreduce).  Serving
        # tables carry no dp_axis, so the ragged step's per-slot vectors
        # keep replicating as before.
        dp_axis = getattr(rules, "dp_axis", None)
        from .parallel.mesh import mesh_axis_sizes

        dp = mesh_axis_sizes(mesh).get(dp_axis, 1) if dp_axis else 1

        def feed_sharding(a):
            if dp > 1 and a.ndim >= 1 and a.shape[0] % dp == 0 \
                    and a.shape[0] > 0:
                return NamedSharding(
                    mesh, PartitionSpec(*((dp_axis,)
                                          + (None,) * (a.ndim - 1))))
            return repl

        t0 = _time.perf_counter()
        feed_np = {n: np.asarray(v) for n, v in feed.items()}
        with RecordEvent("feed_upload", cat="feed"):
            feed_arrays = {n: jax.device_put(a, feed_sharding(a))
                           for n, a in feed_np.items()}
        self._host_feed_ms += (_time.perf_counter() - t0) * 1e3

        feed_sig = tuple(sorted(
            (n, tuple(a.shape), str(a.dtype))
            for n, a in feed_arrays.items()))
        cache = getattr(self, "_spmd_cache", None)
        if cache is None:
            cache = self._spmd_cache = {}
        key_id = (id(program), program._version, feed_sig,
                  tuple(fetch_names), id(scope),
                  bool(get_flag("use_pallas")), get_flag("prng_impl"))
        entry = cache.get(key_id)
        if entry is None:
            from .core.trace import build_traced_function

            # a fresh trace+compile: count it where the engine's
            # no-retrace contract looks (Executor.compile_count)
            self._cache.compile_count += 1
            traced = build_traced_function(
                program, 0, tuple(n for n, _, _ in feed_sig), fetch_names,
                scope, spmd=(mesh, rules))
            sh = {n: self._spmd_state_sharding(program, mesh, rules, n,
                                              scope)
                  for n in set(traced.ro_names) | set(traced.rw_names)
                  | set(traced.updated)}
            jitted = jax.jit(
                traced.fn,
                in_shardings=(
                    {n: feed_arrays[n].sharding for n in feed_arrays},
                    {n: sh[n] for n in traced.ro_names},
                    {n: sh[n] for n in traced.rw_names},
                    repl,
                ),
                out_shardings=(None, {n: sh[n] for n in traced.updated}),
                donate_argnums=(2,),
            )
            # avals[0] records the first call's abstract args so
            # spmd_comm_stats can AOT-lower the same signature later
            entry = cache[key_id] = (traced, jitted, sh, [None])
        traced, jitted, sh, avals = entry

        def commit(n):
            v = scope.find_var(n)
            if isinstance(v, jax.Array) and getattr(v, "committed", True) \
                    and v.sharding == sh[n]:
                return v
            arr = jax.device_put(np.asarray(v), sh[n])
            scope.set(n, arr)
            return arr

        ro_state = {n: commit(n) for n in traced.ro_names}
        rw_state = {n: commit(n) for n in traced.rw_names}
        key = jax.device_put(self._rng_key(program), repl)
        if avals[0] is None:
            avals[0] = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=x.sharding),
                (feed_arrays, ro_state, rw_state, key))
        _ensure_token_regime(
            ("mesh", tuple(d.id for d in mesh.devices.flat)))
        with RecordEvent("executor_run"):
            fetches, new_state = jitted(feed_arrays, ro_state, rw_state,
                                        key)
        for n, v in new_state.items():
            scope.set(n, v)
        if return_numpy:
            return [as_numpy(f) for f in fetches]
        return list(fetches)

    def _run_pipeline(self, program, feed, fetch_names, scope, return_numpy,
                      pp):
        """Run a pipeline-stamped program: the stage-sliced GPipe/1F1B
        schedule compiled as ONE jitted shard_map step over the dp×mp×pp
        mesh.  Stage params + Adam state live packed in [S, L] buffers
        sharded P(pp) (per-device bytes = max stage, not the sum); the
        buffers are donated every step and owned by the cache entry —
        ``transpiler.pipeline.flush_pipeline_state`` writes them back to
        the scope for checkpointing.  Shared state (learning rate,
        schedule counters) stays replicated and mirrors to the scope each
        step like every other path.  One compile per feed signature
        (compile_count accounts it); steady-state steps never retrace."""
        import time as _time

        from jax.sharding import NamedSharding, PartitionSpec

        from .flags import get_flag
        from .parallel.mesh import mesh_axis_sizes

        mesh, plan = pp["mesh"], pp["plan"]
        self._maybe_verify_program(program, feed, fetch_names, scope)
        repl = NamedSharding(mesh, PartitionSpec())
        dp_axis = plan.dp_axis
        dp = mesh_axis_sizes(mesh).get(dp_axis, 1) if dp_axis else 1

        def feed_sharding(a):
            if dp > 1 and a.ndim >= 1 and a.shape[0] % dp == 0 \
                    and a.shape[0] > 0:
                return NamedSharding(
                    mesh, PartitionSpec(*((dp_axis,)
                                          + (None,) * (a.ndim - 1))))
            return repl

        t0 = _time.perf_counter()
        feed_np = {n: np.asarray(v) for n, v in feed.items()}
        with RecordEvent("feed_upload", cat="feed"):
            feed_arrays = {n: jax.device_put(a, feed_sharding(a))
                           for n, a in feed_np.items()}
        self._host_feed_ms += (_time.perf_counter() - t0) * 1e3

        feed_sig = tuple(sorted(
            (n, tuple(a.shape), str(a.dtype))
            for n, a in feed_arrays.items()))
        cache = getattr(self, "_pipeline_cache", None)
        if cache is None:
            cache = self._pipeline_cache = {}
        key_id = (id(program), program._version, feed_sig,
                  tuple(fetch_names), id(scope),
                  bool(get_flag("use_pallas")), get_flag("prng_impl"))
        entry = cache.get(key_id)
        if entry is None:
            from .transpiler.pipeline import (build_pipeline_runtime,
                                              flush_pipeline_state)

            # a previous entry's packed buffers are authoritative for
            # stage-owned state — flush them to the scope before the new
            # signature re-packs, or it would train from stale weights
            flush_pipeline_state(program, scope)
            self._cache.compile_count += 1
            runtime = build_pipeline_runtime(
                program, plan, mesh, scope, feed_arrays, fetch_names)
            entry = cache[key_id] = {
                "runtime": runtime,
                "state": runtime.pack_state(scope),
            }
            for n in runtime.shared_rw:
                entry["state"][n] = jax.device_put(
                    np.asarray(scope.find_var(n)), repl)
        runtime = entry["runtime"]

        def commit(n):
            v = scope.find_var(n)
            if isinstance(v, jax.Array) and getattr(v, "committed", True) \
                    and v.sharding == repl:
                return v
            arr = jax.device_put(np.asarray(v), repl)
            scope.set(n, arr)
            return arr

        feeds = {n: feed_arrays[n] for n in runtime.feed_shardings}
        ro_state = {n: commit(n) for n in runtime.shared_ro}
        rw_state = entry["state"]
        key = jax.device_put(self._rng_key(program), repl)
        _ensure_token_regime(
            ("mesh", tuple(d.id for d in mesh.devices.flat)))
        with RecordEvent("executor_run"):
            fetches, new_state = runtime.jitted(feeds, ro_state, rw_state,
                                                key)
        entry["state"] = new_state
        program._pipeline_runtime = entry
        for n in runtime.shared_rw:
            scope.set(n, new_state[n])
        if return_numpy:
            return [as_numpy(f) for f in fetches]
        return list(fetches)

    def spmd_comm_stats(self, program):
        """Comm-bytes attribution for a GSPMD-stamped program's compiled
        step(s): AOT-lower each cached executable at its recorded call
        signature and sum the output bytes of collective ops in the
        optimized HLO — what the SPMD partitioner actually moves per
        dispatch (qkv/ffn partial-sum all-reduces, vocab-logits merges).
        Returns {"per_op": {kind: {"count", "bytes"}}, "total_bytes"};
        best-effort (an HLO surface change degrades to {} rather than
        failing a bench run)."""
        import re as _re

        _ELEM = {"f64": 8, "s64": 8, "u64": 8, "f32": 4, "s32": 4,
                 "u32": 4, "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
                 "s8": 1, "u8": 1, "pred": 1}
        # matches both the synchronous form (`all-reduce(`) and the
        # async form TPU-optimized HLO emits (`all-reduce-start(` — the
        # paired `-done` re-states the same bytes, so only the start is
        # counted)
        pat = _re.compile(
            r"=\s+(?:\(?)([a-z0-9]+)\[([0-9,]*)\][^=]*?"
            r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|"
            r"collective-permute)(?:-start)?\(")
        out = {}
        total = 0
        cache = getattr(self, "_spmd_cache", None) or {}
        for key, (traced, jitted, sh, avals) in cache.items():
            if key[0] != id(program) or avals[0] is None:
                continue
            try:
                txt = jitted.lower(*avals[0]).compile().as_text()
            except Exception:
                continue
            for m in pat.finditer(txt):
                dt, dims, kind = m.group(1), m.group(2), m.group(3)
                n = 1
                for d in dims.split(","):
                    if d:
                        n *= int(d)
                b = n * _ELEM.get(dt, 4)
                ent = out.setdefault(kind, {"count": 0, "bytes": 0})
                ent["count"] += 1
                ent["bytes"] += b
                total += b
        return {"per_op": out, "total_bytes": total}

    # ---- collective (mesh data-parallel) run path -----------------------
    def _run_collective(self, program, feed, fetch_names, scope,
                        return_numpy, coll):
        """Run a collective-mode trainer program: the traced step is
        wrapped in ``shard_map`` over a ``parallel/mesh.dp_mesh`` so the
        transpiler's ``c_allreduce_*`` ops lower to ``jax.lax``
        collectives — XLA overlaps the gradient all-reduce with backward
        compute, and no Python runs in the dense-grad path.

        Replica semantics: each mesh shard is one logical trainer.
        Array feeds with a leading batch dim are this PROCESS's shard of
        the global batch and split over the axis (multi-process via
        jax.distributed: one feed shard per process — every process MUST
        feed equal-size shards, since the global shape is derived as
        local_rows * process_count; single-process CPU CI: the full
        batch splits over the virtual devices); everything else —
        params, optimizer state, the step RNG key — is replicated.
        Float fetches return the cross-replica mean (the global-batch
        loss), so every process reports the same trajectory.  State
        updates must be replica-invariant (they are, whenever they flow
        from all-reduced grads; batch-stat ops like BN belong on the
        DistributedExecutor path instead)."""
        import time as _time

        from jax.sharding import NamedSharding, PartitionSpec

        from .flags import get_flag
        from .parallel.mesh import shard_map

        # collective programs get the same verify-before-first-run as
        # the single-device path (they bypass _run_slow)
        self._maybe_verify_program(program, feed, fetch_names, scope)

        axis, nranks = str(coll["axis"]), int(coll["nranks"])
        if get_flag("prng_impl") != "threefry":
            raise NotImplementedError(
                "collective mode replicates the raw threefry step key "
                "across the mesh; FLAGS_prng_impl=%s is not supported "
                "here" % get_flag("prng_impl"))
        if any(op.type == "read" for op in program.global_block().ops):
            raise ValueError(
                "collective mode feeds arrays directly; in-program "
                "py_reader ops are not supported on this path")
        cache = getattr(self, "_coll_cache", None)
        if cache is None:
            cache = self._coll_cache = {}
        meshes = getattr(self, "_coll_meshes", None)
        if meshes is None:
            meshes = self._coll_meshes = {}
        mesh = meshes.get((axis, nranks))
        if mesh is None:
            from .parallel.mesh import dp_mesh

            mesh = meshes[(axis, nranks)] = dp_mesh(nranks, axis)
        repl = NamedSharding(mesh, PartitionSpec())
        nproc = jax.process_count()
        local_per_proc = nranks // max(1, nproc)

        def to_mesh(value, spec):
            arr = np.asarray(value)
            sharding = NamedSharding(mesh, spec)
            gshape = tuple(arr.shape)
            if spec != PartitionSpec():
                gshape = (arr.shape[0] * nproc,) + tuple(arr.shape[1:])
            return jax.make_array_from_process_local_data(
                sharding, arr, gshape)

        def feed_spec(arr):
            # a process-local batch shard splits over the axis when every
            # local device can take an equal slice; anything else (odd
            # leading dims, scalars) replicates
            if (arr.ndim and arr.shape[0]
                    and arr.shape[0] % max(1, local_per_proc) == 0):
                return PartitionSpec(axis)
            return PartitionSpec()

        t0 = _time.perf_counter()
        feed_np = {n: np.asarray(v) for n, v in feed.items()}
        specs = {n: feed_spec(a) for n, a in feed_np.items()}
        with RecordEvent("feed_upload", cat="feed"):
            feed_arrays = {n: to_mesh(a, specs[n])
                           for n, a in feed_np.items()}
        self._host_feed_ms += (_time.perf_counter() - t0) * 1e3

        feed_sig = tuple(sorted(
            (n, tuple(a.shape), str(a.dtype)) for n, a in feed_np.items()))
        # (axis, nranks) keys the entry: an ELASTIC collective resize
        # (program._collective["nranks"] rewritten mid-job) must re-trace
        # over the new mesh, not reuse an executable jitted for the old
        # one — _ensure_token_regime below drains the ordered-io tokens
        # across the topology switch, so the resize cannot trip the PjRt
        # layout abort (docs/FAULT_TOLERANCE.md "Elastic autoscaling")
        key_id = (id(program), program._version, feed_sig,
                  tuple(fetch_names), id(scope), axis, nranks)
        entry = cache.get(key_id)
        if entry is None:
            from .core.trace import build_traced_function

            traced = build_traced_function(
                program, 0, tuple(n for n, _, _ in feed_sig), fetch_names,
                scope, collective_axis=(axis, nranks))

            def stepfn(feeds, ro_state, rw_state, rng_key):
                fetches, new_state = traced.fn(
                    feeds, ro_state, rw_state, rng_key)
                # float fetches -> cross-replica mean: shard-mean losses
                # average to the global-batch loss, and the P() out_spec
                # is then genuinely replicated.  Non-float fetches have
                # no sound merge rule (an int count over the sharded
                # batch is per-replica, and check_rep=False would hand
                # back ONE replica's shard as if it were global) — refuse
                # rather than silently return 1/nranks of the truth.
                merged = []
                for name, f in zip(fetch_names, fetches):
                    if jnp.issubdtype(jnp.result_type(f), jnp.inexact):
                        merged.append(jax.lax.pmean(f, axis))
                    else:
                        raise NotImplementedError(
                            "collective mode cannot merge non-float "
                            "fetch %r (dtype %s) across mesh replicas — "
                            "fetch a float metric (cast counts to f32 "
                            "in-program) or use the DistributedExecutor "
                            "path" % (name, jnp.result_type(f)))
                return merged, new_state

            in_specs = ({n: specs[n] for n in feed_np},
                        PartitionSpec(), PartitionSpec(), PartitionSpec())
            wrapped = shard_map(
                stepfn, mesh=mesh, in_specs=in_specs,
                out_specs=(PartitionSpec(), PartitionSpec()),
                check_rep=False)
            jitted = jax.jit(wrapped, donate_argnums=(2,))
            entry = cache[key_id] = (traced, jitted, specs)
        traced, jitted, cached_specs = entry
        if cached_specs != specs:  # same sig must imply same placement
            raise RuntimeError(
                "collective feed sharding changed for a cached signature")

        def commit(n):
            v = scope.find_var(n)
            if (isinstance(v, jax.Array)
                    and getattr(v, "committed", True)
                    and v.sharding == repl):
                return v
            arr = to_mesh(v, PartitionSpec())
            scope.set(n, arr)
            return arr

        ro_state = {n: commit(n) for n in traced.ro_names}
        rw_state = {n: commit(n) for n in traced.rw_names}
        key = to_mesh(self._rng_key(program), PartitionSpec())
        _ensure_token_regime(
            ("mesh", tuple(d.id for d in mesh.devices.flat)))
        with RecordEvent("executor_run"):
            fetches, new_state = jitted(feed_arrays, ro_state, rw_state, key)
        for n, v in new_state.items():
            scope.set(n, v)
        if return_numpy:
            # P() out_specs are fully replicated: np.asarray reads the
            # local shard even in multi-process runs
            return [np.asarray(f) for f in fetches]
        return list(fetches)

    def run_loop(
        self,
        iters,
        program=None,
        feed=None,
        fetch_list=None,
        scope=None,
        return_numpy=True,
    ):
        """Run `iters` steps of `program` as ONE compiled device call —
        a lax.scan over the traced step with all read-write state (params,
        optimizer moments, BN stats) threaded through the carry.

        The per-step host dispatch of run() disappears entirely: one
        launch executes the whole window on-device (the TPU-first form of
        the reference benchmark's iters-per-Run loop, and the tool that
        separates device throughput from host/tunnel dispatch overhead).
        Feeds stay CONSTANT across iterations — this is the steady-state
        benchmark/fixed-batch shape; for data iteration use run() or the
        in-program py_reader path.  RNG advances per iteration (each step
        folds its loop index), matching run()'s stream contract.

        Returns the LAST iteration's fetches; scope state afterwards is
        exactly as after `iters` sequential run() calls."""
        iters = int(iters)
        if iters <= 0:
            raise ValueError("run_loop: iters must be positive")
        if self._closed:
            raise RuntimeError("Executor is closed")
        if program is None:
            program = framework.default_main_program()
        if scope is None:
            scope = global_scope()
        ops = program.global_block().ops
        if any(op.type in ("listen_and_serv", "read") for op in ops):
            raise ValueError(
                "run_loop cannot iterate programs with host-boundary ops "
                "(py_reader 'read' / listen_and_serv) — their IO happens "
                "at the executor boundary, outside the compiled loop"
            )
        if getattr(program, "_collective", None) is not None:
            raise ValueError(
                "run_loop does not drive collective-mode programs (their "
                "allreduces need the mesh-bound run() path); call run() "
                "per step"
            )
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_names = [
            v.name if isinstance(v, framework.Variable) else str(v)
            for v in fetch_list
        ]
        device = self.place.jax_device()
        feed_arrays = self._prepare_feed(program, feed, device)
        feed_sig = tuple(
            sorted((n, tuple(a.shape), str(a.dtype))
                   for n, a in feed_arrays.items())
        )
        from .flags import get_flag

        cache_key = (
            id(program), program._version, feed_sig, tuple(fetch_names),
            iters, id(scope), bool(get_flag("use_pallas")),
            get_flag("prng_impl"),
        )
        hit = getattr(self, "_loop_cache", None)
        if hit is None:
            hit = self._loop_cache = {}
        entry = hit.get(cache_key)
        if entry is None:
            from .core.trace import build_traced_function

            traced = build_traced_function(
                program, 0, tuple(n for n, _, _ in feed_sig), fetch_names,
                scope
            )
            rw_set = set(traced.rw_names)
            fresh = [n for n in traced.updated if n not in rw_set]

            def loop_fn(feeds, ro_state, rw_state, keys):
                # first iteration outside the scan establishes the carry
                # shapes for fetches/fresh state; the rest thread through
                # the carry (O(1) HBM — nothing is stacked over iters)
                f0, n0 = traced.fn(feeds, ro_state, rw_state, keys[0])
                carry0 = (
                    {n: n0[n] for n in traced.rw_names},
                    tuple(f0),
                    {n: n0[n] for n in fresh},
                )

                def body(carry, key):
                    rw, _, _ = carry
                    f, ns = traced.fn(feeds, ro_state, rw, key)
                    return (
                        {n: ns[n] for n in traced.rw_names},
                        tuple(f),
                        {n: ns[n] for n in fresh},
                    ), None

                (rw, fetches, extra), _ = jax.lax.scan(
                    body, carry0, keys[1:]
                )
                final_state = dict(rw)
                final_state.update(extra)
                return list(fetches), final_state

            jitted = jax.jit(loop_fn, donate_argnums=(2,))
            entry = hit[cache_key] = (traced, jitted)
        traced, jitted = entry

        ro_state = {
            n: self._commit_state(n, scope.find_var(n), device, scope)
            for n in traced.ro_names
        }
        rw_state = {
            n: self._commit_state(n, scope.find_var(n), device, scope)
            for n in traced.rw_names
        }
        _ensure_token_regime(("flat", self.place.jax_device().id))
        # EXACT run() stream parity: iteration i uses fold_in(base,
        # step0 + i) — the same key i sequential run() calls would draw
        base = self._rng_base(program)
        step0 = self._step
        self._step += iters
        keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(step0, step0 + iters)
        )
        try:
            fetches, new_state = jitted(feed_arrays, ro_state, rw_state, keys)
        except Exception as e:
            self._step = step0
            # Don't classify by exception TYPE (a TypeError can also come
            # from a host callback AFTER dispatch) — check what actually
            # matters: were the rw_state buffers donated?  jit argument
            # validation fails BEFORE dispatch, leaving every donated-arg
            # buffer alive; any failure after dispatch leaves them
            # deleted (donate_argnums=(2,)).
            donated = any(
                getattr(v, "is_deleted", lambda: False)()
                for v in rw_state.values()
            )
            if not donated:
                # nothing was donated, the scope is intact — surface the
                # plain error
                raise
            # a failure mid-call (device OOM, callback error, ...) leaves
            # the scope holding deleted buffers and every later run()
            # would die with an opaque deleted-buffer error — fail loudly
            # instead.
            raise RuntimeError(
                "Executor.run_loop: the compiled loop failed after its "
                "read-write state was donated to the device; the scope "
                "state for %s is invalidated. Re-run the startup "
                "program or reload a checkpoint before calling run()/"
                "run_loop() on this scope again. Original error: %r"
                % (sorted(traced.rw_names)[:8], e)
            ) from e
        for n, v in new_state.items():
            scope.set(n, v)
        if return_numpy:
            return [as_numpy(f) for f in fetches]
        return list(fetches)

    def close(self):
        """Release cached executables and notify pservers this trainer is
        done (Executor::Close -> SendComplete analog, executor.h:91)."""
        from . import distributed

        distributed.send_complete_all()
        self._cache.clear()
        self._run_cache.clear()
        if getattr(self, "_loop_cache", None):
            self._loop_cache.clear()
        if getattr(self, "_spmd_cache", None):
            self._spmd_cache.clear()
        self._closed = True

    # infer_* helpers used by contrib Trainer/Inferencer
    def _run_startup(self, startup_program=None, scope=None):
        self.run(
            startup_program or framework.default_startup_program(),
            feed={},
            fetch_list=[],
            scope=scope,
        )
