"""RecordIO: chunked record files for the fast input path.

Python surface over the native C++ implementation (paddle_tpu/native/
recordio.cc — the re-design of paddle/fluid/recordio/ writer.h:22,
scanner.h:26 and python/paddle/fluid/recordio_writer.py), with a
pure-Python fallback writing the IDENTICAL on-disk format (struct+zlib),
so files interoperate regardless of which side wrote them.

High-level helpers serialize feed samples (tuples of ndarrays) with
np.savez, mirroring convert_reader_to_recordio_file.
"""

import io as _io
import struct
import zlib

import numpy as np

from . import native

__all__ = [
    "Writer",
    "Scanner",
    "convert_reader_to_recordio_file",
    "recordio_reader",
]

_MAGIC = 0x0A0B0C0D
_HDR = struct.Struct("<5I")
_LEN = struct.Struct("<I")

COMPRESSOR_NONE = 0
COMPRESSOR_ZLIB = 1


class _PyWriter:
    _MAX_BYTES = 4 << 20  # mirror the C++ writer's chunk byte cap

    def __init__(self, path, compressor=COMPRESSOR_ZLIB, max_records=1000):
        self._f = open(path, "wb")
        self._compressor = compressor
        self._max = max_records
        self._buf = []
        self._n = 0
        self._nbytes = 0

    def write(self, data):
        item = _LEN.pack(len(data)) + bytes(data)
        self._buf.append(item)
        self._n += 1
        self._nbytes += len(item)
        if self._n >= self._max or self._nbytes >= self._MAX_BYTES:
            self._flush()

    def _flush(self):
        if not self._n:
            return
        payload = b"".join(self._buf)
        if self._compressor == COMPRESSOR_ZLIB:
            payload = zlib.compress(payload, 1)
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        self._f.write(
            _HDR.pack(_MAGIC, self._compressor, crc, len(payload), self._n)
        )
        self._f.write(payload)
        self._buf = []
        self._n = 0
        self._nbytes = 0

    def close(self):
        self._flush()
        self._f.close()


class _PyScanner:
    def __init__(self, path):
        self._f = open(path, "rb")
        self._records = iter(())
        self._closed = False

    def _next_chunk(self):
        hdr = self._f.read(_HDR.size)
        if len(hdr) == 0:
            return None  # clean EOF
        if len(hdr) < _HDR.size:
            raise IOError("recordio file truncated mid-header")
        magic, comp, crc, plen, n = _HDR.unpack(hdr)
        if magic != _MAGIC:
            raise IOError("bad recordio magic")
        payload = self._f.read(plen)
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            raise IOError("recordio chunk crc mismatch")
        try:
            if comp == COMPRESSOR_ZLIB:
                payload = zlib.decompress(payload)
            out = []
            pos = 0
            for _ in range(n):
                (ln,) = _LEN.unpack_from(payload, pos)
                pos += _LEN.size
                if pos + ln > len(payload):
                    raise IOError("recordio record overruns chunk")
                out.append(payload[pos : pos + ln])
                pos += ln
        except (struct.error, zlib.error) as e:
            raise IOError("recordio chunk corrupted: %s" % e)
        return out

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        while True:
            try:
                return next(self._records)
            except StopIteration:
                chunk = self._next_chunk()
                if chunk is None:
                    self.close()
                    raise
                self._records = iter(chunk)

    def close(self):
        self._closed = True
        self._f.close()


class _NativeWriter:
    def __init__(self, path, compressor=COMPRESSOR_ZLIB, max_records=1000):
        self._lib = native.get_lib()
        self._h = self._lib.rio_writer_open(
            path.encode(), compressor, max_records
        )
        if not self._h:
            raise IOError("cannot open %s" % path)

    def write(self, data):
        if self._lib.rio_writer_write(self._h, bytes(data), len(data)) != 0:
            raise IOError("recordio write failed")

    def close(self):
        if self._h:
            rc = self._lib.rio_writer_close(self._h)
            self._h = None
            if rc != 0:
                raise IOError("recordio writer: final chunk flush failed")


class _NativeScanner:
    def __init__(self, path):
        import ctypes

        self._ctypes = ctypes
        self._lib = native.get_lib()
        self._h = self._lib.rio_scanner_open(path.encode())
        if not self._h:
            raise IOError("cannot open %s" % path)

    def __iter__(self):
        return self

    def __next__(self):
        ct = self._ctypes
        n = ct.c_uint32()
        ptr = self._lib.rio_scanner_next(self._h, ct.byref(n))
        if not ptr:
            corrupted = bool(self._lib.rio_scanner_error(self._h))
            self.close()
            if corrupted:
                raise IOError("recordio chunk corrupted or truncated")
            raise StopIteration
        return ct.string_at(ptr, n.value)

    def close(self):
        if self._h:
            self._lib.rio_scanner_close(self._h)
            self._h = None


def Writer(path, compressor=COMPRESSOR_ZLIB, max_records_per_chunk=1000):
    if native.available():
        return _NativeWriter(path, compressor, max_records_per_chunk)
    return _PyWriter(path, compressor, max_records_per_chunk)


def Scanner(path):
    if native.available():
        return _NativeScanner(path)
    return _PyScanner(path)


# ---- sample (de)serialization -------------------------------------------
def pack_sample(sample):
    """Tuple/list of array-likes -> bytes (np.savez, positional keys)."""
    buf = _io.BytesIO()
    arrays = {
        "f%d" % i: np.asarray(v) for i, v in enumerate(sample)
    }
    np.savez(buf, **arrays)
    return buf.getvalue()


def unpack_sample(data):
    blob = np.load(_io.BytesIO(data))
    return tuple(blob["f%d" % i] for i in range(len(blob.files)))


def convert_reader_to_recordio_file(
    filename, reader_creator, compressor=COMPRESSOR_ZLIB, max_num_records=1000
):
    """Serialize every sample from the reader into a RecordIO file
    (recordio_writer.py analog); returns the record count."""
    w = Writer(filename, compressor, max_num_records)
    count = 0
    try:
        for sample in reader_creator():
            w.write(pack_sample(sample))
            count += 1
    finally:
        w.close()
    return count


def recordio_reader(paths, use_native_loader=True, capacity=256, n_threads=2):
    """Reader creator over RecordIO files; uses the C++ threaded prefetch
    loader when available (the --use_reader_op fast path analog)."""
    if isinstance(paths, str):
        paths = [paths]

    def reader():
        if use_native_loader and native.available():
            loader = native.RecordIOLoader(paths, capacity, n_threads)
            try:
                for rec in loader:
                    yield unpack_sample(rec)
            finally:
                loader.close()
        else:
            for p in paths:
                for rec in Scanner(p):
                    yield unpack_sample(rec)

    return reader
