"""Input pipeline.

Reader combinators (decorator.py) mirror python/paddle/reader; the device
feeding path replaces the reference's reader-op stack (py_reader +
LoDTensorBlockingQueue + double_buffer, operators/reader/) with a host-side
prefetch thread that stages batches ahead with jax.device_put — the
TPU-idiomatic equivalent of double buffering into device memory.
"""

from .decorator import *  # noqa: F401,F403
from .decorator import batch
from .pipeline import PyReader, DeviceFeeder
from .packing import pack_sequences
