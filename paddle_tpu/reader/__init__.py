"""paddle_tpu.reader"""
