"""Sequence packing for fixed-shape TPU training.

The reference's answer to ragged batches is the LoD tensor (no padding,
`lod_tensor.h:58`); the XLA-native answer is rectangular tensors, and
padding waste is the price.  Packing removes most of that price: several
short sequences share one fixed-length row, a per-token segment id keeps
attention (and loss) within each original sequence
(``layers.fused_attention(segment_ids=...)``), and per-segment positions
restart so positional encodings stay correct.  One compiled shape serves
ragged data at high fill rates — no per-length recompiles, no
cross-sequence leakage.
"""

import numpy as np

__all__ = ["pack_sequences"]


def pack_sequences(seqs, seq_len, pad_id=0, dtype="int64"):
    """Pack variable-length token sequences into fixed [N, seq_len] rows
    (first-fit-decreasing bin packing).

    Returns ``(tokens, segment_ids, positions)``:

    - ``tokens`` [N, seq_len] `dtype`: the packed ids, `pad_id` in the
      unused tail.
    - ``segment_ids`` [N, seq_len] int32: 1, 2, ... per original
      sequence within its row, 0 on padding.  Feed to
      ``fused_attention(segment_ids=...)`` (padding shares id 0 with
      other padding only — real tokens never attend it) and use
      ``segment_ids > 0`` as the loss mask.
    - ``positions`` [N, seq_len] int32: 0-based position WITHIN each
      segment (restarts at every boundary), 0 on padding — index your
      positional table with these instead of the row position.

    Sequences longer than `seq_len` raise — truncate or bucket upstream.
    """
    seqs = [np.asarray(s).ravel() for s in seqs]
    for s in seqs:
        if s.size > seq_len:
            raise ValueError(
                "pack_sequences: sequence of length %d exceeds seq_len=%d "
                "— truncate or bucket upstream" % (s.size, seq_len))
        if s.size == 0:
            raise ValueError("pack_sequences: empty sequence")
    # first-fit-decreasing: longest first, into the first row that fits
    order = sorted(range(len(seqs)), key=lambda i: -seqs[i].size)
    rows = []  # list of lists of seq indices
    space = []  # remaining capacity per row
    for i in order:
        n = seqs[i].size
        for r, free in enumerate(space):
            if n <= free:
                rows[r].append(i)
                space[r] -= n
                break
        else:
            rows.append([i])
            space.append(seq_len - n)

    N = len(rows)
    tokens = np.full((N, seq_len), pad_id, dtype=dtype)
    segment_ids = np.zeros((N, seq_len), np.int32)
    positions = np.zeros((N, seq_len), np.int32)
    for r, members in enumerate(rows):
        off = 0
        for sid, i in enumerate(members, start=1):
            s = seqs[i]
            tokens[r, off:off + s.size] = s
            segment_ids[r, off:off + s.size] = sid
            positions[r, off:off + s.size] = np.arange(s.size)
            off += s.size
    return tokens, segment_ids, positions
