"""In-program reader state — the py_reader / double-buffer runtime.

Reference: ``operators/reader/create_py_reader_op.cc`` +
``lod_tensor_blocking_queue.h`` + ``create_double_buffer_reader_op.cc``:
a program owns its input pipeline via reader ops; the executor's `read` op
pops the next batch from a blocking queue that a Python thread fills, with
a double-buffer reader prefetching to device.

TPU re-expression: host IO cannot run inside a compiled XLA program, so
the `read` op's outputs become implicit feeds that ``Executor.run``
satisfies from this state object BEFORE invoking the compiled step.  The
pipeline is two stages:

  feeder thread:  user reader -> serialize -> native BlockingQueue
                  (GIL-free C++ bounded queue, paddle_tpu/native)
  stager thread:  pop -> deserialize -> jax.device_put -> small python
                  queue of ready-on-device batches (the double buffer)

so decode and H2D upload both overlap compute.  Without the native lib the
first stage degrades to a python queue (same semantics).
"""

import pickle
import queue
import threading

import numpy as np


class EOFException(Exception):
    """Raised by Executor.run when an in-program reader is exhausted
    (fluid.core.EOFException parity); catch, then reader.reset() +
    reader.start() for the next epoch."""


class _EOF:
    pass


class ProgramReader:
    """Runtime state behind one `read` op (keyed by reader name)."""

    def __init__(self, name, out_names, shapes, dtypes, capacity=64, place=None):
        self.name = name
        self.out_names = list(out_names)
        self.shapes = [list(s) for s in shapes]
        self.dtypes = list(dtypes)
        self.capacity = int(capacity)
        self._place = place
        self._gen = None
        self._threads = []
        self._out_q = None
        self._nq = None
        self._stop = threading.Event()
        self._started = False
        self._error = None  # pipeline-thread exception, re-raised in next_feed

    # ---- decoration (layers/io.py py_reader contract) -------------------
    def decorate_paddle_reader(self, reader):
        """reader() yields lists of row tuples (paddle.batch style)."""
        self._gen = ("rows", reader)

    decorate_sample_list_generator = decorate_paddle_reader

    def decorate_batch_generator(self, generator):
        """generator() yields feed dicts or tuples of column arrays."""
        self._gen = ("batch", generator)

    decorate_tensor_provider = decorate_batch_generator

    # ---- lifecycle ------------------------------------------------------
    def start(self):
        if self._gen is None:
            raise RuntimeError(
                "py_reader '%s': call decorate_paddle_reader / "
                "decorate_batch_generator before start()" % self.name
            )
        if self._started:
            return
        # ensure any previous epoch's threads have fully exited before the
        # stop flag is cleared (an orphan feeder must not feed this epoch);
        # an un-joinable thread (generator blocked in IO) keeps _stop set
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
            if t.is_alive():
                raise RuntimeError(
                    "py_reader '%s': previous epoch's pipeline thread is "
                    "still running (generator blocked?); cannot restart"
                    % self.name
                )
        self._threads = []
        if self._nq is not None:  # free the previous epoch's native queue
            self._nq.destroy()
            self._nq = None
        self._stop.clear()
        self._error = None
        self._out_q = queue.Queue(maxsize=2)  # the device double buffer
        from ..native import available, BlockingQueue

        self._nq = BlockingQueue(self.capacity) if available() else None
        py_stage = queue.Queue(maxsize=self.capacity) if self._nq is None else None

        kind, gen = self._gen

        def to_columns(batch):
            if isinstance(batch, dict):
                return {k: np.asarray(v) for k, v in batch.items()}
            if kind == "rows":
                cols = list(zip(*batch))
            else:
                cols = list(batch)
            return {
                n: np.asarray(c)
                for n, c in zip(self.out_names, cols)
            }

        def feeder():
            # serialization is only for the native (byte) queue; the
            # python-queue fallback passes column dicts directly
            try:
                for batch in gen():
                    cols = to_columns(batch)
                    item = (
                        pickle.dumps(cols, protocol=pickle.HIGHEST_PROTOCOL)
                        if self._nq is not None
                        else cols
                    )
                    while not self._stop.is_set():
                        if self._nq is not None:
                            if self._nq.push(item, timeout_ms=100):
                                break
                        else:
                            try:
                                py_stage.put(item, timeout=0.1)
                                break
                            except queue.Full:
                                continue
                    if self._stop.is_set():
                        return
            except Exception as e:  # surface to the training loop, not a
                self._error = e  # silent truncated epoch
            finally:
                if self._nq is not None:
                    self._nq.close()
                else:
                    try:
                        py_stage.put(_EOF, timeout=1.0)
                    except queue.Full:
                        pass

        def stager():
            try:
                import jax

                from ..places import default_place

                device = (self._place or default_place()).jax_device()
                while not self._stop.is_set():
                    if self._nq is not None:
                        payload = self._nq.pop(timeout_ms=100)
                        if payload is None:
                            if self._nq.size() == 0 and not feeder_t.is_alive():
                                break
                            continue
                        cols = pickle.loads(payload)
                    else:
                        try:
                            cols = py_stage.get(timeout=0.1)
                        except queue.Empty:
                            if not feeder_t.is_alive():
                                break
                            continue
                        if cols is _EOF:
                            break
                    staged = {
                        k: jax.device_put(v, device) for k, v in cols.items()
                    }
                    while not self._stop.is_set():
                        try:
                            self._out_q.put(staged, timeout=0.1)
                            break
                        except queue.Full:
                            continue
            except Exception as e:
                self._error = e
            finally:
                # blocking put: the buffer may still hold staged batches the
                # consumer hasn't drained — the EOF sentinel must not be
                # lost, INCLUDING on the exception path (a dead stager with
                # no sentinel would hang the executor forever)
                while not self._stop.is_set():
                    try:
                        self._out_q.put(_EOF, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        feeder_t = threading.Thread(target=feeder, daemon=True)
        stager_t = threading.Thread(target=stager, daemon=True)
        self._threads = [feeder_t, stager_t]
        feeder_t.start()
        stager_t.start()
        self._started = True

    def reset(self):
        """Tear the pipeline down (end-of-epoch contract: catch
        EOFException -> reset() -> start())."""
        self._stop.set()
        if self._nq is not None:
            self._nq.close()
        if self._out_q is not None:
            try:
                while True:
                    self._out_q.get_nowait()
            except queue.Empty:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        if self._nq is not None:
            self._nq.destroy()
            self._nq = None
        self._threads = []
        self._started = False

    # ---- executor hook ---------------------------------------------------
    def next_feed(self):
        """Next ready-on-device batch as {var name: array}; raises
        EOFException when the decorated reader is exhausted."""
        if not self._started:
            raise RuntimeError(
                "py_reader '%s': start() must be called before exe.run"
                % self.name
            )
        item = self._out_q.get()
        if item is _EOF:
            self._started = False
            # stop surviving pipeline threads (on the error path the feeder
            # may still be alive pushing stale batches; a later start()
            # must not inherit them)
            self._stop.set()
            if self._nq is not None:
                self._nq.close()
            if self._error is not None:
                err, self._error = self._error, None
                raise RuntimeError(
                    "py_reader '%s' pipeline failed" % self.name
                ) from err
            raise EOFException("py_reader '%s' exhausted" % self.name)
        return item
