"""Functional reader combinators (python/paddle/reader/decorator.py:36-215
analog): a reader is a zero-arg callable returning a fresh iterator of
samples; decorators compose readers."""

import itertools
import multiprocessing
import queue
import random
import subprocess
import threading

__all__ = [
    "map_readers",
    "buffered",
    "compose",
    "chain",
    "shuffle",
    "firstn",
    "xmap_readers",
    "multiprocess_reader",
    "cache",
    "batch",
    "feed_prefetch",
    "PipeReader",
]


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    def reader():
        for r in readers:
            for e in r():
                yield e

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        _missing = object()
        for outputs in itertools.zip_longest(*rs, fillvalue=_missing):
            if any(x is _missing for x in outputs):
                if check_alignment:
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned (different lengths)"
                    )
                yield sum(
                    (make_tuple(x) for x in outputs if x is not _missing), ()
                )
            else:
                yield sum((make_tuple(x) for x in outputs), ())

    return reader


def buffered(reader, size):
    """Prefetch `size` samples on a background thread."""

    class _End:
        pass

    def data_reader():
        r = reader()
        q = queue.Queue(maxsize=size)

        def fill():
            try:
                for d in r:
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e

    return data_reader


def firstn(reader, n):
    def data_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return data_reader


def cache(reader):
    all_data = None

    def data_reader():
        nonlocal all_data
        if all_data is None:
            all_data = list(reader())
        return iter(all_data)

    return data_reader


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (paddle.batch analog)."""

    def data_reader():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return data_reader


def feed_prefetch(reader, place=None, depth=None):
    """Double-buffered device upload (the double_buffer reader-op role,
    de-sugared into a combinator): wrap a reader yielding FEED DICTS
    (name -> host array) so batch N+1 is `jax.device_put` on a
    background thread while step N computes — the executor's fast path
    sees ready-on-device committed arrays and its per-step H2D cost
    drops to a dict lookup.

    `depth` bounds how many staged batches may sit in device memory
    (default FLAGS_feed_prefetch; 0 passes batches through unstaged).
    Upload time lands in the "feed_upload" profiler span (cat="feed"),
    same as the executor's inline uploads, so the two strategies compare
    directly in one trace."""
    if depth is None:
        from ..flags import get_flag

        depth = int(get_flag("feed_prefetch"))
    if depth <= 0:
        return reader

    class _End:
        pass

    def data_reader():
        import jax

        from ..places import default_place
        from ..profiler import RecordEvent

        if place is None:
            device = default_place().jax_device()
        elif hasattr(place, "jax_device"):
            device = place.jax_device()
        else:
            device = place  # already a raw jax device
        q = queue.Queue(maxsize=depth)
        stop = threading.Event()

        def bounded_put(item):
            # bounded put that notices consumer shutdown — an abandoned
            # iterator must not pin staged device buffers, and the END
            # sentinel must not be dropped just because the queue is full
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def stage():
            try:
                for feed in reader():
                    with RecordEvent("feed_upload", cat="feed"):
                        staged = {
                            k: (v if hasattr(v, "devices")
                                else jax.device_put(v, device))
                            for k, v in feed.items()
                        }
                    if not bounded_put(staged):
                        return
            except BaseException as e:
                bounded_put(("__exc__", e))
            finally:
                bounded_put(_End)

        t = threading.Thread(target=stage, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _End:
                    break
                if isinstance(item, tuple) and len(item) == 2 \
                        and item[0] == "__exc__":
                    raise item[1]
                yield item
        finally:
            # abandoned iterator: unblock the producer and drop staged
            # batches so device buffers are reclaimable
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass

    return data_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with worker threads (decorator.py:243).
    Exceptions in the source reader or mapper propagate to the consumer
    (threads always post their end/error sentinel, so no deadlock)."""

    _End = object()

    def data_reader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def feed():
            try:
                for i, d in enumerate(reader()):
                    in_q.put((i, d))
            except BaseException as e:  # propagate through the workers
                out_q.put(("__exc__", e))
            finally:
                for _ in range(process_num):
                    in_q.put(_End)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is _End:
                        break
                    i, d = item
                    out_q.put((i, mapper(d)))
            except BaseException as e:
                out_q.put(("__exc__", e))
            finally:
                out_q.put(_End)

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True) for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is _End:
                finished += 1
                continue
            if item[0] == "__exc__":
                raise item[1]
            if not order:
                yield item[1]
                continue
            pending[item[0]] = item[1]
            while next_idx in pending:
                yield pending.pop(next_idx)
                next_idx += 1
        if order:
            while next_idx in pending:
                yield pending.pop(next_idx)
                next_idx += 1

    return data_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Run multiple readers in subprocesses (decorator.py:338).  As in the
    reference, a sample of None is an error (None is reserved; a tagged
    sentinel marks end-of-reader)."""

    _END = ("__reader_end__",)

    def data_reader():
        q = multiprocessing.Queue(queue_size)

        def work(r):
            try:
                for d in r():
                    if d is None:
                        raise ValueError("sample has None")
                    q.put(d)
            finally:
                q.put(_END)

        procs = [multiprocessing.Process(target=work, args=(r,)) for r in readers]
        for p in procs:
            p.daemon = True
            p.start()
        finished = 0
        while finished < len(readers):
            d = q.get()
            if isinstance(d, tuple) and len(d) == 1 and d[0] == "__reader_end__":
                finished += 1
            else:
                yield d

    return data_reader


class PipeReader:
    """Stream samples from a shell command's stdout (decorator.py:438)."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        self.command = command
        self.bufsize = bufsize
        self.file_type = file_type

    def get_line(self, cut_lines=True, line_break="\n"):
        proc = subprocess.Popen(
            self.command, shell=True, bufsize=self.bufsize, stdout=subprocess.PIPE
        )
        remained = b""
        while True:
            buf = proc.stdout.read(self.bufsize)
            if not buf:
                break
            if cut_lines:
                lines = (remained + buf).split(line_break.encode())
                remained = lines.pop()
                for line in lines:
                    yield line.decode("utf-8", "ignore")
            else:
                yield buf
        if remained:
            yield remained.decode("utf-8", "ignore")
