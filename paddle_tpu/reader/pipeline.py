"""Device feeding pipeline — the py_reader/double_buffer analog.

Reference: `layers/io.py:635 py_reader` + `operators/reader/
create_double_buffer_reader_op.cc`: a blocking queue feeds a prefetching
device reader so input upload overlaps compute.  Here a background thread
converts host batches and `jax.device_put`s them ahead of use; the executor
consumes ready-on-device arrays, so the step function never waits on H2D.
"""

import queue
import threading

import numpy as np

__all__ = ["PyReader", "DeviceFeeder"]


class _Stop:
    pass


class DeviceFeeder:
    """Wrap an iterator of feed dicts; prefetch `capacity` batches to device."""

    def __init__(self, place=None, capacity=2):
        from ..places import default_place

        self.place = place or default_place()
        self.capacity = capacity

    def __call__(self, batches):
        import jax

        device = self.place.jax_device()
        q = queue.Queue(maxsize=self.capacity)
        stop = threading.Event()

        def work():
            try:
                for feed in batches:
                    staged = {
                        k: jax.device_put(np.asarray(v), device)
                        for k, v in feed.items()
                    }
                    # bounded put that notices consumer shutdown — an
                    # abandoned iterator must not pin staged device buffers
                    while not stop.is_set():
                        try:
                            q.put(staged, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            finally:
                try:
                    q.put_nowait(_Stop)
                except queue.Full:
                    pass

        t = threading.Thread(target=work, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _Stop:
                    break
                yield item
        finally:
            # consumer broke out early (or exhausted): release the producer
            # and drop any staged batches so device memory is reclaimable
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass


class PyReader:
    """fluid.layers.py_reader-shaped API: decorate with a paddle-style batch
    reader + feed var list; iterate trained steps off the prefetch queue.

    Usage:
        reader = PyReader(feed_list=[img, label], capacity=4)
        reader.decorate_paddle_reader(paddle.batch(train_reader, 32))
        for feed in reader():
            exe.run(feed=feed, fetch_list=[loss])
    """

    def __init__(self, feed_list, capacity=4, place=None):
        from ..framework import Variable

        self.feed_names = [
            v.name if isinstance(v, Variable) else str(v) for v in feed_list
        ]
        self.capacity = capacity
        self._reader = None
        self._feeder = DeviceFeeder(place, capacity)

    def decorate_paddle_reader(self, reader):
        self._reader = reader

    decorate_sample_list_generator = decorate_paddle_reader

    def decorate_batch_generator(self, generator):
        self._reader = generator

    def __call__(self):
        assert self._reader is not None, "call decorate_paddle_reader first"

        def to_feeds():
            for batch_rows in self._reader():
                if isinstance(batch_rows, dict):
                    yield batch_rows
                    continue
                cols = list(zip(*batch_rows))
                yield {
                    name: np.asarray(col)
                    for name, col in zip(self.feed_names, cols)
                }

        return self._feeder(to_feeds())

    def start(self):
        pass

    def reset(self):
        pass
