"""High-level / incubating APIs (python/paddle/fluid/contrib analog)."""

from . import decoder, mixed_precision, quantize
from .memory_usage_calc import memory_usage
from .op_frequence import op_freq_statistic
from .trainer import (
    BeginEpochEvent,
    BeginStepEvent,
    CheckpointConfig,
    EndEpochEvent,
    EndStepEvent,
    Inferencer,
    Trainer,
    load_checkpoint,
    save_checkpoint,
)

__all__ = [
    "Trainer",
    "Inferencer",
    "CheckpointConfig",
    "BeginEpochEvent",
    "EndEpochEvent",
    "BeginStepEvent",
    "EndStepEvent",
    "save_checkpoint",
    "load_checkpoint",
    "memory_usage",
    "op_freq_statistic",
    "decoder",
    "mixed_precision",
    "quantize",
]
