"""paddle_tpu.contrib"""
