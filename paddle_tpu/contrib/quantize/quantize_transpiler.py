"""Quantization-aware-training program rewrite
(contrib/quantize/quantize_transpiler.py analog).

training_transpile() inserts fake-quantize (quantize-dequantize roundtrip,
straight-through gradient) ops on the activations and weights feeding
matmul/conv ops.  The reference computes in the int8 domain and re-scales
with a post-op dequantize (a cuDNN/GEMM-int8 detail); on TPU the QDQ form
is the right representation — XLA keeps everything bf16/f32 and the
simulated quantization error is identical.

freeze_program() converts a trained program for int8 inference: weight
quant ops are folded by pre-quantizing the scope weights, activation quant
ops switch to their stored scales (is_test).
"""

import numpy as np

from ... import framework
from ...framework import Operator

_QUANTIZABLE = ("conv2d", "depthwise_conv2d", "mul", "matmul")


class QuantizeTranspiler:
    def __init__(
        self,
        weight_bits=8,
        activation_bits=8,
        activation_quantize_type="abs_max",
        weight_quantize_type="abs_max",
        window_size=10000,
        moving_rate=0.9,
    ):
        assert activation_quantize_type in (
            "abs_max",
            "range_abs_max",
            "moving_average_abs_max",
        )
        assert weight_quantize_type in ("abs_max", "channel_wise_abs_max")
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.act_type = activation_quantize_type
        self.weight_type = weight_quantize_type
        self.window_size = window_size
        self.moving_rate = moving_rate

    # ------------------------------------------------------------------
    def _quant_op_for(self, block, name, is_weight, startup=None):
        """Append the fake-quant op quantizing var `name`; returns the
        quantized var name."""
        qname = name + ".quantized"
        sname = name + ".scale"
        bits = self.weight_bits if is_weight else self.activation_bits
        v = block._find_var_recursive(name)
        block.create_var(name=qname, shape=list(v.shape) if v else None, dtype="float32")

        if is_weight and self.weight_type == "channel_wise_abs_max":
            out_c = int(v.shape[0]) if v is not None and v.shape else 1
            block.create_var(name=sname, shape=[out_c], dtype="float32")
            block.append_op(
                "fake_channel_wise_quantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [qname], "OutScale": [sname]},
                attrs={"bit_length": bits},
            )
            return qname

        qtype = "abs_max" if is_weight else self.act_type
        block.create_var(name=sname, shape=[1], dtype="float32", persistable=qtype != "abs_max")
        if qtype == "abs_max":
            block.append_op(
                "fake_quantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [qname], "OutScale": [sname]},
                attrs={"bit_length": bits},
            )
        elif qtype == "moving_average_abs_max":
            state, accum = sname + ".state", sname + ".accum"
            for extra, fill in ((state, 1.0), (accum, 1e-7), (sname, 1e-7)):
                block.create_var(name=extra, shape=[1], dtype="float32", persistable=True)
                if startup is not None:
                    sb = startup.global_block()
                    sb.create_var(name=extra, shape=[1], dtype="float32", persistable=True)
                    sb.append_op(
                        "fill_constant",
                        outputs={"Out": [extra]},
                        attrs={"shape": [1], "dtype": "float32", "value": fill},
                    )
            block.append_op(
                "fake_quantize_moving_average_abs_max",
                inputs={"X": [name], "InScale": [sname], "InState": [state], "InAccum": [accum]},
                outputs={"Out": [qname], "OutScale": [sname], "OutState": [state], "OutAccum": [accum]},
                attrs={"bit_length": bits, "moving_rate": self.moving_rate},
            )
        else:  # range_abs_max
            scales, it = sname + ".buf", sname + ".iter"
            for extra, shape, fill in (
                (sname, [1], 1e-7),
                (scales, [min(self.window_size, 1024)], 0.0),
                (it, [1], 0.0),
            ):
                block.create_var(name=extra, shape=shape, dtype="float32", persistable=True)
                if startup is not None:
                    sb = startup.global_block()
                    sb.create_var(name=extra, shape=shape, dtype="float32", persistable=True)
                    sb.append_op(
                        "fill_constant",
                        outputs={"Out": [extra]},
                        attrs={"shape": shape, "dtype": "float32", "value": fill},
                    )
            block.append_op(
                "fake_quantize_range_abs_max",
                inputs={"X": [name], "InScale": [sname], "InScales": [scales], "Iter": [it]},
                outputs={"Out": [qname], "OutScale": [sname], "OutScales": [scales]},
                attrs={"bit_length": bits, "window_size": min(self.window_size, 1024)},
            )
            block.append_op(
                "increment",
                inputs={"X": [it]},
                outputs={"Out": [it]},
                attrs={"step": 1.0},
            )
        return qname

    # ------------------------------------------------------------------
    def training_transpile(self, program=None, startup_program=None):
        """Insert QDQ ops before every quantizable op (in place)."""
        program = program or framework.default_main_program()
        startup_program = startup_program or framework.default_startup_program()
        block = program.global_block()

        params = set(
            v.name for v in block.vars.values() if isinstance(v, framework.Parameter)
        )
        new_ops = []
        quantized = {}  # var name -> quantized name within this program
        for op in list(block.ops):
            if op.type in _QUANTIZABLE and op.attrs.get("op_role", "forward") == "forward":
                # stage the quant ops into new_ops via a scratch list
                hold = block.ops
                block.ops = new_ops
                for slot, names in list(op.inputs.items()):
                    renamed = []
                    for n in names:
                        if n not in quantized:
                            quantized[n] = self._quant_op_for(
                                block, n, n in params, startup_program
                            )
                        renamed.append(quantized[n])
                    op.inputs[slot] = renamed
                block.ops = hold
            new_ops.append(op)
        block.ops = new_ops
        program._bump_version()
        return program

    # ------------------------------------------------------------------
    def freeze_program(self, program, place=None, scope=None):
        """Prepare a QAT program for inference: pre-quantize weights in the
        scope (QDQ applied offline), remove their quant ops, and pin
        activation quant ops to stored scales (is_test)."""
        from ...executor import global_scope

        scope = scope if scope is not None else global_scope()
        block = program.global_block()
        new_ops = []
        for op in block.ops:
            if op.type in (
                "fake_quantize_abs_max",
                "fake_channel_wise_quantize_abs_max",
            ):
                src = op.inputs["X"][0]
                dst = op.outputs["Out"][0]
                w = scope.find_var(src)
                if w is not None:
                    bits = op.attrs.get("bit_length", 8)
                    rng = float(2 ** (bits - 1) - 1)
                    wv = np.asarray(w, dtype=np.float32)
                    if op.type == "fake_channel_wise_quantize_abs_max":
                        axes = tuple(range(1, wv.ndim))
                        scale = np.maximum(np.abs(wv).max(axis=axes, keepdims=True), 1e-8)
                    else:
                        scale = max(np.abs(wv).max(), 1e-8)
                    q = np.clip(np.round(wv / scale * rng), -rng, rng)
                    scope.set(dst, (q * scale / rng).astype(np.float32))
                    # quantized weight becomes a persistable input
                    v = block._find_var_recursive(dst)
                    if v is not None:
                        v.persistable = True
                    continue
            if op.type in (
                "fake_quantize_range_abs_max",
                "fake_quantize_moving_average_abs_max",
            ):
                op.attrs["is_test"] = True
            new_ops.append(op)
        block.ops = new_ops
        program._is_test = True
        program._bump_version()
        return program

    # ------------------------------------------------------------------
    def convert_to_int8(self, program, place=None, scope=None):
        """Convert a FROZEN QAT program to REAL int8 compute (the
        reference's TensorRT-int8 serving capability,
        inference/tensorrt/convert precedent, re-done TPU-native): each
        quantizable op whose weight was QDQ-folded and whose activation
        feeds through a remaining fake-quantize op becomes a
        ``quantized_*`` op — int8 weight tensor in the scope, int8
        activation quantization in-op (stored scale when the QAT type
        kept one, dynamic abs-max otherwise), int32 accumulation on the
        MXU, one fused dequant rescale.  mul/matmul weights must be
        abs_max-quantized (scalar scale — per-row scales cannot be
        factored out of the contraction); conv weights may be abs_max or
        channel_wise.  ``place`` is accepted for reference-signature
        compat and ignored (XLA owns placement).  Returns the count of
        converted ops."""
        from ...executor import global_scope

        if self.weight_bits != 8 or self.activation_bits != 8:
            raise ValueError(
                "convert_to_int8 requires weight_bits=8 and "
                "activation_bits=8 (got %d/%d): the int8 tensors and "
                "int32 MXU accumulation are 8-bit by construction — wider "
                "QAT configs stay in QDQ form (freeze_program only)"
                % (self.weight_bits, self.activation_bits))
        scope = scope if scope is not None else global_scope()
        block = program.global_block()

        # activation quant ops remaining after freeze: Out -> info
        _ACT_Q = {
            "fake_quantize_abs_max": None,
            "fake_quantize_range_abs_max": "InScale",
            "fake_quantize_moving_average_abs_max": "InScale",
        }
        act_q = {}
        for i, op in enumerate(block.ops):
            if op.type in _ACT_Q and scope.find_var(op.inputs["X"][0]) is None:
                scale_slot = _ACT_Q[op.type]
                act_q[op.outputs["Out"][0]] = {
                    "src": op.inputs["X"][0],
                    "scale": op.inputs[scale_slot][0] if scale_slot else None,
                    "idx": i,
                }

        _W_SLOT = {"mul": "Y", "matmul": "Y",
                   "conv2d": "Filter", "depthwise_conv2d": "Filter"}
        _X_SLOT = {"mul": "X", "matmul": "X",
                   "conv2d": "Input", "depthwise_conv2d": "Input"}
        count = 0
        used_quant_outs = set()
        converted_weights = set()
        for op in block.ops:
            if op.type not in _W_SLOT:
                continue
            wname = op.inputs[_W_SLOT[op.type]][0]
            xname = op.inputs[_X_SLOT[op.type]][0]
            wv = scope.find_var(wname)
            if wv is None or xname not in act_q:
                continue
            if (not op.type.endswith("conv2d")
                    and self.weight_type == "channel_wise_abs_max"):
                # per-row scales can't be factored out of the dot's
                # contraction — leave this op in QDQ form
                continue
            wv = np.asarray(wv, dtype=np.float32)
            bits = self.weight_bits
            rng = float(2 ** (bits - 1) - 1)
            if op.type.endswith("conv2d") and self.weight_type == "channel_wise_abs_max":
                axes = tuple(range(1, wv.ndim))
                scale = np.maximum(np.abs(wv).max(axis=axes), 1e-8)  # [Co]
                w_int8 = np.round(wv / scale.reshape((-1,) + (1,) * (wv.ndim - 1)) * rng)
            else:
                scale = np.array([max(float(np.abs(wv).max()), 1e-8)], np.float32)
                w_int8 = np.round(wv / scale[0] * rng)
            w_int8 = np.clip(w_int8, -rng, rng).astype(np.int8)

            iname, sname = wname + ".int8", wname + ".wscale"
            for nm, val in ((iname, w_int8), (sname, scale.astype(np.float32))):
                block.create_var(name=nm, shape=list(val.shape),
                                 dtype=str(val.dtype), persistable=True)
                scope.set(nm, val)

            info = act_q[xname]
            op.type = "quantized_" + op.type
            op.inputs[_X_SLOT[op.type[len("quantized_"):]]] = [info["src"]]
            op.inputs[_W_SLOT[op.type[len("quantized_"):]]] = [iname]
            op.inputs["WScale"] = [sname]
            if info["scale"] is not None:
                op.inputs["InScale"] = [info["scale"]]
            op.attrs["bit_length"] = bits
            used_quant_outs.add(xname)
            converted_weights.add(wname)
            count += 1

        # drop activation quant ops whose output no other op still reads
        still_read = set()
        for op in block.ops:
            for n in op.input_arg_names():
                still_read.add(n)
        block.ops = [
            op for op in block.ops
            if not (
                op.type in _ACT_Q
                and op.outputs["Out"][0] in used_quant_outs
                and op.outputs["Out"][0] not in still_read
            )
        ]
        # the folded f32 weights are dead once their int8 copy exists —
        # dropping them halves+ the persistable footprint (the point of
        # int8 serving); keep any still read by a non-converted op
        still_read = set()
        for op in block.ops:
            for n in op.input_arg_names():
                still_read.add(n)
        for wname in converted_weights:
            if wname not in still_read:
                scope.erase(wname)
                block.vars.pop(wname, None)
        program._bump_version()
        return count


def quantize_weights_int8(program, scope=None, min_elems=1024):
    """POST-TRAINING weight-only int8 (no QAT required): every
    mul/matmul/conv2d weight parameter >= min_elems becomes an int8
    tensor + scale in the scope, and the op dequantizes at compute time
    (XLA fuses the dequant into the matmul read) — activations stay
    full precision, so there is no activation-quantization error and no
    calibration step.  Halves weight HBM/footprint: the standard
    serving recipe for embedding/vocab-heavy LLM decode.  Weights are
    per-out-channel scaled for conv2d, per-row (axis 0) for embedding
    tables whose every consumer is a lookup — a few outlier rows must
    not crush the precision of the whole vocab — and per-tensor
    otherwise.  Shared weights (tied embeddings, where a matmul also
    reads the table) convert once, per-tensor, since per-row scales
    cannot be factored out of the tied projection's contraction.
    Returns converted-op count."""
    from ...executor import global_scope
    from ... import framework

    scope = scope if scope is not None else global_scope()
    block = program.global_block()
    _W_SLOT = {"mul": "Y", "matmul": "Y",
               "conv2d": "Filter", "depthwise_conv2d": "Filter",
               "lookup_table": "W", "lookup_table_v2": "W"}
    # weight -> set of consumer op types (per-row scales are only legal
    # when the table is exclusively gathered, never contracted)
    consumers = {}
    for op in block.ops:
        slot = _W_SLOT.get(op.type)
        if slot is not None:
            consumers.setdefault(op.inputs[slot][0], set()).add(op.type)
    done = {}  # weight name -> (int8 name, scale name)
    count = 0
    for op in block.ops:
        slot = _W_SLOT.get(op.type)
        if slot is None:
            continue
        wname = op.inputs[slot][0]
        v = block._find_var_recursive(wname)
        wv = scope.find_var(wname)
        if (wv is None or v is None or not getattr(v, "persistable", False)):
            continue
        wv = np.asarray(wv, dtype=np.float32)
        if wv.size < min_elems:
            continue
        rng = 127.0
        if wname not in done:
            lookup_only = all(
                t.startswith("lookup_table") for t in consumers[wname])
            if op.type.endswith("conv2d") or (
                    op.type.startswith("lookup_table") and lookup_only
                    and wv.ndim >= 2):
                axes = tuple(range(1, wv.ndim))
                scale = np.maximum(np.abs(wv).max(axis=axes), 1e-8)
                q = wv / scale.reshape((-1,) + (1,) * (wv.ndim - 1)) * rng
            else:
                scale = np.array([max(float(np.abs(wv).max()), 1e-8)],
                                 np.float32)
                q = wv / scale[0] * rng
            w_int8 = np.clip(np.round(q), -rng, rng).astype(np.int8)
            iname, sname = wname + ".w8", wname + ".w8scale"
            for nm, val in ((iname, w_int8),
                            (sname, scale.astype(np.float32))):
                block.create_var(name=nm, shape=list(val.shape),
                                 dtype=str(val.dtype), persistable=True)
                scope.set(nm, val)
            done[wname] = (iname, sname)
        iname, sname = done[wname]
        op.type = ("quantized_lookup_table"
                   if op.type.startswith("lookup_table")
                   else "quantized_" + op.type)
        op.inputs[slot] = [iname]
        op.inputs["WScale"] = [sname]
        op.attrs["bit_length"] = 8
        op.attrs["weight_only"] = True
        count += 1
    # drop the f32 originals that no remaining op reads
    still_read = set()
    for op in block.ops:
        still_read.update(op.input_arg_names())
    for wname in done:
        if wname not in still_read:
            scope.erase(wname)
            block.vars.pop(wname, None)
    program._bump_version()
    return count
