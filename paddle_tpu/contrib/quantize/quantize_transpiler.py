"""Quantization-aware-training program rewrite
(contrib/quantize/quantize_transpiler.py analog).

training_transpile() inserts fake-quantize (quantize-dequantize roundtrip,
straight-through gradient) ops on the activations and weights feeding
matmul/conv ops.  The reference computes in the int8 domain and re-scales
with a post-op dequantize (a cuDNN/GEMM-int8 detail); on TPU the QDQ form
is the right representation — XLA keeps everything bf16/f32 and the
simulated quantization error is identical.

freeze_program() converts a trained program for int8 inference: weight
quant ops are folded by pre-quantizing the scope weights, activation quant
ops switch to their stored scales (is_test).
"""

import numpy as np

from ... import framework
from ...framework import Operator

_QUANTIZABLE = ("conv2d", "depthwise_conv2d", "mul", "matmul")


class QuantizeTranspiler:
    def __init__(
        self,
        weight_bits=8,
        activation_bits=8,
        activation_quantize_type="abs_max",
        weight_quantize_type="abs_max",
        window_size=10000,
        moving_rate=0.9,
    ):
        assert activation_quantize_type in (
            "abs_max",
            "range_abs_max",
            "moving_average_abs_max",
        )
        assert weight_quantize_type in ("abs_max", "channel_wise_abs_max")
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.act_type = activation_quantize_type
        self.weight_type = weight_quantize_type
        self.window_size = window_size
        self.moving_rate = moving_rate

    # ------------------------------------------------------------------
    def _quant_op_for(self, block, name, is_weight, startup=None):
        """Append the fake-quant op quantizing var `name`; returns the
        quantized var name."""
        qname = name + ".quantized"
        sname = name + ".scale"
        bits = self.weight_bits if is_weight else self.activation_bits
        v = block._find_var_recursive(name)
        block.create_var(name=qname, shape=list(v.shape) if v else None, dtype="float32")

        if is_weight and self.weight_type == "channel_wise_abs_max":
            out_c = int(v.shape[0]) if v is not None and v.shape else 1
            block.create_var(name=sname, shape=[out_c], dtype="float32")
            block.append_op(
                "fake_channel_wise_quantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [qname], "OutScale": [sname]},
                attrs={"bit_length": bits},
            )
            return qname

        qtype = "abs_max" if is_weight else self.act_type
        block.create_var(name=sname, shape=[1], dtype="float32", persistable=qtype != "abs_max")
        if qtype == "abs_max":
            block.append_op(
                "fake_quantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [qname], "OutScale": [sname]},
                attrs={"bit_length": bits},
            )
        elif qtype == "moving_average_abs_max":
            state, accum = sname + ".state", sname + ".accum"
            for extra, fill in ((state, 1.0), (accum, 1e-7), (sname, 1e-7)):
                block.create_var(name=extra, shape=[1], dtype="float32", persistable=True)
                if startup is not None:
                    sb = startup.global_block()
                    sb.create_var(name=extra, shape=[1], dtype="float32", persistable=True)
                    sb.append_op(
                        "fill_constant",
                        outputs={"Out": [extra]},
                        attrs={"shape": [1], "dtype": "float32", "value": fill},
                    )
            block.append_op(
                "fake_quantize_moving_average_abs_max",
                inputs={"X": [name], "InScale": [sname], "InState": [state], "InAccum": [accum]},
                outputs={"Out": [qname], "OutScale": [sname], "OutState": [state], "OutAccum": [accum]},
                attrs={"bit_length": bits, "moving_rate": self.moving_rate},
            )
        else:  # range_abs_max
            scales, it = sname + ".buf", sname + ".iter"
            for extra, shape, fill in (
                (sname, [1], 1e-7),
                (scales, [min(self.window_size, 1024)], 0.0),
                (it, [1], 0.0),
            ):
                block.create_var(name=extra, shape=shape, dtype="float32", persistable=True)
                if startup is not None:
                    sb = startup.global_block()
                    sb.create_var(name=extra, shape=shape, dtype="float32", persistable=True)
                    sb.append_op(
                        "fill_constant",
                        outputs={"Out": [extra]},
                        attrs={"shape": shape, "dtype": "float32", "value": fill},
                    )
            block.append_op(
                "fake_quantize_range_abs_max",
                inputs={"X": [name], "InScale": [sname], "InScales": [scales], "Iter": [it]},
                outputs={"Out": [qname], "OutScale": [sname], "OutScales": [scales]},
                attrs={"bit_length": bits, "window_size": min(self.window_size, 1024)},
            )
            block.append_op(
                "increment",
                inputs={"X": [it]},
                outputs={"Out": [it]},
                attrs={"step": 1.0},
            )
        return qname

    # ------------------------------------------------------------------
    def training_transpile(self, program=None, startup_program=None):
        """Insert QDQ ops before every quantizable op (in place)."""
        program = program or framework.default_main_program()
        startup_program = startup_program or framework.default_startup_program()
        block = program.global_block()

        params = set(
            v.name for v in block.vars.values() if isinstance(v, framework.Parameter)
        )
        new_ops = []
        quantized = {}  # var name -> quantized name within this program
        for op in list(block.ops):
            if op.type in _QUANTIZABLE and op.attrs.get("op_role", "forward") == "forward":
                # stage the quant ops into new_ops via a scratch list
                hold = block.ops
                block.ops = new_ops
                for slot, names in list(op.inputs.items()):
                    renamed = []
                    for n in names:
                        if n not in quantized:
                            quantized[n] = self._quant_op_for(
                                block, n, n in params, startup_program
                            )
                        renamed.append(quantized[n])
                    op.inputs[slot] = renamed
                block.ops = hold
            new_ops.append(op)
        block.ops = new_ops
        program._bump_version()
        return program

    # ------------------------------------------------------------------
    def freeze_program(self, program, place=None, scope=None):
        """Prepare a QAT program for inference: pre-quantize weights in the
        scope (QDQ applied offline), remove their quant ops, and pin
        activation quant ops to stored scales (is_test)."""
        from ...executor import global_scope

        scope = scope if scope is not None else global_scope()
        block = program.global_block()
        new_ops = []
        for op in block.ops:
            if op.type in (
                "fake_quantize_abs_max",
                "fake_channel_wise_quantize_abs_max",
            ):
                src = op.inputs["X"][0]
                dst = op.outputs["Out"][0]
                w = scope.find_var(src)
                if w is not None:
                    bits = op.attrs.get("bit_length", 8)
                    rng = float(2 ** (bits - 1) - 1)
                    wv = np.asarray(w, dtype=np.float32)
                    if op.type == "fake_channel_wise_quantize_abs_max":
                        axes = tuple(range(1, wv.ndim))
                        scale = np.maximum(np.abs(wv).max(axis=axes, keepdims=True), 1e-8)
                    else:
                        scale = max(np.abs(wv).max(), 1e-8)
                    q = np.clip(np.round(wv / scale * rng), -rng, rng)
                    scope.set(dst, (q * scale / rng).astype(np.float32))
                    # quantized weight becomes a persistable input
                    v = block._find_var_recursive(dst)
                    if v is not None:
                        v.persistable = True
                    continue
            if op.type in (
                "fake_quantize_range_abs_max",
                "fake_quantize_moving_average_abs_max",
            ):
                op.attrs["is_test"] = True
            new_ops.append(op)
        block.ops = new_ops
        program._is_test = True
        program._bump_version()
        return program
