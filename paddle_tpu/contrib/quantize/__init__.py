from .quantize_transpiler import QuantizeTranspiler, quantize_weights_int8

__all__ = ["QuantizeTranspiler", "quantize_weights_int8"]
