"""Op-type histograms over programs (contrib/op_frequence.py analog)."""

from collections import Counter, OrderedDict


def op_freq_statistic(program):
    """Returns (single_op_count, adjacent_pair_count) ordered by frequency."""
    singles = Counter()
    pairs = Counter()
    prev = None
    for block in program.blocks:
        prev = None
        for op in block.ops:
            singles[op.type] += 1
            if prev is not None:
                pairs[prev + "," + op.type] += 1
            prev = op.type
    order = lambda c: OrderedDict(sorted(c.items(), key=lambda kv: -kv[1]))
    return order(singles), order(pairs)
