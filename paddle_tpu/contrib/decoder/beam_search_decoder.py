"""High-level beam-search decoder
(contrib/decoder/beam_search_decoder.py analog).

The reference builds a While program with StateCell/TrainingDecoder over
LoD tensor arrays.  TPU-native form: the decode loop is a host-driven step
loop over ONE compiled step program (compile once, run T times — the step
is where the FLOPs are), with the backtrack done by the beam_search_decode
op.  States are plain padded arrays [batch, beam, ...].
"""

import numpy as np


def _beam_topk(total, beam):
    """Shared beam-step selection: flatten [B, beam, V] candidate scores,
    take the top `beam` per example, split back into (parent beam, token,
    score) — the one top-k core behind both decoders."""
    b, _, vocab = total.shape
    flat = total.reshape(b, -1)
    # argpartition: O(beam*V) select, then sort only the `beam` survivors
    # (a full argsort of beam*vocab candidates per token is the hot-path
    # host cost for large vocabs)
    part = np.argpartition(-flat, beam - 1, axis=1)[:, :beam]
    part_scores = np.take_along_axis(flat, part, axis=1)
    order = np.argsort(-part_scores, axis=1)
    top_idx = np.take_along_axis(part, order, axis=1)
    top_scores = np.take_along_axis(part_scores, order, axis=1)
    parent = (top_idx // vocab).astype(np.int32)
    token = (top_idx % vocab).astype(np.int32)
    return parent, token, top_scores


class BeamSearchDecoder:
    """Drives a user step function through beam search.

    step_fn(token_ids [batch*beam], states) -> (log_probs [batch*beam, vocab],
    new_states) — typically a compiled Executor.run over a step program.
    """

    def __init__(self, step_fn, beam_size, start_token, end_token, max_len=32):
        self.step_fn = step_fn
        self.beam_size = beam_size
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.max_len = int(max_len)

    def decode(self, batch_size, init_states=None):
        """Returns (sentence_ids [batch, beam, <=max_len], scores [batch, beam])."""
        beam = self.beam_size
        pre_ids = np.full((batch_size, beam), self.start_token, np.int32)
        pre_scores = np.full((batch_size, beam), -1e9, np.float32)
        pre_scores[:, 0] = 0.0  # only beam 0 is live initially
        states = init_states

        ids_steps, parent_steps, score_steps = [], [], []
        for _ in range(self.max_len):
            logp, states = self.step_fn(pre_ids.reshape(-1), states)
            logp = np.asarray(logp, np.float32).reshape(batch_size, beam, -1)
            vocab = logp.shape[-1]

            finished = pre_ids == self.end_token
            cont = pre_scores[:, :, None] + logp
            frozen = np.full_like(cont, -1e9)
            frozen[:, :, self.end_token] = pre_scores
            total = np.where(finished[:, :, None], frozen, cont)

            parent, token, top_scores = _beam_topk(total, beam)

            ids_steps.append(token)
            parent_steps.append(parent)
            score_steps.append(top_scores)
            pre_ids, pre_scores = token, top_scores
            # states follow their beam's parent
            if states is not None:
                states = _reindex_states(states, parent, batch_size, beam)
            if (token == self.end_token).all():
                break

        # backtrack
        T = len(ids_steps)
        out = np.zeros((batch_size, beam, T), np.int32)
        ptr = np.tile(np.arange(beam, dtype=np.int32), (batch_size, 1))
        rows = np.arange(batch_size)[:, None]
        for t in range(T - 1, -1, -1):
            out[:, :, t] = ids_steps[t][rows, ptr]
            ptr = parent_steps[t][rows, ptr]
        return out, score_steps[-1]


def _reindex_states(states, parent, batch_size, beam):
    """Gather each state along the beam dim by parent index."""
    rows = np.arange(batch_size)[:, None]

    def gather(s):
        s = np.asarray(s)
        shaped = s.reshape(batch_size, beam, *s.shape[1:])
        return shaped[rows, parent].reshape(s.shape)

    if isinstance(states, dict):
        return {k: gather(v) for k, v in states.items()}
    if isinstance(states, (list, tuple)):
        return type(states)(gather(v) for v in states)
    return gather(states)


def full_sequence_beam_search(logits_fn, prompt_buf, prompt_len, beam_size,
                              max_out_len, eos_id, pad_id=0,
                              length_penalty=0.0):
    """Beam search over a fixed-shape full-sequence logits program.

    logits_fn(buf [R, T], cur) -> [R, vocab] next-token logits at position
    cur-1 for every row (R = batch*beam; typically one Executor.run of a
    gpt2_logits_program / transformer_logits_program).  prompt_buf [B, T]
    holds the prompts left-aligned (padded with pad_id); decoding starts
    at prompt_len.  Returns (ids [B, T_out], scores [B]) for the best beam
    per example; finished beams (emitted eos_id) carry their score
    unchanged, optionally normalized by length**length_penalty.
    """
    prompt_buf = np.asarray(prompt_buf)
    b, t = prompt_buf.shape
    limit = min(max_out_len, t)
    buf = np.repeat(prompt_buf, beam_size, axis=0)  # [B*beam, T]
    scores = np.full((b, beam_size), -1e9, np.float32)
    scores[:, 0] = 0.0
    finished = np.zeros((b, beam_size), bool)
    lengths = np.full((b, beam_size), prompt_len, np.int64)
    cur = prompt_len
    while cur < limit and not finished.all():
        logits = np.asarray(logits_fn(buf, cur), np.float32)
        (rows, step_tok, scores, lengths, finished) = _beam_step(
            logits, scores, finished, lengths, beam_size, eos_id, pad_id)
        buf = buf[rows]
        buf[:, cur] = step_tok
        cur += 1
    if length_penalty:
        scores = scores / (lengths.astype(np.float32) ** length_penalty)
    best = np.argmax(scores, axis=1)
    rows = np.arange(b) * beam_size + best
    return buf[rows][:, :cur], scores[np.arange(b), best]


def _logsumexp(x):
    m = x.max(axis=-1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(axis=-1, keepdims=True))


def _beam_step(logits, scores, finished, lengths, beam_size, eos_id, pad_id):
    """One beam expansion shared by the full-sequence and incremental
    searches: finished beams emit pad at zero cost (score frozen), top-k
    over (scores + logp), parent gather.  Returns (flat parent rows,
    flat step tokens, scores, lengths, finished)."""
    b = scores.shape[0]
    logp = logits - _logsumexp(logits)
    v = logp.shape[-1]
    logp = logp.reshape(b, beam_size, v)
    fin = finished
    logp[fin] = -1e9
    logp[fin, pad_id] = 0.0
    cand = scores[:, :, None] + logp  # [B, beam, V]
    parent, tok, scores = _beam_topk(cand, beam_size)
    rows = (np.arange(b)[:, None] * beam_size + parent).reshape(-1)
    was_fin = np.take_along_axis(finished, parent, axis=1)
    step_tok = np.where(was_fin.reshape(-1), pad_id, tok.reshape(-1))
    lengths = np.take_along_axis(lengths, parent, axis=1) + (~was_fin)
    finished = was_fin | (tok == eos_id)
    return rows, step_tok, scores, lengths, finished


def incremental_beam_search(step_fn, reorder_fn, first_logits, prompt_buf,
                            prompt_len, beam_size, max_total_len, eos_id,
                            pad_id=0, length_penalty=0.0):
    """Beam search over a KV-CACHED one-token decode step.

    step_fn(tokens [R, 1], pos) -> [R, vocab] logits for the NEXT
    position; reorder_fn(rows [R]) shuffles the decoder's cache state to
    the selected parent rows BEFORE the next step (the reference's
    beam-search cache-shuffling contract); first_logits [R, vocab] are
    the logits after prefilling the prompt (R = batch*beam, prompt rows
    repeated per beam).  Scoring/finish semantics match
    full_sequence_beam_search; returns (ids [B, T_out], scores [B])."""
    prompt_buf = np.asarray(prompt_buf)
    b, p = prompt_buf.shape
    assert p == prompt_len
    limit = max_total_len
    buf = np.full((b * beam_size, limit), pad_id, np.int64)
    buf[:, :p] = np.repeat(prompt_buf, beam_size, axis=0)
    scores = np.full((b, beam_size), -1e9, np.float32)
    scores[:, 0] = 0.0
    finished = np.zeros((b, beam_size), bool)
    lengths = np.full((b, beam_size), prompt_len, np.int64)
    logits = np.asarray(first_logits, np.float32)
    cur = prompt_len
    while cur < limit and not finished.all():
        (rows, step_tok, scores, lengths, finished) = _beam_step(
            logits, scores, finished, lengths, beam_size, eos_id, pad_id)
        buf = buf[rows]
        buf[:, cur] = step_tok
        cur += 1
        if cur < limit and not finished.all():
            # caches follow the surviving beams — skipped on the final
            # pass, whose shuffle no further step would read
            reorder_fn(rows)
            logits = np.asarray(
                step_fn(step_tok[:, None].astype(np.int64), cur - 1),
                np.float32)
    if length_penalty:
        scores = scores / (lengths.astype(np.float32) ** length_penalty)
    best = np.argmax(scores, axis=1)
    rows = np.arange(b) * beam_size + best
    return buf[rows][:, :cur], scores[np.arange(b), best]
