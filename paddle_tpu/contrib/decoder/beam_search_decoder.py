"""High-level beam-search decoder
(contrib/decoder/beam_search_decoder.py analog).

The reference builds a While program with StateCell/TrainingDecoder over
LoD tensor arrays.  TPU-native form: the decode loop is a host-driven step
loop over ONE compiled step program (compile once, run T times — the step
is where the FLOPs are), with the backtrack done by the beam_search_decode
op.  States are plain padded arrays [batch, beam, ...].
"""

import numpy as np


class BeamSearchDecoder:
    """Drives a user step function through beam search.

    step_fn(token_ids [batch*beam], states) -> (log_probs [batch*beam, vocab],
    new_states) — typically a compiled Executor.run over a step program.
    """

    def __init__(self, step_fn, beam_size, start_token, end_token, max_len=32):
        self.step_fn = step_fn
        self.beam_size = beam_size
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.max_len = int(max_len)

    def decode(self, batch_size, init_states=None):
        """Returns (sentence_ids [batch, beam, <=max_len], scores [batch, beam])."""
        beam = self.beam_size
        pre_ids = np.full((batch_size, beam), self.start_token, np.int32)
        pre_scores = np.full((batch_size, beam), -1e9, np.float32)
        pre_scores[:, 0] = 0.0  # only beam 0 is live initially
        states = init_states

        ids_steps, parent_steps, score_steps = [], [], []
        for _ in range(self.max_len):
            logp, states = self.step_fn(pre_ids.reshape(-1), states)
            logp = np.asarray(logp, np.float32).reshape(batch_size, beam, -1)
            vocab = logp.shape[-1]

            finished = pre_ids == self.end_token
            cont = pre_scores[:, :, None] + logp
            frozen = np.full_like(cont, -1e9)
            frozen[:, :, self.end_token] = pre_scores
            total = np.where(finished[:, :, None], frozen, cont)

            flat = total.reshape(batch_size, beam * vocab)
            top_idx = np.argsort(-flat, axis=1)[:, :beam]
            top_scores = np.take_along_axis(flat, top_idx, axis=1)
            parent = (top_idx // vocab).astype(np.int32)
            token = (top_idx % vocab).astype(np.int32)

            ids_steps.append(token)
            parent_steps.append(parent)
            score_steps.append(top_scores)
            pre_ids, pre_scores = token, top_scores
            # states follow their beam's parent
            if states is not None:
                states = _reindex_states(states, parent, batch_size, beam)
            if (token == self.end_token).all():
                break

        # backtrack
        T = len(ids_steps)
        out = np.zeros((batch_size, beam, T), np.int32)
        ptr = np.tile(np.arange(beam, dtype=np.int32), (batch_size, 1))
        rows = np.arange(batch_size)[:, None]
        for t in range(T - 1, -1, -1):
            out[:, :, t] = ids_steps[t][rows, ptr]
            ptr = parent_steps[t][rows, ptr]
        return out, score_steps[-1]


def _reindex_states(states, parent, batch_size, beam):
    """Gather each state along the beam dim by parent index."""
    rows = np.arange(batch_size)[:, None]

    def gather(s):
        s = np.asarray(s)
        shaped = s.reshape(batch_size, beam, *s.shape[1:])
        return shaped[rows, parent].reshape(s.shape)

    if isinstance(states, dict):
        return {k: gather(v) for k, v in states.items()}
    if isinstance(states, (list, tuple)):
        return type(states)(gather(v) for v in states)
    return gather(states)
