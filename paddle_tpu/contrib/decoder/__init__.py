from .beam_search_decoder import BeamSearchDecoder

__all__ = ["BeamSearchDecoder"]
