"""High-level Trainer / Inferencer with checkpoint-resume
(python/paddle/fluid/contrib/trainer.py analog: Trainer :170,
CheckpointConfig :101, save_checkpoint :664, load_checkpoint :764).

The event-driven train loop, serial-numbered checkpoint dirs with pruning,
and trainer-state persistence are kept; execution is the compiled TPU
executor underneath.
"""

import json
import os
import shutil

import numpy as np

from .. import framework, io
from ..executor import Executor
from ..core.scope import Scope
from .. import core


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """Checkpoint policy (contrib/trainer.py:101)."""

    def __init__(
        self,
        checkpoint_dir=None,
        max_num_checkpoints=3,
        epoch_interval=1,
        step_interval=10,
        pserver_endpoints=None,
    ):
        self.checkpoint_dir = checkpoint_dir or "checkpoint"
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))
        # pserver-mode training: endpoints to checkpoint_notify in step
        # with trainer checkpoints (checkpoint_notify_op.cc analog)
        self.pserver_endpoints = list(pserver_endpoints or ())
        # populated on resume
        self.epoch_id = 0
        self.step_id = 0


_TRAINER_STATE_FILE = "TRAINER_STATE"
_SERIAL_PREFIX = "checkpoint_"


def _serial_dirs(root):
    if not os.path.isdir(root):
        return []
    out = []
    for d in os.listdir(root):
        if d.startswith(_SERIAL_PREFIX):
            try:
                out.append((int(d[len(_SERIAL_PREFIX):]), os.path.join(root, d)))
            except ValueError:
                pass
    return sorted(out)


def save_checkpoint(
    executor, checkpoint_dir, main_program, trainer_args=None,
    max_num_checkpoints=3, scope=None, pserver_endpoints=None,
):
    """Persistables + trainer state into the next serial dir; prune old
    serials (save_checkpoint :664).

    pserver_endpoints: when training in pserver mode, the trainer asks
    every parameter server to snapshot its shard into this serial's
    directory in the same call — the checkpoint_notify path
    (checkpoint_notify_op.cc; reference contrib/trainer.py:1013
    _save_pserver_vars_by_notify) — so trainer and pserver state stay
    consistent instead of relying on the pservers' own timers."""
    serials = _serial_dirs(checkpoint_dir)
    serial = serials[-1][0] + 1 if serials else 0
    cur = os.path.join(checkpoint_dir, _SERIAL_PREFIX + str(serial))
    os.makedirs(cur, exist_ok=True)
    io.save_persistables(executor, cur, main_program, scope=scope)
    with open(os.path.join(cur, _TRAINER_STATE_FILE), "w") as f:
        json.dump(trainer_args or {}, f)
    if pserver_endpoints:
        import threading
        import warnings

        from ..distributed.rpc import RPCClient

        def notify(ep):
            try:
                RPCClient.get(ep).checkpoint_notify(dir=os.path.abspath(cur))
            except Exception as e:  # a transient RPC hiccup must not kill
                warnings.warn(  # training or skip serial pruning below
                    "checkpoint_notify to %s failed: %s" % (ep, e))

        ts = [threading.Thread(target=notify, args=(ep,))
              for ep in pserver_endpoints]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    for old_serial, path in _serial_dirs(checkpoint_dir)[:-max_num_checkpoints]:
        shutil.rmtree(path, ignore_errors=True)
    return serial


def load_checkpoint(executor, checkpoint_dir, main_program, scope=None):
    """Restore the newest serial; returns trainer state dict or None
    (load_checkpoint :764)."""
    serials = _serial_dirs(checkpoint_dir)
    if not serials:
        return None
    _, cur = serials[-1]
    io.load_persistables(executor, cur, main_program, scope=scope)
    state_path = os.path.join(cur, _TRAINER_STATE_FILE)
    if os.path.exists(state_path):
        with open(state_path) as f:
            return json.load(f)
    return {}


class Trainer:
    """Event-driven trainer (contrib/trainer.py:170).

    train_func() builds the model in the fresh default program and returns
    the loss Variable (optionally [loss, ...metrics]); optimizer_func()
    returns the Optimizer.
    """

    def __init__(
        self,
        train_func,
        optimizer_func,
        place=None,
        param_path=None,
        checkpoint_config=None,
    ):
        self.place = place
        self.checkpoint_cfg = checkpoint_config
        self.scope = Scope()
        self.startup_program = framework.Program()
        self.train_program = framework.Program()
        from .. import unique_name

        # fresh name generator: a re-created Trainer (checkpoint resume in a
        # new process or the same one) must assign identical param names
        with unique_name.guard(), framework.program_guard(
            self.train_program, self.startup_program
        ):
            ret = train_func()
            if isinstance(ret, (list, tuple)):
                self.loss = ret[0]
                self.metrics = list(ret)
            else:
                self.loss = ret
                self.metrics = [ret]
            optimizer = optimizer_func()
            optimizer.minimize(self.loss)

        self.exe = Executor(place)
        self.exe.run(self.startup_program, scope=self.scope)
        if param_path:
            io.load_persistables(
                self.exe, param_path, self.train_program, scope=self.scope
            )
        if self.checkpoint_cfg:
            state = load_checkpoint(
                self.exe,
                self.checkpoint_cfg.checkpoint_dir,
                self.train_program,
                scope=self.scope,
            )
            if state is not None:
                self.checkpoint_cfg.epoch_id = int(state.get("epoch_id", 0))
                self.checkpoint_cfg.step_id = int(state.get("step_id", 0))
        self._stop = False

    def stop(self):
        self._stop = True

    def train(self, num_epochs, event_handler, reader, feed_order):
        start_epoch = self.checkpoint_cfg.epoch_id if self.checkpoint_cfg else 0
        step = self.checkpoint_cfg.step_id if self.checkpoint_cfg else 0
        for epoch_id in range(start_epoch, num_epochs):
            if self._stop:
                break
            event_handler(BeginEpochEvent(epoch_id))
            for step_id, data in enumerate(reader()):
                if self._stop:
                    break
                begin = BeginStepEvent(epoch_id, step_id)
                event_handler(begin)
                feed = self._feed_from(data, feed_order)
                fetch = [m.name for m in self.metrics] if begin.fetch_metrics else []
                metrics = self.exe.run(
                    self.train_program, feed=feed, fetch_list=fetch, scope=self.scope
                )
                event_handler(EndStepEvent(epoch_id, step_id, metrics))
                step += 1
                if (
                    self.checkpoint_cfg
                    and step % self.checkpoint_cfg.step_interval == 0
                ):
                    self._checkpoint(epoch_id, step)
            event_handler(EndEpochEvent(epoch_id))
            if (
                self.checkpoint_cfg
                and (epoch_id + 1) % self.checkpoint_cfg.epoch_interval == 0
            ):
                self._checkpoint(epoch_id + 1, step)

    def _feed_from(self, data, feed_order):
        if isinstance(data, dict):
            return data
        feed = {}
        for name, value in zip(feed_order, zip(*data) if _is_rows(data) else data):
            feed[name] = np.asarray(value)
        return feed

    def _checkpoint(self, epoch_id, step_id):
        save_checkpoint(
            self.exe,
            self.checkpoint_cfg.checkpoint_dir,
            self.train_program,
            trainer_args={"epoch_id": epoch_id, "step_id": step_id},
            max_num_checkpoints=self.checkpoint_cfg.max_num_checkpoints,
            scope=self.scope,
            pserver_endpoints=self.checkpoint_cfg.pserver_endpoints,
        )

    def save_params(self, param_path):
        os.makedirs(param_path, exist_ok=True)
        io.save_persistables(
            self.exe, param_path, self.train_program, scope=self.scope
        )

    def save_inference_model(self, param_path, feeded_var_names, target_var_indexes):
        targets = [self.metrics[i] for i in target_var_indexes]
        io.save_inference_model(
            param_path,
            feeded_var_names,
            targets,
            self.exe,
            main_program=self.train_program,
            scope=self.scope,
        )


def _is_rows(data):
    """True when `data` is a list of per-sample tuples (batched reader)."""
    return (
        isinstance(data, (list, tuple))
        and data
        and isinstance(data[0], (list, tuple))
    )


class Inferencer:
    """Build-and-serve counterpart (contrib/inferencer.py analog)."""

    def __init__(self, infer_func, param_path, place=None):
        self.scope = Scope()
        self.startup_program = framework.Program()
        self.inference_program = framework.Program()
        from .. import unique_name

        with unique_name.guard(), framework.program_guard(
            self.inference_program, self.startup_program
        ):
            self.predict_var = infer_func()
        self.inference_program._is_test = True
        self.exe = Executor(place)
        self.exe.run(self.startup_program, scope=self.scope)
        io.load_persistables(
            self.exe, param_path, self.inference_program, scope=self.scope
        )

    def infer(self, inputs):
        return self.exe.run(
            self.inference_program,
            feed=inputs,
            fetch_list=[self.predict_var],
            scope=self.scope,
        )
