"""Estimate a program's memory footprint
(contrib/memory_usage_calc.py analog).

Walks the program's vars, sizes them for a given batch size, and returns a
(low, high) byte range — the high bound assumes every temp is live at once,
the low bound assumes XLA's reuse collapses temps to the two largest (the
usual double-buffer case)."""

DTYPE_TO_SIZE = {
    "float32": 4,
    "float64": 8,
    "float16": 2,
    "bfloat16": 2,
    "int64": 8,
    "int32": 4,
    "int16": 2,
    "int8": 1,
    "uint8": 1,
    "bool": 1,
}


def memory_usage(program, batch_size=1):
    """Returns (low_bytes, high_bytes) for one step of `program`."""
    persist = 0
    temps = []
    for var in program.global_block().vars.values():
        if var.shape is None:
            continue
        numel = 1
        for d in var.shape:
            d = int(d)
            numel *= batch_size if d < 0 else d
        nbytes = numel * DTYPE_TO_SIZE.get(str(var.dtype), 4)
        if var.persistable:
            persist += nbytes
        else:
            temps.append(nbytes)
    temps.sort(reverse=True)
    high = persist + sum(temps)
    low = persist + sum(temps[:2])
    return low, high
