"""bfloat16 automatic mixed precision (contrib/float16 transpiler role,
re-targeted at the TPU's native compute dtype).

The reference's fp16 transpiler rewrites an inference program for half
kernels; on TPU the MXU natively multiplies bf16 at full rate, so AMP is
a training-time rewrite: cast the inputs of every matmul-class op
(mul/matmul/conv2d/depthwise_conv2d) to bfloat16 and the result back to
float32.  Master weights, accumulations, reductions, softmax and the
optimizer all stay float32 — the standard bf16 recipe; no loss scaling is
needed (bf16 has float32's exponent range).

    loss = ...
    rewrite_bf16(fluid.default_main_program())
    opt.minimize(loss)      # grads flow through the casts
"""

from .. import framework

_BF16_OPS = ("mul", "matmul", "conv2d", "depthwise_conv2d", "fused_attention")

# input slots that must stay float32 even when the op is rewritten
# (additive -1e9 padding masks lose nothing in bf16, but keeping them f32
# costs nothing and avoids surprises with user-supplied biases)
_KEEP_F32_SLOTS = {"fused_attention": ("Bias",)}

# dtype-transparent trunk ops: (data input slots, flippable output slots).
# When every data input of one of these is available in half precision,
# the op itself runs in half — its lowering preserves the input dtype
# (batch_norm computes statistics in f32 internally, nn_ops.py) — so the
# conv->bn->relu->residual-add->pool trunk of a convnet stays bf16 in HBM
# instead of bouncing through f32 between every pair of matmul-class ops.
# Parameter/state slots (Scale/Bias/Mean/Variance) and state outputs
# (MeanOut/Saved*) keep f32.
_TRANSPARENT_OPS = {
    "relu": (("X",), ("Out",)),
    "pool2d": (("X",), ("Out",)),
    "batch_norm": (("X",), ("Y",)),
    "elementwise_add": (("X", "Y"), ("Out",)),
}


def _tag_for(dtype):
    return "BF16" if dtype == "bfloat16" else "FP16"


def _emit_raw_and_castback(block, name, dtype, tag):
    """Create the half var `<name>@RAW_<tag>` plus the half->f32 cast op
    restoring `name`; returns (raw_name, cast_back_op).  The caller wires
    the producing op to write the raw var and appends the cast-back after
    it — the shared emission step of both AMP passes."""
    raw = name + "@RAW_" + tag
    v = block._find_var_recursive(name)
    block.create_var(
        name=raw,
        shape=list(v.shape) if v is not None and v.shape else None,
        dtype=dtype,
    )
    cast_back = framework.Operator(
        block,
        "cast",
        None,
        None,
        {"in_dtype": dtype, "out_dtype": "float32"},
    )
    cast_back.inputs = {"X": [raw]}
    cast_back.outputs = {"Out": [name]}
    return raw, cast_back


def rewrite_bf16(program=None, ops=_BF16_OPS, dtype="bfloat16"):
    """Insert half-precision casts around matmul-class ops (in place).
    Must run BEFORE optimizer.minimize so the grad ops differentiate
    through the casts.  Returns the count of rewritten ops.  dtype
    "bfloat16" is the TPU-native training regime; "float16" mirrors the
    reference's fp16 inference transpiler (paddle/contrib/float16)."""
    program = program or framework.default_main_program()
    tag = _tag_for(dtype)
    block = program.global_block()
    new_ops = []
    count = 0
    cast_cache = {}  # var name -> bf16 var name (reuse within the block)

    def cast_var(name, dst_dtype, tag):
        key = (name, dst_dtype)
        if key in cast_cache:
            return cast_cache[key]
        src = block._find_var_recursive(name)
        out = block.create_var(
            name="%s@%s" % (name, tag),
            shape=list(src.shape) if src is not None and src.shape else None,
            dtype=dst_dtype,
        )
        op = framework.Operator(
            block,
            "cast",
            None,
            None,
            {"in_dtype": str(src.dtype) if src is not None else "float32",
             "out_dtype": dst_dtype},
        )
        op.inputs = {"X": [name]}
        op.outputs = {"Out": [out.name]}
        new_ops.append(op)
        cast_cache[key] = out.name
        return out.name

    for op in block.ops:
        if (
            op.type in ops
            and op.attrs.get("op_role", "forward") == "forward"
        ):
            count += 1
            keep_f32 = _KEEP_F32_SLOTS.get(op.type, ())
            for slot, names in list(op.inputs.items()):
                if slot in keep_f32:
                    continue
                op.inputs[slot] = [
                    cast_var(n, dtype, tag) for n in names
                ]
            new_ops.append(op)
            # cast outputs back to f32, keeping downstream names intact:
            # the op writes <out>@RAW_BF16 and a cast restores <out>
            for slot, names in list(op.outputs.items()):
                restored = []
                for n in names:
                    raw, cast_back = _emit_raw_and_castback(
                        block, n, dtype, tag)
                    restored.append((slot, raw, cast_back))
                op.outputs[slot] = [r[1] for r in restored]
                for _, _, cb in restored:
                    new_ops.append(cb)
                    # cast-back redefines the original name: a later bf16
                    # cast of it must re-derive from the new value
                    cast_cache.pop((cb.outputs["Out"][0], dtype), None)
        else:
            new_ops.append(op)
            # anything redefined later must not serve a stale cast
            for names in op.outputs.values():
                for n in names:
                    cast_cache.pop((n, dtype), None)
    block.ops = new_ops
    propagate_half_through_trunk(program, dtype)
    collapse_redundant_casts(program, dtype)
    program._bump_version()
    return count


def propagate_half_through_trunk(program, dtype="bfloat16"):
    """Flip dtype-transparent trunk ops (_TRANSPARENT_OPS) to half.

    An op whose every data input is the f32 cast-back of a half tensor is
    rewired to read the half tensor directly; its data output becomes a
    NEW half var, and a cast-back op re-defines the original f32 name so
    every other consumer (fetches, non-transparent ops, sub-blocks) is
    untouched.  Unused cast-backs are dropped by trace-time DCE, and the
    downstream f32->half re-casts collapse in collapse_redundant_casts —
    net effect: the conv/BN/relu/add/pool trunk runs half end-to-end.
    Returns the number of flipped ops."""
    tag = _tag_for(dtype)
    block = program.global_block()
    castback_src = {}  # f32 name -> half name, current definitions only
    new_ops = []
    flipped = 0
    for op in block.ops:
        spec = _TRANSPARENT_OPS.get(op.type)
        halves = None
        if spec is not None:
            in_slots, out_slots = spec
            names = [n for s in in_slots for n in op.inputs.get(s, [])]
            if names and all(n in castback_src for n in names):
                if op.type == "elementwise_add":
                    # same-shape operands only: axis-broadcast adds (bias
                    # adds) keep their f32 contract
                    vs = [block._find_var_recursive(n) for n in names]
                    if any(
                        v is None or v.shape is None for v in vs
                    ) or len({tuple(v.shape) for v in vs}) != 1:
                        names = None
                if names:
                    halves = {n: castback_src[n] for n in names}
        if halves is not None:
            for s in in_slots:
                if s in op.inputs:
                    op.inputs[s] = [halves.get(n, n) for n in op.inputs[s]]
            new_ops.append(op)
            flipped += 1
            for s in out_slots:
                for i, n in enumerate(list(op.outputs.get(s, []))):
                    raw, cb = _emit_raw_and_castback(block, n, dtype, tag)
                    op.outputs[s][i] = raw
                    new_ops.append(cb)
                    castback_src[n] = raw
            # non-flipped outputs (MeanOut/Saved*) redefine their names
            for s, ns in op.outputs.items():
                if s not in out_slots:
                    for n in ns:
                        castback_src.pop(n, None)
            continue
        is_castback = (op.type == "cast"
                       and op.attrs.get("out_dtype") == "float32"
                       and op.attrs.get("in_dtype") == dtype)
        for n in op.output_arg_names():
            castback_src.pop(n, None)
        if is_castback:
            castback_src[op.outputs["Out"][0]] = op.inputs["X"][0]
        new_ops.append(op)
    if flipped:
        block.ops = new_ops
        program._bump_version()
    return flipped


def collapse_redundant_casts(program, dtype="bfloat16"):
    """Peephole: when a half->f32 cast-back feeds an f32->half re-cast,
    the re-cast collapses — its consumers read the original half tensor
    directly.  Numerically identical (half->f32->half is exact), but
    consecutive matmul-class ops stop bouncing activations through f32 in
    HBM (matmul->matmul chains in transformer blocks).

    The cast-back itself is KEPT: it still defines the original f32 name,
    which may be a fetch target or a sub-block read the global-block
    consumer scan cannot see.  When nothing ends up using it, trace-time
    DCE drops it per fetch set — so the collapse is always safe and the
    HBM win materializes exactly when the f32 value is unused.
    Returns the number of collapsed re-casts."""
    block = program.global_block()
    # ONE ordered pass doing rewrite + drop together, so both the drop
    # decision and every consumer rewrite see only definitions that are
    # current at that position (non-SSA safe), and chained collapses
    # resolve transitively at record time.
    castback_src = {}   # f32 name -> half name (current definitions only)
    active = {}         # dropped re-cast output -> surviving half name
    kept = []
    dropped = 0
    for op in block.ops:
        # consumers first: rewrite inputs with the renames active HERE
        for slot, names in op.inputs.items():
            op.inputs[slot] = [active.get(n, n) for n in names]
        if (op.type == "cast" and op.attrs.get("out_dtype") == dtype
                and op.inputs["X"][0] in castback_src):
            src = castback_src[op.inputs["X"][0]]
            out_n = op.outputs["Out"][0]
            # chase chains: src may itself be a dropped re-cast's name
            active[out_n] = active.get(src, src)
            # the drop still REDEFINES out_n: stale cast-back entries
            # keyed by or valued at out_n must not survive it
            castback_src.pop(out_n, None)
            for f32n in [f for f, h in castback_src.items() if h == out_n]:
                castback_src.pop(f32n, None)
            dropped += 1
            continue  # op dropped
        is_castback = (op.type == "cast"
                       and op.attrs.get("out_dtype") == "float32"
                       and op.attrs.get("in_dtype") == dtype)
        for n in op.output_arg_names():
            # any redefinition supersedes earlier renames/cast-backs of n
            active.pop(n, None)
            castback_src.pop(n, None)
            for f32n in [f for f, h in castback_src.items() if h == n]:
                castback_src.pop(f32n, None)
        if is_castback:
            castback_src[op.outputs["Out"][0]] = op.inputs["X"][0]
        kept.append(op)
    if not dropped:
        return 0
    block.ops = kept
    program._bump_version()
    return dropped


def rewrite_fp16(program=None, ops=_BF16_OPS):
    """float16 inference rewrite (paddle/contrib/float16 transpiler
    parity): same cast insertion with IEEE fp16.  Prefer bf16 for
    training on TPU (fp16's 5-bit exponent underflows grads)."""
    return rewrite_bf16(program, ops, dtype="float16")
