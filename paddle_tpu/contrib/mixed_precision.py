"""bfloat16 automatic mixed precision (contrib/float16 transpiler role,
re-targeted at the TPU's native compute dtype).

The reference's fp16 transpiler rewrites an inference program for half
kernels; on TPU the MXU natively multiplies bf16 at full rate, so AMP is
a training-time rewrite: cast the inputs of every matmul-class op
(mul/matmul/conv2d/depthwise_conv2d) to bfloat16 and the result back to
float32.  Master weights, accumulations, reductions, softmax and the
optimizer all stay float32 — the standard bf16 recipe; no loss scaling is
needed (bf16 has float32's exponent range).

    loss = ...
    rewrite_bf16(fluid.default_main_program())
    opt.minimize(loss)      # grads flow through the casts
"""

from .. import framework

_BF16_OPS = ("mul", "matmul", "conv2d", "depthwise_conv2d",
             "fused_attention",
             # the matmul-epilogue fused ops (fuse_passes): their pallas
             # kernels/dense paths consume the input dtype and accumulate
             # f32, so bf16 inputs run the MXU at full rate
             "fc", "fused_swiglu",
             # logits-free fused loss: bf16 X/W tiles, f32 online
             # logsumexp internals — the projection is the single
             # biggest matmul in the LM programs
             "fused_linear_xent")

# input slots that must stay float32 even when the op is rewritten
# (additive -1e9 padding masks lose nothing in bf16, but keeping them f32
# costs nothing and avoids surprises with user-supplied biases); int
# label slots must never see a float cast at all
_KEEP_F32_SLOTS = {"fused_attention": ("Bias",),
                   "fused_linear_xent": ("Label",)}

# dtype-transparent trunk ops: (data input slots, flippable output slots).
# When every data input of one of these is available in half precision,
# the op itself runs in half — its lowering preserves the input dtype
# (batch_norm/layer_norm compute statistics in f32 internally, nn_ops.py)
# — so the conv->bn->relu->residual-add->pool trunk of a convnet AND the
# mul->bias-add->reshape->transpose->dropout->layer_norm chains of a
# transformer block stay bf16 in HBM instead of bouncing through f32
# between every pair of matmul-class ops.  Parameter/state slots
# (Scale/Bias/Mean/Variance) and state outputs (MeanOut/Saved*/Mask's
# XShape) keep f32.
_TRANSPARENT_OPS = {
    "relu": (("X",), ("Out",)),
    "gelu": (("X",), ("Out",)),
    "pool2d": (("X",), ("Out",)),
    "batch_norm": (("X",), ("Y",)),
    "layer_norm": (("X",), ("Y",)),
    "dropout": (("X",), ("Out",)),
    "reshape2": (("X",), ("Out",)),
    "reshape": (("X",), ("Out",)),
    "transpose2": (("X",), ("Out",)),
    "transpose": (("X",), ("Out",)),
    "scale": (("X",), ("Out",)),
    "elementwise_add": (("X", "Y"), ("Out",)),
    # fused residual-add+LN: both streams half -> the op runs half
    # (stats stay f32 internally, like layer_norm); Scale/Bias params
    # and the Mean/Variance state outputs keep f32
    "fused_residual_ln": (("X", "Y"), ("Sum", "Y")),
}


def _tag_for(dtype):
    return "BF16" if dtype == "bfloat16" else "FP16"


def _emit_cast(block, new_ops, src_name, dst_dtype, out_name):
    """Shared cast-op emitter: create `out_name` in `dst_dtype` (shape
    mirrored from the source var), append the cast op to `new_ops`, and
    return the new name.  in_dtype derives from the source var's declared
    dtype (f32 default)."""
    src = block._find_var_recursive(src_name)
    out = block.create_var(
        name=out_name,
        shape=list(src.shape) if src is not None and src.shape else None,
        dtype=dst_dtype,
    )
    op = framework.Operator(
        block,
        "cast",
        None,
        None,
        {"in_dtype": str(src.dtype) if src is not None else "float32",
         "out_dtype": dst_dtype},
    )
    op.inputs = {"X": [src_name]}
    op.outputs = {"Out": [out.name]}
    new_ops.append(op)
    return out.name


def _emit_raw_and_castback(block, name, dtype, tag):
    """Create the half var `<name>@RAW_<tag>` plus the half->f32 cast op
    restoring `name`; returns (raw_name, cast_back_op).  The caller wires
    the producing op to write the raw var and appends the cast-back after
    it — the shared emission step of both AMP passes."""
    raw = name + "@RAW_" + tag
    v = block._find_var_recursive(name)
    block.create_var(
        name=raw,
        shape=list(v.shape) if v is not None and v.shape else None,
        dtype=dtype,
    )
    cast_back = framework.Operator(
        block,
        "cast",
        None,
        None,
        {"in_dtype": dtype, "out_dtype": "float32"},
    )
    cast_back.inputs = {"X": [raw]}
    cast_back.outputs = {"Out": [name]}
    return raw, cast_back


def rewrite_bf16(program=None, ops=_BF16_OPS, dtype="bfloat16"):
    """Insert half-precision casts around matmul-class ops (in place).
    Must run BEFORE optimizer.minimize so the grad ops differentiate
    through the casts.  Returns the count of rewritten ops.  dtype
    "bfloat16" is the TPU-native training regime; "float16" mirrors the
    reference's fp16 inference transpiler (paddle/contrib/float16)."""
    program = program or framework.default_main_program()
    tag = _tag_for(dtype)
    block = program.global_block()
    new_ops = []
    count = 0
    cast_cache = {}  # var name -> bf16 var name (reuse within the block)

    def cast_var(name, dst_dtype, tag):
        key = (name, dst_dtype)
        if key not in cast_cache:
            cast_cache[key] = _emit_cast(
                block, new_ops, name, dst_dtype, "%s@%s" % (name, tag))
        return cast_cache[key]

    for op in block.ops:
        if (
            op.type in ops
            and op.attrs.get("op_role", "forward") == "forward"
        ):
            count += 1
            keep_f32 = _KEEP_F32_SLOTS.get(op.type, ())
            for slot, names in list(op.inputs.items()):
                if slot in keep_f32:
                    continue
                op.inputs[slot] = [
                    cast_var(n, dtype, tag) for n in names
                ]
            new_ops.append(op)
            # cast outputs back to f32, keeping downstream names intact:
            # the op writes <out>@RAW_BF16 and a cast restores <out>
            for slot, names in list(op.outputs.items()):
                restored = []
                for n in names:
                    raw, cast_back = _emit_raw_and_castback(
                        block, n, dtype, tag)
                    restored.append((slot, raw, cast_back))
                op.outputs[slot] = [r[1] for r in restored]
                for _, _, cb in restored:
                    new_ops.append(cb)
                    # cast-back redefines the original name: a later bf16
                    # cast of it must re-derive from the new value
                    cast_cache.pop((cb.outputs["Out"][0], dtype), None)
        else:
            new_ops.append(op)
            # anything redefined later must not serve a stale cast
            for names in op.outputs.values():
                for n in names:
                    cast_cache.pop((n, dtype), None)
    block.ops = new_ops
    propagate_half_through_trunk(program, dtype)
    collapse_redundant_casts(program, dtype)
    program._bump_version()
    return count


def propagate_half_through_trunk(program, dtype="bfloat16"):
    """Flip dtype-transparent trunk ops (_TRANSPARENT_OPS) to half.

    An op whose every data input is the f32 cast-back of a half tensor is
    rewired to read the half tensor directly; its data output becomes a
    NEW half var, and a cast-back op re-defines the original f32 name so
    every other consumer (fetches, non-transparent ops, sub-blocks) is
    untouched.  Unused cast-backs are dropped by trace-time DCE, and the
    downstream f32->half re-casts collapse in collapse_redundant_casts —
    net effect: the conv/BN/relu/add/pool trunk runs half end-to-end.
    Returns the number of flipped ops."""
    tag = _tag_for(dtype)
    block = program.global_block()
    castback_src = {}  # f32 name -> half name, current definitions only
    new_ops = []
    flipped = 0
    bias_cast_cache = {}  # f32 bias name -> half name

    def half_bias(name):
        """f32->half cast for a BIAS-LIKE elementwise_add Y operand that
        is not itself half-sourced: standard AMP runs the bias add in
        half; bf16 keeps f32's exponent range so small biases round, not
        underflow.  Callers gate on the operand being a true broadcast
        bias — full-shape f32 activations keep their f32 contract.
        Cached per current definition."""
        if name not in bias_cast_cache:
            bias_cast_cache[name] = _emit_cast(
                block, new_ops, name, dtype, "%s@BIAS_%s" % (name, tag))
        return bias_cast_cache[name]

    def _is_broadcast_bias(xn, yn, axis=-1):
        """True when Y is a true bias operand broadcast onto X: lower
        rank (fluid-style axis-broadcast FC/conv bias, e.g. [D] or [C])
        NOT aligned to the batch dim, or same rank with at most ONE
        non-1 dim — which must not be the batch dim — and every dim
        either 1 or matching X (channel bias [1,C,1,1], feature bias
        [1,1,D]).  Per-sample/partially-broadcast f32 ACTIVATIONS — a
        [B,T,1] gate, [B,1,D] mask, [B,1,1] scalar, or axis=0 [B]
        operand — keep their f32 contract."""
        xv = block._find_var_recursive(xn)
        yv = block._find_var_recursive(yn)
        if xv is None or yv is None or xv.shape is None or yv.shape is None:
            return False
        xs, ys = tuple(xv.shape), tuple(yv.shape)
        if xs == ys:
            return False
        if len(ys) < len(xs):
            # elementwise axis semantics: y aligns to x starting at
            # `axis` (default: trailing).  A y whose first dim rides the
            # batch dim (axis==0 and not a broadcast-1) is per-sample
            # data, not a bias.
            eff_axis = axis if axis >= 0 else len(xs) - len(ys)
            return not (eff_axis == 0 and ys and ys[0] != 1)
        if len(ys) > len(xs):
            return False
        if any(yd not in (1, xd) for yd, xd in zip(ys, xs)):
            return False
        non1 = [i for i, yd in enumerate(ys) if yd != 1]
        return len(non1) <= 1 and 0 not in non1

    for op in block.ops:
        spec = _TRANSPARENT_OPS.get(op.type)
        halves = None
        if spec is not None:
            in_slots, out_slots = spec
            names = [n for s in in_slots for n in op.inputs.get(s, [])]
            if op.type == "elementwise_add":
                # X must be half-sourced; Y joins from castback_src when
                # it is too (residual adds), else only a strictly-smaller
                # broadcast operand (bias add) is cast to half in place —
                # a same-shape f32 activation keeps the add in f32
                xn = op.inputs.get("X", [None])[0]
                yn = op.inputs.get("Y", [None])[0]
                if xn in castback_src and yn is not None:
                    if yn in castback_src:
                        halves = {xn: castback_src[xn],
                                  yn: castback_src[yn]}
                    elif _is_broadcast_bias(
                            xn, yn, int(op.attrs.get("axis", -1))):
                        halves = {xn: castback_src[xn],
                                  yn: half_bias(yn)}
            elif names and all(n in castback_src for n in names):
                halves = {n: castback_src[n] for n in names}
        if halves is not None:
            for s in in_slots:
                if s in op.inputs:
                    op.inputs[s] = [halves.get(n, n) for n in op.inputs[s]]
            new_ops.append(op)
            flipped += 1
            for s in out_slots:
                for i, n in enumerate(list(op.outputs.get(s, []))):
                    raw, cb = _emit_raw_and_castback(block, n, dtype, tag)
                    op.outputs[s][i] = raw
                    new_ops.append(cb)
                    castback_src[n] = raw
            # non-flipped outputs (MeanOut/Saved*) redefine their names
            for s, ns in op.outputs.items():
                if s not in out_slots:
                    for n in ns:
                        castback_src.pop(n, None)
                        bias_cast_cache.pop(n, None)
            if op.type == "dropout":
                # the lowering emits Mask in X's dtype (nn_ops._dropout):
                # keep the declaration truthful for fetches/saves
                for n in op.outputs.get("Mask", []):
                    mv = block._find_var_recursive(n)
                    if mv is not None:
                        mv.dtype = dtype
            continue
        is_castback = (op.type == "cast"
                       and op.attrs.get("out_dtype") == "float32"
                       and op.attrs.get("in_dtype") == dtype)
        for n in op.output_arg_names():
            castback_src.pop(n, None)
            bias_cast_cache.pop(n, None)
        if is_castback:
            castback_src[op.outputs["Out"][0]] = op.inputs["X"][0]
        new_ops.append(op)
    if flipped:
        block.ops = new_ops
        program._bump_version()
    return flipped


def collapse_redundant_casts(program, dtype="bfloat16"):
    """Peephole: when a half->f32 cast-back feeds an f32->half re-cast,
    the re-cast collapses — its consumers read the original half tensor
    directly.  Numerically identical (half->f32->half is exact), but
    consecutive matmul-class ops stop bouncing activations through f32 in
    HBM (matmul->matmul chains in transformer blocks).

    The cast-back itself is KEPT: it still defines the original f32 name,
    which may be a fetch target or a sub-block read the global-block
    consumer scan cannot see.  When nothing ends up using it, trace-time
    DCE drops it per fetch set — so the collapse is always safe and the
    HBM win materializes exactly when the f32 value is unused.
    Returns the number of collapsed re-casts."""
    block = program.global_block()
    # ONE ordered pass doing rewrite + drop together, so both the drop
    # decision and every consumer rewrite see only definitions that are
    # current at that position (non-SSA safe), and chained collapses
    # resolve transitively at record time.
    castback_src = {}   # f32 name -> half name (current definitions only)
    active = {}         # dropped re-cast output -> surviving half name
    kept = []
    dropped = 0
    for op in block.ops:
        # consumers first: rewrite inputs with the renames active HERE
        for slot, names in op.inputs.items():
            op.inputs[slot] = [active.get(n, n) for n in names]
        if (op.type == "cast" and op.attrs.get("out_dtype") == dtype
                and op.inputs["X"][0] in castback_src):
            src = castback_src[op.inputs["X"][0]]
            out_n = op.outputs["Out"][0]
            # chase chains: src may itself be a dropped re-cast's name
            active[out_n] = active.get(src, src)
            # the drop still REDEFINES out_n: stale cast-back entries
            # keyed by or valued at out_n must not survive it
            castback_src.pop(out_n, None)
            for f32n in [f for f, h in castback_src.items() if h == out_n]:
                castback_src.pop(f32n, None)
            dropped += 1
            continue  # op dropped
        is_castback = (op.type == "cast"
                       and op.attrs.get("out_dtype") == "float32"
                       and op.attrs.get("in_dtype") == dtype)
        for n in op.output_arg_names():
            # any redefinition supersedes earlier renames/cast-backs of n
            active.pop(n, None)
            castback_src.pop(n, None)
            for f32n in [f for f, h in castback_src.items() if h == n]:
                castback_src.pop(f32n, None)
        if is_castback:
            castback_src[op.outputs["Out"][0]] = op.inputs["X"][0]
        kept.append(op)
    if not dropped:
        return 0
    block.ops = kept
    program._bump_version()
    return dropped


def rewrite_fp16(program=None, ops=_BF16_OPS):
    """float16 inference rewrite (paddle/contrib/float16 transpiler
    parity): same cast insertion with IEEE fp16.  Prefer bf16 for
    training on TPU (fp16's 5-bit exponent underflows grads)."""
    return rewrite_bf16(program, ops, dtype="float16")
