"""Core IR: Program / Block / Operator / Variable.

TPU-native equivalent of the reference's program representation
(``paddle/fluid/framework/framework.proto`` and
``python/paddle/fluid/framework.py``): a ``Program`` is a list of ``Block``s,
each holding ``Variable``s and a sequence of ``Operator``s (type + named
input/output var lists + attrs).  Unlike the reference — where the program is
interpreted op-by-op by a C++ Executor — here the program is a *compile
artifact*: the executor traces a block's ops through their JAX lowering rules
into one XLA computation per (program, shapes) and runs that on TPU.

Serialization is JSON (stable, dependency-free) rather than protobuf; the
schema mirrors ProgramDesc/BlockDesc/OpDesc/VarDesc fields.
"""

import collections
import contextlib
import copy
import json

import numpy as np

from . import unique_name

__all__ = [
    "Program",
    "Block",
    "Operator",
    "Variable",
    "Parameter",
    "default_main_program",
    "default_startup_program",
    "program_guard",
    "name_scope",
    "grad_var_name",
    "cpu_places",
    "tpu_places",
]

GRAD_VAR_SUFFIX = "@GRAD"
ZERO_VAR_SUFFIX = "@ZERO"


def grad_var_name(var_name):
    return var_name + GRAD_VAR_SUFFIX


class VarType:
    """Mirror of the reference VarType enum (framework.proto:105)."""

    LOD_TENSOR = "lod_tensor"
    SELECTED_ROWS = "selected_rows"
    LOD_TENSOR_ARRAY = "lod_tensor_array"
    STEP_SCOPES = "step_scopes"
    READER = "reader"
    RAW = "raw"


def _to_dtype_str(dtype):
    if dtype is None:
        return None
    if isinstance(dtype, str):
        # normalize
        return np.dtype(dtype).name if dtype not in ("bfloat16",) else "bfloat16"
    try:
        import jax.numpy as jnp

        if dtype == jnp.bfloat16:
            return "bfloat16"
    except Exception:
        pass
    return np.dtype(dtype).name


class Variable:
    """A named tensor slot in a Block (VarDesc analog, framework.py:204)."""

    def __init__(
        self,
        block,
        name=None,
        shape=None,
        dtype=None,
        lod_level=0,
        persistable=False,
        stop_gradient=False,
        type=VarType.LOD_TENSOR,
        is_data=False,
        **kwargs,
    ):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = _to_dtype_str(dtype) if dtype is not None else "float32"
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.type = type
        self.is_data = is_data
        # op that produced this var (filled by append_op)
        self.op = None

    def __str__(self):
        return "Variable(name=%s, shape=%s, dtype=%s)" % (
            self.name,
            self.shape,
            self.dtype,
        )

    __repr__ = __str__

    # ---- numpy-ish conveniences (math_op_patch analog) -----------------
    def _binary(self, other, op, reverse=False):
        from .layers import math_op_patch

        return math_op_patch.binary(self, other, op, reverse)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._binary(other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")

    def __rtruediv__(self, other):
        return self._binary(other, "elementwise_div", reverse=True)

    def __pow__(self, other):
        return self._binary(other, "elementwise_pow")

    def __rpow__(self, other):
        return self._binary(other, "elementwise_pow", reverse=True)

    def __neg__(self):
        from .layers import math_op_patch

        return math_op_patch.scale(self, -1.0)

    def __lt__(self, other):
        return self._binary(other, "less_than")

    def __le__(self, other):
        return self._binary(other, "less_equal")

    def __gt__(self, other):
        return self._binary(other, "greater_than")

    def __ge__(self, other):
        return self._binary(other, "greater_equal")

    def astype(self, dtype):
        from .layers import tensor as tensor_layers

        return tensor_layers.cast(self, dtype)

    def to_dict(self):
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "lod_level": self.lod_level,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "type": self.type,
            "is_data": self.is_data,
        }


class Parameter(Variable):
    """A persistable, trainable Variable (framework.py:1977 analog)."""

    def __init__(self, block, shape, dtype, **kwargs):
        kwargs["persistable"] = True
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)
        self.trainable = kwargs.get("trainable", True)
        self.optimize_attr = kwargs.get("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.get("regularizer", None)
        self.gradient_clip_attr = kwargs.get("gradient_clip_attr", None)
        self.do_model_average = kwargs.get("do_model_average", None)

    def to_dict(self):
        d = super().to_dict()
        d["is_parameter"] = True
        d["trainable"] = self.trainable
        d["optimize_attr"] = _serializable_optimize_attr(self.optimize_attr)
        return d


class Operator:
    """OpDesc analog: type + named input/output variable-name lists + attrs."""

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        # slot -> [var names]
        self.inputs = {}
        self.outputs = {}
        if inputs:
            for slot, vars_ in inputs.items():
                self.inputs[slot] = [
                    v.name if isinstance(v, Variable) else v for v in _as_list(vars_)
                ]
        if outputs:
            for slot, vars_ in outputs.items():
                self.outputs[slot] = [
                    v.name if isinstance(v, Variable) else v for v in _as_list(vars_)
                ]
        self.attrs = dict(attrs) if attrs else {}
        # OpRole tagging (op_proto_maker.h:26-38 analog): the transpilers
        # (distribute/memory/inference) key off these to classify ops.
        if "op_role" not in self.attrs and block is not None:
            prog = block.program
            self.attrs["op_role"] = getattr(prog, "op_role", "forward")
            rv = getattr(prog, "_op_role_var", None)
            if rv:
                self.attrs["op_role_var"] = list(rv)

    def input_arg_names(self):
        return [n for names in self.inputs.values() for n in names if n]

    def output_arg_names(self):
        return [n for names in self.outputs.values() for n in names if n]

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    def has_attr(self, name):
        return name in self.attrs

    def attr(self, name):
        return self.attrs[name]

    def _set_attr(self, name, val):
        self.attrs[name] = val

    def __str__(self):
        return "Op(type=%s, inputs=%s, outputs=%s)" % (
            self.type,
            self.inputs,
            self.outputs,
        )

    __repr__ = __str__

    def to_dict(self):
        attrs = {}
        for k, v in self.attrs.items():
            if isinstance(v, np.ndarray):
                attrs[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
            elif isinstance(v, (np.integer,)):
                attrs[k] = int(v)
            elif isinstance(v, (np.floating,)):
                attrs[k] = float(v)
            else:
                attrs[k] = v
        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": attrs,
        }


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _serializable_optimize_attr(attr):
    """optimize_attr may hold a Variable (append_LARS writes a per-param
    LR var): serialize it as a {"__var__": name} marker so to_json and
    the binary desc codec stay closed over JSON-able values."""
    if not attr:
        return attr
    return {
        k: {"__var__": v.name} if isinstance(v, Variable) else v
        for k, v in attr.items()
    }


def _resolve_optimize_attr(attr, block):
    """Inverse of _serializable_optimize_attr: markers resolve back to
    the block's Variable once all vars exist (or stay markers when the
    referenced var was pruned away)."""
    if not attr:
        return attr
    out = {}
    for k, v in attr.items():
        if isinstance(v, dict) and set(v) == {"__var__"}:
            resolved = block._find_var_recursive(v["__var__"])
            out[k] = resolved if resolved is not None else v
        else:
            out[k] = v
    return out


class Block:
    """BlockDesc analog: ordered ops + var table, with parent for control flow."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = collections.OrderedDict()  # name -> Variable
        self.ops = []
        # sub-block attr support for while/cond
        self.forward_block_idx = -1

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    # ---- var management -------------------------------------------------
    def create_var(self, **kwargs):
        name = kwargs.get("name", None)
        if name is not None and name in self.vars:
            return self.vars[name]
        var = Variable(self, **kwargs)
        self.vars[var.name] = var
        return var

    def create_parameter(self, **kwargs):
        param = Parameter(self, kwargs.pop("shape"), kwargs.pop("dtype"), **kwargs)
        # parameters always live in the global (root) block
        gb = self.program.global_block()
        gb.vars[param.name] = param
        param.block = gb
        return param

    def var(self, name):
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError("Variable %s not found in block %d" % (name, self.idx))
        return v

    def has_var(self, name):
        return self._find_var_recursive(name) is not None

    def has_var_local(self, name):
        return name in self.vars

    def _find_var_recursive(self, name):
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # ---- op management --------------------------------------------------
    def _normalize_output_dtypes(self, op):
        """Op construction is where dtype drift enters the IR: a layer
        that creates its output Variable with a raw numpy dtype (or
        mutates ``var.dtype`` after the fact) would serialize
        ``to_dict`` values like ``dtype('float32')`` — desc_codec
        round-trips then stop being byte-stable.  Normalizing at
        append/insert time keeps every op-attached var canonical."""
        for names in op.outputs.values():
            for n in names:
                v = self._find_var_recursive(n) if n else None
                if v is None:
                    continue
                dt = v.dtype
                if dt is not None and not isinstance(dt, str):
                    try:
                        v.dtype = _to_dtype_str(dt)
                    except Exception:
                        pass  # unresolvable: the verifier flags the drift

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        if outputs:
            for vars_ in outputs.values():
                for v in _as_list(vars_):
                    if isinstance(v, Variable):
                        v.op = op
        self._normalize_output_dtypes(op)
        self.program._bump_version()
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self._normalize_output_dtypes(op)
        self.program._bump_version()
        return op

    def insert_op(self, index, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self._normalize_output_dtypes(op)
        self.program._bump_version()
        return op

    def remove_op(self, index):
        del self.ops[index]
        self.program._bump_version()

    def to_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }


class Program:
    """ProgramDesc analog (framework.py:1404).

    Where the reference interprets this op-by-op (executor.cc:380), the TPU
    executor compiles each (block, input-signature) once via JAX tracing and
    caches the XLA executable.
    """

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self._seed = 0
        self._version = 0
        self._is_test = False
        self.op_role = "forward"
        self._op_role_var = []
        self._appending_grad_times = 0

    @contextlib.contextmanager
    def _op_role_guard(self, role, role_var=None):
        """Tag ops appended inside with an OpRole (and optional
        op_role_var [param, grad] pair) — the op_proto_maker OpRole
        mechanism the reference's transpilers are driven by."""
        prev_role, prev_var = self.op_role, self._op_role_var
        self.op_role = role
        self._op_role_var = list(role_var or [])
        try:
            yield
        finally:
            self.op_role, self._op_role_var = prev_role, prev_var

    def _optimized_guard(self, param_and_grad):
        names = [
            p.name if isinstance(p, Variable) else p
            for p in param_and_grad
            if p is not None
        ]
        return self._op_role_guard("optimize", names)

    # version is used as the executor's compile-cache key component
    def _bump_version(self):
        self._version += 1

    @property
    def random_seed(self):
        return self._seed

    @random_seed.setter
    def random_seed(self, seed):
        self._seed = int(seed)

    def global_block(self):
        return self.blocks[0]

    def block(self, idx):
        return self.blocks[idx]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def create_block(self, parent_idx=None):
        new_idx = len(self.blocks)
        parent = self.current_block_idx if parent_idx is None else parent_idx
        self.blocks.append(Block(self, new_idx, parent))
        self.current_block_idx = new_idx
        self._bump_version()
        return self.current_block()

    def rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    @property
    def num_blocks(self):
        return len(self.blocks)

    def list_vars(self):
        for b in self.blocks:
            for v in b.vars.values():
                yield v

    def all_parameters(self):
        return self.global_block().all_parameters()

    # ---- cloning / pruning ---------------------------------------------
    def clone(self, for_test=False):
        p = copy.deepcopy(self)
        if for_test:
            p._is_test = True
            for b in p.blocks:
                for op in b.ops:
                    if "is_test" in op.attrs:
                        op.attrs["is_test"] = True
                    if op.type == "dropout":
                        op.attrs["is_test"] = True
                    if op.type == "batch_norm":
                        op.attrs["is_test"] = True
        p._bump_version()
        return p

    def _prune(self, targets):
        """Backward-slice the program to the ops needed for `targets`
        (prune.cc analog).  Returns a new Program containing only block 0
        ancestors of the target vars."""
        target_names = set(
            t.name if isinstance(t, Variable) else t for t in _as_list(targets)
        )
        keep = backward_slice_keep(self, target_names)
        p = self.clone()
        pb = p.global_block()
        pb.ops = [op for i, op in enumerate(pb.ops) if keep[i]]
        p._bump_version()
        return p

    # ---- serialization --------------------------------------------------
    def to_json(self):
        return json.dumps(
            {
                "version": 1,
                "random_seed": self._seed,
                "blocks": [b.to_dict() for b in self.blocks],
            }
        )

    @staticmethod
    def from_json(text):
        data = json.loads(text)
        prog = Program()
        prog._seed = data.get("random_seed", 0)
        prog.blocks = []
        for bidx, bd in enumerate(data["blocks"]):
            blk = Block(prog, bd["idx"], bd.get("parent_idx", -1))
            prog.blocks.append(blk)
            for vd in bd["vars"]:
                is_param = vd.pop("is_parameter", False)
                trainable = vd.pop("trainable", True)
                optimize_attr = vd.pop("optimize_attr", None)
                name = vd.pop("name")
                shape = vd.pop("shape")
                if is_param:
                    p = Parameter(blk, shape, vd.pop("dtype"), name=name, **vd)
                    p.trainable = trainable
                    if optimize_attr is not None:
                        p.optimize_attr = optimize_attr
                    blk.vars[name] = p
                else:
                    blk.create_var(name=name, shape=shape, **vd)
            for v in blk.vars.values():
                if isinstance(v, Parameter):
                    v.optimize_attr = _resolve_optimize_attr(
                        v.optimize_attr, blk)
            for od in bd["ops"]:
                attrs = {}
                for k, v in od["attrs"].items():
                    if isinstance(v, dict) and "__ndarray__" in v:
                        attrs[k] = np.array(v["__ndarray__"], dtype=v["dtype"])
                    else:
                        attrs[k] = v
                op = Operator(blk, od["type"], None, None, attrs)
                op.inputs = {k: list(v) for k, v in od["inputs"].items()}
                op.outputs = {k: list(v) for k, v in od["outputs"].items()}
                blk.ops.append(op)
        prog.current_block_idx = 0
        return prog

    def __str__(self):
        lines = []
        for b in self.blocks:
            lines.append("-- block %d (parent %d) --" % (b.idx, b.parent_idx))
            for op in b.ops:
                lines.append("  " + str(op))
        return "\n".join(lines)


def backward_slice_keep(program, target_names):
    """Keep-mask of the global block's ancestor ops of `target_names`
    (prune.cc's reverse walk) — THE shared slicer behind
    ``Program._prune`` and the inference transpiler's fetch-cut.  An op
    owning sub-blocks (while / cond / recompute) counts its sub-blocks'
    external reads as inputs, so a kept control-flow op keeps its
    producers."""
    from .core.trace import op_sub_blocks, sub_block_external_reads

    block = program.global_block()
    needed = set(target_names)
    keep = [False] * len(block.ops)
    for i in range(len(block.ops) - 1, -1, -1):
        op = block.ops[i]
        if any(n in needed for n in op.output_arg_names()):
            keep[i] = True
            needed.update(op.input_arg_names())
            for sub_idx in op_sub_blocks(op):
                bound = op.attrs.get("__bound_names__", ())
                needed.update(sub_block_external_reads(
                    program, program.block(sub_idx), bound))
    return keep


# ---------------------------------------------------------------------------
# default program management
# ---------------------------------------------------------------------------
_main_program_ = Program()
_startup_program_ = Program()


def default_main_program():
    return _main_program_


def default_startup_program():
    return _startup_program_


def switch_main_program(program):
    global _main_program_
    prev = _main_program_
    _main_program_ = program
    return prev


def switch_startup_program(program):
    global _startup_program_
    prev = _startup_program_
    _startup_program_ = program
    return prev


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)


_name_scope_stack = []


@contextlib.contextmanager
def name_scope(prefix=None):
    _name_scope_stack.append(prefix or "")
    try:
        yield
    finally:
        _name_scope_stack.pop()


def cpu_places(device_count=None):
    from .places import CPUPlace

    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def tpu_places(device_ids=None):
    from .places import TPUPlace
    import jax

    if device_ids is None:
        device_ids = range(len(jax.devices()))
    return [TPUPlace(i) for i in device_ids]
