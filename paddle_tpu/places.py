"""Device places (place.h analog): CPUPlace / TPUPlace.

The reference dispatches kernels by Place (CPUPlace/CUDAPlace); here a Place
selects the JAX backend + default device for compiled blocks.  TPUPlace is
the CUDAPlace analog named by the north star (BASELINE.json).
"""

import functools


class Place:
    def __eq__(self, other):
        return type(self) is type(other) and getattr(self, "device_id", 0) == getattr(
            other, "device_id", 0
        )

    def __hash__(self):
        return hash((type(self).__name__, getattr(self, "device_id", 0)))


class CPUPlace(Place):
    def __repr__(self):
        return "CPUPlace"

    def jax_device(self):
        import jax

        cpus = [d for d in jax.devices() if d.platform == "cpu"]
        if cpus:
            return cpus[0]
        return jax.devices()[0]


class TPUPlace(Place):
    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return "TPUPlace(%d)" % self.device_id

    def jax_device(self):
        import jax

        tpus = [d for d in jax.devices() if d.platform in ("tpu", "axon")]
        if tpus:
            return tpus[self.device_id % len(tpus)]
        # graceful fallback (CI/CPU sim): use default backend devices
        devs = jax.devices()
        return devs[self.device_id % len(devs)]


# CUDAPlace alias for scripts written against the reference API surface
CUDAPlace = TPUPlace


class TPUPinnedPlace(Place):
    """Host-staging place (CUDAPinnedPlace analog) — host numpy buffers."""

    def __repr__(self):
        return "TPUPinnedPlace"

    def jax_device(self):
        import jax

        cpus = [d for d in jax.devices() if d.platform == "cpu"]
        return cpus[0] if cpus else jax.devices()[0]


@functools.lru_cache(maxsize=None)
def default_place():
    """TPU if attached, else CPU — mirrors fluid's use_cuda auto-detect."""
    import jax

    platforms = {d.platform for d in jax.devices()}
    return TPUPlace(0) if platforms & {"tpu", "axon"} else CPUPlace()


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    return True
