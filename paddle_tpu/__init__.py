"""paddle_tpu — a TPU-native deep-learning framework.

A from-scratch re-design of the PaddlePaddle Fluid capability surface
(reference: feitianyiren/Paddle) for TPU: programs are still built as
Program/Block/Op IR with fluid-style layers, optimizers and executors, but
execution is compile-first — blocks trace through JAX lowering rules into
single XLA executables, autodiff is vjp-derived, parallelism is
mesh+shardings (pjit/GSPMD) instead of NCCL op insertion, and hot kernels
are Pallas.

Typical use (same shape as fluid):

    import paddle_tpu as fluid
    x = fluid.layers.data("x", shape=[784])
    y = fluid.layers.data("y", shape=[1], dtype="int64")
    pred = fluid.layers.fc(x, size=10, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
    fluid.optimizer.SGD(0.01).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    exe.run(feed={...}, fetch_list=[loss])
"""

# memory-fraction knob must land in the environment BEFORE any jax backend
# init (see memory.apply_memory_fraction)
from .memory import apply_memory_fraction as _amf

_amf()

from . import ops  # registers all op lowerings first
from . import analysis  # static verifier + infer rules (ops registered them)
from . import (
    average,
    backward,
    clip,
    debugger,
    evaluator,
    net_drawer,
    flags,
    dataset,
    distributed,
    framework,
    inference,
    device_info,
    initializer,
    layers,
    memory,
    lod,
    metrics,
    nets,
    optimizer,
    parallel,
    param_attr,
    places,
    native,
    profiler,
    reader,
    recordio,
    regularizer,
    transpiler,
    unique_name,
)
from .transpiler import (
    DistributeTranspiler,
    DistributeTranspilerConfig,
    memory_optimize,
    release_memory,
    InferenceTranspiler,
)
from .executor import Executor, global_scope, scope_guard, as_numpy
from .framework import (
    Program,
    Block,
    Operator,
    Variable,
    Parameter,
    default_main_program,
    default_startup_program,
    program_guard,
    name_scope,
    cpu_places,
    tpu_places,
)
from .core.scope import Scope
from .lod import LoDTensor, create_lod_tensor
from .param_attr import ParamAttr, WeightNormParamAttr
from .places import (
    CPUPlace,
    CUDAPlace,
    TPUPlace,
    TPUPinnedPlace,
    default_place,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
)
from .data_feeder import DataFeeder
from .io import (
    save_vars,
    save_params,
    save_persistables,
    load_vars,
    load_params,
    load_persistables,
    save_inference_model,
    load_inference_model,
)
from .parallel_executor import ParallelExecutor, BuildStrategy, ExecutionStrategy
from . import serving

__version__ = "0.2.0"
