// GIL-free TCP transport for the typed-frame RPC protocol.
//
// Native counterpart of the reference's C++ variable-transport server
// (operators/distributed/grpc_server.h:46 AsyncGRPCServer + the legacy
// epoll LightNetwork.cpp): socket accept/read/frame-validation/HMAC and
// reply writes all run on C++ threads with no Python involvement; decoded
// request payloads flow to Python workers (the RequestHandler role) over
// a blocking queue via ctypes.  The wire format is exactly
// distributed/rpc.py's: [8B BE length][1B version][optional 32B
// HMAC-SHA256][typed payload].  Malformed frames (bad length/version/MAC)
// drop the connection in C++ — hostile bytes never reach Python.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

// --------------------------------------------------------------------------
// compact SHA-256 (public-domain style implementation) + HMAC
// --------------------------------------------------------------------------
struct Sha256 {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buflen = 0;

  Sha256() {
    static const uint32_t init[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                     0xa54ff53a, 0x510e527f, 0x9b05688c,
                                     0x1f83d9ab, 0x5be0cd19};
    memcpy(h, init, sizeof(h));
  }

  static uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

  void block(const uint8_t* p) {
    static const uint32_t k[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = (uint32_t(p[i * 4]) << 24) | (uint32_t(p[i * 4 + 1]) << 16) |
             (uint32_t(p[i * 4 + 2]) << 8) | uint32_t(p[i * 4 + 3]);
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + k[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* p, size_t n) {
    len += n;
    while (n) {
      size_t take = std::min(n, sizeof(buf) - buflen);
      memcpy(buf + buflen, p, take);
      buflen += take; p += take; n -= take;
      if (buflen == 64) { block(buf); buflen = 0; }
    }
  }

  void final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t z = 0;
    while (buflen != 56) update(&z, 1);
    uint8_t lb[8];
    for (int i = 0; i < 8; i++) lb[i] = uint8_t(bits >> (56 - 8 * i));
    update(lb, 8);
    for (int i = 0; i < 8; i++) {
      out[i * 4] = uint8_t(h[i] >> 24);
      out[i * 4 + 1] = uint8_t(h[i] >> 16);
      out[i * 4 + 2] = uint8_t(h[i] >> 8);
      out[i * 4 + 3] = uint8_t(h[i]);
    }
  }
};

void hmac_sha256(const std::string& key, const uint8_t* msg, size_t n,
                 uint8_t out[32]) {
  uint8_t k[64] = {0};
  if (key.size() > 64) {
    Sha256 kh;
    kh.update(reinterpret_cast<const uint8_t*>(key.data()), key.size());
    kh.final(k);
  } else {
    memcpy(k, key.data(), key.size());
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; i++) { ipad[i] = k[i] ^ 0x36; opad[i] = k[i] ^ 0x5c; }
  uint8_t inner[32];
  Sha256 hi;
  hi.update(ipad, 64); hi.update(msg, n); hi.final(inner);
  Sha256 ho;
  ho.update(opad, 64); ho.update(inner, 32); ho.final(out);
}

bool const_time_eq(const uint8_t* a, const uint8_t* b, size_t n) {
  uint8_t d = 0;
  for (size_t i = 0; i < n; i++) d |= a[i] ^ b[i];
  return d == 0;
}

// --------------------------------------------------------------------------
// server
// --------------------------------------------------------------------------
constexpr uint8_t kProtoVersion = 1;
constexpr uint64_t kMaxFrame = 1ull << 33;

struct Request {
  uint64_t conn_id;
  std::string body;  // payload with version+mac stripped
};

struct Conn {
  int fd;
  std::mutex write_mu;
};

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::string hmac_key;
  std::atomic<bool> closing{false};
  std::thread accept_thread;
  // readers detach themselves (no per-connection thread handle kept, so
  // reconnect churn cannot grow memory); fs_close waits on this count
  std::atomic<int> active_readers{0};
  std::mutex reap_mu;
  std::condition_variable reap_cv;
  std::mutex conns_mu;
  std::map<uint64_t, std::shared_ptr<Conn>> conns;
  std::atomic<uint64_t> next_id{1};

  std::mutex q_mu;
  std::condition_variable q_cv;
  std::deque<Request*> queue;

  bool read_exact(int fd, uint8_t* p, size_t n) {
    while (n) {
      ssize_t r = recv(fd, p, n, 0);
      if (r <= 0) return false;
      p += r; n -= size_t(r);
    }
    return true;
  }

  void reader_loop(uint64_t id, std::shared_ptr<Conn> c) {
    // active_readers was incremented by accept_loop BEFORE this thread
    // was spawned, so fs_close can never miss a just-accepted reader
    for (;;) {
      uint8_t lb[8];
      if (!read_exact(c->fd, lb, 8)) break;
      uint64_t n = 0;
      for (int i = 0; i < 8; i++) n = (n << 8) | lb[i];
      if (n < 1 || n > kMaxFrame) break;  // length bomb / nonsense
      std::string frame(n, '\0');
      if (!read_exact(c->fd, reinterpret_cast<uint8_t*>(&frame[0]), n)) break;
      if (uint8_t(frame[0]) != kProtoVersion) break;  // version mismatch
      const uint8_t* body = reinterpret_cast<const uint8_t*>(frame.data()) + 1;
      size_t blen = n - 1;
      if (!hmac_key.empty()) {
        if (blen < 32) break;
        uint8_t want[32];
        hmac_sha256(hmac_key, body + 32, blen - 32, want);
        if (!const_time_eq(body, want, 32)) break;  // forged MAC
        body += 32; blen -= 32;
      }
      auto* req = new Request{id, std::string(
          reinterpret_cast<const char*>(body), blen)};
      {
        std::lock_guard<std::mutex> lk(q_mu);
        queue.push_back(req);
      }
      q_cv.notify_one();
    }
    {
      std::lock_guard<std::mutex> lk(conns_mu);
      conns.erase(id);
    }
    {
      // fs_send may hold the Conn shared_ptr: mark it dead UNDER the
      // write lock before close so no reply is ever written to a closed
      // (possibly kernel-reused) fd
      std::lock_guard<std::mutex> lk(c->write_mu);
      close(c->fd);
      c->fd = -1;
    }
    {
      // decrement + notify under reap_mu: without the lock the wakeup
      // can land in fs_close's predicate-check window and be lost
      std::lock_guard<std::mutex> lk(reap_mu);
      active_readers--;
    }
    reap_cv.notify_all();
  }

  void accept_loop() {
    for (;;) {
      int fd = accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (closing) return;
        continue;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto c = std::make_shared<Conn>();
      c->fd = fd;
      uint64_t id = next_id++;
      {
        std::lock_guard<std::mutex> lk(conns_mu);
        conns[id] = c;
      }
      {
        std::lock_guard<std::mutex> lk(reap_mu);
        active_readers++;
      }
      std::thread([this, id, c] { reader_loop(id, c); }).detach();
    }
  }
};

}  // namespace

extern "C" {

void* fs_create(const char* host, int port, const char* hmac_key) {
  auto* s = new Server();
  if (hmac_key && hmac_key[0]) s->hmac_key = hmac_key;
  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) { delete s; return nullptr; }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  if (host && host[0]) {
    // hostname-capable resolution (inet_addr only parses dotted quads)
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host, nullptr, &hints, &res) != 0 || res == nullptr) {
      close(s->listen_fd);
      delete s;
      return nullptr;
    }
    addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    freeaddrinfo(res);
  } else {
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  }
  if (bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) ||
      listen(s->listen_fd, 128)) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  s->port = ntohs(addr.sin_port);
  s->accept_thread = std::thread([s] { s->accept_loop(); });
  return s;
}

int fs_port(void* h) { return static_cast<Server*>(h)->port; }

// Pop the next validated request; returns an opaque handle or NULL on
// timeout/shutdown.
void* fs_next(void* h, int timeout_ms) {
  auto* s = static_cast<Server*>(h);
  std::unique_lock<std::mutex> lk(s->q_mu);
  auto pred = [&] { return s->closing || !s->queue.empty(); };
  if (timeout_ms < 0) {
    s->q_cv.wait(lk, pred);
  } else if (!s->q_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                               pred)) {
    return nullptr;
  }
  if (s->queue.empty()) return nullptr;
  Request* r = s->queue.front();
  s->queue.pop_front();
  return r;
}

const char* fs_req_data(void* req, uint64_t* len) {
  auto* r = static_cast<Request*>(req);
  *len = r->body.size();
  return r->body.data();
}

uint64_t fs_req_conn(void* req) { return static_cast<Request*>(req)->conn_id; }

void fs_req_free(void* req) { delete static_cast<Request*>(req); }

// Frame (length+version+mac) and write a reply payload to a connection.
int fs_send(void* h, uint64_t conn_id, const char* data, uint64_t len) {
  auto* s = static_cast<Server*>(h);
  std::shared_ptr<Conn> c;
  {
    std::lock_guard<std::mutex> lk(s->conns_mu);
    auto it = s->conns.find(conn_id);
    if (it == s->conns.end()) return -1;
    c = it->second;
  }
  std::string mac;
  if (!s->hmac_key.empty()) {
    uint8_t m[32];
    hmac_sha256(s->hmac_key, reinterpret_cast<const uint8_t*>(data), len, m);
    mac.assign(reinterpret_cast<char*>(m), 32);
  }
  uint64_t n = 1 + mac.size() + len;
  std::string head(9 + mac.size(), '\0');
  for (int i = 0; i < 8; i++) head[i] = char(n >> (56 - 8 * i));
  head[8] = char(kProtoVersion);
  memcpy(&head[9], mac.data(), mac.size());
  std::lock_guard<std::mutex> lk(c->write_mu);
  if (c->fd < 0) return -1;  // reader closed it (peer gone)
  if (send(c->fd, head.data(), head.size(), MSG_NOSIGNAL) !=
      ssize_t(head.size()))
    return -1;
  uint64_t off = 0;
  while (off < len) {
    ssize_t w = send(c->fd, data + off, len - off, MSG_NOSIGNAL);
    if (w <= 0) return -1;
    off += uint64_t(w);
  }
  return 0;
}

void fs_close(void* h) {
  auto* s = static_cast<Server*>(h);
  s->closing = true;
  shutdown(s->listen_fd, SHUT_RDWR);
  close(s->listen_fd);
  {
    std::lock_guard<std::mutex> lk(s->conns_mu);
    for (auto& kv : s->conns) shutdown(kv.second->fd, SHUT_RDWR);
  }
  s->q_cv.notify_all();
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {
    std::unique_lock<std::mutex> lk(s->reap_mu);
    s->reap_cv.wait(lk, [&] { return s->active_readers.load() == 0; });
  }
  {
    std::lock_guard<std::mutex> lk(s->q_mu);
    for (auto* r : s->queue) delete r;
    s->queue.clear();
  }
  delete s;
}

}  // extern "C"
