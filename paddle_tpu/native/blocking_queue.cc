// Bounded MPMC blocking byte queue + threaded RecordIO prefetch loader.
//
// C++ re-design of the reference's reader runtime
// (operators/reader/lod_tensor_blocking_queue.h, buffered_reader.cc,
// open_files_op.cc): the Python->device feeding path keeps file IO,
// decompression and queueing OFF the Python GIL — worker threads scan
// RecordIO files and fill the queue; Python pops complete records.
// Exposed as a C ABI for ctypes.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

extern "C" {
// from recordio.cc
void* rio_scanner_open(const char* path);
const char* rio_scanner_next(void* h, uint32_t* len);
int rio_scanner_error(void* h);
void rio_scanner_close(void* h);
}

namespace {

struct Queue {
  size_t capacity;
  std::deque<std::string> items;
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  bool closed = false;

  explicit Queue(size_t cap) : capacity(cap ? cap : 1) {}

  bool push(const char* data, uint32_t len, int timeout_ms) {
    std::unique_lock<std::mutex> lk(mu);
    auto pred = [&] { return closed || items.size() < capacity; };
    if (timeout_ms < 0) {
      not_full.wait(lk, pred);
    } else if (!not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                  pred)) {
      return false;
    }
    if (closed) return false;
    items.emplace_back(data, len);
    not_empty.notify_one();
    return true;
  }

  // returns true + moves front into out; false on timeout or closed+empty
  bool pop(std::string* out, int timeout_ms) {
    std::unique_lock<std::mutex> lk(mu);
    auto pred = [&] { return closed || !items.empty(); };
    if (timeout_ms < 0) {
      not_empty.wait(lk, pred);
    } else if (!not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   pred)) {
      return false;
    }
    if (items.empty()) return false;  // closed + drained
    *out = std::move(items.front());
    items.pop_front();
    not_full.notify_one();
    return true;
  }

  // single-call copy-out: 0 = copied, 1 = dst too small (*len = needed,
  // item stays at the front — stateless probe, no cross-call latch),
  // -1 = timeout or closed+drained
  int pop_into(char* dst, uint32_t cap, uint32_t* len, int timeout_ms) {
    std::unique_lock<std::mutex> lk(mu);
    auto pred = [&] { return closed || !items.empty(); };
    if (timeout_ms < 0) {
      not_empty.wait(lk, pred);
    } else if (!not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   pred)) {
      *len = 0;
      return -1;
    }
    if (items.empty()) {
      *len = 0;
      return -1;
    }
    const std::string& front = items.front();
    *len = front.size();
    if (dst == nullptr || cap < front.size()) return 1;
    memcpy(dst, front.data(), front.size());
    items.pop_front();
    not_full.notify_one();
    return 0;
  }

  void close() {
    std::lock_guard<std::mutex> lk(mu);
    closed = true;
    not_full.notify_all();
    not_empty.notify_all();
  }
};

struct Loader {
  Queue queue;
  std::vector<std::string> files;
  std::vector<std::thread> workers;
  std::atomic<int> active{0};
  std::atomic<size_t> next_file{0};
  std::atomic<bool> stop{false};
  std::atomic<int> err{0};  // open failure or corruption in any file

  Loader(size_t cap) : queue(cap) {}

  void work() {
    for (;;) {
      size_t i = next_file.fetch_add(1);
      if (i >= files.size() || stop.load()) break;
      void* sc = rio_scanner_open(files[i].c_str());
      if (!sc) {
        err.store(1);
        continue;
      }
      uint32_t len;
      const char* rec;
      while (!stop.load() && (rec = rio_scanner_next(sc, &len)) != nullptr) {
        if (!queue.push(rec, len, -1)) break;  // queue closed
      }
      if (rio_scanner_error(sc)) err.store(1);
      rio_scanner_close(sc);
    }
    if (active.fetch_sub(1) == 1) queue.close();  // last worker out: EOF
  }
};

}  // namespace

extern "C" {

// ---- raw queue ---------------------------------------------------------
void* bq_create(uint32_t capacity) { return new Queue(capacity); }

int bq_push(void* h, const char* data, uint32_t len, int timeout_ms) {
  return static_cast<Queue*>(h)->push(data, len, timeout_ms) ? 0 : -1;
}

// pop with length probe: dst=null (or too small) returns 1 and sets *len;
// the item stays at the queue front, so callers loop until rc==0.
int bq_pop(void* h, char* dst, uint32_t cap, uint32_t* len, int timeout_ms) {
  return static_cast<Queue*>(h)->pop_into(dst, cap, len, timeout_ms);
}

uint32_t bq_size(void* h) {
  auto* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->items.size();
}

void bq_close(void* h) { static_cast<Queue*>(h)->close(); }

void bq_destroy(void* h) { delete static_cast<Queue*>(h); }

// ---- prefetch loader ---------------------------------------------------
void* rio_loader_open(const char** paths, uint32_t n_paths, uint32_t capacity,
                      uint32_t n_threads) {
  auto* l = new Loader(capacity);
  for (uint32_t i = 0; i < n_paths; ++i) l->files.emplace_back(paths[i]);
  if (n_threads == 0) n_threads = 1;
  if (n_threads > l->files.size()) n_threads = l->files.size();
  if (n_threads == 0) n_threads = 1;
  l->active.store(static_cast<int>(n_threads));
  for (uint32_t i = 0; i < n_threads; ++i)
    l->workers.emplace_back([l] { l->work(); });
  return l;
}

// copies the next record into dst: probe with dst=null for the length,
// then call with a buffer (record stays at the queue front until copied)
int rio_loader_next(void* h, char* dst, uint32_t cap, uint32_t* len) {
  return static_cast<Loader*>(h)->queue.pop_into(dst, cap, len, -1);
}

// 1 when any file failed to open or stopped on corruption
int rio_loader_error(void* h) {
  return static_cast<Loader*>(h)->err.load();
}

void rio_loader_close(void* h) {
  auto* l = static_cast<Loader*>(h);
  l->stop.store(true);
  l->queue.close();
  for (auto& t : l->workers) t.join();
  delete l;
}

}  // extern "C"
