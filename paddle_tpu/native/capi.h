/* C inference ABI (paddle_fluid C API analog) — see capi.cc. */
#ifndef PADDLE_TPU_NATIVE_CAPI_H_
#define PADDLE_TPU_NATIVE_CAPI_H_

#ifdef __cplusplus
extern "C" {
#endif

/* Start the embedded runtime (idempotent).  repo_root goes on sys.path.
 * After pd_shutdown the runtime CANNOT be restarted in this process. */
int pd_init(const char* repo_root);

/* Load a save_inference_model directory; NULL on error (pd_last_error). */
void* pd_create_predictor(const char* model_dir);

/* Run one float input through the predictor.  out_dims must hold >= 8
 * longs; returns 0 on success. */
int pd_predictor_run(void* handle, const char* input_name,
                     const float* data, int ndim, const long* dims,
                     float* out, long out_capacity, int* out_ndim,
                     long* out_dims);

void pd_destroy_predictor(void* handle);
void pd_shutdown();
const char* pd_last_error();

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_NATIVE_CAPI_H_ */
