// RecordIO: chunked record file format with CRC32 + zlib compression.
//
// C++ re-design of the reference's paddle/fluid/recordio/ (header.h:25
// Compressor enum, chunk.cc, writer.h:22, scanner.h:26) for the TPU
// framework's input pipeline: a file is a sequence of chunks
//
//   [magic u32][compressor u32][crc32 u32][compressed_len u32][num_records u32]
//   [compressed payload: num_records x (u32 len + bytes)]
//
// (snappy in the reference -> zlib here: always present, similar ratio at
// level 1 for tensor data).  Exposed as a C ABI consumed via ctypes; the
// Python fallback in paddle_tpu/recordio.py writes the identical format.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <zlib.h>

namespace {

constexpr uint32_t kMagic = 0x0A0B0C0Du;

enum Compressor : uint32_t { kNone = 0, kZlib = 1 };

struct Writer {
  FILE* f = nullptr;
  uint32_t compressor = kZlib;
  uint32_t max_records = 1000;
  size_t max_bytes = 4u << 20;
  std::string buf;          // raw concatenated records
  uint32_t num_records = 0;

  bool flush_chunk() {
    if (num_records == 0) return true;
    std::string payload;
    if (compressor == kZlib) {
      uLongf dst_len = compressBound(buf.size());
      payload.resize(dst_len);
      if (compress2(reinterpret_cast<Bytef*>(&payload[0]), &dst_len,
                    reinterpret_cast<const Bytef*>(buf.data()), buf.size(),
                    /*level=*/1) != Z_OK)
        return false;
      payload.resize(dst_len);
    } else {
      payload = buf;
    }
    uint32_t crc = crc32(0L, reinterpret_cast<const Bytef*>(payload.data()),
                         payload.size());
    uint32_t hdr[5] = {kMagic, compressor, crc,
                       static_cast<uint32_t>(payload.size()), num_records};
    if (fwrite(hdr, sizeof(hdr), 1, f) != 1) return false;
    if (!payload.empty() &&
        fwrite(payload.data(), payload.size(), 1, f) != 1)
      return false;
    buf.clear();
    num_records = 0;
    return true;
  }
};

struct Scanner {
  FILE* f = nullptr;
  std::string chunk;        // decompressed records of current chunk
  size_t pos = 0;           // cursor into chunk
  uint32_t remaining = 0;   // records left in current chunk
  std::string record;       // last returned record
  int err = 0;              // corruption seen (vs clean EOF)

  bool load_chunk() {
    uint32_t hdr[5];
    size_t got = fread(hdr, 1, sizeof(hdr), f);
    if (got == 0 && feof(f)) return false;  // clean EOF
    if (got < sizeof(hdr)) {
      err = 1;  // truncated header
      return false;
    }
    if (hdr[0] != kMagic) {
      err = 1;
      return false;
    }
    // sanity-cap the chunk length BEFORE allocating: a corrupted length
    // field (pre-CRC) must not drive a multi-GiB allocation whose
    // bad_alloc would escape the C ABI and abort the host process.
    // Writers cap chunks at ~4 MiB; 256 MiB is generously corrupt-proof.
    constexpr uint32_t kMaxChunkBytes = 256u << 20;
    if (hdr[3] > kMaxChunkBytes) {
      err = 1;
      return false;
    }
    std::string payload(hdr[3], '\0');
    if (hdr[3] > 0 && fread(&payload[0], hdr[3], 1, f) != 1) {
      err = 1;  // truncated chunk
      return false;
    }
    uint32_t crc = crc32(0L, reinterpret_cast<const Bytef*>(payload.data()),
                         payload.size());
    if (crc != hdr[2]) {
      err = 1;  // corrupted chunk
      return false;
    }
    if (hdr[1] == kZlib) {
      // records expand; grow until it fits
      uLongf dst_len = payload.size() * 4 + 1024;
      for (;;) {
        chunk.resize(dst_len);
        int rc = uncompress(reinterpret_cast<Bytef*>(&chunk[0]), &dst_len,
                            reinterpret_cast<const Bytef*>(payload.data()),
                            payload.size());
        if (rc == Z_OK) break;
        if (rc != Z_BUF_ERROR) {
          err = 1;
          return false;
        }
        dst_len *= 2;
      }
      chunk.resize(dst_len);
    } else {
      chunk = payload;
    }
    pos = 0;
    remaining = hdr[4];
    return true;
  }

  bool next() {
    while (remaining == 0) {
      if (!load_chunk()) return false;
    }
    if (pos + 4 > chunk.size()) {
      err = 1;
      return false;
    }
    uint32_t len;
    memcpy(&len, chunk.data() + pos, 4);
    pos += 4;
    if (pos + len > chunk.size()) {
      err = 1;
      return false;
    }
    record.assign(chunk.data() + pos, len);
    pos += len;
    --remaining;
    return true;
  }
};

}  // namespace

extern "C" {

void* rio_writer_open(const char* path, uint32_t compressor,
                      uint32_t max_records_per_chunk) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  w->compressor = compressor;
  if (max_records_per_chunk) w->max_records = max_records_per_chunk;
  return w;
}

int rio_writer_write(void* h, const char* data, uint32_t len) {
  auto* w = static_cast<Writer*>(h);
  uint32_t n = len;
  w->buf.append(reinterpret_cast<const char*>(&n), 4);
  w->buf.append(data, len);
  ++w->num_records;
  if (w->num_records >= w->max_records || w->buf.size() >= w->max_bytes)
    return w->flush_chunk() ? 0 : -1;
  return 0;
}

int rio_writer_close(void* h) {
  auto* w = static_cast<Writer*>(h);
  int rc = w->flush_chunk() ? 0 : -1;
  fclose(w->f);
  delete w;
  return rc;
}

void* rio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* s = new Scanner();
  s->f = f;
  return s;
}

// returns pointer to the record (valid until the next call) or null at EOF
const char* rio_scanner_next(void* h, uint32_t* len) {
  auto* s = static_cast<Scanner*>(h);
  if (!s->next()) {
    *len = 0;
    return nullptr;
  }
  *len = s->record.size();
  return s->record.data();
}

// 1 when the scanner stopped on corruption rather than clean EOF
int rio_scanner_error(void* h) { return static_cast<Scanner*>(h)->err; }

void rio_scanner_close(void* h) {
  auto* s = static_cast<Scanner*>(h);
  fclose(s->f);
  delete s;
}

}  // extern "C"
