// Native ProgramDesc codec (the C++ desc-core slot of SURVEY §2.1:
// program_desc.h/version.h/prune.cc roles, re-designed for the binary
// `__model__` format defined in desc.proto).
//
// What lives here (and NOT in Python): parsing + semantic validation of
// serialized programs (version gate, block tree integrity, name
// resolution of every op input/output through the block-parent chain,
// sub-block attr range checks) and lossless JSON <-> binary transcode so
// any tool can inspect a saved model without the Python runtime.
//
// C ABI (ctypes-consumed, see native/__init__.py):
//   pt_desc_max_version()                         -> newest readable version
//   pt_desc_validate(buf, len, err, errcap)       -> 0 ok / 1 error
//   pt_desc_summary(buf, len, long out[4])        -> 0 ok; out = {blocks,
//                                                    vars, ops, version}
//   pt_desc_to_json(buf, len, &out, err, errcap)  -> 0 ok; free w/ pt_desc_free
//   pt_desc_from_json(json, &out, &len, err, errcap)
//   pt_desc_free(ptr)

#include <cstdio>
#include <cstring>
#include <set>
#include <string>
#include <vector>

#include <google/protobuf/util/json_util.h>

#include "desc.pb.h"

namespace {

using paddle_tpu::desc::AttrValue;
using paddle_tpu::desc::BlockDesc;
using paddle_tpu::desc::OpDesc;
using paddle_tpu::desc::ProgramDesc;

// Newest __model__ format this build reads; mirrors
// io.PROGRAM_FORMAT_VERSION (a unit test asserts the two stay equal).
constexpr unsigned kMaxVersion = 1;

void put_err(char* err, int errcap, const std::string& msg) {
  if (err != nullptr && errcap > 0) {
    std::snprintf(err, errcap, "%s", msg.c_str());
  }
}

bool parse(const char* buf, long len, ProgramDesc* prog, char* err,
           int errcap) {
  if (buf == nullptr || len <= 0) {
    put_err(err, errcap, "empty buffer");
    return false;
  }
  if (!prog->ParseFromArray(buf, static_cast<int>(len))) {
    put_err(err, errcap, "not a valid ProgramDesc protobuf");
    return false;
  }
  return true;
}

// Resolve `name` in block `bidx`'s var table or any ancestor's
// (Scope-chain semantics: sub-block ops may use enclosing-block vars).
bool resolves(const ProgramDesc& prog,
              const std::vector<std::set<std::string>>& tables, int bidx,
              const std::string& name) {
  int guard = 0;
  while (bidx >= 0 && bidx < prog.blocks_size() && guard++ < 1024) {
    if (tables[bidx].count(name)) return true;
    bidx = prog.blocks(bidx).parent_idx();
  }
  return false;
}

// attr names whose integer payload references a sub-block index:
// "sub_block"/"block_idx" or a "*_block" suffix (true suffix match only —
// names like "num_blocks" must not be treated as references)
bool is_block_ref_attr(const std::string& key) {
  if (key == "sub_block" || key == "block_idx") return true;
  constexpr const char kSuffix[] = "_block";
  constexpr size_t kLen = sizeof(kSuffix) - 1;
  return key.size() >= kLen &&
         key.compare(key.size() - kLen, kLen, kSuffix) == 0;
}

bool validate(const ProgramDesc& prog, char* err, int errcap) {
  if (prog.format_version() > kMaxVersion) {
    put_err(err, errcap,
            "format_version " + std::to_string(prog.format_version()) +
                " is newer than this build reads (max " +
                std::to_string(kMaxVersion) + ")");
    return false;
  }
  if (prog.blocks_size() == 0) {
    put_err(err, errcap, "program has no blocks");
    return false;
  }
  const int nb = prog.blocks_size();
  std::vector<std::set<std::string>> tables(nb);
  for (int i = 0; i < nb; ++i) {
    const BlockDesc& b = prog.blocks(i);
    if (b.idx() != i) {
      put_err(err, errcap,
              "block " + std::to_string(i) + " carries idx " +
                  std::to_string(b.idx()) + " (blocks must be stored in "
                  "index order)");
      return false;
    }
    if (i == 0 && b.parent_idx() != -1) {
      put_err(err, errcap, "global block must have parent_idx -1");
      return false;
    }
    if (i > 0 && (b.parent_idx() < 0 || b.parent_idx() >= i)) {
      put_err(err, errcap,
              "block " + std::to_string(i) + " parent_idx " +
                  std::to_string(b.parent_idx()) +
                  " must name an earlier block");
      return false;
    }
    for (const auto& v : b.vars()) {
      if (v.name().empty()) {
        put_err(err, errcap,
                "block " + std::to_string(i) + " has an unnamed var");
        return false;
      }
      tables[i].insert(v.name());
    }
  }
  for (int i = 0; i < nb; ++i) {
    const BlockDesc& b = prog.blocks(i);
    for (int oi = 0; oi < b.ops_size(); ++oi) {
      const OpDesc& op = b.ops(oi);
      if (op.type().empty()) {
        put_err(err, errcap, "block " + std::to_string(i) + " op #" +
                                 std::to_string(oi) + " has empty type");
        return false;
      }
      for (const auto& dir : {op.inputs(), op.outputs()}) {
        for (const auto& slot : dir) {
          for (const auto& name : slot.second.v()) {
            if (name.empty()) continue;  // optional slot placeholder
            if (!resolves(prog, tables, i, name)) {
              put_err(err, errcap,
                      "op '" + op.type() + "' (block " + std::to_string(i) +
                          " #" + std::to_string(oi) + ") references var '" +
                          name + "' declared in no reachable block");
              return false;
            }
          }
        }
      }
      for (const auto& at : op.attrs()) {
        if (is_block_ref_attr(at.first) &&
            at.second.value_case() == AttrValue::kI) {
          long ref = static_cast<long>(at.second.i());
          if (ref < 0 || ref >= nb) {
            put_err(err, errcap,
                    "op '" + op.type() + "' attr '" + at.first +
                        "' references block " + std::to_string(ref) +
                        " of " + std::to_string(nb));
            return false;
          }
        }
      }
    }
  }
  return true;
}

char* dup_out(const std::string& s) {
  char* out = static_cast<char*>(std::malloc(s.size() + 1));
  if (out != nullptr) {
    std::memcpy(out, s.data(), s.size());
    out[s.size()] = '\0';
  }
  return out;
}

}  // namespace

extern "C" {

unsigned pt_desc_max_version() { return kMaxVersion; }

int pt_desc_validate(const char* buf, long len, char* err, int errcap) {
  ProgramDesc prog;
  if (!parse(buf, len, &prog, err, errcap)) return 1;
  return validate(prog, err, errcap) ? 0 : 1;
}

int pt_desc_summary(const char* buf, long len, long* out /* [4] */) {
  ProgramDesc prog;
  if (out == nullptr || !parse(buf, len, &prog, nullptr, 0)) return 1;
  long vars = 0, ops = 0;
  for (const auto& b : prog.blocks()) {
    vars += b.vars_size();
    ops += b.ops_size();
  }
  out[0] = prog.blocks_size();
  out[1] = vars;
  out[2] = ops;
  out[3] = prog.format_version();
  return 0;
}

int pt_desc_to_json(const char* buf, long len, char** out, char* err,
                    int errcap) {
  ProgramDesc prog;
  if (out == nullptr) return 1;
  if (!parse(buf, len, &prog, err, errcap)) return 1;
  std::string json;
  google::protobuf::util::JsonPrintOptions opts;
  opts.add_whitespace = false;
  opts.always_print_primitive_fields = false;
  auto st = google::protobuf::util::MessageToJsonString(prog, &json, opts);
  if (!st.ok()) {
    put_err(err, errcap, std::string("json encode: ") +
                             std::string(st.message()));
    return 1;
  }
  *out = dup_out(json);
  return *out == nullptr;
}

int pt_desc_from_json(const char* json, char** out, long* out_len, char* err,
                      int errcap) {
  if (json == nullptr || out == nullptr || out_len == nullptr) return 1;
  ProgramDesc prog;
  auto st = google::protobuf::util::JsonStringToMessage(json, &prog);
  if (!st.ok()) {
    put_err(err, errcap, std::string("json parse: ") +
                             std::string(st.message()));
    return 1;
  }
  if (!validate(prog, err, errcap)) return 1;
  std::string bin;
  if (!prog.SerializeToString(&bin)) {
    put_err(err, errcap, "serialize failed");
    return 1;
  }
  *out = static_cast<char*>(std::malloc(bin.size() ? bin.size() : 1));
  if (*out == nullptr) return 1;
  std::memcpy(*out, bin.data(), bin.size());
  *out_len = static_cast<long>(bin.size());
  return 0;
}

void pt_desc_free(char* ptr) { std::free(ptr); }

}  // extern "C"
