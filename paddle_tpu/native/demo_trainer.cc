// C++-only training demo (paddle/fluid/train/demo_trainer.cc analog):
// load an exported training program and drive the train loop from C++
// with no Python script — the framework is embedded via the CPython API
// (the TPU-native equivalent of linking libpaddle_fluid into a C++ app;
// the XLA/PJRT compute path is reached through the embedded runtime).
//
// Usage: demo_trainer <exported_program_dir> [steps] [batch]
// The directory comes from paddle_tpu.native.demo_driver.export_train_program.

#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <string>

namespace {

// Fail hard with the Python traceback — the enforce.h role.
void check(bool ok, const char* what) {
  if (ok) return;
  if (PyErr_Occurred()) PyErr_Print();
  std::fprintf(stderr, "demo_trainer: %s failed\n", what);
  Py_Finalize();
  std::exit(1);
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <program_dir> [steps] [batch]\n", argv[0]);
    return 2;
  }
  const std::string dir = argv[1];
  const long steps = argc > 2 ? std::strtol(argv[2], nullptr, 10) : 8;
  const long batch = argc > 3 ? std::strtol(argv[3], nullptr, 10) : 16;

  Py_Initialize();

  // repo root (this binary lives in paddle_tpu/native/) onto sys.path
  PyObject* sys_path = PySys_GetObject("path");
  const char* repo = std::getenv("PADDLE_TPU_ROOT");
  if (repo != nullptr) {
    PyObject* p = PyUnicode_FromString(repo);
    PyList_Insert(sys_path, 0, p);
    Py_DECREF(p);
  }

  PyObject* mod = PyImport_ImportModule("paddle_tpu.native.demo_driver");
  check(mod != nullptr, "import paddle_tpu.native.demo_driver");

  PyObject* cls = PyObject_GetAttrString(mod, "DemoTrainer");
  check(cls != nullptr, "DemoTrainer lookup");

  PyObject* trainer = PyObject_CallFunction(cls, "sl", dir.c_str(), batch);
  check(trainer != nullptr, "DemoTrainer(dir, batch)");

  // the train loop lives HERE, in C++ — one step() call per iteration
  double first = 0.0, last = 0.0;
  for (long i = 0; i < steps; ++i) {
    PyObject* loss = PyObject_CallMethod(trainer, "step", nullptr);
    check(loss != nullptr, "step()");
    last = PyFloat_AsDouble(loss);
    Py_DECREF(loss);
    if (i == 0) first = last;
    std::printf("step %ld loss %.6f\n", i, last);
  }
  std::printf("demo_trainer done: first=%.6f last=%.6f improved=%s\n", first,
              last, last < first ? "true" : "false");

  Py_DECREF(trainer);
  Py_DECREF(cls);
  Py_DECREF(mod);
  Py_Finalize();
  return last < first ? 0 : 3;
}
