// C inference ABI (paddle_fluid C API / capi.h analog): opaque predictor
// handles over the AnalysisConfig predictor, consumable from any language
// with a C FFI.  The runtime underneath is the embedded CPython + XLA
// stack (the reference links libpaddle_fluid; here the framework IS the
// embedded runtime — same deployment shape, TPU-native execution).
//
// Surface (see capi.h):
//   pd_init(repo_root)                     — start the runtime (once)
//   pd_create_predictor(model_dir)        -> handle (NULL on error)
//   pd_predictor_run(handle, name, data, ndim, dims, out, out_cap,
//                    out_ndim, out_dims)  -> 0 on success
//   pd_destroy_predictor(handle)
//   pd_shutdown()
//   pd_last_error()                       -> static error string

#include <Python.h>

#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>

namespace {

std::mutex g_err_mu;
std::string g_error;
bool g_inited = false;
bool g_finalized = false;
PyThreadState* g_main_tstate = nullptr;

// RAII GIL guard: every entry point may be called from any host thread
class GilGuard {
 public:
  GilGuard() : state_(PyGILState_Ensure()) {}
  ~GilGuard() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

void set_error(const std::string& msg) {
  std::lock_guard<std::mutex> lk(g_err_mu);
  g_error = msg;
}

void set_error_from_python(const char* what) {
  std::string msg = what;
  if (PyErr_Occurred()) {
    PyObject *type, *value, *tb;
    PyErr_Fetch(&type, &value, &tb);
    if (value != nullptr) {
      PyObject* s = PyObject_Str(value);
      if (s != nullptr) {
        msg += ": ";
        msg += PyUnicode_AsUTF8(s);
        Py_DECREF(s);
      }
    }
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
  }
  set_error(msg);
}

// entry-point precondition: the runtime must be alive (calling
// PyGILState_Ensure on a finalized/uninitialized interpreter aborts)
bool runtime_alive(const char* who) {
  if (g_inited) return true;
  set_error(std::string(who) +
            ": runtime not initialized (call pd_init; after pd_shutdown "
            "the runtime cannot be used)");
  return false;
}

}  // namespace

extern "C" {

const char* pd_last_error() {
  // copy under the lock into a thread-local buffer: g_error may be
  // rewritten concurrently by another thread's failing call
  thread_local static char buf[1024];
  std::lock_guard<std::mutex> lk(g_err_mu);
  std::snprintf(buf, sizeof(buf), "%s", g_error.c_str());
  return buf;
}

int pd_init(const char* repo_root) {
  if (g_inited) return 0;
  if (g_finalized) {
    set_error("pd_init: the embedded interpreter cannot be restarted "
              "after pd_shutdown (numpy does not survive re-init); keep "
              "the runtime alive for the process lifetime");
    return 1;
  }
  const bool first = !Py_IsInitialized();
  PyGILState_STATE st = PyGILState_LOCKED;
  if (first) {
    Py_Initialize();  // holds the GIL
  } else {
    st = PyGILState_Ensure();  // retry after a failed first pd_init
  }
  int rc = 0;
  PyObject* sys_path = PySys_GetObject("path");
  if (repo_root != nullptr) {
    PyObject* p = PyUnicode_FromString(repo_root);
    PyList_Insert(sys_path, 0, p);
    Py_DECREF(p);
  }
  PyObject* mod = PyImport_ImportModule("paddle_tpu.inference");
  if (mod == nullptr) {
    set_error_from_python("import paddle_tpu.inference");
    rc = 1;
  } else {
    Py_DECREF(mod);
    g_inited = true;
  }
  // ALWAYS release the GIL — a failure path that kept it would deadlock
  // every later call from any thread
  if (first) {
    g_main_tstate = PyEval_SaveThread();
  } else {
    PyGILState_Release(st);
  }
  return rc;
}

void* pd_create_predictor(const char* model_dir) {
  if (!runtime_alive("pd_create_predictor")) return nullptr;
  GilGuard gil;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.inference");
  if (mod == nullptr) {
    set_error_from_python("import paddle_tpu.inference");
    return nullptr;
  }
  PyObject* cfg_cls = PyObject_GetAttrString(mod, "AnalysisConfig");
  PyObject* create = PyObject_GetAttrString(mod, "create_paddle_predictor");
  Py_DECREF(mod);
  if (cfg_cls == nullptr || create == nullptr) {
    set_error_from_python("predictor API lookup");
    Py_XDECREF(cfg_cls);
    Py_XDECREF(create);
    return nullptr;
  }
  PyObject* cfg = PyObject_CallFunction(cfg_cls, "s", model_dir);
  Py_DECREF(cfg_cls);
  if (cfg == nullptr) {
    set_error_from_python("AnalysisConfig");
    Py_DECREF(create);
    return nullptr;
  }
  PyObject* pred = PyObject_CallFunctionObjArgs(create, cfg, nullptr);
  Py_DECREF(cfg);
  Py_DECREF(create);
  if (pred == nullptr) {
    set_error_from_python("create_paddle_predictor");
    return nullptr;
  }
  return pred;  // owned reference handed to the caller as an opaque handle
}

int pd_predictor_run(void* handle, const char* input_name,
                     const float* data, int ndim, const long* dims,
                     float* out, long out_capacity, int* out_ndim,
                     long* out_dims /* caller-sized, >= 8 */) {
  if (!runtime_alive("pd_predictor_run")) return 1;
  GilGuard gil;
  PyObject* pred = static_cast<PyObject*>(handle);

  // build a nested-list feed via numpy (frombuffer + reshape)
  PyObject* np = PyImport_ImportModule("numpy");
  if (np == nullptr) {
    set_error_from_python("import numpy");
    return 1;
  }
  long total = 1;
  for (int i = 0; i < ndim; ++i) {
    if (dims[i] <= 0) {
      set_error("pd_predictor_run: dims must be positive");
      Py_DECREF(np);
      return 1;
    }
    total *= dims[i];
  }
  PyObject* bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), total * sizeof(float));
  if (bytes == nullptr) {
    set_error_from_python("input buffer");
    Py_DECREF(np);
    return 1;
  }
  PyObject* arr = PyObject_CallMethod(np, "frombuffer", "Os", bytes, "float32");
  Py_DECREF(bytes);
  if (arr == nullptr) {
    set_error_from_python("np.frombuffer");
    Py_DECREF(np);
    return 1;
  }
  PyObject* shape = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    PyTuple_SET_ITEM(shape, i, PyLong_FromLong(dims[i]));
  }
  PyObject* reshaped = PyObject_CallMethod(arr, "reshape", "O", shape);
  Py_DECREF(arr);
  Py_DECREF(shape);
  if (reshaped == nullptr) {
    set_error_from_python("reshape");
    Py_DECREF(np);
    return 1;
  }

  PyObject* feed = PyDict_New();
  PyDict_SetItemString(feed, input_name, reshaped);
  Py_DECREF(reshaped);
  PyObject* outs = PyObject_CallMethod(pred, "run", "O", feed);
  Py_DECREF(feed);
  if (outs == nullptr) {
    set_error_from_python("predictor.run");
    Py_DECREF(np);
    return 1;
  }
  PyObject* first = PySequence_GetItem(outs, 0);
  Py_DECREF(outs);
  if (first == nullptr) {
    set_error_from_python("no outputs");
    Py_DECREF(np);
    return 1;
  }
  PyObject* as_np = PyObject_CallMethod(np, "ascontiguousarray", "Os", first,
                                        "float32");
  Py_DECREF(first);
  Py_DECREF(np);
  if (as_np == nullptr) {
    set_error_from_python("ascontiguousarray");
    return 1;
  }
  PyObject* shp = PyObject_GetAttrString(as_np, "shape");
  Py_ssize_t rank = PyTuple_Size(shp);
  if (rank > 8) {
    set_error("output rank > 8 exceeds the C ABI dims buffer");
    Py_DECREF(shp);
    Py_DECREF(as_np);
    return 1;
  }
  long n = 1;
  *out_ndim = static_cast<int>(rank);
  for (Py_ssize_t i = 0; i < rank; ++i) {
    out_dims[i] = PyLong_AsLong(PyTuple_GetItem(shp, i));
    n *= out_dims[i];
  }
  Py_DECREF(shp);
  if (n > out_capacity) {
    set_error("output buffer too small");
    Py_DECREF(as_np);
    return 1;
  }
  PyObject* tob = PyObject_CallMethod(as_np, "tobytes", nullptr);
  Py_DECREF(as_np);
  if (tob == nullptr) {
    set_error_from_python("tobytes");
    return 1;
  }
  std::memcpy(out, PyBytes_AsString(tob), n * sizeof(float));
  Py_DECREF(tob);
  return 0;
}

void pd_destroy_predictor(void* handle) {
  if (handle == nullptr) return;
  if (!g_inited) return;  // after shutdown the ref died with the runtime
  GilGuard gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
}

void pd_shutdown() {
  if (g_inited) {
    if (g_main_tstate != nullptr) {
      PyEval_RestoreThread(g_main_tstate);
      g_main_tstate = nullptr;
    }
    Py_Finalize();
    g_inited = false;
    g_finalized = true;  // re-init is refused (numpy can't re-init)
  }
}

}  // extern "C"
