/* C-only inference demo: load a saved model through the C ABI and run a
 * batch (inference/api/demo_ci analog).  Usage:
 *   capi_demo <repo_root> <model_dir> <input_name> <ndim> <d0> <d1> ...
 * Feeds ones; prints the first few outputs and OK/ERR. */
#include <stdio.h>
#include <stdlib.h>

#include "capi.h"

int main(int argc, char** argv) {
  if (argc < 6) {
    fprintf(stderr, "usage: %s <repo_root> <model_dir> <input> <ndim> <dims...>\n",
            argv[0]);
    return 2;
  }
  const char* root = argv[1];
  const char* model_dir = argv[2];
  const char* input = argv[3];
  int ndim = atoi(argv[4]);
  if (ndim < 1 || ndim > 8 || argc < 5 + ndim) {
    fprintf(stderr, "ndim must be 1..8 with that many dims supplied\n");
    return 2;
  }
  long dims[8];
  long total = 1;
  for (int i = 0; i < ndim; ++i) {
    dims[i] = atol(argv[5 + i]);
    total *= dims[i];
  }

  if (pd_init(root) != 0) {
    fprintf(stderr, "pd_init: %s\n", pd_last_error());
    return 1;
  }
  void* pred = pd_create_predictor(model_dir);
  if (pred == NULL) {
    fprintf(stderr, "pd_create_predictor: %s\n", pd_last_error());
    return 1;
  }
  float* in = malloc(total * sizeof(float));
  for (long i = 0; i < total; ++i) in[i] = 1.0f;
  float out[4096];
  long out_dims[8];
  int out_ndim = 0;
  if (pd_predictor_run(pred, input, in, ndim, dims, out, 4096, &out_ndim,
                       out_dims) != 0) {
    fprintf(stderr, "pd_predictor_run: %s\n", pd_last_error());
    return 1;
  }
  long n = 1;
  printf("out_ndim=%d dims=", out_ndim);
  for (int i = 0; i < out_ndim; ++i) {
    printf("%ld%s", out_dims[i], i + 1 < out_ndim ? "x" : "");
    n *= out_dims[i];
  }
  printf(" first=[");
  for (long i = 0; i < n && i < 4; ++i) printf("%s%.6f", i ? ", " : "", out[i]);
  printf("]\n");
  free(in);
  pd_destroy_predictor(pred);
  pd_shutdown();
  printf("CAPI_OK\n");
  return 0;
}
