"""Python side of the C++ demo trainer (train/demo_trainer.cc analog).

The C++ binary (`native/demo_trainer.cc`) embeds CPython, constructs a
DemoTrainer from an exported program directory, and owns the training
loop — the framework supplies exactly one `step()` per iteration, the way
the reference's demo_trainer drives Executor::Run per batch.

Export side: ``export_train_program(dir, main, startup, feeds)`` writes
main.json / startup.json / feeds.json (name, shape, dtype per feed and
the fetch names) so a program built in Python can be trained from C++
with no Python script involved at run time.
"""

import json
import os

import numpy as np


def export_train_program(path, main, startup, feed_specs, fetch_names):
    """feed_specs: [{"name", "shape" (w/o batch), "dtype"}, ...]."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "main.json"), "w") as f:
        f.write(main.to_json())
    with open(os.path.join(path, "startup.json"), "w") as f:
        f.write(startup.to_json())
    with open(os.path.join(path, "feeds.json"), "w") as f:
        json.dump({"feeds": feed_specs, "fetches": list(fetch_names)}, f)


class DemoTrainer:
    """Loads an exported training program; each step() runs one iteration
    on synthetic data shaped by the feed spec and returns the first fetch
    (the loss) as a float."""

    def __init__(self, path, batch_size=16, seed=0):
        import paddle_tpu as fluid
        from paddle_tpu import framework

        self._fluid = fluid
        with open(os.path.join(path, "main.json")) as f:
            self.main = framework.Program.from_json(f.read())
        with open(os.path.join(path, "startup.json")) as f:
            self.startup = framework.Program.from_json(f.read())
        with open(os.path.join(path, "feeds.json")) as f:
            spec = json.load(f)
        self.feed_specs = spec["feeds"]
        self.fetch_names = spec["fetches"]
        self.batch_size = batch_size
        self.rng = np.random.RandomState(seed)
        self.scope = fluid.Scope()
        with fluid.scope_guard(self.scope):
            self.exe = fluid.Executor()
            self.exe.run(self.startup)

    def _batch(self):
        feed = {}
        for fs in self.feed_specs:
            shape = [self.batch_size] + [int(s) for s in fs["shape"]]
            if fs["dtype"].startswith("int"):
                hi = int(fs.get("max", 10))
                feed[fs["name"]] = self.rng.randint(0, hi, shape).astype(fs["dtype"])
            else:
                feed[fs["name"]] = self.rng.rand(*shape).astype(fs["dtype"])
        return feed

    def step(self):
        with self._fluid.scope_guard(self.scope):
            out = self.exe.run(
                self.main, feed=self._batch(), fetch_list=self.fetch_names
            )
        return float(np.asarray(out[0]).reshape(-1)[0])
