"""Native runtime bindings (ctypes over libpaddle_tpu_native.so).

The C++ pieces mirror the reference's native runtime components
(SURVEY §2.13 recordio, §2.6 reader/ runtime): chunked RecordIO with
CRC+compression, a GIL-free bounded blocking queue, and a threaded
prefetch loader.  The library is built on demand with the local toolchain
(`make` in this directory); callers fall back to pure Python when
unavailable (`available()` is False).
"""

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libpaddle_tpu_native.so")

_lib = None
_build_lock = threading.Lock()
_build_failed = False
_build_error = None  # diagnostics when the toolchain/compile fails


def _missing_protobuf(err):
    """True when a full-build failure looks like an absent protobuf
    toolchain (the one condition the `nodesc` fallback exists for) —
    NOT a genuine compile error in the codec sources, which must
    surface instead of silently shipping a library without the codec."""
    low = (err or "").lower()
    # missing-toolchain-specific patterns only: a genuine codec compile
    # error also mentions protobuf headers (g++ notes cite
    # google/protobuf/*.h), so bare substrings would misclassify it
    return any(
        s in low
        for s in (
            "protoc: not found",
            "protoc: command not found",
            "protoc: no such file",
            "fatal error: google/protobuf",  # header include missing
            "cannot find -lprotobuf",  # linker: library missing
        )
    )


def _try_build():
    global _build_failed, _build_error
    # `make -s` is a fast no-op when the .so is newer than the sources,
    # and rebuilds after source edits (stale-library trap avoided).
    # Hosts without libprotobuf/protoc fall back to the `nodesc` target:
    # every native piece except the desc codec.
    compile_failed = False
    for target in ([], ["nodesc"]):
        if target:
            if compile_failed and not _missing_protobuf(_build_error):
                # real compile error — don't mask it with nodesc, but
                # don't fail silently either: callers only see
                # available()==False unless told to check build_error()
                import warnings

                warnings.warn(
                    "paddle_tpu.native: native build failed with a "
                    "compile error (see paddle_tpu.native.build_error())"
                    " — native features disabled", RuntimeWarning)
                break
            if _missing_protobuf(_build_error):
                import warnings

                warnings.warn(
                    "paddle_tpu.native: protobuf toolchain missing — "
                    "building without the desc codec (nodesc)",
                    RuntimeWarning)
            # non-compile failures (timeout, missing make) still retry
            # nodesc: the smaller target may succeed where the full one
            # didn't, matching the pre-guard behavior
        try:
            subprocess.run(
                ["make", "-s"] + target,
                cwd=_DIR,
                check=True,
                capture_output=True,
                timeout=120,
            )
            _build_error = None  # success: drop the failed-attempt log
            return True
        except subprocess.CalledProcessError as e:
            _build_error = (e.stderr or e.stdout or b"").decode(errors="replace")
            compile_failed = True
        except Exception as e:
            _build_error = repr(e)
            compile_failed = False
    _build_failed = True
    return False


def build_error():
    """Compiler/toolchain output from a failed native build, or None."""
    return _build_error


def get_lib():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not _try_build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        # signatures
        lib.rio_writer_open.restype = ctypes.c_void_p
        lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32]
        lib.rio_writer_write.restype = ctypes.c_int
        lib.rio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        lib.rio_writer_close.restype = ctypes.c_int
        lib.rio_writer_close.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_open.restype = ctypes.c_void_p
        lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
        lib.rio_scanner_next.restype = ctypes.POINTER(ctypes.c_char)
        lib.rio_scanner_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32)]
        lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
        lib.bq_create.restype = ctypes.c_void_p
        lib.bq_create.argtypes = [ctypes.c_uint32]
        lib.bq_push.restype = ctypes.c_int
        lib.bq_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_int]
        lib.bq_pop.restype = ctypes.c_int
        lib.bq_pop.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int,
        ]
        lib.bq_size.restype = ctypes.c_uint32
        lib.bq_size.argtypes = [ctypes.c_void_p]
        lib.bq_close.argtypes = [ctypes.c_void_p]
        lib.bq_destroy.argtypes = [ctypes.c_void_p]
        lib.rio_loader_open.restype = ctypes.c_void_p
        lib.rio_loader_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_uint32,
            ctypes.c_uint32,
            ctypes.c_uint32,
        ]
        lib.rio_loader_next.restype = ctypes.c_int
        lib.rio_loader_next.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.rio_loader_error.restype = ctypes.c_int
        lib.rio_loader_error.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_error.restype = ctypes.c_int
        lib.rio_scanner_error.argtypes = [ctypes.c_void_p]
        lib.rio_loader_close.argtypes = [ctypes.c_void_p]
        # frame_server.cc (native RPC transport)
        lib.fs_create.restype = ctypes.c_void_p
        lib.fs_create.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_char_p]
        lib.fs_port.restype = ctypes.c_int
        lib.fs_port.argtypes = [ctypes.c_void_p]
        lib.fs_next.restype = ctypes.c_void_p
        lib.fs_next.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.fs_req_data.restype = ctypes.POINTER(ctypes.c_char)
        lib.fs_req_data.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_uint64)]
        lib.fs_req_conn.restype = ctypes.c_uint64
        lib.fs_req_conn.argtypes = [ctypes.c_void_p]
        lib.fs_req_free.argtypes = [ctypes.c_void_p]
        lib.fs_send.restype = ctypes.c_int
        lib.fs_send.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                ctypes.c_char_p, ctypes.c_uint64]
        lib.fs_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available():
    return get_lib() is not None


class BlockingQueue:
    """GIL-free bounded byte queue (lod_tensor_blocking_queue.h analog)."""

    def __init__(self, capacity=64):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.bq_create(capacity)

    def push(self, data, timeout_ms=-1):
        return self._lib.bq_push(self._h, bytes(data), len(data), timeout_ms) == 0

    def pop(self, timeout_ms=-1):
        """Returns bytes, or None on timeout / closed+drained."""
        n = ctypes.c_uint32(4096)
        buf = ctypes.create_string_buffer(n.value)
        while True:
            rc = self._lib.bq_pop(self._h, buf, len(buf), ctypes.byref(n), timeout_ms)
            if rc < 0:
                return None
            if rc == 0:
                return buf.raw[: n.value]
            # rc == 1: another consumer may race us to the front item, so
            # grow-and-retry until a copy succeeds
            buf = ctypes.create_string_buffer(n.value)

    def size(self):
        return int(self._lib.bq_size(self._h))

    def close(self):
        self._lib.bq_close(self._h)

    def destroy(self):
        """Free the native queue.  Only call once no thread is blocked in
        push/pop — freeing under a blocked waiter is use-after-free."""
        if getattr(self, "_h", None):
            self._lib.bq_close(self._h)
            self._lib.bq_destroy(self._h)
            self._h = None

    def __del__(self):
        # close() only: it wakes blocked waiters safely; the handle itself
        # is reclaimed at process exit (destroy() is explicit because a
        # waiter could still be inside the native call)
        try:
            if getattr(self, "_h", None):
                self._lib.bq_close(self._h)
        except Exception:
            pass


class RecordIOLoader:
    """Threaded prefetching reader over RecordIO files (open_files_op +
    buffered_reader analog): C++ worker threads scan + decompress off the
    GIL; iteration yields raw record bytes."""

    def __init__(self, paths, capacity=256, n_threads=2):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        for p in paths:
            if not os.path.exists(p):
                raise IOError("recordio file not found: %s" % p)
        self._lib = lib
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths]
        )
        self._h = lib.rio_loader_open(arr, len(paths), capacity, n_threads)

    def __iter__(self):
        return self

    def __next__(self):
        if self._h is None:
            raise StopIteration
        n = ctypes.c_uint32(4096)
        buf = ctypes.create_string_buffer(n.value)
        while True:
            rc = self._lib.rio_loader_next(self._h, buf, len(buf), ctypes.byref(n))
            if rc < 0:
                if self._lib.rio_loader_error(self._h):
                    self.close()
                    raise IOError("recordio loader hit a corrupted file")
                raise StopIteration
            if rc == 0:
                return buf.raw[: n.value]
            buf = ctypes.create_string_buffer(n.value)

    def close(self):
        if self._h is not None:
            self._lib.rio_loader_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
