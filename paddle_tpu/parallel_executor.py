"""ParallelExecutor: data parallelism via mesh shardings.

The reference's ParallelExecutor (parallel_executor.h:44) replicates the
program per GPU, builds an SSA graph, and inserts NCCL AllReduce op-handles
per gradient (multi_devices_graph_pass.cc).  TPU-natively none of that graph
surgery exists: the SAME traced step function is jitted with the batch feeds
sharded over a 1-D `dp` device mesh and parameters/state replicated; XLA's
SPMD partitioner inserts the gradient all-reduce over ICI automatically
(psum on the path grad -> replicated param update).  BuildStrategy /
ExecutionStrategy are kept as API-parity config objects; reduce strategy
maps onto XLA's choice of collective.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import framework
from .core import scope as scope_mod
from .core.trace import build_traced_function
from .executor import as_numpy
from .places import default_place

__all__ = ["ParallelExecutor", "BuildStrategy", "ExecutionStrategy"]


class ReduceStrategy:
    AllReduce = 0
    Reduce = 1


class GradientScaleStrategy:
    CoeffNumDevice = 0
    One = 1
    Customized = 2


class BuildStrategy:
    """API parity with details/build_strategy.h:34; on TPU these knobs are
    hints (XLA already fuses and schedules)."""

    ReduceStrategy = ReduceStrategy
    GradientScaleStrategy = GradientScaleStrategy

    def __init__(self):
        self.reduce_strategy = ReduceStrategy.AllReduce
        self.gradient_scale_strategy = GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""
        self.fuse_elewise_add_act_ops = False
        self.enable_data_balance = False
        self.memory_optimize = False
        self.enable_sequential_execution = False


class ExecutionStrategy:
    """API parity with details/execution_strategy.h:22."""

    def __init__(self):
        self.num_threads = 0
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 100
        self.use_experimental_executor = False


class ParallelExecutor:
    """fluid.ParallelExecutor parity (python/paddle/fluid/parallel_executor.py:32)."""

    def __init__(
        self,
        use_cuda=None,
        loss_name=None,
        main_program=None,
        share_vars_from=None,
        exec_strategy=None,
        build_strategy=None,
        num_trainers=1,
        trainer_id=0,
        scope=None,
        use_tpu=None,
        mesh=None,
    ):
        self._program = main_program or framework.default_main_program()
        self._scope = scope or scope_mod.global_scope()
        self._loss_name = loss_name
        self.exec_strategy = exec_strategy or ExecutionStrategy()
        self.build_strategy = build_strategy or BuildStrategy()
        if mesh is not None:
            self._mesh = mesh
        else:
            devices = np.array(jax.devices())
            self._mesh = Mesh(devices, ("dp",))
        self._ndev = int(np.prod([d for d in self._mesh.devices.shape]))
        self._cache = {}
        self._step = 0
        self._base_key = jax.random.PRNGKey(self._program.random_seed or 90157)

    @property
    def device_count(self):
        return self._ndev

    def _compile(self, feed_sig, fetch_names):
        key = (self._program._version, feed_sig, tuple(fetch_names))
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        program = self._program
        # train-safe fusion subset, applied pre-compile when the
        # BuildStrategy asks (details/build_strategy.h fuse_elewise_add_act
        # knob — a real Program rewrite; the fused op differentiates
        # through the generic vjp machinery).  The rewrite runs on a CLONE
        # with this run's fetch targets protected, so the user's program
        # stays pristine and a later fetch of any intermediate still works.
        if self.build_strategy.fuse_elewise_add_act_ops:
            from .transpiler import apply_pass

            program = self._program.clone()
            program._protected_fetch_names = set(fetch_names)
            apply_pass(program, "fuse_elewise_add_act_pass")
            self._last_fused_program = program
        if self.build_strategy.debug_graphviz_path:
            from .transpiler import apply_pass

            program._graph_viz_path = self.build_strategy.debug_graphviz_path
            apply_pass(program, "graph_viz_pass")
        feed_names = tuple(n for n, _, _ in feed_sig)
        traced = build_traced_function(
            program, 0, feed_names, fetch_names, self._scope
        )
        repl = NamedSharding(self._mesh, P())
        data = NamedSharding(self._mesh, P("dp"))
        jitted = jax.jit(
            traced.fn,
            in_shardings=(data, repl, repl, repl),
            out_shardings=(repl, repl),
            donate_argnums=(2,),
        )
        self._cache[key] = (traced, jitted)
        return traced, jitted

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict or {}
        if isinstance(feed, (list, tuple)):
            # per-device feed dicts (reference style): concat along batch
            merged = {}
            for k in feed[0]:
                merged[k] = np.concatenate([np.asarray(f[k]) for f in feed], axis=0)
            feed = merged
        fetch_names = [
            v.name if isinstance(v, framework.Variable) else str(v) for v in fetch_list
        ]
        data_sh = NamedSharding(self._mesh, P("dp"))
        repl = NamedSharding(self._mesh, P())
        feed_arrays = {}
        for name, value in feed.items():
            arr = jnp.asarray(np.asarray(value))
            if arr.shape and arr.shape[0] % self._ndev == 0:
                feed_arrays[name] = jax.device_put(arr, data_sh)
            else:
                feed_arrays[name] = jax.device_put(arr, repl)
        feed_sig = tuple(
            sorted((n, tuple(a.shape), str(a.dtype)) for n, a in feed_arrays.items())
        )
        traced, jitted = self._compile(feed_sig, fetch_names)
        ro_state = {n: jax.device_put(self._scope.find_var(n), repl) for n in traced.ro_names}
        rw_state = {n: jax.device_put(self._scope.find_var(n), repl) for n in traced.rw_names}
        rng = jax.random.fold_in(self._base_key, self._step)
        self._step += 1
        fetches, new_state = jitted(feed_arrays, ro_state, rw_state, rng)
        for n, v in new_state.items():
            self._scope.set(n, v)
        if return_numpy:
            return [as_numpy(f) for f in fetches]
        return list(fetches)
