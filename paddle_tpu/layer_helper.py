"""LayerHelper (python/paddle/fluid/layer_helper.py:29 analog).

Layers use this to create parameters (with startup-program init ops,
create_parameter :288), temp output vars, and to append ops.  Compile-time
shape inference — the reference's per-op C++ InferShape on BlockDesc — is
done here generically by abstract-evaluating the op's JAX lowering with
``jax.eval_shape``: one rule per op serves tracing, compilation *and* shape
inference.  Unknown batch dims (-1) ride through as a sentinel extent.
"""

import copy

import jax
import jax.numpy as jnp
import numpy as np

from . import framework, unique_name
from .core.registry import LowerCtx, get_op, is_registered
from .initializer import Constant, Xavier
from .param_attr import ParamAttr
from .ops.common import jdt

# sentinel for unknown (-1) dims during abstract shape inference; a large
# prime so collision with a real static extent is practically impossible
# (abstract eval allocates nothing, so the size is free)
_DYN = 1000003


def _abstract_inputs(op, block):
    ins = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            v = block._find_var_recursive(n)
            if v is None or v.shape is None:
                return None
            shape = tuple(_DYN if d in (-1, None) else int(d) for d in v.shape)
            vals.append(jax.ShapeDtypeStruct(shape, jdt(v.dtype)))
        ins[slot] = vals
    return ins


def infer_shape(op, block):
    """Set output var shapes/dtypes by abstract evaluation of the lowering."""
    if not is_registered(op.type):
        return
    ins = _abstract_inputs(op, block)
    if ins is None:
        return
    opdef = get_op(op.type)

    def f(ins_):
        ctx = LowerCtx(rng_key=jax.random.PRNGKey(0))
        return opdef.lower(ctx, ins_, op.attrs)

    try:
        outs = jax.eval_shape(f, ins)
    except Exception:
        return
    for slot, names in op.outputs.items():
        shapes = outs.get(slot)
        if shapes is None:
            continue
        for n, s in zip(names, shapes):
            if s is None or not hasattr(s, "shape"):
                continue  # opaque outputs (TensorArray pytrees) carry no shape
            v = block._find_var_recursive(n)
            if v is not None:
                v.shape = tuple(-1 if d == _DYN else d for d in s.shape)
                v.dtype = (
                    "bfloat16" if s.dtype == jnp.bfloat16 else np.dtype(s.dtype).name
                )


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = self.kwargs.get("name", None)
        if name is None:
            self.kwargs["name"] = unique_name.generate(layer_type)

    @property
    def name(self):
        return self.kwargs["name"]

    @property
    def main_program(self):
        return framework.default_main_program()

    @property
    def startup_program(self):
        return framework.default_startup_program()

    # ---- inputs ---------------------------------------------------------
    def multiple_input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if isinstance(inputs, framework.Variable):
            return [inputs]
        return list(inputs)

    def input(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError("%s layer needs exactly one input" % self.layer_type)
        return inputs[0]

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for i in inputs:
            if dtype is None:
                dtype = i.dtype
            elif dtype != i.dtype:
                raise ValueError("mismatched input dtypes")
        return dtype

    # ---- param/bias attr handling ---------------------------------------
    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr", None))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr", None))

    def multiple_param_attr(self, length):
        attr = self.param_attr
        if isinstance(attr, ParamAttr):
            attr = [copy.deepcopy(attr) for _ in range(length)]
        return attr

    # ---- creation --------------------------------------------------------
    def create_parameter(
        self, attr, shape, dtype, is_bias=False, default_initializer=None
    ):
        attr = copy.deepcopy(attr) if attr is not None else ParamAttr()
        if default_initializer is None:
            if is_bias:
                attr._set_default_bias_initializer()
            else:
                attr._set_default_param_initializer()
        else:
            attr._set_default_initializer(default_initializer)
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "w" if not is_bias else "b"]))
        main_block = self.main_program.global_block()
        startup_block = self.startup_program.global_block()
        shape = [int(s) for s in shape]
        param = main_block.create_parameter(
            shape=shape, dtype=dtype, **{k: v for k, v in attr._to_kwargs().items()}
        )
        # mirror var + init op in the startup program
        sp = startup_block.create_var(
            name=param.name, shape=shape, dtype=dtype, persistable=True
        )
        attr.initializer(sp, startup_block)
        return param

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype,
            shape=None,
            persistable=False,
            stop_gradient=stop_gradient,
        )

    # old alias used throughout fluid layer code
    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, *args, **kwargs):
        return self.main_program.current_block().create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, **kwargs
        )

    def create_or_get_global_variable(self, name, *args, **kwargs):
        block = self.main_program.global_block()
        if not block.has_var_local(name):
            return self.create_global_variable(name=name, *args, **kwargs)
        return block.vars[name]

    def set_variable_initializer(self, var, initializer):
        sb = self.startup_program.global_block()
        sv = sb.create_var(
            name=var.name, shape=var.shape, dtype=var.dtype, persistable=True
        )
        initializer(sv, sb)

    # ---- op append -------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        block = self.main_program.current_block()
        op = block.append_op(type, inputs, outputs, attrs)
        infer_shape(op, block)
        return op

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        size = input_var.shape[dim_start:dim_end]
        bias_attr = self.bias_attr
        if not bias_attr:
            return input_var
        b = self.create_parameter(
            attr=bias_attr,
            shape=[int(np.prod([d for d in size]))],
            dtype=input_var.dtype,
            is_bias=True,
        )
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            "elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start},
        )
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act", None)
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = copy.deepcopy(act)
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            act_type, inputs={"X": [input_var]}, outputs={"Out": [tmp]}, attrs=act
        )
        return tmp

    def is_instance(self, param_name, cls):
        param = self.kwargs.get(param_name, None)
        if not isinstance(param, cls):
            raise TypeError("%s must be %s" % (param_name, cls))
