"""Static Program verifier (docs/STATIC_ANALYSIS.md).

``verify_program`` checks a Program/Block/Operator graph WITHOUT tracing
and returns structured ``Diagnostic``s; ``verify_after_pass`` is the
``apply_pass`` postcondition hook (``FLAGS_check_program``) that makes
verified-in => verified-out a structural property of every registry
pass.  The memory-optimize plan assertion and the remat segment-refusal
checks delegate to the diagnostic helpers here instead of carrying
private re-implementations.

Diagnostic classes (each has a triggering negative test in
tests/test_program_verifier.py):

  undefined-read             def-before-use, incl. reads crossing
                             sub-block boundaries (the PR 12 liveness
                             bug class)
  ssa-violation              two ops (re)define one non-persistable name
  slot-arity                 op slots vs the registered infer schema
  shape-mismatch             declared vs inferred shape at an edge
  dtype-mismatch             declared vs inferred dtype at an edge
  dtype-drift                a Variable carries a non-canonical dtype
  dead-write                 an op no fetch/state/side-effect ever needs
  persistable-write-in-remat persistable state written inside a
                             recompute segment
  protected-fetch            a ``_protected_fetch_names`` entry has no
                             remaining definition
  dist-plan                  a param grad reaches neither a collective,
                             a send, nor an optimizer; orphan send/recv
  unknown-op                 no lowering, no grad convention, not
                             structural
  sub-block                  dangling sub_block index
  alias-mismatch             a memory plan pairs dtype/shape-unequal vars
  infer-rule-error           an infer rule itself misbehaved (warning)
  sharding-coverage          a GSPMD-stamped param matches no partition
                             rule (replicated-by-default warning)
  sharding-divisibility      a matched rule's sharded dim does not
                             divide its mesh axis (warning)
  sharding-inconsistency     a grad/optimizer-state name resolves to a
                             different spec than its base param (error)
  pipeline-slice             a pipeline stage slice is ill-formed: a
                             cross-stage read does not resolve through
                             the previous stage's hop vars, a param is
                             read outside its owning stage, or the
                             stage's own slice fails structural verify
"""

from .graph import consumer_map, op_reads
from .infer import infer_program, normalize_dtype

__all__ = [
    "Diagnostic",
    "ProgramVerifyError",
    "verify_program",
    "check_program",
    "verify_after_pass",
    "segment_diagnostics",
    "alias_plan_diagnostics",
    "sharding_diagnostics",
    "pipeline_diagnostics",
]

# canonical dtype strings the IR serializes (desc_codec closed set)
_CANONICAL_DTYPES = frozenset((
    "float16", "bfloat16", "float32", "float64",
    "int8", "uint8", "int16", "int32", "int64", "bool",
))

# ops that terminate a gradient's journey in a dist-transpiled program
_GRAD_SINK_OPS = frozenset((
    "send_bucket", "send_sparse", "send", "send_barrier",
    "c_allreduce_mean", "c_allreduce_sum", "c_allreduce_max",
    "c_allreduce_min", "c_allreduce_prod", "c_reducescatter",
))


class Diagnostic:
    """One verifier finding, locatable to (block, op) and — when raised
    from a pass postcondition — the pass that produced the program."""

    __slots__ = ("code", "severity", "block_idx", "op_idx", "op_type",
                 "message", "pass_name")

    def __init__(self, code, severity, block_idx, op_idx, op_type, message,
                 pass_name=None):
        self.code = code
        self.severity = severity  # "error" | "warning"
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.op_type = op_type
        self.message = message
        self.pass_name = pass_name

    @property
    def is_error(self):
        return self.severity == "error"

    def __str__(self):
        where = "block %s op %s" % (self.block_idx, self.op_idx)
        if self.op_type:
            where += " (%s)" % self.op_type
        s = "[%s] %s: %s" % (self.code, where, self.message)
        if self.pass_name:
            s = "pass '%s': %s" % (self.pass_name, s)
        return s

    __repr__ = __str__


class ProgramVerifyError(RuntimeError):
    """Raised by check_program / verify_after_pass; carries the full
    diagnostic list for programmatic consumers."""

    def __init__(self, message, diagnostics):
        super().__init__(message)
        self.diagnostics = diagnostics


def _is_optimizer_op(op):
    """Structural optimizer detection: consumes a Param and a Grad slot
    (sgd/momentum/adam/... — any rule-based list would rot)."""
    return bool(op.inputs.get("Param")) and bool(op.inputs.get("Grad"))


def _is_grad_op(op):
    return op.type.endswith("_grad") and "__fwd_type__" in op.attrs


def verify_program(program, scope=None, feeds=None, fetches=(),
                   pass_name=None, check_infer=True, dce_fetches=None,
                   keep=None):
    """Statically verify `program`; returns a list of Diagnostics.

    scope:   optional Scope — names resident there count as defined
             (the executor's state-read contract).
    feeds:   iterable of fed names; None = every ``is_data`` var feeds;
             "*" = reads are unconstrained (embedded server shard
             programs whose inputs arrive from the service loop).
    fetches: extra names that must stay defined and count as used.
    dce_fetches: when set, block-0 ops the executor's DCE would drop
             for these fetch targets are skipped (the verify-before-run
             regime checks what will actually trace).
    keep:    explicit block-0 keep mask (bool per op) overriding the
             dce_fetches-derived mask — pipeline stage slices verify
             with the plan's own masks instead of a DCE frontier.
    """
    diags = []
    feed_all = feeds == "*"
    fetch_names = set(
        f.name if hasattr(f, "name") else str(f) for f in (fetches or ()))
    protected = set(getattr(program, "_protected_fetch_names", ()) or ())

    def report(code, severity, bidx, oidx, op, msg):
        diags.append(Diagnostic(
            code, severity, bidx, oidx,
            op.type if op is not None else None, msg, pass_name))

    gb = program.global_block()

    # ---- declared-dtype canonicality (the drift audit) ---------------
    for blk in program.blocks:
        for v in blk.vars.values():
            dt = v.dtype
            if dt is None or (isinstance(dt, str) and dt in _CANONICAL_DTYPES):
                continue
            try:
                canon = normalize_dtype(dt)
            except Exception:
                canon = None
            diags.append(Diagnostic(
                "dtype-drift", "warning", blk.idx, None, None,
                "var '%s' carries non-canonical dtype %r%s — normalize at "
                "append_op time so to_dict/desc_codec round-trips stay "
                "byte-stable" % (
                    v.name, dt,
                    (" (canonical: %r)" % canon) if canon else ""),
                pass_name))

    # ---- executor-DCE mask for the verify-before-run regime ----------
    explicit_keep = keep is not None
    if keep is None and dce_fetches is not None:
        from ..core.trace import dce_mask

        keep = dce_mask(program, 0, list(dce_fetches))

    def skipped(bidx, oidx):
        return keep is not None and bidx == 0 and not keep[oidx]

    # ---- seed the defined-name universe ------------------------------
    def is_defined_externally(block, name):
        if feed_all:
            return True
        v = block._find_var_recursive(name)
        if v is not None and (v.persistable or getattr(v, "is_data", False)
                              and feeds is None):
            return True
        if feeds is not None and name in feed_set:
            return True
        if scope is not None and scope.has_var(name):
            return True
        return False

    feed_set = set(feeds) if feeds not in (None, "*") else set()

    # ---- structural walk (recursing into sub-blocks) -----------------
    from ..core.registry import OPS
    from ..core.trace import op_sub_blocks
    from .infer import SOURCE_OPS, STRUCTURAL_OPS

    # names legitimately multi-written: loop carries and bound sub-block
    # names (the while body re-defines its carried vars every iteration)
    multi_ok = set()
    for blk in program.blocks:
        for op in blk.ops:
            multi_ok.update(op.attrs.get("carried_vars", ()) or ())
            multi_ok.update(op.attrs.get("__bound_names__", ()) or ())

    def walk(bidx, defined, in_remat):
        block = program.block(bidx)
        writers = {}
        for oidx, op in enumerate(block.ops):
            if skipped(bidx, oidx):
                continue
            if op.type in SOURCE_OPS:
                for n in op.output_arg_names():
                    defined.add(n)
                continue
            if op.type == "fetch":
                continue

            # unknown op: nothing will lower it at trace time
            if (
                op.type not in OPS
                and not _is_grad_op(op)
                and op.type not in STRUCTURAL_OPS
            ):
                report(
                    "unknown-op", "error", bidx, oidx, op,
                    "op type '%s' has no registered lowering, no "
                    "<type>_grad convention, and is not structural"
                    % op.type)

            # def-before-use on the op's own declared inputs
            for n in op.input_arg_names():
                if n in defined or is_defined_externally(block, n):
                    continue
                report(
                    "undefined-read", "error", bidx, oidx, op,
                    "op %s reads '%s' which is neither fed, persistable, "
                    "in scope, nor defined by an earlier op in this "
                    "block's scope chain" % (op.type, n))

            # sub-blocks: validate index, recurse with the bound env
            subs = op_sub_blocks(op)
            for sub_idx in subs:
                if not (0 <= sub_idx < program.num_blocks):
                    report(
                        "sub-block", "error", bidx, oidx, op,
                        "op %s references sub_block %d but the program "
                        "has %d blocks"
                        % (op.type, sub_idx, program.num_blocks))
                    continue
                bound = set(op.attrs.get("__bound_names__", ()) or ())
                bound.update(op.attrs.get("carried_vars", ()) or ())
                bound.update(op.input_arg_names())
                walk(sub_idx, set(defined) | bound,
                     in_remat or op.type == "recompute")

            # writes: SSA accounting + remat persistable hazard
            own_reads = set(op.input_arg_names())
            for n in op.output_arg_names():
                v = block._find_var_recursive(n)
                persistable = v is not None and v.persistable
                if persistable and in_remat:
                    report(
                        "persistable-write-in-remat", "error", bidx, oidx,
                        op,
                        "op %s writes persistable '%s' inside a recompute "
                        "segment — the backward re-run would apply the "
                        "state update twice" % (op.type, n))
                if not persistable and n not in own_reads \
                        and n not in multi_ok:
                    prev = writers.get(n)
                    if prev is not None and prev[2] is not op:
                        report(
                            "ssa-violation", "error", bidx, oidx, op,
                            "op %s redefines '%s' already written by op %d "
                            "(%s) — non-persistable names must have one "
                            "static writer"
                            % (op.type, n, prev[0], prev[1]))
                    writers[n] = (oidx, op.type, op)
                defined.add(n)

            # embedded server programs (listen_and_serv carries its shard
            # programs as serialized JSON attrs)
            if op.type == "listen_and_serv":
                _verify_embedded(program, op, bidx, oidx, diags, pass_name)

    walk(0, set(feed_set), False)

    # ---- dead writes -------------------------------------------------
    used = set(fetch_names) | protected
    for blk in program.blocks:
        for op in blk.ops:
            try:
                used.update(op_reads(program, op))
            except IndexError:
                # dangling sub_block index: already reported above
                used.update(op.input_arg_names())
    for blk in program.blocks:
        for oidx, op in enumerate(blk.ops):
            if skipped(blk.idx, oidx):
                continue
            if (op.type in SOURCE_OPS
                    or op.type in ("fetch", "listen_and_serv")):
                continue
            opdef = OPS.get(op.type)
            if opdef is not None and getattr(opdef, "side_effect", False):
                continue
            if op_sub_blocks(op):
                continue
            outs = [n for n in op.output_arg_names()]
            if not outs:
                continue
            live = False
            for n in outs:
                v = blk._find_var_recursive(n)
                if (v is not None and v.persistable) or n in used:
                    live = True
                    break
            if not live:
                report(
                    "dead-write", "warning", blk.idx, oidx, op,
                    "op %s writes only %s, which nothing reads, fetches "
                    "or persists — executor DCE will drop it; delete it "
                    "from the program" % (op.type, outs))

    # ---- protected fetches keep a definition -------------------------
    produced = set()
    for blk in program.blocks:
        for op in blk.ops:
            produced.update(op.output_arg_names())
    for name in sorted(protected | fetch_names):
        if name in produced:
            continue
        v = gb._find_var_recursive(name)
        if v is not None and (v.persistable or getattr(v, "is_data", False)):
            continue
        if scope is not None and scope.has_var(name):
            continue
        if feed_all or name in feed_set:
            continue
        report(
            "protected-fetch", "error", 0, None, None,
            "fetch target '%s' has no remaining definition — a pass "
            "deleted or renamed its producer (the _protected_fetch_names "
            "contract)" % name)

    # ---- dist-plan consistency ---------------------------------------
    if (getattr(program, "_dist_plan_spec", None) is not None
            or getattr(program, "_collective", None) is not None
            or any(op.type in _GRAD_SINK_OPS for op in gb.ops)):
        _check_dist_plan(program, report, skipped)

    # ---- sharding consistency (GSPMD-stamped programs) ---------------
    if getattr(program, "_spmd", None) is not None:
        diags.extend(sharding_diagnostics(program, pass_name=pass_name))

    # ---- pipeline stage-boundary consistency -------------------------
    # explicit_keep guards recursion: pipeline_diagnostics re-enters
    # verify_program once per stage with keep=<that stage's mask>
    if not explicit_keep and getattr(program, "_pipeline", None) is not None:
        diags.extend(pipeline_diagnostics(
            program, scope=scope, pass_name=pass_name))

    # ---- shape/dtype/arity inference ---------------------------------
    if check_infer:
        seed = list(feeds) if feeds not in (None, "*") else ()
        infer_program(program, feeds=seed, report=report, skip=skipped)

    return diags


def _verify_embedded(program, op, bidx, oidx, diags, pass_name):
    """Recursively verify listen_and_serv's embedded shard programs.
    Their non-persistable inputs arrive from the service loop, so reads
    are unconstrained (feeds="*"); structure and shapes still check."""
    from ..framework import Program

    blobs = list(op.attrs.get("optimize_programs", ()) or ())
    lr = op.attrs.get("lr_program")
    if lr:
        blobs.append(lr)
    for i, blob in enumerate(blobs):
        if not isinstance(blob, str):
            continue
        try:
            sub = Program.from_json(blob)
        except Exception as e:
            diags.append(Diagnostic(
                "sub-block", "error", bidx, oidx, op.type,
                "listen_and_serv embedded program #%d does not "
                "deserialize: %s" % (i, e), pass_name))
            continue
        for d in verify_program(sub, feeds="*", check_infer=True):
            if not d.is_error:
                continue
            diags.append(Diagnostic(
                d.code, d.severity, bidx, oidx, op.type,
                "embedded shard program #%d: %s" % (i, d.message),
                pass_name))


def _check_dist_plan(program, report, skipped=lambda b, i: False):
    """Every trainable param's grad must reach a collective, a send, or
    an on-trainer optimizer op; send/recv pairs must not be orphaned.
    Ops the caller's DCE mask drops neither produce grad roots nor
    serve as consumers (they will not trace)."""
    block = program.global_block()
    consumers = {
        n: [i for i in idxs if not skipped(0, i)]
        for n, idxs in consumer_map(block).items()
    }

    # param-grad pairs from the op_role_var tagging (op_proto_maker
    # analog the transpilers key off)
    grads = {}
    for oidx, op in enumerate(block.ops):
        if skipped(0, oidx):
            continue
        rv = op.attrs.get("op_role_var") or ()
        for p, g in zip(rv[0::2], rv[1::2]):
            grads[g] = p
    if not grads:
        # fall back to the grad-name convention against trainable params
        # (backward.py uniquifies: `<param>@GRAD` or `<param>@GRAD_<n>`)
        from ..framework import Parameter, grad_var_name

        produced = set()
        for oidx, op in enumerate(block.ops):
            if skipped(0, oidx):
                continue
            produced.update(op.output_arg_names())
        import re

        for v in block.vars.values():
            if not (isinstance(v, Parameter)
                    and getattr(v, "trainable", True)):
                continue
            g = grad_var_name(v.name)
            # exactly `<p>@GRAD` or its uniquified `<p>@GRAD_<n>` — a
            # derived name (`...@GRAD_0@SEND_TOKEN`) is not a grad root
            pat = re.compile(re.escape(g) + r"(_\d+)?$")
            for n in produced:
                if pat.fullmatch(n):
                    grads[n] = v.name

    for g, p in sorted(grads.items()):
        seen = set()
        frontier = [g]
        routed = False
        while frontier and not routed:
            name = frontier.pop()
            if name in seen:
                continue
            seen.add(name)
            for ci in consumers.get(name, ()):
                cop = block.ops[ci]
                if cop.type in _GRAD_SINK_OPS or _is_optimizer_op(cop):
                    routed = True
                    break
                frontier.extend(cop.output_arg_names())
        if not routed:
            gi = next(
                (i for i, o in enumerate(block.ops)
                 if g in o.output_arg_names()), None)
            op = block.ops[gi] if gi is not None else None
            report(
                "dist-plan", "error", 0, gi, op,
                "gradient '%s' of param '%s' reaches neither a collective, "
                "a send op, nor an optimizer — the dist transpile left an "
                "orphan gradient" % (g, p))

    has_send = any(op.type == "send_bucket" for op in block.ops)
    has_recv = any(op.type == "recv_bucket" for op in block.ops)
    if has_send != has_recv:
        report(
            "dist-plan", "warning", 0, None, None,
            "program has %s without %s — sync pserver rounds pair the "
            "grad push with the param pull" % (
                "send_bucket" if has_send else "recv_bucket",
                "recv_bucket" if has_send else "send_bucket"))


# ---------------------------------------------------------------------------
# raising wrappers
# ---------------------------------------------------------------------------
def _raise_on_errors(diags, prefix):
    """Shared raise discipline: first 8 errors formatted, the rest
    counted; warnings never raise."""
    errors = [d for d in diags if d.is_error]
    if errors:
        head = "\n  ".join(str(d) for d in errors[:8])
        more = "" if len(errors) <= 8 else "\n  ... and %d more" % (
            len(errors) - 8)
        raise ProgramVerifyError(
            "%s with %d error(s):\n  %s%s"
            % (prefix, len(errors), head, more), diags)
    return diags


def check_program(program, **kwargs):
    """verify_program, raising ProgramVerifyError on any error-severity
    diagnostic (warnings pass)."""
    return _raise_on_errors(
        verify_program(program, **kwargs),
        "program verification failed")


def verify_after_pass(program, name, scope=None):
    """The apply_pass postcondition (FLAGS_check_program): any registry
    pass that emits an ill-formed program fails loudly AT THE PASS
    BOUNDARY with the pass and the offending op named."""
    return _raise_on_errors(
        verify_program(program, scope=scope, pass_name=name),
        "pass '%s' postcondition failed — the pass emitted an "
        "ill-formed program" % name)


# ---------------------------------------------------------------------------
# diagnostic helpers other subsystems delegate to
# ---------------------------------------------------------------------------
def segment_diagnostics(program, ops_seg):
    """Remat segment-refusal diagnostics: persistable writes inside the
    candidate segment and non-SSA redefinition across its boundary
    (transpiler.remat._wrappable delegates here; wrapping proceeds only
    when this returns [])."""
    diags = []
    block = program.global_block()
    seg_set = set(id(op) for op in ops_seg)
    defined = set()
    start = None
    try:
        start = block.ops.index(ops_seg[0])
    except (ValueError, IndexError):
        pass
    for j, op in enumerate(ops_seg):
        for name in op.output_arg_names():
            v = block._find_var_recursive(name)
            if v is not None and v.persistable:
                diags.append(Diagnostic(
                    "persistable-write-in-remat", "error", 0,
                    None if start is None else start + j, op.type,
                    "op %s writes persistable '%s' — stateful updates "
                    "cannot cross a remat boundary" % (op.type, name)))
            defined.add(name)
    for blk in program.blocks:
        for oidx, op in enumerate(blk.ops):
            if id(op) in seg_set:
                continue
            clash = [n for n in op.output_arg_names() if n in defined]
            if clash:
                diags.append(Diagnostic(
                    "ssa-violation", "error", blk.idx, oidx, op.type,
                    "op %s redefines %s also written inside the candidate "
                    "segment — the private sub-block env could not tell "
                    "which value to export" % (op.type, clash)))
    return diags


def sharding_diagnostics(program, mesh=None, rules=None, pass_name=None):
    """Rule-table consistency for a GSPMD-stamped program (the
    ``annotate_spmd`` contract made checkable):

      sharding-coverage       a multi-element persistable param matches
                              NO rule — it will replicate by default on
                              every device (warning: legal, but the
                              silent form of the failure the registry's
                              replicated_log exists to surface)
      sharding-divisibility   a rule matched but a sharded dim does not
                              divide its mesh axis — sharding_for will
                              quietly fall back to replicated at run
      sharding-inconsistency  a TRAINING derived name (<p>@GRAD, Adam
                              accumulators, @RAW_BF16 casts) resolves to
                              a DIFFERENT spec than its base param —
                              grads/optimizer state must shard like the
                              param or the optimizer update cross-shards
                              (error: this breaks the ZeRO-state layout)

    mesh/rules default to the program's ``_spmd`` stamp; returns [] for
    unstamped programs.  Delegated to by verify_program (and therefore
    by the apply_pass postcondition under FLAGS_check_program) whenever
    the stamp is present."""
    import numpy as np

    spmd = getattr(program, "_spmd", None)
    if mesh is None or rules is None:
        if spmd is None:
            return []
        mesh = mesh if mesh is not None else spmd["mesh"]
        rules = rules if rules is not None else spmd["rules"]
    from ..parallel.mesh import mesh_axis_sizes

    sizes = mesh_axis_sizes(mesh)
    base_name = getattr(rules, "base_name", None)
    diags = []

    def add(code, severity, msg):
        diags.append(Diagnostic(code, severity, 0, None, None, msg,
                                pass_name))

    seen = set()
    derived = []
    for blk in program.blocks:
        for name, v in sorted(blk.vars.items()):
            if name in seen:
                continue
            seen.add(name)
            shape = tuple(getattr(v, "shape", ()) or ())
            if not shape or int(np.prod(shape)) <= 1:
                continue  # the scalar guard replicates these unlogged
            base = base_name(name) if base_name is not None else name
            if base != name:
                derived.append((name, base, shape))
                continue
            if not getattr(v, "persistable", False) \
                    or getattr(v, "is_data", False):
                continue
            spec, pat = rules.match(name)
            if spec is None:
                if len(shape) >= 2:
                    # unmatched VECTORS (ln scales, biases) replicate by
                    # design in every family table — only a matrix
                    # slipping through the rules is worth surfacing
                    add("sharding-coverage", "warning",
                        "persistable '%s' %s matches no partition rule "
                        "— it replicates on every device"
                        % (name, list(shape)))
                continue
            if len(spec) > len(shape):
                add("sharding-divisibility", "warning",
                    "'%s' rank %d < rule %r spec %s — the rank guard "
                    "replicates it" % (name, len(shape), pat, spec))
                continue
            for dim, axes in zip(shape, tuple(spec)):
                if axes is None:
                    continue
                for ax in (axes if isinstance(axes, tuple) else (axes,)):
                    if int(dim) % int(sizes.get(ax, 1)) != 0:
                        add("sharding-divisibility", "warning",
                            "'%s' dim %d does not divide mesh axis "
                            "%s=%d (rule %r) — sharding_for falls back "
                            "to replicated"
                            % (name, dim, ax, sizes.get(ax, 1), pat))
    for name, base, shape in derived:
        if base not in seen:
            continue
        s_derived = rules.spec_for(name, shape)
        s_base = rules.spec_for(base, shape)
        if s_derived != s_base:
            add("sharding-inconsistency", "error",
                "derived '%s' resolves to %s but its param '%s' to %s — "
                "grads and optimizer state must shard like their param"
                % (name, s_derived, base, s_base))
    return diags


def pipeline_diagnostics(program, plan=None, scope=None, pass_name=None):
    """Stage-boundary diagnostics for a pipeline-sliced program (the
    ``pipeline_program`` contract made checkable):

      1. hop resolution — every cross-stage activation a stage reads
         (``plan.boundary_in[s]``) must be carried by the previous
         stage's hop vars (``plan.boundary_out[s-1]``); a mis-sliced
         program yields an error naming the stage and the boundary op
         that cannot resolve its input
      2. param exclusivity — a stage's forward slice must only read
         params its stage owns (the packed per-stage state layout has
         no row for a foreign param)
      3. per-stage structural verify — each stage slice re-enters
         ``verify_program`` with ``keep=<that stage's mask>``, feeds =
         the stage's hop + data names, fetches = its hop outputs (loss
         on the last stage); any error surfaces as ``pipeline-slice``
         prefixed with the stage index

    ``plan`` defaults to the program's ``_pipeline`` stamp; returns []
    for unstamped programs.  Delegated to by verify_program (and the
    executor's verify-before-first-run) whenever the stamp is present.
    """
    if plan is None:
        pp = getattr(program, "_pipeline", None)
        if pp is None:
            return []
        plan = pp["plan"]
    diags = []
    block = program.global_block()
    S = plan.n_stages

    def first_reader(mask, name):
        for oidx, op in enumerate(block.ops):
            if oidx < len(mask) and mask[oidx] \
                    and name in op.input_arg_names():
                return oidx, op
        return None, None

    # 1. hop resolution
    for s in range(S):
        prev_out = set(plan.boundary_out[s - 1]) if s > 0 else set()
        for name in sorted(plan.boundary_in[s]):
            if name in prev_out:
                continue
            oidx, op = first_reader(plan.fwd_masks[s], name)
            diags.append(Diagnostic(
                "pipeline-slice", "error", 0, oidx,
                op.type if op is not None else None,
                "stage %d boundary op %s reads '%s' across the stage "
                "boundary but stage %d's hop vars %s do not carry it — "
                "the activation cannot resolve through the pipeline"
                % (s, "?" if oidx is None else oidx, name, s - 1,
                   sorted(plan.boundary_out[s - 1]) if s > 0 else []),
                pass_name))

    # 2. param exclusivity
    from ..framework import Parameter

    for s in range(S):
        mask = plan.fwd_masks[s]
        for oidx, op in enumerate(block.ops):
            if oidx >= len(mask) or not mask[oidx]:
                continue
            for n in op.input_arg_names():
                v = block._find_var_recursive(n)
                if not isinstance(v, Parameter):
                    continue
                owner = plan.resolution.stage_for(n)
                if owner is not None and owner != s:
                    diags.append(Diagnostic(
                        "pipeline-slice", "error", 0, oidx, op.type,
                        "stage %d op %d (%s) reads param '%s' owned by "
                        "stage %d — the per-stage packed state has no "
                        "row for a foreign param"
                        % (s, oidx, op.type, n, owner), pass_name))

    # 3. per-stage structural verify (errors only; warnings like
    # dead-write are a property of the full program, not the slice)
    for s in range(S):
        fetches = ([plan.loss_name] if s == S - 1
                   else sorted(plan.boundary_out[s]))
        stage_diags = verify_program(
            program, scope=scope,
            feeds=sorted(plan.stage_feed_names[s]),
            fetches=fetches, keep=plan.fwd_masks[s],
            check_infer=False, pass_name=pass_name)
        for d in stage_diags:
            if not d.is_error:
                continue
            diags.append(Diagnostic(
                "pipeline-slice", "error", d.block_idx, d.op_idx,
                d.op_type, "stage %d slice: %s" % (s, d.message),
                pass_name))
    return diags


def alias_plan_diagnostics(block, reuse):
    """Memory-plan soundness: every reuse pair must alias identically
    typed, identically shaped slots (memory_optimize's defense-in-depth
    assertion delegates here)."""

    def key(name):
        v = block._find_var_recursive(name)
        if v is None:
            return None
        return (str(v.dtype), tuple(int(d) for d in (v.shape or ())))

    diags = []
    for name, cand in sorted((reuse or {}).items()):
        if key(name) != key(cand):
            diags.append(Diagnostic(
                "alias-mismatch", "error", block.idx, None, None,
                "memory plan aliases '%s' -> '%s' but their (dtype, "
                "shape) identities differ (%s vs %s)"
                % (name, cand, key(name), key(cand))))
    return diags
