"""Shared def-use graph helpers over Program/Block/Operator.

Before this module existed the repo carried four private copies of the
same walks: ``pass_registry.OpPattern._consumer_map``, the memory
transpiler's ``ControlFlowGraph`` def-use construction, the
``inference_transpiler`` producer/consumer maps, and the
``debugger``/``net_drawer`` edge iteration.  They now all consume these
helpers, so a fix to (say) sub-block external-read handling lands in
every walker at once.
"""

__all__ = [
    "consumer_map",
    "consumer_count",
    "producer_map",
    "op_reads",
    "def_use_lists",
    "block_edges",
]


def consumer_map(block):
    """name -> [op indices that read it] over one block's op list
    (the OpPattern matcher's def-use edge source)."""
    consumers = {}
    for i, op in enumerate(block.ops):
        for name in op.input_arg_names():
            consumers.setdefault(name, []).append(i)
    return consumers


def consumer_count(block):
    """name -> number of reading ops (single-consumer checks)."""
    return {n: len(idxs) for n, idxs in consumer_map(block).items()}


def producer_map(block):
    """name -> index of its LAST writing op (matches the walk order the
    fold passes rely on: a later redefinition shadows earlier ones)."""
    prod = {}
    for i, op in enumerate(block.ops):
        for n in op.output_arg_names():
            prod[n] = i
    return prod


def consumer_ops(block):
    """name -> [Operator objects that read it] (the fuse-pass matchers
    hold op identities across their own block mutations)."""
    consumers = {}
    for op in block.ops:
        for name in op.input_arg_names():
            consumers.setdefault(name, []).append(op)
    return consumers


def producer_ops(block):
    """name -> LAST writing Operator object."""
    prod = {}
    for op in block.ops:
        for n in op.output_arg_names():
            prod[n] = op
    return prod


def op_reads(program, op):
    """Every name an op reads: its declared inputs plus its sub-blocks'
    external reads (a while/cond/recompute op must keep alive whatever
    its body consumes from the outer scope)."""
    from ..core.trace import op_sub_blocks, sub_block_external_reads

    reads = list(op.input_arg_names())
    for sub_idx in op_sub_blocks(op):
        bound = op.attrs.get("__bound_names__", ())
        reads.extend(sub_block_external_reads(
            program, program.block(sub_idx), bound))
    return reads


def def_use_lists(program, block_idx=0):
    """Per-op (defs, uses) sets over one block, uses including sub-block
    external reads — the ControlFlowGraph liveness input."""
    block = program.block(block_idx)
    defs = []
    uses = []
    for op in block.ops:
        defs.append(set(op.output_arg_names()))
        uses.append(set(op_reads(program, op)))
    return defs, uses


def block_edges(block):
    """Yield (op_idx, op, in_names, out_names) per op — the one edge
    iteration behind the graphviz dumps."""
    for i, op in enumerate(block.ops):
        ins = [n for names in op.inputs.values() for n in names if n]
        outs = [n for names in op.outputs.values() for n in names if n]
        yield i, op, ins, outs
