"""Static program analysis: def-use graph utilities, per-op shape/dtype
infer rules, and the Program verifier (docs/STATIC_ANALYSIS.md).

The Fluid reference validates a ProgramDesc before execution through each
op's ``InferShape``/``InferVarType``; here the same role is played by a
standalone package the transpiler passes, the executor, and the lint CLI
(``tools/check_program.py``) all share:

- ``analysis.graph`` — THE def-use/consumer-map helpers every program
  walker consumes (pass_registry.OpPattern, the memory transpiler's
  ControlFlowGraph, debugger/net_drawer, the verifier itself).
- ``analysis.infer`` — infer-rule registry + whole-program propagation
  engine; rules register alongside the op lowerings in ``ops/``.
- ``analysis.verifier`` — ``verify_program`` producing structured
  diagnostics, the ``apply_pass`` postcondition hook
  (``FLAGS_check_program``), and the diagnostic helpers the
  memory-optimize/remat safety checks delegate to.

Import order note: ``ops`` modules import ``analysis.infer`` to register
their rules, so nothing in this package may import ``ops``.
"""

from .graph import (  # noqa: F401
    consumer_map,
    consumer_count,
    consumer_ops,
    producer_map,
    producer_ops,
    op_reads,
    def_use_lists,
    block_edges,
)
from .infer import (  # noqa: F401
    VarInfo,
    register_infer,
    get_infer_rule,
    infer_program,
)
from .verifier import (  # noqa: F401
    Diagnostic,
    ProgramVerifyError,
    verify_program,
    check_program,
    verify_after_pass,
    segment_diagnostics,
    alias_plan_diagnostics,
    sharding_diagnostics,
    pipeline_diagnostics,
)

__all__ = [
    "consumer_map",
    "consumer_count",
    "consumer_ops",
    "producer_map",
    "producer_ops",
    "op_reads",
    "def_use_lists",
    "block_edges",
    "VarInfo",
    "register_infer",
    "get_infer_rule",
    "infer_program",
    "Diagnostic",
    "ProgramVerifyError",
    "verify_program",
    "check_program",
    "verify_after_pass",
    "segment_diagnostics",
    "alias_plan_diagnostics",
    "sharding_diagnostics",
    "pipeline_diagnostics",
]
