"""Per-op static infer rules + whole-program propagation engine.

The Fluid reference gives every op an ``InferShape``/``InferVarType``
(operator.h) so a ProgramDesc validates before execution; here the same
contract is a registry of *infer rules* that live alongside the op
lowerings in ``ops/`` (``from ..analysis.infer import register_infer``)
and a propagation engine that walks a Program WITHOUT tracing:

- a rule maps input ``VarInfo`` (shape/dtype/var-type) to output
  ``VarInfo`` under the op's attrs, mirroring its lowering's shape
  semantics;
- the engine threads an env through every block (recursing into
  while / cond / recompute / switch sub-blocks), applies the generic
  ``<type>_grad`` convention (grad slots mirror the forward inputs),
  checks each op's slot arity against the rule's declared schema, and
  reports inferred-vs-declared disagreements through a callback — the
  verifier turns those into diagnostics.

Conventions:
- shapes are tuples of ints with ``-1`` = unknown dim, or ``None`` =
  fully unknown rank; dtypes are normalized strings or ``None``;
- a rule RETURNS ``None`` entries (or omits slots) where it cannot
  infer — unknown is always sound, a wrong guess never is;
- a rule RAISES ``InferError`` when the op's input edges are
  inconsistent (rank/contraction mismatch) — the static analog of the
  shape error XLA would raise at trace time.

Dependency note: this module is imported BY the ops modules, so it must
not import ``ops`` (or anything that does).
"""

__all__ = [
    "VarInfo",
    "InferError",
    "register_infer",
    "get_infer_rule",
    "infer_program",
    "same_as",
    "broadcast_shapes",
]

FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")
INT_DTYPES = ("int8", "uint8", "int16", "int32", "int64")


class InferError(Exception):
    """An op's input edges are statically inconsistent (shape rank /
    contraction / dtype contract violation the lowering would also
    reject, caught before any trace)."""


class VarInfo:
    """Static knowledge about one value: shape (tuple with -1 unknown
    dims, or None), dtype (normalized string or None), var type."""

    __slots__ = ("shape", "dtype", "var_type")

    def __init__(self, shape=None, dtype=None, var_type="lod_tensor"):
        self.shape = tuple(int(d) for d in shape) if shape is not None else None
        self.dtype = dtype
        self.var_type = var_type

    @property
    def ndim(self):
        return None if self.shape is None else len(self.shape)

    def __repr__(self):
        return "VarInfo(shape=%s, dtype=%s)" % (self.shape, self.dtype)


class InferRule:
    __slots__ = ("fn", "req_ins", "req_outs")

    def __init__(self, fn, req_ins, req_outs):
        self.fn = fn
        self.req_ins = tuple(req_ins)
        self.req_outs = tuple(req_outs)


_RULES = {}


def register_infer(*types, req_ins=(), req_outs=("Out",)):
    """Decorator registering an infer rule for one or more op types.

        @register_infer("relu", req_ins=("X",))
        def _r(op, ins): ...

    ``req_ins`` / ``req_outs`` declare the op's slot schema: the engine
    reports a ``slot-arity`` diagnostic when a required slot is missing
    or empty.  The rule fn takes (op, ins) with ins = {slot: [VarInfo]}
    and returns {slot: [VarInfo or None]}; use ``None`` (or return {})
    where nothing can be inferred.  Passing fn=None via the schema-only
    form ``register_infer("t", req_ins=...)(None)`` records arity alone.
    """

    def deco(fn):
        for t in types:
            _RULES[t] = InferRule(fn, req_ins, req_outs)
        return fn

    return deco


def get_infer_rule(type_):
    return _RULES.get(type_)


def list_infer_rules():
    return sorted(_RULES)


# ---------------------------------------------------------------------------
# rule-building helpers
# ---------------------------------------------------------------------------
def same_as(slot, out_slots=("Out",)):
    """Outputs mirror the first input in `slot` exactly (shape, dtype,
    AND var type — an identity-through op keeps SelectedRows-ness)."""

    def rule(op, ins):
        x = _first(ins, slot)
        return {o: [x] for o in out_slots}

    return rule


def slot_info(ins, slot, j=0):
    """The j-th VarInfo of a slot, or None when absent/short — THE slot
    accessor every rule body uses (ops modules import it rather than
    carrying private copies)."""
    vals = ins.get(slot) or []
    return vals[j] if j < len(vals) else None


def _first(ins, slot):
    return slot_info(ins, slot)


def combine_dim(a, b, what="operand"):
    """Combine two dims under numpy broadcasting; -1 is a wildcard."""
    a, b = int(a), int(b)
    if a == b:
        return a
    if a == 1:
        return b
    if b == 1:
        return a
    if a == -1 or b == -1:
        return -1
    raise InferError("%s dims %d vs %d do not broadcast" % (what, a, b))


def broadcast_shapes(xs, ys, what="operand"):
    """Numpy-style trailing-aligned broadcast of two shapes (either may
    be None = unknown)."""
    if xs is None or ys is None:
        return None
    xs, ys = tuple(xs), tuple(ys)
    n = max(len(xs), len(ys))
    xs = (1,) * (n - len(xs)) + xs
    ys = (1,) * (n - len(ys)) + ys
    return tuple(combine_dim(a, b, what) for a, b in zip(xs, ys))


def elementwise_shape(x, y, axis=-1):
    """Paddle elementwise broadcast: Y aligns onto X starting at `axis`
    (ops/common.bcast_y).  Returns the out shape (None if unknown)."""
    if x is None or x.shape is None:
        return None
    if y is None or y.shape is None:
        return tuple(x.shape)
    xs, ys = x.shape, y.shape
    if len(xs) == len(ys) or len(ys) > len(xs):
        # equal ranks, or Y outranking X: plain numpy broadcasting (the
        # lowering's reshape is a no-op for equal ranks; a bigger Y only
        # occurs against numel-1 X and numpy handles it)
        return broadcast_shapes(xs, ys, "elementwise")
    a = len(xs) - len(ys) if axis in (-1, None) else int(axis)
    aligned = (1,) * a + tuple(ys) + (1,) * (len(xs) - a - len(ys))
    return broadcast_shapes(xs, aligned, "elementwise")


def same_dtype(x, y):
    """Common dtype of two operands, or None when unknown/mixed (mixed
    operands promote on device; the static level stays agnostic)."""
    if x is None or y is None:
        return None
    if x.dtype is not None and x.dtype == y.dtype:
        return x.dtype
    return None


def numel_known(shape):
    if shape is None or any(int(d) < 0 for d in shape):
        return None
    n = 1
    for d in shape:
        n *= int(d)
    return n


def normalize_dtype(dtype):
    """Any dtype spelling -> the canonical string the IR serializes
    (framework._to_dtype_str, re-exported here so ops modules and the
    verifier share one normalizer)."""
    from ..framework import _to_dtype_str

    return _to_dtype_str(dtype)


def attr_dtype(value, default=None):
    """Resolve a dtype ATTR (string / numpy dtype / framework.proto int
    id) to the canonical string, or None when unresolvable."""
    if value is None:
        return default
    try:
        if isinstance(value, int) and not isinstance(value, bool):
            from ..ops.common import _PROTO_DTYPE  # lazy: no import cycle

            value = _PROTO_DTYPE.get(int(value), None)
            if value is None:
                return default
        return normalize_dtype(value)
    except Exception:
        return default


# ---------------------------------------------------------------------------
# propagation engine
# ---------------------------------------------------------------------------
# op types the tracer consumes structurally (core/trace.py trace_ops) —
# they have no lowering and no infer rule but are NOT unknown ops
STRUCTURAL_OPS = frozenset((
    "feed", "fetch", "read", "create_py_reader", "while", "cond",
    "listen_and_serv",
))

# source ops whose outputs arrive from outside the compiled step (host
# feeds, staged reader queues) — every walker treats them as defs
SOURCE_OPS = frozenset(("feed", "read", "create_py_reader"))


# device dtype policy (ops/common._DTYPE_MAP): int64 and float64 compute
# as their 32-bit forms on TPU, so the IR legitimately mixes the two
# spellings across an edge — statically equivalent, never a mismatch
_DTYPE_EQUIV = {
    "int64": "int32", "int32": "int32",
    "float64": "float32", "float32": "float32",
}


def dtypes_equivalent(a, b):
    if a == b:
        return True
    return _DTYPE_EQUIV.get(a, a) == _DTYPE_EQUIV.get(b, b)


def var_static_info(block, name):
    """VarInfo from a name's declared Variable (or None if undeclared)."""
    v = block._find_var_recursive(name)
    if v is None:
        return None
    shape = None
    if v.shape is not None:
        shape = tuple(int(d) for d in v.shape)
    dtype = v.dtype if isinstance(v.dtype, str) else None
    return VarInfo(shape, dtype, getattr(v, "type", "lod_tensor"))


def _merge(inferred, declared):
    """Best static knowledge: inferred dims where known, declared
    otherwise (ranks must already have been checked by the caller).
    var_type follows the declaration — SelectedRows-ness is a property
    of the declared slot, not of the rule result."""
    if inferred is None:
        return declared
    if declared is None or declared.shape is None or inferred.shape is None:
        shape = inferred.shape if inferred.shape is not None else (
            declared.shape if declared is not None else None)
    elif len(inferred.shape) != len(declared.shape):
        shape = inferred.shape
    else:
        shape = tuple(
            i if i >= 0 else d
            for i, d in zip(inferred.shape, declared.shape))
    dtype = inferred.dtype or (declared.dtype if declared else None)
    var_type = (declared.var_type if declared is not None
                else inferred.var_type)
    return VarInfo(shape, dtype, var_type)


def _grad_op_infer(op, ins):
    """Generic `<type>_grad` rule: each `<slot>@GRAD` output mirrors the
    forward input values in `<slot>` (backward.py's construction feeds
    the forward inputs through under their own slot names)."""
    outs = {}
    for slot in op.outputs:
        if not slot.endswith("@GRAD"):
            continue
        fwd_slot = slot[: -len("@GRAD")]
        fwd_vals = ins.get(fwd_slot)
        if fwd_vals is None:
            continue
        outs[slot] = list(fwd_vals[: len(op.outputs[slot])])
    return outs


def infer_program(program, feeds=(), report=None, block_idx=0, env=None,
                  skip=None):
    """Propagate VarInfo through `program` starting at `block_idx`.

    report(code, severity, block_idx, op_idx, op, message) receives
    every finding ("slot-arity" / "shape-mismatch" / "dtype-mismatch" /
    "infer-rule-error"); pass None to propagate silently.  skip(bidx,
    oidx) -> True drops an op from analysis (the executor's DCE mask:
    ops that will not trace are not checked).  Returns the final env
    {name: VarInfo}.
    """
    if report is None:
        def report(code, severity, bidx, oidx, op, msg):
            return None

    env = {} if env is None else env
    if feeds:
        block = program.block(block_idx)
        for n in feeds:
            info = var_static_info(block, n)
            if info is not None:
                env.setdefault(n, info)
    _infer_block(program, block_idx, env, report, skip)
    return env


def _lookup(env, block, name):
    info = env.get(name)
    if info is not None:
        return info
    return var_static_info(block, name)


# while bodies feed carried shapes back into themselves; 4 widening
# passes bound the fixpoint far above any rank's worth of dim churn
_WHILE_FIXPOINT_MAX = 4


def _info_key(info):
    if info is None:
        return None
    return (info.shape, info.dtype, info.var_type)


def _join_info(after, before):
    """Shape join across two while iterations: agreeing dims keep their
    value, disagreeing dims widen to -1 (unknown), rank or dtype
    disagreement widens the whole field — monotone loss of knowledge,
    so the fixpoint below cannot oscillate forever."""
    if after is None or before is None:
        return after if before is None else before
    if after.shape is None or before.shape is None:
        # unknown joined with known keeps the known value: refinement
        # is fine, only DISAGREEMENT between two known values widens
        shape = after.shape if before.shape is None else before.shape
    elif len(after.shape) != len(before.shape):
        shape = None
    else:
        shape = tuple(a if a == b else -1
                      for a, b in zip(after.shape, before.shape))
    dtype = (after.dtype if before.dtype is None else
             (before.dtype if after.dtype is None else
              (after.dtype if after.dtype == before.dtype else None)))
    var_type = (after.var_type if after.var_type == before.var_type
                else "lod_tensor")
    return VarInfo(shape, dtype, var_type)


def _infer_while_fixpoint(program, subs, env, report, skip):
    """A ``while``/``bounded_while`` body's carried vars feed back into
    the next iteration, so one sub-block pass infers shapes that may
    only hold for iteration 0 (a concat growing a carried dim).  Run
    the body SILENTLY to a bounded fixpoint — after each pass, join
    every changed VarInfo with its previous value, widening disagreeing
    dims to -1 — then make the single reporting pass over the
    stabilized env, so iteration-0-only shapes never become
    diagnostics (and never duplicate them)."""

    def mute(code, severity, bidx, oidx, op, msg):
        return None

    for it in range(_WHILE_FIXPOINT_MAX):
        before = dict(env)
        for sub_idx in subs:
            if 0 <= sub_idx < program.num_blocks:
                _infer_block(program, sub_idx, env, mute, skip)
        changed = [
            n for n, old in before.items()
            if _info_key(env.get(n)) != _info_key(old)
        ]
        # pass 0 populates body-local names — never a reason to stop
        if it > 0 and not changed:
            break
        for n in changed:
            env[n] = _join_info(env.get(n), before[n])
    for sub_idx in subs:
        if 0 <= sub_idx < program.num_blocks:
            _infer_block(program, sub_idx, env, report, skip)


def _check_out(env, block, bidx, oidx, op, name, inferred, report):
    declared = var_static_info(block, name)
    if inferred is not None and declared is not None:
        if (
            inferred.dtype is not None
            and declared.dtype is not None
            and not dtypes_equivalent(inferred.dtype, declared.dtype)
        ):
            report(
                "dtype-mismatch", "error", bidx, oidx, op,
                "output '%s' is declared %s but the %s rule infers %s"
                % (name, declared.dtype, op.type, inferred.dtype))
        if inferred.shape is not None and declared.shape is not None:
            # fluid scalar convention: () and (1,) interchange freely
            # (mean reshapes to [1], losses declare (), fill_constant
            # seeds loss grads as [1]) — numel-1 shapes never conflict
            if (numel_known(inferred.shape) == 1
                    and numel_known(declared.shape) == 1):
                pass
            elif len(inferred.shape) != len(declared.shape):
                report(
                    "shape-mismatch", "error", bidx, oidx, op,
                    "output '%s' is declared rank %d %s but the %s rule "
                    "infers rank %d %s"
                    % (name, len(declared.shape), declared.shape, op.type,
                       len(inferred.shape), inferred.shape))
            else:
                for ax, (i, d) in enumerate(
                        zip(inferred.shape, declared.shape)):
                    if i >= 0 and d >= 0 and i != d:
                        report(
                            "shape-mismatch", "error", bidx, oidx, op,
                            "output '%s' dim %d is declared %d but the "
                            "%s rule infers %d"
                            % (name, ax, d, op.type, i))
                        break
    env[name] = _merge(inferred, declared)


def _infer_block(program, bidx, env, report, skip=None):
    block = program.block(bidx)
    for oidx, op in enumerate(block.ops):
        if skip is not None and skip(bidx, oidx):
            continue
        if op.type in SOURCE_OPS:
            for n in op.output_arg_names():
                env.setdefault(n, var_static_info(block, n) or VarInfo())
            continue
        if op.type == "fetch":
            continue

        is_grad = op.type.endswith("_grad") and "__fwd_type__" in op.attrs
        rule = _RULES.get(op.type)

        # ---- slot arity vs the declared schema -----------------------
        if rule is not None:
            for slot in rule.req_ins:
                if not any(n for n in op.inputs.get(slot, ())):
                    report(
                        "slot-arity", "error", bidx, oidx, op,
                        "op %s requires input slot '%s' (schema: ins=%s "
                        "outs=%s)" % (op.type, slot, list(rule.req_ins),
                                      list(rule.req_outs)))
            for slot in rule.req_outs:
                if not any(n for n in op.outputs.get(slot, ())):
                    report(
                        "slot-arity", "error", bidx, oidx, op,
                        "op %s requires output slot '%s' (schema: ins=%s "
                        "outs=%s)" % (op.type, slot, list(rule.req_ins),
                                      list(rule.req_outs)))

        # ---- gather input infos --------------------------------------
        ins = {}
        for slot, names in op.inputs.items():
            ins[slot] = [
                _lookup(env, block, n) if n else None for n in names
            ]

        # ---- sub-block ops: recurse, then take declared outputs ------
        from ..core.trace import op_sub_blocks

        subs = op_sub_blocks(op)
        if subs:
            if op.type in ("while", "bounded_while"):
                _infer_while_fixpoint(program, subs, env, report, skip)
            else:
                for sub_idx in subs:
                    if 0 <= sub_idx < program.num_blocks:
                        _infer_block(program, sub_idx, env, report, skip)
            for n in op.output_arg_names():
                # recompute exports sub-block-computed names: prefer the
                # env info the recursion just produced
                env[n] = env.get(n) or var_static_info(block, n) or VarInfo()
            continue

        # ---- run the rule --------------------------------------------
        outs = {}
        if is_grad:
            outs = _grad_op_infer(op, ins)
        elif rule is not None and rule.fn is not None:
            try:
                outs = rule.fn(op, ins) or {}
            except InferError as e:
                report("shape-mismatch", "error", bidx, oidx, op,
                       "op %s: %s" % (op.type, e))
                outs = {}
            except Exception as e:  # a rule bug must never kill analysis
                report(
                    "infer-rule-error", "warning", bidx, oidx, op,
                    "infer rule for %s raised %s: %s"
                    % (op.type, type(e).__name__, e))
                outs = {}

        for slot, names in op.outputs.items():
            infos = outs.get(slot)
            for j, n in enumerate(names):
                if not n:
                    continue
                inferred = None
                if infos is not None and j < len(infos):
                    inferred = infos[j]
                _check_out(env, block, bidx, oidx, op, n, inferred, report)
