"""Inference engine (paddle/fluid/inference analog, SURVEY §2.10).

The reference's serving stack is C++: NativePaddlePredictor (Scope +
Executor + feed/fetch, api/api_impl.h:41) and AnalysisPredictor
(ir fusion passes + NaiveExecutor, api/analysis_predictor.h:42).  Here the
executor already compiles a program to ONE XLA executable, so the
predictor's job is: load the saved model, run the analysis passes
(program-level algebraic rewrites — conv+bn folding, dropout removal —
XLA does the rest of the fusion), pin is_test, and serve through a cached
compiled callable.

    config = AnalysisConfig(model_dir)
    predictor = create_paddle_predictor(config)
    outs = predictor.run({"image": batch})
"""

import numpy as np

from .. import framework, io
from ..core.scope import Scope
from ..executor import Executor


class NativeConfig:
    """Plain load-and-run config (NativeConfig analog)."""

    def __init__(self, model_dir=None, place=None):
        self.model_dir = model_dir
        self.place = place
        self.model_filename = None
        self.params_filename = None
        self.ir_optim = False


class AnalysisConfig(NativeConfig):
    """Adds the analysis/IR-pass pipeline (AnalysisConfig analog)."""

    def __init__(self, model_dir=None, place=None):
        super().__init__(model_dir, place)
        self.ir_optim = True
        # attention fusion runs BEFORE drop_train_ops: the dropout-aware
        # attention patterns must see the original dropout op (is_test
        # rewriting turns it into a scale op the matcher doesn't target)
        self._passes = [
            "fold_batch_norm",
            "attention_fuse_pass",
            # fc_fuse first: the recurrent/embedding fuses match its output
            "fc_fuse_pass",
            "embedding_fc_lstm_fuse_pass",
            "fc_gru_fuse_pass",
            "fc_lstm_fuse_pass",
            "conv_eltadd_relu_fuse_pass",
            "seqconv_eltadd_relu_fuse_pass",
            "seqexpand_concat_fc_fuse_pass",
            "fuse_elewise_add_act_pass",
            "drop_train_ops",
            "memory_optimize",
        ]

    def switch_ir_optim(self, flag=True):
        self.ir_optim = bool(flag)
        return self

    def pass_builder(self):
        return self._passes


class Predictor:
    """Serving handle: owns a private scope + compiled program."""

    def __init__(self, config):
        self.config = config
        self.scope = Scope()
        self.exe = Executor(config.place)
        (
            self.program,
            self.feed_names,
            self.fetch_vars,
        ) = io.load_inference_model(
            config.model_dir,
            self.exe,
            model_filename=config.model_filename,
            params_filename=config.params_filename,
            scope=self.scope,
        )
        self.program._is_test = True
        if config.ir_optim:
            self._apply_analysis_passes()
        self.fetch_names = [
            v.name if isinstance(v, framework.Variable) else v
            for v in self.fetch_vars
        ]

    # legacy pass_builder names -> registered pass names
    _PASS_ALIASES = {
        "fold_batch_norm": "conv_bn_fuse_pass",
        "drop_train_ops": "is_test_pass",
        "memory_optimize": "memory_optimize_pass",
    }

    def _apply_analysis_passes(self):
        """IRPassManager analog: resolve the config's pass list through the
        pass registry, so user-registered passes (transpiler.register_pass)
        run inside the predictor like built-ins."""
        from ..transpiler import apply_pass, get_pass

        passes = (
            self.config.pass_builder()
            if isinstance(self.config, AnalysisConfig)
            else ["fold_batch_norm", "drop_train_ops"]
        )
        resolved = [self._PASS_ALIASES.get(n, n) for n in passes]
        for name in resolved:
            get_pass(name)  # validate the whole list before ANY mutation
        # fusion passes must not delete the model's fetch targets
        self.program._protected_fetch_names = {
            v.name if isinstance(v, framework.Variable) else v
            for v in self.fetch_vars
        }
        for name in resolved:
            apply_pass(self.program, name, scope=self.scope)

    def run(self, inputs):
        """inputs: dict name->array, or list aligned with feed_names.
        Returns list of np.ndarrays aligned with the fetch targets."""
        if not isinstance(inputs, dict):
            inputs = dict(zip(self.feed_names, inputs))
        outs = self.exe.run(
            self.program,
            feed=inputs,
            fetch_list=self.fetch_names,
            scope=self.scope,
        )
        return [np.asarray(o) for o in outs]

    def get_input_names(self):
        return list(self.feed_names)

    def get_output_names(self):
        return list(self.fetch_names)

    def clone(self):
        """A predictor sharing this one's weights (zero-copy scope share),
        with its own compile cache — the reference's thread-serving clone."""
        cloned = Predictor.__new__(Predictor)
        cloned.config = self.config
        cloned.scope = self.scope
        cloned.exe = Executor(self.config.place)
        cloned.program = self.program
        cloned.feed_names = list(self.feed_names)
        cloned.fetch_vars = self.fetch_vars
        cloned.fetch_names = list(self.fetch_names)
        return cloned


def create_paddle_predictor(config):
    """CreatePaddlePredictor analog."""
    return Predictor(config)


__all__ = [
    "NativeConfig",
    "AnalysisConfig",
    "Predictor",
    "create_paddle_predictor",
]
