"""Inference engine (paddle/fluid/inference analog, SURVEY §2.10).

The reference's serving stack is C++: NativePaddlePredictor (Scope +
Executor + feed/fetch, api/api_impl.h:41) and AnalysisPredictor
(ir fusion passes + NaiveExecutor, api/analysis_predictor.h:42).  Here the
executor already compiles a program to ONE XLA executable, so the
predictor's job is: load the saved model, run the analysis passes
(program-level algebraic rewrites — conv+bn folding, dropout removal —
XLA does the rest of the fusion), pin is_test, and serve through a cached
compiled callable.

    config = AnalysisConfig(model_dir)
    predictor = create_paddle_predictor(config)
    outs = predictor.run({"image": batch})
"""

import numpy as np

from .. import framework, io
from ..core.scope import Scope
from ..executor import Executor


class NativeConfig:
    """Plain load-and-run config (NativeConfig analog)."""

    def __init__(self, model_dir=None, place=None):
        self.model_dir = model_dir
        self.place = place
        self.model_filename = None
        self.params_filename = None
        self.ir_optim = False


class AnalysisConfig(NativeConfig):
    """Adds the analysis/IR-pass pipeline (AnalysisConfig analog)."""

    def __init__(self, model_dir=None, place=None):
        super().__init__(model_dir, place)
        self.ir_optim = True
        self.int8 = False
        # attention fusion runs BEFORE drop_train_ops: the dropout-aware
        # attention patterns must see the original dropout op (is_test
        # rewriting turns it into a scale op the matcher doesn't target)
        self._passes = [
            "fold_batch_norm",
            "attention_fuse_pass",
            # fc_fuse first: the recurrent/embedding fuses match its output
            "fc_fuse_pass",
            "embedding_fc_lstm_fuse_pass",
            "fc_gru_fuse_pass",
            "fc_lstm_fuse_pass",
            "conv_eltadd_relu_fuse_pass",
            "seqconv_eltadd_relu_fuse_pass",
            "seqexpand_concat_fc_fuse_pass",
            "fuse_elewise_add_act_pass",
            "drop_train_ops",
            "memory_optimize",
        ]

    def switch_ir_optim(self, flag=True):
        self.ir_optim = bool(flag)
        return self

    def enable_int8(self, quantize_transpiler=None):
        """Serve a QAT-saved model with REAL int8 compute (the
        ``EnableTensorRtEngine(precision=Int8)`` analog,
        paddle_inference_api.h): at load the predictor runs
        ``freeze_program`` + ``convert_to_int8`` on the loaded program —
        int8 weights, int32 MXU accumulation, fused dequant.  Pass a
        configured ``QuantizeTranspiler`` when the model was QAT-trained
        with non-default types (e.g. channel-wise weights)."""
        self._int8_transpiler = quantize_transpiler
        self.int8 = True
        return self

    def pass_builder(self):
        return self._passes


class Predictor:
    """Serving handle: owns a private scope + compiled program."""

    def __init__(self, config):
        self.config = config
        self.scope = Scope()
        self._zero_copy_outputs = {}
        self.exe = Executor(config.place)
        (
            self.program,
            self.feed_names,
            self.fetch_vars,
        ) = io.load_inference_model(
            config.model_dir,
            self.exe,
            model_filename=config.model_filename,
            params_filename=config.params_filename,
            scope=self.scope,
        )
        self.program._is_test = True
        if getattr(config, "int8", False):
            from ..contrib.quantize import QuantizeTranspiler

            qt = getattr(config, "_int8_transpiler", None) or QuantizeTranspiler()
            qt.freeze_program(self.program, scope=self.scope)
            if not qt.convert_to_int8(self.program, scope=self.scope):
                raise ValueError(
                    "enable_int8: no quantizable ops converted — the "
                    "saved model has no QAT fake-quantize ops (train "
                    "with QuantizeTranspiler.training_transpile before "
                    "save_inference_model)"
                )
        if config.ir_optim:
            self._apply_analysis_passes()
        self.fetch_names = [
            v.name if isinstance(v, framework.Variable) else v
            for v in self.fetch_vars
        ]

    # legacy pass_builder names -> registered pass names
    _PASS_ALIASES = {
        "fold_batch_norm": "conv_bn_fuse_pass",
        "drop_train_ops": "is_test_pass",
        "memory_optimize": "memory_optimize_pass",
    }

    def _apply_analysis_passes(self):
        """IRPassManager analog: resolve the config's pass list through the
        pass registry, so user-registered passes (transpiler.register_pass)
        run inside the predictor like built-ins."""
        from ..transpiler import apply_pass, get_pass

        passes = (
            self.config.pass_builder()
            if isinstance(self.config, AnalysisConfig)
            else ["fold_batch_norm", "drop_train_ops"]
        )
        resolved = [self._PASS_ALIASES.get(n, n) for n in passes]
        for name in resolved:
            get_pass(name)  # validate the whole list before ANY mutation
        # fusion passes must not delete the model's fetch targets
        self.program._protected_fetch_names = {
            v.name if isinstance(v, framework.Variable) else v
            for v in self.fetch_vars
        }
        for name in resolved:
            apply_pass(self.program, name, scope=self.scope)

    def run(self, inputs):
        """inputs: dict name->array, list aligned with feed_names, or a
        list of PaddleTensor (api_impl.h Run contract — returns
        PaddleTensor outputs in that case).
        Returns list of np.ndarrays aligned with the fetch targets."""
        tensor_mode = (
            isinstance(inputs, (list, tuple)) and inputs
            and isinstance(inputs[0], PaddleTensor)
        )
        if tensor_mode:
            feed = {t.name or n: t.data
                    for t, n in zip(inputs, self.feed_names)}
        elif not isinstance(inputs, dict):
            feed = dict(zip(self.feed_names, inputs))
        else:
            feed = inputs
        outs = self.exe.run(
            self.program,
            feed=feed,
            fetch_list=self.fetch_names,
            scope=self.scope,
        )
        if tensor_mode:
            return [PaddleTensor(np.asarray(o), name=n)
                    for o, n in zip(outs, self.fetch_names)]
        return [np.asarray(o) for o in outs]

    # ---- zero-copy serving (paddle_api.h:98 ZeroCopyTensor /
    # analysis_predictor.h:53 GetInput/OutputTensor + ZeroCopyRun) ----
    def get_input_tensor(self, name):
        if name not in self.feed_names:
            raise KeyError("unknown input '%s' (have %s)"
                           % (name, self.feed_names))
        handles = getattr(self, "_zero_copy_inputs", None)
        if handles is None:
            handles = self._zero_copy_inputs = {}
        if name not in handles:
            handles[name] = ZeroCopyTensor(self, name, is_input=True)
        return handles[name]

    def get_output_tensor(self, name):
        if name not in self.fetch_names:
            raise KeyError("unknown output '%s' (have %s)"
                           % (name, self.fetch_names))
        return ZeroCopyTensor(self, name, is_input=False)

    def zero_copy_run(self):
        """Run from the bound input buffers; outputs readable through
        get_output_tensor(...).copy_to_cpu()."""
        handles = getattr(self, "_zero_copy_inputs", {})
        missing = [n for n in self.feed_names if n not in handles
                   or handles[n]._buf is None]
        if missing:
            raise RuntimeError(
                "zero_copy_run: inputs %s not bound — get_input_tensor + "
                "reshape/copy_from_cpu first" % missing)
        feed = {n: handles[n]._buf for n in self.feed_names}
        outs = self.exe.run(
            self.program,
            feed=feed,
            fetch_list=self.fetch_names,
            scope=self.scope,
            return_numpy=False,
        )
        self._zero_copy_outputs = dict(zip(self.fetch_names, outs))
        return True

    def get_input_names(self):
        return list(self.feed_names)

    def get_output_names(self):
        return list(self.fetch_names)

    def clone(self):
        """A predictor sharing this one's weights (zero-copy scope share),
        with its own compile cache — the reference's thread-serving clone."""
        cloned = Predictor.__new__(Predictor)
        cloned.config = self.config
        cloned._zero_copy_outputs = {}
        cloned.scope = self.scope
        cloned.exe = Executor(self.config.place)
        cloned.program = self.program
        cloned.feed_names = list(self.feed_names)
        cloned.fetch_vars = self.fetch_vars
        cloned.fetch_names = list(self.fetch_names)
        return cloned


class PaddleTensor:
    """Named host tensor for the classic Run(inputs)->outputs serving call
    (paddle_api.h:87 PaddleTensor: name + shape + data blob + lod).
    `data` is a numpy array; `lod` is reference-style offset lists."""

    def __init__(self, data=None, name="", lod=None):
        self.name = name
        self.data = None if data is None else np.asarray(data)
        self.lod = [list(l) for l in (lod or [])]

    @property
    def shape(self):
        return [] if self.data is None else list(self.data.shape)

    @property
    def dtype(self):
        return None if self.data is None else str(self.data.dtype)


class ZeroCopyTensor:
    """Scope-bound tensor handle (paddle_api.h:98): write inputs in place
    and read outputs without intermediate staging buffers.

    TPU reading of "zero copy": the EXACT ndarray the caller fills via
    `mutable_data()`/`copy_from_cpu()` is what the executor device_puts —
    no feed-dict marshalling copy in between — and `copy_to_cpu()` is the
    single device→host materialization of the executor's output buffer.
    """

    def __init__(self, predictor, name, is_input):
        self._pred = predictor
        self._name = name
        self._is_input = is_input
        self._buf = None

    def name(self):
        return self._name

    def reshape(self, shape):
        """Allocate (or reuse) the host-side input buffer — the
        mutable_data contract: Reshape first, then write."""
        shape = tuple(int(d) for d in shape)
        if self._buf is None or self._buf.shape != shape:
            dtype = self._buf.dtype if self._buf is not None else np.float32
            self._buf = np.zeros(shape, dtype)
        return self

    def mutable_data(self, dtype="float32"):
        """Writable ndarray backing this input (call reshape first)."""
        if not self._is_input:
            raise RuntimeError("mutable_data is for input tensors")
        if self._buf is None:
            raise RuntimeError("call reshape(shape) before mutable_data()")
        if str(self._buf.dtype) != str(np.dtype(dtype)):
            self._buf = self._buf.astype(dtype)
        return self._buf

    def copy_from_cpu(self, arr):
        if not self._is_input:
            raise RuntimeError("copy_from_cpu is for input tensors")
        self._buf = np.ascontiguousarray(arr)
        return self

    def copy_to_cpu(self):
        if self._is_input:
            return np.asarray(self._buf)
        out = self._pred._zero_copy_outputs.get(self._name)
        if out is None:
            raise RuntimeError(
                "no output for '%s' yet — call zero_copy_run() first"
                % self._name)
        return np.asarray(out)

    def shape(self):
        if self._is_input:
            return [] if self._buf is None else list(self._buf.shape)
        out = self._pred._zero_copy_outputs.get(self._name)
        return [] if out is None else list(np.asarray(out).shape)

    def set_lod(self, lod):
        self._lod = [list(l) for l in lod]

    def lod(self):
        return getattr(self, "_lod", [])


def create_paddle_predictor(config):
    """CreatePaddlePredictor analog."""
    return Predictor(config)


__all__ = [
    "NativeConfig",
    "AnalysisConfig",
    "Predictor",
    "PaddleTensor",
    "ZeroCopyTensor",
    "create_paddle_predictor",
]
