"""Weight-decay regularizers (python/paddle/fluid/regularizer.py analog):
appended as ops onto gradients before the optimizer ops (regularizer.py:23)."""

from . import framework

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
           "append_regularization_ops"]


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        decay = block.create_var(
            name=framework.unique_name.generate(param.name + "_l2decay"),
            shape=param.shape,
            dtype=param.dtype,
            stop_gradient=True,
        )
        block.append_op(
            "scale",
            inputs={"X": [param]},
            outputs={"Out": [decay]},
            attrs={"scale": self._coeff},
        )
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        sign = block.create_var(
            name=framework.unique_name.generate(param.name + "_sign"),
            shape=param.shape,
            dtype=param.dtype,
            stop_gradient=True,
        )
        block.append_op("sign", inputs={"X": [param]}, outputs={"Out": [sign]})
        decay = block.create_var(
            name=framework.unique_name.generate(param.name + "_l1decay"),
            shape=param.shape,
            dtype=param.dtype,
            stop_gradient=True,
        )
        block.append_op(
            "scale",
            inputs={"X": [sign]},
            outputs={"Out": [decay]},
            attrs={"scale": self._coeff},
        )
        return decay


def append_regularization_ops(parameters_and_grads, regularization=None):
    params_and_grads = []
    block = framework.default_main_program().global_block()
    for param, grad in parameters_and_grads:
        regularization_term = None
        if grad is None:
            params_and_grads.append((param, grad))
            continue
        reg = param.regularizer or regularization
        if reg is not None:
            regularization_term = reg(param, grad, block)
        if regularization_term is None:
            params_and_grads.append((param, grad))
            continue
        new_grad = block.create_var(
            name=framework.unique_name.generate(grad.name + "_reg"),
            shape=grad.shape,
            dtype=grad.dtype,
            stop_gradient=True,
        )
        block.append_op(
            "sum",
            inputs={"X": [grad, regularization_term]},
            outputs={"Out": [new_grad]},
        )
        params_and_grads.append((param, new_grad))
    return params_and_grads


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
