"""HBM budget/stats layer (paddle/fluid/memory/ §2.8 re-expression).

The reference owns allocation through a BuddyAllocator with knobs like
``FLAGS_fraction_of_gpu_memory_to_use`` (detail/system_allocator.cc).  On
TPU, PJRT owns HBM — what survives is the *knob surface* and the *stats
surface*:

- ``apply_memory_fraction()`` translates the reference's memory-fraction
  flag into XLA's client allocator budget (must run before backend init —
  paddle_tpu/__init__ calls it on import).
- ``memory_stats`` / ``memory_allocated`` / ``max_memory_allocated`` read
  PJRT's live allocator counters (the memory::Used analog).
- eager deletion (FLAGS_eager_delete_tensor_gb) is subsumed by buffer
  donation + XLA liveness (core/trace.py donates rw state).
"""

import os

__all__ = [
    "apply_memory_fraction",
    "memory_stats",
    "memory_allocated",
    "max_memory_allocated",
    "memory_limit",
]


def apply_memory_fraction():
    """FLAGS_fraction_of_gpu_memory_to_use -> XLA client mem fraction.

    Reads the flag from the environment (FLAGS_... / PADDLE_TPU_FLAGS)
    because it must take effect BEFORE the first jax backend init; a
    fraction <= 0 keeps XLA's default behavior."""
    frac = os.environ.get("FLAGS_fraction_of_gpu_memory_to_use")
    # PADDLE_TPU_FLAGS batch form overrides the single-var form — the same
    # precedence flags.py applies (_parse_batch_env runs last there)
    for tok in os.environ.get("PADDLE_TPU_FLAGS", "").split():
        if tok.startswith("--fraction_of_gpu_memory_to_use="):
            frac = tok.split("=", 1)[1]
    if not frac:
        return
    try:
        val = float(frac)
    except ValueError:
        return
    if 0.0 < val <= 1.0:
        os.environ.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", str(val))


def _device(place=None):
    if place is not None:
        return place.jax_device()
    import jax

    return jax.devices()[0]


def memory_stats(place=None):
    """Raw PJRT allocator stats dict (bytes_in_use, peak_bytes_in_use,
    bytes_limit, ...); {} when the backend exposes none (CPU)."""
    d = _device(place)
    try:
        return dict(d.memory_stats() or {})
    except Exception:
        return {}


def memory_allocated(place=None):
    """Live allocated bytes on the device (memory::Used analog)."""
    return int(memory_stats(place).get("bytes_in_use", 0))


def max_memory_allocated(place=None):
    """High-water allocated bytes since process start."""
    stats = memory_stats(place)
    return int(stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0)))


def memory_limit(place=None):
    """Allocator budget in bytes (0 when unknown)."""
    return int(memory_stats(place).get("bytes_limit", 0))
