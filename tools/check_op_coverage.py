"""Op-coverage checker: the registered-lowering surface vs the reference's
REGISTER_OPERATOR inventory (tools/diff_api.py's op-level sibling; the
CI-guard role of paddle/scripts/paddle_build.sh API checks).

Usage:
    python tools/check_op_coverage.py [--reference /root/reference]

Prints the coverage summary and exits non-zero if any reference op type
is neither registered, generically derived (`*_grad` via jax.vjp), nor
on the documented structural/N-A list below.
"""

import argparse
import re
import sys
from pathlib import Path

# Reference op types deliberately NOT backed by a lowering rule:
#   - executor/trace-structural: handled by core/trace.py or executor.py
#     machinery, not per-op lowerings
#   - N/A on TPU: CUDA/TensorRT/Go-runtime artifacts with no TPU analog
STRUCTURAL = {
    "feed": "executor feed boundary (executor.py)",
    "fetch": "executor fetch boundary (executor.py)",
    "while": "lowered to lax.while_loop by core/trace.py",
    "conditional_block": "lowered to lax.cond by core/trace.py",
    "read": "reader boundary op satisfied by the executor (program_reader)",
    "create_custom_reader": "reader decorators + layers.Preprocessor subsume; PROVEN by tests/test_pipeline_and_metrics.py::test_create_custom_reader_semantics_via_decorators",
    "listen_and_serv": "pserver service loop (distributed/ps_server.py)",
    "gen_nccl_id": "jax.distributed.initialize bootstrap (distributed)",
    "ncclInit": "ICI collectives need no communicator init",
    "get_places": "device enumeration is jax.devices() (ParallelExecutor)",
}
NOT_APPLICABLE = {
    "go": "CSP experiment; no analog",
    "parallel_do": "deprecated in the reference; ParallelExecutor subsumes",
    "tensorrt_engine": "TensorRT handoff; XLA is the compiler here",
    "ncclAllReduce": "ICI collectives via shard_map/pjit (parallel/)",
    "ncclBcast": "ICI collectives via shard_map/pjit (parallel/)",
    "ncclReduce": "ICI collectives via shard_map/pjit (parallel/)",
}
# grep artifacts (macro parameter names, not op types)
MACRO_NOISE = {"KERNEL_TYPE", "op_type", "op_name"}


def reference_op_types(ref_root):
    # both registration macros define op types (REGISTER_OP_WITHOUT_GRADIENT
    # covers the optimizer/random/metric ops)
    pat = re.compile(r"REGISTER_OP(?:ERATOR|_WITHOUT_GRADIENT)\(\s*(\w+)")
    types = set()
    ops_dir = Path(ref_root) / "paddle" / "fluid" / "operators"
    for path in ops_dir.rglob("*.cc"):
        try:
            types |= set(pat.findall(path.read_text(errors="ignore")))
        except OSError:
            continue
    return types - MACRO_NOISE


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference")
    args = ap.parse_args(argv)

    import paddle_tpu  # noqa: F401  (registers all lowerings)
    import paddle_tpu.ops  # noqa: F401
    from paddle_tpu.core.registry import OPS

    ref = reference_op_types(args.reference)
    if not ref:
        print("reference tree not found at %s — nothing to check" % args.reference)
        return 0
    grad = {t for t in ref if t.endswith("_grad")}
    base = ref - grad
    covered = {t for t in base if t in OPS}
    explained = {t for t in base if t in STRUCTURAL or t in NOT_APPLICABLE}
    missing = sorted(base - covered - explained)
    # grad types derive generically from the forward lowering (jax.vjp);
    # a grad whose base is structural/N-A is explained by the same reason
    grad_ok = {t for t in grad if t[: -len("_grad")] in OPS}
    grad_explained = {
        t for t in grad
        if t[: -len("_grad")] in STRUCTURAL
        or t[: -len("_grad")] in NOT_APPLICABLE
    }
    missing += sorted(grad - grad_ok - grad_explained)

    print("reference op types: %d (%d forward, %d grad)"
          % (len(ref), len(base), len(grad)))
    print("registered lowerings: %d" % len(OPS))
    print("forward coverage: %d lowered + %d structural/N-A = %d/%d"
          % (len(covered), len(explained), len(covered) + len(explained),
             len(base)))
    print("grad coverage: %d generic-vjp + %d structural/N-A = %d/%d"
          % (len(grad_ok), len(grad_explained),
             len(grad_ok) + len(grad_explained), len(grad)))
    if missing:
        print("MISSING (no lowering, no documented reason):")
        for t in missing:
            print("  " + t)
        return 1
    print("OK: every reference op type is lowered or documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
