"""Public-API signature dump (tools/print_signatures.py analog).

Prints one line per public symbol — `module.name (args...)` — sorted, so a
diff against a committed snapshot catches accidental API breaks the way
the reference's diff_api.py CI check does (paddle/scripts/paddle_build.sh).

Usage:
    python tools/print_signatures.py > API.spec
    python tools/diff_api.py API.spec        # non-zero exit on breakage
"""

import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODULES = [
    "paddle_tpu",
    "paddle_tpu.layers",
    "paddle_tpu.layers.control_flow",
    "paddle_tpu.layers.detection",
    "paddle_tpu.layers.sequence",
    "paddle_tpu.layers.io",
    "paddle_tpu.optimizer",
    "paddle_tpu.initializer",
    "paddle_tpu.regularizer",
    "paddle_tpu.clip",
    "paddle_tpu.metrics",
    "paddle_tpu.evaluator",
    "paddle_tpu.average",
    "paddle_tpu.io",
    "paddle_tpu.backward",
    "paddle_tpu.transpiler",
    "paddle_tpu.inference",
    "paddle_tpu.memory",
    "paddle_tpu.device_info",
    "paddle_tpu.parallel.collective",
    "paddle_tpu.parallel.partition_rules",
    "paddle_tpu.parallel.pipeline",
    "paddle_tpu.transpiler.pipeline",
    "paddle_tpu.serving",
    "paddle_tpu.serving.router",
    "paddle_tpu.ops.pallas_kernels",
    "paddle_tpu.ops.kernel_tuning",
    "paddle_tpu.analysis",
    "paddle_tpu.transpiler.autotune",
    "paddle_tpu.utils.memory_analysis",
    "paddle_tpu.dataset.mnist",
    "paddle_tpu.dataset.movielens",
    "paddle_tpu.dataset.wmt14",
    "paddle_tpu.reader.decorator",
]


def _public_names(mod):
    names = getattr(mod, "__all__", None)
    if names is None:
        names = [n for n in dir(mod) if not n.startswith("_")]
    return sorted(set(names))


def _sig(obj):
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return ""


def iter_signatures():
    import importlib

    for modname in MODULES:
        mod = importlib.import_module(modname)
        for name in _public_names(mod):
            obj = getattr(mod, name, None)
            if obj is None or inspect.ismodule(obj):
                continue
            if inspect.isclass(obj):
                yield "%s.%s %s" % (modname, name, _sig(obj.__init__))
                for mname, meth in sorted(vars(obj).items()):
                    if mname.startswith("_") or not callable(meth):
                        continue
                    yield "%s.%s.%s %s" % (modname, name, mname, _sig(meth))
            elif callable(obj):
                yield "%s.%s %s" % (modname, name, _sig(obj))


def main():
    for line in sorted(set(iter_signatures())):
        print(line)


if __name__ == "__main__":
    main()
