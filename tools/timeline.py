#!/usr/bin/env python
"""Merge per-worker profiler artifacts into one chrome-trace timeline
(tools/timeline.py:160 role: the reference turns per-device Profile
protos into a single chrome trace; here the inputs are the chrome-trace
JSONs the paddle_tpu profiler writes — one per process/worker).

    python tools/timeline.py --out merged.json \
        trainer0=/tmp/profile_t0.json pserver0=/tmp/profile_ps0.json

Each input gets its own pid lane with a process_name metadata row, so a
distributed run's trainers and pservers line up on one timeline in
chrome://tracing / perfetto.
"""

import argparse
import json


def merge(named_paths):
    events = []
    for pid, (name, path) in enumerate(named_paths):
        with open(path) as f:
            data = json.load(f)
        events.append({
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": name},
        })
        for e in data.get("traceEvents", []):
            e = dict(e)
            e["pid"] = pid
            events.append(e)
    return {"traceEvents": events}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("inputs", nargs="+",
                    help="name=path pairs (or bare paths)")
    args = ap.parse_args()
    named = []
    for item in args.inputs:
        if "=" in item:
            name, path = item.split("=", 1)
        else:
            name, path = item, item
        named.append((name, path))
    with open(args.out, "w") as f:
        json.dump(merge(named), f)
    print("wrote %s (%d workers)" % (args.out, len(named)))


if __name__ == "__main__":
    main()
