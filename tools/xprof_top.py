"""Offline xplane-trace analyzer: top ops by device time.

The on-chip attribution step of the MFU plan (docs/PERF.md): run the
bench with `BENCH_PROFILE=/tmp/xprof`, then

    PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python \
        python tools/xprof_top.py /tmp/xprof [-n 20]

No tensorboard server needed — parses the raw `*.xplane.pb` with the
bundled tsl proto (tools/timeline.py's device-side sibling; the
device_tracer.h 'which kernels ate the step' role).
"""

import argparse
import collections
import glob
import os
import sys


def load_xspaces(path):
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    files = sorted(glob.glob(os.path.join(path, "**", "*.xplane.pb"),
                             recursive=True))
    if not files and os.path.isfile(path):
        files = [path]
    spaces = []
    for f in files:
        xs = xplane_pb2.XSpace()
        with open(f, "rb") as fh:
            xs.ParseFromString(fh.read())
        spaces.append((f, xs))
    return spaces


def _plane_totals(plane):
    totals = collections.Counter()
    span_ps = 0
    meta = {m_id: m.name for m_id, m in plane.event_metadata.items()}
    for line in plane.lines:
        for ev in line.events:
            totals[meta.get(ev.metadata_id, "?")] += ev.duration_ps
            span_ps = max(span_ps, ev.offset_ps + ev.duration_ps)
    return totals, span_ps


def device_op_totals(xspace):
    """{op name: total_ps} summed over device-plane lines (XLA ops);
    falls back to the busiest plane when no TPU/GPU plane exists (CPU
    traces)."""
    totals = collections.Counter()
    device_ps = 0
    for plane in xspace.planes:
        name = plane.name.lower()
        if not ("tpu" in name or "/device:" in name or "gpu" in name):
            continue
        t, s = _plane_totals(plane)
        totals.update(t)
        device_ps = max(device_ps, s)
    if not totals:
        best = None
        for plane in xspace.planes:
            t, s = _plane_totals(plane)
            if best is None or sum(t.values()) > sum(best[0].values()):
                best = (t, s, plane.name)
        if best and sum(best[0].values()):
            print("(no device plane; using busiest plane %r)" % best[2])
            return best[0], best[1]
    return totals, device_ps


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("path", help="profile dir (or one .xplane.pb file)")
    ap.add_argument("-n", type=int, default=20, help="top-N ops")
    args = ap.parse_args(argv)

    spaces = load_xspaces(args.path)
    if not spaces:
        print("no *.xplane.pb under %s" % args.path)
        return 1
    for fname, xs in spaces:
        totals, span_ps = device_op_totals(xs)
        if not totals:
            continue
        busy_ps = sum(totals.values())
        print("== %s" % os.path.basename(fname))
        print("device busy %.2f ms over a %.2f ms span (%.0f%% occupancy)"
              % (busy_ps / 1e9, span_ps / 1e9,
                 100.0 * busy_ps / span_ps if span_ps else 0.0))
        width = max(len(n) for n, _ in totals.most_common(args.n))
        for name, ps in totals.most_common(args.n):
            print("  %-*s %9.3f ms  %5.1f%%"
                  % (width, name, ps / 1e9, 100.0 * ps / busy_ps))
    return 0


if __name__ == "__main__":
    sys.exit(main())
