#!/usr/bin/env python
"""Static lint over every model-builder program x pass pipeline.

The CI static-analysis lane (scripts/ci.sh) runs this before the test
lanes: each builder program (train / decode / ragged serving /
dist-transpiled / remat'd / AMP'd / fused / int8) is built, pushed
through its pass pipeline with ``FLAGS_check_program`` armed (so every
``apply_pass`` postcondition fires), and verified with
``analysis.verify_program`` — all without tracing a single op.

    python tools/check_program.py             # full matrix
    python tools/check_program.py -k gpt2     # filter by name
    python tools/check_program.py --fast      # the tier-1 sweep subset

Exit status 1 if any combination reports an error-severity diagnostic.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("FLAGS_check_program", "1")

SEQ = 8


def _fresh():
    import paddle_tpu as fluid
    from paddle_tpu import framework, unique_name

    framework.switch_main_program(fluid.Program())
    framework.switch_startup_program(fluid.Program())
    unique_name.switch()


def _tiny_tfm_hp():
    from paddle_tpu.models import transformer as tfm

    class HP(tfm.ModelHyperParams):
        max_length = 16
        d_model = 16
        d_inner_hid = 32
        n_layer = 2
        n_head = 2
        src_vocab_size = 50
        trg_vocab_size = 50
        fused_attn = True

    return HP


def _tiny_gpt2_hp():
    from paddle_tpu.models import gpt2

    class G(gpt2.GPT2Config):
        vocab_size = 97
        n_ctx = 32
        d_model = 16
        n_layer = 2
        n_head = 2
        dropout = 0.1

    return G


def _mlp():
    import paddle_tpu as fluid
    from paddle_tpu import layers

    x = layers.data(name="x", shape=[8], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(x, size=16, act="relu")
    p = layers.fc(h, size=1)
    loss = layers.reduce_mean(layers.square_error_cost(p, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return fluid.default_main_program(), loss


# ---------------------------------------------------------------------------
# the builder x pipeline matrix; each case returns (program, verify_kwargs)
# ---------------------------------------------------------------------------
def case_mlp_train():
    main, loss = _mlp()
    return main, {"fetches": [loss.name]}


def case_mlp_memory_optimize():
    import paddle_tpu as fluid
    from paddle_tpu import transpiler

    main, loss = _mlp()
    transpiler.apply_pass(main, "memory_optimize_pass")
    return main, {"fetches": [loss.name]}


def case_mlp_dist_trainer():
    import paddle_tpu as fluid

    main, loss = _mlp()
    t = fluid.DistributeTranspiler()
    t.transpile(0, program=main,
                pservers="127.0.0.1:6174,127.0.0.1:6175", trainers=2)
    return t.get_trainer_program(), {"fetches": [loss.name]}


def case_mlp_dist_pserver():
    import paddle_tpu as fluid

    main, _loss = _mlp()
    t = fluid.DistributeTranspiler()
    t.transpile(0, program=main,
                pservers="127.0.0.1:6174,127.0.0.1:6175", trainers=2)
    return t.get_pserver_program("127.0.0.1:6174"), {}


def case_tfm_train():
    from paddle_tpu.models import transformer as tfm

    main, _s, _f, fetches = tfm.wmt_transformer_program(
        _tiny_tfm_hp(), src_len=SEQ, trg_len=SEQ)
    return main, {"fetches": [v.name for v in fetches]}


def case_tfm_amp():
    from paddle_tpu.models import transformer as tfm

    main, _s, _f, fetches = tfm.wmt_transformer_program(
        _tiny_tfm_hp(), src_len=SEQ, trg_len=SEQ, use_bf16=True)
    return main, {"fetches": [v.name for v in fetches]}


def case_tfm_remat():
    from paddle_tpu import flags
    from paddle_tpu.models import transformer as tfm

    flags.set_flags({"hbm_budget_bytes": 200 * 1024})
    try:
        main, _s, _f, fetches = tfm.wmt_transformer_program(
            _tiny_tfm_hp(), src_len=SEQ, trg_len=SEQ)
    finally:
        flags.set_flags({"hbm_budget_bytes": 0})
    return main, {"fetches": [v.name for v in fetches]}


def case_gpt2_train():
    from paddle_tpu.models import gpt2

    main, _s, _f, fetches = gpt2.gpt2_lm_program(_tiny_gpt2_hp(), seq_len=SEQ)
    return main, {"fetches": [v.name for v in fetches]}


def case_gpt2_decode():
    from paddle_tpu.models import gpt2

    out = gpt2.gpt2_decode_step_program(_tiny_gpt2_hp(), batch=2,
                                        t_max=16, width=1)
    return out[0], {}


def case_gpt2_ragged():
    from paddle_tpu.models import gpt2

    out = gpt2.gpt2_ragged_step_program(_tiny_gpt2_hp(), batch=2,
                                        t_max=16, width=4)
    return out[0], {}


def case_gpt2_ragged_tp():
    """The tensor-parallel serving step: the SAME ragged program
    GSPMD-stamped (annotate_spmd changes execution placement only — the
    IR must verify identically to the plain build), with the gpt2
    family rule table resolving every slot-pool persistable to its
    heads-axis spec rather than a logged replicate-fallback."""
    import jax

    from paddle_tpu.models import gpt2
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.partition_rules import (
        annotate_spmd,
        partition_rules_for,
    )

    hp = _tiny_gpt2_hp()
    main, _cs, _f, _fetch, cache_names = gpt2.gpt2_ragged_step_program(
        hp, batch=2, t_max=16, width=4)
    mesh = make_mesh({"mp": -1}, devices=jax.devices())
    rules = partition_rules_for(hp.partition_family, mp_axis="mp")
    annotate_spmd(main, mesh, rules)
    specs, _repl = rules.match_table(
        {n: (2, hp.n_head, 16, hp.d_model // hp.n_head)
         for n in cache_names})
    unruled = [n for n, s in specs.items() if len(s) == 0]
    if unruled:
        raise AssertionError(
            "slot-pool persistables fell through to replication: %s"
            % unruled)
    return main, {}


def case_bert_train():
    from paddle_tpu.models import bert

    class B(bert.BertConfig):
        vocab_size = 97
        d_model = 16
        n_layer = 2
        n_head = 2
        d_inner = 32
        max_pos = 32
        type_vocab = 2

    out = bert.bert_pretrain_program(B, seq_len=SEQ)
    return out[0], {}


def case_resnet_train():
    from paddle_tpu.models import resnet

    out = resnet.build_resnet_train_program(
        batch_size=2, image_shape=(3, 32, 32), class_dim=10, depth=50)
    return out[0], {"fetches": [
        v.name if hasattr(v, "name") else str(v) for v in out[3]]}


def _conv_bn_classifier():
    """conv+BN+relu trunk with an initialized scope — the inference
    pipeline (bn_fold / train prune / int8) needs real weight values."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    img = layers.data(name="img", shape=[3, 8, 8], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    c = layers.conv2d(img, num_filters=4, filter_size=3, padding=1)
    bn = layers.batch_norm(c, act="relu")
    p = layers.fc(layers.flatten(bn), size=10, act="softmax")
    loss = layers.mean(layers.cross_entropy(p, label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
    return fluid.default_main_program(), p, scope


def case_inference_pipeline():
    import paddle_tpu as fluid

    main, pred, scope = _conv_bn_classifier()
    infer = main.clone(for_test=True)
    fluid.InferenceTranspiler().transpile(
        infer, scope=scope, fetches=[pred])
    return infer, {"scope": scope, "fetches": [pred.name]}


def case_int8_pipeline():
    import paddle_tpu as fluid

    main, pred, scope = _conv_bn_classifier()
    infer = main.clone(for_test=True)
    fluid.InferenceTranspiler().transpile(
        infer, scope=scope, fetches=[pred], quantize_int8=True,
        int8_min_elems=4)
    return infer, {"scope": scope, "fetches": [pred.name]}


CASES = [
    ("mlp_train", case_mlp_train, True),
    ("mlp_memory_optimize", case_mlp_memory_optimize, True),
    ("mlp_dist_trainer", case_mlp_dist_trainer, True),
    ("mlp_dist_pserver", case_mlp_dist_pserver, True),
    ("tfm_train_fused", case_tfm_train, False),
    ("tfm_amp", case_tfm_amp, False),
    ("tfm_remat", case_tfm_remat, False),
    ("gpt2_train_fused", case_gpt2_train, False),
    ("gpt2_decode_step", case_gpt2_decode, True),
    ("gpt2_ragged_serving", case_gpt2_ragged, True),
    ("gpt2_ragged_serving_tp", case_gpt2_ragged_tp, True),
    ("bert_train_fused", case_bert_train, False),
    ("resnet_train", case_resnet_train, False),
    ("inference_bn_fold_prune", case_inference_pipeline, False),
    ("inference_weight_int8", case_int8_pipeline, False),
]


def run_matrix(pattern=None, fast=False, quiet=False):
    """Returns (n_checked, n_failed, results) where results maps case
    name -> list of error diagnostics."""
    from paddle_tpu.analysis import verify_program

    results = {}
    n_checked = n_failed = 0
    for name, builder, in_fast in CASES:
        if pattern and pattern not in name:
            continue
        if fast and not in_fast:
            continue
        _fresh()
        try:
            prog, kwargs = builder()
            diags = verify_program(prog, **kwargs)
        except Exception as e:  # build or postcondition failure
            results[name] = ["BUILD/PASS FAILURE: %s: %s"
                             % (type(e).__name__, e)]
            n_checked += 1
            n_failed += 1
            if not quiet:
                print("FAIL  %-26s %s" % (name, results[name][0]))
            continue
        errors = [d for d in diags if d.is_error]
        warnings = len(diags) - len(errors)
        results[name] = [str(d) for d in errors]
        n_checked += 1
        ops = sum(len(b.ops) for b in prog.blocks)
        if errors:
            n_failed += 1
            if not quiet:
                print("FAIL  %-26s %4d ops, %d error(s), %d warning(s)"
                      % (name, ops, len(errors), warnings))
                for d in errors[:6]:
                    print("        %s" % d)
        elif not quiet:
            print("ok    %-26s %4d ops, %d warning(s)"
                  % (name, ops, warnings))
    return n_checked, n_failed, results


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-k", dest="pattern", default=None,
                    help="substring filter on case names")
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 sweep subset (cheap builders only)")
    ap.add_argument("-q", dest="quiet", action="store_true")
    args = ap.parse_args(argv)

    n, failed, _results = run_matrix(args.pattern, args.fast, args.quiet)
    print("check_program: %d/%d combinations verify clean"
          % (n - failed, n))
    return 1 if failed or n == 0 else 0


if __name__ == "__main__":
    sys.exit(main())
