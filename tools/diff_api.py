"""API-stability check (tools/diff_api.py analog): compare the live public
API against a committed snapshot; REMOVED or re-signatured symbols fail
(additions are allowed — the reference's CI contract).

Usage: python tools/diff_api.py API.spec
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_spec(path):
    out = {}
    with open(path) as f:
        for ln in f:
            ln = ln.rstrip("\n")
            if not ln:
                continue
            name, _, sig = ln.partition(" ")
            out[name] = sig
    return out


def main():
    from print_signatures import iter_signatures

    spec_path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "API.spec",
    )
    want = load_spec(spec_path)
    have = {}
    for ln in iter_signatures():
        name, _, sig = ln.partition(" ")
        have[name] = sig
    broken = []
    for name, sig in sorted(want.items()):
        if name not in have:
            broken.append("REMOVED  %s" % name)
        elif have[name] != sig:
            broken.append("CHANGED  %s: %s -> %s" % (name, sig, have[name]))
    if broken:
        print("\n".join(broken))
        print("\n%d public API break(s) vs %s" % (len(broken), spec_path))
        return 1
    added = sorted(set(have) - set(want))
    print("API stable (%d symbols, %d new)" % (len(want), len(added)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
