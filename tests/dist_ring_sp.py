"""Multi-PROCESS sequence parallelism runner: 2 localhost processes x 4
virtual CPU devices bootstrap ``jax.distributed`` (the DCN control plane)
and run ring attention over an sp=8 mesh that SPANS both processes — the
ppermute kv ring actually crosses the process boundary, which is the
multi-host long-context claim (SURVEY §5.7/§5.8) exercised for real
rather than on a single-process virtual mesh.

Prints CHECKS <json> with value/grad checksums; test_dist_train.py
compares them against the single-process dense reference.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

B, H, T, D = 2, 2, 64, 8


def _setup_env():
    """Process env for the runner role — called ONLY under __main__ so
    that the test process can import this module for make_qkv/constants
    without its os.environ being rewritten."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    flags.append("--xla_force_host_platform_device_count=4")
    os.environ["XLA_FLAGS"] = " ".join(flags)


def make_qkv():
    rng = np.random.RandomState(17)
    return [rng.rand(B, H, T, D).astype("float32") for _ in range(3)]


def main():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu import parallel
    from paddle_tpu.parallel import collective

    pid = int(os.environ["PADDLE_TRAINER_ID"])
    nproc = int(os.environ["PADDLE_TRAINERS"])
    collective.init_distributed_env(
        coordinator_address=os.environ["COORDINATOR"],
        num_processes=nproc,
        process_id=pid,
    )
    assert jax.process_count() == nproc
    assert jax.device_count() == 4 * nproc  # 4 local devices per process

    mesh = Mesh(np.array(jax.devices()), ("sp",))
    sharding = NamedSharding(mesh, P(None, None, "sp", None))
    q_np, k_np, v_np = make_qkv()

    def to_global(x):
        # every process holds the same full array; hand jax this
        # process's local shard of the time axis
        per = T // jax.device_count()
        lo = pid * 4 * per
        hi = lo + 4 * per
        return jax.make_array_from_process_local_data(
            sharding, x[:, :, lo:hi, :], x.shape)

    q, k, v = to_global(q_np), to_global(k_np), to_global(v_np)

    def loss(q, k, v):
        out = parallel.ring.ring_attention_sharded(
            q, k, v, mesh, "sp", causal=True)
        return jnp.sum(out ** 2)

    val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))(
        q, k, v)
    gsums = [float(jnp.sum(g ** 2)) for g in grads]
    print("CHECKS " + json.dumps({"val": float(val), "gsums": gsums}),
          flush=True)


if __name__ == "__main__":
    _setup_env()
    main()
