"""Pipeline-parallel training (docs/PERFORMANCE.md §"Pipeline
parallelism"): ``pipeline_program`` slices a built train program into S
stage sub-programs at detect_segments boundaries, drives a GPipe or
1F1B microbatch schedule as one lax.scan inside shard_map over a
dp x pp mesh, and reuses the program's own optimizer slice per stage.

Exactness contract: pp=1 returns the program UNTOUCHED (bit-identical
trajectory); pp>=2 holds rtol<=1e-5 loss parity vs the unpipelined
program over >=5 steps WITH DROPOUT LIVE (the microbatch_rows RNG
window makes per-microbatch masks bit-equal to the full-batch draw);
both schedules agree with each other; ZERO retraces after the first
step.  1F1B's stash is O(S) while GPipe's is O(M) — the activation
report must order them strictly at M > 2S-1.

Structural tests (plan slicing, reports, verifier diagnostics, the
autotune knob) ride the fast suite; everything that compiles a
schedule is @slow and runs in the ci.sh pipeline lane (-m "").
"""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
import paddle_tpu.framework as fw
from paddle_tpu import flags
from paddle_tpu.core import scope as scope_mod
from paddle_tpu.models import gpt2
from paddle_tpu.parallel import make_mesh
from paddle_tpu.transpiler.pipeline import (
    build_pipeline_plan,
    pipeline_activation_report,
    pipeline_program,
    pipeline_state_report,
)

needs_four_devices = pytest.mark.skipif(
    len(jax.devices()) < 4,
    reason="needs XLA_FLAGS=--xla_force_host_platform_device_count>=4")


class TinyHP(gpt2.GPT2Config):
    vocab_size = 64
    n_ctx = 16
    d_model = 32
    n_layer = 2
    n_head = 4
    d_inner = 64
    dropout = 0.1  # LIVE: the parity bar covers the RNG window
    tie_embeddings = False


class SixLayerHP(TinyHP):
    n_layer = 6


def _fresh():
    fw.switch_main_program(fluid.Program())
    fw.switch_startup_program(fluid.Program())
    scope_mod._switch_scope(scope_mod.Scope())


def _build(hp=TinyHP, seq=8, use_bf16=False):
    _fresh()
    return gpt2.gpt2_lm_program(hp, seq_len=seq, lr=3e-3,
                                use_bf16=use_bf16)


def _train(mesh=None, schedule="gpipe", M=4, steps=5, batch=8, seq=8,
           hp=TinyHP, use_bf16=False, extra_flags=None):
    """Fresh scope+programs, `steps` Adam steps on per-step-varying
    fake-LM batches; returns (losses, main, executor)."""
    _fresh()
    old = {k: flags.get_flag(k) for k in (extra_flags or {})}
    flags.set_flags(extra_flags or {})
    try:
        main, startup, feeds, fetches = gpt2.gpt2_lm_program(
            hp, seq_len=seq, lr=3e-3, use_bf16=use_bf16)
        if mesh is not None:
            main = pipeline_program(main, mesh, n_microbatches=M,
                                    schedule=schedule)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = []
        for step in range(steps):
            fb = gpt2.make_fake_lm_batch(batch, seq, hp, seed=step)
            out = exe.run(main, feed=fb, fetch_list=fetches)
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
        return losses, main, exe
    finally:
        flags.set_flags(old)


def _max_rel(a, b):
    return max(abs(x - y) / abs(y) for x, y in zip(a, b))


# ---------------------------------------------------------------------------
# structural: plan slicing (fast suite)
# ---------------------------------------------------------------------------
def test_plan_slices_cover_forward_and_route_hops():
    main, _, feeds, fetches = _build()
    plan = build_pipeline_plan(main, 2, 4, "gpipe")
    assert plan.n_stages == 2 and plan.n_microbatches == 4
    # stage ranges partition the forward region exactly
    assert plan.stage_ranges[0][0] == 0
    assert plan.stage_ranges[-1][1] == plan.fwd_end
    for (a, b), (c, d) in zip(plan.stage_ranges, plan.stage_ranges[1:]):
        assert b == c
    # every cross-stage read resolves through the previous stage's hops
    assert plan.boundary_in[0] == []
    assert set(plan.boundary_in[1]) <= set(plan.boundary_out[0])
    # the loss lives on the last stage
    assert plan.loss_name
    # params partition exactly: no param on two stages, none dropped
    owned = [p for s in range(2) for p in plan.stage_params[s]]
    assert len(owned) == len(set(owned))


def test_plan_balances_by_activation_bytes_not_op_count():
    """A 6-layer model at S=4: the balancer must not put 3 segments on
    one stage just to even out op counts — per-stage state bytes stay
    within the lexicographic (max_act, max_state) optimum, which for
    this model keeps every transformer stage under 40% of the total."""
    main, _, feeds, fetches = _build(hp=SixLayerHP)
    plan = build_pipeline_plan(main, 4, 8, "1f1b")
    rep_state = plan.state_bytes
    total = sum(rep_state)
    assert max(rep_state) / total < 0.40


def test_pipeline_program_pp1_returns_program_untouched():
    main, _, feeds, fetches = _build()
    mesh = make_mesh({"pp": 1}, devices=jax.devices()[:1])
    before_version = main._version
    before_ops = [op.type for op in main.global_block().ops]
    out = pipeline_program(main, mesh, n_microbatches=4)
    assert out is main
    assert getattr(out, "_pipeline", None) is None
    # bit-identical program, bit-identical run: no mutation happened
    assert out._version == before_version
    assert [op.type for op in out.global_block().ops] == before_ops


def test_activation_report_orders_1f1b_strictly_below_gpipe():
    """The whole point of 1F1B: at M=8, S=2 the gpipe stash holds M
    microbatches per stage while 1f1b holds at most 2S-1."""
    main, _, feeds, fetches = _build()
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    main = pipeline_program(main, mesh, n_microbatches=8,
                            schedule="1f1b")
    rep = pipeline_activation_report(main)
    assert rep["1f1b"]["peak_bytes"] < rep["gpipe"]["peak_bytes"]
    # and the ratio reflects O(S) vs O(M): 2S-1=3 copies vs M=8
    assert rep["1f1b"]["peak_bytes"] <= rep["gpipe"]["peak_bytes"] * 0.5


def test_state_report_splits_params_and_opt_state_across_stages():
    main, _, feeds, fetches = _build(hp=SixLayerHP)
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    main = pipeline_program(main, mesh, n_microbatches=8)
    rep = pipeline_state_report(main)
    assert len(rep["per_stage_bytes"]) == 4
    assert sum(rep["per_stage_bytes"]) <= rep["single_device_bytes"]
    # per-device peak strictly below replicating everything everywhere
    assert rep["per_device_peak_bytes"] < rep["single_device_bytes"]
    assert rep["peak_ratio"] < 0.5


# ---------------------------------------------------------------------------
# structural: verifier stage-boundary diagnostics (fast suite)
# ---------------------------------------------------------------------------
def test_pipeline_diagnostics_clean_on_well_formed_slices():
    from paddle_tpu.analysis import pipeline_diagnostics, verify_program

    main, _, feeds, fetches = _build()
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    main = pipeline_program(main, mesh, n_microbatches=4)
    assert not any(d.is_error for d in pipeline_diagnostics(main))
    # verify_program picks the stamp up without being told
    diags = verify_program(main, check_infer=False)
    assert not any(d.code == "pipeline-slice" for d in diags)


def test_mis_sliced_program_yields_golden_stage_boundary_diagnostic():
    """Deliberately break the hop table: dropping a boundary activation
    from stage 0's hop vars must name BOTH the consuming stage and the
    boundary op that can no longer resolve its input."""
    from paddle_tpu.analysis import pipeline_diagnostics

    main, _, feeds, fetches = _build()
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    main = pipeline_program(main, mesh, n_microbatches=4)
    plan = main._pipeline["plan"]
    victim = sorted(plan.boundary_in[1])[0]
    plan.boundary_out[0] = [n for n in plan.boundary_out[0]
                            if n != victim]
    errs = [d for d in pipeline_diagnostics(main) if d.is_error]
    assert errs, "mis-slice must not verify clean"
    golden = [d for d in errs if d.code == "pipeline-slice"
              and victim in d.message and "stage 1" in d.message]
    assert golden, [str(d) for d in errs]
    # locatable: the diagnostic pins the boundary op reading the hop
    assert golden[0].op_idx is not None
    op = main.global_block().ops[golden[0].op_idx]
    assert victim in op.input_arg_names()


def test_foreign_param_read_is_a_pipeline_slice_error():
    from paddle_tpu.analysis import pipeline_diagnostics

    main, _, feeds, fetches = _build()
    plan = build_pipeline_plan(main, 2, 4, "gpipe")
    stolen = sorted(plan.stage_params[1])[0]
    plan.resolution.stage_of_param[stolen] = 0
    errs = [d for d in pipeline_diagnostics(main, plan=plan)
            if d.is_error]
    assert any(stolen in d.message and d.code == "pipeline-slice"
               for d in errs)


# ---------------------------------------------------------------------------
# structural: the autotune knob (fast suite)
# ---------------------------------------------------------------------------
def test_autotune_mesh_candidates_extend_to_pp_axis():
    from paddle_tpu.transpiler import autotune as at

    main, _, feeds, fetches = _build()
    cands = at._candidates_for("mesh_shape", lambda d: None, main)
    pp3 = [c for c in cands if len(c) == 3]
    assert (1, 1, 2) in pp3
    n = len(jax.devices())
    assert all(dp * mp * pp <= n for dp, mp, pp in pp3)


def test_n_microbatches_is_a_consult_only_knob():
    from paddle_tpu.transpiler import autotune as at

    assert at.DEFAULT_DECISION["n_microbatches"] is None
    # never searched: no candidate generator produces values for it
    assert "n_microbatches" not in at._KNOB_ORDER
    assert at.pipeline_knobs(dict(at.DEFAULT_DECISION)) == {}
    d = dict(at.DEFAULT_DECISION, n_microbatches=8)
    assert at.pipeline_knobs(d) == {"n_microbatches": 8}


def test_ci_pinned_pp_decision_consults_without_search():
    """The committed CI cache pins (mesh_shape=(1,1,4), M=8) for the
    BENCH_SPMD_PP probe program: consult-only mode must return it
    verbatim, never timing anything (FLAGS_program_autotune=0 is the
    CI regime)."""
    from paddle_tpu.transpiler import autotune as at
    from paddle_tpu.utils import memory_analysis as ma

    import bench

    if not str(flags.get_flag("program_tune_cache")).endswith(
            "ci_program_tune_cache.json"):
        pytest.skip("pinned program tune cache not configured "
                    "(the ci.sh transpiler lane sets it)")
    _fresh()
    at.clear_cache(forget_path=True)
    try:
        _, probe, _, feeds, _ = bench._pp_bench_program(False, 16)
        spec = ma.program_feed_specs(probe, feeds, batch_hint=8)
        d = at.tune(probe, spec)
        assert d["mesh_shape"] == (1, 1, 4)
        assert at.pipeline_knobs(d) == {"n_microbatches": 8}
        assert at.cache_stats()["stats"]["searches"] == 0
    finally:
        at.clear_cache(forget_path=True)


# ---------------------------------------------------------------------------
# runtime: schedule equivalence (ci.sh pipeline lane, -m "")
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_gpipe_and_1f1b_match_unpipelined_with_dropout_live():
    """The tentpole bar: both schedules == the unpipelined trajectory
    at rtol<=1e-5 over 5 steps with dropout LIVE and a different batch
    every step, and ZERO retraces after the first step (compile_count
    stays at startup+1 across all 5 steps)."""
    base, _, _ = _train()
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    for sched in ("gpipe", "1f1b"):
        losses, _, exe = _train(mesh=mesh, schedule=sched)
        assert _max_rel(losses, base) <= 1e-5, (sched, losses, base)
        assert exe._cache.compile_count == 2, sched


@pytest.mark.slow
@needs_four_devices
def test_dp_times_pp_matches_unpipelined():
    """(dp, pp)=(2, 2): each dp slice runs its own pipeline; the grad
    psum over dp keeps the batch-mean contract."""
    base, _, _ = _train()
    mesh = make_mesh({"dp": 2, "pp": 2}, devices=jax.devices()[:4])
    for sched in ("gpipe", "1f1b"):
        losses, _, exe = _train(mesh=mesh, schedule=sched)
        assert _max_rel(losses, base) <= 1e-5, (sched, losses, base)


@pytest.mark.slow
@needs_four_devices
def test_pp4_six_layers_matches_unpipelined():
    """(dp, pp)=(1, 4) on the 6-layer model — the bench topology."""
    base, _, _ = _train(hp=SixLayerHP, steps=3)
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    losses, main, _ = _train(hp=SixLayerHP, steps=3, mesh=mesh,
                             schedule="1f1b", M=8)
    assert _max_rel(losses, base) <= 1e-5, (losses, base)
    rep = pipeline_state_report(main)
    assert rep["peak_ratio"] < 0.5


@pytest.mark.slow
def test_pp_composes_with_remat_and_bf16_amp():
    """pp x remat x bf16 AMP: the sliced stages carry the recompute
    sub-blocks and the AMP cast chain; bf16 arithmetic widens the
    tolerance but the two programs share it exactly."""
    eflags = {"hbm_budget_bytes": 1 << 20}
    base, main_b, _ = _train(hp=SixLayerHP, steps=3, use_bf16=True,
                             extra_flags=eflags)
    assert any(op.type == "recompute"
               for op in main_b.global_block().ops)
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    losses, main, _ = _train(hp=SixLayerHP, steps=3, mesh=mesh,
                             schedule="1f1b", M=4, use_bf16=True,
                             extra_flags=eflags)
    assert any(op.type == "recompute"
               for op in main.global_block().ops)
    assert _max_rel(losses, base) <= 2e-2, (losses, base)


@pytest.mark.slow
def test_pipeline_state_stays_on_device_between_steps():
    """The packed per-stage buffers are authoritative between flushes:
    param updates persist across steps (losses must DECREASE on a
    fixed batch) and flush_pipeline_state writes them back to scope."""
    from paddle_tpu.transpiler.pipeline import flush_pipeline_state

    _fresh()
    main, startup, feeds, fetches = gpt2.gpt2_lm_program(
        TinyHP, seq_len=8, lr=3e-3)
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    main = pipeline_program(main, mesh, n_microbatches=4)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    fb = gpt2.make_fake_lm_batch(8, 8, TinyHP, seed=0)
    losses = [float(np.asarray(exe.run(main, feed=fb,
                                       fetch_list=fetches)[0]).reshape(-1)[0])
              for _ in range(4)]
    assert losses[-1] < losses[0]
    scope = scope_mod.global_scope()
    plan = main._pipeline["plan"]
    p = sorted(plan.stage_params[0])[0]
    before = np.array(scope.find_var(p))
    flush_pipeline_state(main, scope)
    after = np.array(scope.find_var(p))
    assert not np.allclose(before, after)  # training moved the param
