"""nccl2-mode (multi-host collective DP) runner: 2 localhost processes
bootstrap via ``collective.init_distributed_env`` (the gen_nccl_id_op.cc +
NCCLContextMap re-expression — jax.distributed over DCN) and train a tiny
data-parallel linear model with grad psum over the cross-process axis.

Prints LOSSES <json> so test_dist_train.py can compare against the
single-process full-batch run (test_dist_base.py nccl2-mode parity).
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
# exactly one local CPU device per process (conftest may have forced 8)
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if not f.startswith("--xla_force_host_platform_device_count")
]
_flags.append("--xla_force_host_platform_device_count=1")
os.environ["XLA_FLAGS"] = " ".join(_flags)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.parallel import collective

    pid = int(os.environ["PADDLE_TRAINER_ID"])
    nproc = int(os.environ["PADDLE_TRAINERS"])
    collective.init_distributed_env(
        coordinator_address=os.environ["COORDINATOR"],
        num_processes=nproc,
        process_id=pid,
    )
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.device_count() == nproc  # 1 cpu device per process

    # global batch split across processes: parity target is the LOCAL role
    # training on the full batch with mean loss
    rng = np.random.RandomState(3)
    x = rng.rand(16, 4).astype("float32")
    w_true = np.array([[1.0], [-2.0], [3.0], [0.5]], np.float32)
    y = x @ w_true + 0.1 * rng.rand(16, 1).astype("float32")
    shard = 16 // nproc
    xs, ys = x[pid * shard:(pid + 1) * shard], y[pid * shard:(pid + 1) * shard]

    from functools import partial

    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.parallel.mesh import shard_map

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def step(w, xb, yb):
        # differentiate the GLOBAL loss (psum inside the grad): version-
        # robust — shard_map's autodiff auto-psums cotangents of
        # replicated inputs, so pmean-ing local grads after the fact
        # double-counts (2x grads); putting the collective inside the
        # differentiated function is correct under either semantics
        def global_loss(w):
            contrib = jnp.sum((xb @ w - yb) ** 2) / 16.0
            return collective.all_reduce(contrib, "dp", op="sum")

        loss, g = jax.value_and_grad(global_loss)(w)
        return w - 0.1 * g, loss

    sstep = jax.jit(
        shard_map(
            step,
            mesh=mesh,
            in_specs=(P(), P("dp"), P("dp")),
            out_specs=(P(), P()),
        )
    )
    from jax.sharding import NamedSharding

    # build the [16, 4] GLOBAL arrays from each process's local shard
    # (host_local_array_to_global_array in this jax treats the local value
    # as already-global, silently halving the batch)
    gx = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), xs, (16, 4)
    )
    gy = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), ys, (16, 1)
    )
    w = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P()), np.zeros((4, 1), np.float32), (4, 1)
    )
    if os.environ.get("DIST_DEBUG"):
        print("DEBUG gx.shape=%s xs[0]=%s" % (gx.shape, xs[0]), flush=True)
        probe = jax.jit(
            shard_map(
                lambda xb: (
                    jnp.reshape(jnp.asarray(jax.lax.psum(1, "dp"), jnp.float32), (1,)),
                    jnp.reshape(jnp.mean(xb), (1,)),
                ),
                mesh=mesh,
                in_specs=(P("dp"),),
                out_specs=(P(), P("dp")),
            )
        )
        sz, lm = probe(gx)
        print(
            "DEBUG axis=%s localmean=%s"
            % (
                float(np.asarray(sz.addressable_data(0))[0]),
                float(np.asarray(lm.addressable_data(0))[0]),
            ),
            flush=True,
        )

    losses = []
    for _ in range(int(os.environ.get("DIST_STEPS", "4"))):
        w, lv = sstep(w, gx, gy)
        losses.append(float(np.asarray(lv.addressable_data(0)).reshape(-1)[0]))
    print("LOSSES " + json.dumps(losses), flush=True)


if __name__ == "__main__":
    main()
