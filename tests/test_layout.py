"""NHWC layout pass (transpiler/layout_transpiler.py).

The TPU analog of the reference's data_layout_transform + mkldnn
placement passes (`paddle/fluid/framework/data_layout_transform.*`):
conv trunks rewritten to channels-last with transposes only at the
boundaries, exact-parity with the NCHW program (same math, different
operand layouts).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.transpiler.layout_transpiler import rewrite_nhwc


def _build_trunk(seed=7):
    """conv -> BN(relu) -> maxpool -> conv -> residual add(relu) ->
    global avgpool -> fc(softmax) -> xent loss: every trunk op kind the
    pass handles, ending at a layout-sensitive consumer (fc)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = seed
        img = layers.data("image", shape=[3, 16, 16], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        conv1 = layers.conv2d(input=img, num_filters=8, filter_size=3,
                              stride=1, padding=1, bias_attr=False)
        bn1 = layers.batch_norm(input=conv1, act="relu")
        pool1 = layers.pool2d(bn1, pool_size=2, pool_stride=2, pool_type="max")
        conv2 = layers.conv2d(input=pool1, num_filters=8, filter_size=3,
                              stride=1, padding=1, bias_attr=False)
        bn2 = layers.batch_norm(input=conv2)
        res = layers.elementwise_add(pool1, bn2, act="relu")
        gap = layers.pool2d(res, pool_type="avg", global_pooling=True)
        predict = layers.fc(input=gap, size=10, act="softmax")
        cost = layers.cross_entropy(input=predict, label=label)
        loss = layers.mean(cost)
    return main, startup, loss


def _train(main, startup, loss, steps=3, lr=0.1, minimize=True):
    if minimize:
        with fluid.framework.program_guard(main, startup):
            opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9)
            opt.minimize(loss)
    rng = np.random.RandomState(0)
    x = rng.rand(4, 3, 16, 16).astype("float32")
    y = rng.randint(0, 10, (4, 1)).astype("int64")
    losses = []
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for _ in range(steps):
            (lv,) = exe.run(main, feed={"image": x, "label": y},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
    return losses


def test_nhwc_rewrite_structure():
    main, startup, loss = _build_trunk()
    n = rewrite_nhwc(main)
    blk = main.global_block()
    convs = [op for op in blk.ops if op.type == "conv2d"]
    pools = [op for op in blk.ops if op.type == "pool2d"]
    bns = [op for op in blk.ops if op.type == "batch_norm"]
    assert n == len(convs) + len(pools) + len(bns) == 6
    assert all(op.attrs["data_format"] == "NHWC" for op in convs + pools)
    assert all(op.attrs["data_layout"] == "NHWC" for op in bns)
    # exactly ONE entry transpose (the image) and ONE exit transpose
    # (global-pool output into fc); the trunk itself carries no transposes
    tps = [op for op in blk.ops if op.type == "transpose2"]
    assert len(tps) == 2, [str(op) for op in tps]
    assert tps[0].attrs["axis"] == [0, 2, 3, 1]
    assert tps[-1].attrs["axis"] == [0, 3, 1, 2]
    # alias vars carry the permuted static shape
    conv1_alias = convs[0].outputs["Output"][0]
    assert conv1_alias.endswith("@NHWC")
    assert list(blk.var(conv1_alias).shape)[-1] == 8  # channels minor


def test_nhwc_training_parity():
    """3 momentum steps: NHWC program matches NCHW losses (same math,
    different layout — only reduction-order noise allowed)."""
    ref = _train(*_build_trunk())
    main, startup, loss = _build_trunk()
    rewrite_nhwc(main)
    got = _train(main, startup, loss)
    assert not np.allclose(ref, [ref[0]] * len(ref)), "loss must move"
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-6)


def test_nhwc_plus_bf16_amp_parity():
    """Layout pass then AMP: the inserted transposes are dtype-
    transparent, so the NHWC+bf16 trunk trains at bf16 tolerance of the
    plain f32 NCHW program."""
    from paddle_tpu.contrib.mixed_precision import rewrite_bf16

    ref = _train(*_build_trunk())
    main, startup, loss = _build_trunk()
    rewrite_nhwc(main)
    rewrite_bf16(main)
    got = _train(main, startup, loss)
    np.testing.assert_allclose(got, ref, rtol=5e-2, atol=5e-2)


def test_nhwc_boundary_consumer_gets_nchw():
    """A non-trunk consumer (reshape) of a conv output forces a lazy
    transpose back to the ORIGINAL var name; values match NCHW exactly."""
    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.framework.program_guard(main, startup):
            startup.random_seed = 3
            img = layers.data("image", shape=[3, 8, 8], dtype="float32")
            conv = layers.conv2d(input=img, num_filters=4, filter_size=3,
                                 stride=1, padding=1, bias_attr=False)
            flat = layers.reshape(conv, shape=[0, -1])
            out = layers.reduce_sum(flat, dim=1)
        return main, startup, out

    x = np.random.RandomState(5).rand(2, 3, 8, 8).astype("float32")

    def run(rewrite):
        main, startup, out = build()
        if rewrite:
            rewrite_nhwc(main)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            (v,) = exe.run(main, feed={"image": x}, fetch_list=[out])
        return np.asarray(v)

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6)


def test_nhwc_via_pass_registry():
    from paddle_tpu.transpiler import apply_pass

    main, startup, loss = _build_trunk()
    apply_pass(main, "nhwc_layout_pass")
    assert any(op.type == "transpose2" for op in main.global_block().ops)


def test_depthwise_and_ceil_pool_nhwc_parity():
    """depthwise conv + ceil_mode/exclusive avg pool in NHWC vs NCHW."""
    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.framework.program_guard(main, startup):
            startup.random_seed = 11
            img = layers.data("image", shape=[6, 9, 9], dtype="float32")
            conv = layers.conv2d(input=img, num_filters=6, filter_size=3,
                                 stride=1, padding=1, groups=6,
                                 bias_attr=False)
            pool = layers.pool2d(conv, pool_size=2, pool_stride=2,
                                 pool_type="avg", ceil_mode=True)
            out = layers.reduce_sum(pool)
        return main, startup, out

    x = np.random.RandomState(2).rand(2, 6, 9, 9).astype("float32")

    def run(rewrite):
        main, startup, out = build()
        if rewrite:
            rewrite_nhwc(main)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            (v,) = exe.run(main, feed={"image": x}, fetch_list=[out])
        return np.asarray(v)

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6)


@pytest.mark.slow  # full-train/full-model integration pass (tens of seconds on this 2-core sandbox); rides scripts/ci.sh --full — the fast lane must finish inside tier-1's time budget
def test_nhwc_grouped_conv_se_resnext_parity():
    """The pass generalizes past plain convs: se_resnext's grouped convs
    (cardinality), SE squeeze (global pool -> fc -> scale) and ceil-mode
    pools produce identical losses under NHWC."""
    from paddle_tpu.models.se_resnext import se_resnext

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.framework.program_guard(main, startup):
            startup.random_seed = 17
            img = layers.data("image", shape=[3, 32, 32], dtype="float32")
            label = layers.data("label", shape=[1], dtype="int64")
            # is_test=True: the head dropout otherwise draws a DIFFERENT
            # position-seeded RNG stream in the rewritten program (the
            # inserted transposes shift op indices) — same distribution,
            # but not bit-parity; the layout pass's parity contract is
            # over deterministic programs
            pred = se_resnext(img, class_dim=5, depth=50, is_test=True)
            loss = layers.mean(
                layers.cross_entropy(input=pred, label=label))
        return main, startup, loss

    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 32, 32).astype("float32")
    y = rng.randint(0, 5, (2, 1)).astype("int64")

    def run(rewrite):
        main, startup, loss = build()
        if rewrite:
            n = rewrite_nhwc(main)
            assert n > 30, n  # the deep trunk actually converted
        with fluid.framework.program_guard(main, startup):
            fluid.optimizer.Momentum(0.01, momentum=0.9).minimize(loss)
        scope = fluid.Scope()
        out = []
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            for _ in range(2):
                (lv,) = exe.run(main, feed={"image": x, "label": y},
                                fetch_list=[loss])
                out.append(float(np.asarray(lv).ravel()[0]))
        return out

    np.testing.assert_allclose(run(True), run(False), rtol=2e-5, atol=2e-6)


def test_nhwc_protected_fetch_materialized():
    """ADVICE r4 (low): a trunk intermediate listed in
    program._protected_fetch_names stays materialized in NCHW after
    rewrite_nhwc (same default-closed contract as the fuse passes), even
    when its every consumer was rewired to the @NHWC alias."""
    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.framework.program_guard(main, startup):
            startup.random_seed = 11
            img = layers.data("image", shape=[3, 8, 8], dtype="float32")
            conv = layers.conv2d(input=img, num_filters=4, filter_size=3,
                                 stride=1, padding=1, bias_attr=False)
            act = layers.relu(conv)
            out = layers.reduce_sum(act, dim=[1, 2, 3])
        return main, startup, conv.name, out

    x = np.random.RandomState(2).rand(2, 3, 8, 8).astype("float32")

    def run(rewrite):
        main, startup, conv_name, out = build()
        if rewrite:
            main._protected_fetch_names = {conv_name}
            rewrite_nhwc(main)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            vals = exe.run(main, feed={"image": x},
                           fetch_list=[conv_name, out])
        return [np.asarray(v) for v in vals]

    got, ref = run(True), run(False)
    assert got[0].shape == ref[0].shape  # NCHW, not the NHWC alias
    np.testing.assert_allclose(got[0], ref[0], rtol=1e-6)
    np.testing.assert_allclose(got[1], ref[1], rtol=1e-6)
