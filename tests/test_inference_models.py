"""Real-model inference analyzer tests (analyzer_*_tester.cc role).

The reference validates its inference stack on REAL models with
accuracy + latency checks (inference/tests/api/analyzer_resnet50_tester.cc:25,
analyzer_rnn1_tester.cc): train → save → load through the analysis
pipeline with every fusion pass on → compare against the training-mode
forward and record latency.  Here the same cycle runs on the in-repo
ResNet-50 (models/resnet.py) and Transformer encoder
(models/transformer.py), one leg routed through the C inference ABI
(native/capi.cc), on small shapes so the cycle fits the CPU suite.
"""

import os
import shutil
import subprocess
import sysconfig
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor


def _latency_ms(fn, warmup=1, iters=5):
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters * 1e3


def _op_types(program):
    return [op.type for op in program.global_block().ops]


@pytest.mark.slow  # full-train/full-model integration pass (tens of seconds on this 2-core sandbox); rides scripts/ci.sh --full — the fast lane must finish inside tier-1's time budget
def test_analyzer_resnet50(tmp_path, capsys):
    """analyzer_resnet50_tester.cc:25 cycle on the in-repo ResNet-50:
    2 train steps → save_inference_model → AnalysisConfig (conv+bn fold
    et al on) → output parity vs the training program's for_test clone
    + a latency record."""
    from paddle_tpu.models.resnet import resnet_imagenet

    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        img = layers.data("image", shape=[3, 32, 32])
        label = layers.data("label", shape=[1], dtype="int64")
        predict = resnet_imagenet(img, class_dim=10, depth=50)
        loss = layers.mean(layers.cross_entropy(predict, label))
        test_prog = main.clone(for_test=True)
        fluid.optimizer.Momentum(0.01, momentum=0.9).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 32, 32).astype("float32")
    y = rng.randint(0, 10, (2, 1)).astype("int64")
    for _ in range(2):
        exe.run(main, feed={"image": x, "label": y}, fetch_list=[loss])

    model_dir = str(tmp_path / "resnet50")
    fluid.save_inference_model(model_dir, ["image"], [predict], exe,
                               main_program=main)
    (ref,) = exe.run(test_prog, feed={"image": x}, fetch_list=[predict])

    predictor = create_paddle_predictor(AnalysisConfig(model_dir))
    types = _op_types(predictor.program)
    # conv_bn_fuse_pass folded every inference-mode batch_norm
    assert "batch_norm" not in types, types
    (out,) = predictor.run({"image": x})
    np.testing.assert_allclose(out, np.asarray(ref), rtol=2e-3, atol=1e-5)

    ms = _latency_ms(lambda: predictor.run({"image": x}))
    with capsys.disabled():
        print("\n[analyzer] resnet50 bs2/32px cpu latency %.1f ms/batch "
              "(%d fused ops vs %d trained)" %
              (ms, len(types), len(_op_types(test_prog))))
    assert ms > 0


@pytest.mark.slow  # full-train/full-model integration pass (tens of seconds on this 2-core sandbox); rides scripts/ci.sh --full — the fast lane must finish inside tier-1's time budget
def test_analyzer_resnet50_c_abi(tmp_path):
    """The same saved ResNet-50 served from C through the inference ABI
    (inference/capi demo_ci role): outputs must match the Python
    AnalysisConfig predictor on the identical feed."""
    from paddle_tpu.models.resnet import resnet_imagenet

    native_dir = os.path.join(os.path.dirname(fluid.__file__), "native")
    py_h = os.path.join(sysconfig.get_paths()["include"], "Python.h")
    if (shutil.which("g++") is None or shutil.which("make") is None
            or not os.path.exists(py_h)):
        pytest.skip("no C++ toolchain / Python headers")
    subprocess.run(["make", "capi_demo"], cwd=native_dir, check=True,
                   capture_output=True)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        img = layers.data("image", shape=[3, 16, 16])
        predict = resnet_imagenet(img, class_dim=4, depth=50, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    model_dir = str(tmp_path / "resnet50_capi")
    fluid.save_inference_model(model_dir, ["image"], [predict], exe,
                               main_program=main, scope=scope)

    x = np.ones((1, 3, 16, 16), "float32")
    predictor = create_paddle_predictor(AnalysisConfig(model_dir))
    (ref,) = predictor.run({"image": x})

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [os.path.join(native_dir, "capi_demo"),
         os.path.dirname(os.path.dirname(fluid.__file__)),
         model_dir, "image", "4", "1", "3", "16", "16"],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "CAPI_OK" in proc.stdout
    line = [l for l in proc.stdout.splitlines() if "first=" in l][0]
    got = [float(v) for v in line.split("first=[")[1].rstrip("]").split(",")]
    np.testing.assert_allclose(got, np.asarray(ref)[0][:4], rtol=1e-3,
                               atol=1e-5)


@pytest.mark.slow  # full-train/full-model integration pass (tens of seconds on this 2-core sandbox); rides scripts/ci.sh --full — the fast lane must finish inside tier-1's time budget
def test_analyzer_transformer_encoder(tmp_path, capsys):
    """Transformer-encoder analyzer cycle (analyzer_* role for the
    attention stack): train a 2-layer encoder classifier, save, load via
    AnalysisConfig — attention_fuse_pass must collapse each encoder
    layer's attention into ONE fused_attention op — and match the
    training program's for_test clone, with a latency record."""
    from paddle_tpu.models.transformer import (
        ModelHyperParams,
        encoder_layer,
        prepare_embedding,
    )

    class TinyHP(ModelHyperParams):
        src_vocab_size = 128
        max_length = 32
        d_model = 32
        d_inner_hid = 64
        n_head = 4
        n_layer = 2
        dropout = 0.1

    T = 16
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        with fluid.unique_name.guard():
            ids = layers.data("src_ids", shape=[T], dtype="int64")
            # rank-1 key-padding bias [B, 1, 1, Tk] — the fusable mask
            # pattern (attention_fuse_pass leaves dense [B,1,Tq,Tk] alone)
            bias = layers.data("src_bias", shape=[1, 1, T])
            label = layers.data("label", shape=[1], dtype="int64")
            x = prepare_embedding(
                ids, TinyHP.src_vocab_size, TinyHP.d_model, TinyHP.max_length,
                TinyHP.dropout, "src_pos_enc_table")
            for _ in range(TinyHP.n_layer):
                x = encoder_layer(x, bias, TinyHP)
            pooled = layers.reduce_mean(x, dim=1)
            pred = layers.fc(pooled, size=4, act="softmax")
            loss = layers.mean(layers.cross_entropy(pred, label))
            test_prog = main.clone(for_test=True)
            fluid.optimizer.Adam(1e-3).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(1)
    ids_np = rng.randint(1, TinyHP.src_vocab_size, (4, T)).astype("int64")
    bias_np = np.zeros((4, 1, 1, T), "float32")
    bias_np[:, :, :, -2:] = -1e9  # pad out the last two key slots
    label_np = rng.randint(0, 4, (4, 1)).astype("int64")
    for _ in range(3):
        exe.run(main, feed={"src_ids": ids_np, "src_bias": bias_np,
                            "label": label_np}, fetch_list=[loss])

    model_dir = str(tmp_path / "tfm_encoder")
    fluid.save_inference_model(model_dir, ["src_ids", "src_bias"], [pred],
                               exe, main_program=main)
    (ref,) = exe.run(test_prog, feed={"src_ids": ids_np,
                                      "src_bias": bias_np},
                     fetch_list=[pred])

    predictor = create_paddle_predictor(AnalysisConfig(model_dir))
    types = _op_types(predictor.program)
    assert types.count("fused_attention") == TinyHP.n_layer, types
    (out,) = predictor.run({"src_ids": ids_np, "src_bias": bias_np})
    np.testing.assert_allclose(out, np.asarray(ref), rtol=2e-4, atol=1e-6)

    ms = _latency_ms(
        lambda: predictor.run({"src_ids": ids_np, "src_bias": bias_np}))
    with capsys.disabled():
        print("\n[analyzer] transformer-encoder bs4/T16 cpu latency "
              "%.1f ms/batch (fused_attention x%d)" %
              (ms, types.count("fused_attention")))
    assert ms > 0
