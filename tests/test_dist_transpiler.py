"""DistributeTranspiler unit tests (test_dist_transpiler.py analog):
assert the exact op rewrite of trainer/pserver programs — legacy
per-variable AND bucketed paths — plus in-process E2E parity and the
deterministic comm-counter evidence for the bucketing work."""

import socket
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.transpiler.distribute_transpiler import (
    pack_buckets,
    slice_variable,
)


def _build(optimizer=None):
    x = layers.data("x", shape=[16])
    y = layers.data("y", shape=[1])
    pred = layers.fc(x, size=4)
    loss = layers.mean(layers.square_error_cost(pred, y))
    (optimizer or fluid.optimizer.SGD(0.1)).minimize(loss)
    return loss


def _transpile(trainer_id=0, eps="127.0.0.1:6174,127.0.0.1:6175", **cfg_kw):
    config = fluid.DistributeTranspilerConfig()
    config.min_block_size = 4
    for k, v in cfg_kw.items():
        setattr(config, k, v)
    t = fluid.DistributeTranspiler(config=config)
    t.transpile(
        trainer_id,
        program=fluid.default_main_program(),
        pservers=eps,
        trainers=2,
        sync_mode=True,
    )
    return t


def test_slice_variable():
    blocks = slice_variable([("w", 100)], 3, min_block_size=10)["w"]
    assert sum(b.size for b in blocks) == 100
    assert len(blocks) == 3
    assert blocks[0].begin == 0 and blocks[-1].end == 100
    # below min size: single block
    blocks = slice_variable([("b", 8)], 3, min_block_size=10)["b"]
    assert len(blocks) == 1 and blocks[0].size == 8


def test_trainer_program_rewrite():
    _build()
    # comm_bucket_bytes=0: the legacy per-variable rpc tail, still
    # selectable (and still the wire contract fallback)
    t = _transpile(comm_bucket_bytes=0)
    prog = t.get_trainer_program()
    types = [op.type for op in prog.global_block().ops]
    # optimizer ops moved off the trainer
    assert "sgd" not in types
    # rpc tail: scale+send per grad, one send_barrier, recv per param,
    # one fetch_barrier, in that order
    assert types.count("send") == 2  # fc w + b
    assert types.count("recv") == 2
    assert types.count("send_barrier") == 1
    assert types.count("fetch_barrier") == 1
    assert types.index("send_barrier") > max(
        i for i, t_ in enumerate(types) if t_ == "send"
    )
    assert types.index("fetch_barrier") > max(
        i for i, t_ in enumerate(types) if t_ == "recv"
    )
    # every rpc op is tagged with the rpc role
    for op in prog.global_block().ops:
        if op.type in ("send", "recv", "send_barrier", "fetch_barrier"):
            assert op.attrs["op_role"] == "rpc"


def test_pserver_program_shards():
    _build()
    t = _transpile()
    eps = t.pserver_endpoints
    progs = [t.get_pserver_program(ep) for ep in eps]
    ops = [p.global_block().ops[0] for p in progs]
    assert all(op.type == "listen_and_serv" for op in ops)
    # the fc weight (16*4=64 elems) splits across both servers
    n_shards = [len(op.attrs["optimize_programs"]) for op in ops]
    assert sum(n_shards) >= 3  # w split in 2 + bias
    assert all(n >= 1 for n in n_shards)
    # slice plans reconstruct full params exactly
    total = {}
    for op in ops:
        for src, blk, b, e in op.attrs["slice_plan"]:
            total.setdefault(src, []).append((b, e))
    w_ranges = sorted(total["fc_0.w_0"])
    assert w_ranges[0][0] == 0 and w_ranges[-1][1] == 64
    for (b1, e1), (b2, e2) in zip(w_ranges, w_ranges[1:]):
        assert e1 == b2


def test_adam_accumulators_sliced():
    _build(fluid.optimizer.Adam(0.01))
    t = _transpile()
    import json

    found_moment_slice = False
    for ep in t.pserver_endpoints:
        op = t.get_pserver_program(ep).global_block().ops[0]
        for sp_json in op.attrs["optimize_programs"]:
            sp = fluid.Program.from_json(sp_json)
            adam = sp.global_block().ops[0]
            assert adam.type == "adam"
            for slot in ("Moment1", "Moment2"):
                n = adam.inputs[slot][0]
                if ".block" in n:
                    found_moment_slice = True
    assert found_moment_slice


def test_trainer_program_rewrite_bucketed():
    """Default (bucketed) rpc tail: scale per grad, then ONE send_bucket
    and ONE recv_bucket — the barriers are folded into the bucket stream
    (sync_totals / fetch_totals), so no dedicated barrier ops remain."""
    _build()
    t = _transpile()  # comm_bucket_bytes defaults to the 4 MiB flag
    prog = t.get_trainer_program()
    types = [op.type for op in prog.global_block().ops]
    assert "sgd" not in types
    assert types.count("send_bucket") == 1
    assert types.count("recv_bucket") == 1
    assert "send" not in types and "recv" not in types
    assert "send_barrier" not in types and "fetch_barrier" not in types
    assert types.index("send_bucket") < types.index("recv_bucket")
    ops = {op.type: op for op in prog.global_block().ops}
    send, recv = ops["send_bucket"], ops["recv_bucket"]
    assert send.attrs["op_role"] == "rpc"
    assert recv.attrs["op_role"] == "rpc"
    # one bucket per endpoint at the 4 MiB default for this tiny model,
    # and the folded-barrier totals agree with the plan
    eps = t.pserver_endpoints
    send_eps = [ep for ep, _ in send.attrs["buckets"]]
    assert sorted(set(send_eps)) == sorted(eps)
    for ep in eps:
        assert send.attrs["sync_totals"][ep] == send_eps.count(ep)
    recv_eps = [ep for ep, _ in recv.attrs["buckets"]]
    for ep in eps:
        assert recv.attrs["fetch_totals"][ep] == recv_eps.count(ep)
    # every grad block appears in exactly one send bucket; every param
    # block in exactly one recv bucket, and reassembly covers each param
    sent = [bn for _, entries in send.attrs["buckets"]
            for _, _, _, bn in entries]
    assert len(sent) == len(set(sent))
    got = [n for _, names in recv.attrs["buckets"] for n in names]
    spec_blocks = [bn for _, _, _, bnames in recv.attrs["params"]
                   for bn in bnames]
    assert sorted(got) == sorted(spec_blocks)
    assert [p for p, *_ in recv.attrs["params"]] == recv.outputs["Out"]


def test_pack_buckets_caps_and_orders():
    entries = [(10, "a"), (10, "b"), (10, "c"), (25, "d"), (10, "e")]
    out = pack_buckets(entries, 20)
    assert out == [["a", "b"], ["c"], ["d"], ["e"]]
    # an oversized single entry still ships (its own bucket)
    assert pack_buckets([(100, "x")], 20) == [["x"]]
    assert pack_buckets([], 20) == []


def test_bucket_cap_splits_into_multiple_buckets():
    """A tiny byte cap forces several buckets per endpoint; totals and
    coverage stay consistent."""
    _build()
    t = _transpile(comm_bucket_bytes=32)  # 8 floats per bucket
    prog = t.get_trainer_program()
    ops = {op.type: op for op in prog.global_block().ops}
    send = ops["send_bucket"]
    per_ep = {}
    for ep, entries in send.attrs["buckets"]:
        per_ep[ep] = per_ep.get(ep, 0) + 1
        assert sum(e - b for _, b, e, _ in entries) * 4 <= 32 or \
            len(entries) == 1
    assert max(per_ep.values()) > 1
    for ep, n in per_ep.items():
        assert send.attrs["sync_totals"][ep] == n


def test_size_weighted_dispatcher_balances_uneven_params():
    """Satellite: SizeWeighted spreads a skewed model by bytes, where
    RoundRobin striping can pile every co-indexed block onto the same
    server; RoundRobin/HashName remain selectable."""
    from paddle_tpu.transpiler.ps_dispatcher import (
        HashName, RoundRobin, SizeWeighted)

    eps = ["ep0", "ep1"]

    class Blk:
        def __init__(self, name, size):
            self.block_name = name
            self.size = size

    big = [Blk("w%d.block0" % i, 100) for i in range(2)]
    small = [Blk("b%d.block0" % i, 1) for i in range(6)]
    sw = SizeWeighted(eps)
    placed = {}
    for blk in [big[0]] + small[:3] + [big[1]] + small[3:]:
        placed[blk.block_name] = sw.dispatch([blk])[0]
    load = {ep: 0 for ep in eps}
    for blk in big + small:
        load[placed[blk.block_name]] += blk.size
    assert abs(load["ep0"] - load["ep1"]) <= 2, load
    # RoundRobin on the same order piles both big blocks unevenly
    rr = RoundRobin(eps)
    rr_placed = {}
    for blk in [big[0]] + small[:3] + [big[1]] + small[3:]:
        rr_placed[blk.block_name] = rr.dispatch([blk])[0]
    rr_load = {ep: 0 for ep in eps}
    for blk in big + small:
        rr_load[rr_placed[blk.block_name]] += blk.size
    assert abs(rr_load["ep0"] - rr_load["ep1"]) > 2, rr_load
    # HashName hashes the stable block NAME (never the repr/address) so
    # every process plans the same placement
    hn = HashName(eps)
    assert hn.dispatch(big) == hn.dispatch(big)
    assert hn.dispatch([big[0]])[0] == hn.dispatch(
        [Blk("w0.block0", 999)])[0]


# ---------------------------------------------------------------------------
# in-process E2E: bucketed vs legacy parity + deterministic comm counters
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def no_heartbeats():
    """Silence the liveness sender for in-process clusters — and restore
    the flag afterwards so later tests keep the default behavior."""
    from paddle_tpu.flags import get_flag, set_flags

    prev = get_flag("heartbeat_interval")
    set_flags({"heartbeat_interval": 0})
    yield
    set_flags({"heartbeat_interval": prev})


def _make_optimizer(kind):
    if kind == "sgd":
        return fluid.optimizer.SGD(0.1)
    if kind == "momentum":
        return fluid.optimizer.Momentum(0.05, momentum=0.9)
    if kind == "adagrad":
        return fluid.optimizer.Adagrad(0.1)
    if kind == "adam":
        return fluid.optimizer.Adam(0.01)
    raise ValueError(kind)


def _run_inprocess_cluster(bucket_bytes, steps=3, n_pservers=2,
                           wire_dtype="float32", grad_int8=False,
                           hidden=8, optimizer="sgd"):
    """Build the 4-param MLP, transpile for `n_pservers` in-process
    VarServer threads, train `steps` sync steps, return (losses,
    comm_stats, transpiler).  `wire_dtype`/`grad_int8` pin the wire
    compression per run (config beats the flag), so the bit-exact
    legacy-parity assertion stays meaningful under a compressed-wire CI
    pass (scripts/ci.sh FLAGS_comm_wire_dtype=bfloat16)."""
    from paddle_tpu import framework, unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.distributed import rpc
    from paddle_tpu.ops import dist_ops

    # two cluster runs share one test: each needs virgin default programs
    framework.switch_main_program(fluid.Program())
    framework.switch_startup_program(fluid.Program())
    unique_name.switch()
    main = fluid.default_main_program()
    startup = fluid.default_startup_program()
    main.random_seed = 7
    startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.data("y", shape=[1])
        h = layers.fc(x, size=hidden, act="relu")
        # per-param lr exercises the optimize-role `scale` chain the
        # fused-apply analyzer folds into a factor
        pred = layers.fc(h, size=1,
                         param_attr=fluid.ParamAttr(learning_rate=0.5))
        loss = layers.mean(layers.square_error_cost(pred, y))
        _make_optimizer(optimizer).minimize(loss)
    config = fluid.DistributeTranspilerConfig()
    config.min_block_size = 4
    config.comm_bucket_bytes = bucket_bytes
    config.comm_wire_dtype = wire_dtype
    config.comm_grad_int8 = grad_int8
    t = fluid.DistributeTranspiler(config=config)
    eps = ["127.0.0.1:%d" % _free_port() for _ in range(n_pservers)]
    t.transpile(0, program=main, pservers=",".join(eps), trainers=1,
                sync_mode=True, startup_program=startup)
    dist_ops.reset_fences()  # fresh fence + error-feedback state per run
    threads = []
    for ep in eps:
        psprog = t.get_pserver_program(ep)
        pstart = t.get_startup_program(ep, psprog)
        scope = Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(pstart, scope=scope)
        th = threading.Thread(target=exe.run, args=(psprog,),
                              kwargs={"scope": scope}, daemon=True)
        th.start()
        threads.append(th)
    rpc.reset_comm_stats()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(3)
    xv = rng.rand(16, 4).astype("float32")
    w = np.array([[1.0], [-2.0], [3.0], [0.5]], dtype=np.float32)
    yv = xv @ w + 0.1 * rng.rand(16, 1).astype("float32")
    losses = []
    for _ in range(steps):
        (lv,) = exe.run(program=main, feed={"x": xv, "y": yv},
                        fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    stats = rpc.get_comm_stats()
    exe.close()
    for th in threads:
        th.join(timeout=30)
    assert all(not th.is_alive() for th in threads), "pserver thread hung"
    rpc.RPCClient.reset_all()
    return losses, stats, t


def test_bucketed_e2e_matches_legacy_and_cuts_round_trips(no_heartbeats):
    """THE acceptance evidence, threshold-free: the bucketed sync run
    produces bit-identical losses to the legacy per-variable path, its
    round-trip count is exactly what the bucket plan predicts (steps x
    (send buckets + get buckets) + completes), and the reduction vs the
    legacy plan is >= 4x for the dist MLP workload."""
    steps = 3
    bucketed, sb, tb = _run_inprocess_cluster(4 << 20, steps=steps)
    legacy, sl, tl = _run_inprocess_cluster(0, steps=steps)
    np.testing.assert_allclose(bucketed, legacy, rtol=1e-6, atol=1e-7)

    n_send = len(tb.send_bucket_plan)
    n_recv = len(tb.recv_bucket_plan)
    n_eps = len(tb.pserver_endpoints)
    # folded barriers: a sync step is exactly the bucket frames (stats
    # snapshot before close(), so completes are not in the count)
    assert sb["rpc_round_trips"] == steps * (n_send + n_recv), sb
    # legacy: one round trip per grad/param block + 2 barriers per ep
    blocks = sum(len(blks) for blks in tl.param_blocks.values())
    assert sl["rpc_round_trips"] == \
        steps * (2 * blocks + 2 * n_eps), (sl, blocks)
    assert sl["rpc_round_trips"] >= 4 * sb["rpc_round_trips"], (sl, sb)
    # coalescing also cuts framing bytes, not just frame count
    assert sb["comm_bytes_sent"] < sl["comm_bytes_sent"]


@pytest.mark.slow  # tier-1 runs at the edge of its time budget; this
# rides scripts/ci.sh's compressed-wire pass (-m "") and --full instead
def test_bf16_wire_parity_within_tolerance_and_bytes_cut(no_heartbeats):
    """Wire compression acceptance: the SAME workload over a bfloat16
    wire stays within bf16 rounding of the float32 run (grads and
    fetched params round to 8 mantissa bits; server state stays f32)
    and ships >= 40% fewer bytes per step — the counters are plan
    properties, so the reduction asserts exactly, no wall clock."""
    steps = 3
    # wide enough that array payloads dominate framing (the tiny default
    # model is envelope-bound and no wire dtype could cut 40% there)
    f32, s32, _t = _run_inprocess_cluster(4 << 20, steps=steps,
                                          hidden=512)
    bf, sbf, tb = _run_inprocess_cluster(4 << 20, steps=steps,
                                         wire_dtype="bfloat16",
                                         hidden=512)
    assert np.isfinite(bf).all()
    np.testing.assert_allclose(bf, f32, rtol=0.05, atol=1e-3)
    # the acceptance threshold: >= 40% fewer bytes on the wire
    assert sbf["comm_bytes_sent"] <= 0.6 * s32["comm_bytes_sent"], \
        (sbf["comm_bytes_sent"], s32["comm_bytes_sent"])
    assert sbf["comm_bytes_recv"] < s32["comm_bytes_recv"]
    assert sbf["comm_bytes_saved"] > 0 and s32["comm_bytes_saved"] == 0
    # same round-trip count: compression changes bytes, never the plan
    assert sbf["rpc_round_trips"] == s32["rpc_round_trips"]
    assert tb.comm_wire_dtype == "bfloat16"
    # the COUNTERS tag reflects the PLANNED wire (the config override),
    # not the untouched global flag (still float32 here)
    assert sbf["wire_dtype"] == "bfloat16", sbf
    assert s32["wire_dtype"] == "float32", s32


@pytest.mark.slow  # see test_bf16_wire_parity_within_tolerance_and_bytes_cut
def test_int8_error_feedback_wire_tracks_f32(no_heartbeats):
    """FLAGS_comm_grad_int8: dense grads ship as int8 + per-block scale
    with the quantization residual kept trainer-side and folded into
    the next round (error feedback) — the loss must track the f32 run
    and the grad leg of the wire shrinks to ~1/4."""
    steps = 4
    f32, s32, _t = _run_inprocess_cluster(4 << 20, steps=steps)
    i8, si8, _t8 = _run_inprocess_cluster(4 << 20, steps=steps,
                                          grad_int8=True)
    assert np.isfinite(i8).all()
    np.testing.assert_allclose(i8, f32, rtol=0.2, atol=5e-2)
    assert si8["comm_bytes_sent"] < s32["comm_bytes_sent"]
    assert si8["comm_bytes_saved"] > 0
    from paddle_tpu.ops.dist_ops import _ef_residuals

    assert _ef_residuals, "error-feedback residuals never recorded"


@pytest.mark.slow  # see test_bf16_wire_parity_within_tolerance_and_bytes_cut
@pytest.mark.parametrize("optimizer",
                         ["sgd", "momentum", "adagrad", "adam"])
def test_fused_apply_matches_per_block_executor(no_heartbeats, optimizer):
    """FLAGS_ps_fused_apply: the jitted stacked update must be
    BIT-identical to the per-block executor programs it replaces — the
    rules are the same elementwise math, so fused on/off may not differ
    in a single float.  Parametrized over every fusable rule, with a
    per-param lr so the scale-chain factor fold and (for adam) the
    beta-pow scalar-slot write-back are all under the == assertion."""
    from paddle_tpu.flags import get_flag, set_flags

    steps = 3
    fused, sf, _ = _run_inprocess_cluster(4 << 20, steps=steps,
                                          optimizer=optimizer)
    prev = get_flag("ps_fused_apply")
    set_flags({"ps_fused_apply": 0})
    try:
        legacy, sl, _ = _run_inprocess_cluster(4 << 20, steps=steps,
                                               optimizer=optimizer)
    finally:
        set_flags({"ps_fused_apply": prev})
    assert fused == legacy, (optimizer, fused, legacy)
    # identical wire too: fusion is a server-side dispatch change only
    assert sf["comm_bytes_sent"] == sl["comm_bytes_sent"]
    assert sf["rpc_round_trips"] == sl["rpc_round_trips"]


def test_zero_block_pserver_gets_empty_bucket_and_terminates(no_heartbeats):
    """A pserver that receives no blocks (fewer blocks than servers)
    still gets an EMPTY bucket in both plans: it participates in every
    round via the folded barriers, is registered for complete at close,
    and its serve loop terminates instead of waiting forever."""
    from paddle_tpu import framework, unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.distributed import rpc

    framework.switch_main_program(fluid.Program())
    framework.switch_startup_program(fluid.Program())
    unique_name.switch()
    main = fluid.default_main_program()
    startup = fluid.default_startup_program()
    main.random_seed = 7
    startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2])
        y = layers.data("y", shape=[1])
        pred = layers.fc(x, size=1, bias_attr=False)  # ONE tiny param
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    config = fluid.DistributeTranspilerConfig()
    config.min_block_size = 4  # w has 2 elems -> a single block
    t = fluid.DistributeTranspiler(config=config)
    eps = ["127.0.0.1:%d" % _free_port() for _ in range(2)]
    t.transpile(0, program=main, pservers=",".join(eps), trainers=1,
                sync_mode=True, startup_program=startup)
    # exactly one endpoint got the block; the other got an empty bucket
    loaded = {ep: sum(len(entries) for pep, entries in t.send_bucket_plan
                      if pep == ep)
              for ep, _entries in t.send_bucket_plan}
    assert sorted(loaded.values()) == [0, 1], t.send_bucket_plan
    assert {ep for ep, _ in t.send_bucket_plan} == set(eps)
    assert {ep for ep, _ in t.recv_bucket_plan} == set(eps)
    threads = []
    for ep in eps:
        psprog = t.get_pserver_program(ep)
        pstart = t.get_startup_program(ep, psprog)
        scope = Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(pstart, scope=scope)
        th = threading.Thread(target=exe.run, args=(psprog,),
                              kwargs={"scope": scope}, daemon=True)
        th.start()
        threads.append(th)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.random.RandomState(0).rand(8, 2).astype("float32")
    yv = (xv @ np.array([[1.0], [2.0]], np.float32))
    for _ in range(2):
        exe.run(program=main, feed={"x": xv, "y": yv}, fetch_list=[loss])
    exe.close()
    for th in threads:
        th.join(timeout=30)
    # THE assertion: the zero-block pserver's serve loop exited too
    assert all(not th.is_alive() for th in threads), \
        "zero-block pserver never terminated"
    rpc.RPCClient.reset_all()


# ---------------------------------------------------------------------------
# collective dense-gradient backend (DistributeTranspiler mode="collective")
# ---------------------------------------------------------------------------

def _fresh_mlp(hidden=8, seed=7):
    """Fresh default programs + the 4-param MLP (same architecture as
    _run_inprocess_cluster) — several runs share one test, each needs
    virgin programs."""
    from paddle_tpu import framework, unique_name

    framework.switch_main_program(fluid.Program())
    framework.switch_startup_program(fluid.Program())
    unique_name.switch()
    main = fluid.default_main_program()
    startup = fluid.default_startup_program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.data("y", shape=[1])
        h = layers.fc(x, size=hidden, act="relu")
        pred = layers.fc(h, size=1,
                         param_attr=fluid.ParamAttr(learning_rate=0.5))
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _mlp_data():
    rng = np.random.RandomState(3)
    xv = rng.rand(16, 4).astype("float32")
    w = np.array([[1.0], [-2.0], [3.0], [0.5]], dtype=np.float32)
    yv = xv @ w + 0.1 * rng.rand(16, 1).astype("float32")
    return xv, yv


def test_collective_trainer_program_rewrite():
    """mode="collective": ONE c_allreduce_mean per dense grad lands
    between the backward and the optimizer ops — which STAY on the
    trainer — and no pserver rpc op survives anywhere in the program."""
    _build()
    t = _transpile(mode="collective")
    prog = t.get_trainer_program()
    ops = prog.global_block().ops
    types = [op.type for op in ops]
    assert types.count("c_allreduce_mean") == 2  # fc w + b
    assert "sgd" in types  # the optimizer never leaves the trainer
    for rpc_ty in ("send", "recv", "send_bucket", "recv_bucket",
                   "send_barrier", "fetch_barrier", "scale"):
        assert rpc_ty not in types, rpc_ty
    first_opt = min(i for i, op in enumerate(ops)
                    if op.attrs.get("op_role") == "optimize")
    for i, op in enumerate(ops):
        if op.type != "c_allreduce_mean":
            continue
        assert i < first_opt
        # in-place on the grad: optimizer reads the allreduced value
        assert op.inputs["X"] == op.outputs["Out"]
        assert op.attrs["axis_name"] == "dp"
        assert op.attrs["nranks"] == 2
        assert op.attrs["op_role"] == "backward"
    # the executor keys its mesh run path off the program marker
    assert prog._collective == {"axis": "dp", "nranks": 2}


def _run_collective_mlp(t, main, startup, loss, xv, yv, steps):
    from paddle_tpu.core.scope import Scope

    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    losses = []
    for _ in range(steps):
        (lv,) = exe.run(program=t.get_trainer_program(),
                        feed={"x": xv, "y": yv}, fetch_list=[loss],
                        scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses


def test_collective_mode_bit_exact_vs_single_process_baseline():
    """THE collective acceptance evidence: (1) with every mesh replica
    fed the SAME batch, pmean of identical grads is IEEE-exact, so the
    2-device collective trajectory must be BIT-identical to the
    single-process baseline; (2) the sharded-batch run (the real DP
    deployment) matches to reduction-order tolerance; (3) the comm
    counters prove ZERO rpc round trips — dense grads never leave the
    compiled step."""
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.distributed import rpc

    steps = 3
    xv, yv = _mlp_data()
    # single-process full-batch baseline
    main, startup, loss = _fresh_mlp()
    scope = Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    base = []
    for _ in range(steps):
        (lv,) = exe.run(program=main, feed={"x": xv, "y": yv},
                        fetch_list=[loss], scope=scope)
        base.append(float(np.asarray(lv).reshape(-1)[0]))

    def transpiled():
        main, startup, loss = _fresh_mlp()
        config = fluid.DistributeTranspilerConfig()
        config.mode = "collective"
        config.min_block_size = 4
        t = fluid.DistributeTranspiler(config=config)
        t.transpile(0, program=main, pservers="", trainers=2,
                    sync_mode=True, startup_program=startup)
        return t, main, startup, loss

    rpc.reset_comm_stats()
    # replicated batch: each of the 2 replicas sees the full baseline
    # batch; (g+g)/2 == g exactly in IEEE f32 -> bit-exact trajectory
    t, main, startup, loss = transpiled()
    repl = _run_collective_mlp(
        t, main, startup, loss,
        np.concatenate([xv, xv]), np.concatenate([yv, yv]), steps)
    assert repl == base, (repl, base)
    # sharded batch (half per replica): global-mean loss and pmean'd
    # grads equal the baseline up to float reduction order
    t, main, startup, loss = transpiled()
    shard = _run_collective_mlp(t, main, startup, loss, xv, yv, steps)
    np.testing.assert_allclose(shard, base, rtol=1e-5, atol=1e-7)
    # zero-RPC acceptance: no pserver round trips of ANY kind
    stats = rpc.get_comm_stats()
    assert stats["rpc_round_trips"] == 0, stats
    assert stats["rpc_verbs"] == {}, stats


def _run_sparse_cluster(mode, nranks, steps=4, wire_dtype="float32",
                        sync=True, feed_ids=None):
    """Sparse dist MLP (the DIST_MODEL=sparse architecture) over 2
    in-process pserver threads: mode="pserver" is the classic sync path,
    mode="collective" is HYBRID — dense grads ride the mesh, embedding
    rows still flow prefetch/send_sparse.  sync=False runs the ASYNC
    pserver path (fenced delivery: seq-stamped chunks, clock-stamped
    prefetches)."""
    from paddle_tpu import framework, unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.distributed import rpc
    from paddle_tpu.ops import dist_ops

    framework.switch_main_program(fluid.Program())
    framework.switch_startup_program(fluid.Program())
    unique_name.switch()
    main = fluid.default_main_program()
    startup = fluid.default_startup_program()
    main.random_seed = 7
    startup.random_seed = 7
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[1], dtype="int64")
        y = layers.data("y", shape=[1])
        emb = layers.embedding(ids, size=[20, 8], dtype="float32",
                               is_distributed=True)
        emb = layers.reshape(emb, [-1, 8])
        pred = layers.fc(emb, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    rng = np.random.RandomState(5)
    idv = rng.randint(0, 20, (16, 1)).astype("int64")
    if feed_ids is not None:  # caller pins the ids (rowless-shard legs)
        idv = np.asarray(feed_ids, np.int64).reshape(-1, 1)
    yv = (idv.astype("float32") / 10.0) - 1.0

    config = fluid.DistributeTranspilerConfig()
    config.min_block_size = 4
    config.mode = mode
    config.comm_wire_dtype = wire_dtype
    t = fluid.DistributeTranspiler(config=config)
    eps = ["127.0.0.1:%d" % _free_port() for _ in range(2)]
    t.transpile(0, program=main, pservers=",".join(eps), trainers=nranks,
                sync_mode=sync, startup_program=startup)
    dist_ops.reset_fences()
    threads = []
    for ep in eps:
        psprog = t.get_pserver_program(ep)
        pstart = t.get_startup_program(ep, psprog)
        scope = Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(pstart, scope=scope)
        th = threading.Thread(target=exe.run, args=(psprog,),
                              kwargs={"scope": scope}, daemon=True)
        th.start()
        threads.append(th)
    rpc.reset_comm_stats()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for _ in range(steps):
        (lv,) = exe.run(program=t.get_trainer_program(),
                        feed={"ids": idv, "y": yv}, fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    stats = rpc.get_comm_stats()
    exe.close()
    for th in threads:
        th.join(timeout=30)
    assert all(not th.is_alive() for th in threads), "pserver thread hung"
    rpc.RPCClient.reset_all()
    return losses, stats


@pytest.mark.slow  # rides scripts/ci.sh's collective pass (-m "")
def test_hybrid_collective_sparse_parity_vs_pure_pserver(no_heartbeats):
    """Hybrid acceptance on the sparse dist MLP: the collective run's
    loss trajectory matches the pure-pserver sync run, its dense grads
    NEVER touch rpc (zero send/send_bucket/recv/get_bucket round trips)
    while sparse rows still reach the pserver (prefetch + send_sparse
    flow), and the per-replica pushes cover every logical trainer."""
    steps = 4
    pure, ps = _run_sparse_cluster("pserver", nranks=1, steps=steps)
    hybrid, hs = _run_sparse_cluster("collective", nranks=2, steps=steps)
    assert np.isfinite(hybrid).all()
    np.testing.assert_allclose(hybrid, pure, rtol=1e-4, atol=1e-6)
    # dense grads ride the mesh: zero dense-bucket round trips
    for dense_verb in ("send", "send_bucket", "recv", "get_bucket",
                      "barrier"):
        assert hs["rpc_verbs"].get(dense_verb, 0) == 0, hs["rpc_verbs"]
    # sparse rows still reach the pserver — once per replica per step
    # (2 replicas x `steps`, each split across the touched servers)
    assert hs["rpc_verbs"].get("send_sparse", 0) >= 2 * steps
    assert hs["rpc_verbs"].get("prefetch", 0) >= 2 * steps
    # the pure-pserver run, for contrast, shipped dense buckets
    assert ps["rpc_verbs"].get("send_bucket", 0) > 0


@pytest.mark.slow  # rides scripts/ci.sh's collective pass (-m "")
def test_hybrid_collective_sparse_bf16_wire(no_heartbeats):
    """The sparse bf16 wire composes with the hybrid backend: row values
    compress (bytes saved > 0), ids stay exact, and the trajectory
    tracks the f32 hybrid run within bf16 rounding."""
    steps = 4
    f32, s32 = _run_sparse_cluster("collective", nranks=2, steps=steps)
    bf, sbf = _run_sparse_cluster("collective", nranks=2, steps=steps,
                                  wire_dtype="bfloat16")
    assert np.isfinite(bf).all()
    np.testing.assert_allclose(bf, f32, rtol=0.05, atol=1e-3)
    assert sbf["comm_bytes_saved"] > 0
    assert sbf["comm_bytes_sent"] < s32["comm_bytes_sent"]
    assert s32["comm_bytes_saved"] == 0


# ---------------------------------------------------------------------------
# durable async sparse: fenced delivery + trainer-side hot-row cache
# ---------------------------------------------------------------------------

def test_async_transpile_stamps_fenced_delivery_contract():
    """Async pserver mode stamps the fenced-delivery attrs: send_sparse
    and prefetch carry async_fence + the mirrorable optimizer spec,
    send_bucket carries async_fence; sync mode stamps none of it."""
    from paddle_tpu import framework, unique_name

    for sync in (True, False):
        framework.switch_main_program(fluid.Program())
        framework.switch_startup_program(fluid.Program())
        unique_name.switch()
        with fluid.program_guard(fluid.default_main_program(),
                                 fluid.default_startup_program()):
            ids = layers.data("ids", shape=[1], dtype="int64")
            y = layers.data("y", shape=[1])
            emb = layers.embedding(ids, size=[20, 8], dtype="float32",
                                   is_distributed=True)
            pred = layers.fc(layers.reshape(emb, [-1, 8]), size=1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        config = fluid.DistributeTranspilerConfig()
        config.min_block_size = 4
        t = fluid.DistributeTranspiler(config=config)
        t.transpile(0, pservers="127.0.0.1:6174,127.0.0.1:6175",
                    trainers=1, sync_mode=sync)
        ops = {op.type: op for op in
               t.get_trainer_program().global_block().ops}
        for name in ("prefetch", "send_sparse", "send_bucket"):
            assert ops[name].attrs.get("async_fence") is (not sync), \
                (name, sync)
        # the mirror spec is only stamped on an UNCOMPRESSED wire: the
        # server applies bf16-decoded grads the client doesn't hold, so
        # a compressed plan must stamp None (PR 8 contract) — this test
        # runs under both wire regimes (the ci.sh bf16 lane)
        from paddle_tpu.flags import get_flag

        want_hot = ({"type": "sgd", "lr": 0.1}
                    if str(get_flag("comm_wire_dtype")) == "float32"
                    else None)
        assert ops["send_sparse"].attrs["hot_opt"] == want_hot
        assert ops["prefetch"].attrs["hot_opt"] == want_hot


def test_async_fenced_sparse_trains_and_counts(no_heartbeats):
    """The async fenced path end to end through real ops: training
    converges, every chunk ships with a seq token exactly once (no dups
    witnessed on a healthy wire), and the client-side COUNTERS finally
    see the async traffic (async_sparse_sends — the fix for
    `_async_sends` being server-internal only)."""
    steps = 4
    losses, stats = _run_sparse_cluster("pserver", nranks=1, steps=steps,
                                        sync=False)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # one chunk per (step, server): fenced mode ships to EVERY server
    # (empty chunks carry the clock), so exactly steps * 2 sends
    assert stats["async_sparse_sends"] == steps * 2, stats
    assert stats["async_dedup_drops"] == 0
    assert stats["async_resends"] == 0
    assert stats["rpc_verbs"].get("send_sparse", 0) == steps * 2


def test_hot_row_cache_matches_cache_off(no_heartbeats):
    """Satellite acceptance: FLAGS_sparse_hot_rows on vs off — same
    model, same stream — must match within tolerance (sgd + constant lr
    mirrors exactly, so the tolerance is tight), while actually cutting
    prefetch round trips."""
    from paddle_tpu.flags import get_flag, set_flags

    steps = 5
    base, bstats = _run_sparse_cluster("pserver", nranks=1, steps=steps,
                                       sync=False)
    prev_rows = get_flag("sparse_hot_rows")
    prev_ttl = get_flag("sparse_hot_ttl")
    set_flags({"sparse_hot_rows": 32, "sparse_hot_ttl": 3})
    try:
        cached, cstats = _run_sparse_cluster("pserver", nranks=1,
                                             steps=steps, sync=False)
    finally:
        set_flags({"sparse_hot_rows": prev_rows,
                   "sparse_hot_ttl": prev_ttl})
    np.testing.assert_allclose(cached, base, rtol=1e-6, atol=1e-7)
    assert cstats["rpc_verbs"].get("prefetch", 0) < \
        bstats["rpc_verbs"].get("prefetch", 0), \
        "cache-on run did not cut prefetch round trips"


def test_hot_row_cache_mirror_and_refresh_unit():
    """HotRowCache in isolation: the sgd mirror matches a reference
    table bit for bit (duplicates merged), TTL expiry forces a refresh,
    LRU eviction respects capacity, and the refresh residual feeds the
    drift predictor forward."""
    from paddle_tpu.ops.dist_ops import HotRowCache

    lr = 0.1
    tbl = np.arange(12, dtype=np.float32).reshape(4, 3)
    cache = HotRowCache(capacity=3, ttl=2, lr=lr)
    cache.tick()
    gids = np.array([0, 1, 0])  # duplicate id 0: must merge
    hits, miss = cache.lookup(gids)
    assert miss.all() and hits == {}
    cache.insert(gids, tbl[gids])
    grads = np.array([[1, 1, 1], [2, 2, 2], [3, 3, 3]], np.float32)
    cache.push(gids, grads)
    # the reference apply (ps_server._apply_sparse sgd rule)
    ref = np.array(tbl)
    uids, inv = np.unique(gids, return_inverse=True)
    g = np.zeros((uids.size, 3), np.float32)
    np.add.at(g, inv, grads)
    ref[uids] -= lr * g
    hits, miss = cache.lookup(np.array([0, 1]))
    assert not miss.any()
    np.testing.assert_array_equal(hits[0], ref[0])
    np.testing.assert_array_equal(hits[1], ref[1])
    # TTL expiry: two more ticks -> both entries stale -> misses
    cache.tick()
    cache.tick()
    _, miss = cache.lookup(np.array([0, 1]))
    assert miss.all(), "TTL never expired the entries"
    # refresh with DIFFERENT server truth (another trainer moved rows):
    # the residual records the drift for the predictor
    truth = ref[[0]] + 0.5
    cache.insert(np.array([0]), truth)
    np.testing.assert_allclose(cache.residuals[0], np.full(3, 0.5),
                               rtol=1e-5)
    hits, _ = cache.lookup(np.array([0]))
    np.testing.assert_array_equal(hits[0], truth[0])
    # the next mirrored push feeds residual/ttl forward
    cache.push(np.array([0]), np.zeros((1, 3), np.float32))
    hits, _ = cache.lookup(np.array([0]))
    np.testing.assert_allclose(hits[0], truth[0] + 0.5 / 2, rtol=1e-5)
    # LRU capacity: inserting a 4th id evicts the oldest
    cache.insert(np.array([1, 2, 3]), tbl[[1, 2, 3]])
    assert len(cache.rows) == 3
    assert 0 not in cache.rows and 0 not in cache.residuals


def test_memory_optimize_plan():
    _build()
    prog = fluid.default_main_program()
    plan = fluid.memory_optimize(prog)
    assert "reuse" in plan and plan["saved_bytes"] >= 0
    # reused vars must be non-persistable temporaries
    block = prog.global_block()
    for var, cache in plan["reuse"].items():
        v = block._find_var_recursive(var)
        assert v is not None and not v.persistable


# ---------------------------------------------------------------------------
# elastic autoscaling: runtime re-derivable plans + clock-only coalescing
# ---------------------------------------------------------------------------

def test_derive_plan_bit_identical_and_matches_stamped_attrs():
    """THE re-plan contract: derive_plan over the program-carried spec
    is deterministic (two calls agree exactly) and, for the unchanged
    world, reproduces the transpile-time plan bit for bit — bucket
    layouts, folded-barrier totals, reassembly specs, block placement.
    A changed world only changes the grad scale (endpoints are the
    pserver set, which does not churn here)."""
    from paddle_tpu.transpiler.distribute_transpiler import derive_plan

    _build()
    t = _transpile(comm_bucket_bytes=4 << 20)
    spec = t.plan_spec
    p1 = derive_plan(spec)
    p2 = derive_plan(spec)
    # deterministic: independent derivations agree exactly
    assert p1["send_buckets"] == p2["send_buckets"]
    assert p1["recv_buckets"] == p2["recv_buckets"]
    assert p1["params_spec"] == p2["params_spec"]
    assert p1["sync_totals"] == p2["sync_totals"]
    assert p1["fetch_totals"] == p2["fetch_totals"]
    assert p1["block_eps"] == p2["block_eps"]
    assert p1["grad_scale"] == p2["grad_scale"] == 0.5  # trainers=2
    # ... and reproduce what the transpiler stamped into the ops
    ops = {op.type: op for op in
           t.get_trainer_program().global_block().ops}
    sb, rb = ops["send_bucket"], ops["recv_bucket"]
    assert sb.attrs["buckets"] == p1["send_buckets"]
    assert sb.attrs["sync_totals"] == p1["sync_totals"]
    assert rb.attrs["buckets"] == p1["recv_buckets"]
    assert rb.attrs["params"] == p1["params_spec"]
    assert rb.attrs["fetch_totals"] == p1["fetch_totals"]
    assert sb.attrs["plan_spec"] == spec == rb.attrs["plan_spec"]
    assert sb.attrs["plan_gid"] == rb.attrs["plan_gid"]
    assert t.get_trainer_program()._dist_plan_spec == spec
    # block placement: the derived VarBlock layout IS the transpiler's
    for p, blks in p1["blocks"].items():
        tb = t.param_blocks[p]
        assert [(b.idx, b.begin, b.end) for b in blks] == \
            [(b.idx, b.begin, b.end) for b in tb]
    # a re-plan for a CHANGED world: same layout (endpoints fixed),
    # only the grad scale moves
    p3 = derive_plan(spec, world={"trainers": 3})
    assert p3["send_buckets"] == p1["send_buckets"]
    assert p3["recv_buckets"] == p1["recv_buckets"]
    assert p3["grad_scale"] == 1.0 / 3.0
    # the spec is JSON-able (it is CARRIED in the program, not code)
    import json as _json

    assert _json.loads(_json.dumps(spec)) == spec


def test_async_clock_only_chunks_coalesce_into_one_frame(no_heartbeats):
    """Satellite acceptance (PR 8 known limit closed): with every id
    EVEN, pserver 1's shard is rowless — its per-step clock used to
    ride one empty send_sparse per table per step.  Now the rowless
    clocks buffer and ship as ONE merged sparse_clocks frame per
    endpoint per step: data sends halve, the merge counter sees every
    frame, and training still converges (monotonic fence semantics
    preserved)."""
    steps = 4
    rng = np.random.RandomState(5)
    even = (rng.randint(0, 20, (16, 1)) // 2) * 2
    losses, stats = _run_sparse_cluster("pserver", nranks=1, steps=steps,
                                        sync=False, feed_ids=even)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # rows only ever reach server 0: one data chunk per step there,
    # and ONE merged clock frame per step for rowless server 1
    assert stats["async_sparse_sends"] == steps, stats
    assert stats["async_clock_merges"] == steps, stats
    assert stats["rpc_verbs"].get("send_sparse", 0) == steps, stats
    assert stats["rpc_verbs"].get("sparse_clocks", 0) == steps, stats


def test_derive_plan_stable_shards_across_endpoint_worlds():
    """Live pserver migration's plan contract: block SLICING keys off
    the spec's BASE endpoint count, so shard identity (names +
    boundaries) is invariant under a pserver-set change — only the
    dispatch moves.  An unchanged world stays bit-identical to the old
    rule, and sparse_eps maps each stable shard (rows hash g % n_base
    forever) onto the live endpoint set — identity when unchanged."""
    from paddle_tpu.transpiler.distribute_transpiler import derive_plan

    spec = {"params": [["w", [64, 4], "float32", "w@GRAD"],
                       ["b", [4], "float32", "b@GRAD"]],
            "endpoints": ["a:1", "b:2"], "trainers": 2,
            "flags": {"slice_var_up": True, "min_block_size": 4,
                      "split_method": "SizeWeighted",
                      "comm_bucket_bytes": 4096,
                      "comm_wire_dtype": "float32",
                      "comm_grad_int8": False}}
    base = derive_plan(spec)
    same = derive_plan(spec, world={"endpoints": ["a:1", "b:2"]})
    assert same["block_eps"] == base["block_eps"]
    assert same["send_buckets"] == base["send_buckets"]
    assert same["recv_buckets"] == base["recv_buckets"]
    assert same["sparse_eps"] == ["a:1", "b:2"]  # identity
    grown = derive_plan(spec, world={"endpoints": ["a:1", "b:2", "c:3"]})
    # shard identity stable: same (param, idx) keys, same block sizes
    assert set(grown["block_eps"]) == set(base["block_eps"])
    for p in ("w", "b"):
        assert [(blk.begin, blk.end) for blk in grown["blocks"][p]] == \
            [(blk.begin, blk.end) for blk in base["blocks"][p]]
    # ...but dispatch now spans the grown world
    assert set(grown["block_eps"].values()) == {"a:1", "b:2", "c:3"}
    # shrink below base MOVES a sparse shard (stable shard 1 lands on
    # the surviving endpoint)
    shrunk = derive_plan(spec, world={"endpoints": ["a:1"]})
    assert shrunk["sparse_eps"] == ["a:1", "a:1"]
    assert set(shrunk["block_eps"].values()) == {"a:1"}


def test_elastic_pserver_program_is_empty_and_plan_stamped():
    """The grown server's program: no shards, no slice plan — state
    arrives exclusively via journaled handoff — but the plan spec and
    round config ride along so it can re-derive dispatch and join the
    protocol.  get_pserver_program also stamps the plan spec now (the
    server-side diff computation needs it)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = layers.data("x", shape=[4])
        y = layers.data("y", shape=[1])
        loss = layers.mean(layers.square_error_cost(
            layers.fc(x, size=2), y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    config = fluid.DistributeTranspilerConfig()
    config.min_block_size = 4
    t = fluid.DistributeTranspiler(config=config)
    t.transpile(0, program=prog, startup_program=startup,
                pservers="127.0.0.1:7001,127.0.0.1:7002", trainers=2)
    ps = t.get_pserver_program("127.0.0.1:7001")
    a = ps.global_block().ops[0].attrs
    assert a["plan_spec"] == t.plan_spec
    el = t.get_elastic_pserver_program("127.0.0.1:7099")
    ea = el.global_block().ops[0].attrs
    assert ea["elastic"] and ea["plan_spec"] == t.plan_spec
    assert ea["optimize_programs"] == [] and ea["slice_plan"] == []
    assert ea["trainers"] == 2 and ea["sync_mode"] is True
    import pytest as _pytest

    with _pytest.raises(ValueError):
        t.get_elastic_pserver_program("127.0.0.1:7001")  # base ep

def test_consistent_hash_dispatcher_stable_and_balanced():
    """ConsistentHash places by name on a vnode ring: placement is a
    pure function of (endpoint set, block name) — instance-independent,
    reset-independent, PYTHONHASHSEED-independent — and the finalized
    ring spreads realistic near-identical endpoint strings instead of
    collapsing onto one server."""
    from paddle_tpu.transpiler.ps_dispatcher import ConsistentHash

    eps = ["10.0.0.%d:6000" % i for i in range(1, 4)]

    class Blk:
        def __init__(self, name):
            self.block_name = name

    blocks = [Blk("w%d.block%d" % (p, b))
              for p in range(4) for b in range(5)]
    d1, d2 = ConsistentHash(eps), ConsistentHash(list(eps))
    placed = d1.dispatch(blocks)
    assert placed == d2.dispatch(blocks)
    d1.reset()
    assert placed == d1.dispatch(blocks)
    # every endpoint gets SOME share (the djb2-only ring collapsed
    # near-identical endpoint strings onto a single server)
    assert set(placed) == set(eps)


def test_consistent_hash_plan_walk_moves_bounded_and_restores():
    """ACCEPTANCE (satellite): a 3 -> 4 -> 3 endpoint-world walk under
    `split_method: "ConsistentHash"` moves at most ceil(S/N) of the S
    shard blocks per membership step — every 3->4 move lands ON the
    added endpoint and every 4->3 move comes FROM the removed one (no
    survivor-to-survivor churn, each such move being a live-migration
    handoff the fabric never needed) — and removing the added endpoint
    restores the original placement exactly."""
    import math

    from paddle_tpu.transpiler.distribute_transpiler import derive_plan

    eps3 = ["10.0.0.%d:6000" % i for i in range(1, 4)]
    eps4 = eps3 + ["10.0.0.4:6000"]
    spec = {"params": [["w0", [64, 8], "float32", "w0@GRAD"],
                       ["w1", [48, 8], "float32", "w1@GRAD"],
                       ["w2", [32, 4], "float32", "w2@GRAD"],
                       ["b0", [16], "float32", "b0@GRAD"]],
            "endpoints": eps3, "trainers": 2,
            "flags": {"slice_var_up": True, "min_block_size": 4,
                      "split_method": "ConsistentHash",
                      "comm_bucket_bytes": 4096,
                      "comm_wire_dtype": "float32",
                      "comm_grad_int8": False}}
    a = derive_plan(spec)["block_eps"]
    b = derive_plan(spec, world={"endpoints": eps4})["block_eps"]
    c = derive_plan(spec, world={"endpoints": eps3})["block_eps"]
    S = len(a)
    assert S >= 12 and set(a) == set(b) == set(c)  # stable shard ids
    bound = math.ceil(S / 4.0)
    moved_up = [k for k in a if a[k] != b[k]]
    moved_dn = [k for k in b if b[k] != c[k]]
    assert 1 <= len(moved_up) <= bound, (len(moved_up), bound)
    assert 1 <= len(moved_dn) <= bound, (len(moved_dn), bound)
    assert all(b[k] == eps4[3] for k in moved_up), \
        "a grow moved a shard between SURVIVORS"
    assert all(b[k] == eps4[3] for k in moved_dn), \
        "a shrink moved a shard a removal did not force"
    assert a == c, "3 -> 4 -> 3 must restore the placement exactly"
    # the walked worlds stay whole: every live endpoint serves blocks
    assert set(b.values()) == set(eps4)
