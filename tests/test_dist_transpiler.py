"""DistributeTranspiler unit tests (test_dist_transpiler.py analog):
assert the exact op rewrite of trainer/pserver programs, no processes."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.transpiler.distribute_transpiler import slice_variable


def _build(optimizer=None):
    x = layers.data("x", shape=[16])
    y = layers.data("y", shape=[1])
    pred = layers.fc(x, size=4)
    loss = layers.mean(layers.square_error_cost(pred, y))
    (optimizer or fluid.optimizer.SGD(0.1)).minimize(loss)
    return loss


def _transpile(trainer_id=0, eps="127.0.0.1:6174,127.0.0.1:6175", **cfg_kw):
    config = fluid.DistributeTranspilerConfig()
    config.min_block_size = 4
    for k, v in cfg_kw.items():
        setattr(config, k, v)
    t = fluid.DistributeTranspiler(config=config)
    t.transpile(
        trainer_id,
        program=fluid.default_main_program(),
        pservers=eps,
        trainers=2,
        sync_mode=True,
    )
    return t


def test_slice_variable():
    blocks = slice_variable([("w", 100)], 3, min_block_size=10)["w"]
    assert sum(b.size for b in blocks) == 100
    assert len(blocks) == 3
    assert blocks[0].begin == 0 and blocks[-1].end == 100
    # below min size: single block
    blocks = slice_variable([("b", 8)], 3, min_block_size=10)["b"]
    assert len(blocks) == 1 and blocks[0].size == 8


def test_trainer_program_rewrite():
    _build()
    t = _transpile()
    prog = t.get_trainer_program()
    types = [op.type for op in prog.global_block().ops]
    # optimizer ops moved off the trainer
    assert "sgd" not in types
    # rpc tail: scale+send per grad, one send_barrier, recv per param,
    # one fetch_barrier, in that order
    assert types.count("send") == 2  # fc w + b
    assert types.count("recv") == 2
    assert types.count("send_barrier") == 1
    assert types.count("fetch_barrier") == 1
    assert types.index("send_barrier") > max(
        i for i, t_ in enumerate(types) if t_ == "send"
    )
    assert types.index("fetch_barrier") > max(
        i for i, t_ in enumerate(types) if t_ == "recv"
    )
    # every rpc op is tagged with the rpc role
    for op in prog.global_block().ops:
        if op.type in ("send", "recv", "send_barrier", "fetch_barrier"):
            assert op.attrs["op_role"] == "rpc"


def test_pserver_program_shards():
    _build()
    t = _transpile()
    eps = t.pserver_endpoints
    progs = [t.get_pserver_program(ep) for ep in eps]
    ops = [p.global_block().ops[0] for p in progs]
    assert all(op.type == "listen_and_serv" for op in ops)
    # the fc weight (16*4=64 elems) splits across both servers
    n_shards = [len(op.attrs["optimize_programs"]) for op in ops]
    assert sum(n_shards) >= 3  # w split in 2 + bias
    assert all(n >= 1 for n in n_shards)
    # slice plans reconstruct full params exactly
    total = {}
    for op in ops:
        for src, blk, b, e in op.attrs["slice_plan"]:
            total.setdefault(src, []).append((b, e))
    w_ranges = sorted(total["fc_0.w_0"])
    assert w_ranges[0][0] == 0 and w_ranges[-1][1] == 64
    for (b1, e1), (b2, e2) in zip(w_ranges, w_ranges[1:]):
        assert e1 == b2


def test_adam_accumulators_sliced():
    _build(fluid.optimizer.Adam(0.01))
    t = _transpile()
    import json

    found_moment_slice = False
    for ep in t.pserver_endpoints:
        op = t.get_pserver_program(ep).global_block().ops[0]
        for sp_json in op.attrs["optimize_programs"]:
            sp = fluid.Program.from_json(sp_json)
            adam = sp.global_block().ops[0]
            assert adam.type == "adam"
            for slot in ("Moment1", "Moment2"):
                n = adam.inputs[slot][0]
                if ".block" in n:
                    found_moment_slice = True
    assert found_moment_slice


def test_memory_optimize_plan():
    _build()
    prog = fluid.default_main_program()
    plan = fluid.memory_optimize(prog)
    assert "reuse" in plan and plan["saved_bytes"] >= 0
    # reused vars must be non-persistable temporaries
    block = prog.global_block()
    for var, cache in plan["reuse"].items():
        v = block._find_var_recursive(var)
        assert v is not None and not v.persistable
