"""Worker for the launcher test: bootstrap via the launcher-provided
PADDLE_* env (init_collective), then psum the ranks across processes."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if not f.startswith("--xla_force_host_platform_device_count")
]
_flags.append("--xla_force_host_platform_device_count=1")
os.environ["XLA_FLAGS"] = " ".join(_flags)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu import distributed

    if os.environ.get("LAUNCH_WORKER_FAIL_RANK") == os.environ.get(
        "PADDLE_TRAINER_ID"
    ):
        sys.exit(3)

    distributed.init_collective()
    nproc = int(os.environ["PADDLE_TRAINERS"])
    assert jax.process_count() == nproc, jax.process_count()

    from paddle_tpu.parallel.mesh import shard_map

    from jax.sharding import NamedSharding

    mesh = Mesh(np.array(jax.devices()), ("x",))
    rank_local = np.asarray([float(jax.process_index())], np.float32)
    ranks = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("x")), rank_local, (nproc,)
    )

    f = jax.jit(
        shard_map(
            lambda r: jax.lax.psum(r, "x"),
            mesh=mesh,
            in_specs=P("x"),
            out_specs=P("x"),
        )
    )
    # DIST_STEPS: the bench dist-smoke times N collective steps; the
    # launcher tests leave it at 1 and just check the value
    steps = max(1, int(os.environ.get("DIST_STEPS", "1")))
    for _ in range(steps):
        out = f(ranks)
    local = np.asarray(out.addressable_data(0))
    print("PSUM %.1f" % float(local[0]), flush=True)


if __name__ == "__main__":
    main()
