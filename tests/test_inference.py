"""Inference predictor: save_inference_model -> Native/Analysis predictor
parity with direct Executor runs (analyzer_*_tester.cc role)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.inference import (
    AnalysisConfig,
    NativeConfig,
    create_paddle_predictor,
)


def _train_and_save(tmp_path):
    img = layers.data("img", shape=[3, 8, 8])
    label = layers.data("label", shape=[1], dtype="int64")
    c = layers.conv2d(img, num_filters=4, filter_size=3, act=None)
    bn = layers.batch_norm(c)
    flat = layers.flatten(layers.relu(bn), axis=1)
    pred = layers.fc(layers.dropout(flat, 0.3), size=10, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, label))
    fluid.optimizer.SGD(0.1).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    x = rng.rand(8, 3, 8, 8).astype("float32")
    y = rng.randint(0, 10, (8, 1)).astype("int64")
    for _ in range(3):
        exe.run(feed={"img": x, "label": y}, fetch_list=[loss])

    model_dir = str(tmp_path / "model")
    fluid.save_inference_model(model_dir, ["img"], [pred], exe)
    test_prog = fluid.default_main_program().clone(for_test=True)
    (ref,) = exe.run(program=test_prog, feed={"img": x}, fetch_list=[pred])
    return model_dir, x, np.asarray(ref)


def test_native_predictor_parity(tmp_path):
    model_dir, x, ref = _train_and_save(tmp_path)
    pred = create_paddle_predictor(NativeConfig(model_dir))
    (out,) = pred.run({"img": x})
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=1e-5)
    assert pred.get_input_names() == ["img"]
    assert len(pred.get_output_names()) == 1


def test_analysis_predictor_serves_binary_model(tmp_path):
    """The serving path is format-agnostic: a binary (protobuf) __model__
    loads through the same predictor API with identical outputs."""
    import paddle_tpu.layers as layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 5
        img = layers.data("img", shape=[6])
        pred = layers.fc(layers.fc(img, 8, act="relu"), 3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        d = str(tmp_path / "pbm")
        fluid.save_inference_model(d, ["img"], [pred], exe,
                                   main_program=main, model_format="pb")
        x = np.random.RandomState(2).rand(4, 6).astype("float32")
        (ref,) = exe.run(main, feed={"img": x}, fetch_list=[pred])
    p = create_paddle_predictor(AnalysisConfig(d))
    (out,) = p.run({"img": x})
    np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_analysis_predictor_parity_and_fusion(tmp_path):
    model_dir, x, ref = _train_and_save(tmp_path)
    pred = create_paddle_predictor(AnalysisConfig(model_dir))
    types = [op.type for op in pred.program.global_block().ops]
    assert "batch_norm" not in types  # folded by the analysis pass
    assert "dropout" not in types
    (out,) = pred.run({"img": x})
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)
    # positional input form + clone sharing weights
    clone = pred.clone()
    (out2,) = clone.run([x])
    np.testing.assert_allclose(out2, out, rtol=1e-6)


def test_predictor_runs_user_registered_pass(tmp_path):
    """IRPassManager analog: a pass registered via transpiler.register_pass
    participates in the predictor's analysis pipeline by name."""
    from paddle_tpu.transpiler import register_pass

    calls = []

    @register_pass("test_probe_pass")
    def _probe(program, scope):
        calls.append(len(program.global_block().ops))
        return program

    x = layers.data("upx", shape=[4])
    pred = layers.fc(x, 3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    model_dir = str(tmp_path / "up_model")
    fluid.save_inference_model(model_dir, ["upx"], [pred], exe)

    cfg = AnalysisConfig(model_dir)
    cfg.pass_builder().append("test_probe_pass")
    predictor = create_paddle_predictor(cfg)
    assert calls, "registered pass did not run in the predictor"
    (out,) = predictor.run({"upx": np.ones((2, 4), "float32")})
    assert out.shape == (2, 3)


def test_zero_copy_tensor_serving(tmp_path):
    """ZeroCopyTensor cycle (paddle_api.h:98, analysis_predictor.h:53):
    bind input buffers once, write in place, zero_copy_run, read outputs
    — identical results to the feed-dict path; rebinding data without
    reallocation also matches."""
    model_dir, x, ref = _train_and_save(tmp_path)
    pred = create_paddle_predictor(AnalysisConfig(model_dir))

    inp = pred.get_input_tensor("img")
    inp.reshape(x.shape)
    buf = inp.mutable_data("float32")
    buf[...] = x
    assert pred.zero_copy_run()
    out = pred.get_output_tensor(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=1e-5)

    # in-place rewrite of the SAME buffer (the zero-copy contract)
    buf[...] = x * 0.0
    assert pred.zero_copy_run()
    out0 = pred.get_output_tensor(pred.get_output_names()[0]).copy_to_cpu()
    assert not np.allclose(out0, out)

    # copy_from_cpu path + error contracts
    inp.copy_from_cpu(x)
    pred.zero_copy_run()
    out2 = pred.get_output_tensor(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out2, ref, rtol=2e-4, atol=1e-5)
    import pytest

    with pytest.raises(KeyError):
        pred.get_input_tensor("nope")
    with pytest.raises(RuntimeError, match="for input tensors"):
        pred.get_output_tensor(pred.get_output_names()[0]).mutable_data()
    pred2 = create_paddle_predictor(NativeConfig(model_dir))
    with pytest.raises(RuntimeError, match="reshape"):
        pred2.get_input_tensor("img").mutable_data()
    with pytest.raises(RuntimeError, match="zero_copy_run"):
        pred2.get_output_tensor(pred2.get_output_names()[0]).copy_to_cpu()


def test_paddle_tensor_run_mode(tmp_path):
    """PaddleTensor list in -> PaddleTensor list out (api_impl.h Run
    contract), matching the dict path."""
    from paddle_tpu.inference import PaddleTensor

    model_dir, x, ref = _train_and_save(tmp_path)
    pred = create_paddle_predictor(NativeConfig(model_dir))
    (out_t,) = pred.run([PaddleTensor(x, name="img")])
    assert isinstance(out_t, PaddleTensor)
    assert out_t.name == pred.get_output_names()[0]
    assert out_t.dtype == "float32" and out_t.shape == list(ref.shape)
    np.testing.assert_allclose(out_t.data, ref, rtol=2e-4, atol=1e-5)
