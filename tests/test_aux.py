"""Aux subsystems: flags (+check_nan_inf), debugger dumps, fault-tolerant
master task queue, bf16 AMP rewrite."""

import os
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags, layers
from paddle_tpu.contrib.mixed_precision import rewrite_bf16
from paddle_tpu.distributed import Master, MasterClient
from paddle_tpu.distributed.rpc import RPCClient


def _mlp():
    x = layers.data("x", shape=[4])
    y = layers.data("y", shape=[1])
    pred = layers.fc(layers.fc(x, size=8, act="relu"), size=1)
    return layers.mean(layers.square_error_cost(pred, y))


# ---------------------------------------------------------------------------
def test_flags_registry_and_env():
    assert flags.get_flag("rpc_deadline") == 180000
    flags.set_flags({"FLAGS_rpc_deadline": "5000", "max_retry": 2})
    assert flags.get_flag("rpc_deadline") == 5000
    assert flags.get_flag("max_retry") == 2
    with pytest.raises(KeyError):
        flags.set_flags({"not_a_flag": 1})
    flags.set_flags({"rpc_deadline": 180000, "max_retry": 30})
    assert "check_nan_inf" in flags.flag_items()


def test_check_nan_inf_flag():
    loss = _mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    bad = np.full((4, 4), np.nan, "float32")
    y = np.zeros((4, 1), "float32")
    flags.set_flags({"check_nan_inf": True})
    try:
        with pytest.raises(RuntimeError, match="NaN/Inf"):
            exe.run(feed={"x": bad, "y": y}, fetch_list=[loss])
    finally:
        flags.set_flags({"check_nan_inf": False})


# ---------------------------------------------------------------------------
def test_debugger_dumps(tmp_path):
    from paddle_tpu import debugger

    loss = _mlp()
    prog = fluid.default_main_program()
    text = debugger.pprint_program_codes(prog)
    assert "mul(" in text and "x[-1x4,float32]" in text
    dot_path = str(tmp_path / "g.dot")
    dot = debugger.draw_block_graphviz(
        prog.global_block(), highlights=[loss.name], path=dot_path
    )
    assert dot.startswith("digraph G {") and "lightcoral" in dot
    assert os.path.exists(dot_path)


# ---------------------------------------------------------------------------
def test_master_task_queue_lease_finish_and_timeout(tmp_path):
    snap = str(tmp_path / "master.json")
    master = Master("127.0.0.1:0", timeout_s=0.5, failure_max=3,
                    snapshot_path=snap, chunks_per_task=2)
    try:
        cli = MasterClient(master.endpoint, trainer_id=0)
        cli.set_dataset(["c%d" % i for i in range(6)])  # 3 tasks of 2

        t1, p1 = cli.get_task()
        assert sorted(p1) == ["c0", "c1"]
        cli.task_finished(t1)

        # lease a task and let it time out (dead trainer)
        t2, _ = cli.get_task()
        time.sleep(0.7)
        # after timeout the task re-queues; drain everything
        seen = set()
        while True:
            tid, payload = cli.get_task()
            if tid is None:
                break
            seen.add(tid)
            cli.task_finished(tid)
        assert t2 in seen  # the timed-out lease came back
        assert cli.epoch_done()
        s = cli.stats()
        assert s["done"] == 3 and s["todo"] == 0 and s["pending"] == 0
    finally:
        master.shutdown()

    # snapshot restore: a new master resumes with completed state
    master2 = Master("127.0.0.1:0", snapshot_path=snap)
    try:
        RPCClient.reset_all()
        cli2 = MasterClient(master2.endpoint)
        s = cli2.stats()
        assert s["done"] == 3 and s["todo"] == 0
    finally:
        master2.shutdown()
        RPCClient.reset_all()


def test_master_failure_max_discards(tmp_path):
    master = Master("127.0.0.1:0", timeout_s=30, failure_max=2)
    try:
        RPCClient.reset_all()
        cli = MasterClient(master.endpoint)
        cli.set_dataset(["only"])
        for _ in range(2):  # fail it failure_max times
            tid, _ = cli.get_task()
            assert tid is not None
            cli.task_failed(tid)
        tid, _ = cli.get_task()
        assert tid is None and cli.epoch_done()  # discarded, not re-queued
    finally:
        master.shutdown()
        RPCClient.reset_all()


# ---------------------------------------------------------------------------
def test_bf16_amp_rewrite_trains_and_matches_f32():
    rng = np.random.RandomState(0)
    xv = rng.rand(16, 4).astype("float32")
    yv = (xv @ np.array([[1.0], [-2.0], [3.0], [0.5]], "float32"))

    def run(amp):
        import paddle_tpu.framework as fw
        from paddle_tpu.core import scope as scope_mod
        from paddle_tpu import unique_name

        fw.switch_main_program(fluid.Program())
        fw.switch_startup_program(fluid.Program())
        unique_name.switch()
        scope_mod._switch_scope(scope_mod.Scope())
        fluid.default_main_program().random_seed = 7
        fluid.default_startup_program().random_seed = 7
        loss = _mlp()
        n = rewrite_bf16() if amp else 0
        # lr/steps sized so the halving bar below has real margin: at
        # SGD(0.05) x 10 steps BOTH precisions only reach ~0.60x (the
        # old bar failed for f32 and bf16 alike — a convergence-budget
        # problem, not a precision one); 0.1 x 20 reaches ~0.31x with
        # the bf16-vs-f32 trajectory gap still ~0.4% << the 15% parity
        # tolerance
        fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        losses = [
            float(np.ravel(exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])[0])[0])
            for _ in range(20)
        ]
        return losses, n

    f32_losses, _ = run(False)
    amp_losses, n_rewritten = run(True)
    assert n_rewritten == 2  # both fc muls
    assert amp_losses[-1] < amp_losses[0] * 0.5  # trains
    # bf16 has ~3 decimal digits: trajectories agree loosely
    np.testing.assert_allclose(amp_losses, f32_losses, rtol=0.15, atol=0.02)
    # and the rewritten program actually contains bf16 casts
    types = [op.type for op in fluid.default_main_program().global_block().ops]
    assert types.count("cast") >= 4


def test_memory_and_device_info_surfaces():
    """HBM stats + device info layer (SURVEY §2.7/§2.8 re-expression)."""
    import paddle_tpu as fluid

    assert fluid.device_info.cpu_count() >= 1
    assert fluid.device_info.device_count() >= 1
    assert isinstance(fluid.device_info.device_kind(), str)
    stats = fluid.memory.memory_stats()
    assert isinstance(stats, dict)
    assert fluid.memory.memory_allocated() >= 0
    assert fluid.memory.max_memory_allocated() >= fluid.memory.memory_allocated() or not stats


def test_memory_fraction_env_wiring(monkeypatch):
    import paddle_tpu.memory as mem

    monkeypatch.delenv("XLA_PYTHON_CLIENT_MEM_FRACTION", raising=False)
    monkeypatch.setenv("FLAGS_fraction_of_gpu_memory_to_use", "0.5")
    mem.apply_memory_fraction()
    import os

    assert os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.5"


def test_lowering_error_carries_op_context():
    """enforce.h-style error context: a shape error inside the compiled
    block names the op, block index, and input shapes.  With the static
    verifier armed (FLAGS_check_program) the same defect is caught
    BEFORE tracing, as an attributable diagnostic; the trace-time
    context machinery is exercised with the flag pinned off."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import flags, layers
    from paddle_tpu.analysis import ProgramVerifyError

    x = layers.data("ec_x", shape=[3, 4], append_batch_size=False)
    y = layers.data("ec_y", shape=[5, 6], append_batch_size=False)
    out = layers.matmul(x, y)
    exe = fluid.Executor(fluid.CPUPlace())
    import pytest

    feed = {
        "ec_x": np.ones((3, 4), "float32"),
        "ec_y": np.ones((5, 6), "float32"),
    }
    old = flags.get_flag("check_program")
    flags.set_flags({"check_program": True})
    try:
        with pytest.raises(ProgramVerifyError,
                           match=r"\[shape-mismatch\].*\(matmul\)"):
            exe.run(feed=feed, fetch_list=[out])
    finally:
        flags.set_flags({"check_program": old})
    flags.set_flags({"check_program": False})
    try:
        with pytest.raises(RuntimeError, match="lowering op 'matmul'.*shapes"):
            exe.run(feed=feed, fetch_list=[out])
    finally:
        flags.set_flags({"check_program": old})


def test_nested_lod_two_levels():
    """2-level LoD: [doc -> sents -> tokens] padded to
    [docs, max_sents, max_toks] with per-sentence lengths."""
    import numpy as np
    from paddle_tpu.lod import create_lod_tensor

    data = np.arange(10, dtype="float32").reshape(10, 1)
    # doc0 has 2 sentences (3 + 2 tokens), doc1 has 1 sentence (5 tokens)
    t = create_lod_tensor(data, recursive_seq_lens=[[2, 1], [3, 2, 5]])
    assert t.lod_level() == 2
    assert t.data.shape == (2, 2, 5, 1)
    np.testing.assert_array_equal(t.nested_seq_lens, [[3, 2], [5, 0]])
    np.testing.assert_allclose(t.data[0, 0, :3, 0], [0, 1, 2])
    np.testing.assert_allclose(t.data[0, 1, :2, 0], [3, 4])
    np.testing.assert_allclose(t.data[1, 0, :, 0], [5, 6, 7, 8, 9])
    np.testing.assert_array_equal(t.seq_lens(0), [2, 1])
    np.testing.assert_array_equal(t.seq_lens(1), [3, 2, 5])


def test_nested_lod_three_levels():
    """N-level LoD composition (lod_tensor.h:58's arbitrary recursion):
    3 levels [corpus -> docs -> sents -> tokens] pad to
    [corpora, max_docs, max_sents, max_toks, *feat] with a per-level
    padded lengths pyramid in `padded_lens`."""
    import numpy as np
    from paddle_tpu.lod import create_lod_tensor

    data = np.arange(12, dtype="float32").reshape(12, 1)
    # corpus0: 2 docs (doc0: 2 sents of 2+1 toks; doc1: 1 sent of 3)
    # corpus1: 1 doc  (doc2: 2 sents of 4+2 toks)
    t = create_lod_tensor(
        data,
        recursive_seq_lens=[[2, 1], [2, 1, 2], [2, 1, 3, 4, 2]],
    )
    assert t.lod_level() == 3
    assert t.data.shape == (2, 2, 2, 4, 1)
    # level-0: docs per corpus
    np.testing.assert_array_equal(t.padded_lens[0], [2, 1])
    # level-1: sents per doc, padded to [corpora, max_docs]
    np.testing.assert_array_equal(t.padded_lens[1], [[2, 1], [2, 0]])
    # level-2: tokens per sent, padded to [corpora, max_docs, max_sents]
    np.testing.assert_array_equal(
        t.padded_lens[2],
        [[[2, 1], [3, 0]], [[4, 2], [0, 0]]],
    )
    np.testing.assert_allclose(t.data[0, 0, 0, :2, 0], [0, 1])
    np.testing.assert_allclose(t.data[0, 0, 1, :1, 0], [2])
    np.testing.assert_allclose(t.data[0, 1, 0, :3, 0], [3, 4, 5])
    np.testing.assert_allclose(t.data[1, 0, 0, :4, 0], [6, 7, 8, 9])
    np.testing.assert_allclose(t.data[1, 0, 1, :2, 0], [10, 11])
    # untouched slots are zero padding
    assert float(np.abs(t.data[1, 1]).sum()) == 0.0
    np.testing.assert_array_equal(t.seq_lens(0), [2, 1])
    np.testing.assert_array_equal(t.seq_lens(2), [2, 1, 3, 4, 2])
    # mismatched level sums still raise
    import pytest

    with pytest.raises(ValueError, match="level-0"):
        create_lod_tensor(data, recursive_seq_lens=[[2], [2, 1, 2],
                                                    [2, 1, 3, 4, 2]])


def test_api_spec_stability():
    """tools/diff_api.py CI contract: the live public API covers the
    committed API.spec snapshot (removals/re-signatures fail)."""
    import subprocess
    import sys
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "diff_api.py"),
         os.path.join(root, "API.spec")],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_ploter_csv_fallback_and_api(tmp_path):
    """utils.plot.Ploter (python/paddle/utils/plot.py parity): append/plot/
    reset; files land whether or not matplotlib exists."""
    from paddle_tpu.utils.plot import Ploter

    p = Ploter("train cost", "test cost")
    for i in range(5):
        p.append("train cost", i, 1.0 / (i + 1))
    p.append("test cost", 0, 0.5)
    out = str(tmp_path / "curve.png")
    p.plot(out)
    import os
    produced = os.listdir(str(tmp_path))
    assert produced, "plot() wrote nothing"
    p.reset()
    assert p.__plot_data__["train cost"].step == []


def test_dataset_image_transforms():
    """dataset.image (python/paddle/dataset/image.py parity): resize_short
    keeps aspect, crops/flip/chw/mean behave, and the pipeline is
    deterministic for eval."""
    from paddle_tpu.dataset import image as img

    rng = np.random.RandomState(0)
    im = (rng.rand(40, 60, 3) * 255).astype("uint8")

    r = img.resize_short(im, 20)
    assert r.shape[:2] == (20, 30)  # shorter edge 40 -> 20, aspect kept
    r2 = img.resize_short(im.transpose(1, 0, 2), 20)
    assert r2.shape[:2] == (30, 20)

    c = img.center_crop(r, 16)
    assert c.shape[:2] == (16, 16)
    rc = img.random_crop(r, 16, rng=np.random.RandomState(3))
    assert rc.shape[:2] == (16, 16)

    f = img.left_right_flip(c)
    np.testing.assert_array_equal(f[:, 0], c[:, -1])

    chw = img.to_chw(c)
    assert chw.shape == (3, 16, 16)

    out = img.simple_transform(im, 24, 16, is_train=False,
                               mean=[1.0, 2.0, 3.0])
    assert out.shape == (3, 16, 16) and out.dtype == np.float32
    out2 = img.simple_transform(im, 24, 16, is_train=False,
                                mean=[1.0, 2.0, 3.0])
    np.testing.assert_array_equal(out, out2)  # eval path deterministic

    # train path with a fixed rng is reproducible too
    t1 = img.simple_transform(im, 24, 16, True, rng=np.random.RandomState(5))
    t2 = img.simple_transform(im, 24, 16, True, rng=np.random.RandomState(5))
    np.testing.assert_array_equal(t1, t2)

    # grayscale path
    g = img.resize_short(im[:, :, 0], 20)
    assert g.shape == (20, 30)

    # bilinear sanity: resize of a constant image stays constant
    const = np.full((10, 14, 3), 7, "uint8")
    rr = img.resize_short(const, 5)
    assert np.all(rr == 7)


def test_dataset_image_decode_roundtrip(tmp_path):
    """load_image / load_image_bytes decode an encoded PNG back to the
    original pixels (PIL-backed IO convenience)."""
    from PIL import Image

    from paddle_tpu.dataset import image as img

    rng = np.random.RandomState(1)
    arr = (rng.rand(8, 9, 3) * 255).astype("uint8")
    p = tmp_path / "t.png"
    Image.fromarray(arr).save(str(p))
    got = img.load_image(str(p))
    np.testing.assert_array_equal(got, arr)
    gray = img.load_image(str(p), is_color=False)
    assert gray.shape == (8, 9)


def test_image_resize_rounds_not_truncates():
    """uint8 bilinear resize rounds to nearest (PIL/cv2 parity) instead of
    truncation-darkening."""
    from paddle_tpu.dataset import image as img

    im = np.full((4, 6, 3), 201, "uint8")
    im[::2] = 202  # interpolated rows land at ~201.5
    out = img.resize_short(im, 3)
    assert out.dtype == np.uint8
    # every output pixel must be one of the neighbors or the ROUNDED mid
    assert set(np.unique(out)) <= {201, 202}
    mid = img._bilinear_resize(
        np.array([[100, 101]], "uint8").reshape(1, 2), 1, 3
    )
    assert mid.flatten().tolist()[1] in (100, 101)  # rounded, never 99


def test_packaging_metadata_builds():
    """pyproject.toml is a valid setuptools package definition: the
    package set resolves to paddle_tpu.* with the native sources included
    (the reference's wheel/cmake packaging role, python-side)."""
    import os

    import setuptools

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert os.path.exists(os.path.join(repo, "pyproject.toml"))
    try:
        import tomllib
    except ImportError:
        import tomli as tomllib
    with open(os.path.join(repo, "pyproject.toml"), "rb") as f:
        cfg = tomllib.load(f)
    assert cfg["project"]["name"] == "paddle-tpu"
    pkgs = setuptools.find_packages(repo, include=["paddle_tpu*"])
    assert "paddle_tpu" in pkgs and "paddle_tpu.ops" in pkgs
    assert "tests" not in pkgs
    data = cfg["tool"]["setuptools"]["package-data"]["paddle_tpu.native"]
    assert "*.cc" in data and "Makefile" in data


def test_per_op_timeline_correlated_tracks(tmp_path):
    """per_op_timeline (device_tracer + tools/timeline.py capability): one
    chrome trace with host+device tracks sharing a correlation id per op,
    and a per-op table sorted by device time."""
    import json

    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers, profiler

    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        x = layers.data("x", shape=[16])
        y = layers.fc(layers.fc(x, 32, act="relu"), 4)
        loss = layers.mean(y)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        path = str(tmp_path / "perop.json")
        rows = profiler.per_op_timeline(
            main, {"x": np.random.rand(4, 16).astype("float32")},
            scope=scope, path=path)
    assert rows and all(len(r) == 4 for r in rows)
    types = {r[0] for r in rows}
    assert "mul" in types and "mean" in types
    trace = json.load(open(path))["traceEvents"]
    spans = [e for e in trace if e.get("ph") == "X"]
    host = {e["args"]["correlation"] for e in spans if e["tid"] == 1}
    dev = {e["args"]["correlation"] for e in spans if e["tid"] == 2}
    assert host == dev and len(host) == len(rows)
    # device rows are the sort key
    assert rows == sorted(rows, key=lambda r: -r[3])


def test_comm_compute_split_attributes_phase_spans():
    """Wire-compression observability: cat-tagged serialize/compress/
    apply spans surface as their own phase lines in comm_compute_split
    instead of lumping into comm — and stay absent when no such spans
    were recorded."""
    from paddle_tpu import profiler

    rows = [("send_bucket", 0, 4.0, 4.0), ("mul", 1, 6.0, 6.0)]
    base = profiler.comm_compute_split(rows, events=[])
    assert base["comm_ms"] == 4.0 and base["compute_ms"] == 6.0
    assert not any(k.endswith("_ms") and k not in ("comm_ms", "compute_ms")
                   for k in base)
    events = [
        {"name": "rpc_serialize", "cat": "serialize", "dur": 1500.0},
        {"name": "wire_compress", "cat": "compress", "dur": 250.0},
        {"name": "ps_apply_round", "cat": "apply", "dur": 3000.0},
        {"name": "rpc_send", "cat": "comm", "dur": 9000.0},  # not a phase
    ]
    out = profiler.comm_compute_split(rows, events=events)
    assert out["serialize_ms"] == 1.5
    assert out["compress_ms"] == 0.25
    assert out["apply_ms"] == 3.0
    # real spans: the profiler's captured events feed the split by default
    profiler.reset_profiler()
    profiler.start_profiler("CPU", None)
    try:
        with profiler.RecordEvent("rpc_serialize", cat="serialize"):
            import time as _time

            _time.sleep(0.002)
    finally:
        profiler.stop_profiler(profile_path=None)
    assert "serialize_ms" in profiler.comm_compute_split(rows)
    profiler.reset_profiler()


def test_timeline_tool_merges_worker_profiles(tmp_path):
    """tools/timeline.py (reference tools/timeline.py:160 role): merge
    per-worker profiler JSONs into one trace with per-process lanes."""
    import json
    import subprocess
    import sys

    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers, profiler

    paths = []
    for i in range(2):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.framework.program_guard(main, startup):
            x = layers.data("x", shape=[4])
            y = layers.fc(x, 2)
        scope = fluid.Scope()
        p = str(tmp_path / ("w%d.json" % i))
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            with profiler.profiler("CPU", profile_path=p):
                exe.run(main, feed={"x": np.ones((2, 4), "float32")},
                        fetch_list=[y])
        paths.append(p)

    out = str(tmp_path / "merged.json")
    r = subprocess.run(
        [sys.executable, "tools/timeline.py", "--out", out,
         "trainer0=%s" % paths[0], "pserver0=%s" % paths[1]],
        cwd="/root/repo", timeout=120,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    assert r.returncode == 0, r.stdout.decode()
    trace = json.load(open(out))["traceEvents"]
    names = {e["args"]["name"] for e in trace if e.get("ph") == "M"}
    assert {"trainer0", "pserver0"} <= names
    pids = {e["pid"] for e in trace}
    assert pids == {0, 1}
    assert any(e.get("ph") == "X" for e in trace)


def test_op_coverage_vs_reference():
    """Every reference REGISTER_OPERATOR type is lowered, generically
    derived, or on the documented structural/N-A list
    (tools/check_op_coverage.py — the op-level diff_api.py sibling)."""
    import os
    import subprocess
    import sys

    if not os.path.isdir("/root/reference"):
        import pytest

        pytest.skip("reference tree unavailable")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "check_op_coverage.py")],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_prng_impl_flag_rbg():
    """FLAGS_prng_impl=rbg swaps the in-program generator for the TPU
    hardware RBG: dropout still masks at ~rate with correct scaling, the
    run()/run_loop() stream parity contract holds (both draw
    fold_in(base, step)), and the stream differs from threefry's."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import flags, layers

    x = np.ones((64, 256), dtype="float32")

    def masked(impl):
        flags.set_flags({"prng_impl": impl})
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.framework.program_guard(main, startup):
                inp = layers.data("x", shape=[256])
                out = layers.dropout(inp, dropout_prob=0.4)
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                (v,) = exe.run(main, feed={"x": x}, fetch_list=[out])
                # run_loop must draw the SAME per-step keys as run()
                exe2 = fluid.Executor(fluid.CPUPlace())
                exe2.run(startup)  # align step counters with exe
                (v_loop,) = exe2.run_loop(1, main, feed={"x": x},
                                          fetch_list=[out])
            return np.asarray(v), np.asarray(v_loop)
        finally:
            flags.set_flags({"prng_impl": "threefry"})

    rbg, rbg_loop = masked("rbg")
    fry, _ = masked("threefry")
    for v in (rbg, fry):
        rate = float((v == 0).mean())
        assert 0.3 < rate < 0.5, rate
        nz = v[v != 0]
        np.testing.assert_allclose(nz, nz[0], rtol=1e-6)  # 1/(1-p) scale
    np.testing.assert_allclose(rbg, rbg_loop)
    assert (rbg == 0).sum() != 0 and not np.array_equal(rbg == 0, fry == 0)
