"""Fusion-pass parity sweep (ir/*fuse_pass* analogs): each pass must (a)
fire on its pattern — rewriting the op sequence — and (b) leave outputs
numerically identical; train programs (whose grad ops make intermediates
multi-consumer) must be left untouched."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.transpiler import apply_pass


def _fresh():
    main, startup = fluid.Program(), fluid.Program()
    return main, startup


def _run(main, startup, feed, fetch, scope=None):
    scope = scope or fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        out = exe.run(main, feed=feed, fetch_list=fetch)
    return [np.asarray(o) for o in out], scope


def _op_types(prog):
    return [op.type for op in prog.global_block().ops]


def test_fc_fuse_pass_fires_and_matches():
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 3
        x = layers.data("x", shape=[8])
        y = layers.fc(x, 6, act="relu")
    xv = np.random.RandomState(0).rand(4, 8).astype("float32")
    scope = fluid.Scope()
    before, scope = _run(main, startup, {"x": xv}, [y], scope)
    assert "mul" in _op_types(main) and "relu" in _op_types(main)

    apply_pass(main, "fc_fuse_pass")
    assert main._fc_fused_count == 1
    types = _op_types(main)
    assert "fc" in types and "mul" not in types and "relu" not in types
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-5, atol=1e-6)


def test_fc_fuse_pass_leaves_train_programs_alone():
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        x = layers.data("x", shape=[8])
        y = layers.fc(x, 6, act="relu")
        loss = layers.mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
    apply_pass(main, "fc_fuse_pass")
    # grad ops consume the mul/add intermediates -> no single-consumer
    # chain -> the rewrite must not fire (train safety)
    assert main._fc_fused_count == 0
    assert "mul" in _op_types(main)


def test_fuse_elewise_add_act_pass():
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        a = layers.data("a", shape=[6])
        b = layers.data("b", shape=[6])
        s = layers.elementwise_add(a, b)
        y = layers.tanh(s)
    av = np.random.RandomState(1).rand(3, 6).astype("float32")
    bv = np.random.RandomState(2).rand(3, 6).astype("float32")
    before, scope = _run(main, startup, {"a": av, "b": bv}, [y])

    apply_pass(main, "fuse_elewise_add_act_pass")
    assert main._elewise_act_fused_count == 1
    assert "fused_elemwise_activation" in _op_types(main)
    assert "elementwise_add" not in _op_types(main)
    after, _ = _run(main, startup, {"a": av, "b": bv}, [y])
    np.testing.assert_allclose(before[0], after[0], rtol=1e-5, atol=1e-6)


def test_conv_eltadd_relu_fuse_pass():
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 5
        x = layers.data("x", shape=[3, 8, 8])
        c = layers.conv2d(x, num_filters=4, filter_size=3, bias_attr=False)
        bias = layers.create_parameter([4], "float32", name="cb")
        s = layers.elementwise_add(c, layers.reshape(bias, shape=[1, 4, 1, 1]))
        y = layers.relu(s)
    xv = np.random.RandomState(3).rand(2, 3, 8, 8).astype("float32")
    scope = fluid.Scope()
    before, scope = _run(main, startup, {"x": xv}, [y], scope)

    apply_pass(main, "conv_eltadd_relu_fuse_pass")
    assert main._conv_eltadd_fused_count == 1
    types = _op_types(main)
    assert "relu" not in types and "elementwise_add" not in types
    conv = [op for op in main.global_block().ops if op.type == "conv2d"][0]
    assert conv.attrs.get("fuse_relu") and conv.inputs.get("Bias")
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-4, atol=1e-5)


def test_seqconv_eltadd_relu_fuse_pass():
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 7
        x = layers.data("x", shape=[5, 6])  # [B, T, D]
        sc = layers.sequence_conv(x, num_filters=4, filter_size=3,
                                  bias_attr=False)
        bias = layers.create_parameter([4], "float32", name="scb")
        y = layers.relu(layers.elementwise_add(sc, bias))
    xv = np.random.RandomState(4).rand(2, 5, 6).astype("float32")
    scope = fluid.Scope()
    before, scope = _run(main, startup, {"x": xv}, [y], scope)

    apply_pass(main, "seqconv_eltadd_relu_fuse_pass")
    assert main._seqconv_fused_count == 1
    assert "fusion_seqconv_eltadd_relu" in _op_types(main)
    assert "sequence_conv" not in _op_types(main)
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-4, atol=1e-5)


def _gru_program():
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 9
        x = layers.data("x", shape=[5, 6])
        proj = layers.fc(x, 3 * 4, num_flatten_dims=2, bias_attr=False)
        h = layers.dynamic_gru(proj, size=4)
    return main, startup, h


def test_fc_gru_fuse_pass():
    main, startup, h = _gru_program()
    xv = np.random.RandomState(5).rand(2, 5, 6).astype("float32")
    scope = fluid.Scope()
    before, scope = _run(main, startup, {"x": xv}, [h], scope)

    apply_pass(main, "fc_fuse_pass")  # no bias -> fc pass leaves bare mul
    apply_pass(main, "fc_gru_fuse_pass")
    assert main._fc_gru_fused_count == 1
    types = _op_types(main)
    assert "fusion_gru" in types and "mul" not in types
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed={"x": xv}, fetch_list=[h])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-4, atol=1e-5)


def test_seqexpand_concat_fc_fuse_pass():
    """sequence_expand + concat + fc -> fusion_seqexpand_concat_fc
    (seq_concat_fc_fuse_pass role): fires after fc_fuse, matches
    numerically, and leaves train programs alone."""
    def build():
        main, startup = _fresh()
        with fluid.framework.program_guard(main, startup):
            startup.random_seed = 7
            seq = layers.data("seq", shape=[5, 12])  # [B, T, D]
            vec = layers.data("vec", shape=[6])      # [B, D1]
            exp = layers.sequence_expand(vec, seq)
            cat = layers.concat([seq, exp], axis=2)
            out = layers.fc(cat, 10, num_flatten_dims=2, act="relu")
        return main, startup, out

    rng = np.random.RandomState(1)
    feed = {"seq": rng.rand(3, 5, 12).astype("float32"),
            "vec": rng.rand(3, 6).astype("float32")}

    main, startup, out = build()
    scope = fluid.Scope()
    before, scope = _run(main, startup, feed, [out], scope)
    apply_pass(main, "fc_fuse_pass")
    apply_pass(main, "seqexpand_concat_fc_fuse_pass")
    assert main._seqexpand_concat_fc_fused_count == 1
    types = _op_types(main)
    assert "fusion_seqexpand_concat_fc" in types
    assert "sequence_expand" not in types and "concat" not in types
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed=feed, fetch_list=[out])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-5, atol=1e-6)

    # train program: grad ops consume the intermediates -> must not fire
    main2, startup2, out2 = build()
    with fluid.framework.program_guard(main2, startup2):
        loss = layers.mean(out2)
        fluid.optimizer.SGD(0.1).minimize(loss)
    apply_pass(main2, "seqexpand_concat_fc_fuse_pass")
    assert main2._seqexpand_concat_fc_fused_count == 0
    assert "sequence_expand" in _op_types(main2)


def test_embedding_fc_lstm_fuse_pass():
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 11
        ids = layers.data("ids", shape=[7], dtype="int64")
        emb = layers.embedding(ids, size=[30, 6])
        proj = layers.fc(emb, 4 * 4, num_flatten_dims=2, bias_attr=False)
        h, c = layers.dynamic_lstm(proj, size=4 * 4)
    iv = np.random.RandomState(6).randint(0, 30, (2, 7)).astype("int64")
    scope = fluid.Scope()
    before, scope = _run(main, startup, {"ids": iv}, [h], scope)

    apply_pass(main, "embedding_fc_lstm_fuse_pass")
    assert main._emb_fc_lstm_fused_count == 1
    types = _op_types(main)
    assert "fused_embedding_fc_lstm" in types
    assert "lookup_table" not in types and "mul" not in types
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed={"ids": iv}, fetch_list=[h])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-4, atol=1e-5)


def test_analysis_predictor_runs_fuse_pipeline(tmp_path):
    """The AnalysisConfig default pipeline applies the fusion suite to a
    saved model and predictions stay identical to the Native predictor."""
    from paddle_tpu.inference import (
        AnalysisConfig,
        NativeConfig,
        create_paddle_predictor,
    )

    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 13
        x = layers.data("x", shape=[8])
        y = layers.fc(layers.fc(x, 16, act="relu"), 4, act="softmax")
    scope = fluid.Scope()
    d = str(tmp_path / "m")
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.save_inference_model(d, ["x"], [y], exe, main_program=main)

    xv = np.random.RandomState(7).rand(3, 8).astype("float32")
    native = create_paddle_predictor(NativeConfig(model_dir=d))
    analysis = create_paddle_predictor(AnalysisConfig(model_dir=d))
    out_n = native.run({"x": xv})
    out_a = analysis.run({"x": xv})
    np.testing.assert_allclose(np.asarray(out_n[0]), np.asarray(out_a[0]),
                               rtol=1e-5, atol=1e-6)
    assert "fc" in [op.type for op in analysis.program.global_block().ops]


def test_build_strategy_fuse_knob_applies_pass():
    """BuildStrategy.fuse_elewise_add_act_ops=True rewrites the PE's
    forward program pre-compile with unchanged results."""
    from paddle_tpu.parallel_executor import BuildStrategy, ParallelExecutor

    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        a = layers.data("a", shape=[6])
        b = layers.data("b", shape=[6])
        y = layers.relu(layers.elementwise_add(a, b))
    av = np.random.RandomState(8).rand(8, 6).astype("float32")
    bv = np.random.RandomState(9).rand(8, 6).astype("float32")
    ref, _ = _run(main, startup, {"a": av, "b": bv}, [y])

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        bs = BuildStrategy()
        bs.fuse_elewise_add_act_ops = True
        pe = ParallelExecutor(use_cuda=False, main_program=main,
                              build_strategy=bs, scope=scope)
        out = pe.run(feed={"a": av, "b": bv}, fetch_list=[y.name])
        # the fusion ran on a clone; the user's program stays pristine
        assert "elementwise_add" in _op_types(main)
        fused_types = [op.type for op in
                       pe._last_fused_program.global_block().ops]
        assert "fused_elemwise_activation" in fused_types
        np.testing.assert_allclose(
            ref[0], np.asarray(out[0]).reshape(ref[0].shape),
            rtol=1e-5, atol=1e-6)
        # fetching the fused-away intermediate still works: that fetch
        # set's clone protects the chain from fusing
        s_name = [op.outputs["Out"][0] for op in main.global_block().ops
                  if op.type == "elementwise_add"][0]
        mid = pe.run(feed={"a": av, "b": bv}, fetch_list=[s_name])
        np.testing.assert_allclose(np.asarray(mid[0]).reshape(av.shape),
                                   av + bv, rtol=1e-5, atol=1e-6)


def test_smooth_label_xent_fuse_numeric_and_grads():
    """one_hot->label_smooth->soft-label-xent folds into ONE
    smooth_label_xent op with identical loss AND parameter grads (closed
    form, no [N,V] label arrays; dist_transformer.py loss idiom)."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.transpiler.pass_registry import apply_pass

    B, T, V = 3, 5, 17
    rng = np.random.RandomState(0)
    xv = rng.randn(B, T, 8).astype("float32")
    yv = rng.randint(0, V, (B, T)).astype("int64")

    def build(fuse):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.framework.program_guard(main, startup):
            startup.random_seed = 11
            x = layers.data("sx3", shape=[B, T, 8], append_batch_size=False)
            lbl = layers.data("sy", shape=[B, T], append_batch_size=False,
                              dtype="int64")
            logits = layers.fc(x, V, num_flatten_dims=2,
                               param_attr=fluid.ParamAttr(name="slx_w"))
            oh = layers.one_hot(lbl, V)
            sm = layers.label_smooth(oh, epsilon=0.1)
            cost = layers.softmax_with_cross_entropy(logits, sm,
                                                     soft_label=True)
            loss = layers.reduce_mean(cost)
            if fuse:
                apply_pass(main, "smooth_label_xent_fuse_pass")
                types = [op.type for op in main.global_block().ops]
                assert "smooth_label_xent" in types, types
                assert "one_hot" not in types and "label_smooth" not in types
                assert main._smooth_xent_fused_count == 1
            fluid.optimizer.SGD(0.5).minimize(loss)
        return main, startup, loss

    results = {}
    for fuse in (False, True):
        main, startup, loss = build(fuse)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            vals = [float(np.asarray(exe.run(
                main, feed={"sx3": xv, "sy": yv}, fetch_list=[loss])[0]))
                for _ in range(3)]
            w = np.array(scope.get("slx_w"))
        results[fuse] = (vals, w)

    np.testing.assert_allclose(results[False][0], results[True][0],
                               rtol=1e-5, atol=1e-6)
    # identical trained weights => identical grads through the fused op
    np.testing.assert_allclose(results[False][1], results[True][1],
                               rtol=1e-5, atol=1e-6)


def test_smooth_label_xent_fuse_guards():
    """Conservative guards: a consumed Softmax output or a PriorDist
    input must block the rewrite."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.transpiler.pass_registry import apply_pass

    B, V = 4, 7
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        x = layers.data("gx", shape=[B, 8], append_batch_size=False)
        lbl = layers.data("gy", shape=[B, 1], append_batch_size=False,
                          dtype="int64")
        logits = layers.fc(x, V)
        oh = layers.one_hot(lbl, V)
        sm = layers.label_smooth(oh, epsilon=0.1)
        cost, softmax = layers.softmax_with_cross_entropy(
            logits, sm, soft_label=True, return_softmax=True)
        out = layers.reduce_mean(cost) + layers.reduce_mean(softmax)
    apply_pass(main, "smooth_label_xent_fuse_pass")
    types = [op.type for op in main.global_block().ops]
    assert "smooth_label_xent" not in types  # Softmax consumed -> no fuse
    assert main._smooth_xent_fused_count == 0

    # PriorDist guard: a non-uniform prior blocks the uniform closed form
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main2, startup2):
        x = layers.data("gx2", shape=[B, 8], append_batch_size=False)
        lbl = layers.data("gy2", shape=[B, 1], append_batch_size=False,
                          dtype="int64")
        prior = layers.data("gp2", shape=[V], append_batch_size=False)
        logits = layers.fc(x, V)
        oh = layers.one_hot(lbl, V)
        sm = layers.label_smooth(oh, prior_dist=prior, epsilon=0.1)
        cost = layers.softmax_with_cross_entropy(logits, sm, soft_label=True)
        layers.reduce_mean(cost)
    apply_pass(main2, "smooth_label_xent_fuse_pass")
    types2 = [op.type for op in main2.global_block().ops]
    assert "smooth_label_xent" not in types2, types2
    assert main2._smooth_xent_fused_count == 0


def test_smooth_label_xent_out_of_range_labels_match_unfused():
    """-1 padding label ids: one_hot emits an all-zero row, so the loss
    there is only the smoothing term — the fused op must match exactly
    (take_along_axis would otherwise wrap to the last vocab entry)."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.transpiler.pass_registry import apply_pass

    N, V = 6, 9
    rng = np.random.RandomState(3)
    xv = rng.randn(N, V).astype("float32")
    yv = rng.randint(0, V, (N, 1)).astype("int64")
    yv[1, 0] = -1
    yv[4, 0] = V + 3

    def run(fuse):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.framework.program_guard(main, startup):
            lg = layers.data("ox", shape=[N, V], append_batch_size=False)
            lbl = layers.data("oy", shape=[N, 1], append_batch_size=False,
                              dtype="int64")
            oh = layers.one_hot(lbl, V)
            sm = layers.label_smooth(oh, epsilon=0.1)
            cost = layers.softmax_with_cross_entropy(lg, sm, soft_label=True)
            if fuse:
                apply_pass(main, "smooth_label_xent_fuse_pass")
                assert main._smooth_xent_fused_count == 1
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            return np.asarray(exe.run(main, feed={"ox": xv, "oy": yv},
                                      fetch_list=[cost])[0])

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# matmul-epilogue layer passes (swiglu / residual-ln / linear-xent)
# ---------------------------------------------------------------------------
def test_swiglu_fuse_pass_fires_and_matches():
    """The gpt2 use_swiglu diamond — mul+swish alongside mul, joined by
    elementwise_mul — collapses to ONE fused_swiglu op, same numbers."""
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 11
        x = layers.data("x", shape=[4, 8])
        gate = layers.fc(x, 12, num_flatten_dims=2, act="swish",
                         bias_attr=False)
        up = layers.fc(x, 12, num_flatten_dims=2, bias_attr=False)
        y = layers.elementwise_mul(gate, up)
    xv = np.random.RandomState(0).rand(2, 4, 8).astype("float32")
    before, scope = _run(main, startup, {"x": xv}, [y])
    assert "swish" in _op_types(main)

    apply_pass(main, "swiglu_fuse_pass")
    assert main._swiglu_fused_count == 1
    types = _op_types(main)
    assert "fused_swiglu" in types
    assert "swish" not in types and "elementwise_mul" not in types
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-5, atol=1e-6)


def test_swiglu_fuse_pass_leaves_mismatched_inputs_alone():
    """Two muls over DIFFERENT inputs must not fuse."""
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        a = layers.data("a", shape=[4, 8])
        b = layers.data("b", shape=[4, 8])
        gate = layers.fc(a, 12, num_flatten_dims=2, act="swish",
                         bias_attr=False)
        up = layers.fc(b, 12, num_flatten_dims=2, bias_attr=False)
        layers.elementwise_mul(gate, up)
    apply_pass(main, "swiglu_fuse_pass")
    assert main._swiglu_fused_count == 0


def test_residual_ln_fuse_pass_fires_and_matches():
    """add -> layer_norm fuses; the SUM survives under its original name
    (it is the residual stream — gpt2 reads it again after the norm)."""
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 12
        a = layers.data("a", shape=[4, 16])
        b = layers.data("b", shape=[4, 16])
        s = layers.elementwise_add(a, b)
        y = layers.layer_norm(s, begin_norm_axis=2)
        z = layers.elementwise_add(s, y)  # sum consumed AGAIN post-norm
    rng = np.random.RandomState(1)
    av = rng.rand(2, 4, 16).astype("float32")
    bv = rng.rand(2, 4, 16).astype("float32")
    before, scope = _run(main, startup, {"a": av, "b": bv}, [s, y, z])

    apply_pass(main, "residual_ln_fuse_pass")
    assert main._residual_ln_fused_count == 1
    types = _op_types(main)
    assert "fused_residual_ln" in types and "layer_norm" not in types
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed={"a": av, "b": bv}, fetch_list=[s, y, z])
    for x, x2 in zip(before, after):
        np.testing.assert_allclose(x, np.asarray(x2), rtol=1e-5, atol=1e-6)


def test_residual_ln_fuse_pass_skips_broadcast_bias_adds():
    """A [H]-bias add is NOT a residual add — the pass must not fire."""
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        a = layers.data("a", shape=[4, 16])
        bias = layers.create_parameter(shape=[16], dtype="float32")
        s = layers.elementwise_add(a, bias)
        layers.layer_norm(s, begin_norm_axis=2)
    apply_pass(main, "residual_ln_fuse_pass")
    assert main._residual_ln_fused_count == 0


def test_fc_fuse_pass_takes_gelu_and_swish_epilogues():
    """mul+bias+gelu collapses to fc(gelu) — the matmul-epilogue form."""
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 13
        x = layers.data("x", shape=[8])
        y = layers.fc(x, 6, act="gelu")
    xv = np.random.RandomState(2).rand(4, 8).astype("float32")
    before, scope = _run(main, startup, {"x": xv}, [y])
    apply_pass(main, "fc_fuse_pass")
    assert main._fc_fused_count == 1
    assert "gelu" not in _op_types(main)
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-5, atol=1e-6)


def test_linear_xent_fuse_pass_fires_and_matches():
    """mul -> softmax_with_cross_entropy (hard label, Softmax unused)
    becomes fused_linear_xent; losses identical (dense path here)."""
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 14
        x = layers.data("x", shape=[4, 8])
        w = layers.create_parameter(shape=[8, 20], dtype="float32")
        logits = layers.matmul(x, w)
        lbl = layers.data("lbl", shape=[4, 1], dtype="int64")
        loss = layers.softmax_with_cross_entropy(logits, lbl)
    # the builder idiom is mul (layers.fc without bias); matmul without
    # transpose is NOT matched — build the mul form explicitly
    main2, startup2 = _fresh()
    with fluid.framework.program_guard(main2, startup2):
        startup2.random_seed = 14
        x = layers.data("x", shape=[4, 8])
        logits = layers.fc(x, 20, num_flatten_dims=2, bias_attr=False)
        lbl = layers.data("lbl", shape=[4, 1], dtype="int64")
        loss = layers.softmax_with_cross_entropy(logits, lbl)
    rng = np.random.RandomState(3)
    xv = rng.rand(2, 4, 8).astype("float32")
    lv = rng.randint(0, 20, (2, 4, 1)).astype("int64")
    before, scope = _run(main2, startup2, {"x": xv, "lbl": lv}, [loss])
    apply_pass(main2, "linear_xent_fuse_pass")
    assert main2._linear_xent_fused_count == 1
    types = _op_types(main2)
    assert "fused_linear_xent" in types
    assert "softmax_with_cross_entropy" not in types and "mul" not in types
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main2, feed={"x": xv, "lbl": lv}, fetch_list=[loss])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-5, atol=1e-6)


def test_linear_xent_fuse_pass_respects_softmax_consumers():
    """A consumed Softmax output (or soft labels) blocks the rewrite —
    the fused op cannot provide either."""
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        x = layers.data("x", shape=[4, 8])
        logits = layers.fc(x, 20, num_flatten_dims=2, bias_attr=False)
        lbl = layers.data("lbl", shape=[4, 1], dtype="int64")
        loss = layers.softmax_with_cross_entropy(logits, lbl)
        # find and consume the Softmax output
        xent = [op for op in main.global_block().ops
                if op.type == "softmax_with_cross_entropy"][0]
        sm_name = xent.outputs["Softmax"][0]
        sm_var = main.global_block().var(sm_name)
        layers.mean(sm_var)
    apply_pass(main, "linear_xent_fuse_pass")
    assert main._linear_xent_fused_count == 0


def test_linear_xent_fuse_pass_skips_non_last_axis_mul():
    """A mul whose row/contraction split is NOT at the last axis
    (x_num_col_dims < rank-1) must not fuse: the fused_linear_xent
    lowering flattens x as [..., H] -> [R, H], which would mismatch the
    mul's contraction dims."""
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        x = layers.data("x", shape=[4, 8])
        # num_flatten_dims=1 on the rank-3 var: rows=B, contract 4*8
        logits = layers.fc(x, 20, num_flatten_dims=1, bias_attr=False)
        lbl = layers.data("lbl", shape=[1], dtype="int64")
        loss = layers.softmax_with_cross_entropy(logits, lbl)
    apply_pass(main, "linear_xent_fuse_pass")
    assert main._linear_xent_fused_count == 0
    assert "fused_linear_xent" not in _op_types(main)


def test_bf16_amp_pass_registry_keeps_f32_master_params():
    """The AMP satellite contract: bf16_amp_pass applied through the
    pass registry BEFORE minimize (the gpt2 builder's use_bf16 route)
    trains with f32 master params — every parameter and optimizer slot
    in the scope stays float32 while the compiled step computes its
    matmul-class ops in bf16."""
    from paddle_tpu.models import gpt2
    import paddle_tpu.framework as fw
    from paddle_tpu import unique_name

    fw.switch_main_program(fluid.Program())
    fw.switch_startup_program(fluid.Program())
    unique_name.switch()

    class HP(gpt2.GPT2Config):
        vocab_size = 40
        n_ctx = 16
        d_model = 16
        n_layer = 1
        n_head = 2
        dropout = 0.0

    main, startup, feeds, fetches = gpt2.gpt2_lm_program(
        HP, seq_len=8, lr=1e-3, use_bf16=True)
    # the AMP rewrite actually engaged (cast ops present)
    assert any(op.type == "cast" for op in main.global_block().ops)
    batch = gpt2.make_fake_lm_batch(2, 8, HP, seed=0)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = []
        for _ in range(2):
            out = exe.run(main, feed=batch, fetch_list=fetches)
            losses.append(float(np.ravel(out[0])[0]))
    assert all(np.isfinite(losses))
    for p in main.global_block().all_parameters():
        got = np.asarray(scope.find_var(p.name))
        assert got.dtype == np.dtype("float32"), (p.name, got.dtype)
