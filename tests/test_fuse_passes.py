"""Fusion-pass parity sweep (ir/*fuse_pass* analogs): each pass must (a)
fire on its pattern — rewriting the op sequence — and (b) leave outputs
numerically identical; train programs (whose grad ops make intermediates
multi-consumer) must be left untouched."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.transpiler import apply_pass


def _fresh():
    main, startup = fluid.Program(), fluid.Program()
    return main, startup


def _run(main, startup, feed, fetch, scope=None):
    scope = scope or fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        out = exe.run(main, feed=feed, fetch_list=fetch)
    return [np.asarray(o) for o in out], scope


def _op_types(prog):
    return [op.type for op in prog.global_block().ops]


def test_fc_fuse_pass_fires_and_matches():
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 3
        x = layers.data("x", shape=[8])
        y = layers.fc(x, 6, act="relu")
    xv = np.random.RandomState(0).rand(4, 8).astype("float32")
    scope = fluid.Scope()
    before, scope = _run(main, startup, {"x": xv}, [y], scope)
    assert "mul" in _op_types(main) and "relu" in _op_types(main)

    apply_pass(main, "fc_fuse_pass")
    assert main._fc_fused_count == 1
    types = _op_types(main)
    assert "fc" in types and "mul" not in types and "relu" not in types
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-5, atol=1e-6)


def test_fc_fuse_pass_leaves_train_programs_alone():
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        x = layers.data("x", shape=[8])
        y = layers.fc(x, 6, act="relu")
        loss = layers.mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
    apply_pass(main, "fc_fuse_pass")
    # grad ops consume the mul/add intermediates -> no single-consumer
    # chain -> the rewrite must not fire (train safety)
    assert main._fc_fused_count == 0
    assert "mul" in _op_types(main)


def test_fuse_elewise_add_act_pass():
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        a = layers.data("a", shape=[6])
        b = layers.data("b", shape=[6])
        s = layers.elementwise_add(a, b)
        y = layers.tanh(s)
    av = np.random.RandomState(1).rand(3, 6).astype("float32")
    bv = np.random.RandomState(2).rand(3, 6).astype("float32")
    before, scope = _run(main, startup, {"a": av, "b": bv}, [y])

    apply_pass(main, "fuse_elewise_add_act_pass")
    assert main._elewise_act_fused_count == 1
    assert "fused_elemwise_activation" in _op_types(main)
    assert "elementwise_add" not in _op_types(main)
    after, _ = _run(main, startup, {"a": av, "b": bv}, [y])
    np.testing.assert_allclose(before[0], after[0], rtol=1e-5, atol=1e-6)


def test_conv_eltadd_relu_fuse_pass():
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 5
        x = layers.data("x", shape=[3, 8, 8])
        c = layers.conv2d(x, num_filters=4, filter_size=3, bias_attr=False)
        bias = layers.create_parameter([4], "float32", name="cb")
        s = layers.elementwise_add(c, layers.reshape(bias, shape=[1, 4, 1, 1]))
        y = layers.relu(s)
    xv = np.random.RandomState(3).rand(2, 3, 8, 8).astype("float32")
    scope = fluid.Scope()
    before, scope = _run(main, startup, {"x": xv}, [y], scope)

    apply_pass(main, "conv_eltadd_relu_fuse_pass")
    assert main._conv_eltadd_fused_count == 1
    types = _op_types(main)
    assert "relu" not in types and "elementwise_add" not in types
    conv = [op for op in main.global_block().ops if op.type == "conv2d"][0]
    assert conv.attrs.get("fuse_relu") and conv.inputs.get("Bias")
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-4, atol=1e-5)


def test_seqconv_eltadd_relu_fuse_pass():
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 7
        x = layers.data("x", shape=[5, 6])  # [B, T, D]
        sc = layers.sequence_conv(x, num_filters=4, filter_size=3,
                                  bias_attr=False)
        bias = layers.create_parameter([4], "float32", name="scb")
        y = layers.relu(layers.elementwise_add(sc, bias))
    xv = np.random.RandomState(4).rand(2, 5, 6).astype("float32")
    scope = fluid.Scope()
    before, scope = _run(main, startup, {"x": xv}, [y], scope)

    apply_pass(main, "seqconv_eltadd_relu_fuse_pass")
    assert main._seqconv_fused_count == 1
    assert "fusion_seqconv_eltadd_relu" in _op_types(main)
    assert "sequence_conv" not in _op_types(main)
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-4, atol=1e-5)


def _gru_program():
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 9
        x = layers.data("x", shape=[5, 6])
        proj = layers.fc(x, 3 * 4, num_flatten_dims=2, bias_attr=False)
        h = layers.dynamic_gru(proj, size=4)
    return main, startup, h


def test_fc_gru_fuse_pass():
    main, startup, h = _gru_program()
    xv = np.random.RandomState(5).rand(2, 5, 6).astype("float32")
    scope = fluid.Scope()
    before, scope = _run(main, startup, {"x": xv}, [h], scope)

    apply_pass(main, "fc_fuse_pass")  # no bias -> fc pass leaves bare mul
    apply_pass(main, "fc_gru_fuse_pass")
    assert main._fc_gru_fused_count == 1
    types = _op_types(main)
    assert "fusion_gru" in types and "mul" not in types
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed={"x": xv}, fetch_list=[h])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-4, atol=1e-5)


def test_seqexpand_concat_fc_fuse_pass():
    """sequence_expand + concat + fc -> fusion_seqexpand_concat_fc
    (seq_concat_fc_fuse_pass role): fires after fc_fuse, matches
    numerically, and leaves train programs alone."""
    def build():
        main, startup = _fresh()
        with fluid.framework.program_guard(main, startup):
            startup.random_seed = 7
            seq = layers.data("seq", shape=[5, 12])  # [B, T, D]
            vec = layers.data("vec", shape=[6])      # [B, D1]
            exp = layers.sequence_expand(vec, seq)
            cat = layers.concat([seq, exp], axis=2)
            out = layers.fc(cat, 10, num_flatten_dims=2, act="relu")
        return main, startup, out

    rng = np.random.RandomState(1)
    feed = {"seq": rng.rand(3, 5, 12).astype("float32"),
            "vec": rng.rand(3, 6).astype("float32")}

    main, startup, out = build()
    scope = fluid.Scope()
    before, scope = _run(main, startup, feed, [out], scope)
    apply_pass(main, "fc_fuse_pass")
    apply_pass(main, "seqexpand_concat_fc_fuse_pass")
    assert main._seqexpand_concat_fc_fused_count == 1
    types = _op_types(main)
    assert "fusion_seqexpand_concat_fc" in types
    assert "sequence_expand" not in types and "concat" not in types
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed=feed, fetch_list=[out])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-5, atol=1e-6)

    # train program: grad ops consume the intermediates -> must not fire
    main2, startup2, out2 = build()
    with fluid.framework.program_guard(main2, startup2):
        loss = layers.mean(out2)
        fluid.optimizer.SGD(0.1).minimize(loss)
    apply_pass(main2, "seqexpand_concat_fc_fuse_pass")
    assert main2._seqexpand_concat_fc_fused_count == 0
    assert "sequence_expand" in _op_types(main2)


def test_embedding_fc_lstm_fuse_pass():
    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 11
        ids = layers.data("ids", shape=[7], dtype="int64")
        emb = layers.embedding(ids, size=[30, 6])
        proj = layers.fc(emb, 4 * 4, num_flatten_dims=2, bias_attr=False)
        h, c = layers.dynamic_lstm(proj, size=4 * 4)
    iv = np.random.RandomState(6).randint(0, 30, (2, 7)).astype("int64")
    scope = fluid.Scope()
    before, scope = _run(main, startup, {"ids": iv}, [h], scope)

    apply_pass(main, "embedding_fc_lstm_fuse_pass")
    assert main._emb_fc_lstm_fused_count == 1
    types = _op_types(main)
    assert "fused_embedding_fc_lstm" in types
    assert "lookup_table" not in types and "mul" not in types
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        after = exe.run(main, feed={"ids": iv}, fetch_list=[h])
    np.testing.assert_allclose(before[0], np.asarray(after[0]),
                               rtol=1e-4, atol=1e-5)


def test_analysis_predictor_runs_fuse_pipeline(tmp_path):
    """The AnalysisConfig default pipeline applies the fusion suite to a
    saved model and predictions stay identical to the Native predictor."""
    from paddle_tpu.inference import (
        AnalysisConfig,
        NativeConfig,
        create_paddle_predictor,
    )

    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        startup.random_seed = 13
        x = layers.data("x", shape=[8])
        y = layers.fc(layers.fc(x, 16, act="relu"), 4, act="softmax")
    scope = fluid.Scope()
    d = str(tmp_path / "m")
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.save_inference_model(d, ["x"], [y], exe, main_program=main)

    xv = np.random.RandomState(7).rand(3, 8).astype("float32")
    native = create_paddle_predictor(NativeConfig(model_dir=d))
    analysis = create_paddle_predictor(AnalysisConfig(model_dir=d))
    out_n = native.run({"x": xv})
    out_a = analysis.run({"x": xv})
    np.testing.assert_allclose(np.asarray(out_n[0]), np.asarray(out_a[0]),
                               rtol=1e-5, atol=1e-6)
    assert "fc" in [op.type for op in analysis.program.global_block().ops]


def test_build_strategy_fuse_knob_applies_pass():
    """BuildStrategy.fuse_elewise_add_act_ops=True rewrites the PE's
    forward program pre-compile with unchanged results."""
    from paddle_tpu.parallel_executor import BuildStrategy, ParallelExecutor

    main, startup = _fresh()
    with fluid.framework.program_guard(main, startup):
        a = layers.data("a", shape=[6])
        b = layers.data("b", shape=[6])
        y = layers.relu(layers.elementwise_add(a, b))
    av = np.random.RandomState(8).rand(8, 6).astype("float32")
    bv = np.random.RandomState(9).rand(8, 6).astype("float32")
    ref, _ = _run(main, startup, {"a": av, "b": bv}, [y])

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        bs = BuildStrategy()
        bs.fuse_elewise_add_act_ops = True
        pe = ParallelExecutor(use_cuda=False, main_program=main,
                              build_strategy=bs, scope=scope)
        out = pe.run(feed={"a": av, "b": bv}, fetch_list=[y.name])
        # the fusion ran on a clone; the user's program stays pristine
        assert "elementwise_add" in _op_types(main)
        fused_types = [op.type for op in
                       pe._last_fused_program.global_block().ops]
        assert "fused_elemwise_activation" in fused_types
        np.testing.assert_allclose(
            ref[0], np.asarray(out[0]).reshape(ref[0].shape),
            rtol=1e-5, atol=1e-6)
        # fetching the fused-away intermediate still works: that fetch
        # set's clone protects the chain from fusing
        s_name = [op.outputs["Out"][0] for op in main.global_block().ops
                  if op.type == "elementwise_add"][0]
        mid = pe.run(feed={"a": av, "b": bv}, fetch_list=[s_name])
        np.testing.assert_allclose(np.asarray(mid[0]).reshape(av.shape),
                                   av + bv, rtol=1e-5, atol=1e-6)


def test_smooth_label_xent_fuse_numeric_and_grads():
    """one_hot->label_smooth->soft-label-xent folds into ONE
    smooth_label_xent op with identical loss AND parameter grads (closed
    form, no [N,V] label arrays; dist_transformer.py loss idiom)."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.transpiler.pass_registry import apply_pass

    B, T, V = 3, 5, 17
    rng = np.random.RandomState(0)
    xv = rng.randn(B, T, 8).astype("float32")
    yv = rng.randint(0, V, (B, T)).astype("int64")

    def build(fuse):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.framework.program_guard(main, startup):
            startup.random_seed = 11
            x = layers.data("sx3", shape=[B, T, 8], append_batch_size=False)
            lbl = layers.data("sy", shape=[B, T], append_batch_size=False,
                              dtype="int64")
            logits = layers.fc(x, V, num_flatten_dims=2,
                               param_attr=fluid.ParamAttr(name="slx_w"))
            oh = layers.one_hot(lbl, V)
            sm = layers.label_smooth(oh, epsilon=0.1)
            cost = layers.softmax_with_cross_entropy(logits, sm,
                                                     soft_label=True)
            loss = layers.reduce_mean(cost)
            if fuse:
                apply_pass(main, "smooth_label_xent_fuse_pass")
                types = [op.type for op in main.global_block().ops]
                assert "smooth_label_xent" in types, types
                assert "one_hot" not in types and "label_smooth" not in types
                assert main._smooth_xent_fused_count == 1
            fluid.optimizer.SGD(0.5).minimize(loss)
        return main, startup, loss

    results = {}
    for fuse in (False, True):
        main, startup, loss = build(fuse)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            vals = [float(np.asarray(exe.run(
                main, feed={"sx3": xv, "sy": yv}, fetch_list=[loss])[0]))
                for _ in range(3)]
            w = np.array(scope.get("slx_w"))
        results[fuse] = (vals, w)

    np.testing.assert_allclose(results[False][0], results[True][0],
                               rtol=1e-5, atol=1e-6)
    # identical trained weights => identical grads through the fused op
    np.testing.assert_allclose(results[False][1], results[True][1],
                               rtol=1e-5, atol=1e-6)


def test_smooth_label_xent_fuse_guards():
    """Conservative guards: a consumed Softmax output or a PriorDist
    input must block the rewrite."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.transpiler.pass_registry import apply_pass

    B, V = 4, 7
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        x = layers.data("gx", shape=[B, 8], append_batch_size=False)
        lbl = layers.data("gy", shape=[B, 1], append_batch_size=False,
                          dtype="int64")
        logits = layers.fc(x, V)
        oh = layers.one_hot(lbl, V)
        sm = layers.label_smooth(oh, epsilon=0.1)
        cost, softmax = layers.softmax_with_cross_entropy(
            logits, sm, soft_label=True, return_softmax=True)
        out = layers.reduce_mean(cost) + layers.reduce_mean(softmax)
    apply_pass(main, "smooth_label_xent_fuse_pass")
    types = [op.type for op in main.global_block().ops]
    assert "smooth_label_xent" not in types  # Softmax consumed -> no fuse
    assert main._smooth_xent_fused_count == 0

    # PriorDist guard: a non-uniform prior blocks the uniform closed form
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main2, startup2):
        x = layers.data("gx2", shape=[B, 8], append_batch_size=False)
        lbl = layers.data("gy2", shape=[B, 1], append_batch_size=False,
                          dtype="int64")
        prior = layers.data("gp2", shape=[V], append_batch_size=False)
        logits = layers.fc(x, V)
        oh = layers.one_hot(lbl, V)
        sm = layers.label_smooth(oh, prior_dist=prior, epsilon=0.1)
        cost = layers.softmax_with_cross_entropy(logits, sm, soft_label=True)
        layers.reduce_mean(cost)
    apply_pass(main2, "smooth_label_xent_fuse_pass")
    types2 = [op.type for op in main2.global_block().ops]
    assert "smooth_label_xent" not in types2, types2
    assert main2._smooth_xent_fused_count == 0


def test_smooth_label_xent_out_of_range_labels_match_unfused():
    """-1 padding label ids: one_hot emits an all-zero row, so the loss
    there is only the smoothing term — the fused op must match exactly
    (take_along_axis would otherwise wrap to the last vocab entry)."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.transpiler.pass_registry import apply_pass

    N, V = 6, 9
    rng = np.random.RandomState(3)
    xv = rng.randn(N, V).astype("float32")
    yv = rng.randint(0, V, (N, 1)).astype("int64")
    yv[1, 0] = -1
    yv[4, 0] = V + 3

    def run(fuse):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.framework.program_guard(main, startup):
            lg = layers.data("ox", shape=[N, V], append_batch_size=False)
            lbl = layers.data("oy", shape=[N, 1], append_batch_size=False,
                              dtype="int64")
            oh = layers.one_hot(lbl, V)
            sm = layers.label_smooth(oh, epsilon=0.1)
            cost = layers.softmax_with_cross_entropy(lg, sm, soft_label=True)
            if fuse:
                apply_pass(main, "smooth_label_xent_fuse_pass")
                assert main._smooth_xent_fused_count == 1
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            return np.asarray(exe.run(main, feed={"ox": xv, "oy": yv},
                                      fetch_list=[cost])[0])

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5, atol=1e-6)
