"""Multi-process distributed training on localhost
(test_dist_base.py:34 TestDistBase.check_with_place analog): spawn real
pserver + trainer subprocesses, compare dist losses to a local run."""

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

# multi-process / full-train-cycle integration tests: excluded from the
# default fast run (pytest.ini addopts -m "not slow"); run with -m "" 
pytestmark = pytest.mark.slow

_DIR = os.path.dirname(os.path.abspath(__file__))
_RUNNER = os.path.join(_DIR, "dist_mlp.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(env):
    full = dict(os.environ)
    full.update(env)
    full["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, _RUNNER],
        env=full,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )


def _losses(proc, timeout=240):
    out, err = proc.communicate(timeout=timeout)
    assert proc.returncode == 0, "runner failed:\n%s\n%s" % (out, err)
    for line in out.splitlines():
        if line.startswith("LOSSES "):
            return json.loads(line[len("LOSSES "):])
    raise AssertionError("no LOSSES line in output:\n%s\n%s" % (out, err))


def _wait_port(port, timeout=60):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=1).close()
            return
        except OSError:
            time.sleep(0.2)
    raise TimeoutError("pserver port %d never opened" % port)


def _run_cluster(n_trainers, sync=True, steps=4, extra_env=None):
    ports = [_free_port(), _free_port()]
    eps = ",".join("127.0.0.1:%d" % p for p in ports)
    common = {
        "PADDLE_PSERVER_EPS": eps,
        "PADDLE_TRAINERS": str(n_trainers),
        "DIST_SYNC_MODE": "1" if sync else "0",
        "DIST_STEPS": str(steps),
    }
    common.update(extra_env or {})
    pservers = [
        _spawn(
            dict(
                common,
                PADDLE_TRAINING_ROLE="PSERVER",
                PADDLE_CURRENT_ENDPOINT="127.0.0.1:%d" % p,
            )
        )
        for p in ports
    ]
    try:
        for p in ports:
            _wait_port(p)
        trainers = [
            _spawn(
                dict(
                    common,
                    PADDLE_TRAINING_ROLE="TRAINER",
                    PADDLE_TRAINER_ID=str(i),
                )
            )
            for i in range(n_trainers)
        ]
        losses = [_losses(t) for t in trainers]
        for ps in pservers:
            ps.communicate(timeout=90)
        return losses
    finally:
        for ps in pservers:
            if ps.poll() is None:
                ps.kill()


def _local_losses(steps=4, extra_env=None):
    env = {"PADDLE_TRAINING_ROLE": "LOCAL", "DIST_STEPS": str(steps)}
    env.update(extra_env or {})
    proc = _spawn(env)
    return _losses(proc)


@pytest.mark.slow
def test_dist_sync_1trainer_matches_local():
    """1 trainer + 2 pservers sync == local run exactly (same data, same
    init by construction: identical seeded startup on trainer & pservers)."""
    local = _local_losses()
    (dist,) = _run_cluster(1, sync=True)
    np.testing.assert_allclose(dist, local, rtol=2e-4, atol=1e-5)


@pytest.mark.slow
def test_dist_sync_2trainers_matches_local_global_batch():
    """2 trainers on half-batches, grads averaged on pservers == local
    full-batch run: mean of the two trainers' losses equals the local loss
    at every step."""
    local = _local_losses()
    l0, l1 = _run_cluster(2, sync=True)
    merged = (np.array(l0) + np.array(l1)) / 2.0
    np.testing.assert_allclose(merged, local, rtol=2e-3, atol=1e-4)


@pytest.mark.slow
def test_dist_adam_lr_decay_matches_local():
    """Adam + exponential LR decay + per-param lr: the decay chain moves to
    the pservers (lrsched role), moments are sliced per block, beta pows
    are per-block copies — dist must still match local exactly."""
    env = {"DIST_OPTIMIZER": "adam_decay"}
    local = _local_losses(steps=5, extra_env=env)
    (dist,) = _run_cluster(1, sync=True, steps=5, extra_env=env)
    np.testing.assert_allclose(dist, local, rtol=2e-4, atol=1e-5)


@pytest.mark.slow
def test_dist_async_trains():
    """Async mode: no barriers; loss must still go down."""
    losses = _run_cluster(2, sync=False, steps=6)
    for l in losses:
        assert l[-1] < l[0]


@pytest.mark.slow
def test_dist_sparse_lookup_table_matches_local():
    """Distributed lookup table: embedding rows sharded over pservers,
    prefetch forward + sparse SGD backward at the round barrier —
    1-trainer run matches the local plain-embedding run exactly."""
    env = {"DIST_MODEL": "sparse"}
    local = _local_losses(steps=5, extra_env=env)
    (dist,) = _run_cluster(1, sync=True, steps=5, extra_env=env)
    np.testing.assert_allclose(dist, local, rtol=2e-4, atol=1e-5)


@pytest.mark.slow
def test_dist_sparse_lookup_momentum_matches_local():
    """Sparse momentum on the pserver: the densified
    SparseMomentumFunctor rule per shard (every row's velocity decays
    each round, momentum_op.h:343) — dist matches the local is_sparse
    momentum run exactly."""
    env = {"DIST_MODEL": "sparse", "DIST_OPTIMIZER": "momentum"}
    local = _local_losses(steps=6, extra_env=env)
    (dist,) = _run_cluster(1, sync=True, steps=6, extra_env=env)
    np.testing.assert_allclose(dist, local, rtol=2e-4, atol=1e-5)


@pytest.mark.slow
def test_dist_sparse_lookup_adam_decay_matches_local():
    """VERDICT r4 #6: the sparse pserver path beyond SGD — the table's
    ADAM slot state (moments + beta pows) lives per shard on the
    pserver, the lr comes DECAYED from the pserver's lr_program, and the
    dist run matches the local lazy-adam (is_sparse) run exactly."""
    env = {"DIST_MODEL": "sparse", "DIST_OPTIMIZER": "adam_decay"}
    local = _local_losses(steps=6, extra_env=env)
    (dist,) = _run_cluster(1, sync=True, steps=6, extra_env=env)
    np.testing.assert_allclose(dist, local, rtol=2e-4, atol=1e-5)


@pytest.mark.slow
def test_dist_sparse_adam_skewed_shard_matches_local():
    """Code-review r5 E2E: ids chosen so EVERY row hashes to pserver 0 —
    pserver 1's shard sees only rowless rounds, whose adam beta pows
    must still advance in lockstep with the local run (the stall the
    per-round advance exists to prevent)."""
    env = {"DIST_MODEL": "sparse", "DIST_OPTIMIZER": "adam_decay",
           "DIST_SPARSE_IDS": "even"}
    local = _local_losses(steps=6, extra_env=env)
    (dist,) = _run_cluster(1, sync=True, steps=6, extra_env=env)
    np.testing.assert_allclose(dist, local, rtol=2e-4, atol=1e-5)


@pytest.mark.slow
def test_collective_mode_2process_matches_local():
    """Collective dense-grad backend over a REAL 2-process mesh
    (launch --mode collective + jax.distributed/gloo): every trainer
    reports the same global (pmean'd) loss trajectory, it matches the
    local full-batch run to reduction-order tolerance, and the COUNTERS
    line proves zero rpc round trips — the dense path never leaves the
    compiled step."""
    local = _local_losses()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               DIST_MODE="collective", DIST_STEPS="4")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--mode", "collective", "--nproc", "2", "tests/dist_mlp.py"],
        cwd=_DIR + "/..", env=env, timeout=600,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    text = r.stdout.decode()
    assert r.returncode == 0, text
    losses, counters = [], []
    for line in text.splitlines():
        pos = line.find("LOSSES ")
        if pos >= 0:
            losses.append(json.loads(line[pos + len("LOSSES "):]))
        pos = line.find("COUNTERS ")
        if pos >= 0:
            counters.append(json.loads(line[pos + len("COUNTERS "):]))
    assert len(losses) == 2 and len(counters) == 2, text
    # both replicas report the SAME allreduced trajectory
    np.testing.assert_allclose(losses[0], losses[1], rtol=0)
    np.testing.assert_allclose(losses[0], local, rtol=1e-5, atol=1e-7)
    for c in counters:
        assert c["rpc_round_trips"] == 0, c
        assert c.get("rpc_verbs") == {}, c


_NCCL2_RUNNER = os.path.join(_DIR, "dist_nccl2.py")


def _spawn_nccl2(env):
    full = dict(os.environ)
    full.update(env)
    return subprocess.Popen(
        [sys.executable, _NCCL2_RUNNER],
        env=full,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )


@pytest.mark.slow
def test_nccl2_mode_2process_matches_local():
    """nccl2 (multi-host collective DP) path: 2 localhost processes
    bootstrap jax.distributed, psum-average grads over the cross-process
    axis; losses match the 1-process full-batch run
    (test_dist_base.py:34 nccl2 coverage)."""
    port = _free_port()
    coord = "127.0.0.1:%d" % port
    common = {"COORDINATOR": coord, "DIST_STEPS": "4"}
    procs = [
        _spawn_nccl2(
            dict(common, PADDLE_TRAINERS="2", PADDLE_TRAINER_ID=str(i))
        )
        for i in range(2)
    ]
    dist = [_losses(p, timeout=180) for p in procs]
    # both replicas report the same (allreduced) loss
    np.testing.assert_allclose(dist[0], dist[1], rtol=1e-6)

    solo = _spawn_nccl2(
        {
            "COORDINATOR": "127.0.0.1:%d" % _free_port(),
            "DIST_STEPS": "4",
            "PADDLE_TRAINERS": "1",
            "PADDLE_TRAINER_ID": "0",
        }
    )
    local = _losses(solo, timeout=180)
    np.testing.assert_allclose(dist[0], local, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_pserver_checkpoint_kill_and_restart(tmp_path):
    """Fault tolerance (go/pserver service.go:346 capability): async
    pserver checkpoints every round; killing it mid-training and
    restarting recovers from the snapshot (PSERVER RESTORED) and the
    trainer — whose RPC layer retries through the outage — finishes all
    steps with finite losses."""
    port = _free_port()
    eps = "127.0.0.1:%d" % port
    ckpt = str(tmp_path / "ckpt")
    common = {
        "PADDLE_PSERVER_EPS": eps,
        "PADDLE_TRAINERS": "1",
        "DIST_SYNC_MODE": "0",
        "DIST_STEPS": "14",
        "DIST_STEP_SLEEP": "0.4",
        "PADDLE_PSERVER_CKPT_DIR": ckpt,
        "PADDLE_PSERVER_CKPT_EVERY": "1",
        "FLAGS_max_retry": "200",
    }
    ps_env = dict(
        common,
        PADDLE_TRAINING_ROLE="PSERVER",
        PADDLE_CURRENT_ENDPOINT=eps,
    )
    ps1 = _spawn(ps_env)
    try:
        _wait_port(port)
        trainer = _spawn(
            dict(common, PADDLE_TRAINING_ROLE="TRAINER", PADDLE_TRAINER_ID="0")
        )
        # wait until real progress exists: the first shard snapshot on disk
        ckpt_file = os.path.join(ckpt, "pserver_0.ckpt")
        t0 = time.time()
        while time.time() - t0 < 90 and not os.path.exists(ckpt_file):
            time.sleep(0.2)
        assert os.path.exists(ckpt_file), "no checkpoint written before kill"
        time.sleep(0.5)  # let a couple more rounds land
        ps1.kill()
        ps1.wait()
        # restart on the same endpoint; must restore from the snapshot
        ps2 = _spawn(ps_env)
        try:
            losses = _losses(trainer, timeout=360)
            assert len(losses) == 14
            assert np.isfinite(losses).all()
            # recovery, not monotonicity: the restored shard may be a
            # couple of rounds stale, so the loss can bounce right after
            # the restart — but the back half must beat the start
            assert min(losses[7:]) < losses[0], losses
            out, err = ps2.communicate(timeout=90)
            assert "PSERVER RESTORED" in out, (out, err)
        finally:
            if ps2.poll() is None:
                ps2.kill()
    finally:
        if ps1.poll() is None:
            ps1.kill()


def test_pserver_cluster_over_native_transport(tmp_path):
    """The full 2x2 pserver cluster trains over the C++ frame-server
    transport (PADDLE_TPU_NATIVE_RPC=1) with losses identical to the
    Python transport (same wire protocol, native framing/HMAC/IO)."""
    import os
    import subprocess
    import sys

    from paddle_tpu.native import get_lib

    if get_lib() is None:
        pytest.skip("native lib unavailable")

    def run(native):
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
                   DIST_STEPS="4",
                   PADDLE_TPU_NATIVE_RPC="1" if native else "0")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--mode", "pserver", "--nproc", "2", "--pservers", "2",
             "tests/dist_mlp.py"],
            cwd=_DIR + "/..", env=env, timeout=600,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        text = r.stdout.decode()
        assert r.returncode == 0, text
        return sorted(l for l in text.splitlines() if "LOSSES" in l)

    native_losses = run(True)
    python_losses = run(False)
    assert native_losses and native_losses == python_losses


_RING_SP_RUNNER = os.path.join(_DIR, "dist_ring_sp.py")


@pytest.mark.slow
def test_multiprocess_ring_attention_matches_dense():
    """Ring attention over an sp mesh SPANNING 2 processes (4 virtual
    devices each): the ppermute kv ring crosses the jax.distributed
    process boundary — the multi-host long-context path — and value +
    q/k/v grad checksums match the single-process dense reference."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("drs", _RING_SP_RUNNER)
    drs = importlib.util.module_from_spec(spec)
    # only for make_qkv/shape constants; no jax work happens at import
    spec.loader.exec_module(drs)

    port = _free_port()
    common = {"COORDINATOR": "127.0.0.1:%d" % port, "PADDLE_TRAINERS": "2"}
    procs = [
        subprocess.Popen(
            [sys.executable, _RING_SP_RUNNER],
            env=dict(os.environ, **common, PADDLE_TRAINER_ID=str(i)),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, "ring sp runner failed:\n%s\n%s" % (
                out, err)
            for line in out.splitlines():
                if line.startswith("CHECKS "):
                    outs.append(json.loads(line[len("CHECKS "):]))
                    break
            else:
                raise AssertionError("no CHECKS line:\n%s" % out)
    finally:
        # a dead coordinator must not orphan its blocked peer
        for p in procs:
            if p.poll() is None:
                p.kill()
    # both processes report the SAME global result
    np.testing.assert_allclose(outs[0]["val"], outs[1]["val"], rtol=1e-6)
    np.testing.assert_allclose(outs[0]["gsums"], outs[1]["gsums"],
                               rtol=1e-6)

    # single-process dense reference on the same arrays
    import jax
    import jax.numpy as jnp

    q, k, v = (jnp.asarray(x) for x in drs.make_qkv())
    Dh = q.shape[-1]

    def dense_loss(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (Dh ** -0.5)
        mask = np.tril(np.ones((drs.T, drs.T), bool))
        p = jax.nn.softmax(jnp.where(mask[None, None], s, -1e30), -1)
        return jnp.sum(jnp.einsum("bhqk,bhkd->bhqd", p, v) ** 2)

    val_ref, grads_ref = jax.value_and_grad(
        dense_loss, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(outs[0]["val"], float(val_ref), rtol=2e-4)
    np.testing.assert_allclose(
        outs[0]["gsums"], [float(jnp.sum(g ** 2)) for g in grads_ref],
        rtol=2e-3)
