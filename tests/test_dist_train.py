"""Multi-process distributed training on localhost
(test_dist_base.py:34 TestDistBase.check_with_place analog): spawn real
pserver + trainer subprocesses, compare dist losses to a local run."""

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

_DIR = os.path.dirname(os.path.abspath(__file__))
_RUNNER = os.path.join(_DIR, "dist_mlp.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(env):
    full = dict(os.environ)
    full.update(env)
    full["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, _RUNNER],
        env=full,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )


def _losses(proc, timeout=240):
    out, err = proc.communicate(timeout=timeout)
    assert proc.returncode == 0, "runner failed:\n%s\n%s" % (out, err)
    for line in out.splitlines():
        if line.startswith("LOSSES "):
            return json.loads(line[len("LOSSES "):])
    raise AssertionError("no LOSSES line in output:\n%s\n%s" % (out, err))


def _wait_port(port, timeout=60):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=1).close()
            return
        except OSError:
            time.sleep(0.2)
    raise TimeoutError("pserver port %d never opened" % port)


def _run_cluster(n_trainers, sync=True, steps=4, extra_env=None):
    ports = [_free_port(), _free_port()]
    eps = ",".join("127.0.0.1:%d" % p for p in ports)
    common = {
        "PADDLE_PSERVER_EPS": eps,
        "PADDLE_TRAINERS": str(n_trainers),
        "DIST_SYNC_MODE": "1" if sync else "0",
        "DIST_STEPS": str(steps),
    }
    common.update(extra_env or {})
    pservers = [
        _spawn(
            dict(
                common,
                PADDLE_TRAINING_ROLE="PSERVER",
                PADDLE_CURRENT_ENDPOINT="127.0.0.1:%d" % p,
            )
        )
        for p in ports
    ]
    try:
        for p in ports:
            _wait_port(p)
        trainers = [
            _spawn(
                dict(
                    common,
                    PADDLE_TRAINING_ROLE="TRAINER",
                    PADDLE_TRAINER_ID=str(i),
                )
            )
            for i in range(n_trainers)
        ]
        losses = [_losses(t) for t in trainers]
        for ps in pservers:
            ps.communicate(timeout=90)
        return losses
    finally:
        for ps in pservers:
            if ps.poll() is None:
                ps.kill()


def _local_losses(steps=4, extra_env=None):
    env = {"PADDLE_TRAINING_ROLE": "LOCAL", "DIST_STEPS": str(steps)}
    env.update(extra_env or {})
    proc = _spawn(env)
    return _losses(proc)


@pytest.mark.slow
def test_dist_sync_1trainer_matches_local():
    """1 trainer + 2 pservers sync == local run exactly (same data, same
    init by construction: identical seeded startup on trainer & pservers)."""
    local = _local_losses()
    (dist,) = _run_cluster(1, sync=True)
    np.testing.assert_allclose(dist, local, rtol=2e-4, atol=1e-5)


@pytest.mark.slow
def test_dist_sync_2trainers_matches_local_global_batch():
    """2 trainers on half-batches, grads averaged on pservers == local
    full-batch run: mean of the two trainers' losses equals the local loss
    at every step."""
    local = _local_losses()
    l0, l1 = _run_cluster(2, sync=True)
    merged = (np.array(l0) + np.array(l1)) / 2.0
    np.testing.assert_allclose(merged, local, rtol=2e-3, atol=1e-4)


@pytest.mark.slow
def test_dist_adam_lr_decay_matches_local():
    """Adam + exponential LR decay + per-param lr: the decay chain moves to
    the pservers (lrsched role), moments are sliced per block, beta pows
    are per-block copies — dist must still match local exactly."""
    env = {"DIST_OPTIMIZER": "adam_decay"}
    local = _local_losses(steps=5, extra_env=env)
    (dist,) = _run_cluster(1, sync=True, steps=5, extra_env=env)
    np.testing.assert_allclose(dist, local, rtol=2e-4, atol=1e-5)


@pytest.mark.slow
def test_dist_async_trains():
    """Async mode: no barriers; loss must still go down."""
    losses = _run_cluster(2, sync=False, steps=6)
    for l in losses:
        assert l[-1] < l[0]


@pytest.mark.slow
def test_dist_sparse_lookup_table_matches_local():
    """Distributed lookup table: embedding rows sharded over pservers,
    prefetch forward + immediate sparse SGD backward — 1-trainer run
    matches the local plain-embedding run exactly."""
    env = {"DIST_MODEL": "sparse"}
    local = _local_losses(steps=5, extra_env=env)
    (dist,) = _run_cluster(1, sync=True, steps=5, extra_env=env)
    np.testing.assert_allclose(dist, local, rtol=2e-4, atol=1e-5)
