"""Registry-wide OpTest sweep (VERDICT round 1, item 4).

The reference's contract is a per-op test file with output parity + numeric
gradient checks (op_test.py:132,401; ~250 test_*_op.py files).  Here one
table-driven sweep covers the long tail: every case runs the real op
through a program+executor against a numpy reference, and smooth
differentiable ops get central-difference gradient checks through the
actual backward machinery.  test_sweep_coverage_target asserts the direct
per-op coverage floor across the whole test suite.
"""

import math

import numpy as np
import pytest

import paddle_tpu as fluid  # noqa: F401  (registers ops)
from op_test import OpTest, run_single_op

COVERED = set()


def _r(*shape, seed=0, lo=-1.0, hi=1.0):
    rng = np.random.RandomState(seed + sum(shape))
    return (rng.rand(*shape) * (hi - lo) + lo).astype("float32")


def check(op_type, inputs, attrs, outputs, grad=None, atol=1e-5, rtol=1e-4,
          max_rel=5e-3, no_check=None):
    COVERED.add(op_type)

    class T(OpTest):
        def setup(self):
            self.op_type = op_type
            self.inputs = inputs
            self.attrs = attrs
            self.outputs = outputs

    t = T()
    t.check_output(atol=atol, rtol=rtol, no_check_set=no_check)
    if grad:
        t2 = T()
        t2.check_grad(grad, list(outputs)[0], max_relative_error=max_rel)


def probe(op_type, inputs, attrs, out_slots):
    """Run without an expected-output table (shape/properties asserted by
    the caller)."""
    COVERED.add(op_type)
    return run_single_op(op_type, inputs, attrs, out_slots)


_erf = np.vectorize(math.erf)

# name -> (numpy ref(x), attrs, grad_check, input domain)
UNARY = {
    "abs": (np.abs, {}, True, (0.2, 1.0)),
    "ceil": (np.ceil, {}, False, (-1, 1)),
    "floor": (np.floor, {}, False, (-1, 1)),
    "round": (np.round, {}, False, (-1, 1)),
    "exp": (np.exp, {}, True, (-1, 1)),
    "log": (np.log, {}, True, (0.5, 2.0)),
    "sqrt": (np.sqrt, {}, True, (0.5, 2.0)),
    "rsqrt": (lambda x: 1 / np.sqrt(x), {}, True, (0.5, 2.0)),
    "square": (np.square, {}, True, (-1, 1)),
    "reciprocal": (lambda x: 1 / x, {}, True, (0.5, 2.0)),
    "sign": (np.sign, {}, False, (0.2, 1.0)),
    "sin": (np.sin, {}, True, (-1, 1)),
    "cos": (np.cos, {}, True, (-1, 1)),
    "erf": (_erf, {}, True, (-1, 1)),
    "relu": (lambda x: np.maximum(x, 0), {}, True, (0.2, 1.0)),
    "relu6": (lambda x: np.clip(x, 0, 6), {"threshold": 6.0}, True, (0.2, 1.0)),
    "brelu": (
        lambda x: np.clip(x, 0.5, 2.0),
        {"t_min": 0.5, "t_max": 2.0},
        False,
        (0.0, 3.0),
    ),
    "leaky_relu": (
        lambda x: np.where(x > 0, x, 0.02 * x),
        {"alpha": 0.02},
        True,
        (0.2, 1.0),
    ),
    "elu": (
        lambda x: np.where(x > 0, x, 1.0 * (np.exp(x) - 1)),
        {"alpha": 1.0},
        True,
        (0.2, 1.0),
    ),
    "selu": (
        lambda x: 1.0507009873554805 * np.where(
            x > 0, x, 1.6732632423543772 * (np.exp(x) - 1)
        ),
        {},
        True,
        (0.2, 1.0),
    ),
    "gelu": (
        lambda x: 0.5 * x * (1 + _erf(x / np.sqrt(2.0))),
        {},
        True,
        (-1, 1),
    ),
    "sigmoid": (lambda x: 1 / (1 + np.exp(-x)), {}, True, (-1, 1)),
    "logsigmoid": (lambda x: np.log(1 / (1 + np.exp(-x))), {}, True, (-1, 1)),
    "hard_sigmoid": (
        lambda x: np.clip(0.2 * x + 0.5, 0, 1),
        {"slope": 0.2, "offset": 0.5},
        False,
        (-1, 1),
    ),
    "hard_shrink": (
        lambda x: np.where(np.abs(x) > 0.5, x, 0.0),
        {"threshold": 0.5},
        False,
        (0.6, 1.5),
    ),
    "tanh": (np.tanh, {}, True, (-1, 1)),
    "tanh_shrink": (lambda x: x - np.tanh(x), {}, True, (-1, 1)),
    "stanh": (
        lambda x: 1.7159 * np.tanh(0.67 * x),
        {"scale_a": 0.67, "scale_b": 1.7159},
        True,
        (-1, 1),
    ),
    "softplus": (lambda x: np.log1p(np.exp(x)), {}, True, (-1, 1)),
    "softsign": (lambda x: x / (1 + np.abs(x)), {}, True, (0.2, 1.0)),
    "soft_relu": (
        lambda x: np.log1p(np.exp(np.clip(x, -40.0, 40.0))),
        {"threshold": 40.0},
        True,
        (-1, 1),
    ),
    "swish": (
        lambda x: x / (1 + np.exp(-1.0 * x)),
        {"beta": 1.0},
        True,
        (-1, 1),
    ),
    "thresholded_relu": (
        lambda x: np.where(x > 1.0, x, 0.0),
        {"threshold": 1.0},
        False,
        (1.2, 2.0),
    ),
    "pow": (lambda x: np.power(x, 3.0), {"factor": 3.0}, True, (0.5, 1.5)),
}


@pytest.mark.parametrize("name", sorted(UNARY))
def test_unary_activations(name):
    ref, attrs, do_grad, (lo, hi) = UNARY[name]
    x = _r(2, 3, seed=11, lo=lo, hi=hi)
    check(name, {"X": x}, attrs, {"Out": ref(x)},
          grad=["x"] if do_grad else None)


BINARY = {
    "elementwise_add": (np.add, True),
    "elementwise_sub": (np.subtract, True),
    "elementwise_mul": (np.multiply, True),
    "elementwise_div": (np.divide, True),
    "elementwise_max": (np.maximum, False),
    "elementwise_min": (np.minimum, False),
    "elementwise_pow": (np.power, False),
    "maximum": (np.maximum, False),
    "minimum": (np.minimum, False),
}


@pytest.mark.parametrize("name", sorted(BINARY))
def test_binary_elementwise(name):
    ref, do_grad = BINARY[name]
    x = _r(2, 3, seed=3, lo=0.5, hi=2.0)
    y = _r(2, 3, seed=5, lo=0.5, hi=2.0)
    check(name, {"X": x, "Y": y}, {}, {"Out": ref(x, y)},
          grad=["x", "y"] if do_grad else None)


def test_elementwise_broadcast_axis():
    x = _r(2, 3, 4, seed=1, lo=0.5, hi=2.0)
    y = _r(3, seed=2, lo=0.5, hi=2.0)
    check("elementwise_add", {"X": x, "Y": y}, {"axis": 1},
          {"Out": x + y.reshape(1, 3, 1)})


def test_elementwise_int_mod_floordiv():
    x = np.array([[7, 8, 9]], "int32")
    y = np.array([[2, 3, 4]], "int32")
    check("elementwise_mod", {"X": x, "Y": y}, {}, {"Out": x % y})
    check("elementwise_floordiv", {"X": x, "Y": y}, {}, {"Out": x // y})


COMPARE = {
    "equal": np.equal,
    "not_equal": np.not_equal,
    "less_than": np.less,
    "less_equal": np.less_equal,
    "greater_than": np.greater,
    "greater_equal": np.greater_equal,
}


@pytest.mark.parametrize("name", sorted(COMPARE))
def test_compare_ops(name):
    x = np.array([[1.0, 2.0, 3.0]], "float32")
    y = np.array([[2.0, 2.0, 2.0]], "float32")
    check(name, {"X": x, "Y": y}, {}, {"Out": COMPARE[name](x, y)})


LOGICAL = {
    "logical_and": np.logical_and,
    "logical_or": np.logical_or,
    "logical_xor": np.logical_xor,
}


@pytest.mark.parametrize("name", sorted(LOGICAL))
def test_logical_ops(name):
    x = np.array([True, True, False])
    y = np.array([True, False, False])
    check(name, {"X": x, "Y": y}, {}, {"Out": LOGICAL[name](x, y)})


def test_logical_not():
    x = np.array([True, False])
    check("logical_not", {"X": x}, {}, {"Out": ~x})


REDUCE = {
    "reduce_sum": np.sum,
    "reduce_mean": np.mean,
    "reduce_max": np.max,
    "reduce_min": np.min,
    "reduce_prod": np.prod,
}


@pytest.mark.parametrize("name", sorted(REDUCE))
def test_reduce_ops(name):
    ref = REDUCE[name]
    x = _r(2, 3, 4, seed=7, lo=0.5, hi=1.5)
    check(name, {"X": x}, {"dim": [1]}, {"Out": ref(x, axis=1)},
          grad=["x"] if name in ("reduce_sum", "reduce_mean") else None)
    check(name, {"X": x}, {"dim": [1], "keep_dim": True},
          {"Out": ref(x, axis=1, keepdims=True)})
    check(name, {"X": x}, {"reduce_all": True}, {"Out": ref(x)})


def test_norm_reductions():
    x = _r(2, 3, seed=9, lo=0.5, hi=1.5)
    check("frobenius_norm", {"X": x}, {"reduce_all": True},
          {"Out": np.linalg.norm(x)})
    check("squared_l2_norm", {"X": x}, {}, {"Out": (x * x).sum()}, grad=["x"])
    check("mean", {"X": x}, {}, {"Out": x.mean()}, grad=["x"])


# ---------------------------------------------------------------------------
# shape / indexing / structure
# ---------------------------------------------------------------------------
def test_reshape_squeeze_unsqueeze_flatten():
    x = _r(2, 1, 6, seed=13)
    check("reshape", {"X": x}, {"shape": [3, 4]}, {"Out": x.reshape(3, 4)},
          grad=["x"])
    check("squeeze", {"X": x}, {"axes": [1]}, {"Out": x.squeeze(1)})
    (out,) = probe("squeeze2", {"X": x}, {"axes": [1]}, ["Out"])
    np.testing.assert_allclose(out, x.squeeze(1))
    check("unsqueeze", {"X": x.squeeze(1)}, {"axes": [1]}, {"Out": x})
    (out,) = probe("unsqueeze2", {"X": x.squeeze(1)}, {"axes": [1]}, ["Out"])
    np.testing.assert_allclose(out, x)
    check("flatten", {"X": x}, {"axis": 2}, {"Out": x.reshape(2, 6)})
    (out,) = probe("flatten2", {"X": x}, {"axis": 2}, ["Out"])
    np.testing.assert_allclose(out, x.reshape(2, 6))


def test_transpose_ops():
    x = _r(2, 3, 4, seed=15)
    check("transpose", {"X": x}, {"axis": [2, 0, 1]},
          {"Out": x.transpose(2, 0, 1)}, grad=["x"])


def test_stack_unstack_split_concat():
    a, b = _r(2, 3, seed=17), _r(2, 3, seed=19)
    check("stack", {"X": [("a", a), ("b", b)]}, {"axis": 0},
          {"Y": np.stack([a, b])})
    outs = probe("unstack", {"X": np.stack([a, b])}, {"axis": 0}, [("Y", 2)])
    np.testing.assert_allclose(outs[0], a)
    np.testing.assert_allclose(outs[1], b)
    outs = probe("split", {"X": np.concatenate([a, b], 1)},
                 {"num": 2, "axis": 1}, [("Out", 2)])
    np.testing.assert_allclose(outs[0], a)


def test_expand_tile_ops():
    x = _r(1, 3, seed=21)
    check("expand", {"X": x}, {"expand_times": [2, 1]},
          {"Out": np.tile(x, (2, 1))})
    check("tile", {"X": x}, {"repeat_times": [2, 2]},
          {"Out": np.tile(x, (2, 2))})
    y = np.zeros((4, 3), "float32")
    check("expand_as", {"X": x, "target_tensor": y}, {},
          {"Out": np.broadcast_to(x, (4, 3))})


def test_slice_family():
    x = _r(4, 5, seed=23)
    check("slice", {"Input": x},
          {"axes": [0, 1], "starts": [1, 0], "ends": [3, 4]},
          {"Out": x[1:3, 0:4]}, grad=["input"])
    check("strided_slice", {"Input": x},
          {"axes": [0], "starts": [0], "ends": [4], "strides": [2]},
          {"Out": x[0:4:2]})
    check("crop", {"X": x}, {"offsets": [1, 1], "shape": [2, 3]},
          {"Out": x[1:3, 1:4]})


def test_pad_family():
    x = _r(2, 3, seed=25)
    check("pad", {"X": x}, {"paddings": [1, 1, 0, 2], "pad_value": 0.5},
          {"Out": np.pad(x, [(1, 1), (0, 2)], constant_values=0.5)})
    img = _r(1, 1, 2, 2, seed=27)
    check("pad2d", {"X": img}, {"paddings": [1, 1, 1, 1], "mode": "constant"},
          {"Out": np.pad(img, [(0, 0), (0, 0), (1, 1), (1, 1)])})


def test_reverse_roll():
    x = _r(2, 3, seed=29)
    check("reverse", {"X": x}, {"axis": [1]}, {"Out": x[:, ::-1]})
    check("roll", {"X": x}, {"shifts": [1], "axis": [1]},
          {"Out": np.roll(x, 1, axis=1)})


def test_gather_scatter_family():
    x = _r(5, 3, seed=31)
    idx = np.array([0, 2, 4], "int64")
    check("gather", {"X": x, "Index": idx}, {}, {"Out": x[idx]}, grad=["x"])
    nd_idx = np.array([[0, 1], [2, 0]], "int64")
    check("gather_nd", {"X": x, "Index": nd_idx}, {},
          {"Out": x[nd_idx[:, 0], nd_idx[:, 1]]})
    upd = _r(2, 3, seed=33)
    sidx = np.array([1, 3], "int64")
    ref = x.copy()
    ref[sidx] = upd
    check("scatter", {"X": x, "Ids": sidx, "Updates": upd}, {}, {"Out": ref})
    check("index_select", {"X": x, "Index": np.array([1, 1, 0], "int64")},
          {"dim": 0}, {"Out": x[[1, 1, 0]]})


def test_where_ops():
    c = np.array([[True, False], [False, True]])
    x, y = _r(2, 2, seed=35), _r(2, 2, seed=37)
    check("where", {"Condition": c, "X": x, "Y": y}, {},
          {"Out": np.where(c, x, y)})
    (out,) = probe("where_index", {"Condition": np.array([0, 1, 1, 0])}, {},
                   ["Out"])
    # padded contract: first rows are the true indices
    np.testing.assert_array_equal(np.sort(out.reshape(-1)[:2]), [1, 2])


def test_tensor_generators():
    check("eye", {}, {"num_rows": 3, "num_columns": 4}, {"Out": np.eye(3, 4, dtype="float32")})
    check("linspace", {}, {"start": 0.0, "stop": 1.0, "num": 5},
          {"Out": np.linspace(0, 1, 5, dtype="float32")})
    check("range", {}, {"start": 1.0, "end": 7.0, "step": 2.0},
          {"Out": np.arange(1, 7, 2, dtype="float32")})
    check("diag", {"Diagonal": np.array([1.0, 2.0], "float32")}, {},
          {"Out": np.diag([1.0, 2.0]).astype("float32")})
    x = _r(2, 2, seed=39)
    check("fill_any_like", {"X": x}, {"value": 3.0},
          {"Out": np.full_like(x, 3.0)})
    outs = probe("meshgrid", {"X": [("mx", np.arange(2.0, dtype="float32")),
                                    ("my", np.arange(3.0, dtype="float32"))]},
                 {}, [("Out", 2)])
    np.testing.assert_allclose(outs[0], np.broadcast_to([[0.], [1.]], (2, 3)))


def test_index_and_sort_ops():
    x = np.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]], "float32")
    check("arg_max", {"X": x}, {"axis": 1}, {"Out": x.argmax(1)})
    check("arg_min", {"X": x}, {"axis": 1}, {"Out": x.argmin(1)})
    out, idx = probe("argsort", {"X": x}, {"axis": 1}, ["Out", "Indices"])
    np.testing.assert_allclose(out, np.sort(x, 1))
    np.testing.assert_array_equal(idx, np.argsort(x, 1))
    check("cumsum", {"X": x}, {"axis": 1}, {"Out": np.cumsum(x, 1)})


def test_misc_tensor_ops():
    x = _r(2, 3, seed=41, lo=0.5, hi=2.0)
    check("assign", {"X": x}, {}, {"Out": x})
    check("shape", {"Input": x}, {}, {"Out": np.array([2, 3], "int32")})
    check("clip", {"X": x}, {"min": 0.8, "max": 1.2},
          {"Out": np.clip(x, 0.8, 1.2)})
    n = np.linalg.norm(x)
    check("clip_by_norm", {"X": x}, {"max_norm": 1.0}, {"Out": x / n})
    check("l2_normalize", {"X": x}, {"axis": 1},
          {"Out": x / np.linalg.norm(x, axis=1, keepdims=True)})
    check("dot", {"X": x, "Y": x}, {},
          {"Out": (x * x).sum(axis=1, keepdims=True)})
    check("isfinite", {"X": np.array([1.0, np.inf], "float32")}, {},
          {"Out": np.array(False)})
    check("label_smooth", {"X": np.array([[0.0, 1.0]], "float32")},
          {"epsilon": 0.1}, {"Out": np.array([[0.05, 0.95]], "float32")})
    check("one_hot", {"X": np.array([[1], [0]], "int64")}, {"depth": 3},
          {"Out": np.array([[0, 1, 0], [1, 0, 0]], "float32")})


def test_cos_sim_and_similarity():
    x, y = _r(2, 4, seed=43, lo=0.5), _r(2, 4, seed=45, lo=0.5)
    cs = (x * y).sum(1) / (np.linalg.norm(x, axis=1) * np.linalg.norm(y, axis=1))
    out = probe("cos_sim", {"X": x, "Y": y}, {},
                ["Out", "XNorm", "YNorm"])
    np.testing.assert_allclose(out[0].reshape(-1), cs, rtol=1e-5)


def test_bilinear_and_interp():
    x = np.arange(4, dtype="float32").reshape(1, 1, 2, 2)
    (out,) = probe("nearest_interp", {"X": x},
                   {"out_h": 4, "out_w": 4}, ["Out"])
    assert out.shape == (1, 1, 4, 4)
    np.testing.assert_allclose(out[0, 0, :2, :2],
                               np.array([[0, 0], [0, 0]], "float32"))
    (out,) = probe("bilinear_interp", {"X": x},
                   {"out_h": 3, "out_w": 3, "align_corners": True}, ["Out"])
    np.testing.assert_allclose(out[0, 0, 0], [0.0, 0.5, 1.0], rtol=1e-5)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------
def test_loss_ops_numpy_parity():
    p = np.array([[0.2, 0.8], [0.6, 0.4]], "float32")
    lbl = np.array([[1], [0]], "int64")
    check("cross_entropy", {"X": p, "Label": lbl}, {},
          {"Y": -np.log(p[np.arange(2), lbl.ravel()]).reshape(-1, 1)})
    x, y = _r(2, 3, seed=47), _r(2, 3, seed=49)
    check("square_error_cost", {"X": x, "Y": y}, {}, {"Out": (x - y) ** 2},
          grad=["x"])
    check("huber_loss", {"X": x, "Y": y}, {"delta": 0.5},
          {"Residual": y - x,
           "Out": np.where(np.abs(y - x) <= 0.5, 0.5 * (y - x) ** 2,
                           0.5 * (np.abs(y - x) - 0.25))},
          no_check=["Residual"])
    logit = _r(2, 3, seed=51)
    label = (np.asarray(_r(2, 3, seed=53)) > 0).astype("float32")
    sig = 1 / (1 + np.exp(-logit))
    ref = -label * np.log(sig) - (1 - label) * np.log(1 - sig)
    check("sigmoid_cross_entropy_with_logits",
          {"X": logit, "Label": label}, {}, {"Out": ref}, grad=["x"])
    d = (x * x).sum(1, keepdims=True) + (y * y).sum(1, keepdims=True) - 2 * (x * y).sum(1, keepdims=True)
    sub = x - y
    check("squared_l2_distance", {"X": x, "Y": y}, {},
          {"sub_result": sub, "Out": (sub * sub).sum(1, keepdims=True)},
          no_check=["sub_result"])


def test_smooth_l1_loss_op():
    x, y = _r(2, 4, seed=55), _r(2, 4, seed=57)
    sigma2 = 1.0
    d = np.abs(x - y)
    ref = np.where(d < 1.0 / sigma2, 0.5 * d * d * sigma2, d - 0.5 / sigma2)
    out = probe("smooth_l1_loss", {"X": x, "Y": y}, {"sigma": 1.0},
                ["Out", "Diff"])
    np.testing.assert_allclose(out[0].reshape(-1), ref.sum(1), rtol=1e-4)


# ---------------------------------------------------------------------------
# nn extras
# ---------------------------------------------------------------------------
def test_norm_ops_against_numpy():
    x = _r(2, 4, 3, 3, seed=59)
    # instance_norm: per (n, c) spatial normalization
    scale = np.ones(4, "float32")
    bias = np.zeros(4, "float32")
    mean = x.mean(axis=(2, 3), keepdims=True)
    var = x.var(axis=(2, 3), keepdims=True)
    ref = (x - mean) / np.sqrt(var + 1e-5)
    out = probe("instance_norm", {"X": x, "Scale": scale, "Bias": bias},
                {"epsilon": 1e-5}, ["Y"])
    np.testing.assert_allclose(out[0], ref, rtol=1e-4, atol=1e-4)
    # group_norm with 2 groups
    g = 2
    xr = x.reshape(2, g, 2, 3, 3)
    gm = xr.mean(axis=(2, 3, 4), keepdims=True)
    gv = xr.var(axis=(2, 3, 4), keepdims=True)
    gref = ((xr - gm) / np.sqrt(gv + 1e-5)).reshape(x.shape)
    out = probe("group_norm", {"X": x, "Scale": scale, "Bias": bias},
                {"groups": g, "epsilon": 1e-5}, ["Y", "Mean", "Variance"])
    np.testing.assert_allclose(out[0], gref, rtol=1e-4, atol=1e-4)
    # norm: l2 along axis
    out = probe("norm", {"X": x}, {"axis": 1, "epsilon": 1e-10},
                ["Out", "Norm"])
    np.testing.assert_allclose(
        out[0], x / np.sqrt((x * x).sum(1, keepdims=True) + 1e-10),
        rtol=1e-4,
    )


def test_prelu_and_maxout():
    x = _r(2, 4, seed=61)
    alpha = np.array([0.25], "float32")
    check("prelu", {"X": x, "Alpha": alpha}, {"mode": "all"},
          {"Out": np.where(x > 0, x, 0.25 * x)})
    xm = _r(1, 4, 2, 2, seed=63)
    ref = xm.reshape(1, 2, 2, 2, 2).max(axis=2)
    check("maxout", {"X": xm}, {"groups": 2}, {"Out": ref})


def test_lrn_local_response_norm():
    x = _r(1, 5, 2, 2, seed=65, lo=0.5)
    n, alpha, beta, k = 5, 1e-4, 0.75, 1.0
    sq = np.zeros_like(x)
    for c in range(5):
        lo_c, hi = max(0, c - n // 2), min(5, c + n // 2 + 1)
        sq[:, c] = (x[:, lo_c:hi] ** 2).sum(1)
    ref = x / (k + alpha * sq) ** beta
    (out, _mid) = probe("lrn", {"X": x}, {"n": n, "alpha": alpha, "beta": beta,
                                          "k": k}, ["Out", "MidOut"])
    np.testing.assert_allclose(out, ref, rtol=1e-4)


def test_affine_grid_sampler_pair():
    theta = np.array([[[1.0, 0, 0], [0, 1.0, 0]]], "float32")  # identity
    (grid,) = probe("affine_grid", {"Theta": theta},
                    {"output_shape": [1, 1, 4, 4]}, ["Output"])
    assert grid.shape == (1, 4, 4, 2)
    np.testing.assert_allclose(grid[0, 0, 0], [-1, -1], atol=1e-6)


def test_lstm_unit_op():
    B, D = 2, 3
    x = _r(B, 4 * D, seed=67)
    c_prev = _r(B, D, seed=69)
    i, f, c, o = np.split(x, 4, axis=1)
    sig = lambda v: 1 / (1 + np.exp(-v))
    c_new = sig(f) * c_prev + sig(i) * np.tanh(c)
    h = sig(o) * np.tanh(c_new)
    check("lstm_unit", {"X": x, "C_prev": c_prev}, {},
          {"C": c_new, "H": h})


def test_im2sequence_op():
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    (out,) = probe("im2sequence", {"X": x},
                   {"kernels": [2, 2], "strides": [2, 2]}, ["Out"])
    assert out.shape[-1] == 4
    np.testing.assert_allclose(np.asarray(out).reshape(-1, 4)[0],
                               [0, 1, 4, 5])


def test_hierarchical_sigmoid_and_nce_run():
    B, D, C = 2, 4, 6
    x = _r(B, D, seed=71)
    label = np.array([[1], [3]], "int64")
    w = _r(C - 1, D, seed=73)
    bias = np.zeros((C - 1,), "float32")
    (cost, pre) = probe(
        "hierarchical_sigmoid",
        {"X": x, "W": w, "Label": label, "Bias": bias},
        {"num_classes": C},
        ["Out", "PreOut"],
    )
    assert np.isfinite(cost).all() and cost.shape[0] == B
    wn = _r(C, D, seed=75)
    bn = np.zeros((C,), "float32")
    sample_ids = np.array([[0, 2], [4, 5]], "int64")
    outs = probe(
        "nce",
        {"Input": x, "Weight": wn, "Bias": bn, "Label": label,
         "CustomDistProbs": np.full((C,), 1.0 / C, "float32"),
         "SampleIds": sample_ids},
        {"num_total_classes": C, "num_neg_samples": 2},
        ["Cost", "SampleLogits", "SampleLabels"],
    )
    assert np.isfinite(outs[0]).all()


def test_random_ops_statistics():
    (g,) = probe("gaussian_random", {}, {"shape": [2000], "mean": 1.0,
                                         "std": 2.0}, ["Out"])
    assert abs(g.mean() - 1.0) < 0.2 and abs(g.std() - 2.0) < 0.2
    (u,) = probe("uniform_random", {}, {"shape": [2000], "min": -2.0,
                                        "max": 2.0}, ["Out"])
    assert -2.0 <= u.min() and u.max() <= 2.0 and abs(u.mean()) < 0.2
    (t,) = probe("truncated_gaussian_random", {}, {"shape": [2000],
                                                   "mean": 0.0, "std": 1.0},
                 ["Out"])
    assert np.abs(t).max() <= 2.0 + 1e-5
    (ri,) = probe("randint", {}, {"shape": [1000], "low": 0, "high": 5},
                  ["Out"])
    assert ri.min() >= 0 and ri.max() < 5
    x = np.zeros((3, 2), "float32")
    (gb,) = probe("gaussian_random_batch_size_like", {"Input": x},
                  {"shape": [-1, 4], "mean": 0.0, "std": 1.0}, ["Out"])
    assert gb.shape == (3, 4)
    (ub,) = probe("uniform_random_batch_size_like", {"Input": x},
                  {"shape": [-1, 4], "min": 0.0, "max": 1.0}, ["Out"])
    assert ub.shape == (3, 4)
    (rc,) = probe("random_crop", {"X": _r(1, 3, 6, 6, seed=77)},
                  {"shape": [3, 4, 4]}, ["Out"])
    assert rc.shape == (1, 3, 4, 4)


def test_sequence_ops_padded():
    x = _r(2, 4, 3, seed=79)
    lens = np.array([4, 2], "int32")
    (out,) = probe("sequence_pool", {"X": x, "SeqLen": lens},
                   {"pooltype": "SUM"}, ["Out"])
    ref = np.stack([x[0].sum(0), x[1, :2].sum(0)])
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    (out,) = probe("sequence_reverse", {"X": x, "SeqLen": lens}, {}, ["Y"])
    np.testing.assert_allclose(out[1, 0], x[1, 1])
    (m,) = probe("sequence_mask", {"X": lens}, {"maxlen": 4}, ["Y"])
    np.testing.assert_array_equal(
        m, np.array([[1, 1, 1, 1], [1, 1, 0, 0]], m.dtype)
    )
    (sm,) = probe("sequence_softmax", {"X": _r(2, 4, seed=81), "SeqLen": lens},
                  {}, ["Out"])
    np.testing.assert_allclose(sm[1, :2].sum(), 1.0, rtol=1e-5)
    (se,) = probe("sequence_expand", {"X": np.array([[1.0], [2.0]], "float32"),
                                      "Y": x}, {}, ["Out"])
    assert se.shape[0] == 2


def test_sequence_slice_unfold_cond_take():
    x = _r(2, 5, 3, seed=91)
    off = np.array([1, 0], "int64")
    ln = np.array([3, 2], "int64")
    out, outlen = probe(
        "sequence_slice", {"X": x, "Offset": off, "Length": ln}, {},
        ["Out", "OutLen"],
    )
    np.testing.assert_allclose(out[0, :3], x[0, 1:4], rtol=1e-6)
    np.testing.assert_allclose(out[1, :2], x[1, :2], rtol=1e-6)
    assert np.all(out[0, 3:] == 0) and np.all(out[1, 2:] == 0)
    np.testing.assert_array_equal(outlen, ln)

    img = _r(1, 2, 4, 4, seed=92)
    (y,) = probe(
        "unfold", {"X": img},
        {"kernel_sizes": [2, 2], "strides": [1, 1], "paddings": [0, 0],
         "dilations": [1, 1]},
        ["Y"],
    )
    assert y.shape == (1, 2 * 2 * 2, 9)
    # first patch = top-left 2x2 window, channel-major
    np.testing.assert_allclose(
        y[0, :, 0],
        img[0, :, :2, :2].reshape(2, -1).reshape(-1),
        rtol=1e-6,
    )

    v = np.array([3.0, -1.0, 4.0, -2.0], "float32")
    mask = np.array([1, 0, 1, 0], "int32")
    taken, count = probe("cond_take", {"X": v, "Mask": mask}, {},
                         ["Out", "Count"])
    np.testing.assert_allclose(taken, [3.0, 4.0, 0.0, 0.0])
    assert int(count[0]) == 2

    # out-of-range window: clamped at the tensor bound, truncated length
    # reported (never duplicated frames presented as valid data)
    out2, outlen2 = probe(
        "sequence_slice",
        {"X": x, "Offset": np.array([3, 0], "int64"),
         "Length": np.array([4, 2], "int64")}, {},
        ["Out", "OutLen"],
    )
    np.testing.assert_array_equal(outlen2, [2, 2])
    np.testing.assert_allclose(out2[0, :2], x[0, 3:5], rtol=1e-6)
    assert np.all(out2[0, 2:] == 0)


def test_auc_pr_curve_and_guards():
    rng = np.random.RandomState(3)
    n, nt = 64, 200
    score = rng.rand(n).astype("float32")
    label = (rng.rand(n) < score).astype("int64")  # informative scores
    z = np.zeros(nt + 1, "float32")
    (roc, sp, sn) = probe(
        "auc", {"Predict": score, "Label": label, "StatPos": z, "StatNeg": z},
        {"num_thresholds": nt, "curve": "ROC"},
        ["AUC", "StatPosOut", "StatNegOut"],
    )
    (pr, _, _) = probe(
        "auc", {"Predict": score, "Label": label, "StatPos": z, "StatNeg": z},
        {"num_thresholds": nt, "curve": "PR"},
        ["AUC", "StatPosOut", "StatNegOut"],
    )
    # sklearn-free sanity: informative scores => both areas well above chance
    assert 0.6 < float(roc) <= 1.0
    base_rate = label.mean()
    assert base_rate < float(pr) <= 1.0
    # perfect classifier: every positive in the top bucket — PR area must be 1
    perf_score = label.astype("float32")
    (pr1, _, _) = probe(
        "auc", {"Predict": perf_score, "Label": label, "StatPos": z,
                "StatNeg": z},
        {"num_thresholds": nt, "curve": "PR"},
        ["AUC", "StatPosOut", "StatNegOut"],
    )
    assert abs(float(pr1) - 1.0) < 1e-6
    with pytest.raises(Exception, match="curve"):
        probe("auc", {"Predict": score, "Label": label, "StatPos": z,
                      "StatNeg": z}, {"curve": "XYZ", "num_thresholds": nt},
              ["AUC", "StatPosOut", "StatNegOut"])
    with pytest.raises(Exception, match="Predict"):
        probe("auc", {"Predict": rng.rand(8, 3).astype("float32"),
                      "Label": label[:8], "StatPos": z, "StatNeg": z},
              {"num_thresholds": nt}, ["AUC", "StatPosOut", "StatNegOut"])


def test_position_encoding_and_interp_extras():
    x = _r(1, 4, 6, seed=83)
    (out,) = probe("add_position_encoding", {"X": x},
                   {"alpha": 1.0, "beta": 1.0}, ["Out"])
    assert out.shape == x.shape
    # pixel_shuffle: [N, C*r^2, H, W] -> [N, C, H*r, W*r]
    ps = _r(1, 4, 2, 2, seed=85)
    (out,) = probe("pixel_shuffle", {"X": ps}, {"upscale_factor": 2}, ["Out"])
    assert out.shape == (1, 1, 4, 4)


def test_quantize_family_roundtrip():
    x = _r(2, 3, seed=87)
    # fake_quantize emits the quant-dequantized value + the abs-max scale
    (q, scale) = probe("fake_quantize_abs_max", {"X": x}, {"bit_length": 8},
                       ["Out", "OutScale"])
    s = float(np.asarray(scale).reshape(-1)[0])
    np.testing.assert_allclose(s, np.abs(x).max(), rtol=1e-5)
    np.testing.assert_allclose(q, x, atol=s / 100)
    ints = np.array([[-127.0, 64.0, 127.0]], "float32")
    (dq,) = probe("fake_dequantize_max_abs",
                  {"X": ints, "Scale": np.array([s], "float32")},
                  {"max_range": 127.0}, ["Out"])
    np.testing.assert_allclose(dq, ints * s / 127.0, rtol=1e-5)


def test_beam_search_and_ctc_shapes():
    # ctc_align: collapse repeats + remove blanks
    ids = np.array([[1, 1, 0, 2, 2, 0, 3]], "int32")
    (out,) = probe("ctc_align", {"Input": ids}, {"blank": 0,
                                                 "merge_repeated": True},
                   ["Output"])
    np.testing.assert_array_equal(np.asarray(out).reshape(-1)[:3], [1, 2, 3])


def test_conv_shift_circular():
    x = _r(2, 5, seed=89)
    y = _r(2, 3, seed=91)
    ref = np.zeros_like(x)
    for b in range(2):
        for i in range(5):
            for j in range(3):
                ref[b, i] += x[b, (i + j - 1) % 5] * y[b, j]
    check("conv_shift", {"X": x, "Y": y}, {}, {"Out": ref})


def test_accuracy_and_auc_ops():
    pred = np.array([[0.1, 0.9], [0.8, 0.2]], "float32")
    label = np.array([[1], [1]], "int64")
    top1 = pred.argmax(-1).reshape(-1, 1).astype("int64")
    out = probe("accuracy", {"Out": pred, "Label": label, "Indices": top1},
                {}, ["Accuracy", "Correct", "Total"])
    np.testing.assert_allclose(float(np.asarray(out[0]).reshape(-1)[0]), 0.5)


def test_scale_bias_ops():
    x = _r(2, 3, seed=93)
    check("scale", {"X": x}, {"scale": 2.0, "bias": 1.0}, {"Out": 2 * x + 1},
          grad=["x"])
    s = np.array([2.0, 3.0, 4.0], "float32")
    b = np.array([0.5, 0.5, 0.5], "float32")
    xc = _r(1, 3, 2, 2, seed=95)
    check("affine_channel", {"X": xc, "Scale": s, "Bias": b}, {},
          {"Out": xc * s.reshape(1, 3, 1, 1) + b.reshape(1, 3, 1, 1)})


def test_lookup_table_v2_and_embedding_grad():
    table = _r(6, 4, seed=97)
    ids = np.array([1, 3, 3], "int64")
    check("lookup_table_v2", {"W": table, "Ids": ids}, {},
          {"Out": table[ids]})


def test_matmul_variants():
    a = _r(2, 3, 4, seed=99)
    b = _r(2, 4, 5, seed=101)
    check("matmul", {"X": a, "Y": b}, {}, {"Out": a @ b}, grad=["x", "y"])
    check("matmul", {"X": a, "Y": _r(2, 3, 5, seed=103)},
          {"transpose_X": True},
          {"Out": np.swapaxes(a, 1, 2) @ _r(2, 3, 5, seed=103)})


def test_pool2d_with_index_sweep():
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    out, mask = probe("pool2d_with_index", {"X": x},
                      {"ksize": [2, 2], "strides": [2, 2]}, ["Out", "Mask"])
    np.testing.assert_allclose(out.reshape(-1), [5, 7, 13, 15])
    np.testing.assert_array_equal(mask.reshape(-1), [5, 7, 13, 15])


def test_average_accumulates_op():
    p = _r(2, 2, seed=105)
    outs = probe(
        "average_accumulates",
        {"param": p,
         "in_sum_1": np.zeros_like(p), "in_sum_2": np.zeros_like(p),
         "in_sum_3": np.zeros_like(p),
         "in_num_accumulates": np.array([0], "int64"),
         "in_old_num_accumulates": np.array([0], "int64"),
         "in_num_updates": np.array([0], "int64")},
        {"average_window": 0.5, "min_average_window": 2,
         "max_average_window": 4},
        ["out_sum_1", "out_sum_2", "out_sum_3", "out_num_accumulates",
         "out_old_num_accumulates", "out_num_updates"],
    )
    np.testing.assert_allclose(outs[0], p)


# ---------------------------------------------------------------------------
# optimizers: one step vs numpy
# ---------------------------------------------------------------------------
def test_optimizer_ops_single_step():
    p = _r(3, seed=107)
    g = _r(3, seed=109)
    lr = np.array([0.1], "float32")
    (out,) = probe("sgd", {"Param": p, "Grad": g, "LearningRate": lr}, {},
                   ["ParamOut"])
    np.testing.assert_allclose(out, p - 0.1 * g, rtol=1e-6)

    v = np.zeros(3, "float32")
    outs = probe("momentum", {"Param": p, "Grad": g, "Velocity": v,
                              "LearningRate": lr}, {"mu": 0.9},
                 ["ParamOut", "VelocityOut"])
    np.testing.assert_allclose(outs[1], g, rtol=1e-6)
    np.testing.assert_allclose(outs[0], p - 0.1 * g, rtol=1e-6)

    m = np.zeros(3, "float32")
    vv = np.zeros(3, "float32")
    b1p = np.array([0.9], "float32")
    b2p = np.array([0.999], "float32")
    outs = probe("adam", {"Param": p, "Grad": g, "Moment1": m, "Moment2": vv,
                          "LearningRate": lr, "Beta1Pow": b1p, "Beta2Pow": b2p},
                 {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
                 ["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
                  "Beta2PowOut"])
    m1 = 0.1 * g
    m2 = 0.001 * g * g
    lr_t = 0.1 * np.sqrt(1 - 0.999) / (1 - 0.9)
    ref = p - lr_t * m1 / (np.sqrt(m2) + 1e-8)
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5)

    acc = np.full(3, 0.1, "float32")
    outs = probe("adagrad", {"Param": p, "Grad": g, "Moment": acc,
                             "LearningRate": lr}, {"epsilon": 1e-6},
                 ["ParamOut", "MomentOut"])
    np.testing.assert_allclose(outs[1], acc + g * g, rtol=1e-6)

    for op, slots in [
        ("adamax", {"Param": p, "Grad": g, "Moment": m, "InfNorm": acc,
                    "LearningRate": lr, "Beta1Pow": b1p}),
        ("adadelta", {"Param": p, "Grad": g, "AvgSquaredGrad": acc,
                      "AvgSquaredUpdate": acc}),
        ("decayed_adagrad", {"Param": p, "Grad": g, "Moment": acc,
                             "LearningRate": lr}),
        ("rmsprop", {"Param": p, "Grad": g, "MeanSquare": acc,
                     "Moment": m, "LearningRate": lr}),
        ("ftrl", {"Param": p, "Grad": g, "SquaredAccumulator": acc,
                  "LinearAccumulator": m, "LearningRate": lr}),
        ("lars_momentum", {"Param": p, "Grad": g, "Velocity": v,
                           "LearningRate": lr}),
    ]:
        outs = probe(op, slots, {}, ["ParamOut"])
        COVERED.add(op)
        assert np.isfinite(outs[0]).all() and not np.allclose(outs[0], p)


def test_remaining_singletons(tmp_path):
    x = _r(1, 2, 4, 4, seed=111)
    (out,) = probe("adaptive_pool2d", {"X": x},
                   {"pooling_size": [2, 2], "pooling_type": "avg"}, ["Out"])
    np.testing.assert_allclose(out, x.reshape(1, 2, 2, 2, 2, 2).mean(axis=(3, 5)),
                               rtol=1e-5)
    lg = _r(2, 3, seed=113)
    e = np.exp(lg - lg.max(-1, keepdims=True))
    check("log_softmax", {"X": lg}, {}, {"Out": np.log(e / e.sum(-1, keepdims=True))},
          grad=["x"])
    check("fill", {}, {"shape": [2, 2], "dtype": "float32",
                       "value": [1.0, 2.0, 3.0, 4.0]},
          {"Out": np.array([[1, 2], [3, 4]], "float32")})
    x3 = _r(1, 2, 3, 4, 4, seed=115)
    (out,) = probe("conv3d", {"Input": x3, "Filter": _r(4, 2, 1, 1, 1, seed=117)},
                   {"strides": [1, 1, 1], "paddings": [0, 0, 0]}, ["Output"])
    assert out.shape == (1, 4, 3, 4, 4)
    xp = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    out, mask = probe("max_pool2d_with_index", {"X": xp},
                      {"ksize": [2, 2], "strides": [2, 2]}, ["Out", "Mask"])
    np.testing.assert_allclose(np.asarray(out).reshape(-1), [5, 7, 13, 15])
    # save_combine/load_combine roundtrip
    import os

    path = str(tmp_path / "combined")
    a, b = _r(2, 2, seed=119), _r(3, seed=121)
    probe("save_combine", {"X": [("sc_a", a), ("sc_b", b)]},
          {"file_path": path}, [])
    outs = probe("load_combine", {}, {"file_path": path}, [("Out", 2)])
    np.testing.assert_allclose(outs[0], a)
    np.testing.assert_allclose(outs[1], b)


# ---------------------------------------------------------------------------
# coverage floor
# ---------------------------------------------------------------------------
# ops directly tested in the OTHER test files (kept in sync by grep:
# run_op(/run_single_op(/append_op(/op_type= string literals under tests/)
COVERED_ELSEWHERE = """
add_position_encoding affine_channel batch_norm bilinear_tensor_product
bpr_loss cast clip concat conv2d conv_shift cos_sim crop depthwise_conv2d
elementwise_add elementwise_div elementwise_mul grid_sampler hash
hinge_loss is_empty kldiv_loss l1_norm layer_norm load log_loss
lookup_table margin_rank_loss matmul mean_iou minus modified_huber_loss
mul multiplex one_hot pad_constant_like pool2d pool3d rank_loss
reduce_mean reduce_sum reshape2 row_conv sampling_id save scale selu
shuffle_channel sigmoid slice softmax softmax_with_cross_entropy
space_to_depth spp squared_l2_distance sum tanh top_k transpose2
write_to_array read_from_array lod_array_length lod_tensor_to_array
array_to_lod_tensor recurrent bounded_while switch ifelse_select
gru_unit padded_gru padded_lstm box_coder multiclass_nms ssd_loss
generate_proposals rpn_target_assign generate_proposal_labels
mine_hard_examples roi_perspective_transform roi_pool roi_align
anchor_generator bipartite_match target_assign iou_similarity prior_box
density_prior_box sequence_conv attention_lstm conv3d_transpose
max_pool3d_with_index data_norm conv2d_transpose sequence_scatter
sequence_erase sequence_enumerate positive_negative_pair edit_distance
chunk_eval linear_chain_crf crf_decoding warpctc beam_search
beam_search_decode fill_constant fill_zeros_like assign_value dropout
lstm_unit accuracy auc precision_recall fake_quantize_range_abs_max
fake_quantize_moving_average_abs_max fake_channel_wise_quantize_abs_max
expand increment less_than greater_than equal model_average_accum
fill_constant_batch_size_like lod_rank_table max_sequence_len
shrink_rnn_memory rnn_memory_helper sequence_expand_as lod_reset
fused_attention im2sequence unpool similarity_focus polygon_box_transform
send recv prefetch send_barrier fetch_barrier send_sparse print delete_var
send_bucket recv_bucket
adamax adadelta decayed_adagrad rmsprop ftrl lars_momentum
fc fusion_seqconv_eltadd_relu fused_embedding_fc_lstm
fusion_seqexpand_concat_fc split_selected_rows split_byref
checkpoint_notify lookup_table_grad lookup_table_v2_grad
""".split()


def test_sweep_coverage_target():
    """>= 300 registered ops have direct test coverage (VERDICT item 4).

    Order-independent: op names are read statically from this module's
    check()/probe() call sites plus the family tables, so the floor holds
    under random/parallel test scheduling too."""
    import os
    import re

    from paddle_tpu.core.registry import OPS

    src = open(os.path.abspath(__file__)).read()
    called = set(
        re.findall(r'(?:check|probe)\(\s*\n?\s*"([a-z0-9_]+)"', src)
    )
    table_ops = (
        set(UNARY) | set(BINARY) | set(COMPARE) | set(LOGICAL) | set(REDUCE)
    )
    direct = (set(COVERED) | called | table_ops | set(COVERED_ELSEWHERE)) & set(OPS)
    missing = sorted(set(OPS) - direct)
    assert len(direct) >= 300, (
        "only %d ops directly tested; missing e.g. %s"
        % (len(direct), missing[:30])
    )


def test_compat_recurrent_ops():
    """gru/lstm/lstmp reference-contract entry points (gru_op.cc,
    lstm_op.cc, lstmp_op.cc) run and agree with the padded lowerings."""
    b, t, h = 2, 5, 4
    xg = _r(b, t, 3 * h, seed=101)
    wg = _r(h, 3 * h, seed=102)
    lens = np.array([5, 3], "int32")
    (hid_ref, last_ref) = probe(
        "padded_gru", {"Input": xg, "Weight": wg, "SeqLen": lens}, {},
        ["Hidden", "LastH"],
    )
    (hid, last) = probe(
        "gru", {"Input": xg, "Weight": wg, "SeqLen": lens}, {},
        ["Hidden", "LastH"],
    )
    np.testing.assert_allclose(hid, hid_ref, rtol=1e-5)
    COVERED.add("fusion_gru")
    (hid_f, _) = probe(
        "fusion_gru", {"Input": xg, "Weight": wg, "SeqLen": lens}, {},
        ["Hidden", "LastH"],
    )
    np.testing.assert_allclose(hid_f, hid_ref, rtol=1e-5)

    xl = _r(b, t, 4 * h, seed=103)
    wl = _r(h, 4 * h, seed=104)
    (hl, cl, lastl) = probe(
        "lstm", {"Input": xl, "Weight": wl, "SeqLen": lens}, {},
        ["Hidden", "Cell", "LastH"],
    )
    (hl_ref, last_ref2, lastc_ref) = probe(
        "padded_lstm", {"Input": xl, "Weight": wl, "SeqLen": lens}, {},
        ["Hidden", "LastH", "LastC"],
    )
    np.testing.assert_allclose(hl, hl_ref, rtol=1e-5)
    # Cell is the per-timestep cell sequence (lstm_op.cc contract): same
    # shape as Hidden, and its final valid step equals LastC
    assert cl.shape == hl.shape
    np.testing.assert_allclose(cl[0, -1], lastc_ref[0], rtol=1e-5)
    np.testing.assert_allclose(cl[1, 2], lastc_ref[1], rtol=1e-5)  # len 3
    COVERED.add("fusion_lstm")

    p = 3
    xp = _r(b, t, 4 * h, seed=105)
    wp = _r(p, 4 * h, seed=106)
    pw = _r(h, p, seed=107)
    (proj, cell, lastc) = probe(
        "lstmp", {"Input": xp, "Weight": wp, "ProjWeight": pw,
                  "SeqLen": lens}, {},
        ["Projection", "Cell", "LastC"],
    )
    assert proj.shape == (b, t, p)
    # Cell is the per-timestep cell sequence; its last step == LastC
    assert cell.shape == (b, t, h)
    np.testing.assert_allclose(cell[0, -1], lastc[0], rtol=1e-6)
    # row 1 frozen past its length: projection at t>=3 equals t=2
    np.testing.assert_allclose(proj[1, 3], proj[1, 2], rtol=1e-6)


def test_compat_sequence_shape_ops():
    b, t, d = 2, 4, 6
    x = _r(b, t, d, seed=111)
    lens = np.array([4, 2], "int32")
    out, length = probe(
        "sequence_pad",
        {"X": x, "PadValue": np.array([0.5], "float32"), "SeqLen": lens},
        {"padded_length": 6}, ["Out", "Length"],
    )
    assert out.shape == (b, 6, d)
    np.testing.assert_allclose(out[1, 2], np.full(d, 0.5), rtol=1e-6)
    np.testing.assert_array_equal(length, [4, 2])
    # padded_length below the time axis could silently truncate: rejected
    with pytest.raises(Exception, match="padded_length"):
        probe("sequence_pad",
              {"X": x, "PadValue": np.array([0.0], "float32"),
               "SeqLen": lens},
              {"padded_length": 3}, ["Out", "Length"])

    (unp,) = probe("sequence_unpad", {"X": x, "Length": lens}, {}, ["Out"])
    assert np.all(unp[1, 2:] == 0)
    np.testing.assert_allclose(unp[0], x[0], rtol=1e-6)

    out_r, len_r = probe(
        "sequence_reshape", {"X": x, "SeqLen": lens}, {"new_dim": 3},
        ["Out", "OutLen"],
    )
    assert out_r.shape == (b, t * d // 3, 3)
    np.testing.assert_array_equal(len_r, [8, 4])
    # non-divisible feature dim with ragged rows would smear valid data
    # into padding: reject
    with pytest.raises(Exception, match="divisible"):
        probe("sequence_reshape", {"X": x, "SeqLen": lens}, {"new_dim": 4},
              ["Out", "OutLen"])
    # dense (no SeqLen) rows have no padding boundary: allowed
    (dense_r,) = probe("sequence_reshape", {"X": _r(2, 8, 2, seed=113)},
                       {"new_dim": 4}, ["Out"])
    assert dense_r.shape == (2, 4, 4)

    y = _r(b, 3, d, seed=112)
    ylens = np.array([1, 3], "int32")
    cat, cat_len = probe(
        "sequence_concat",
        {"X": [("sc_a", x), ("sc_b", y)],
         "SeqLen": [("sc_la", lens), ("sc_lb", ylens)]}, {},
        ["Out", "OutLen"],
    )
    np.testing.assert_array_equal(cat_len, [5, 5])
    np.testing.assert_allclose(cat[0, :4], x[0, :4], rtol=1e-6)
    np.testing.assert_allclose(cat[0, 4], y[0, 0], rtol=1e-6)
    np.testing.assert_allclose(cat[1, :2], x[1, :2], rtol=1e-6)
    np.testing.assert_allclose(cat[1, 2:5], y[1, :3], rtol=1e-6)
    assert np.all(cat[0, 5:] == 0)


def test_compat_lod_plumbing_ops():
    x = _r(6, 3, seed=121)
    mask = np.array([1, 0, 1, 1, 0, 1], "int32")
    ot, of, ct, cf = probe(
        "split_lod_tensor", {"X": x, "Mask": mask}, {},
        ["OutTrue", "OutFalse", "CountTrue", "CountFalse"],
    )
    assert int(ct[0]) == 4 and int(cf[0]) == 2
    np.testing.assert_allclose(ot[:4], x[mask.astype(bool)], rtol=1e-6)
    np.testing.assert_allclose(of[:2], x[~mask.astype(bool)], rtol=1e-6)

    (merged,) = probe(
        "merge_lod_tensor",
        {"InTrue": ot, "InFalse": of, "Mask": mask}, {}, ["Out"],
    )
    np.testing.assert_allclose(merged, x, rtol=1e-6)

    perm = np.array([2, 0, 1, 5, 4, 3], "int32")
    (reord,) = probe(
        "reorder_lod_tensor_by_rank", {"X": x, "RankTable": perm}, {}, ["Out"]
    )
    np.testing.assert_allclose(reord, x[perm], rtol=1e-6)


def test_compat_misc_ops():
    img = _r(1, 2, 4, 4, seed=131)
    (up,) = probe(
        "interpolate", {"X": img},
        {"interp_method": "nearest", "out_h": 8, "out_w": 8}, ["Out"],
    )
    assert up.shape == (1, 2, 8, 8)

    with pytest.raises(Exception, match="interp_method"):
        probe("interpolate", {"X": img}, {"interp_method": "bicubic",
                                          "out_h": 8, "out_w": 8}, ["Out"])

    x = _r(2, 5, seed=132)
    y = _r(2, 5, seed=133)
    # reference compound conventions: [binary, unary] = Binary(X, Unary(Y));
    # [unary, binary] = Unary(Binary(X, Y))
    (fea,) = probe(
        "fused_elemwise_activation", {"X": x, "Y": y},
        {"functor_list": ["elementwise_add", "relu"]}, ["Out"],
    )
    np.testing.assert_allclose(fea, x + np.maximum(y, 0), rtol=1e-6)
    (fea2,) = probe(
        "fused_elemwise_activation", {"X": x, "Y": y},
        {"functor_list": ["relu", "elementwise_add"]}, ["Out"],
    )
    np.testing.assert_allclose(fea2, np.maximum(x + y, 0), rtol=1e-6)

    (fi,) = probe("fake_init", {}, {"shape": [3, 2]}, ["Out"])
    assert fi.shape == (3, 2) and np.all(fi == 0)


def test_split_merge_ids_roundtrip():
    ids = np.array([0, 3, 4, 7, 2, 5], "int64")
    outs = run_single_op("split_ids", {"Ids": ids}, {"num_shards": 2},
                         [("Out", 2), ("Count", 2)])
    shard0, shard1, c0, c1 = outs[0], outs[1], outs[2], outs[3]
    assert int(c0[0]) == 3 and int(c1[0]) == 3  # evens: 0,4,2; odds: 3,7,5
    np.testing.assert_array_equal(np.sort(shard0[:3]), [0, 2, 4])
    np.testing.assert_array_equal(np.sort(shard1[:3]), [3, 5, 7])
    COVERED.add("split_ids")

    # merge: rows for each shard in its compacted id order
    d = 2
    table = np.arange(16, dtype="float32").reshape(8, d)
    rows0 = table[shard0[:3].astype(int)]
    rows0 = np.concatenate([rows0, np.zeros((3, d), "float32")])
    rows1 = table[shard1[:3].astype(int)]
    rows1 = np.concatenate([rows1, np.zeros((3, d), "float32")])
    (merged,) = run_single_op(
        "merge_ids",
        {"Ids": ids, "X": [("mi_r0", rows0), ("mi_r1", rows1)]}, {}, ["Out"]
    )
    np.testing.assert_allclose(merged, table[ids], rtol=1e-6)
    COVERED.add("merge_ids")


def test_overflow_checks_and_remaining_delegates():
    """has_inf/has_nan and the delegate compat ops get direct probes (no
    coverage-by-claim: every name in the floor count has a real test)."""
    x = np.array([[1.0, 2.0], [3.0, 4.0]], "float32")
    (hi,) = probe("has_inf", {"X": x}, {}, ["Out"])
    (hn,) = probe("has_nan", {"X": x}, {}, ["Out"])
    assert not bool(hi) and not bool(hn)
    (hi2,) = probe("has_inf", {"X": np.array([np.inf], "float32")}, {}, ["Out"])
    (hn2,) = probe("has_nan", {"X": np.array([np.nan], "float32")}, {}, ["Out"])
    assert bool(hi2) and bool(hn2)

    # depthwise transpose == conv2d_transpose with groups=C
    xdw = _r(1, 2, 3, 3, seed=141)
    wdw = _r(2, 1, 2, 2, seed=142)
    (dt,) = probe(
        "depthwise_conv2d_transpose", {"Input": xdw, "Filter": wdw},
        {"strides": [1, 1], "paddings": [0, 0]}, ["Output"],
    )
    (ref_dt,) = probe(
        "conv2d_transpose", {"Input": xdw, "Filter": wdw},
        {"strides": [1, 1], "paddings": [0, 0], "groups": 2}, ["Output"],
    )
    np.testing.assert_allclose(dt, ref_dt, rtol=1e-5)

    table = _r(10, 4, seed=143)
    ids = np.array([[1], [7]], "int64")
    (lst,) = probe("lookup_sparse_table", {"W": table, "Ids": ids}, {}, ["Out"])
    np.testing.assert_allclose(np.asarray(lst).reshape(2, 4), table[[1, 7]],
                               rtol=1e-6)


def test_tensor_array_to_tensor_masks_unwritten():
    """tensor_array_to_tensor stacks only the written prefix (unwritten
    static-capacity slots come out zeroed, never garbage)."""
    import paddle_tpu as fl
    from paddle_tpu import layers

    prog = fl.Program()
    startup = fl.Program()
    with fl.framework.program_guard(prog, startup):
        x = layers.data("ta_x", shape=[3])
        arr = None
        for i in range(2):
            idx = layers.fill_constant([1], "int64", i)
            arr = layers.array_write(x, idx, array=arr, capacity=4)
        blk = prog.global_block()
        out = blk.create_var(name="ta_out", dtype="float32", shape=None)
        blk.append_op(
            "tensor_array_to_tensor", inputs={"X": [arr.name]},
            outputs={"Out": [out.name]}, attrs={"use_stack": True, "axis": 0},
        )
    exe = fl.Executor(fl.CPUPlace())
    with fl.scope_guard(fl.Scope()):
        xv = np.ones((2, 3), "float32")
        (got,) = exe.run(prog, feed={"ta_x": xv}, fetch_list=[out])
    got = np.asarray(got)
    assert got.shape[0] == 4
    np.testing.assert_allclose(got[0], xv, rtol=1e-6)
    np.testing.assert_allclose(got[1], xv, rtol=1e-6)
    assert np.all(got[2:] == 0)
    COVERED.add("tensor_array_to_tensor")


def test_detection_map_op():
    det = np.array([
        [0, 0.9, 0, 0, 10, 10],
        [0, 0.8, 20, 20, 30, 30],
        [1, 0.7, 0, 0, 10, 10],
    ], "float32")
    gt = np.array([
        [0, 0, 0, 10, 10],
        [1, 0, 0, 10, 10],
    ], "float32")
    (mp,) = probe("detection_map", {"DetectRes": det, "Label": gt},
                  {"overlap_threshold": 0.5}, ["MAP"])
    assert 0.0 <= float(mp[0]) <= 1.0
    assert float(mp[0]) > 0.9  # both gts matched by top-scoring dets


def test_proximal_optimizer_ops_match_reference_math():
    """proximal_gd / proximal_adagrad (optimizers/proximal_*_op.h): the
    prox step soft-thresholds by lr*l1 and shrinks by 1/(1+lr*l2)."""
    import numpy as np

    from paddle_tpu.core.registry import get_op

    rng = np.random.RandomState(0)
    p = rng.randn(6).astype("float32")
    g = rng.randn(6).astype("float32")
    lr, l1, l2 = 0.1, 0.05, 0.2

    out = get_op("proximal_gd").lower(
        None,
        {"Param": [p], "Grad": [g], "LearningRate": [np.float32(lr)]},
        {"l1": l1, "l2": l2},
    )
    prox = p - lr * g
    want = np.sign(prox) * np.maximum(np.abs(prox) - lr * l1, 0) / (1 + lr * l2)
    np.testing.assert_allclose(np.asarray(out["ParamOut"][0]), want, rtol=1e-6)

    m = np.abs(rng.randn(6)).astype("float32")
    out = get_op("proximal_adagrad").lower(
        None,
        {"Param": [p], "Grad": [g], "Moment": [m],
         "LearningRate": [np.float32(lr)]},
        {"l1": l1, "l2": l2},
    )
    m_new = m + g * g
    prox = p - lr * g / np.sqrt(m_new)
    want = np.sign(prox) * np.maximum(np.abs(prox) - lr * l1, 0) / (1 + lr * l2)
    np.testing.assert_allclose(np.asarray(out["ParamOut"][0]), want, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["MomentOut"][0]), m_new, rtol=1e-6)


def test_ref_by_trainer_id_selects_input():
    import numpy as np

    from paddle_tpu.core.registry import get_op

    xs = [np.full((2, 2), i, "float32") for i in range(3)]
    out = get_op("ref_by_trainer_id").lower(
        None, {"X": xs, "TrainerId": [np.array([1], "int64")]}, {}
    )
    np.testing.assert_array_equal(np.asarray(out["Out"][0]), xs[1])
