"""Launcher tests (paddle CLI / cluster_train analog): collective-mode
rank wiring + coordination bootstrap, pserver-mode role orchestration via
the existing dist_mlp runner, and fail-fast teardown."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

# multi-process / full-train-cycle integration tests: excluded from the
# default fast run (pytest.ini addopts -m "not slow"); run with -m "" 
pytestmark = pytest.mark.slow

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)


def _run_launch(args, extra_env=None, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch"] + args,
        env=env,
        cwd=_REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        timeout=timeout,
    )
    return proc.returncode, proc.stdout


@pytest.mark.slow
def test_launch_collective_psum():
    """2 launched ranks bootstrap jax.distributed from the launcher's env
    and psum their ranks: both print PSUM 1.0 (0+1)."""
    rc, out = _run_launch(
        ["--nproc", "2", os.path.join(_DIR, "launch_worker.py")]
    )
    assert rc == 0, out
    psums = [l for l in out.splitlines() if "PSUM" in l]
    assert len(psums) == 2, out
    assert all(l.strip().endswith("1.0") for l in psums), psums


@pytest.mark.slow
def test_launch_pserver_mode_dist_mlp():
    """pserver mode spawns 2 pservers + 2 trainers around dist_mlp.py and
    every trainer converges (LOSSES decreasing)."""
    rc, out = _run_launch(
        ["--mode", "pserver", "--nproc", "2", "--pservers", "2",
         os.path.join(_DIR, "dist_mlp.py")],
        extra_env={"DIST_STEPS": "4"},
    )
    assert rc == 0, out
    losses = []
    for line in out.splitlines():
        if "LOSSES " in line:
            losses.append(json.loads(line.split("LOSSES ", 1)[1]))
    assert len(losses) == 2, out
    for ls in losses:
        assert np.isfinite(ls).all() and ls[-1] < ls[0], ls


@pytest.mark.slow
def test_launch_fail_fast():
    """A failing rank tears the cluster down and surfaces its exit code."""
    rc, out = _run_launch(
        ["--nproc", "2", os.path.join(_DIR, "launch_worker.py")],
        extra_env={"LAUNCH_WORKER_FAIL_RANK": "1"},
        timeout=120,
    )
    assert rc == 3, (rc, out)
