"""Sequence packing (reader/packing.py) + segment-masked attention
(`fused_attention(segment_ids=...)`): packed rows must behave exactly
like the original unpacked sequences — no cross-sequence leakage."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.reader import pack_sequences


def test_pack_sequences_structure():
    rng = np.random.RandomState(0)
    seqs = [rng.randint(1, 100, (n,)) for n in (7, 3, 8, 2, 6, 5)]
    tokens, seg, pos = pack_sequences(seqs, seq_len=10)
    # every sequence appears intact in exactly one row, contiguous
    found = 0
    for s in seqs:
        hits = 0
        for r in range(tokens.shape[0]):
            for off in range(0, 10 - s.size + 1):
                if (tokens[r, off:off + s.size] == s).all() and \
                        len(set(seg[r, off:off + s.size])) == 1 and \
                        seg[r, off] > 0 and \
                        (pos[r, off:off + s.size] == np.arange(s.size)).all():
                    hits += 1
                    break
        found += hits
    assert found == len(seqs)
    # padding is segment 0, fill rate beats one-row-per-sequence
    total = sum(s.size for s in seqs)
    assert (seg > 0).sum() == total
    assert tokens.shape[0] < len(seqs)
    # a too-long sequence raises
    with pytest.raises(ValueError, match="exceeds seq_len"):
        pack_sequences([np.arange(11)], seq_len=10)


def test_segment_masked_attention_matches_unpacked():
    """Two sequences packed into one row with causal self-attention ==
    each sequence attended alone: positions of seq A in the packed
    output must equal A's standalone attention output."""
    rng = np.random.RandomState(1)
    h, d = 2, 8
    la, lb, L = 5, 3, 8

    def run(qkv, seg=None, t=None):
        t = t or qkv[0].shape[2]
        main, startup = fluid.Program(), fluid.Program()
        with fluid.framework.program_guard(main, startup):
            q = layers.data("q", shape=[h, t, d])
            k = layers.data("k", shape=[h, t, d])
            v = layers.data("v", shape=[h, t, d])
            kwargs = {}
            feed = {"q": qkv[0], "k": qkv[1], "v": qkv[2]}
            if seg is not None:
                sv = layers.data("seg", shape=[t], dtype="int32")
                kwargs["segment_ids"] = sv
                feed["seg"] = seg
            out = layers.fused_attention(q, k, v, causal=True, **kwargs)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            (o,) = exe.run(main, feed=feed, fetch_list=[out])
        return np.asarray(o)

    a = rng.rand(1, h, la, d).astype("float32")
    b = rng.rand(1, h, lb, d).astype("float32")
    packed = np.zeros((1, h, L, d), "float32")
    packed[:, :, :la] = a
    packed[:, :, la:la + lb] = b
    seg = np.zeros((1, L), "int32")
    seg[0, :la] = 1
    seg[0, la:la + lb] = 2

    got = run((packed,) * 3, seg=seg)
    ref_a = run((a,) * 3)
    ref_b = run((b,) * 3)
    np.testing.assert_allclose(got[:, :, :la], ref_a, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got[:, :, la:la + lb], ref_b,
                               rtol=1e-5, atol=1e-6)


def test_segment_attention_grads_flow():
    """minimize() through segment-masked attention works (int ids get no
    grad; q/k/v do) and the loss is finite."""
    rng = np.random.RandomState(2)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        x = layers.data("x", shape=[2, 8, 8])
        seg = layers.data("seg", shape=[8], dtype="int32")
        q = layers.fc(x, 8, num_flatten_dims=3)
        out = layers.fused_attention(q, q, q, causal=True, segment_ids=seg)
        loss = layers.mean(out * out)
        fluid.optimizer.SGD(0.1).minimize(loss)
    sv = np.zeros((2, 8), "int32")
    sv[:, :5] = 1
    sv[:, 5:] = 2
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (lv,) = exe.run(main, feed={
            "x": rng.rand(2, 2, 8, 8).astype("float32"), "seg": sv},
            fetch_list=[loss])
    assert np.isfinite(np.asarray(lv)).all()


@pytest.mark.slow  # full-train/full-model integration pass (tens of seconds on this 2-core sandbox); rides scripts/ci.sh --full — the fast lane must finish inside tier-1's time budget
def test_flash_segment_ids_match_dense():
    """Flash kernels with segment ids (interpret mode) == dense-XLA
    segment masking: forward and all grads, causal and bidirectional,
    at both single-block and multi-block sizes."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import (
        _dense_attention, flash_attention)

    rng = np.random.RandomState(4)
    for T, bq, bk in ((16, 16, 16), (256, 128, 128)):
        BH, d = 2, 8
        q, k, v = (jnp.asarray(rng.rand(BH, T, d).astype("float32"))
                   for _ in range(3))
        seg = np.ones((BH, T), np.int32)
        seg[:, T // 3:] = 2
        seg[:, 2 * T // 3:] = 3
        seg = jnp.asarray(seg)
        for causal in (False, True):
            def f_flash(q, k, v):
                o = flash_attention(q, k, v, None, causal, None,
                                    bq, bk, 0, seg)
                return o, jnp.sum(o * o)

            def f_dense(q, k, v):
                o = _dense_attention(q, k, v, causal, 1.0 / d ** 0.5,
                                     seg=seg)
                return o, jnp.sum(o * o)

            of, _ = f_flash(q, k, v)
            od, _ = f_dense(q, k, v)
            np.testing.assert_allclose(np.asarray(of), np.asarray(od),
                                       rtol=2e-5, atol=2e-6)
            gf = jax.grad(lambda *a: f_flash(*a)[1], argnums=(0, 1, 2))(
                q, k, v)
            gd = jax.grad(lambda *a: f_dense(*a)[1], argnums=(0, 1, 2))(
                q, k, v)
            for a, b in zip(gf, gd):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=2e-4, atol=2e-5)


def test_op_segment_ids_ride_flash_under_pallas_flag():
    """FLAGS_use_pallas=1: the fused_attention op with SegmentIds routes
    through the flash kernels (interpret mode on CPU) and matches the
    dense path bit-for-tolerance."""
    from paddle_tpu import flags

    rng = np.random.RandomState(5)
    h, t, d = 2, 16, 8
    qv = rng.rand(2, h, t, d).astype("float32")
    sv = np.ones((2, t), np.int32)
    sv[:, t // 2:] = 2

    def run():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.framework.program_guard(main, startup):
            q = layers.data("q", shape=[h, t, d])
            seg = layers.data("seg", shape=[t], dtype="int32")
            out = layers.fused_attention(q, q, q, causal=True,
                                         segment_ids=seg)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            (o,) = exe.run(main, feed={"q": qv, "seg": sv},
                           fetch_list=[out])
        return np.asarray(o)

    dense = run()
    flags.set_flags({"use_pallas": True})
    try:
        flash = run()
    finally:
        flags.set_flags({"use_pallas": False})
    np.testing.assert_allclose(flash, dense, rtol=2e-5, atol=2e-6)
