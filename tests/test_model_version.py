"""Serialized-program versioning (framework.proto:24 Version +
framework/version.h analog): __model__ carries a format version; the
loader accepts <= current (including the version-less round-2 era as v0)
and refuses newer formats.  The committed r2-era fixture must keep
loading in every future round (compat contract)."""

import json
import os
import shutil

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.io import PROGRAM_FORMAT_VERSION, is_program_version_supported

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "saved_model_r2")


def test_version_field_written_and_roundtrips(tmp_path):
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.fc(x, 2)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        d = str(tmp_path / "m")
        fluid.save_inference_model(d, ["x"], [y], exe, main_program=main)
        meta = json.load(open(os.path.join(d, "__model__")))
        assert meta["version"] == PROGRAM_FORMAT_VERSION
        prog, feeds, fetches = fluid.load_inference_model(d, exe)
        out = exe.run(prog, feed={"x": np.ones((1, 4), "float32")},
                      fetch_list=fetches)
        assert np.asarray(out[0]).shape == (1, 2)


def test_r2_era_versionless_fixture_still_loads():
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        prog, feeds, fetches = fluid.load_inference_model(FIXTURE, exe)
        xin = np.arange(8, dtype="float32").reshape(2, 4) / 10.0
        out = exe.run(prog, feed={feeds[0]: xin}, fetch_list=fetches)
    expected = np.load(FIXTURE + "_expected.npy")
    np.testing.assert_allclose(np.asarray(out[0]), expected,
                               rtol=1e-5, atol=1e-6)


def test_future_version_refused(tmp_path):
    d = str(tmp_path / "future")
    shutil.copytree(FIXTURE, d)
    p = os.path.join(d, "__model__")
    meta = json.load(open(p))
    meta["version"] = PROGRAM_FORMAT_VERSION + 1
    json.dump(meta, open(p, "w"))
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(RuntimeError, match="newer than this build"):
        fluid.load_inference_model(d, exe)
    assert not is_program_version_supported(PROGRAM_FORMAT_VERSION + 1)
    assert is_program_version_supported(0)


def test_r3_era_binary_fixture_still_loads():
    """The committed round-3 binary (protobuf) __model__ must keep
    loading in every future build — the format-compat contract of the
    pb path (native/desc.proto), sibling of the JSON r2 fixture."""
    fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                           "saved_model_r3_pb")
    from paddle_tpu import desc_codec

    raw = open(os.path.join(fixture, "__model__"), "rb").read()
    assert desc_codec.looks_like_pb(raw)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        prog, feeds, fetches = fluid.load_inference_model(fixture, exe)
        xin = np.arange(8, dtype="float32").reshape(2, 4) / 10.0
        out = exe.run(prog, feed={feeds[0]: xin}, fetch_list=fetches)
    expected = np.load(fixture + "_expected.npy")
    np.testing.assert_allclose(np.asarray(out[0]), expected,
                               rtol=1e-5, atol=1e-6)
