"""Per-op checks (output + numeric-gradient parity) for the math/elementwise
surface — the mirror of the reference's test_elementwise_*_op.py,
test_mul_op.py, test_softmax_op.py, test_reduce_op.py contract."""

import numpy as np
import pytest

from op_test import OpTest

rng = np.random.RandomState(42)


class TestElementwiseAdd(OpTest):
    def setup(self):
        self.op_type = "elementwise_add"
        x = rng.rand(3, 4).astype("float32")
        y = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {}
        self.outputs = {"Out": x + y}

    def test(self):
        self.check_output()
        self.check_grad(["x", "y"], "Out")


class TestElementwiseAddBroadcastAxis(OpTest):
    def setup(self):
        self.op_type = "elementwise_add"
        x = rng.rand(2, 3, 4).astype("float32")
        y = rng.rand(3).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x + y.reshape(1, 3, 1)}

    def test(self):
        self.check_output()
        self.check_grad(["x", "y"], "Out")


class TestElementwiseMulBroadcastTrailing(OpTest):
    def setup(self):
        self.op_type = "elementwise_mul"
        x = rng.rand(2, 3, 4).astype("float32")
        y = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": -1}
        self.outputs = {"Out": x * y}

    def test(self):
        self.check_output()
        self.check_grad(["x", "y"], "Out")


class TestElementwiseDiv(OpTest):
    def setup(self):
        self.op_type = "elementwise_div"
        x = rng.rand(3, 4).astype("float32") + 0.5
        y = rng.rand(3, 4).astype("float32") + 0.5
        self.inputs = {"X": x, "Y": y}
        self.attrs = {}
        self.outputs = {"Out": x / y}

    def test(self):
        self.check_output()
        self.check_grad(["x", "y"], "Out", max_relative_error=1e-2)


class TestMulOp(OpTest):
    def setup(self):
        self.op_type = "mul"
        x = rng.rand(4, 6).astype("float32")
        y = rng.rand(6, 5).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"x_num_col_dims": 1, "y_num_col_dims": 1}
        self.outputs = {"Out": x @ y}

    def test(self):
        self.check_output()
        self.check_grad(["x", "y"], "Out", max_relative_error=1e-2)


class TestMulOpFlatten(OpTest):
    def setup(self):
        self.op_type = "mul"
        x = rng.rand(2, 3, 4).astype("float32")
        y = rng.rand(4, 5).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"x_num_col_dims": 2, "y_num_col_dims": 1}
        self.outputs = {"Out": (x.reshape(6, 4) @ y).reshape(2, 3, 5)}

    def test(self):
        self.check_output()


class TestMatmulTranspose(OpTest):
    def setup(self):
        self.op_type = "matmul"
        x = rng.rand(5, 3).astype("float32")
        y = rng.rand(5, 4).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_X": True, "transpose_Y": False, "alpha": 2.0}
        self.outputs = {"Out": 2.0 * (x.T @ y)}

    def test(self):
        self.check_output()
        self.check_grad(["x", "y"], "Out", max_relative_error=1e-2)


class TestMatmulBatched(OpTest):
    def setup(self):
        self.op_type = "matmul"
        x = rng.rand(2, 3, 4).astype("float32")
        y = rng.rand(2, 4, 5).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {}
        self.outputs = {"Out": np.matmul(x, y)}

    def test(self):
        self.check_output()


class TestSoftmax(OpTest):
    def setup(self):
        self.op_type = "softmax"
        x = rng.rand(4, 7).astype("float32")
        e = np.exp(x - x.max(-1, keepdims=True))
        self.inputs = {"X": x}
        self.attrs = {}
        self.outputs = {"Out": e / e.sum(-1, keepdims=True)}

    def test(self):
        self.check_output()
        self.check_grad(["x"], "Out", max_relative_error=5e-2)


class TestSoftmaxWithCrossEntropy(OpTest):
    def setup(self):
        self.op_type = "softmax_with_cross_entropy"
        logits = rng.rand(5, 8).astype("float32") * 3
        label = rng.randint(0, 8, (5, 1)).astype("int64")
        e = np.exp(logits - logits.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        loss = -np.log(sm[np.arange(5), label[:, 0]]).reshape(5, 1)
        self.inputs = {"Logits": logits, "Label": label}
        self.attrs = {}
        self.outputs = {"Softmax": sm, "Loss": loss}

    def test(self):
        self.check_output()
        self.check_grad(["logits"], "Loss", max_relative_error=1e-2)


class TestReduceSum(OpTest):
    def setup(self):
        self.op_type = "reduce_sum"
        x = rng.rand(3, 4, 5).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"dim": [1], "keep_dim": False}
        self.outputs = {"Out": x.sum(axis=1)}

    def test(self):
        self.check_output()
        self.check_grad(["x"], "Out")


class TestReduceMeanAll(OpTest):
    def setup(self):
        self.op_type = "reduce_mean"
        x = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"reduce_all": True}
        self.outputs = {"Out": np.asarray(x.mean())}

    def test(self):
        self.check_output()
        self.check_grad(["x"], "Out")


class TestTanh(OpTest):
    def setup(self):
        self.op_type = "tanh"
        x = rng.rand(3, 4).astype("float32") * 2 - 1
        self.inputs = {"X": x}
        self.attrs = {}
        self.outputs = {"Out": np.tanh(x)}

    def test(self):
        self.check_output()
        self.check_grad(["x"], "Out")


class TestSigmoid(OpTest):
    def setup(self):
        self.op_type = "sigmoid"
        x = rng.rand(3, 4).astype("float32") * 2 - 1
        self.inputs = {"X": x}
        self.attrs = {}
        self.outputs = {"Out": 1 / (1 + np.exp(-x))}

    def test(self):
        self.check_output()
        self.check_grad(["x"], "Out")


class TestLayerNormOp(OpTest):
    def setup(self):
        self.op_type = "layer_norm"
        x = rng.rand(4, 6).astype("float32")
        scale = rng.rand(6).astype("float32")
        bias = rng.rand(6).astype("float32")
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        y = (x - mean) / np.sqrt(var + 1e-5) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"begin_norm_axis": 1, "epsilon": 1e-5}
        self.outputs = {"Y": y}

    def test(self):
        self.check_output()
        self.check_grad(["x", "scale", "bias"], "Y", max_relative_error=2e-2)


class TestLookupTable(OpTest):
    def setup(self):
        self.op_type = "lookup_table"
        w = rng.rand(10, 4).astype("float32")
        ids = rng.randint(0, 10, (5, 1)).astype("int64")
        self.inputs = {"W": w, "Ids": ids}
        self.attrs = {}
        self.outputs = {"Out": w[ids[:, 0]]}

    def test(self):
        self.check_output()
        self.check_grad(["w"], "Out")


class TestConcat(OpTest):
    def setup(self):
        self.op_type = "concat"
        a = rng.rand(2, 3).astype("float32")
        b = rng.rand(2, 4).astype("float32")
        self.inputs = {"X": [("a", a), ("b", b)]}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": np.concatenate([a, b], axis=1)}

    def test(self):
        self.check_output()
        self.check_grad(["a", "b"], "Out")


class TestTranspose(OpTest):
    def setup(self):
        self.op_type = "transpose2"
        x = rng.rand(2, 3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"axis": [0, 2, 1]}
        self.outputs = {"Out": x.transpose(0, 2, 1)}

    def test(self):
        self.check_output()
        self.check_grad(["x"], "Out")


class TestReshape(OpTest):
    def setup(self):
        self.op_type = "reshape2"
        x = rng.rand(2, 6).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"shape": [4, -1]}
        self.outputs = {"Out": x.reshape(4, 3)}

    def test(self):
        self.check_output()
        self.check_grad(["x"], "Out")


class TestSliceOp(OpTest):
    def setup(self):
        self.op_type = "slice"
        x = rng.rand(4, 5, 6).astype("float32")
        self.inputs = {"Input": x}
        self.attrs = {"axes": [0, 2], "starts": [1, 2], "ends": [3, 5]}
        self.outputs = {"Out": x[1:3, :, 2:5]}

    def test(self):
        self.check_output()
        self.check_grad(["input"], "Out")


class TestScale(OpTest):
    def setup(self):
        self.op_type = "scale"
        x = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"scale": 2.5, "bias": 0.3}
        self.outputs = {"Out": x * 2.5 + 0.3}

    def test(self):
        self.check_output()
        self.check_grad(["x"], "Out")


class TestClip(OpTest):
    def setup(self):
        self.op_type = "clip"
        x = (rng.rand(3, 4).astype("float32") - 0.5) * 4
        self.inputs = {"X": x}
        self.attrs = {"min": -1.0, "max": 1.0}
        self.outputs = {"Out": np.clip(x, -1, 1)}

    def test(self):
        self.check_output()


class TestTopK(OpTest):
    def setup(self):
        self.op_type = "top_k"
        x = rng.rand(3, 6).astype("float32")
        k = 2
        idx = np.argsort(-x, axis=1)[:, :k]
        vals = np.take_along_axis(x, idx, axis=1)
        self.inputs = {"X": x}
        self.attrs = {"k": k}
        self.outputs = {"Out": vals, "Indices": idx.astype("int64")}

    def test(self):
        self.check_output()


class TestSumOp(OpTest):
    def setup(self):
        self.op_type = "sum"
        a = rng.rand(3, 4).astype("float32")
        b = rng.rand(3, 4).astype("float32")
        c = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": [("sa", a), ("sb", b), ("sc", c)]}
        self.attrs = {}
        self.outputs = {"Out": a + b + c}

    def test(self):
        self.check_output()
        self.check_grad(["sa", "sb", "sc"], "Out")


class TestCast(OpTest):
    def setup(self):
        self.op_type = "cast"
        x = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"in_dtype": "float32", "out_dtype": "int32"}
        self.outputs = {"Out": x.astype("int32")}

    def test(self):
        self.check_output()


class TestOneHot(OpTest):
    def setup(self):
        self.op_type = "one_hot"
        x = rng.randint(0, 5, (4, 1)).astype("int64")
        out = np.zeros((4, 5), "float32")
        out[np.arange(4), x[:, 0]] = 1
        self.inputs = {"X": x}
        self.attrs = {"depth": 5}
        self.outputs = {"Out": out}

    def test(self):
        self.check_output()
