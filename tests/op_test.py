"""OpTest harness — the per-op test contract of the reference
(python/paddle/fluid/tests/unittests/op_test.py:132): declare op_type /
inputs / attrs / expected outputs in numpy, `check_output` runs the single
op through a real program+executor and compares, `check_grad` compares the
framework's analytic gradients (built via the real append_backward + vjp
machinery) against numeric central-difference gradients.
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import framework, layers
from paddle_tpu.core import scope as scope_mod


class OpTest:
    """Subclass contract: set self.op_type, self.inputs, self.attrs,
    self.outputs in setup(); inputs/outputs map slot -> ndarray or
    [(name, ndarray), ...] for multi-var slots."""

    op_type = None
    inputs = {}
    outputs = {}
    attrs = {}

    def _norm_slot(self, slot_val, slot):
        if isinstance(slot_val, (list, tuple)) and slot_val and isinstance(
            slot_val[0], tuple
        ):
            return [(n, np.asarray(a)) for n, a in slot_val]
        return [(slot.lower(), np.asarray(slot_val))]

    def _build(self, stop_gradient=True):
        """Fresh program with the single op; returns (program, feed, out_vars)."""
        prog = fluid.Program()
        startup = fluid.Program()
        feed = {}
        with framework.program_guard(prog, startup):
            block = prog.global_block()
            in_names = {}
            for slot, val in self.inputs.items():
                pairs = self._norm_slot(val, slot)
                names = []
                for n, arr in pairs:
                    block.create_var(
                        name=n,
                        shape=arr.shape,
                        dtype=str(arr.dtype),
                        stop_gradient=stop_gradient,
                        is_data=True,
                    )
                    feed[n] = arr
                    names.append(n)
                in_names[slot] = names
            out_vars = {}
            out_names = {}
            for slot, val in self.outputs.items():
                pairs = self._norm_slot(val, slot)
                names = []
                for n, arr in pairs:
                    v = block.create_var(
                        name=n + "@out", dtype=str(arr.dtype),
                        shape=None)
                    names.append(v.name)
                    out_vars.setdefault(slot, []).append((v, arr))
                out_names[slot] = names
            block.append_op(
                self.op_type, inputs=in_names, outputs=out_names, attrs=dict(self.attrs)
            )
        return prog, feed, out_vars

    def check_output(self, atol=1e-5, rtol=1e-4, no_check_set=None):
        self.setup()
        prog, feed, out_vars = self._build()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            for slot, pairs in out_vars.items():
                if no_check_set and slot in no_check_set:
                    continue
                fetch = [v for v, _ in pairs]
                got = exe.run(prog, feed=feed, fetch_list=fetch)
                for (v, expect), actual in zip(pairs, got):
                    np.testing.assert_allclose(
                        np.asarray(actual).astype("float64"),
                        np.asarray(expect).astype("float64"),
                        atol=atol,
                        rtol=rtol,
                        err_msg="op %s output %s/%s mismatch"
                        % (self.op_type, slot, v.name),
                    )

    def check_grad(
        self,
        inputs_to_check,
        output_name,
        max_relative_error=5e-3,
        delta=5e-3,
        no_grad_set=None,
    ):
        """Analytic (vjp-machinery) vs numeric central-difference grads of
        loss = sum(output) w.r.t. each input in inputs_to_check."""
        self.setup()
        prog, feed, out_vars = self._build(stop_gradient=False)
        startup = fluid.Program()
        with framework.program_guard(prog, startup):
            block = prog.global_block()
            # find the output var for output_name (slot name or var name)
            target = None
            expect = None
            for slot, pairs in out_vars.items():
                for v, arr in pairs:
                    if slot == output_name or v.name == output_name + "@out":
                        target, expect = v, arr
            assert target is not None, "output %s not found" % output_name
            # loss = sum(out * W) with fixed random W — avoids degenerate
            # constant losses (e.g. sum of softmax rows == N)
            wname = "__grad_check_w__"
            block.create_var(
                name=wname,
                shape=expect.shape,
                dtype="float32",
                stop_gradient=True,
                is_data=True,
            )
            feed[wname] = np.random.RandomState(7).uniform(
                0.5, 1.5, expect.shape
            ).astype("float32")
            weighted = layers.elementwise_mul(target, block.var(wname))
            loss = layers.reduce_sum(weighted)
            grads = fluid.backward.calc_gradient(
                loss, [block.var(n) for n in inputs_to_check], no_grad_set=no_grad_set
            )
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            analytic = exe.run(prog, feed=feed, fetch_list=[g for g in grads])

            def loss_fn(feed_over):
                (lv,) = exe.run(prog, feed=feed_over, fetch_list=[loss])
                return float(np.asarray(lv).sum())

            for name, ana in zip(inputs_to_check, analytic):
                base = feed[name].astype("float64")
                num = np.zeros_like(base)
                flat = base.reshape(-1)
                for i in range(flat.size):
                    f2 = dict(feed)
                    pert = flat.copy()
                    pert[i] += delta
                    f2[name] = pert.reshape(base.shape).astype(feed[name].dtype)
                    up = loss_fn(f2)
                    pert[i] -= 2 * delta
                    f2[name] = pert.reshape(base.shape).astype(feed[name].dtype)
                    down = loss_fn(f2)
                    num.reshape(-1)[i] = (up - down) / (2 * delta)
                ana = np.asarray(ana).astype("float64")
                abs_err = np.abs(ana - num)
                denom = np.maximum(np.maximum(np.abs(ana), np.abs(num)), 1e-3)
                rel = (abs_err / denom).max()
                assert rel < max_relative_error, (
                    "op %s grad of %s: max rel err %.5f >= %.5f\nanalytic=%s\nnumeric=%s"
                    % (self.op_type, name, rel, max_relative_error, ana, num)
                )

    def setup(self):
        raise NotImplementedError


def run_single_op(op_type, inputs, attrs, out_slots):
    """Shared single-op driver for tests that don't fit the OpTest
    declare-expected-outputs shape (multi-output probes, property tests).
    inputs: slot -> ndarray (or [(name, ndarray), ...] for multi-var slots).
    Returns the fetched outputs as numpy arrays, in out_slots order."""
    prog = fluid.Program()
    startup = fluid.Program()
    with framework.program_guard(prog, startup):
        blk = prog.global_block()
        in_names = {}
        feed = {}
        for slot, val in inputs.items():
            pairs = (
                [(n, np.asarray(a)) for n, a in val]
                if isinstance(val, (list, tuple)) and val and isinstance(val[0], tuple)
                else [("i_" + slot.lower(), np.asarray(val))]
            )
            names = []
            for n, arr in pairs:
                blk.create_var(
                    name=n, shape=arr.shape, dtype=str(arr.dtype), is_data=True
                )
                feed[n] = arr
                names.append(n)
            in_names[slot] = names
        out_names = {}
        out_vars = []
        for slot in out_slots:
            # (slot, n) requests an n-var output slot
            slot, count = slot if isinstance(slot, tuple) else (slot, 1)
            names = []
            for i in range(count):
                suffix = "" if count == 1 else "_%d" % i
                v = blk.create_var(
                    name="o_" + slot.lower().replace("-", "_") + suffix,
                    shape=None,
                )
                # the driver has no expected arrays: the output dtype is
                # genuinely unknown here, and a float32 default would be
                # a mis-declaration the program verifier rightly flags
                v.dtype = None
                names.append(v.name)
                out_vars.append(v)
            out_names[slot] = names
        blk.append_op(op_type, inputs=in_names, outputs=out_names, attrs=attrs)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        return [np.asarray(r) for r in exe.run(prog, feed=feed, fetch_list=out_vars)]
