"""Test harness config: force an 8-device virtual CPU mesh (the TPU-sim
test topology per the build plan) before JAX initializes.

Note: the sandbox autoloads a TPU-tunnel PJRT plugin via sitecustomize that
overrides jax_platforms; tests must run CPU-only, so we pin the config back
to cpu and clear the plugin's env gate for any subprocesses.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# static program verification is ON for the whole suite (the tests/CI
# regime of FLAGS_check_program): every apply_pass postcondition-checks
# its result and every program verifies once before its first compile.
# An explicit env value (e.g. a lane measuring the flag-off cost) wins.
os.environ.setdefault("FLAGS_check_program", "1")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # stop plugin load in subprocesses
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_programs():
    """Each test gets fresh default programs, scope and name counters."""
    import paddle_tpu as fluid
    from paddle_tpu import framework, unique_name
    from paddle_tpu.core import scope as scope_mod

    old_main = framework.switch_main_program(fluid.Program())
    old_startup = framework.switch_startup_program(fluid.Program())
    old_gen = unique_name.switch()
    old_scope = scope_mod._switch_scope(scope_mod.Scope())
    yield
    framework.switch_main_program(old_main)
    framework.switch_startup_program(old_startup)
    unique_name.switch(old_gen)
    scope_mod._switch_scope(old_scope)


@pytest.fixture(autouse=True)
def restore_use_pallas_flag():
    """Flag-toggling tests must not leak their final use_pallas value
    into the rest of the process: the ci.sh pallas pass arms
    FLAGS_use_pallas=1 in the ENVIRONMENT for a whole multi-file pytest
    run, and a test's hardcoded `set_flags({"use_pallas": False})`
    cleanup would silently put every later test back on the dense
    path — the exact coverage the pass exists for."""
    from paddle_tpu import flags as _pflags

    old = _pflags.get_flag("use_pallas")
    yield
    _pflags.set_flags({"use_pallas": old})
