"""Static program verifier (paddle_tpu/analysis): one triggering
negative test per diagnostic class, the apply_pass postcondition
contract (FLAGS_check_program), the executor verify-before-first-run
hook, the shared graph-helper dedup, and the builder x pipeline sweep
(docs/STATIC_ANALYSIS.md)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags, layers
from paddle_tpu.analysis import (
    ProgramVerifyError,
    alias_plan_diagnostics,
    segment_diagnostics,
    verify_program,
)


def _codes(diags):
    return [d.code for d in diags]


def _errors(diags):
    return [d for d in diags if d.is_error]


def _find(diags, code):
    out = [d for d in diags if d.code == code]
    assert out, "expected a %r diagnostic, got %s" % (code, diags)
    return out[0]


def _prog():
    return fluid.Program()


# ---------------------------------------------------------------------------
# negative tests: one per diagnostic class, golden message pins the
# op index and block
# ---------------------------------------------------------------------------
def test_diag_undefined_read():
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4], dtype="float32")
    b.create_var(name="y", shape=[4], dtype="float32")
    b.append_op("relu", inputs={"X": ["ghost"]}, outputs={"Out": ["y"]})
    d = _find(verify_program(p), "undefined-read")
    assert d.is_error
    assert "block 0 op 0 (relu)" in str(d) and "'ghost'" in str(d)


def test_diag_undefined_read_across_sub_block_boundary():
    """The PR 12 liveness bug class: a sub-block reading an outer name
    that nothing defines."""
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    sub = p.create_block(parent_idx=0)
    p.current_block_idx = 0
    sub.create_var(name="inner_out", shape=[4], dtype="float32")
    # sub-block op reads an outer name with no definition anywhere
    op = fluid.Operator(sub, "relu", None, None, {})
    op.inputs = {"X": ["never_defined"]}
    op.outputs = {"Out": ["inner_out"]}
    sub.ops.append(op)
    rec = fluid.Operator(b, "recompute", None, None, {
        "sub_block_idx": sub.idx, "in_names": ["x"], "out_names":
        ["inner_out"], "__bound_names__": ["x"]})
    rec.inputs = {"X": ["x"]}
    rec.outputs = {"Out": ["inner_out"]}
    b.ops.append(rec)
    d = _find(verify_program(p), "undefined-read")
    assert d.block_idx == sub.idx and "'never_defined'" in str(d)


def test_diag_ssa_violation():
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    b.create_var(name="t", shape=[4], dtype="float32")
    b.append_op("relu", inputs={"X": ["x"]}, outputs={"Out": ["t"]})
    b.append_op("tanh", inputs={"X": ["x"]}, outputs={"Out": ["t"]})
    d = _find(verify_program(p), "ssa-violation")
    assert d.is_error
    assert "block 0 op 1 (tanh)" in str(d) and "op 0" in str(d)


def test_diag_slot_arity():
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4, 8], dtype="float32", is_data=True)
    b.create_var(name="o", shape=[4, 8], dtype="float32")
    b.append_op("mul", inputs={"X": ["x"]}, outputs={"Out": ["o"]})  # no Y
    d = _find(verify_program(p), "slot-arity")
    assert d.is_error
    assert "block 0 op 0 (mul)" in str(d) and "'Y'" in str(d)


def test_diag_dtype_mismatch():
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    # declared float32 but cast produces bool — a real edge-type bug
    b.create_var(name="o", shape=[4], dtype="float32")
    b.append_op("cast", inputs={"X": ["x"]}, outputs={"Out": ["o"]},
                attrs={"out_dtype": "bool"})
    d = _find(verify_program(p), "dtype-mismatch")
    assert d.is_error
    assert "block 0 op 0 (cast)" in str(d)
    assert "float32" in str(d) and "bool" in str(d)


def test_diag_shape_mismatch_declared():
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4, 8], dtype="float32", is_data=True)
    b.create_var(name="w", shape=[8, 2], dtype="float32", persistable=True)
    b.create_var(name="o", shape=[4, 3], dtype="float32")  # wrong: [4, 2]
    b.append_op("mul", inputs={"X": ["x"], "Y": ["w"]},
                outputs={"Out": ["o"]})
    d = _find(verify_program(p), "shape-mismatch")
    assert d.is_error and "block 0 op 0 (mul)" in str(d)


def test_diag_shape_mismatch_contraction_edge():
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4, 8], dtype="float32", is_data=True)
    b.create_var(name="w", shape=[7, 2], dtype="float32", persistable=True)
    b.create_var(name="o", shape=[4, 2], dtype="float32")
    b.append_op("mul", inputs={"X": ["x"], "Y": ["w"]},
                outputs={"Out": ["o"]})
    d = _find(verify_program(p), "shape-mismatch")
    assert "contraction" in str(d) and "block 0 op 0 (mul)" in str(d)


def test_diag_dead_write_warning():
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    b.create_var(name="t", shape=[4], dtype="float32")
    b.append_op("relu", inputs={"X": ["x"]}, outputs={"Out": ["t"]})
    diags = verify_program(p)
    d = _find(diags, "dead-write")
    assert not d.is_error  # warning: DCE handles it, verification passes
    assert "block 0 op 0 (relu)" in str(d)
    # counting it as a fetch silences the warning
    assert "dead-write" not in _codes(verify_program(p, fetches=["t"]))


def test_diag_persistable_write_in_remat():
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    b.create_var(name="state", shape=[4], dtype="float32", persistable=True)
    sub = p.create_block(parent_idx=0)
    p.current_block_idx = 0
    op = fluid.Operator(sub, "relu", None, None, {})
    op.inputs = {"X": ["x"]}
    op.outputs = {"Out": ["state"]}
    sub.ops.append(op)
    rec = fluid.Operator(b, "recompute", None, None, {
        "sub_block_idx": sub.idx, "in_names": ["x"],
        "out_names": ["state"], "__bound_names__": ["x"]})
    rec.inputs = {"X": ["x"]}
    rec.outputs = {"Out": ["state"]}
    b.ops.append(rec)
    d = _find(verify_program(p), "persistable-write-in-remat")
    assert d.is_error and "'state'" in str(d)
    assert d.block_idx == sub.idx


def test_diag_protected_fetch():
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    b.create_var(name="t", shape=[4], dtype="float32")
    b.append_op("relu", inputs={"X": ["x"]}, outputs={"Out": ["t"]})
    p._protected_fetch_names = ("t", "vanished")
    diags = verify_program(p)
    d = _find(diags, "protected-fetch")
    assert d.is_error and "'vanished'" in str(d)
    # the produced one is fine
    assert sum(1 for d in diags if d.code == "protected-fetch") == 1


def _dist_trainer():
    x = layers.data(name="x", shape=[8], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(x, size=16, act="relu")
    pred = layers.fc(h, size=1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    t = fluid.DistributeTranspiler()
    t.transpile(0, program=fluid.default_main_program(),
                pservers="127.0.0.1:6174,127.0.0.1:6175", trainers=2)
    return t.get_trainer_program()


def test_diag_dist_plan_orphan_grad():
    prog = _dist_trainer()
    b = prog.global_block()
    # delete the grad push: every dense grad is now an orphan
    b.ops = [op for op in b.ops if op.type != "send_bucket"]
    diags = verify_program(prog)
    d = _find(diags, "dist-plan")
    assert any(d2.is_error and "orphan" in str(d2)
               for d2 in diags if d2.code == "dist-plan")
    # and the send/recv pairing warning names the missing half
    assert any("send_bucket" in str(d2)
               for d2 in diags if d2.code == "dist-plan")
    assert d is not None


def test_dist_plan_clean_on_transpiled_program():
    prog = _dist_trainer()
    assert not _errors(verify_program(prog))


def test_diag_unknown_op():
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    b.create_var(name="o", shape=[4], dtype="float32")
    b.append_op("totally_bogus_op", inputs={"X": ["x"]},
                outputs={"Out": ["o"]})
    d = _find(verify_program(p), "unknown-op")
    assert d.is_error
    assert "block 0 op 0 (totally_bogus_op)" in str(d)


def test_diag_dangling_sub_block():
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    rec = fluid.Operator(b, "recompute", None, None, {
        "sub_block_idx": 99, "in_names": ["x"], "out_names": ["o"]})
    rec.inputs = {"X": ["x"]}
    rec.outputs = {"Out": ["o"]}
    b.ops.append(rec)
    d = _find(verify_program(p), "sub-block")
    assert d.is_error and "99" in str(d)


def test_diag_dtype_drift_and_append_op_normalization():
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    v = b.create_var(name="o", shape=[4], dtype="float32")
    v.dtype = np.dtype("float32")  # raw numpy dtype: serialization drift
    d = _find(verify_program(p), "dtype-drift")
    assert not d.is_error and "'o'" in str(d)
    # append_op normalizes its outputs' declared dtypes back to strings
    b.append_op("relu", inputs={"X": ["x"]}, outputs={"Out": ["o"]})
    assert v.dtype == "float32" and isinstance(v.dtype, str)
    assert "dtype-drift" not in _codes(verify_program(p))


def test_diag_alias_mismatch():
    p = _prog()
    b = p.global_block()
    b.create_var(name="a", shape=[4, 8], dtype="float32")
    b.create_var(name="c", shape=[32], dtype="int64")
    diags = alias_plan_diagnostics(b, {"a": "c"})
    assert len(diags) == 1 and diags[0].code == "alias-mismatch"
    assert diags[0].is_error and "'a'" in str(diags[0])
    assert not alias_plan_diagnostics(b, {})


def test_diag_sharding_coverage_divisibility_inconsistency():
    """The GSPMD rule-table classes: an unmatched matrix warns
    (replicated-by-default), a non-dividing sharded dim warns, and a
    derived name resolving unlike its base param errors."""
    import jax

    from paddle_tpu.analysis import sharding_diagnostics
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.partition_rules import (
        P, PartitionRules, TrainPartitionRules)

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices for a named mesh axis")
    mesh = make_mesh({"mp": 2}, devices=jax.devices()[:2])
    p = _prog()
    b = p.global_block()
    b.create_var(name="fc_0.w_0", shape=[8, 6], dtype="float32",
                 persistable=True)
    b.create_var(name="odd.w_0", shape=[8, 5], dtype="float32",
                 persistable=True)
    b.create_var(name="ln_0.w_0", shape=[8], dtype="float32",
                 persistable=True)

    # coverage: fc_0.w_0 (a matrix) matches nothing; the 1-D ln scale
    # also matches nothing but replicating vectors is by design
    diags = sharding_diagnostics(
        p, mesh=mesh, rules=PartitionRules([(r"odd\.w", P(None, "mp"))]))
    cov = [d for d in diags if d.code == "sharding-coverage"]
    assert [("fc_0.w_0" in str(d)) for d in cov] == [True]
    assert not any("ln_0.w_0" in str(d) for d in diags)
    # divisibility: odd.w_0 dim1=5 does not divide mp=2
    d = _find(diags, "sharding-divisibility")
    assert not d.is_error and "odd.w_0" in str(d) and "mp=2" in str(d)

    # inconsistency: a PLAIN rule table (no base_name stripping on
    # spec_for) whose grad rule disagrees with its param rule
    class SplitRules(PartitionRules):
        base_name = staticmethod(TrainPartitionRules.base_name)

    bad = SplitRules([
        (r"fc_0\.w_0@GRAD", P("mp", None)),
        (r"fc_0\.w_0", P(None, "mp")),
    ])
    b.create_var(name="fc_0.w_0@GRAD", shape=[8, 6], dtype="float32")
    d = _find(sharding_diagnostics(p, mesh=mesh, rules=bad),
              "sharding-inconsistency")
    assert d.is_error and "fc_0.w_0@GRAD" in str(d)

    # the TRAIN wrapper resolves derived names via base_name: clean
    ok = TrainPartitionRules([(r"fc_0\.w_0", P(None, "mp")),
                              (r"odd\.w", P())])
    assert not sharding_diagnostics(p, mesh=mesh, rules=ok)

    # stamped programs route through verify_program automatically
    from paddle_tpu.parallel import annotate_spmd

    annotate_spmd(p, mesh, ok)
    assert not [d for d in verify_program(p)
                if d.code.startswith("sharding")]


def test_while_carried_shape_fixpoint():
    """A while body growing a carried dim must widen it to -1 (unknown)
    instead of pinning iteration 0's value — and must not emit
    iteration-0-only shape-mismatch diagnostics (bounded fixpoint in
    analysis/infer.py)."""
    from paddle_tpu.analysis.infer import infer_program

    p = _prog()
    b = p.global_block()
    b.create_var(name="acc", shape=[-1, 4], dtype="float32")
    b.create_var(name="x0", shape=[1, 4], dtype="float32", is_data=True)
    b.create_var(name="cond", shape=[1], dtype="bool")
    b.append_op("fill_constant", inputs={}, outputs={"Out": ["acc"]},
                attrs={"shape": [2, 4], "value": 0.0, "dtype": "float32"})
    sub = p.create_block(parent_idx=0)
    sub.create_var(name="grown", shape=[-1, 4], dtype="float32")
    sub.append_op("concat", inputs={"X": ["acc", "x0"]},
                  outputs={"Out": ["grown"]}, attrs={"axis": 0})
    sub.append_op("assign", inputs={"X": ["grown"]}, outputs={"Out": ["acc"]})
    b.append_op("while", inputs={"Condition": ["cond"]},
                outputs={"Out": ["acc"]},
                attrs={"sub_block_idx": sub.idx, "carried_vars": ["acc"]})

    reports = []
    env = infer_program(
        p, feeds=["x0"],
        report=lambda c, s, bi, oi, op, m: reports.append((c, m)))
    # iteration 0 would say (3, 4); the fixpoint widens the fed-back dim
    assert env["acc"].shape == (-1, 4), env["acc"]
    assert env["grown"].shape == (-1, 4), env["grown"]
    assert reports == [], reports

    # a shape-STABLE body converges and keeps its concrete dims
    p2 = _prog()
    b2 = p2.global_block()
    b2.create_var(name="s", shape=[2, 4], dtype="float32", is_data=True)
    b2.create_var(name="cond", shape=[1], dtype="bool")
    sub2 = p2.create_block(parent_idx=0)
    sub2.create_var(name="t", shape=[2, 4], dtype="float32")
    sub2.append_op("relu", inputs={"X": ["s"]}, outputs={"Out": ["t"]})
    sub2.append_op("assign", inputs={"X": ["t"]}, outputs={"Out": ["s"]})
    b2.append_op("while", inputs={"Condition": ["cond"]},
                 outputs={"Out": ["s"]},
                 attrs={"sub_block_idx": sub2.idx, "carried_vars": ["s"]})
    env2 = infer_program(p2, feeds=["s"])
    assert env2["s"].shape == (2, 4)


def test_segment_diagnostics_back_remat_refusal():
    """remat._wrappable delegates here: persistable writes and cross-
    boundary redefinition refuse, a clean segment passes."""
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    b.create_var(name="t", shape=[4], dtype="float32")
    b.create_var(name="s", shape=[4], dtype="float32", persistable=True)
    op1 = b.append_op("relu", inputs={"X": ["x"]}, outputs={"Out": ["t"]})
    op2 = b.append_op("assign", inputs={"X": ["t"]}, outputs={"Out": ["s"]})
    assert not segment_diagnostics(p, [op1])
    bad = segment_diagnostics(p, [op1, op2])
    assert [d.code for d in bad] == ["persistable-write-in-remat"]
    from paddle_tpu.transpiler.remat import _wrappable

    assert _wrappable(p, [op1])
    assert not _wrappable(p, [op1, op2])


# ---------------------------------------------------------------------------
# pass postconditions (FLAGS_check_program)
# ---------------------------------------------------------------------------
def _fc_chain():
    x = layers.data(name="x", shape=[8], dtype="float32")
    h = layers.fc(x, size=4, act="relu")
    out = layers.fc(h, size=2)
    return fluid.default_main_program(), out


def test_apply_pass_postcondition_names_pass_and_op():
    from paddle_tpu.transpiler import pass_registry

    @pass_registry.register_pass("_test_breaking_pass")
    def _breaking(program, scope):
        # delete the first fc: its output's consumers now read a ghost
        b = program.global_block()
        del b.ops[1]
        program._bump_version()
        return program

    prog, _ = _fc_chain()
    old = flags.get_flag("check_program")
    flags.set_flags({"check_program": True})
    try:
        with pytest.raises(ProgramVerifyError) as ei:
            pass_registry.apply_pass(prog, "_test_breaking_pass")
    finally:
        flags.set_flags({"check_program": old})
        pass_registry._PASSES.pop("_test_breaking_pass", None)
    msg = str(ei.value)
    assert "pass '_test_breaking_pass'" in msg
    assert "undefined-read" in msg and "block 0" in msg


def test_apply_pass_flag_off_skips_verification():
    from paddle_tpu.transpiler import pass_registry

    @pass_registry.register_pass("_test_breaking_pass2")
    def _breaking(program, scope):
        del program.global_block().ops[1]
        program._bump_version()
        return program

    prog, _ = _fc_chain()
    old = flags.get_flag("check_program")
    flags.set_flags({"check_program": False})
    try:
        out = pass_registry.apply_pass(prog, "_test_breaking_pass2")
        assert out is prog  # ill-formed result returned, not raised
    finally:
        flags.set_flags({"check_program": old})
        pass_registry._PASSES.pop("_test_breaking_pass2", None)


def test_every_registered_pass_postcondition_clean_on_mlp():
    """The builders' own pipeline passes keep programs verified: apply
    each side-effect-free registered pass to a fresh MLP under
    FLAGS_check_program and none may trip its own postcondition."""
    from paddle_tpu.transpiler import pass_registry

    runnable = ["memory_optimize_pass", "fuse_relu_into_conv_pass",
                "attention_fuse_pass", "is_test_pass", "bf16_amp_pass"]
    old = flags.get_flag("check_program")
    flags.set_flags({"check_program": True})
    try:
        for name in runnable:
            fluid.framework.switch_main_program(fluid.Program())
            prog, _ = _fc_chain()
            pass_registry.apply_pass(prog, name)  # raises on violation
    finally:
        flags.set_flags({"check_program": old})


# ---------------------------------------------------------------------------
# executor verify-before-first-run
# ---------------------------------------------------------------------------
def test_executor_verifies_before_first_compile():
    p = _prog()
    startup = fluid.Program()
    with fluid.program_guard(p, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        out = layers.relu(x)
    # corrupt after build: the consumer now reads a deleted name
    b = p.global_block()
    b.ops[-1].inputs["X"] = ["missing_input"]
    p._bump_version()
    exe = fluid.Executor(fluid.CPUPlace())
    old = flags.get_flag("check_program")
    flags.set_flags({"check_program": True})
    try:
        with pytest.raises(ProgramVerifyError) as ei:
            exe.run(p, feed={"x": np.zeros((2, 4), np.float32)},
                    fetch_list=[out])
    finally:
        flags.set_flags({"check_program": old})
    assert "undefined-read" in str(ei.value)


def test_executor_flag_off_skips_verifier_entirely():
    import paddle_tpu.analysis as analysis_mod

    p = _prog()
    startup = fluid.Program()
    with fluid.program_guard(p, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        out = layers.relu(x)
    exe = fluid.Executor(fluid.CPUPlace())

    def _boom(*a, **kw):
        raise AssertionError("verifier must not run with the flag off")

    old_fn = analysis_mod.check_program
    old = flags.get_flag("check_program")
    flags.set_flags({"check_program": False})
    analysis_mod.check_program = _boom
    try:
        (r,) = exe.run(p, feed={"x": np.ones((2, 4), np.float32)},
                       fetch_list=[out])
    finally:
        analysis_mod.check_program = old_fn
        flags.set_flags({"check_program": old})
    assert np.allclose(np.asarray(r), 1.0)


def test_executor_verifies_once_per_program_version():
    import paddle_tpu.analysis as analysis_mod

    calls = []
    old_fn = analysis_mod.check_program

    def _counting(prog, **kw):
        calls.append(1)
        return old_fn(prog, **kw)

    p = _prog()
    startup = fluid.Program()
    with fluid.program_guard(p, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        out = layers.relu(x)
    exe = fluid.Executor(fluid.CPUPlace())
    old = flags.get_flag("check_program")
    flags.set_flags({"check_program": True})
    analysis_mod.check_program = _counting
    try:
        feed = {"x": np.ones((2, 4), np.float32)}
        exe.run(p, feed=feed, fetch_list=[out])
        exe.run(p, feed=feed, fetch_list=[out])
        exe.run(p, feed=feed, fetch_list=[out])
    finally:
        analysis_mod.check_program = old_fn
        flags.set_flags({"check_program": old})
    assert len(calls) == 1  # memoized per program version


# ---------------------------------------------------------------------------
# shared graph helpers (the four-private-copies dedup)
# ---------------------------------------------------------------------------
def test_graph_helpers_shared_by_all_walkers():
    from paddle_tpu.analysis import graph
    from paddle_tpu.transpiler.pass_registry import OpPattern

    prog, _ = _fc_chain()
    b = prog.global_block()
    cm = graph.consumer_map(b)
    assert OpPattern(["mul"])._consumer_map(b) == cm
    cc = graph.consumer_count(b)
    assert {n: len(v) for n, v in cm.items()} == cc
    pm = graph.producer_map(b)
    for n, i in pm.items():
        assert n in b.ops[i].output_arg_names()
    # ControlFlowGraph consumes def_use_lists
    from paddle_tpu.transpiler.memory_optimization_transpiler import (
        ControlFlowGraph,
    )

    cfg = ControlFlowGraph(prog)
    defs, uses = graph.def_use_lists(prog, 0)
    assert cfg.defs == defs and cfg.uses == uses


def test_def_use_includes_sub_block_external_reads():
    p = _prog()
    b = p.global_block()
    b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    b.create_var(name="ext", shape=[4], dtype="float32")
    b.create_var(name="t", shape=[4], dtype="float32")
    b.append_op("relu", inputs={"X": ["x"]}, outputs={"Out": ["ext"]})
    sub = p.create_block(parent_idx=0)
    p.current_block_idx = 0
    op = fluid.Operator(sub, "tanh", None, None, {})
    op.inputs = {"X": ["ext"]}
    op.outputs = {"Out": ["t"]}
    sub.ops.append(op)
    rec = fluid.Operator(b, "recompute", None, None, {
        "sub_block_idx": sub.idx, "in_names": [], "out_names": ["t"],
        "__bound_names__": []})
    rec.inputs = {"X": []}
    rec.outputs = {"Out": ["t"]}
    b.ops.append(rec)
    from paddle_tpu.analysis.graph import def_use_lists

    _defs, uses = def_use_lists(p, 0)
    assert "ext" in uses[1]  # the sub-block's external read surfaces


# ---------------------------------------------------------------------------
# positive sweeps: builders x pipelines verify clean
# ---------------------------------------------------------------------------
def test_builder_sweep_fast():
    """Tier-1 subset of the lint CLI matrix (cheap builders)."""
    import importlib

    mod = importlib.import_module("tools.check_program")
    n, failed, results = mod.run_matrix(fast=True, quiet=True)
    assert n >= 5
    assert failed == 0, results


@pytest.mark.slow
def test_builder_sweep_full_matrix():
    """ALL builder x pass-pipeline combinations in the lint CLI verify
    clean (the ci.sh static-analysis lane runs the CLI itself too)."""
    import importlib

    mod = importlib.import_module("tools.check_program")
    n, failed, results = mod.run_matrix(quiet=True)
    assert n >= 14
    assert failed == 0, results


def test_train_builder_with_backward_verifies_clean():
    """Grad-var conventions: a full fwd+bwd+optimizer program (grad ops
    carrying the __fwd_* bookkeeping, sum fan-in, @GRAD naming) passes
    the propagation engine with zero errors."""
    x = layers.data(name="x", shape=[8], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(x, size=16, act="relu")
    pred = layers.fc(h, size=1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    diags = verify_program(fluid.default_main_program(),
                           fetches=[loss.name])
    assert not _errors(diags), diags


def test_executor_verify_is_dce_scoped_but_refetch_reverifies():
    """Review-hardening regressions: (a) ops the executor's DCE drops
    for THIS run's fetches are not verified (a malformed unfetched
    branch must not block a healthy fetch); (b) fetching the malformed
    branch later re-verifies (the memo keys on the fetch set); (c) the
    same program against a DIFFERENT scope re-verifies too."""
    p = _prog()
    startup = fluid.Program()
    with fluid.program_guard(p, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        good = layers.relu(x)
    # malformed side branch: matmul with an impossible contraction,
    # feeding a var nobody fetches by default
    b = p.global_block()
    b.create_var(name="badw", shape=[5, 6], dtype="float32",
                 persistable=True)
    b.create_var(name="bad_out", shape=[2, 6], dtype="float32")
    bad = fluid.Operator(b, "matmul", None, None, {})
    bad.inputs = {"X": [x.name], "Y": ["badw"]}
    bad.outputs = {"Out": ["bad_out"]}
    b.ops.append(bad)
    p._bump_version()

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        import paddle_tpu.initializer  # noqa: F401
        scope.set("badw", np.zeros((5, 6), np.float32))
        feed = {"x": np.ones((2, 4), np.float32)}
        old = flags.get_flag("check_program")
        flags.set_flags({"check_program": True})
        try:
            # (a) healthy fetch: the bad branch is DCE'd, run succeeds
            (r,) = exe.run(p, feed=feed, fetch_list=[good])
            assert np.allclose(np.asarray(r), 1.0)
            # (b) fetching the bad branch re-verifies and raises
            with pytest.raises(ProgramVerifyError, match="shape-mismatch"):
                exe.run(p, feed=feed, fetch_list=["bad_out"])
        finally:
            flags.set_flags({"check_program": old})

    # (c) a different scope re-verifies: drop a scope-resident read
    import paddle_tpu.analysis as analysis_mod

    calls = []
    old_fn = analysis_mod.check_program

    def _counting(prog, **kw):
        calls.append(1)
        return old_fn(prog, **kw)

    scope2 = fluid.Scope()
    analysis_mod.check_program = _counting
    flags.set_flags({"check_program": True})
    try:
        with fluid.scope_guard(scope):
            exe.run(p, feed=feed, fetch_list=[good])  # memoized: no call
        assert calls == []
        with fluid.scope_guard(scope2):
            # a different scope identity re-verifies (scope-resident
            # names count as defined, so the verdict is scope-dependent)
            exe.run(p, feed=feed, fetch_list=[good])
        assert len(calls) == 1
    finally:
        analysis_mod.check_program = old_fn
        flags.set_flags({"check_program": old})
