"""Control flow: While -> lax.while_loop, cond -> lax.cond, calc_gradient."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def test_while_loop_sums_to_n():
    i = layers.fill_constant([1], "float32", 0.0)
    n = layers.fill_constant([1], "float32", 10.0)
    total = layers.fill_constant([1], "float32", 0.0)
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        t2 = layers.elementwise_add(total, i)
        layers.assign(t2, total)
        layers.increment(i, 1.0)
        layers.less_than(i, n, cond=cond)
    exe = fluid.Executor(fluid.CPUPlace())
    (out,) = exe.run(fetch_list=[total])
    assert float(out[0]) == 45.0  # 0+1+..+9


def test_cond_branches():
    x = layers.data("x", shape=[1], append_batch_size=False)
    pred = layers.greater_than(x, layers.fill_constant([1], "float32", 0.0))

    def true_fn():
        return layers.elementwise_mul(x, x)

    def false_fn():
        return layers.scale(x, -1.0)

    out = fluid.layers.control_flow.cond(pred, true_fn, false_fn)
    exe = fluid.Executor(fluid.CPUPlace())
    (r,) = exe.run(feed={"x": np.array([3.0], "float32")}, fetch_list=[out])
    assert float(r[0]) == 9.0
    (r,) = exe.run(feed={"x": np.array([-4.0], "float32")}, fetch_list=[out])
    assert float(r[0]) == 4.0


def test_calc_gradient():
    x = layers.data("x", shape=[4], append_batch_size=False, stop_gradient=False)
    y = layers.reduce_sum(layers.square(x))
    (gx,) = fluid.backward.calc_gradient(y, x)
    assert gx is not None
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([1.0, 2.0, 3.0, 4.0], "float32")
    (g,) = exe.run(feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(g, 2 * xv, rtol=1e-6)


def test_numpy_scalar_operand():
    x = layers.data("x", shape=[3], append_batch_size=False)
    y = x * np.float32(2.0) + np.float32(1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    (r,) = exe.run(feed={"x": np.ones(3, "float32")}, fetch_list=[y])
    np.testing.assert_allclose(r, [3.0, 3.0, 3.0])


def test_static_rnn_matches_numpy_and_numeric_grad():
    """StaticRNN (time-major) == numpy scan; W grad == finite differences."""
    T, B, D, H = 4, 3, 5, 6
    rng = np.random.RandomState(0)
    xv = rng.randn(T, B, D).astype("float32")
    x = layers.data("x", shape=[T, B, D], append_batch_size=False, stop_gradient=False)
    rnn = layers.StaticRNN()
    with rnn.step():
        xt = rnn.step_input(x)
        h_prev = rnn.memory(shape=[H], batch_ref=xt, init_value=0.0)
        h = layers.fc(xt, H, bias_attr=False, param_attr=fluid.ParamAttr(name="srnn_W"))
        h2 = layers.fc(h_prev, H, bias_attr=False, param_attr=fluid.ParamAttr(name="srnn_U"))
        hn = layers.tanh(layers.elementwise_add(h, h2))
        rnn.update_memory(h_prev, hn)
        rnn.output(hn)
    out = rnn()
    loss = layers.mean(out)
    pg = fluid.backward.append_backward(loss)
    gnames = {p.name: g.name for p, g in pg}
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    scope = fluid.global_scope()
    res = exe.run(feed={"x": xv}, fetch_list=[out, gnames["srnn_W"]])
    W = np.array(scope.find_var("srnn_W"))
    U = np.array(scope.find_var("srnn_U"))
    h = np.zeros((B, H), "float32")
    outs = []
    for t in range(T):
        h = np.tanh(xv[t] @ W + h @ U)
        outs.append(h)
    np.testing.assert_allclose(res[0], np.stack(outs, 0), rtol=1e-5, atol=1e-5)

    def lossf(Wv):
        hh = np.zeros((B, H))
        acc = []
        for t in range(T):
            hh = np.tanh(xv[t] @ Wv + hh @ U)
            acc.append(hh)
        return np.mean(np.stack(acc))

    eps, gW = 1e-3, res[1]
    for i in range(2):
        for j in range(2):
            Wp, Wm = W.copy(), W.copy()
            Wp[i, j] += eps
            Wm[i, j] -= eps
            num = (lossf(Wp) - lossf(Wm)) / (2 * eps)
            assert abs(gW[i, j] - num) < 1e-3, (i, j, gW[i, j], num)


def test_dynamic_rnn_seq_len_masking_and_grads():
    """DynamicRNN (batch-major padded) with ragged lengths == masked numpy
    scan; gradients flow to in-loop parameters."""
    B, T, D, H = 3, 5, 4, 6
    rng = np.random.RandomState(1)
    xv = rng.randn(B, T, D).astype("float32")
    lens = np.array([5, 3, 2], "int32")
    x = layers.data("x", shape=[B, T, D], append_batch_size=False, stop_gradient=False)
    sl = layers.data("sl", shape=[B], append_batch_size=False, dtype="int32")
    drnn = layers.DynamicRNN()
    with drnn.block():
        xt = drnn.step_input(x, seq_len=sl)
        mem = drnn.memory(shape=[H], value=0.0)
        h = layers.fc(xt, H, bias_attr=False, param_attr=fluid.ParamAttr(name="drnn_W"))
        h2 = layers.fc(mem, H, bias_attr=False, param_attr=fluid.ParamAttr(name="drnn_U"))
        hn = layers.tanh(layers.elementwise_add(h, h2))
        drnn.update_memory(mem, hn)
        drnn.output(hn)
    out = drnn()
    loss = layers.mean(out)
    pg = fluid.backward.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    scope = fluid.global_scope()
    res = exe.run(feed={"x": xv, "sl": lens}, fetch_list=[out, pg[0][1].name])
    W = np.array(scope.find_var("drnn_W"))
    U = np.array(scope.find_var("drnn_U"))
    ref = np.zeros((B, T, H), "float32")
    h = np.zeros((B, H), "float32")
    for t in range(T):
        hn = np.tanh(xv[:, t] @ W + h @ U)
        act = (t < lens)[:, None]
        h = np.where(act, hn, h)
        ref[:, t] = np.where(act, hn, 0.0)
    np.testing.assert_allclose(res[0], ref, rtol=1e-4, atol=1e-5)
    assert np.abs(res[1]).sum() > 0


def test_dynamic_rnn_gru_matches_padded_gru_op():
    """A DynamicRNN stepping gru_unit == the fused padded_gru scan op —
    the VERDICT round-1 acceptance check (padded-scan parity within 1e-4)."""
    B, T, H = 2, 4, 3
    rng = np.random.RandomState(2)
    xv = rng.randn(B, T, 3 * H).astype("float32")
    wv = rng.randn(H, 3 * H).astype("float32")
    x = layers.data("x", shape=[B, T, 3 * H], append_batch_size=False)
    w = layers.data("w", shape=[H, 3 * H], append_batch_size=False)

    drnn = layers.DynamicRNN()
    with drnn.block():
        xt = drnn.step_input(x)
        mem = drnn.memory(shape=[H], value=0.0)
        helper = fluid.layer_helper.LayerHelper("gru_step")
        hidden = helper.create_variable_for_type_inference("float32")
        gate = helper.create_variable_for_type_inference("float32")
        rhp = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            "gru_unit",
            inputs={"Input": [xt], "HiddenPrev": [mem], "Weight": [w]},
            outputs={"Gate": [gate], "ResetHiddenPrev": [rhp], "Hidden": [hidden]},
        )
        drnn.update_memory(mem, hidden)
        drnn.output(hidden)
    out = drnn()

    helper = fluid.layer_helper.LayerHelper("padded_gru_ref")
    ref_h = helper.create_variable_for_type_inference("float32")
    ref_last = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "padded_gru",
        inputs={"Input": [x], "Weight": [w]},
        outputs={"Hidden": [ref_h], "LastH": [ref_last]},
    )
    exe = fluid.Executor(fluid.CPUPlace())
    r = exe.run(feed={"x": xv, "w": wv}, fetch_list=[out, ref_h])
    np.testing.assert_allclose(r[0], r[1], rtol=1e-4, atol=1e-5)


def test_bounded_while_gradient():
    """While(max_iters=N) lowers to a masked scan and is differentiable:
    acc doubles 4 times -> d(sum)/dx = 16 (unbounded While raises)."""
    x = layers.data("x", shape=[3], append_batch_size=False, stop_gradient=False)
    acc = layers.assign(x)
    i = layers.fill_constant([1], "float32", 0.0)
    n = layers.fill_constant([1], "float32", 4.0)
    cond = layers.less_than(i, n)
    w = layers.While(cond, max_iters=8)
    with w.block():
        layers.assign(layers.scale(acc, 2.0), acc)
        layers.increment(i, 1.0)
        layers.less_than(i, n, cond=cond)
    s = layers.reduce_sum(acc)
    (gx,) = fluid.backward.calc_gradient(s, x)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([1.0, 2.0, 3.0], "float32")
    r = exe.run(feed={"x": xv}, fetch_list=[acc, gx])
    np.testing.assert_allclose(r[0], xv * 16, rtol=1e-6)
    np.testing.assert_allclose(r[1], np.full(3, 16.0), rtol=1e-6)


def test_unbounded_while_grad_raises():
    x = layers.data("x", shape=[3], append_batch_size=False, stop_gradient=False)
    acc = layers.assign(x)
    i = layers.fill_constant([1], "float32", 0.0)
    n = layers.fill_constant([1], "float32", 4.0)
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        layers.assign(layers.scale(acc, 2.0), acc)
        layers.increment(i, 1.0)
        layers.less_than(i, n, cond=cond)
    s = layers.reduce_sum(acc)
    import pytest

    with pytest.raises(RuntimeError, match="max_iters"):
        fluid.backward.calc_gradient(s, x)


def test_tensor_array_write_read_in_while():
    i = layers.fill_constant([1], "int32", 0)
    n = layers.fill_constant([1], "int32", 5)
    x0 = layers.fill_constant([2], "float32", 1.0)
    arr = layers.array_write(x0, i, capacity=8)
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        v = layers.array_read(arr, i)
        layers.increment(i, 1.0)
        layers.array_write(layers.scale(v, 2.0), i, array=arr)
        layers.less_than(i, n, cond=cond)
    ln = layers.array_length(arr)
    last = layers.array_read(arr, layers.fill_constant([1], "int32", 5))
    exe = fluid.Executor(fluid.CPUPlace())
    r = exe.run(fetch_list=[ln, last])
    assert int(r[0][0]) == 6
    np.testing.assert_allclose(r[1], [32.0, 32.0])


def test_lod_tensor_to_array_roundtrip():
    B, T, D = 2, 3, 4
    xv = np.random.RandomState(3).randn(B, T, D).astype("float32")
    x = layers.data("x", shape=[B, T, D], append_batch_size=False)
    arr = layers.lod_tensor_to_array(x)
    step1 = layers.array_read(arr, layers.fill_constant([1], "int32", 1))
    back = layers.array_to_lod_tensor(arr)
    exe = fluid.Executor(fluid.CPUPlace())
    r = exe.run(feed={"x": xv}, fetch_list=[step1, back])
    np.testing.assert_allclose(r[0], xv[:, 1])
    np.testing.assert_allclose(r[1], xv)


def test_ifelse_row_select():
    xb = layers.data("xb", shape=[4, 2], append_batch_size=False)
    zero = layers.fill_constant([4, 1], "float32", 0.0)
    m = layers.reduce_sum(xb, dim=1, keep_dim=True)
    c = layers.greater_than(m, zero)
    ie = layers.IfElse(c)
    with ie.true_block():
        d = ie.input(xb)
        ie.output(layers.scale(d, 10.0))
    with ie.false_block():
        d = ie.input(xb)
        ie.output(layers.scale(d, -1.0))
    (out,) = ie()
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([[1, 1], [-1, -2], [3, 0], [-1, 0.5]], "float32")
    (r,) = exe.run(feed={"xb": xv}, fetch_list=[out])
    np.testing.assert_allclose(r, np.where(xv.sum(1, keepdims=True) > 0, xv * 10, -xv))


def test_switch_piecewise_lr():
    step = layers.data("step", shape=[1], append_batch_size=False)
    lr = layers.fill_constant([1], "float32", 0.0)
    b1 = layers.fill_constant([1], "float32", 10.0)
    b2 = layers.fill_constant([1], "float32", 100.0)
    with layers.Switch() as sw:
        with sw.case(layers.less_than(step, b1)):
            layers.assign(layers.fill_constant([1], "float32", 0.1), lr)
        with sw.case(layers.less_than(step, b2)):
            layers.assign(layers.fill_constant([1], "float32", 0.01), lr)
        with sw.default():
            layers.assign(layers.fill_constant([1], "float32", 0.001), lr)
    exe = fluid.Executor(fluid.CPUPlace())
    for sv, expect in [(5.0, 0.1), (50.0, 0.01), (500.0, 0.001)]:
        (r,) = exe.run(feed={"step": np.array([sv], "float32")}, fetch_list=[lr])
        assert abs(float(r[0]) - expect) < 1e-8


def test_ifelse_side_effecting_op_rejected():
    """IfElse branches run compute-both, so a print op inside a branch
    would fire for every row regardless of cond — the branch guard must
    reject it with a clear error (the reference executes only the taken
    branch: control_flow.py:1412)."""
    import pytest

    xb = layers.data("sex", shape=[4, 2], append_batch_size=False)
    zero = layers.fill_constant([4, 1], "float32", 0.0)
    c = layers.greater_than(layers.reduce_sum(xb, dim=1, keep_dim=True), zero)
    ie = layers.IfElse(c)
    with pytest.raises(ValueError, match="side-effecting op 'print'"):
        with ie.true_block():
            d = ie.input(xb)
            layers.Print(d, message="branch")
            ie.output(d)


def test_ifelse_persistable_write_rejected():
    """A persistable write inside an IfElse branch would apply
    unconditionally under the compute-both lowering — rejected, with the
    Switch-based alternative named in the error."""
    import pytest

    xb = layers.data("pwx", shape=[4, 2], append_batch_size=False)
    zero = layers.fill_constant([4, 1], "float32", 0.0)
    c = layers.greater_than(layers.reduce_sum(xb, dim=1, keep_dim=True), zero)
    gstate = layers.create_global_var([4, 2], 0.0, "float32",
                                      persistable=True, name="pw_gstate")
    ie = layers.IfElse(c)
    with pytest.raises(ValueError, match="persistable var 'pw_gstate'"):
        with ie.true_block():
            d = ie.input(xb)
            layers.assign(layers.scale(d, 2.0), gstate)
            ie.output(d)


def test_ifelse_branch_batch_norm_inference_ok_training_rejected():
    """batch_norm lists its persistable moving stats as outputs even in
    is_test mode where no update occurs — the guard must allow the
    inference form and reject only the genuinely mutating train form."""
    import pytest

    xb = layers.data("bnx", shape=[4, 6], append_batch_size=False)
    zero = layers.fill_constant([4, 1], "float32", 0.0)
    c = layers.greater_than(layers.reduce_sum(xb, dim=1, keep_dim=True), zero)
    ie = layers.IfElse(c)
    with ie.true_block():
        d = ie.input(xb)
        ie.output(layers.batch_norm(d, is_test=True))  # allowed: no-op write
    with ie.false_block():
        ie.output(ie.input(xb))
    ie()

    ie2 = layers.IfElse(c)
    with pytest.raises(ValueError, match="persistable"):
        with ie2.true_block():
            d = ie2.input(xb)
            ie2.output(layers.batch_norm(d))  # train mode mutates stats


def test_ifelse_nested_sub_block_side_effect_rejected():
    """Effects hidden in a nested sub-block (a Switch case inside the
    branch) are just as unconditional — the guard recurses into
    sub_block attrs and rejects them too."""
    import pytest

    xb = layers.data("nsx", shape=[4, 2], append_batch_size=False)
    zero = layers.fill_constant([4, 1], "float32", 0.0)
    c = layers.greater_than(layers.reduce_sum(xb, dim=1, keep_dim=True), zero)
    g = layers.create_global_var([1], 0.0, "float32", persistable=True,
                                 name="ns_gvar")
    ie = layers.IfElse(c)
    with pytest.raises(ValueError, match="persistable var 'ns_gvar'"):
        with ie.true_block():
            d = ie.input(xb)
            one = layers.fill_constant([1], "float32", 1.0)
            with layers.Switch() as sw:
                with sw.case(layers.less_than(one, one)):
                    layers.assign(layers.fill_constant([1], "float32", 2.0),
                                  g)
                with sw.default():
                    layers.assign(layers.fill_constant([1], "float32", 3.0),
                                  g)
            ie.output(d)


def test_ifelse_rng_branch_is_pure_row_select():
    """RNG ops ARE allowed in IfElse branches: the per-run key is
    threaded functionally by the executor (fresh masks each run, as
    training needs), and the row merge keeps only the taken branch's
    values per row — the untaken branch's draws never leak into
    cond-false rows."""
    xb = layers.data("irx", shape=[4, 2], append_batch_size=False)
    zero = layers.fill_constant([4, 1], "float32", 0.0)
    c = layers.greater_than(layers.reduce_sum(xb, dim=1, keep_dim=True), zero)
    ie = layers.IfElse(c)
    with ie.true_block():
        d = ie.input(xb)
        ie.output(layers.dropout(layers.scale(d, 10.0), 0.5, seed=11))
    with ie.false_block():
        d = ie.input(xb)
        ie.output(layers.scale(d, -1.0))
    (out,) = ie()
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([[1, 1], [-1, -2], [3, 0.5], [-1, 0.5]], "float32")
    mask = xv.sum(1, keepdims=True) > 0
    for _ in range(2):  # fresh dropout key each run; invariants hold always
        (r,) = exe.run(feed={"irx": xv}, fetch_list=[out])
        r = np.asarray(r)
        # cond-false rows never see the true branch's draws
        np.testing.assert_allclose(np.where(mask, 0, r),
                                   np.where(mask, 0, -xv))
        # cond-true rows: dropout kept (10x) or dropped (0), elementwise
        tr = r[mask[:, 0]]
        tx = xv[mask[:, 0]]
        assert np.all(
            (np.abs(tr) < 1e-6) | (np.abs(tr - tx * 10.0) < 1e-4)), tr


def test_switch_case_write_only_lands_when_taken():
    """Contrast with IfElse: Switch case sub-blocks ARE the sanctioned
    place for conditional persistable writes — the trace merges every
    case's writes by condition, so only the taken case's value lands."""
    step = layers.data("swp", shape=[1], append_batch_size=False)
    g = layers.create_global_var([1], -1.0, "float32", persistable=True,
                                 name="sw_gvar")
    b1 = layers.fill_constant([1], "float32", 10.0)
    with layers.Switch() as sw:
        with sw.case(layers.less_than(step, b1)):
            layers.assign(layers.fill_constant([1], "float32", 7.0), g)
        with sw.default():
            layers.assign(layers.fill_constant([1], "float32", 9.0), g)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    (r,) = exe.run(feed={"swp": np.array([5.0], "float32")}, fetch_list=[g])
    assert abs(float(r[0]) - 7.0) < 1e-8
    (r,) = exe.run(feed={"swp": np.array([50.0], "float32")}, fetch_list=[g])
    assert abs(float(r[0]) - 9.0) < 1e-8


def test_dynamic_rnn_seq2seq_trains():
    """Encoder-decoder built on DynamicRNN trains end-to-end (grads flow
    through the recurrence into all parameters; loss decreases)."""
    B, T, V, H = 4, 6, 20, 16
    rng = np.random.RandomState(4)
    src = rng.randint(0, V, (B, T)).astype("int64")
    trg = rng.randint(0, V, (B, T)).astype("int64")
    s = layers.data("src", shape=[B, T], append_batch_size=False, dtype="int64")
    t = layers.data("trg", shape=[B, T], append_batch_size=False, dtype="int64")
    semb = layers.embedding(s, size=[V, H], param_attr=fluid.ParamAttr(name="s2s_emb"))
    ctx_vec = layers.reduce_mean(semb, dim=1)  # [B, H] encoder summary
    temb = layers.embedding(t, size=[V, H], param_attr=fluid.ParamAttr(name="s2s_demb"))
    drnn = layers.DynamicRNN()
    with drnn.block():
        xt = drnn.step_input(temb)
        cvec = drnn.static_input(ctx_vec)
        mem = drnn.memory(shape=[H], value=0.0)
        cat = layers.concat([xt, cvec, mem], axis=1)
        hn = layers.fc(cat, H, act="tanh", param_attr=fluid.ParamAttr(name="s2s_W"))
        drnn.update_memory(mem, hn)
        drnn.output(hn)
    dec = drnn()  # [B, T, H]
    logits = layers.fc(
        layers.reshape(dec, [-1, H]), V, param_attr=fluid.ParamAttr(name="s2s_O")
    )
    label = layers.reshape(t, [-1, 1])
    loss = layers.mean(
        layers.softmax_with_cross_entropy(logits, label)
    )
    fluid.optimizer.SGD(0.5).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = []
    for _ in range(8):
        (lv,) = exe.run(feed={"src": src, "trg": trg}, fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] - 0.1, losses


def test_recompute_scope_matches_plain_forward_and_grads():
    """layers.recompute: identical forward AND parameter gradients vs the
    plain graph (jax.checkpoint only trades memory for FLOPs), grads flow
    into both the scope's inputs and the parameters created inside."""
    import paddle_tpu.framework as fw
    from paddle_tpu import unique_name
    from paddle_tpu.core import scope as scope_mod

    rng = np.random.RandomState(0)
    xv = rng.rand(4, 8).astype("float32")
    yv = rng.randint(0, 3, (4, 1)).astype("int64")

    def build(use_remat):
        fw.switch_main_program(fluid.Program())
        fw.switch_startup_program(fluid.Program())
        unique_name.switch()
        scope_mod._switch_scope(scope_mod.Scope())
        x = layers.data("rx", shape=[8])
        y = layers.data("ry", shape=[1], dtype="int64")

        def block(h):
            h = layers.fc(h, 16, act="gelu",
                          param_attr=fluid.ParamAttr(name="rc_w1"))
            return layers.fc(h, 8, param_attr=fluid.ParamAttr(name="rc_w2"))

        h = layers.recompute(block, x) if use_remat else block(x)
        pred = layers.fc(h, 3, act="softmax",
                         param_attr=fluid.ParamAttr(name="rc_w3"))
        loss = layers.mean(layers.cross_entropy(pred, y))
        fluid.optimizer.SGD(0.5).minimize(loss)
        main = fluid.default_main_program()
        main.random_seed = 7
        fluid.default_startup_program().random_seed = 7
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        losses = []
        for _ in range(4):
            (lv,) = exe.run(main, feed={"rx": xv, "ry": yv},
                            fetch_list=[loss])
            losses.append(float(np.ravel(lv)[0]))
        weights = {
            n: np.asarray(fluid.global_scope().get(n))
            for n in ("rc_w1", "rc_w2", "rc_w3")
        }
        return losses, weights

    plain_losses, plain_w = build(False)
    remat_losses, remat_w = build(True)
    # identical math: losses and post-SGD weights match step for step
    np.testing.assert_allclose(remat_losses, plain_losses, rtol=1e-5)
    for n in plain_w:
        np.testing.assert_allclose(remat_w[n], plain_w[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)
    assert plain_losses[-1] < plain_losses[0]


def test_recompute_rejects_unreturned_outer_writes():
    """Stateful updates crossing the remat boundary (batch_norm moving
    stats) fail loudly at build time instead of silently freezing."""
    import pytest

    x = layers.data("rj_x", shape=[3, 8, 8])

    def block(h):
        c = layers.conv2d(h, 4, 3)
        return layers.batch_norm(c)  # writes moving stats to outer vars

    with pytest.raises(ValueError, match="outer variable"):
        layers.recompute(block, x)
