"""Control flow: While -> lax.while_loop, cond -> lax.cond, calc_gradient."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def test_while_loop_sums_to_n():
    i = layers.fill_constant([1], "float32", 0.0)
    n = layers.fill_constant([1], "float32", 10.0)
    total = layers.fill_constant([1], "float32", 0.0)
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        t2 = layers.elementwise_add(total, i)
        layers.assign(t2, total)
        layers.increment(i, 1.0)
        layers.less_than(i, n, cond=cond)
    exe = fluid.Executor(fluid.CPUPlace())
    (out,) = exe.run(fetch_list=[total])
    assert float(out[0]) == 45.0  # 0+1+..+9


def test_cond_branches():
    x = layers.data("x", shape=[1], append_batch_size=False)
    pred = layers.greater_than(x, layers.fill_constant([1], "float32", 0.0))

    def true_fn():
        return layers.elementwise_mul(x, x)

    def false_fn():
        return layers.scale(x, -1.0)

    out = fluid.layers.control_flow.cond(pred, true_fn, false_fn)
    exe = fluid.Executor(fluid.CPUPlace())
    (r,) = exe.run(feed={"x": np.array([3.0], "float32")}, fetch_list=[out])
    assert float(r[0]) == 9.0
    (r,) = exe.run(feed={"x": np.array([-4.0], "float32")}, fetch_list=[out])
    assert float(r[0]) == 4.0


def test_calc_gradient():
    x = layers.data("x", shape=[4], append_batch_size=False, stop_gradient=False)
    y = layers.reduce_sum(layers.square(x))
    (gx,) = fluid.backward.calc_gradient(y, x)
    assert gx is not None
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([1.0, 2.0, 3.0, 4.0], "float32")
    (g,) = exe.run(feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(g, 2 * xv, rtol=1e-6)


def test_numpy_scalar_operand():
    x = layers.data("x", shape=[3], append_batch_size=False)
    y = x * np.float32(2.0) + np.float32(1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    (r,) = exe.run(feed={"x": np.ones(3, "float32")}, fetch_list=[y])
    np.testing.assert_allclose(r, [3.0, 3.0, 3.0])
