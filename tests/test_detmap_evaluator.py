import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers, evaluator, metrics

def test_detection_map_evaluator_streams_and_resets():
    """evaluator.DetectionMAP (evaluator.py:298 parity): cur_map is the
    batch mAP, accum_map streams across runs, reset() clears the
    accumulator; difficult gts excluded when evaluate_difficult=False."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        det = layers.data("det", shape=[4, 6], dtype="float32")
        gl = layers.data("gl", shape=[2, 1], dtype="float32")
        gb = layers.data("gb", shape=[2, 4], dtype="float32")
        ev = evaluator.DetectionMAP(
            layers.reshape(det, [-1, 6]),
            layers.reshape(gl, [-1, 1]),
            layers.reshape(gb, [-1, 4]))
        cur, acc = ev.get_map_var()

    def batch(seed):
        rng = np.random.RandomState(seed)
        gbx = np.zeros((1, 2, 4), "float32")
        gbx[0, :, :2] = rng.rand(2, 2) * 4
        gbx[0, :, 2:] = gbx[0, :, :2] + 1.0 + rng.rand(2, 2)
        gl = rng.randint(0, 3, (1, 2, 1)).astype("float32")
        d = np.full((1, 4, 6), -1, "float32")
        # two detections: one matching gt 0 exactly, one garbage box
        d[0, 0] = [gl[0, 0, 0], 0.9, *gbx[0, 0]]
        d[0, 1] = [gl[0, 1, 0], 0.7, *(gbx[0, 1] + 3.0)]
        return {"det": d, "gl": gl, "gb": gbx}

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        b1, b2 = batch(1), batch(2)
        c1, a1 = exe.run(main, feed=b1, fetch_list=[cur, acc])
        c2, a2 = exe.run(main, feed=b2, fetch_list=[cur, acc])

    # host-side reference on the union vs each batch
    def ref(batches):
        m = metrics.DetectionMAP()
        for b in batches:
            m.update(b["det"][0], b["gb"][0], b["gl"][0].reshape(-1))
        return m.eval()

    assert abs(float(np.asarray(c1)) - ref([b1])) < 1e-6
    assert abs(float(np.asarray(a1)) - ref([b1])) < 1e-6
    assert abs(float(np.asarray(c2)) - ref([b2])) < 1e-6
    assert abs(float(np.asarray(a2)) - ref([b1, b2])) < 1e-6

    # reset clears the stream: next accum == that batch alone
    ev.reset()
    with fluid.scope_guard(scope):
        c3, a3 = exe.run(main, feed=b1, fetch_list=[cur, acc])
    assert abs(float(np.asarray(a3)) - ref([b1])) < 1e-6


def test_detection_map_difficult_gts_excluded():
    """VOC difficult convention: with evaluate_difficult=False a
    difficult gt leaves npos and its matches are neither tp nor fp."""
    det = np.array([[0, 0.9, 0, 0, 1, 1], [0, 0.8, 2, 2, 3, 3]], "float32")
    gb = np.array([[0, 0, 1, 1], [2, 2, 3, 3]], "float32")
    gl = np.array([0, 0], "float32")
    hard = np.array([0, 1], "float32")
    m_all = metrics.DetectionMAP()
    m_all.update(det, gb, gl)
    m_excl = metrics.DetectionMAP()
    m_excl.update(det, gb, gl, difficult=hard)
    assert abs(m_all.eval() - 1.0) < 1e-6
    # difficult gt excluded: only 1 positive, its detection matches -> 1.0
    assert abs(m_excl.eval() - 1.0) < 1e-6
    # but npos differs: only one class-0 positive counted
    assert m_excl._npos == {0: 1}


def test_detmap_accumulator_outlives_dropped_evaluator_var():
    """ADVICE r5: the program holds a strong ref to its DetectionMAP
    evaluator, so a user dropping the evaluator variable mid-run cannot
    GC-reset the stream; and an op that DOES recreate a finalized key
    (orphaned program copy) warns instead of silently restarting."""
    import gc
    import warnings

    import pytest

    from paddle_tpu.ops import compat_ops

    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        det = layers.data("det", shape=[2, 6], dtype="float32")
        gl = layers.data("gl", shape=[1, 1], dtype="float32")
        gb = layers.data("gb", shape=[1, 4], dtype="float32")
        ev = evaluator.DetectionMAP(
            layers.reshape(det, [-1, 6]), layers.reshape(gl, [-1, 1]),
            layers.reshape(gb, [-1, 4]))
        cur, acc = ev.get_map_var()
    key = ev._accum_key
    assert main._detmap_keepalive[key] is ev
    del ev
    gc.collect()
    # the program still anchors the evaluator: no finalization happened
    assert key not in compat_ops._DETMAP_FINALIZED

    feed = {
        "det": np.array([[[0, .9, 0, 0, 1, 1], [-1, 0, 0, 0, 0, 0]]],
                        "float32"),
        "gl": np.array([[[0]]], "float32"),
        "gb": np.array([[[0, 0, 1, 1]]], "float32"),
    }
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[cur, acc])
        assert key in compat_ops._DETMAP_ACCUMS  # stream is live
        # now simulate the program itself dying: the finalizer fires...
        compat_ops.finalize_detection_map_accum(key)
        assert key not in compat_ops._DETMAP_ACCUMS
        # ...and a still-runnable copy of the op warns on the silent
        # stream restart instead of hiding it
        with pytest.warns(RuntimeWarning, match="garbage-collected"):
            exe.run(main, feed=feed, fetch_list=[cur, acc])
        # warn once per key: the next run is quiet
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            exe.run(main, feed=feed, fetch_list=[cur, acc])


def test_detection_map_accum_survives_unfetched_runs():
    """The streaming op is side-effecting: a run that fetches ONLY
    cur_map (reference training-loop pattern) must still feed the
    accumulator — dead-op pruning may not drop it."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        det = layers.data("det", shape=[2, 6], dtype="float32")
        gl = layers.data("gl", shape=[1, 1], dtype="float32")
        gb = layers.data("gb", shape=[1, 4], dtype="float32")
        ev = evaluator.DetectionMAP(
            layers.reshape(det, [-1, 6]), layers.reshape(gl, [-1, 1]),
            layers.reshape(gb, [-1, 4]))
        cur, acc = ev.get_map_var()
    feed_hit = {
        "det": np.array([[[0, .9, 0, 0, 1, 1], [-1, 0, 0, 0, 0, 0]]], "float32"),
        "gl": np.array([[[0]]], "float32"),
        "gb": np.array([[[0, 0, 1, 1]]], "float32"),
    }
    feed_miss = {
        "det": np.array([[[0, .9, 5, 5, 6, 6], [-1, 0, 0, 0, 0, 0]]], "float32"),
        "gl": np.array([[[0]]], "float32"),
        "gb": np.array([[[0, 0, 1, 1]]], "float32"),
    }
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        # two runs fetching only cur_map: one hit, one miss
        exe.run(main, feed=feed_hit, fetch_list=[cur])
        exe.run(main, feed=feed_miss, fetch_list=[cur])
        _, a = exe.run(main, feed=feed_hit, fetch_list=[cur, acc])
    # stream saw hit, miss, hit: 2 tp + 1 fp over 3 positives
    got = float(np.asarray(a))
    assert 0.0 < got < 1.0, got  # unfetched runs WERE accumulated
