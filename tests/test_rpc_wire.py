"""Typed binary RPC wire format (grpc_serde.cc / send_recv.proto.in role):
no pickle on the wire, closed type system, version byte, frame-size guard,
optional HMAC — a hostile peer gets a parse error, never code execution."""

import socket
import struct
import threading

import numpy as np
import pytest

from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.rpc import (
    PROTO_VERSION,
    RPCClient,
    VarServer,
    _encode,
    _Reader,
    _recv_msg,
    _send_msg,
)


class _EchoService:
    def handle(self, verb, **kw):
        if verb == "echo":
            return kw
        if verb == "ping":
            return {"ok": True}
        return {"__error__": "unknown verb %s" % verb}


def _mk_server():
    srv = VarServer("127.0.0.1:0", _EchoService()).start()
    return srv, srv.endpoint


def test_roundtrip_all_types():
    vals = {
        "none": None,
        "t": True,
        "f": False,
        "i": -42,
        "fl": 3.5,
        "s": "héllo",
        "b": b"\x00\xffraw",
        "lst": [1, "two", None],
        "tup": (1, 2),
        "nested": {"a": {"b": [True, 2.0]}},
        "arr_f32": np.arange(6, dtype="float32").reshape(2, 3),
        "arr_i64": np.array([[7]], dtype="int64"),
        "arr_0d": np.float64(2.0),  # numpy scalar -> float
    }
    buf = bytes(_encode(vals, bytearray()))
    out = _Reader(buf).decode()
    assert out["none"] is None and out["t"] is True and out["f"] is False
    assert out["i"] == -42 and out["fl"] == 3.5
    assert out["s"] == "héllo" and out["b"] == b"\x00\xffraw"
    assert out["lst"] == [1, "two", None] and out["tup"] == (1, 2)
    assert out["nested"] == {"a": {"b": [True, 2.0]}}
    np.testing.assert_array_equal(out["arr_f32"], vals["arr_f32"])
    assert out["arr_f32"].dtype == np.float32
    np.testing.assert_array_equal(out["arr_i64"], vals["arr_i64"])


def test_bucket_frame_roundtrip_mixed_dtypes():
    """Bucketed wire format: a send_bucket payload is ONE dict frame of
    mixed-dtype block arrays; pack/unpack must round-trip every block
    bit-exactly, in one frame, through the real server."""
    blocks = {
        "w.block0": np.arange(12, dtype="float32") * 0.5,
        "w.block1": np.arange(5, dtype="float64") - 2.5,
        "emb.block0": np.array([3, -1, 7], dtype="int64"),
        "mask.block0": np.array([True, False, True]),
        "half.block0": np.arange(4, dtype="float16"),
    }
    buf = bytes(_encode(blocks, bytearray()))
    out = _Reader(buf).decode()
    assert sorted(out) == sorted(blocks)
    for k in blocks:
        np.testing.assert_array_equal(out[k], blocks[k])
        assert out[k].dtype == blocks[k].dtype
    # through a live server: one round trip carries the whole bucket
    srv, ep = _mk_server()
    try:
        before = rpc.get_comm_stats()["rpc_round_trips"]
        cli = RPCClient(ep, timeout=5, retries=2)
        echoed = cli.call("echo", blocks=blocks)["blocks"]
        assert rpc.get_comm_stats()["rpc_round_trips"] == before + 1
        for k in blocks:
            np.testing.assert_array_equal(echoed[k], blocks[k])
            assert echoed[k].dtype == blocks[k].dtype
        cli.close()
    finally:
        srv.shutdown()


def test_bucket_truncation_midframe_retries_once_applied():
    """A bucket frame truncated mid-wire (FaultyChannel): the client
    reconnects and replays; the pserver's dedup applies the bucket
    exactly once and the pending table holds every block of the
    coalesced frame."""
    from paddle_tpu.distributed.faults import FaultyChannel
    from paddle_tpu.distributed.ps_server import ParameterServer

    ps = ParameterServer([None, None], {"g0": 0, "g1": 1}, num_trainers=2,
                         sync_mode=True)
    srv = VarServer("127.0.0.1:0", ps).start()
    chan = FaultyChannel(srv.endpoint,
                         schedule={"c2s": {0: "truncate"}}).start()
    try:
        cli = RPCClient(chan.endpoint, timeout=2, retries=4,
                        retry_wait=0.05)
        blocks = {"g0": np.full((3,), 2.0, np.float32),
                  "g1": np.arange(4, dtype=np.float32)}
        r = cli.call("send_bucket", blocks=blocks, trainer_id=0)
        assert r == {"ok": True}
        assert chan.stats["c2s"]["truncate"] == 1
        assert sorted(ps._pending) == ["g0", "g1"]
        for name, want in blocks.items():
            per_trainer = ps._pending[name]
            assert list(per_trainer) == [0]  # applied once, one trainer
            np.testing.assert_array_equal(per_trainer[0], want)
        cli.close()
    finally:
        chan.stop()
        srv.shutdown()


def test_get_bucket_returns_all_blocks_one_frame():
    from paddle_tpu.distributed.ps_server import ParameterServer

    ps = ParameterServer([], {}, num_trainers=1, sync_mode=False)
    ps.scope.set("p.block0", np.arange(4, dtype=np.float32))
    ps.scope.set("p.block1", np.arange(3, dtype=np.float32) + 10)
    srv = VarServer("127.0.0.1:0", ps).start()
    try:
        cli = RPCClient(srv.endpoint, timeout=5, retries=2)
        before = rpc.get_comm_stats()["rpc_round_trips"]
        out = cli.call("get_bucket", names=["p.block0", "p.block1"],
                       trainer_id=0)
        assert rpc.get_comm_stats()["rpc_round_trips"] == before + 1
        np.testing.assert_array_equal(out["p.block0"],
                                      np.arange(4, dtype=np.float32))
        np.testing.assert_array_equal(out["p.block1"],
                                      np.arange(3, dtype=np.float32) + 10)
        with pytest.raises(RuntimeError):
            cli.call("get_bucket", names=["missing"], trainer_id=0)
        cli.close()
    finally:
        srv.shutdown()


def test_bf16_wire_array_roundtrip():
    """Compressed array tag: a Bf16Wire-wrapped float array ships as
    bf16 payload and decodes back to its ORIGINAL dtype with bf16
    rounding — half the array bytes of the f32 tag, same shape/dtype on
    arrival."""
    from paddle_tpu.distributed.rpc import Bf16Wire

    rng = np.random.RandomState(11)
    arr = (rng.rand(64, 3).astype("float32") - 0.5) * 8.0
    buf = bytes(_encode({"g": Bf16Wire(arr)}, bytearray()))
    plain = bytes(_encode({"g": arr}, bytearray()))
    assert len(buf) < len(plain) - arr.nbytes // 4  # payload halved
    out = _Reader(buf).decode()["g"]
    assert out.dtype == np.float32 and out.shape == arr.shape
    # bf16 keeps 8 mantissa bits: relative error bounded by 2^-8
    np.testing.assert_allclose(out, arr, rtol=1 / 256.0, atol=1e-6)
    # through a live server: the service sees a plain f32 array
    srv, ep = _mk_server()
    try:
        cli = RPCClient(ep, timeout=5, retries=2)
        echoed = cli.call("echo", value=Bf16Wire(arr))["value"]
        assert echoed.dtype == np.float32
        np.testing.assert_allclose(echoed, arr, rtol=1 / 256.0, atol=1e-6)
        cli.close()
    finally:
        srv.shutdown()


def test_int8_wire_array_roundtrip_exact_dequant():
    """Int8 tag: the decoder returns scale * q exactly (the quantization
    error lives in the CALLER's error-feedback residual, never the
    wire), in the declared original dtype."""
    from paddle_tpu.distributed.rpc import Int8Wire

    q = np.array([[-127, 0, 1], [64, -3, 127]], np.int8)
    scale = 0.0375
    buf = bytes(_encode([Int8Wire(q, scale, "<f4")], bytearray()))
    (out,) = _Reader(buf).decode()
    assert out.dtype == np.float32 and out.shape == q.shape
    np.testing.assert_array_equal(
        out, q.astype(np.float32) * np.float32(scale))
    # wrapper refuses non-int8 payloads and non-float targets
    with pytest.raises(TypeError):
        Int8Wire(q.astype(np.int16), scale)
    with pytest.raises(TypeError):
        Int8Wire(q, scale, "<i4")


def test_compressed_tags_malformed_frames_rejected():
    """Hostile/truncated compressed-array frames are parse errors:
    truncation mid-header, mid-payload, size-mismatch, non-float
    original dtype, and garbage dtype strings all raise ValueError."""
    from paddle_tpu.distributed.rpc import Bf16Wire, Int8Wire

    good_bf = bytes(_encode(
        Bf16Wire(np.arange(6, dtype="float32")), bytearray()))
    good_i8 = bytes(_encode(
        Int8Wire(np.arange(6, dtype=np.int8), 0.5), bytearray()))
    for good in (good_bf, good_i8):
        for cut in (1, 5, len(good) - 3):
            with pytest.raises(ValueError, match="truncated"):
                _Reader(good[:cut]).decode()
    # nbytes disagreeing with shape: refused before any frombuffer
    for tag in (b"h", b"q"):
        bad = bytearray()
        bad += tag + struct.pack(">I", 3) + b"<f4" + bytes([1])
        bad += struct.pack(">q", 4)  # shape (4,)
        bad += struct.pack(">Q", 2) + b"\x00" * 16
        with pytest.raises(ValueError, match="size mismatch"):
            _Reader(bytes(bad)).decode()
    # original dtype must be float: an int target is refused
    bad = bytearray()
    bad += b"h" + struct.pack(">I", 3) + b"<i4" + bytes([1])
    bad += struct.pack(">q", 2) + struct.pack(">Q", 4) + b"\x00" * 4
    with pytest.raises(ValueError, match="refuses dtype"):
        _Reader(bytes(bad)).decode()
    # garbage dtype string is a parse error, not a TypeError escape
    bad = bytearray()
    bad += b"q" + struct.pack(">I", 3) + b"zz9" + bytes([1])
    bad += struct.pack(">q", 2) + struct.pack(">Q", 2)
    bad += struct.pack(">d", 1.0) + b"\x00\x00"
    with pytest.raises(ValueError):
        _Reader(bytes(bad)).decode()


def test_sparse_rows_bf16_wire_roundtrip_live_pserver():
    """Sparse bf16 wire (PR 5's documented f32-only gap, closed):
    Bf16Wire-wrapped ROW VALUES ride the versioned `h` tag and arrive at
    the pserver as plain f32 with bf16 rounding — ids stay exact, the
    service never sees a wire dtype, and the applied update equals the
    bf16-rounded rows bit for bit."""
    from paddle_tpu.distributed.ps_server import ParameterServer
    from paddle_tpu.distributed.rpc import Bf16Wire

    tbl = np.zeros((8, 4), np.float32)
    ps = ParameterServer(
        {}, {}, num_trainers=1, sync_mode=False,
        sparse_tables={"t.shard0": {
            "tbl": tbl, "lr": 1.0, "opt": {"type": "sgd", "attrs": {}}}})
    srv = VarServer("127.0.0.1:0", ps).start()
    try:
        cli = RPCClient(srv.endpoint, timeout=5, retries=2)
        rows = (np.random.RandomState(0).rand(3, 4).astype("float32")
                - 0.5) * 4.0
        ids = np.array([1, 3, 5], np.int64)
        r = cli.call("send_sparse", table="t.shard0", ids=ids,
                     rows=Bf16Wire(rows), trainer_id=0)
        assert r["ok"] is True
        import ml_dtypes

        want = -(rows.astype(ml_dtypes.bfloat16).astype(np.float32))
        np.testing.assert_array_equal(tbl[ids], want)  # lr=1.0 sgd
        untouched = [i for i in range(8) if i not in ids]
        assert np.all(tbl[untouched] == 0.0)
        cli.close()
    finally:
        srv.shutdown()
        rpc.RPCClient.reset_all()


def test_sparse_sync_send_records_keep_compressed_rows():
    """The send_sparse lowering under FLAGS_comm_wire_dtype=bfloat16:
    the sync-mode fenced-replay record stores the already-WRAPPED rows
    (compressed form), so a pserver restart re-ships byte-identical
    chunks; the server's queued pending chunk holds the decoded
    (bf16-rounded) f32 rows with exact ids; and comm_bytes_saved counts
    the cut."""
    import paddle_tpu as fluid
    from paddle_tpu import framework
    from paddle_tpu.distributed.ps_server import ParameterServer
    from paddle_tpu.distributed.rpc import Bf16Wire
    from paddle_tpu.ops import dist_ops

    tbl = np.zeros((8, 4), np.float32)
    ps = ParameterServer(
        {}, {}, num_trainers=1, sync_mode=True,
        sparse_tables={"t.shard0": {
            "tbl": tbl, "lr": 1.0, "opt": {"type": "sgd", "attrs": {}}}})
    srv = VarServer("127.0.0.1:0", ps).start()
    ep = srv.endpoint
    try:
        prog = fluid.Program()
        b = prog.global_block()
        b.create_var(name="ids", shape=[3, 1], dtype="int64")
        b.create_var(name="g", shape=[3, 4], dtype="float32")
        b.create_var(name="tok", shape=[1])
        op = framework.Operator(
            b, "send_sparse", None, None,
            {"epmap": [ep], "table_names": ["t.shard0"], "trainer_id": 0,
             "scale": 1.0, "sync_mode": True, "wire_dtype": "bfloat16",
             "op_role": "rpc"})
        op.inputs = {"Ids": ["ids"], "Grad": ["g"]}
        op.outputs = {"Out": ["tok"]}
        b.ops.append(op)
        dist_ops.reset_fences()
        rpc.reset_comm_stats()
        rows = (np.random.RandomState(1).rand(3, 4).astype("float32")
                - 0.5) * 4.0
        ids = np.array([[1], [3], [5]], np.int64)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(prog, feed={"ids": ids, "g": rows}, fetch_list=[])
        # the replay record holds the WRAPPED (compressed) rows
        kw = dist_ops._fences[ep]["sparse"]["t.shard0"]
        assert isinstance(kw["rows"], Bf16Wire)
        np.testing.assert_array_equal(kw["ids"], ids.reshape(-1))
        # re-encoding the record reproduces the shipped bytes exactly
        first = bytes(_encode(kw["rows"], bytearray()))
        again = bytes(_encode(kw["rows"], bytearray()))
        assert first == again
        # server queued the DECODED rounded rows under the step token
        import ml_dtypes

        (qids, qrows), = [v for (k, _t), v in ps._pending_sparse.items()
                          if k == 0]
        np.testing.assert_array_equal(qids, ids.reshape(-1))
        np.testing.assert_array_equal(
            qrows, rows.astype(ml_dtypes.bfloat16).astype(np.float32))
        assert rpc.get_comm_stats()["comm_bytes_saved"] == \
            rows.nbytes - 2 * rows.size
    finally:
        srv.shutdown()
        dist_ops.reset_fences()
        rpc.reset_comm_stats()
        rpc.RPCClient.reset_all()


def test_sparse_bf16_malformed_rows_frame_rejected():
    """A truncated/hostile bf16 rows payload inside a send_sparse frame
    is a parse error server-side: the connection drops, the server stays
    alive, and a well-formed sparse send still lands afterwards."""
    from paddle_tpu.distributed.ps_server import ParameterServer
    from paddle_tpu.distributed.rpc import Bf16Wire

    tbl = np.zeros((4, 2), np.float32)
    ps = ParameterServer(
        {}, {}, num_trainers=1, sync_mode=False,
        sparse_tables={"t.shard0": {
            "tbl": tbl, "lr": 1.0, "opt": {"type": "sgd", "attrs": {}}}})
    srv = VarServer("127.0.0.1:0", ps).start()
    try:
        host, port = srv.endpoint.rsplit(":", 1)
        good = bytes(_encode(
            ("send_sparse",
             {"table": "t.shard0", "ids": np.array([0], np.int64),
              "rows": Bf16Wire(np.ones((1, 2), np.float32)),
              "trainer_id": 0}, "req-1"), bytearray()))
        # truncate INSIDE the bf16 payload: the frame length lies, the
        # decoder sees a short `h` tag body and must refuse
        cut = good[:-1]
        s = socket.create_connection((host, int(port)), timeout=5)
        s.sendall(struct.pack(">Q", 1 + len(cut)) + bytes([PROTO_VERSION])
                  + cut)
        s.settimeout(5)
        assert s.recv(1) == b""  # dropped, not crashed
        s.close()
        assert np.all(tbl == 0.0)  # nothing was applied
        # the server still serves a well-formed sparse send
        cli = RPCClient(srv.endpoint, timeout=5, retries=2)
        r = cli.call("send_sparse", table="t.shard0",
                     ids=np.array([2], np.int64),
                     rows=Bf16Wire(np.ones((1, 2), np.float32)),
                     trainer_id=0)
        assert r["ok"] is True
        assert tbl[2, 0] == -1.0
        cli.close()
    finally:
        srv.shutdown()
        rpc.RPCClient.reset_all()


def test_scatter_gather_segments_match_bytearray_encoder():
    """Zero-copy framing invariant: joining the _SegWriter segments
    reproduces the copying encoder's byte stream exactly — for frames
    with large arrays (own memoryview segment), small arrays (inlined),
    compressed wrappers and nested containers."""
    from paddle_tpu.distributed.rpc import Bf16Wire, _SegWriter

    rng = np.random.RandomState(3)
    obj = {
        "big": rng.rand(4096).astype("float32"),  # own segment
        "small": np.arange(7, dtype="int64"),     # inlined
        "bf": Bf16Wire(rng.rand(2048).astype("float32")),
        "nest": [1, "two", {"k": np.float64(2.5)}, b"raw"],
    }
    segs = _encode(obj, _SegWriter()).segments()
    joined = b"".join(bytes(s) for s in segs)
    assert joined == bytes(_encode(obj, bytearray()))
    assert len(segs) > 1, "large payloads should ride as own segments"
    out = _Reader(joined).decode()
    np.testing.assert_array_equal(out["big"], obj["big"])


def test_scatter_gather_large_frame_over_live_socket():
    """A frame big enough to exercise sendmsg short-write resumption
    round-trips intact through the real transport."""
    srv, ep = _mk_server()
    try:
        cli = RPCClient(ep, timeout=30, retries=2)
        rng = np.random.RandomState(9)
        blocks = {"b%d" % i: rng.rand(1 << 16).astype("float32")
                  for i in range(8)}  # ~2 MiB total, 8 sg segments
        echoed = cli.call("echo", blocks=blocks)["blocks"]
        for k, v in blocks.items():
            np.testing.assert_array_equal(echoed[k], v)
        cli.close()
    finally:
        srv.shutdown()


def test_no_pickle_in_rpc_module():
    import inspect

    src = inspect.getsource(rpc)
    assert "pickle" not in src


def test_object_dtype_refused_both_directions():
    with pytest.raises(TypeError, match="cannot ship"):
        _encode(np.array([object()]), bytearray())
    # hand-craft an array frame claiming dtype '|O8'
    bad = bytearray()
    bad += b"A" + struct.pack(">I", 3) + b"|O8" + bytes([1])
    bad += struct.pack(">q", 1) + struct.pack(">I", 8) + b"\x00" * 8
    with pytest.raises((ValueError, TypeError)):
        _Reader(bytes(bad)).decode()


def test_unknown_tag_and_truncation_rejected():
    with pytest.raises(ValueError, match="unknown type tag"):
        _Reader(b"Z").decode()
    good = bytes(_encode({"a": 1}, bytearray()))
    with pytest.raises(ValueError, match="truncated"):
        _Reader(good[:-2]).decode()


def test_malformed_frame_does_not_kill_server():
    srv, ep = _mk_server()
    try:
        host, port = ep.rsplit(":", 1)
        # 1) garbage bytes with a plausible length prefix
        s = socket.create_connection((host, int(port)), timeout=5)
        s.sendall(struct.pack(">Q", 16) + b"\x01" + b"Z" * 15)
        # server must close our connection, not crash
        s.settimeout(5)
        assert s.recv(1) == b""
        s.close()
        # 2) absurd length prefix (memory bomb) — also just dropped
        s2 = socket.create_connection((host, int(port)), timeout=5)
        s2.sendall(struct.pack(">Q", 1 << 60))
        s2.settimeout(5)
        assert s2.recv(1) == b""
        s2.close()
        # 3) wrong protocol version
        s3 = socket.create_connection((host, int(port)), timeout=5)
        payload = bytes(_encode(("ping", {}, "r1"), bytearray()))
        s3.sendall(struct.pack(">Q", 1 + len(payload)) + bytes([99]) + payload)
        s3.settimeout(5)
        assert s3.recv(1) == b""
        s3.close()
        # a well-formed client still works afterwards
        cli = RPCClient(ep, timeout=5, retries=2)
        assert cli.call("ping")["ok"] is True
        cli.close()
    finally:
        srv.shutdown()


def test_client_server_verbs_with_arrays():
    srv, ep = _mk_server()
    try:
        cli = RPCClient(ep, timeout=5, retries=2)
        arr = np.random.RandomState(0).rand(4, 3).astype("float32")
        out = cli.call("echo", name="w", value=arr, trainer_id=1)
        np.testing.assert_array_equal(out["value"], arr)
        assert out["name"] == "w" and out["trainer_id"] == 1
        cli.close()
    finally:
        srv.shutdown()


def test_hmac_rejects_unkeyed_and_accepts_keyed(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_RPC_HMAC_KEY", "sekret")
    srv, ep = _mk_server()
    try:
        cli = RPCClient(ep, timeout=5, retries=2)
        assert cli.call("ping")["ok"] is True  # both sides keyed
        cli.close()
        # wrong-keyed peer: hand-craft a frame MACed with a different key
        # (the server and client share this process's env, so the forgery
        # must be built manually)
        import hashlib
        import hmac as hmac_mod

        payload = bytes(_encode(("ping", {}, "r9"), bytearray()))
        mac = hmac_mod.new(b"wrong", payload, hashlib.sha256).digest()
        frame = bytes([PROTO_VERSION]) + mac + payload
        host, port = ep.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=5)
        s.sendall(struct.pack(">Q", len(frame)) + frame)
        s.settimeout(5)
        assert s.recv(1) == b""
        s.close()
    finally:
        srv.shutdown()
        monkeypatch.delenv("PADDLE_TPU_RPC_HMAC_KEY", raising=False)


def test_trainer_checkpoint_notifies_pservers(tmp_path):
    """save_checkpoint(pserver_endpoints=...) makes every pserver snapshot
    its shard into the trainer's serial dir in the same call
    (checkpoint_notify_op.cc / _save_pserver_vars_by_notify analog)."""
    import os

    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.contrib.trainer import save_checkpoint
    from paddle_tpu.distributed.ps_server import ParameterServer

    ps = ParameterServer({}, {}, num_trainers=1, sync_mode=False,
                         checkpoint_dir=str(tmp_path / "unused"),
                         server_idx=0)
    srv = VarServer("127.0.0.1:0", ps).start()
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.framework.program_guard(main, startup):
            x = layers.data("x", shape=[4])
            layers.fc(x, 2)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            ckdir = str(tmp_path / "ck")
            serial = save_checkpoint(
                exe, ckdir, main, trainer_args={"step_id": 1},
                scope=scope, pserver_endpoints=[srv.endpoint])
        serial_dir = os.path.join(ckdir, "checkpoint_%d" % serial)
        assert os.path.exists(os.path.join(serial_dir, "pserver_0.ckpt")), \
            os.listdir(serial_dir)
    finally:
        srv.shutdown()
        RPCClient.reset_all()


def test_checkpoint_notify_op_in_program(tmp_path):
    """The in-program checkpoint_notify op (checkpoint_notify_op.cc):
    running a program containing it makes the pserver snapshot."""
    import os

    import paddle_tpu as fluid
    from paddle_tpu.distributed.ps_server import ParameterServer

    ps = ParameterServer({}, {}, num_trainers=1, sync_mode=False,
                         server_idx=0)
    srv = VarServer("127.0.0.1:0", ps).start()
    try:
        target = str(tmp_path / "snap")
        main = fluid.Program()
        blk = main.global_block()
        tok = blk.create_var(name="ck_tok", dtype="int32", shape=[])
        blk.append_op("checkpoint_notify", inputs={},
                      outputs={"Out": ["ck_tok"]},
                      attrs={"epmap": [srv.endpoint], "dir": target})
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(main, feed={}, fetch_list=[tok])
        assert os.path.exists(os.path.join(target, "pserver_0.ckpt"))
    finally:
        srv.shutdown()
        RPCClient.reset_all()


@pytest.fixture(params=["python", "native"])
def any_server(request):
    """Both transports (Python socketserver and the C++ frame server)
    behind the same wire protocol."""
    from paddle_tpu.distributed.rpc import NativeVarServer

    def mk(service):
        if request.param == "native":
            try:
                return NativeVarServer("127.0.0.1:0", service).start()
            except RuntimeError:
                pytest.skip("native lib unavailable")
        return VarServer("127.0.0.1:0", service).start()

    return mk


def test_both_transports_serve_verbs(any_server):
    srv = any_server(_EchoService())
    try:
        cli = RPCClient(srv.endpoint, timeout=5, retries=2)
        arr = np.random.RandomState(1).rand(3, 5).astype("float32")
        out = cli.call("echo", name="p", value=arr, trainer_id=2)
        np.testing.assert_array_equal(out["value"], arr)
        assert out["trainer_id"] == 2
        assert cli.call("ping")["ok"] is True
        cli.close()
    finally:
        srv.shutdown()
        RPCClient.reset_all()


def test_native_transport_drops_malformed_and_survives():
    """Hostile bytes are rejected in C++ (connection dropped, nothing
    reaches Python); well-formed clients keep working."""
    from paddle_tpu.distributed.rpc import NativeVarServer

    try:
        srv = NativeVarServer("127.0.0.1:0", _EchoService()).start()
    except RuntimeError:
        pytest.skip("native lib unavailable")
    try:
        host, port = srv.endpoint.rsplit(":", 1)
        # garbage frame
        s = socket.create_connection((host, int(port)), timeout=5)
        s.sendall(struct.pack(">Q", 16) + b"\x01" + b"Z" * 15)
        # the payload reaches a Python worker and is dropped there; the
        # CONNECTION survives C++ framing but gets no reply — now send a
        # wrong version byte, which C++ kills outright
        s2 = socket.create_connection((host, int(port)), timeout=5)
        payload = bytes(_encode(("ping", {}, "n1"), bytearray()))
        s2.sendall(struct.pack(">Q", 1 + len(payload)) + bytes([99]) + payload)
        s2.settimeout(5)
        assert s2.recv(1) == b""
        s2.close()
        # absurd length prefix: killed in C++
        s3 = socket.create_connection((host, int(port)), timeout=5)
        s3.sendall(struct.pack(">Q", 1 << 60))
        s3.settimeout(5)
        assert s3.recv(1) == b""
        s3.close()
        s.close()
        cli = RPCClient(srv.endpoint, timeout=5, retries=2)
        assert cli.call("ping")["ok"] is True
        cli.close()
    finally:
        srv.shutdown()
        RPCClient.reset_all()


def test_native_transport_hmac(monkeypatch):
    """C++-side HMAC: keyed server accepts the keyed client and kills a
    forged-MAC frame without waking Python."""
    from paddle_tpu.distributed.rpc import NativeVarServer

    monkeypatch.setenv("PADDLE_TPU_RPC_HMAC_KEY", "sekret")
    try:
        srv = NativeVarServer("127.0.0.1:0", _EchoService()).start()
    except RuntimeError:
        pytest.skip("native lib unavailable")
    try:
        cli = RPCClient(srv.endpoint, timeout=5, retries=2)
        assert cli.call("ping")["ok"] is True
        cli.close()
        import hashlib
        import hmac as hmac_mod

        payload = bytes(_encode(("ping", {}, "n2"), bytearray()))
        mac = hmac_mod.new(b"wrong", payload, hashlib.sha256).digest()
        frame = bytes([PROTO_VERSION]) + mac + payload
        host, port = srv.endpoint.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=5)
        s.sendall(struct.pack(">Q", len(frame)) + frame)
        s.settimeout(5)
        assert s.recv(1) == b""
        s.close()
    finally:
        srv.shutdown()
        RPCClient.reset_all()


def test_decoder_oversized_inner_lengths_rejected():
    """Length fields INSIDE the payload claiming more bytes than the
    frame holds must raise a parse error, never read past the buffer or
    allocate the claimed size."""
    # str claiming 4 GiB
    bad = b"S" + struct.pack(">I", 0xFFFFFFFF) + b"abc"
    with pytest.raises(ValueError, match="truncated"):
        _Reader(bad).decode()
    # bytes claiming far more than present
    bad = b"B" + struct.pack(">I", 1 << 30) + b"x"
    with pytest.raises(ValueError, match="truncated"):
        _Reader(bad).decode()
    # list claiming 2**32-1 elements backed by nothing
    bad = b"L" + struct.pack(">I", 0xFFFFFFFF)
    with pytest.raises(ValueError, match="truncated"):
        _Reader(bad).decode()
    # array header promising 255 dims, then EOF
    bad = b"A" + struct.pack(">I", 3) + b"<f4" + bytes([255])
    with pytest.raises(ValueError, match="truncated"):
        _Reader(bad).decode()


def test_decoder_rejects_non_str_dict_keys():
    bad = b"M" + struct.pack(">I", 1) + b"I" + struct.pack(">q", 1) + b"N"
    with pytest.raises(ValueError, match="dict key"):
        _Reader(bad).decode()


def test_decoder_array_size_mismatch_rejected():
    """An array frame whose nbytes field disagrees with shape*itemsize is
    refused (a lying peer can't make frombuffer mis-slice)."""
    ds = b"<f4"
    bad = bytearray()
    bad += b"A" + struct.pack(">I", len(ds)) + ds + bytes([1])
    bad += struct.pack(">q", 2)  # shape (2,) => expect 8 bytes
    bad += struct.pack(">Q", 4) + b"\x00" * 4  # claims (and ships) 4
    with pytest.raises(ValueError, match="size mismatch"):
        _Reader(bytes(bad)).decode()


def test_partial_frame_then_close_leaves_server_alive():
    """A peer that promises a frame and dies mid-payload (the truncation
    chaos case): the server's reader sees EOF, drops the connection, and
    keeps serving well-formed clients — it must never hang waiting."""
    srv, ep = _mk_server()
    try:
        host, port = ep.rsplit(":", 1)
        payload = bytes(_encode(("ping", {}, "trunc1"), bytearray()))
        frame = bytes([PROTO_VERSION]) + payload
        s = socket.create_connection((host, int(port)), timeout=5)
        s.sendall(struct.pack(">Q", len(frame)) + frame[: len(frame) // 2])
        s.close()  # die mid-frame
        # zero-length frame is also rejected (length must be >= 1)
        s2 = socket.create_connection((host, int(port)), timeout=5)
        s2.sendall(struct.pack(">Q", 0))
        s2.settimeout(5)
        assert s2.recv(1) == b""
        s2.close()
        cli = RPCClient(ep, timeout=5, retries=2)
        assert cli.call("ping")["ok"] is True
        cli.close()
    finally:
        srv.shutdown()


def test_client_truncated_reply_raises_not_hangs():
    """A 'server' that replies with half a frame then closes: the client
    must surface a connection/parse error promptly — with retries
    exhausted it raises instead of hanging or trusting the partial."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    ep = "127.0.0.1:%d" % lsock.getsockname()[1]

    def evil_server():
        for _ in range(3):  # one per client round-trip attempt
            try:
                conn, _ = lsock.accept()
            except OSError:
                return
            try:
                conn.settimeout(5)
                _recv_msg(conn)  # read the request fully
                reply = bytes(_encode(("__reply__", "x", {"ok": True}),
                                      bytearray()))
                frame = bytes([PROTO_VERSION]) + reply
                conn.sendall(struct.pack(">Q", len(frame))
                             + frame[: len(frame) // 2])
            except (OSError, ValueError):
                pass
            finally:
                conn.close()

    t = threading.Thread(target=evil_server, daemon=True)
    t.start()
    try:
        cli = RPCClient(ep, timeout=5, retries=2, retry_wait=0.05)
        with pytest.raises((ConnectionError, OSError)):
            cli.call("ping")
        cli.close()
    finally:
        lsock.close()
        t.join(timeout=5)


def test_wire_decoder_fuzz_never_crashes():
    """Property check: random byte soup either decodes to a value or
    raises ValueError/UnicodeDecodeError — never any other exception and
    never code execution (the closed-type-system guarantee)."""
    import random

    rnd = random.Random(1234)
    tags = b"NTFIDSBALUMZ\x00\xff"
    for trial in range(300):
        n = rnd.randrange(0, 64)
        buf = bytes(rnd.choice(tags) if rnd.random() < 0.3
                    else rnd.randrange(256) for _ in range(n))
        try:
            _Reader(buf).decode()
        except (ValueError, UnicodeDecodeError, OverflowError):
            pass


def test_pserver_adam_beta_pows_advance_on_rowless_rounds():
    """Code-review r5: a sync round in which a shard receives NO rows for
    an adam table must still advance that table's beta pows — the local
    adam op advances them every step regardless of touched rows, and a
    shard missed by one batch's id hashing must not fall out of parity."""
    import numpy as np

    from paddle_tpu.distributed.ps_server import ParameterServer

    ps = ParameterServer(
        {}, {}, num_trainers=1, sync_mode=True,
        sparse_tables={"t.shard0": {
            "tbl": np.zeros((4, 2), np.float32), "lr": 0.1,
            "opt": {"type": "adam",
                    "attrs": {"beta1": 0.9, "beta2": 0.999}},
        }})
    ps._h_send_sparse("t.shard0", np.array([1]), np.ones((1, 2), np.float32))
    with ps._cv:
        ps._run_round()  # round with rows
    info = ps.sparse_tables["t.shard0"]
    b1p_1, b2p_1 = info["beta1_pow"], info["beta2_pow"]
    assert abs(b1p_1 - 0.9 ** 2) < 1e-12  # used 0.9, then advanced
    with ps._cv:
        ps._run_round()  # ROWLESS round: pows must still advance
    assert abs(info["beta1_pow"] - b1p_1 * 0.9) < 1e-12
    assert abs(info["beta2_pow"] - b2p_1 * 0.999) < 1e-12


def test_pserver_async_rowless_tables_advance_on_lr_trigger():
    """ADVICE r5: in ASYNC mode a sparse table that receives no rows must
    still advance its slot state — caught up once per lr-trigger send
    (the per-step marker).  Touched tables keep the per-application
    lazy-adam rule and are NOT double-advanced by the trigger."""
    import numpy as np

    from paddle_tpu.distributed.ps_server import ParameterServer

    adam = {"type": "adam", "attrs": {"beta1": 0.9, "beta2": 0.999}}
    ps = ParameterServer(
        [None], {"g": 0}, num_trainers=1, sync_mode=False,
        sparse_tables={
            "touched": {"tbl": np.zeros((4, 2), np.float32), "lr": 0.1,
                        "opt": dict(adam)},
            "idle": {"tbl": np.zeros((4, 2), np.float32), "lr": 0.1,
                     "opt": dict(adam)},
            "idle_m": {"tbl": np.ones((4, 2), np.float32), "lr": 0.1,
                       "opt": {"type": "momentum", "attrs": {"mu": 0.5}}},
        })
    ps._apply_shard = lambda idx, feed: None
    ps.sparse_tables["idle_m"]["velocity"] = np.ones((4, 2), np.float32)

    # step 1: rows for "touched" only, then the dense lr-trigger send
    ps._h_send_sparse("touched", np.array([1]),
                      np.ones((1, 2), np.float32))
    ps._h_send("g", np.zeros((1,), np.float32))
    t, i = ps.sparse_tables["touched"], ps.sparse_tables["idle"]
    assert abs(t["beta1_pow"] - 0.9 ** 2) < 1e-12  # one application
    assert abs(i["beta1_pow"] - 0.9 ** 2) < 1e-12  # trigger catch-up
    np.testing.assert_allclose(ps.sparse_tables["idle_m"]["velocity"],
                               0.5 * np.ones((4, 2)))  # decayed once

    # step 2: NO sparse rows at all; the trigger advances everything once
    ps._h_send("g", np.zeros((1,), np.float32))
    assert abs(t["beta1_pow"] - 0.9 ** 3) < 1e-12
    assert abs(i["beta1_pow"] - 0.9 ** 3) < 1e-12
    np.testing.assert_allclose(ps.sparse_tables["idle_m"]["velocity"],
                               0.25 * np.ones((4, 2)))


def test_pserver_momentum_rowless_round_decays_velocity():
    """Code-review r5: a sync round where a momentum table receives NO
    rows must still decay every row's velocity (the densified
    SparseMomentumFunctor covers all rows each step) — and must not
    crash on the empty-rows reshape."""
    import numpy as np

    from paddle_tpu.distributed.ps_server import ParameterServer

    tbl = np.ones((4, 2), np.float32)
    ps = ParameterServer(
        {}, {}, num_trainers=1, sync_mode=True,
        sparse_tables={"m.shard0": {
            "tbl": tbl, "lr": 0.1,
            "opt": {"type": "momentum", "attrs": {"mu": 0.5}},
        }})
    ps._h_send_sparse("m.shard0", np.array([0]), np.ones((1, 2), np.float32))
    with ps._cv:
        ps._run_round()  # round WITH rows: v[0] = 1, others 0
    info = ps.sparse_tables["m.shard0"]
    v1 = info["velocity"].copy()
    assert v1[0, 0] == 1.0 and v1[1, 0] == 0.0
    with ps._cv:
        ps._run_round()  # ROWLESS round: v *= mu, p -= lr*v
    np.testing.assert_allclose(info["velocity"], v1 * 0.5)

    # velocity must survive a checkpoint roundtrip (snapshot key filter)
    snap = ps._snapshot()
    assert "velocity" in snap["sparse"]["m.shard0"], snap["sparse"].keys()
