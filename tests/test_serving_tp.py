"""GSPMD tensor-parallel serving pools (docs/SERVING.md §"Tensor-
parallel pools"): the partition-rule registry's resolution contracts
(precedence, scalar/rank/divisibility guards, logged replicate-by-
default) and the sharded engine's preservation of BOTH load-bearing
PR 9 contracts on a 2-virtual-device CPU mesh — every request's tokens
bit-identical to its solo run under churn, and zero retraces across
occupancy changes — plus the pool-bytes-per-device drop the sharding
exists for."""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu.models import gpt2
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.partition_rules import (
    P,
    PartitionRules,
    partition_rules_for,
    registered_families,
)
from paddle_tpu.serving import Request, ServingEngine

needs_two_devices = pytest.mark.skipif(
    len(jax.devices()) < 2,
    reason="needs XLA_FLAGS=--xla_force_host_platform_device_count>=2")


# ---------------------------------------------------------------------------
# the rule registry
# ---------------------------------------------------------------------------
def test_rule_precedence_first_match_wins():
    """Rules resolve in ORDER: an earlier narrow rule shadows a later
    broad one — the family tables lean on this (pos_emb.w must hit its
    replicate rule before the emb.w vocab rule would re.search-match
    the 'emb.w' substring)."""
    r = PartitionRules([
        (r"special\.w", P("mp")),
        (r"\.w", P(None, "mp")),
    ])
    assert r.spec_for("special.w_0", (8, 8)) == P("mp")
    assert r.spec_for("plain.w_0", (8, 8)) == P(None, "mp")
    # the gpt2 family table's instance of the same contract
    fam = partition_rules_for("gpt2", mp_axis="mp")
    assert fam.spec_for("pos_emb.w_0", (32, 16)) == P()
    assert fam.spec_for("emb.w_0", (64, 16)) == P("mp", None)


def test_gpt2_family_table_covers_the_serving_persistables():
    r = partition_rules_for("gpt2", mp_axis="mp")
    assert r.spec_for("mha_q.w_3", (16, 16)) == P(None, "mp")
    assert r.spec_for("mha_o.w_1", (16, 16)) == P("mp", None)
    assert r.spec_for("ffn_gate.w_0", (16, 44)) == P(None, "mp")
    assert r.spec_for("ffn_out.w_0", (64, 16)) == P("mp", None)
    # the slot-pool persistables shard their HEADS axis
    assert (r.spec_for("gpt2_kcache_0", (4, 4, 24, 8))
            == P(None, "mp", None, None))
    assert (r.spec_for("gpt2_vcache_11", (4, 4, 24, 8))
            == P(None, "mp", None, None))
    assert "gpt2" in registered_families()
    with pytest.raises(KeyError, match="gpt2"):
        partition_rules_for("no_such_family")


def test_unmatched_name_replicates_and_logs_once():
    """Replicate-by-default is LOUD: the fallback lands in
    replicated_log exactly once per name (steady-state re-resolution
    must not grow it), and matching names never log."""
    r = PartitionRules([(r"\.w$", P("mp"))])
    assert r.spec_for("layer_norm_0.b", (8,)) == P()
    assert r.spec_for("layer_norm_0.b", (8,)) == P()
    assert r.replicated_log == [("layer_norm_0.b", "no rule matched")]
    assert r.spec_for("dense.w", (8,)) == P("mp")
    assert len(r.replicated_log) == 1


def test_scalar_and_rank_guards_replicate():
    r = PartitionRules([(r"counter|step|mha_q\.w", P("mp"))])
    # scalars/1-element values never shard — and never log (beta_pows,
    # counters are not worth surfacing)
    assert r.spec_for("counter", ()) == P()
    assert r.spec_for("step", (1,)) == P()
    assert r.replicated_log == []
    # a rank-1 value under a rank-1 spec shards fine...
    assert r.spec_for("mha_q.w_bias", (4,)) == P("mp")
    # ...but a matched rule whose spec OUTRANKS the value replicates
    # with a log
    r2 = PartitionRules([(r"x", P("a", "b"))])
    assert r2.spec_for("x", (6,)) == P()
    assert r2.replicated_log and "rank" in r2.replicated_log[0][1]


@needs_two_devices
def test_divisibility_guard_replicates_on_mesh():
    mesh = make_mesh({"mp": 2}, devices=jax.devices()[:2])
    r = PartitionRules([(r"cache", P(None, "mp", None, None))])
    ok = r.sharding_for(mesh, "cache_a", (4, 4, 24, 8))
    assert ok.spec == P(None, "mp", None, None)
    # 3 kv heads on a 2-way mesh: replicate, loudly
    bad = r.sharding_for(mesh, "cache_b", (4, 3, 24, 8))
    assert bad.spec == P()
    assert any(n == "cache_b" for n, _ in r.replicated_log)


# ---------------------------------------------------------------------------
# the sharded engine: both PR 9 contracts survive GSPMD
# ---------------------------------------------------------------------------
class TinyHP(gpt2.GPT2Config):
    vocab_size = 61
    n_ctx = 32
    d_model = 32
    n_layer = 2
    n_head = 4
    dropout = 0.0


def _churn_trace(vocab, seed=0):
    rng = np.random.RandomState(seed)
    reqs = []
    for i in range(8):
        sampled = i % 2 == 1
        reqs.append(Request(
            rid=i,
            prompt=rng.randint(1, vocab, int(rng.randint(2, 11))),
            max_new_tokens=int(rng.randint(3, 9)),
            temperature=0.8 + 0.1 * (i % 3) if sampled else 1.0,
            top_k=[0, 8, 16][i % 3] if sampled else 0,
            top_p=0.9 if sampled and i % 4 == 1 else 1.0,
            seed=1000 + i if sampled else None,
            arrival=float(i) * 0.9))
    return reqs


def _tp_engine(scope, seed=7):
    mesh = make_mesh({"mp": 2}, devices=jax.devices()[:2])
    _, lm_startup, _, _ = gpt2.gpt2_logits_program(TinyHP, seq_len=24)
    exe = fluid.Executor(fluid.CPUPlace())
    lm_startup.random_seed = seed
    exe.run(lm_startup)
    return exe, ServingEngine(exe, TinyHP, n_slots=4, width=4, t_max=24,
                              mesh=mesh)


@needs_two_devices
def test_tp_engine_churn_exactness_and_pool_bytes():
    """The tensor-parallel pool on a 2-virtual-device mp mesh: every
    request's tokens (greedy AND per-request-seeded sampled) are
    bit-identical to its solo run through the SAME sharded engine under
    admission churn, and the KV pool's per-device resident bytes drop
    to 50% of the pool (the acceptance bar is <= 60%)."""
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe, eng = _tp_engine(scope)
        reqs = _churn_trace(TinyHP.vocab_size)
        results, stats = eng.run(list(reqs))
        assert stats["finished"] == len(reqs) > eng.n_slots
        admits = sorted(results[r.rid]["admit_step"] for r in reqs)
        assert admits[-1] > admits[0]  # real churn happened
        for r in reqs:
            solo, _ = eng.run_solo(r)
            np.testing.assert_array_equal(
                results[r.rid]["tokens"], solo,
                err_msg="request %r sharded pooled != solo" % r.rid)
        pool = eng.kv_pool_bytes(scope)
        ratio = pool["max_device_bytes"] / pool["total_bytes"]
        assert ratio <= 0.6, pool
        # the heads-axis cache rule actually fired (not a fallback)
        assert not any("cache" in n for n, _ in
                       eng.partition_rules.replicated_log)


@needs_two_devices
def test_tp_engine_compiles_once_across_occupancy():
    """The no-retrace contract through the GSPMD path: after the warm
    run (cache startup + slot reset + step traced) every occupancy
    change — admission, eviction, reuse, drain — reuses the same
    sharded executables."""
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe, eng = _tp_engine(scope)
        warm = [Request(900, np.array([1, 2, 3]), 3, arrival=0.0),
                Request(901, np.array([4, 5]), 2, arrival=0.0)]
        eng.run(warm)
        baseline = exe.compile_count
        reqs = _churn_trace(TinyHP.vocab_size, seed=9)
        _, stats = eng.run(reqs)
        assert stats["finished"] == len(reqs)
        assert exe.compile_count == baseline, (
            "occupancy churn retraced the sharded serving step: %d -> %d"
            % (baseline, exe.compile_count))


@needs_two_devices
def test_tp_engine_pallas_qvec_under_shard_map():
    """FLAGS_use_pallas=1 on the mesh: the ragged step's attention
    rides flash_attention_qvec inside shard_map (each device runs the
    kernel on its own head slice; interpret mode on CPU, the same
    kernel Mosaic compiles on chip) and churn exactness holds."""
    from paddle_tpu import flags

    flags.set_flags({"use_pallas": True})
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe, eng = _tp_engine(scope, seed=5)
        reqs = _churn_trace(TinyHP.vocab_size, seed=3)[:6]
        results, stats = eng.run(list(reqs))
        assert stats["finished"] == len(reqs)
        base = exe.compile_count
        for r in reqs:
            solo, _ = eng.run_solo(r)
            np.testing.assert_array_equal(results[r.rid]["tokens"], solo)
        assert exe.compile_count == base


@pytest.mark.slow  # second pallas engine compile; rides ci.sh TP lane (-m "")
@needs_two_devices
def test_tp_engine_epilogue_kernels_dispatch_under_shard_map():
    """The matmul-epilogue kernels run shard_map-wrapped per-device
    inside the sharded serving step — the PR 14 limit (they used to
    operand-replicate, all-gathering the sharded weight) is closed.
    Attribution counters prove dispatch; churn exactness still holds."""
    from paddle_tpu import flags
    from paddle_tpu.ops import kernel_tuning

    flags.set_flags({"use_pallas": True})
    kernel_tuning.reset_attribution()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe, eng = _tp_engine(scope, seed=11)
        reqs = _churn_trace(TinyHP.vocab_size, seed=5)[:4]
        results, stats = eng.run(list(reqs))
        assert stats["finished"] == len(reqs)
        hits = kernel_tuning.attribution()["pallas_hits"]
        assert hits.get("matmul_epilogue", 0) > 0, hits
        for r in reqs[:2]:
            solo, _ = eng.run_solo(r)
            np.testing.assert_array_equal(results[r.rid]["tokens"], solo)
