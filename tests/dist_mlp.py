"""Distributed-training runner: one process = one role (trainer/pserver).

The model file of the reference's dist test harness (test_dist_base.py:34
TestDistRunnerBase + dist_mnist.py): test_dist_train.py spawns this script
as localhost subprocesses with the PADDLE_* env contract and compares
trainer losses against a local run.

Env contract (fluid_benchmark.py:63-100 analog):
  PADDLE_TRAINING_ROLE = TRAINER | PSERVER | LOCAL
  PADDLE_PSERVER_EPS   = "127.0.0.1:p1,127.0.0.1:p2"
  PADDLE_CURRENT_ENDPOINT (pserver role)
  PADDLE_TRAINERS, PADDLE_TRAINER_ID
  DIST_SYNC_MODE = 1|0, DIST_STEPS, DIST_BATCH
  DIST_MODE = pserver (default) | collective — collective lowers dense
    grad sync into the compiled step (c_allreduce over the dp mesh, no
    pserver round trip for dense params); multi-process when launched
    with PADDLE_TRAINER_ENDPOINTS (one device per process via
    jax.distributed), else a single-process CPU mesh of
    DIST_COLLECTIVE_DEVICES (default 2) virtual devices.  With
    DIST_MODEL=sparse the run is HYBRID: embedding rows still ride the
    pserver (PADDLE_PSERVER_EPS), dense grads ride the mesh.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

_COLLECTIVE = os.environ.get("DIST_MODE") == "collective"
_TRAINER_EPS = [e for e in os.environ.get(
    "PADDLE_TRAINER_ENDPOINTS", "").split(",") if e.strip()]


def _parse_resize(spec):
    """DIST_RESIZE="step:nranks[,step:nranks]" — deterministic elastic
    collective driver: at training step `step`, resize the virtual mesh
    to `nranks` (re-trace + token drain happen inside the executor)."""
    out = []
    for part in (spec or "").split(","):
        part = part.strip()
        if part:
            a, _, b = part.partition(":")
            out.append((int(a), int(b)))
    return sorted(out)


_RESIZE_PLAN = _parse_resize(os.environ.get("DIST_RESIZE"))
if _COLLECTIVE and os.environ.get("PADDLE_TRAINING_ROLE") != "PSERVER":
    # device topology must be pinned BEFORE jax loads: multi-process runs
    # put ONE device in each trainer process (the mesh spans processes);
    # a single process hosts the whole mesh as virtual CPU devices.
    # Elastic collective (--elastic / DIST_RESIZE) pins the MAX mesh the
    # job can grow to — resizes then only re-trace, never re-boot jax.
    _n_dev = (1 if len(_TRAINER_EPS) > 1
              else int(os.environ.get("DIST_COLLECTIVE_DEVICES", "2")))
    for _, _to in _RESIZE_PLAN:
        _n_dev = max(_n_dev, _to)
    _el = os.environ.get("DIST_COLLECTIVE_ELASTIC", "")
    if _el:
        _n_dev = max(_n_dev, int(_el.split(":")[1]))
    _flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
              if not f.startswith("--xla_force_host_platform_device_count")]
    _flags.append("--xla_force_host_platform_device_count=%d" % _n_dev)
    os.environ["XLA_FLAGS"] = " ".join(_flags)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers

SEED = 7


def build_sparse_model(distributed):
    """Distributed-lookup-table model (dist role passes distributed=True;
    LOCAL runs the plain lookup so parity compares the two paths).
    DIST_OPTIMIZER=adam_decay swaps in Adam + exponential lr decay with
    is_sparse=True, so the LOCAL reference runs the lazy SelectedRows
    adam branch — the exact rule the pserver replays per shard."""
    opt_kind = os.environ.get("DIST_OPTIMIZER", "sgd")
    lazy = opt_kind in ("adam_decay", "momentum")
    ids = layers.data("ids", shape=[1], dtype="int64")
    y = layers.data("y", shape=[1])
    emb = layers.embedding(
        ids, size=[20, 8], dtype="float32", is_sparse=lazy,
        is_distributed=distributed
    )
    emb = layers.reshape(emb, [-1, 8])
    pred = layers.fc(emb, size=1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    if opt_kind == "adam_decay":
        lr = layers.exponential_decay(0.05, decay_steps=2, decay_rate=0.9)
        fluid.optimizer.Adam(lr).minimize(loss)
    elif opt_kind == "momentum":
        fluid.optimizer.Momentum(0.05, momentum=0.9).minimize(loss)
    else:
        fluid.optimizer.SGD(0.1).minimize(loss)
    return loss


def gen_sparse_data(n=16):
    rng = np.random.RandomState(5)
    ids = rng.randint(0, 20, (n, 1)).astype("int64")
    if os.environ.get("DIST_SPARSE_IDS") == "even":
        # every id lands on pserver 0 (id % 2 == 0): shard 1 sees
        # ROWLESS rounds only — the adam beta-pow / momentum-decay
        # advance-on-empty path end to end
        ids = (ids // 2) * 2
    y = (ids.astype("float32") / 10.0) - 1.0
    return ids, y


def build_model():
    x = layers.data("x", shape=[4])
    y = layers.data("y", shape=[1])
    # DIST_HIDDEN widens the MLP so wire-compression A/Bs can measure a
    # payload-bound step (the default 8 is framing-bound); parity tests
    # keep the default
    hidden = int(os.environ.get("DIST_HIDDEN", "8"))
    h = layers.fc(x, size=hidden, act="relu")
    # per-param lr exercises the optimize-role `scale` helper op path
    pred = layers.fc(h, size=1, param_attr=fluid.ParamAttr(learning_rate=0.5))
    loss = layers.mean(layers.square_error_cost(pred, y))
    if os.environ.get("DIST_OPTIMIZER", "sgd") == "adam_decay":
        lr = layers.exponential_decay(0.05, decay_steps=2, decay_rate=0.9)
        opt = fluid.optimizer.Adam(lr)
    else:
        opt = fluid.optimizer.SGD(0.1)
    opt.minimize(loss)
    return loss


def gen_data(n=16):
    rng = np.random.RandomState(3)
    x = rng.rand(n, 4).astype("float32")
    w = np.array([[1.0], [-2.0], [3.0], [0.5]], dtype=np.float32)
    y = x @ w + 0.1 * rng.rand(n, 1).astype("float32")
    return x, y


def main():
    role = os.environ.get("PADDLE_TRAINING_ROLE", "LOCAL")
    eps = os.environ.get("PADDLE_PSERVER_EPS", "")
    trainers = int(os.environ.get("PADDLE_TRAINERS", "1"))
    trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    sync_mode = os.environ.get("DIST_SYNC_MODE", "1") == "1"
    steps = int(os.environ.get("DIST_STEPS", "4"))
    batch = int(os.environ.get("DIST_BATCH", "16"))

    main_prog = fluid.default_main_program()
    main_prog.random_seed = SEED
    fluid.default_startup_program().random_seed = SEED
    sparse = os.environ.get("DIST_MODEL") == "sparse"
    if sparse:
        loss = build_sparse_model(distributed=(role != "LOCAL"))
        x, y = gen_sparse_data()
        feed_x = "ids"
    else:
        loss = build_model()
        x, y = gen_data()
        feed_x = "x"

    exe = fluid.Executor(fluid.CPUPlace())

    if role == "LOCAL":
        exe.run(fluid.default_startup_program())
        losses = []
        for _ in range(steps):
            (lv,) = exe.run(
                feed={feed_x: x[:batch], "y": y[:batch]}, fetch_list=[loss]
            )
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        print("LOSSES " + json.dumps(losses))
        return

    collective = os.environ.get("DIST_MODE") == "collective"
    # collective mode: one logical trainer per mesh replica — processes
    # when launched multi-process (one device each), virtual CPU devices
    # when single-process
    nranks = (len(_TRAINER_EPS) if len(_TRAINER_EPS) > 1
              else int(os.environ.get("DIST_COLLECTIVE_DEVICES", "2")))

    config = fluid.DistributeTranspilerConfig()
    config.min_block_size = 4  # tiny model: force splitting across servers
    if collective:
        config.mode = "collective"
    t = fluid.DistributeTranspiler(config=config)
    t.transpile(
        trainer_id,
        program=main_prog,
        pservers=eps,
        trainers=nranks if collective else trainers,
        sync_mode=sync_mode,
    )

    if role == "PSERVER":
        cur = os.environ["PADDLE_CURRENT_ENDPOINT"]
        if os.environ.get("PADDLE_PSERVER_ELASTIC") == "1":
            # elastic-grown server at an endpoint OUTSIDE the base set:
            # boots EMPTY and acquires shards via journaled handoff
            # (migrate_in) — docs/FAULT_TOLERANCE.md "Live shard
            # migration"
            pserver_prog = t.get_elastic_pserver_program(cur)
        else:
            pserver_prog = t.get_pserver_program(cur)
        startup = t.get_startup_program(cur, pserver_prog)
        scope = fluid.global_scope()
        exe.run(startup, scope=scope)
        print("PSERVER READY", flush=True)
        exe.run(pserver_prog, scope=scope)  # blocks until trainers complete
        print("PSERVER DONE")
        return

    # TRAINER
    trainer_prog = t.get_trainer_program()
    if collective and len(_TRAINER_EPS) > 1:
        # mesh spans processes: rank 0's endpoint coordinates
        from paddle_tpu import distributed as _dist

        _dist.init_collective()
    exe.run(fluid.default_startup_program())
    # this PROCESS's shard of the global batch (collective single-process
    # runs feed the whole batch; the executor splits it over the mesh)
    if collective:
        nproc = max(1, len(_TRAINER_EPS))
        shard = batch // nproc
        slot = trainer_id
    else:
        shard = batch // trainers
        # elastic ranks: a policy-grown trainer gets an id >= the
        # transpile-time world (PADDLE_TRAINERS) — it reuses a data slot
        # mod the original shard count (the plan epoch re-scales grads
        # for the LIVE world, so the extra contribution is weighted
        # correctly)
        slot = trainer_id % trainers
    lo, hi = slot * shard, (slot + 1) * shard
    step_sleep = float(os.environ.get("DIST_STEP_SLEEP", "0"))
    # chaos hook (tests/test_fault_tolerance.py): SIGKILL this rank after
    # step N — a real mid-training process death, no cleanup, no complete.
    # DIST_CRASH_ONCE names a marker file: the crash fires only while the
    # marker is absent (created just before the kill), so a SUPERVISED
    # relaunch of the same rank runs clean instead of crash-looping —
    # the deterministic "die once, rejoin" fence for the elastic tests.
    crash_rank = int(os.environ.get("DIST_CRASH_RANK", "-1"))
    crash_after = int(os.environ.get("DIST_CRASH_AFTER_STEP", "-1"))
    crash_once = os.environ.get("DIST_CRASH_ONCE", "")
    if crash_once and os.path.exists(crash_once):
        crash_rank = -1  # this incarnation already died once
    # elastic collective: DIST_RESIZE pins step-indexed mesh sizes;
    # DIST_COLLECTIVE_SCHEDULE (launch --elastic-schedule passthrough)
    # is the wall-clock +N/-N form, applied at step boundaries.  The
    # resize just rewrites program._collective["nranks"]: the executor
    # re-traces over the new dp mesh, drains the ordered-io tokens
    # across the topology switch, and the mesh split re-shards the same
    # global batch — the mean-gradient trajectory is split-invariant.
    import time as _time

    resize_plan = list(_RESIZE_PLAN) if collective else []
    tsched, cur_n = [], nranks
    if collective and os.environ.get("DIST_COLLECTIVE_SCHEDULE"):
        lo, hi = (int(x) for x in
                  os.environ["DIST_COLLECTIVE_ELASTIC"].split(":"))
        for part in os.environ["DIST_COLLECTIVE_SCHEDULE"].split(","):
            part = part.strip()
            if part:
                t_s, _, d = part.partition(":")
                tsched.append((float(t_s), int(d)))
        tsched.sort()
        cur_n = min(max(cur_n, lo), hi)
    t0_wall = _time.monotonic()

    def maybe_resize(step_i):
        nonlocal cur_n
        new_n = cur_n
        while resize_plan and step_i >= resize_plan[0][0]:
            new_n = resize_plan.pop(0)[1]
        while tsched and _time.monotonic() - t0_wall >= tsched[0][0]:
            new_n += tsched.pop(0)[1]
            lo, hi = (int(x) for x in
                      os.environ["DIST_COLLECTIVE_ELASTIC"].split(":"))
            new_n = min(max(new_n, lo), hi)
        if new_n != cur_n:
            cur_n = new_n
            trainer_prog._collective["nranks"] = cur_n
            print("COLLECTIVE RESIZE step=%d nranks=%d" % (step_i, cur_n),
                  flush=True)

    losses = []
    for i in range(steps):
        if collective and (resize_plan or tsched):
            maybe_resize(i)
        (lv,) = exe.run(
            program=trainer_prog,
            feed={feed_x: x[lo:hi], "y": y[lo:hi]},
            fetch_list=[loss],
        )
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
        print("STEP %d" % i, flush=True)
        if trainer_id == crash_rank and i == crash_after:
            import signal

            if crash_once:
                with open(crash_once, "w") as f:
                    f.write("crashed\n")
            print("CRASHING trainer %d after step %d" % (trainer_id, i),
                  flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
        if step_sleep:
            import time

            time.sleep(step_sleep)
    # comm evidence: client-side round trips / bytes plus feed-upload
    # time — deterministic counters bench.py and the smoke tests read
    from paddle_tpu.distributed import rpc as _rpc

    counters = _rpc.get_comm_stats()
    counters["host_feed_ms"] = round(exe.host_feed_ms, 3)
    # wire-compression evidence: bytes on the wire per sync step (plan
    # property at fixed step count — the A/B the bf16 wire is judged on)
    counters["bytes_per_step"] = round(
        counters["comm_bytes_sent"] / max(1, steps), 1)
    if sparse and os.environ.get("DIST_DUMP_TABLE") == "1":
        # fetch EVERY row of each distributed table back from the
        # pservers (global row g lives on server g%N at local index
        # g//N) and print it exactly — the async chaos E2E asserts a
        # killed-and-restored run's table is BIT-IDENTICAL to an
        # unkilled run's (journal replay + fenced resend lose nothing)
        from paddle_tpu.distributed.rpc import RPCClient
        from paddle_tpu.ops import dist_ops

        ep_list = [e.strip() for e in eps.split(",") if e.strip()]
        # live pserver migration: shard s may have MOVED off the base
        # endpoint — route each read through the CURRENT plan (the
        # base endpoint may even be retired and gone)
        plan_st = dist_ops._plans.get(getattr(t, "plan_gid", None))
        dump = {}
        for w, info in sorted(t.sparse_tables.items()):
            n_rows = 20  # build_sparse_model's table size
            tbl = np.zeros((n_rows, info["emb_dim"]), np.float32)
            for s in range(len(ep_list)):
                ep = dist_ops._sparse_route(plan_st, s, ep_list)
                gids = np.arange(s, n_rows, len(ep_list), dtype=np.int64)
                rows = np.asarray(RPCClient.get(ep).prefetch(
                    info["shards"][s], gids // len(ep_list),
                    trainer_id=trainer_id))
                tbl[gids] = rows
            dump[w] = tbl.tolist()
        print("TABLE " + json.dumps(dump))
    exe.close()  # SendComplete to pservers
    print("COUNTERS " + json.dumps(counters))
    print("LOSSES " + json.dumps(losses))


if __name__ == "__main__":
    main()
