"""GSPMD tensor-parallel TRAINING (docs/PERFORMANCE.md §"Sharded
training"): the train-lifted partition-rule registry drives
``Executor._run_spmd`` over a dp x mp mesh with NO model edits —
grads and Adam state shard like their param (ZeRO-style), the dp axis
keeps the collective backend's allreduce-mean semantics, and the
whole thing composes with remat, bf16 AMP, and the pallas epilogue
kernels.  Exactness contract: stamped mp=1 is BIT-identical to the
unstamped program; mp=2 on the virtual-device CI mesh holds rtol
parity; optimizer state is provably sharded (per-device bytes)."""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
import paddle_tpu.framework as fw
from paddle_tpu import flags
from paddle_tpu.core import scope as scope_mod
from paddle_tpu.models import gpt2
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.partition_rules import P, train_partition_rules_for

needs_four_devices = pytest.mark.skipif(
    len(jax.devices()) < 4,
    reason="needs XLA_FLAGS=--xla_force_host_platform_device_count>=4")


class TinyHP(gpt2.GPT2Config):
    vocab_size = 64
    n_ctx = 16
    d_model = 32
    n_layer = 2
    n_head = 4
    d_inner = 64
    dropout = 0.0  # determinism: the parity runs must share arithmetic
    tie_embeddings = False


def _fresh():
    fw.switch_main_program(fluid.Program())
    fw.switch_startup_program(fluid.Program())
    scope_mod._switch_scope(scope_mod.Scope())


def _train(mesh, steps=4, use_pallas=False, use_bf16=False, hp=TinyHP,
           extra_flags=None, batch=4, seq=8):
    """Fresh scope+programs, `steps` Adam steps on the fake-LM batch;
    returns (losses, scope, main_program, executor)."""
    _fresh()
    names = ["use_pallas", "kernel_autotune"] + sorted(extra_flags or ())
    old = {k: flags.get_flag(k) for k in names}
    flags.set_flags(dict({"use_pallas": use_pallas,
                          "kernel_autotune": False}, **(extra_flags or {})))
    try:
        main, startup, feeds, fetches = gpt2.gpt2_lm_program(
            hp, seq_len=seq, lr=3e-3, use_bf16=use_bf16, mesh=mesh)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = []
        for _ in range(steps):
            fb = gpt2.make_fake_lm_batch(batch, seq, hp, seed=0)
            out = exe.run(main, feed=fb, fetch_list=fetches)
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
        return losses, scope_mod.global_scope(), main, exe
    finally:
        flags.set_flags(old)


def _spec_of(scope, name):
    v = scope.find_var(name)
    assert v is not None, name
    return tuple(v.sharding.spec)


_BASE_CACHE = {}


def _base_losses(steps=3):
    """The unsharded reference trajectory, computed once per process —
    every parity test diffs against the same run (tier-1's time budget:
    one baseline compile, not one per test)."""
    if steps not in _BASE_CACHE:
        _BASE_CACHE[steps] = _train(None, steps=steps)[0]
    return _BASE_CACHE[steps]


# ---------------------------------------------------------------------------
# exactness
# ---------------------------------------------------------------------------
@pytest.mark.slow  # two 3-step compiles; rides ci.sh spmd lane (-m "")
def test_mp1_stamped_bit_identical_to_unstamped():
    """A (dp=1, mp=1) stamp must change NOTHING: same jaxpr shapes, no
    collectives, bit-identical losses — the registry's guards replicate
    everything and the epilogue wrappers decline single-shard meshes."""
    base = _base_losses(steps=3)
    mesh = make_mesh({"dp": 1, "mp": 1}, devices=jax.devices()[:1])
    got, _, _, _ = _train(mesh, steps=3)
    assert got == base, (got, base)


@pytest.mark.slow  # sharded + baseline compiles; rides ci.sh spmd lane
@needs_four_devices
def test_mp2_rtol_parity():
    """Pure tensor parallelism (dp=1, mp=2): losses track the unsharded
    run to rtol 1e-5 (float reassociation across shards is the only
    permitted difference)."""
    got, _, _, _ = _train(make_mesh({"dp": 1, "mp": 2},
                                    devices=jax.devices()[:2]), steps=3)
    np.testing.assert_allclose(got, _base_losses(steps=3), rtol=1e-5)


@pytest.mark.slow  # one compile per mesh shape; rides ci.sh spmd lane (-m "")
@needs_four_devices
def test_mp2_rtol_parity_across_mesh_shapes():
    """The remaining mesh shapes — pure dp and the full dp x mp grid —
    hold the same rtol 1e-5 contract as the (1, 2) tier-1 leg."""
    base = _base_losses(steps=3)
    for dp, mp in ((2, 1), (2, 2)):
        got, _, _, _ = _train(make_mesh({"dp": dp, "mp": mp}), steps=3)
        np.testing.assert_allclose(got, base, rtol=1e-5,
                                   err_msg="dp=%d mp=%d" % (dp, mp))


@pytest.mark.slow  # interpret-mode kernels + second compile; ci.sh spmd lane
@needs_four_devices
def test_epilogue_kernels_dispatch_inside_sharded_step():
    """FLAGS_use_pallas on the dp2 x mp2 mesh: the shard_map-wrapped
    epilogue kernels DISPATCH (kernel-attribution counters move — no
    operand replication fallback) and parity holds vs the dense mesh
    run."""
    from paddle_tpu.ops import kernel_tuning

    dense, _, _, _ = _train(make_mesh({"dp": 2, "mp": 2}))
    kernel_tuning.reset_attribution()
    got, _, _, _ = _train(make_mesh({"dp": 2, "mp": 2}), use_pallas=True)
    hits = kernel_tuning.attribution()["pallas_hits"]
    assert hits.get("matmul_epilogue", 0) > 0, hits
    assert hits.get("xent", 0) > 0, hits
    np.testing.assert_allclose(got, dense, rtol=1e-5)


# ---------------------------------------------------------------------------
# sharded optimizer state (the ZeRO-style leg)
# ---------------------------------------------------------------------------
@needs_four_devices
def test_zero_state_specs_bytes_and_comm_stats():
    """ONE dp2 x mp2 training step proves the whole ZeRO-style story
    (one compile — tier-1's time budget): every Adam moment carries its
    PARAM's PartitionSpec (the registry resolves `<p>_moment1_0` through
    base_name), the per-device param+state footprint lands under the
    0.55x acceptance bar (matrices halve; ln scales / biases / beta-pows
    stay replicated), and `spmd_comm_stats` reports the train-program
    collectives with at least the grad all-reduce visible."""
    class OneLayerHP(TinyHP):
        n_layer = 1  # tier-1 time budget: one block is enough to place
        #              every param class (emb/pos/qkvo/ffn/ln/head)
    _, sc, main, exe = _train(make_mesh({"dp": 2, "mp": 2}), steps=1,
                              hp=OneLayerHP)
    # --- moment specs follow the param ---
    moments = sorted(n for n in sc.all_var_names() if "moment" in n)
    assert moments, "no Adam state in scope"
    checked = 0
    for n in moments:
        base = train_partition_rules_for("gpt2").base_name(n)
        v = sc.find_var(n)
        if v is None or not hasattr(v, "sharding"):
            continue
        assert _spec_of(sc, n) == _spec_of(sc, base), (n, base)
        checked += 1
    assert checked >= 10
    # spot-check the load-bearing placements
    assert _spec_of(sc, "ffn_in.w_0_moment1_0") == (None, "mp")
    assert _spec_of(sc, "ffn_out.w_0_moment2_0") == ("mp", None)
    assert _spec_of(sc, "emb.w_0_moment1_0") == ("mp", None)
    # scalars (beta pows) stay replicated via the scalar guard
    rules = train_partition_rules_for("gpt2")
    assert rules.spec_for("fc_0.w_0_beta1_pow_acc_0", (1,)) == P()
    # --- per-device bytes: the acceptance floor ---
    per_device = replicated = 0
    for n in sc.all_var_names():
        v = sc.find_var(n)
        if v is None or not hasattr(v, "sharding"):
            continue
        replicated += v.nbytes
        shard = v.sharding.shard_shape(v.shape)
        nb = v.dtype.itemsize
        for d in shard:
            nb *= int(d)
        per_device += nb
    assert replicated > 0
    ratio = per_device / replicated
    assert ratio <= 0.55, (per_device, replicated, ratio)
    # --- comm attribution covers train programs ---
    stats = exe.spmd_comm_stats(main)
    assert stats["total_bytes"] > 0, stats
    assert any("all-reduce" in k for k in stats["per_op"]), stats


# ---------------------------------------------------------------------------
# composition legs
# ---------------------------------------------------------------------------
@pytest.mark.slow  # remat'd + plain compiles per leg; rides ci.sh spmd lane
@needs_four_devices
def test_remat_composes_with_mp():
    """HBM-budgeted remat under a mesh: the budget scales per-shard
    (maybe_remat multiplies by the mesh size since the estimator sees
    the GLOBAL program) and parity holds."""
    extra = {"hbm_budget_bytes": 1 << 20}
    base, _, _, _ = _train(None, extra_flags=extra)
    got, _, main, _ = _train(make_mesh({"dp": 2, "mp": 2}),
                             extra_flags=extra)
    np.testing.assert_allclose(got, base, rtol=1e-5)
    rep = getattr(main, "_remat_report", None)
    if rep is not None:
        assert rep.get("mesh_shards") == 4


@pytest.mark.slow  # two bf16 compiles; rides ci.sh spmd lane (-m "")
@needs_four_devices
def test_bf16_amp_composes_with_mp():
    """bf16 AMP under a mesh: f32 master params keep the param's spec
    (the @RAW_BF16 cast resolves through base_name) and training stays
    close to the unsharded bf16 run."""
    base, _, _, _ = _train(None, use_bf16=True)
    got, sc, _, _ = _train(make_mesh({"dp": 2, "mp": 2}), use_bf16=True)
    np.testing.assert_allclose(got, base, rtol=1e-4)
    rules = train_partition_rules_for("gpt2")
    casts = [n for n in sc.all_var_names() if "@RAW_BF16" in n
             and "ffn_in.w" in n]
    for n in casts:
        assert _spec_of(sc, n) == _spec_of(sc, rules.base_name(n)), n


