"""Optimization transpiler layer: HBM-budgeted remat, the generalized
inference pass pipeline, the program autotuner, and the memory_optimize
aliasing contracts (docs/PERFORMANCE.md "Optimization transpiler
layer")."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags, layers


SEQ = 8


def _tiny_hp():
    from paddle_tpu.models import transformer as tfm

    class HP(tfm.ModelHyperParams):
        max_length = 16
        d_model = 16
        d_inner_hid = 32
        n_layer = 2
        n_head = 2
        src_vocab_size = 50
        trg_vocab_size = 50
        fused_attn = True

    return HP


def _build_tfm(budget=0, is_test=False):
    from paddle_tpu.models import transformer as tfm

    flags.set_flags({"hbm_budget_bytes": budget})
    try:
        return tfm.wmt_transformer_program(
            _tiny_hp(), src_len=SEQ, trg_len=SEQ, is_test=is_test)
    finally:
        flags.set_flags({"hbm_budget_bytes": 0})


def _run_steps(main, startup, fetches, n=3, seed=7):
    from paddle_tpu.models import transformer as tfm

    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        startup.random_seed = seed
        exe.run(startup)
        batch = tfm.make_fake_batch(4, SEQ, SEQ, _tiny_hp(), seed=0)
        for _ in range(n):
            out = exe.run(main, feed=batch, fetch_list=fetches)
            losses.append(np.asarray(out[0]).copy())
    return losses


# ---------------------------------------------------------------------------
# remat: estimator + budgeted pass
# ---------------------------------------------------------------------------
def test_remat_cuts_peak_at_forcing_budget_and_losses_bit_exact():
    """THE acceptance bar: at a budget that forces recompute, the
    transformer builder's estimated peak activation bytes drop >= 40%,
    and training losses are bit-identical to the same partitioned
    program with checkpointing disabled (policy=everything_saveable —
    identical vjp structure, nothing recomputed), i.e. the RECOMPUTE
    decision changes scheduling only, never math.  Vs the UNPARTITIONED
    program: step-0 forward is bit-identical (identical fwd ops, RNG
    streams pinned); later steps agree to float-roundoff (the
    segment-level vjp may reassociate gradient fan-in sums by a ULP)."""
    main_r, st, _, fetches = _build_tfm(budget=1)  # 1 byte: force max
    rep = main_r._remat_report
    assert rep["segments_marked"] >= 2
    cut = 1.0 - rep["after_bytes"] / rep["before_bytes"]
    assert cut >= 0.40, rep

    twin = main_r.clone()
    for op in twin.global_block().ops:
        if op.type == "recompute":
            op.attrs["policy"] = "everything_saveable"
    twin._bump_version()

    l_remat = _run_steps(main_r, st, fetches, n=2)
    l_twin = _run_steps(twin, st, fetches, n=2)
    assert all(np.array_equal(a, b) for a, b in zip(l_remat, l_twin)), (
        l_remat, l_twin)

    main_0, st_0, _, f_0 = _build_tfm(budget=0)
    assert not any(op.type == "recompute"
                   for op in main_0.global_block().ops)
    l_base = _run_steps(main_0, st_0, f_0, n=2)
    assert np.array_equal(l_base[0], l_remat[0])
    for a, b in zip(l_base, l_remat):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=0)


@pytest.mark.slow
def test_remat_partial_budget_pins_rng_streams():
    """PARTIAL marking: a budget met by a SUBSET of segments shifts the
    positions of later UNWRAPPED ops — pin_rng_streams must keep every
    dropout's draw identical to the unremat program (the tiny HP has
    dropout=0.1 live, so an unpinned stream flips step-0's loss).
    Rides the ci.sh transpiler lane (-m \"\")."""
    main_f, _, _, _ = _build_tfm(budget=1)  # learn the before/after span
    rep = main_f._remat_report
    mid = (rep["before_bytes"] + rep["after_bytes"]) // 2
    main_p, st_p, _, f_p = _build_tfm(budget=mid)
    rep_p = main_p._remat_report
    assert 0 < rep_p["segments_marked"] < rep["segments_marked"], rep_p
    assert rep_p["fits"] and rep_p["after_bytes"] <= mid, rep_p
    main_0, st_0, _, f_0 = _build_tfm(budget=0)
    l_part = _run_steps(main_p, st_p, f_p, n=1)
    l_base = _run_steps(main_0, st_0, f_0, n=1)
    assert np.array_equal(l_part[0], l_base[0]), (l_part[0], l_base[0])


def test_estimator_monotone_in_marked_segments():
    """More recomputed segments can only lower (never raise) the
    estimated fwd+bwd peak — the property budgeted greedy marking and
    its binary search rely on."""
    from paddle_tpu.transpiler.remat import detect_segments, wrap_segment
    from paddle_tpu.utils import memory_analysis as ma

    main, _, feeds, fetches = _build_tfm(is_test=True)
    loss = fetches[0].name
    specs = ma.program_feed_specs(main, feeds, batch_hint=4)
    segments = detect_segments(main)
    assert len(segments) >= 4, segments

    peaks = []
    for k in (0, 2, len(segments) - 1):
        clone = main.clone()
        cblock = clone.global_block()
        runs = []
        for (a, b) in segments[:-1][:k]:
            runs.append((a, b - a))
        for a, ln in sorted(runs, reverse=True):
            wrap_segment(clone, cblock.ops[a:a + ln], protect=(loss,))
        # fwd+BWD: remat trades backward residuals for recompute — a
        # forward-only trace has no residuals and nothing to cut
        peaks.append(ma.estimate_peak_activation_bytes(
            clone, specs, loss, wrt="params")["peak_bytes"])
    assert peaks[0] >= peaks[1] >= peaks[2], peaks
    assert peaks[2] < peaks[0], peaks


def test_jaxpr_peak_bytes_counts_liveness_not_totals():
    """The walk reports simultaneously-live bytes: a chain of N equal
    buffers peaks near a couple of buffers, not N of them."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.utils.memory_analysis import jaxpr_peak_bytes

    def chain(x):
        for _ in range(10):
            x = jnp.tanh(x) + 1.0
        return x

    x = jnp.zeros((128, 128), jnp.float32)
    peak, largest = jaxpr_peak_bytes(jax.make_jaxpr(chain)(x))
    assert largest == 128 * 128 * 4
    assert peak <= 3 * largest, peak  # live set, not sum of all temps


def test_remat_pass_registry_form_marks_segments():
    from paddle_tpu.transpiler import apply_pass

    main, _, _, fetches = _build_tfm(is_test=True)
    main._protected_fetch_names = (fetches[0].name,)
    apply_pass(main, "remat_pass")
    n = sum(1 for op in main.global_block().ops
            if op.type == "recompute")
    assert n >= 2
    assert main._remat_marked_count == n


# ---------------------------------------------------------------------------
# inference transpiler sub-passes
# ---------------------------------------------------------------------------
def _startup_run(startup, scope, seed=3):
    exe = fluid.Executor(fluid.CPUPlace())
    startup.random_seed = seed
    exe.run(startup, scope=scope)
    return exe


def test_bn_fold_conv_bn_relu_parity():
    """conv+BN+relu: the BN folds into the conv weights (>= 1 op gone),
    the relu survives, outputs match at rtol 1e-5."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        img = layers.data("cbr_img", shape=[3, 8, 8])
        c = layers.conv2d(img, num_filters=4, filter_size=3, act=None)
        bn = layers.batch_norm(c, is_test=True)
        out = layers.relu(bn)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = _startup_run(startup, scope)
        scope.set("batch_norm_0.w_1",
                  np.random.RandomState(1).rand(4).astype("float32"))
        scope.set("batch_norm_0.w_2",
                  (np.random.RandomState(2).rand(4) + 0.5).astype("float32"))
        x = np.random.RandomState(0).rand(2, 3, 8, 8).astype("float32")
        (ref,) = exe.run(main, feed={"cbr_img": x}, fetch_list=[out],
                         scope=scope)
        n_before = len(main.global_block().ops)
        from paddle_tpu.transpiler import apply_pass

        apply_pass(main, "bn_fold_pass", scope=scope)
        types = [op.type for op in main.global_block().ops]
        assert "batch_norm" not in types, types
        assert "relu" in types, types
        assert len(types) <= n_before - 1
        (got,) = exe.run(main, feed={"cbr_img": x}, fetch_list=[out],
                         scope=scope)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_bn_fold_fc_bn_parity():
    """fc+BN (the per-out-column fold, new in the generalized pass):
    outputs match at rtol 1e-5 with the BN op gone."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        x_in = layers.data("fcbn_x", shape=[6])
        h = layers.fc(x_in, size=5, act=None)
        out = layers.batch_norm(h, is_test=True)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = _startup_run(startup, scope)
        scope.set("batch_norm_0.w_1",
                  np.random.RandomState(4).rand(5).astype("float32"))
        scope.set("batch_norm_0.w_2",
                  (np.random.RandomState(5).rand(5) + 0.5).astype("float32"))
        x = np.random.RandomState(0).rand(3, 6).astype("float32")
        (ref,) = exe.run(main, feed={"fcbn_x": x}, fetch_list=[out],
                         scope=scope)
        from paddle_tpu.transpiler import apply_pass

        apply_pass(main, "bn_fold_pass", scope=scope)
        types = [op.type for op in main.global_block().ops]
        assert "batch_norm" not in types, types
        (got,) = exe.run(main, feed={"fcbn_x": x}, fetch_list=[out],
                         scope=scope)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_bn_fold_scale_chain_parity():
    """conv -> pure scale -> BN (the scale-chain form): both the scale
    and the BN fold into the conv weights."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        img = layers.data("sc_img", shape=[2, 6, 6])
        c = layers.conv2d(img, num_filters=3, filter_size=3,
                          act=None, bias_attr=False)
        s = layers.scale(c, scale=1.7)
        out = layers.batch_norm(s, is_test=True)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = _startup_run(startup, scope)
        scope.set("batch_norm_0.w_1",
                  np.random.RandomState(6).rand(3).astype("float32"))
        scope.set("batch_norm_0.w_2",
                  (np.random.RandomState(7).rand(3) + 0.5).astype("float32"))
        x = np.random.RandomState(0).rand(2, 2, 6, 6).astype("float32")
        (ref,) = exe.run(main, feed={"sc_img": x}, fetch_list=[out],
                         scope=scope)
        from paddle_tpu.transpiler import apply_pass

        apply_pass(main, "bn_fold_pass", scope=scope)
        types = [op.type for op in main.global_block().ops]
        assert "batch_norm" not in types and "scale" not in types, types
        (got,) = exe.run(main, feed={"sc_img": x}, fetch_list=[out],
                         scope=scope)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_bn_fold_refuses_double_bias_chain():
    """fc-with-Bias -> elementwise_add(second bias) -> BN: folding only
    the add's operand would leave the fc's own bias unscaled — the pass
    must refuse, and the unfused program must still match itself."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        x_in = layers.data("db_x", shape=[6])
        h = layers.fc(x_in, size=5, act=None)  # fc carries its own Bias
        b2 = layers.create_parameter([5], "float32", name="db_b2")
        out = layers.batch_norm(layers.elementwise_add(h, b2),
                                is_test=True)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = _startup_run(startup, scope)
        x = np.random.RandomState(0).rand(3, 6).astype("float32")
        from paddle_tpu.transpiler import apply_pass

        # normalize mul+add to a real fc op carrying the Bias slot —
        # the double-bias shape the fold must refuse
        apply_pass(main, "fc_fuse_pass")
        fc_ops = [op for op in main.global_block().ops
                  if op.type == "fc"]
        assert fc_ops and fc_ops[0].inputs.get("Bias")
        (ref,) = exe.run(main, feed={"db_x": x}, fetch_list=[out],
                         scope=scope)
        apply_pass(main, "bn_fold_pass", scope=scope)
        assert "batch_norm" in [op.type
                                for op in main.global_block().ops]
        (got,) = exe.run(main, feed={"db_x": x}, fetch_list=[out],
                         scope=scope)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_bn_fold_refuses_train_mode_bn():
    """A TRAIN-mode BN normalizes by batch statistics; folding the
    moving stats into the weights would silently change the math — the
    pass must leave it alone (clone(for_test=True) is the opt-in)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        img = layers.data("tm_img", shape=[3, 8, 8])
        c = layers.conv2d(img, num_filters=4, filter_size=3, act=None)
        layers.batch_norm(c, is_test=False)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        _startup_run(startup, scope)
        from paddle_tpu.transpiler import apply_pass

        apply_pass(main, "bn_fold_pass", scope=scope)
    assert "batch_norm" in [op.type for op in main.global_block().ops]


def test_bn_fold_respects_protected_mid_chain_fetch():
    """A protected fetch of the conv output must survive: the fold
    rewires the conv to write the BN output name, which would delete
    the fetched definition — refuse instead."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        img = layers.data("pf_img", shape=[3, 8, 8])
        c = layers.conv2d(img, num_filters=4, filter_size=3, act=None,
                          bias_attr=False)
        bn = layers.batch_norm(c, is_test=True)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = _startup_run(startup, scope)
        x = np.random.RandomState(0).rand(2, 3, 8, 8).astype("float32")
        main._protected_fetch_names = (c.name,)
        from paddle_tpu.transpiler import apply_pass

        apply_pass(main, "bn_fold_pass", scope=scope)
        assert "batch_norm" in [op.type for op in main.global_block().ops]
        # both fetches still evaluable
        exe.run(main, feed={"pf_img": x}, fetch_list=[c, bn], scope=scope)


def test_train_prune_pass_drops_loss_head_fetch_equal():
    """A train program pruned at the prediction cut loses its label
    slot, loss head and optimizer ops; the kept fetch is
    value-identical."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        x_in = layers.data("tp_x", shape=[4])
        lbl = layers.data("tp_y", shape=[1], dtype="int64")
        h = layers.fc(x_in, size=8, act="relu")
        h = layers.dropout(h, 0.3)
        pred = layers.fc(h, size=3, act="softmax")
        loss = layers.mean(layers.cross_entropy(pred, lbl))
        fluid.optimizer.SGD(0.1).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = _startup_run(startup, scope)
        x = np.random.RandomState(0).rand(5, 4).astype("float32")
        infer = main.clone(for_test=True)
        (ref,) = exe.run(infer, feed={"tp_x": x}, fetch_list=[pred],
                         scope=scope)
        opt = fluid.InferenceTranspiler().transpile(
            main.clone(for_test=True), fluid.CPUPlace(), scope=scope,
            fetches=[pred])
        types = [op.type for op in opt.global_block().ops]
        assert "cross_entropy" not in types, types
        assert "dropout" not in types, types
        assert not any(t.endswith("_grad") or t == "sgd" for t in types), types
        # the label slot is below the cut: the pruned program must not
        # read it at all
        reads = {n for op in opt.global_block().ops
                 for n in op.input_arg_names()}
        assert "tp_y" not in reads
        (got,) = exe.run(opt, feed={"tp_x": x}, fetch_list=[pred],
                         scope=scope)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=0)


def test_weight_int8_pass_generic_program_parity():
    """weight_int8_pass quantizes ANY program's weights (here a plain
    fc MLP, not the serving engine): converted ops counted, outputs
    within the established post-training-quant tolerance."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        x_in = layers.data("q8_x", shape=[16])
        h = layers.fc(x_in, size=32, act="relu")
        pred = layers.fc(h, size=8)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = _startup_run(startup, scope)
        x = np.random.RandomState(0).rand(4, 16).astype("float32")
        (ref,) = exe.run(main, feed={"q8_x": x}, fetch_list=[pred],
                         scope=scope)
        from paddle_tpu.contrib.quantize import quantize_weights_int8

        n = quantize_weights_int8(main, scope=scope, min_elems=64)
        assert n >= 2, n
        types = [op.type for op in main.global_block().ops]
        assert any(t.startswith("quantized_") for t in types), types
        (got,) = exe.run(main, feed={"q8_x": x}, fetch_list=[pred],
                         scope=scope)
    ref, got = np.asarray(ref), np.asarray(got)
    # int8 weight-only tolerance (tests/test_quant_int8.py discipline)
    assert np.max(np.abs(got - ref)) < 0.1 * (np.max(np.abs(ref)) + 1)


def test_inference_transpile_pipeline_end_to_end():
    """transpile(fetches=..., quantize_int8=True) runs fold -> prune ->
    int8 in one call on a conv+BN+relu+fc classifier."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        img = layers.data("p_img", shape=[3, 8, 8])
        lbl = layers.data("p_lbl", shape=[1], dtype="int64")
        c = layers.conv2d(img, num_filters=4, filter_size=3, act=None)
        bn = layers.batch_norm(c, is_test=True)
        flat = layers.flatten(layers.relu(bn), axis=1)
        pred = layers.fc(layers.dropout(flat, 0.3), size=10, act="softmax")
        loss = layers.mean(layers.cross_entropy(pred, lbl))
        fluid.optimizer.SGD(0.1).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = _startup_run(startup, scope)
        x = np.random.RandomState(0).rand(2, 3, 8, 8).astype("float32")
        (ref,) = exe.run(main.clone(for_test=True), feed={"p_img": x},
                         fetch_list=[pred], scope=scope)
        opt = fluid.InferenceTranspiler().transpile(
            main.clone(for_test=True), fluid.CPUPlace(), scope=scope,
            fetches=[pred], quantize_int8=True, int8_min_elems=64)
        types = [op.type for op in opt.global_block().ops]
        assert "batch_norm" not in types
        assert "cross_entropy" not in types
        assert any(t.startswith("quantized_") for t in types), types
        (got,) = exe.run(opt, feed={"p_img": x}, fetch_list=[pred],
                         scope=scope)
    ref, got = np.asarray(ref), np.asarray(got)
    assert np.max(np.abs(got - ref)) < 0.05, np.max(np.abs(got - ref))


# ---------------------------------------------------------------------------
# memory_optimize aliasing contracts
# ---------------------------------------------------------------------------
def test_memory_optimize_refuses_cross_dtype_and_shape():
    """The seed-era pool matched on numel/bytes only; aliasing is only
    sound between identically-typed, identically-shaped slots."""
    from paddle_tpu.transpiler import memory_optimize

    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        x_in = layers.data("mo_x", shape=[4, 8], append_batch_size=False)
        a = layers.relu(x_in)           # f32 [4, 8], dies early
        b = layers.cast(a, "int64")     # int64 [4, 8]: HALF the numel of
        #                                 a same-bytes f32 — never alias a
        c = layers.reshape(layers.relu(x_in), shape=[32])  # f32 [32]
        d = layers.scale(layers.cast(b, "float32"), 2.0)
        out = layers.elementwise_add(
            layers.reshape(d, shape=[32]), c)
        layers.reduce_sum(out)
    plan = memory_optimize(main)
    block = main.global_block()
    for name, cand in plan["reuse"].items():
        v, cv = block.var(name), block.var(cand)
        assert str(v.dtype) == str(cv.dtype), (name, cand)
        assert tuple(v.shape) == tuple(cv.shape), (name, cand)


def test_memory_optimize_nested_block_liveness():
    """A var read ONLY inside a later op's sub-block (recompute here)
    must stay live until that op: the plan may not hand its storage to
    a var defined in between."""
    from paddle_tpu.transpiler import memory_optimize
    from paddle_tpu.transpiler.memory_optimization_transpiler import (
        ControlFlowGraph,
    )

    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup):
        x_in = layers.data("nb_x", shape=[4, 8], append_batch_size=False)
        early = layers.relu(x_in)  # read only inside the sub-block below
        mid = layers.tanh(layers.scale(x_in, 2.0))

        def body(m):
            return layers.elementwise_add(m, early)

        out = layers.recompute(body, mid)
        layers.reduce_sum(out)

    cfg = ControlFlowGraph(main)
    ranges = cfg.live_ranges()
    rec_idx = next(i for i, op in enumerate(main.global_block().ops)
                   if op.type == "recompute")
    # the nested read extends early's live range to the recompute op
    assert ranges[early.name][1] >= rec_idx, ranges[early.name]
    plan = memory_optimize(main)
    assert plan["reuse"].get(mid.name) != early.name


# ---------------------------------------------------------------------------
# program autotuner
# ---------------------------------------------------------------------------
def _mini_program(hidden=8):
    from paddle_tpu import unique_name

    main, startup = fluid.Program(), fluid.Program()
    with fluid.framework.program_guard(main, startup), \
            unique_name.guard():
        x_in = layers.data("at_x", shape=[4])
        h = layers.fc(x_in, size=hidden, act="relu")
        layers.fc(h, size=2)
    return main, startup


def test_autotune_search_cache_and_consult_only(tmp_path):
    from paddle_tpu.transpiler import autotune as at

    path = str(tmp_path / "ptc.json")
    main, _ = _mini_program()
    spec = {"at_x": ((4, 4), "float32")}

    # injected measurer: rbg + window 8 is the planted optimum; the
    # greedy search must find it and persist the decision
    def measure(decision):
        sps = 100.0
        if decision.get("prng_impl") == "rbg":
            sps += 50.0
        if decision.get("steps_per_dispatch", 1) > 1:
            sps += 25.0
        if decision.get("bf16_amp"):
            sps -= 40.0  # the CPU reality: AMP must be rejected
        return sps

    at.clear_cache(forget_path=True)
    saved = {k: flags.get_flag(k)
             for k in ("program_tune_cache", "program_autotune")}
    flags.set_flags({"program_tune_cache": path, "program_autotune": 1})
    try:
        d = at.tune(main, spec, measure=measure)
        assert d["prng_impl"] == "rbg"
        assert d["steps_per_dispatch"] == 8
        assert d["bf16_amp"] is False
        # hit path: no measurer needed
        d2 = at.tune(main, spec)
        assert d2 == d
        st = at.cache_stats()
        assert st["searched"] == 1 and st["stats"]["hits"] == 1

        # fresh-process view reloads the persisted decision
        at.clear_cache(forget_path=True)
        d3 = at.tune(main, spec)
        assert d3 == d

        # a DIFFERENT program signature in consult-only mode seeds the
        # all-defaults decision and never searches
        at.clear_cache(forget_path=True)
        flags.set_flags({"program_autotune": 0})
        other, other_st = _mini_program(hidden=16)  # distinct signature
        spec2 = {"at_x": ((4, 4), "float32")}
        d4 = at.tune(other, spec2, startup=other_st, fetches=[])
        assert d4 == at.DEFAULT_DECISION
        assert at.cache_stats()["stats"]["searches"] == 0
        # and the consult-only miss never lands on disk
        at.clear_cache(forget_path=True)
        flags.set_flags({"program_autotune": 1})
        d5 = at.tune(other, spec2)  # no measurer, no startup: defaults
        assert d5 == at.DEFAULT_DECISION
    finally:
        flags.set_flags(saved)
        at.clear_cache(forget_path=True)


def test_ci_pinned_program_tune_cache_consults_without_search():
    """The ci.sh transpiler lane pins FLAGS_program_tune_cache to the
    committed tests/data/ci_program_tune_cache.json with
    FLAGS_program_autotune=0: CI NEVER searches — the pinned decision
    for the reference mini program comes back verbatim, and a miss on
    any other signature seeds the all-defaults decision."""
    from paddle_tpu.transpiler import autotune as at

    if not str(flags.get_flag("program_tune_cache")).endswith(
            "ci_program_tune_cache.json"):
        pytest.skip("pinned program tune cache not configured "
                    "(the ci.sh transpiler lane sets it)")
    at.clear_cache(forget_path=True)
    try:
        main, _ = _mini_program()
        d = at.tune(main, {"at_x": ((4, 4), "float32")})
        # the committed searched decision (see tests/data/README note)
        assert d["steps_per_dispatch"] == 8, d
        assert d["prng_impl"] == "threefry", d
        st = at.cache_stats()
        assert st["stats"]["searches"] == 0
        assert st["stats"]["hits"] == 1
        # unknown signature: all-defaults, still no search
        other, _ = _mini_program(hidden=32)
        d2 = at.tune(other, {"at_x": ((4, 4), "float32")})
        assert d2 == at.DEFAULT_DECISION
        assert at.cache_stats()["stats"]["searches"] == 0
    finally:
        at.clear_cache(forget_path=True)


def test_autotune_signature_stable_and_value_insensitive():
    from paddle_tpu.transpiler.autotune import program_signature

    a, _ = _mini_program()
    b, _ = _mini_program()
    assert program_signature(a) == program_signature(b)
    c, _ = _mini_program(hidden=16)  # structurally different program
    assert program_signature(a) != program_signature(c)


@pytest.mark.slow
def test_autotuned_window_matches_per_step_trajectory():
    """steps_per_dispatch is schedule, not math: run_loop(K) reproduces
    K sequential run() losses exactly (same RNG fold indices), so a
    tuned window never changes the training trajectory.  Rides the
    ci.sh transpiler lane (-m \"\")."""
    from paddle_tpu.models import transformer as tfm

    main, st, _, fetches = _build_tfm()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        st.random_seed = 11
        exe.run(st)
        batch = tfm.make_fake_batch(2, SEQ, SEQ, _tiny_hp(), seed=1)
        per_step = []
        for _ in range(3):
            out = exe.run(main, feed=batch, fetch_list=fetches)
            per_step.append(float(np.asarray(out[0])))
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2 = fluid.Executor(fluid.CPUPlace())
        st.random_seed = 11
        exe2.run(st)
        batch = tfm.make_fake_batch(2, SEQ, SEQ, _tiny_hp(), seed=1)
        out = exe2.run_loop(3, main, feed=batch, fetch_list=fetches)
        assert float(np.asarray(out[0])) == per_step[-1]


# ---------------------------------------------------------------------------
# decode/serving epilogue satellite
# ---------------------------------------------------------------------------
def test_decode_and_ragged_builders_get_epilogue_fusions():
    """PR 11's 'epilogue passes rewrite training programs only' limit is
    closed: the classic decode step AND the continuous-batching ragged
    step carry fused fc / residual-LN ops (the churn-exactness suite
    under FLAGS_use_pallas=1 guards the numerics)."""
    from paddle_tpu.models import gpt2

    class HP(gpt2.GPT2Config):
        vocab_size = 97
        n_ctx = 32
        d_model = 16
        n_layer = 2
        n_head = 2
        dropout = 0.0

    with fluid.scope_guard(fluid.Scope()):
        main, _, _, _, _ = gpt2.gpt2_decode_step_program(HP, batch=2,
                                                         t_max=16)
    assert getattr(main, "_fc_fused_count", 0) >= 1
    assert getattr(main, "_residual_ln_fused_count", 0) >= 1
    types = [op.type for op in main.global_block().ops]
    assert "fc" in types and "fused_residual_ln" in types

    with fluid.scope_guard(fluid.Scope()):
        ragged, _, _, _, _ = gpt2.gpt2_ragged_step_program(
            HP, batch=2, t_max=16, width=4)
    assert getattr(ragged, "_fc_fused_count", 0) >= 1
    assert getattr(ragged, "_residual_ln_fused_count", 0) >= 1
