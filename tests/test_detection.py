"""Detection suite: SSD loss / RPN / proposal sampling / NMS / mAP.

Covers the VERDICT round-1 acceptance: an SSD-style and an RCNN-style toy
train step, plus unit checks of the new dense padded detection ops
(reference: paddle/fluid/operators/detection/*.cc,
python/paddle/fluid/layers/detection.py).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.layers import detection as det


def _run(feed, fetches):
    exe = fluid.Executor(fluid.CPUPlace())
    return exe.run(feed=feed, fetch_list=fetches)


def test_box_coder_layer_roundtrip():
    P = 6
    rng = np.random.RandomState(0)
    prior = np.sort(rng.rand(P, 4).astype("float32"), axis=1)
    pvar = np.full((P, 4), 0.1, "float32")
    gt = np.sort(rng.rand(3, 4).astype("float32"), axis=1)
    pb = layers.data("pb", shape=[P, 4], append_batch_size=False)
    pv = layers.data("pv", shape=[P, 4], append_batch_size=False)
    tb = layers.data("tb", shape=[3, 4], append_batch_size=False)
    enc = det.box_coder(pb, pv, tb, code_type="encode_center_size")
    dec = det.box_coder(pb, pv, enc, code_type="decode_center_size")
    r = _run({"pb": prior, "pv": pvar, "tb": gt}, [enc, dec])
    # decode(encode(gt)) == gt per prior row; row m decodes against prior m
    for m in range(P):
        np.testing.assert_allclose(r[1][:, m], gt, rtol=1e-4, atol=1e-4)


def test_ssd_toy_train_step():
    """SSD-style head: priors + loc/conf predictions -> ssd_loss trains."""
    B, P, C, G = 2, 12, 4, 3
    rng = np.random.RandomState(1)
    feats = rng.rand(B, 8).astype("float32")
    gt_box = np.sort(rng.rand(B, G, 4).astype("float32"), axis=2)
    gt_label = rng.randint(1, C, (B, G, 1)).astype("int64")
    prior = np.sort(rng.rand(P, 4).astype("float32"), axis=1)
    pvar = np.full((P, 4), 0.1, "float32")

    x = layers.data("x", shape=[B, 8], append_batch_size=False)
    gb = layers.data("gb", shape=[B, G, 4], append_batch_size=False)
    gl = layers.data("gl", shape=[B, G, 1], append_batch_size=False, dtype="int64")
    pb = layers.data("pb", shape=[P, 4], append_batch_size=False)
    pv = layers.data("pv", shape=[P, 4], append_batch_size=False)
    h = layers.fc(x, 32, act="relu")
    loc = layers.reshape(layers.fc(h, P * 4), [B, P, 4])
    conf = layers.reshape(layers.fc(h, P * C), [B, P, C])
    loss_map = det.ssd_loss(loc, conf, gb, gl, pb, pv, background_label=0)
    loss = layers.mean(loss_map)
    fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": feats, "gb": gt_box, "gl": gt_label, "pb": prior, "pv": pvar}
    losses = [float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0]).reshape(-1)[0])
              for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


@pytest.mark.slow  # heavy leg; fast run keeps sibling coverage
def test_rcnn_toy_train_step():
    """RCNN-style: anchors -> rpn targets -> proposals -> sampled RoIs ->
    roi_align head trains (grads flow through roi features)."""
    N, A, H, W, G = 1, 3, 4, 4, 2
    rng = np.random.RandomState(2)
    feat = rng.rand(N, 6, H, W).astype("float32")
    gt = np.array([[[2.0, 2.0, 9.0, 9.0], [5.0, 5.0, 14.0, 14.0]]], "float32")
    im_info = np.array([[16.0, 16.0, 1.0]], "float32")

    x = layers.data("x", shape=[N, 6, H, W], append_batch_size=False)
    gb = layers.data("gb", shape=[N, G, 4], append_batch_size=False)
    info = layers.data("info", shape=[N, 3], append_batch_size=False)
    anchors, avar = det.anchor_generator(
        x, anchor_sizes=[4.0, 8.0, 12.0], aspect_ratios=[1.0], stride=[4.0, 4.0]
    )
    conv = layers.conv2d(x, 16, 1, act="relu")
    scores = layers.conv2d(conv, A, 1)
    deltas = layers.conv2d(conv, A * 4, 1)

    # rpn targets (dense): labels [N, HWA], targets [N, HWA, 4]
    labels, tgts, inw = det.rpn_target_assign(
        deltas, scores, anchors, avar, gb,
        rpn_positive_overlap=0.3, rpn_negative_overlap=0.1,
    )
    score_flat = layers.reshape(layers.transpose(scores, [0, 2, 3, 1]), [N, -1])
    lab_f = layers.cast(labels, "float32")
    valid = layers.cast(layers.greater_equal(lab_f, layers.fill_constant([1], "float32", 0.0)), "float32")
    rpn_cls_loss = layers.reduce_sum(
        layers.sigmoid_cross_entropy_with_logits(score_flat, lab_f) * valid
    ) / (layers.reduce_sum(valid) + 1e-6)

    rois, probs, rois_num = det.generate_proposals(
        scores, deltas, info, anchors, avar,
        pre_nms_top_n=24, post_nms_top_n=8, nms_thresh=0.7, min_size=1.0,
    )
    roi_feat = det.roi_align(
        conv, layers.reshape(rois, [-1, 4]), pooled_height=2, pooled_width=2,
        spatial_scale=0.25,
    )
    head = layers.fc(layers.reshape(roi_feat, [8, -1]), 4)
    loss = layers.mean(head * head) + rpn_cls_loss
    fluid.optimizer.SGD(0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": feat, "gb": gt, "info": im_info}
    losses = [float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0]).reshape(-1)[0])
              for _ in range(4)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_generate_proposal_labels_shapes_and_sampling():
    N, R, G, BSZ = 1, 10, 2, 8
    rng = np.random.RandomState(3)
    rois = np.sort(rng.rand(N, R, 4).astype("float32") * 10, axis=2)
    gt = np.array([[[1.0, 1.0, 5.0, 5.0], [6.0, 6.0, 9.0, 9.0]]], "float32")
    gtc = np.array([[1, 2]], "int64")
    rv = layers.data("rv", shape=[N, R, 4], append_batch_size=False)
    gbv = layers.data("gbv", shape=[N, G, 4], append_batch_size=False)
    gcv = layers.data("gcv", shape=[N, G], append_batch_size=False, dtype="int64")
    out = det.generate_proposal_labels(
        rv, gcv, gt_boxes=gbv, batch_size_per_im=BSZ, fg_fraction=0.5,
        fg_thresh=0.5, class_nums=4,
    )
    r = _run({"rv": rois, "gbv": gt, "gcv": gtc}, list(out))
    s_rois, s_lab, s_tgt, s_inw, s_outw, s_num = r
    assert s_rois.shape == (N, BSZ, 4)
    assert s_lab.shape == (N, BSZ)
    assert s_tgt.shape == (N, BSZ, 16)
    # gt boxes are appended to the roi set, so at least the gt rows are fg
    assert (s_lab >= 1).sum() >= G
    assert int(s_num[0]) <= BSZ


def test_mine_hard_examples_selects_highest_loss():
    cls_loss = np.array([[0.1, 0.9, 0.5, 0.7, 0.2]], "float32")
    match = np.array([[0, -1, -1, -1, -1]], "int32")  # 1 positive
    cl = layers.data("cl", shape=[1, 5], append_batch_size=False)
    mi = layers.data("mi", shape=[1, 5], append_batch_size=False, dtype="int32")
    neg, upd = det.mine_hard_examples(cl, mi, neg_pos_ratio=2.0)
    r = _run({"cl": cls_loss, "mi": match}, [neg, upd])
    # 1 pos -> 2 hard negatives: indices 1 (0.9) and 3 (0.7)
    np.testing.assert_array_equal(r[0][0], [0, 1, 0, 1, 0])
    np.testing.assert_array_equal(r[1][0], [0, -1, -1, -1, -1])


def test_multiclass_nms_layer_suppresses_overlaps():
    # two heavily-overlapping boxes + one separate, single class
    boxes = np.array(
        [[[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]]], "float32"
    )
    scores = np.array([[[0.9, 0.8, 0.7]]], "float32")  # [N, C=1, M]
    bv = layers.data("bv", shape=[1, 3, 4], append_batch_size=False)
    sv = layers.data("sv", shape=[1, 1, 3], append_batch_size=False)
    out, num = det.multiclass_nms(bv, sv, score_threshold=0.1, nms_threshold=0.5,
                                  keep_top_k=3, background_label=-1)
    r = _run({"bv": boxes, "sv": scores}, [out, num])
    assert int(r[1][0]) == 2  # overlap suppressed
    kept = r[0][0][r[0][0][:, 0] >= 0]
    np.testing.assert_allclose(sorted(kept[:, 1], reverse=True), [0.9, 0.7], rtol=1e-6)


def test_roi_perspective_transform_identity_quad():
    # axis-aligned quad == crop+resize of the region
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    quad = np.array([[0.0, 0.0, 3.0, 0.0, 3.0, 3.0, 0.0, 3.0]], "float32")
    xv = layers.data("xv", shape=[1, 1, 4, 4], append_batch_size=False)
    qv = layers.data("qv", shape=[1, 8], append_batch_size=False)
    out = det.roi_perspective_transform(xv, qv, 4, 4)
    (r,) = _run({"xv": x, "qv": quad}, [out])
    # sampling the full image at 4x4 grid centers ~ the image itself
    assert r.shape == (1, 1, 4, 4)
    np.testing.assert_allclose(r[0, 0, 1:3, 1:3], x[0, 0, 1:3, 1:3], atol=2.0)


def test_detection_map_metric():
    from paddle_tpu.metrics import DetectionMAP

    m = DetectionMAP(overlap_threshold=0.5, ap_version="integral")
    gt_boxes = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], "float32")
    gt_labels = np.array([1, 2])
    dets = np.array(
        [
            [1, 0.9, 0, 0, 10, 10],     # TP class 1
            [2, 0.8, 20, 20, 30, 30],   # TP class 2
            [1, 0.7, 50, 50, 60, 60],   # FP class 1
            [-1, 0.0, 0, 0, 0, 0],      # padding
        ],
        "float32",
    )
    m.update(dets, gt_boxes, gt_labels)
    v = m.eval()
    assert 0.9 <= v <= 1.0  # both gts found at rank 1


def test_detection_output_end_to_end():
    B, P, C = 1, 4, 3
    rng = np.random.RandomState(5)
    prior = np.sort(rng.rand(P, 4).astype("float32"), axis=1)
    pvar = np.full((P, 4), 0.1, "float32")
    loc = np.zeros((B, P, 4), "float32")
    scores = rng.rand(B, P, C).astype("float32")
    pb = layers.data("pb", shape=[P, 4], append_batch_size=False)
    pv = layers.data("pv", shape=[P, 4], append_batch_size=False)
    lv = layers.data("lv", shape=[B, P, 4], append_batch_size=False)
    sv = layers.data("sv", shape=[B, P, C], append_batch_size=False)
    out = det.detection_output(lv, sv, pb, pv, score_threshold=0.01)
    (r,) = _run({"pb": prior, "pv": pvar, "lv": loc, "sv": scores}, [out])
    assert r.shape[-1] == 6
    assert np.isfinite(r).all()


def test_multi_box_head_ssd_composition():
    """multi_box_head over two feature maps with a dynamic batch: aligned
    loc/conf/prior counts, run end-to-end."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.framework.program_guard(main, startup):
        img = layers.data("mb_img", shape=[3, 32, 32])
        f1 = layers.conv2d(img, 8, 3, stride=2, padding=1)
        f2 = layers.conv2d(f1, 8, 3, stride=2, padding=1)
        locs, confs, boxes, vars_ = layers.multi_box_head(
            [f1, f2], img, base_size=32, num_classes=4,
            aspect_ratios=[2.0, 3.0],  # flat list = one ratio PER LAYER
            min_ratio=20, max_ratio=90,
        )
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        rng = np.random.RandomState(0)
        lv, cv, bv, vv = exe.run(
            main, feed={"mb_img": rng.rand(2, 3, 32, 32).astype("float32")},
            fetch_list=[locs, confs, boxes, vars_],
        )
    lv, cv, bv, vv = map(np.asarray, (lv, cv, bv, vv))
    assert lv.shape[0] == 2 and cv.shape[0] == 2
    assert lv.shape[1] == cv.shape[1] == bv.shape[0] == vv.shape[0]
    assert lv.shape[2] == 4 and cv.shape[2] == 4  # 4 coords / 4 classes
    assert np.isfinite(lv).all() and np.isfinite(bv).all()


def test_multi_box_head_narrow_ratio_range():
    """A ratio range narrower than the layer count pads the schedule
    instead of crashing (6 maps, 2-point range)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.framework.program_guard(main, startup):
        img = layers.data("nr_img", shape=[3, 32, 32])
        feats, f = [], img
        for _ in range(6):
            f = layers.conv2d(f, 4, 3, stride=1, padding=1)
            feats.append(f)
        locs, confs, boxes, vars_ = layers.multi_box_head(
            feats, img, base_size=32, num_classes=3,
            aspect_ratios=[2.0] * 6, min_ratio=20, max_ratio=22,
        )
    assert locs is not None and boxes is not None
