"""Serving fabric (paddle_tpu/serving/router.py, docs/SERVING.md §7):
multi-pool routing exactness, chaos-tested degradation, and the unified
control plane.

The contracts under test:
* sticky placement keeps every request's stream bit-identical to its
  solo run (the PR 9 exactness contract, now fabric-wide);
* pool death (the `pool_kill` fault action) re-places queued AND
  in-flight requests onto survivors with the emitted prefix replayed —
  the full stream stays token-identical to solo, and the survivors see
  zero retraces;
* drain-and-retire leaves no orphaned slots;
* the fabric admission queue is the backpressure signal — overflow is a
  loud REJECTED_QUEUE_FULL at the router, never a hang;
* ONE _ScalingPolicy instance governs trainers, pservers, and pools
  under one shared cooldown + action budget (no flap when axes
  disagree).
"""

import math

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.distributed.faults import FaultSchedule
from paddle_tpu.distributed.launch import _RestartPolicy, _ScalingPolicy
from paddle_tpu.models import gpt2
from paddle_tpu.serving import (
    FabricRouter,
    Request,
    ServingEngine,
    make_poisson_trace,
    parse_pool_schedule,
)


class TinyHP(gpt2.GPT2Config):
    vocab_size = 61
    n_ctx = 32
    d_model = 32
    n_layer = 2
    n_head = 4
    dropout = 0.0


T_MAX = 24


def _pool_factory(n_slots=2, width=4, seed=7, engines=None):
    """Factory building one pool: tiny-GPT2 weights in a FRESH scope
    (fixed startup seed -> every pool holds identical weights, the
    failover-replay precondition).  `engines` collects every engine
    ever built so tests can assert on RETIRED pools too."""

    def factory():
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            _, lm_startup, _, _ = gpt2.gpt2_logits_program(
                TinyHP, seq_len=T_MAX)
            exe = fluid.Executor(fluid.CPUPlace())
            lm_startup.random_seed = seed
            exe.run(lm_startup)
            eng = ServingEngine(exe, TinyHP, n_slots=n_slots,
                                width=width, t_max=T_MAX)
        if engines is not None:
            engines.append(eng)
        return eng, scope

    return factory


def _trace(n, rate, seed, out_hi=10):
    return make_poisson_trace(
        n, rate=rate, prompt_len_range=(2, 8), out_len_range=(4, out_hi),
        vocab_size=TinyHP.vocab_size, seed=seed)


def _assert_solo_exact(results, trace_args):
    """Every OK stream must be BIT-identical to its solo run on a fresh
    pool (same weights: the factory's fixed startup seed)."""
    eng, scope = _pool_factory(n_slots=4)()
    with fluid.scope_guard(scope):
        for r in _trace(*trace_args):
            if results[r.rid]["status"] != "OK":
                continue
            ref, _ = eng.run_solo(r)
            got = np.asarray(results[r.rid]["tokens"])
            assert np.array_equal(np.asarray(ref), got), (
                "rid %r diverged from solo" % (r.rid,))


# ---------------------------------------------------------------------------
# routing exactness + stickiness (no faults)
# ---------------------------------------------------------------------------
def test_fabric_multi_pool_exactness():
    router = FabricRouter(_pool_factory(n_slots=2), n_pools=3,
                          queue_depth=16)
    args = (12, 0.9, 3)
    results, stats = router.run(_trace(*args))
    assert {r["status"] for r in results.values()} == {"OK"}
    assert stats["finished"] == 12 and stats["rejected"] == 0
    assert stats["replaced"] == 0
    # sticky: every result names exactly one pool
    assert all(isinstance(r["pool"], int) for r in results.values())
    _assert_solo_exact(results, args)


def test_fabric_single_pool_matches_engine_run():
    """One-pool fabric is the engine plus router bookkeeping — the
    token streams must match engine.run on the same trace exactly."""
    router = FabricRouter(_pool_factory(n_slots=4), n_pools=1)
    results, _ = router.run(_trace(10, 0.7, 5))
    eng, scope = _pool_factory(n_slots=4)()
    with fluid.scope_guard(scope):
        ref, _ = eng.run(_trace(10, 0.7, 5))
    for rid, r in ref.items():
        assert np.array_equal(np.asarray(r["tokens"]),
                              np.asarray(results[rid]["tokens"])), rid


def test_fabric_duplicate_and_oversized_rejected_at_submit():
    router = FabricRouter(_pool_factory(), n_pools=1)
    router.submit(Request(rid=0, prompt=[1, 2], max_new_tokens=3))
    with pytest.raises(ValueError, match="duplicate"):
        router.submit(Request(rid=0, prompt=[3], max_new_tokens=2))
    with pytest.raises(ValueError, match="capacity"):
        router.submit(Request(rid=1, prompt=[1] * T_MAX,
                              max_new_tokens=T_MAX))


# ---------------------------------------------------------------------------
# chaos: pool death mid-stream
# ---------------------------------------------------------------------------
def test_kill_pool_mid_stream_failover_preserves_solo_stream():
    """SIGKILL one of 3 pools mid-stream (the `pool_kill` fault action
    on the pinned-seed FaultSchedule): every affected request finishes
    on a survivor, the full stream token-identical to its solo run —
    the replay path (prompt + emitted prefix, sample keys offset past
    it) reconstructs the exact continuation.  Survivors see ZERO
    retraces from the failover."""
    fs = FaultSchedule({"fabric": {8: "pool_kill:0"}})
    router = FabricRouter(_pool_factory(n_slots=2), n_pools=3,
                          queue_depth=16, fault_schedule=fs)
    args = (14, 1.2, 4, 12)
    for r in _trace(*args):
        router.submit(r)
    replays, survivors_warm = [], None
    while any(h.engine.queue or h.engine.pool.active_slots()
              for h in router.pools.values()) or router.queue:
        router.step()
        if router.counters["pools_died"] and survivors_warm is None:
            # snapshot immediately after the failover: the replayed
            # requests sit in the router queue with offset sample keys
            replays = [q for q in router.queue
                       if q.sample_step_base > 0]
            survivors_warm = {
                pid: h.engine.exe.compile_count
                for pid, h in router.pools.items()}
        assert router.now < 3000
    results = dict(router._results)
    stats = router.stats()
    assert stats["pool_kills"] == 1 and stats["pools_died"] == 1
    assert stats["replaced"] > 0, "kill must catch in-flight requests"
    assert {r["status"] for r in results.values()} == {"OK"}
    assert sum(bool(r.get("replayed")) for r in results.values()) \
        == stats["replaced"]
    _assert_solo_exact(results, args)
    # the re-decoded tail alone must equal a solo re-run FROM the
    # replayed prefix (prefill of prompt+prefix continues the solo
    # sample sequence): serve each captured replay request solo
    assert replays, "failover must have enqueued replay requests"
    eng, scope = _pool_factory(n_slots=4)()
    with fluid.scope_guard(scope):
        for rep in replays:
            tail, _ = eng.run(
                [Request(rid="replay-%s" % rep.rid, prompt=rep.prompt,
                         max_new_tokens=rep.max_new_tokens,
                         temperature=rep.temperature, top_k=rep.top_k,
                         top_p=rep.top_p, seed=rep.seed,
                         eos_id=rep.eos_id,
                         sample_step_base=rep.sample_step_base)])
            tail = np.asarray(tail["replay-%s" % rep.rid]["tokens"])
            full = np.asarray(results[rep.rid]["tokens"])
            assert np.array_equal(full[rep.sample_step_base:], tail), (
                rep.rid)
    # zero retraces on survivors: no recompiles after the failover
    for pid, h in router.pools.items():
        assert h.engine.exe.compile_count == survivors_warm[pid], (
            "pool %d retraced during failover" % pid)


def test_pool_kill_seeded_pick_is_deterministic():
    """A bare `pool_kill` picks its victim off the schedule's seeded
    per-frame hash — two routers with the same seed kill the same
    pool."""
    victims = []
    for _ in range(2):
        fs = FaultSchedule({"fabric": {6: "pool_kill"}}, seed=11)
        router = FabricRouter(_pool_factory(n_slots=2), n_pools=3,
                              queue_depth=16, fault_schedule=fs)
        results, stats = router.run(_trace(10, 1.0, 4))
        assert stats["pool_kills"] == 1
        assert {r["status"] for r in results.values()} == {"OK"}
        victims.append({int(p) for p in stats["pools"]})
    assert victims[0] == victims[1]


def test_dead_step_thread_fails_over_immediately():
    """An exception inside a pool's step loop (a dead step thread, not
    a silent kill) declares the pool dead the SAME step."""
    router = FabricRouter(_pool_factory(n_slots=2), n_pools=2,
                          queue_depth=16)
    args = (8, 1.0, 6)
    for r in _trace(*args):
        router.submit(r)
    for _ in range(4):
        router.step()
    victim = sorted(router.pools)[0]
    router.pools[victim].engine.exe = None  # step() will raise
    while router.queue or any(h.engine.queue or
                              h.engine.pool.active_slots()
                              for h in router.pools.values()):
        router.step()
        assert router.now < 3000
    assert victim not in router.pools
    assert router.counters["pools_died"] == 1
    results = dict(router._results)
    assert {r["status"] for r in results.values()} == {"OK"}
    _assert_solo_exact(results, args)


# ---------------------------------------------------------------------------
# scaling: 1 -> 3 -> 1 under the seeded trace
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_scale_pools_1_3_1_latency_and_zero_retrace():
    """The deterministic chaos/bench walk: grow 1->3 at T1, shrink back
    3->1 at T2 under one seeded Poisson trace.  Bars: zero rejections
    (capacity exists throughout), every stream OK and solo-exact, p99
    latency of the 3-pool phase within 2x of a STATIC 3-pool run, and
    zero retraces per pool (scaling must never recompile anyone)."""
    args = (24, 1.2, 9, 8)
    t_grow, t_shrink = 6, 30

    static_engines = []
    static = FabricRouter(
        _pool_factory(n_slots=2, engines=static_engines), n_pools=3,
        queue_depth=64)
    static_res, _ = static.run(_trace(*args))

    engines = []
    router = FabricRouter(_pool_factory(n_slots=2, engines=engines),
                          n_pools=1, queue_depth=64)
    results, stats = router.run(
        _trace(*args), pool_schedule=[(t_grow, +2), (t_shrink, -2)])
    assert stats["rejected"] == 0 and stats["rejection_rate"] == 0.0
    assert {r["status"] for r in results.values()} == {"OK"}
    assert stats["pools_added"] == 3 and stats["pools_retired"] == 2
    assert stats["n_pools"] == 1
    _assert_solo_exact(results, args)

    def p99(res, lo, hi):
        lats = sorted(r["latency_steps"] for r in res.values()
                      if lo <= r["arrival_step"] < hi)
        return lats[min(len(lats) - 1,
                        int(math.ceil(0.99 * len(lats)) - 1))]

    # 3-pool phase: arrivals once the grow landed, before the shrink
    assert p99(results, t_grow, t_shrink) \
        <= 2 * max(1, p99(static_res, t_grow, t_shrink))
    # zero retraces per pool, RETIRED pools included: every engine ever
    # built compiled the same program set as an undisturbed static pool
    warm = max(e.exe.compile_count for e in static_engines)
    for e in engines:
        assert e.exe.compile_count <= warm, "scaling caused a retrace"


def test_drain_and_retire_leaves_no_orphans():
    """drain_pool mid-stream: no new placements, in-flight requests
    finish on their slots, and the retired pool ends with zero active
    slots and an empty queue (nothing re-placed, nothing lost)."""
    engines = []
    router = FabricRouter(_pool_factory(n_slots=2, engines=engines),
                          n_pools=2, queue_depth=32)
    args = (10, 1.0, 7)
    for r in _trace(*args):
        router.submit(r)
    drained = None
    while router.queue or any(h.engine.queue or
                              h.engine.pool.active_slots()
                              for h in router.pools.values()):
        router.step()
        if router.now == 5:
            drained = sorted(router.pools)[0]
            router.drain_pool(drained)
        assert router.now < 3000
    assert drained is not None and drained not in router.pools
    stats = router.stats()
    assert stats["pools_retired"] == 1 and stats["replaced"] == 0
    results = dict(router._results)
    assert {r["status"] for r in results.values()} == {"OK"}
    for e in engines:  # no orphaned slots anywhere, retiree included
        assert not e.pool.active_slots() and not e.queue
    _assert_solo_exact(results, args)


def test_scale_down_never_drains_last_pool():
    router = FabricRouter(_pool_factory(), n_pools=2, queue_depth=8)
    router.scale_pools(-5)
    assert len(router._live()) == 1


# ---------------------------------------------------------------------------
# backpressure + router-side deadlines
# ---------------------------------------------------------------------------
def test_router_backpressure_rejects_loudly_at_depth(capsys):
    """An arrival finding queue_depth requests already waiting is
    REJECTED_QUEUE_FULL at the router, immediately and loudly — the
    fabric never hangs and never queues unboundedly."""
    router = FabricRouter(_pool_factory(n_slots=2), n_pools=1,
                          queue_depth=2)
    burst = [Request(rid=i, prompt=np.arange(1, 5), max_new_tokens=6,
                     arrival=0.0) for i in range(8)]
    results, stats = router.run(burst)
    st = [results[i]["status"] for i in range(8)]
    assert st.count("REJECTED_QUEUE_FULL") == 4  # 2 slots + 2 waiting
    assert st.count("OK") == 4
    assert stats["rejected"] == 4 and stats["rejection_rate"] == 0.5
    for i in range(8):
        if results[i]["status"] == "OK":
            assert len(results[i]["tokens"]) == 6
    assert "REJECTED_QUEUE_FULL" in capsys.readouterr().out


def test_router_deadline_expires_waiting_requests():
    router = FabricRouter(_pool_factory(n_slots=2), n_pools=1,
                          queue_depth=8)
    reqs = [Request(rid=i, prompt=np.arange(1, 6), max_new_tokens=8,
                    arrival=0.0, deadline=3) for i in range(5)]
    results, _ = router.run(reqs)
    statuses = sorted(results[i]["status"] for i in range(5))
    assert "DEADLINE_EXPIRED" in statuses  # the ones stuck waiting
    # whoever got a slot in time either finished or expired mid-decode;
    # nobody hung
    assert set(statuses) <= {"OK", "DEADLINE_EXPIRED"}


# ---------------------------------------------------------------------------
# control plane: stats verb, RPC service, schedule parser
# ---------------------------------------------------------------------------
def test_parse_pool_schedule():
    assert parse_pool_schedule("4:+2,30:-2") == [(4.0, 2), (30.0, -2)]
    assert parse_pool_schedule(" 9:-1 , 2:+3 ") == [(2.0, 3), (9.0, -1)]
    assert parse_pool_schedule("") == []
    assert parse_pool_schedule(None) == []


def test_control_service_speaks_stats_and_scale_over_rpc():
    """The router's control plane rides the SAME VarServer/RPCClient
    stack the pservers use: `stats` returns the shared signal set, and
    `scale_pools` lands at the next step boundary."""
    from paddle_tpu.distributed.rpc import RPCClient

    router = FabricRouter(_pool_factory(), n_pools=1, queue_depth=8)
    srv = router.serve_control("127.0.0.1:0")
    try:
        cli = RPCClient(srv.endpoint, timeout=5, retries=2)
        try:
            s = cli.call("stats")
            assert s["n_pools"] == 1 and "occupancy" in s \
                and "queue_depth" in s and "rejection_rate" in s
            r = cli.call("scale_pools", delta=1)
            assert r["ok"]
            router.step()  # boundary applies the pending delta
            assert cli.call("stats")["n_pools"] == 2
            with pytest.raises(Exception):
                cli.call("no_such_verb")
        finally:
            cli.close()
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# unified supervisor: one policy, three axes, one budget
# ---------------------------------------------------------------------------
def _policy(**kw):
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("hysteresis", 2)
    kw.setdefault("budget",
                  _RestartPolicy(max_restarts=8, window_s=60.0,
                                 backoff_s=0.0))
    return _ScalingPolicy(1, 4, min_ps=1, max_ps=4, min_pools=1,
                          max_pools=4, **kw)


def test_scaling_policy_pool_axis_signals():
    """Pool axis of _ScalingPolicy: pressure (queue depth / occupancy /
    rejections) grows after `hysteresis` observations, sustained idle
    shrinks after twice that, and a re-placement burst (failover in
    progress) suppresses and resets — load measured mid-failover must
    not drive scaling."""
    p = _policy()
    hot = {"queue_depth": 3, "occupancy": 0.5, "rejected": 0,
           "replaced": 0}
    assert p.observe_pool_load(1, hot) is None
    assert p.observe_pool_load(1, hot) == ("grow_pool", None)
    # occupancy alone is pressure too
    occ = {"queue_depth": 0, "occupancy": 0.95, "rejected": 0,
           "replaced": 0}
    assert p.observe_pool_load(2, occ) is None
    assert p.observe_pool_load(2, occ) == ("grow_pool", None)
    # a rejection DELTA is pressure (cumulative counter diffed)
    p2 = _policy()
    assert p2.observe_pool_load(
        1, {"queue_depth": 0, "occupancy": 0.4, "rejected": 5,
            "replaced": 0}) is None  # baseline diff = 0, no streak
    assert p2.observe_pool_load(
        1, {"queue_depth": 0, "occupancy": 0.4, "rejected": 9,
            "replaced": 0}) is None
    assert p2.observe_pool_load(
        1, {"queue_depth": 0, "occupancy": 0.4, "rejected": 12,
            "replaced": 0}) == ("grow_pool", None)
    # shrink needs twice the evidence
    p3 = _policy()
    idle = {"queue_depth": 0, "occupancy": 0.1, "rejected": 0,
            "replaced": 0}
    for _ in range(3):
        assert p3.observe_pool_load(2, idle) is None
    assert p3.observe_pool_load(2, idle) == ("shrink_pool", None)
    # never below min_pools
    p4 = _policy()
    for _ in range(8):
        assert p4.observe_pool_load(1, idle) is None
    # replacement burst suppresses and resets the streaks
    p5 = _policy()
    assert p5.observe_pool_load(1, hot) is None
    assert p5.observe_pool_load(
        1, {"queue_depth": 3, "occupancy": 0.5, "rejected": 0,
            "replaced": 2}) is None
    assert p5.observe_pool_load(1, hot) is None  # streak restarted
    assert p5.observe_pool_load(1, hot) == ("grow_pool", None)


def test_one_action_budget_governs_all_three_axes():
    """ONE policy instance spans trainers, pservers, and pools: every
    action draws from the same _RestartPolicy budget, so exhausting it
    on any mix of axes silences the rest — three loops cannot fight."""
    p = _ScalingPolicy(1, 4, cooldown_s=0.0, hysteresis=1,
                       min_ps=1, max_ps=4, min_pools=1, max_pools=4,
                       budget=_RestartPolicy(max_restarts=2,
                                             window_s=60.0,
                                             backoff_s=0.0))
    hot_pool = {"queue_depth": 3, "occupancy": 0.2, "rejected": 0,
                "replaced": 0}
    hot_ps = {"queue_depth": 9, "staleness_parks": 0,
              "stale_plan_drops": 0}
    assert p.observe_pool_load(1, hot_pool) == ("grow_pool", None)
    assert p.observe_ps_load(1, hot_ps, n_trainers=2) == ("grow_ps",
                                                          None)
    # budget (2 actions / window) exhausted: the TRAINER axis is
    # silenced by pool+pserver spend, and vice versa
    assert p.observe_pool_load(2, hot_pool) is None
    assert p.decide({"trainer.0", "trainer.1"},
                    {"trainer.0": 1.0, "trainer.1": 1.0}) is None


def test_no_flap_when_two_axes_disagree():
    """Axes pulling OPPOSITE directions in one window produce at most
    ONE action: the shared cooldown serializes them, so the fabric
    cannot grow pools while the pserver axis shrinks servers in the
    same breath (and re-observation later still works)."""
    p = _ScalingPolicy(1, 4, cooldown_s=3600.0, hysteresis=1,
                       min_ps=1, max_ps=4, min_pools=1, max_pools=4,
                       budget=_RestartPolicy(max_restarts=8,
                                             window_s=60.0,
                                             backoff_s=0.0))
    # manufacture an expired cooldown for the FIRST action only
    p._last_action -= 7200.0
    idle_ps = {"queue_depth": 0, "staleness_parks": 0,
               "stale_plan_drops": 0}
    hot_pool = {"queue_depth": 5, "occupancy": 0.9, "rejected": 0,
                "replaced": 0}
    # pserver axis wants to shrink (sustained idle)...
    assert p.observe_ps_load(3, idle_ps, n_trainers=2) is None
    act = p.observe_ps_load(3, idle_ps, n_trainers=2)
    assert act == ("shrink_ps", None)
    # ...pool axis wants to grow RIGHT NOW: cooldown says no
    assert p.observe_pool_load(1, hot_pool) is None
    assert p.observe_pool_load(1, hot_pool) is None


def test_pool_kill_action_validation():
    """`pool_kill` (and its pinned `pool_kill:<pid>` form) is a
    fabric-direction action; wire directions reject it, and wire faults
    reject the fabric direction — a schedule typo fails loudly at
    construction, not silently mid-chaos."""
    FaultSchedule({"fabric": {3: "pool_kill"}})
    FaultSchedule({"fabric": {3: "pool_kill:2", 5: "pass"}})
    with pytest.raises(ValueError, match="not valid"):
        FaultSchedule({"c2s": {3: "pool_kill"}})
    with pytest.raises(ValueError, match="not valid"):
        FaultSchedule({"fabric": {3: "drop"}})
    with pytest.raises(ValueError, match="unknown fault action"):
        FaultSchedule({"fabric": {3: "pool_kill:x"}})
    with pytest.raises(ValueError, match="direction"):
        FaultSchedule({"sideways": {0: "pass"}})


# ---------------------------------------------------------------------------
# cross-pool placement: heterogeneous capacities (in-process, fast)
# ---------------------------------------------------------------------------
def _sized_pool_factory(n_slots=2, width=4, seed=7, t_max=T_MAX):
    """Like _pool_factory but with a per-pool t_max: heterogeneous
    capacities are what cross-pool placement keys off."""

    def factory():
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            _, lm_startup, _, _ = gpt2.gpt2_logits_program(
                TinyHP, seq_len=t_max)
            exe = fluid.Executor(fluid.CPUPlace())
            lm_startup.random_seed = seed
            exe.run(lm_startup)
            eng = ServingEngine(exe, TinyHP, n_slots=n_slots,
                                width=width, t_max=t_max)
        return eng, scope

    return factory


def _hetero_router(queue_depth=8):
    """One SMALL pool (t_max=12) + one BIG pool (t_max=24)."""
    router = FabricRouter(_sized_pool_factory(t_max=12), n_pools=1,
                          queue_depth=queue_depth)
    router.pool_factory = _sized_pool_factory(t_max=T_MAX)
    big = router.add_pool()
    return router, big


@pytest.mark.slow  # ~8s engine builds; rides the ci.sh fabric lane
def test_cross_pool_placement_long_request_keys_to_big_pool():
    """A long-context request fits ONLY the big pool and lands there;
    a short one prefers the SMALLEST fitting pool (best-fit keeps the
    big pool free for requests only it can hold)."""
    router, big = _hetero_router()
    small = [pid for pid in router.pools if pid != big][0]
    long_req = Request(rid="L", prompt=np.arange(1, 13),
                       max_new_tokens=12, arrival=0.0)  # 24 > 12+1
    short_req = Request(rid="S", prompt=np.arange(1, 5),
                        max_new_tokens=4, arrival=0.0)
    router.submit(long_req)
    router.submit(short_req)
    router.step()
    placed = {pid: {s.req.rid for _, s in
                    h.engine.pool.active_slots()}
              for pid, h in router.pools.items()}
    assert "L" in placed[big] and "L" not in placed[small]
    assert "S" in placed[small]


@pytest.mark.slow  # ~8s engine builds; rides the ci.sh fabric lane
def test_cross_pool_submit_rejects_when_no_pool_fits():
    """A request bigger than EVERY pool is rejected at submit with the
    reason in the error — never silently truncated, never queued to
    wait for a pool that cannot exist."""
    router, _ = _hetero_router()
    too_big = Request(rid="XXL", prompt=np.arange(1, 20),
                      max_new_tokens=20, arrival=0.0)
    with pytest.raises(ValueError, match="capacity"):
        router.submit(too_big)
    assert not router.queue


@pytest.mark.slow  # ~8s engine builds; rides the ci.sh fabric lane
def test_cross_pool_no_fit_after_big_pool_dies_is_loud():
    """The ONLY pool that could hold a queued long request dies before
    placement: the request terminates REJECTED_NO_FIT at the next
    placement pass — reject-with-reason, not an unbounded wait."""
    router, big = _hetero_router()
    long_req = Request(rid="L", prompt=np.arange(1, 13),
                       max_new_tokens=12, arrival=0.0)
    router.submit(long_req)
    router.kill_pool(big)
    for _ in range(8):  # death declared after miss_beats, then place
        router.step()
        if "L" in router._results:
            break
    assert router._results["L"]["status"] == "REJECTED_NO_FIT"
    assert router.counters["rejected"] == 1


@pytest.mark.slow  # ~8s engine builds; rides the ci.sh fabric lane
def test_prefix_aware_placement_prefers_resident_pool():
    """The placement fix (docs/SERVING.md §8): the raw best-fit key
    len(prompt)+max_new overestimates footprint for prefix-hit
    requests, so the score now consults the prefix match — a request
    opening with a registered template lands on the pool HOLDING that
    prefix (less remaining work) even when tie-breaks would otherwise
    send it elsewhere; cold traffic keeps the old ordering."""

    def plain():
        return _pool_factory(n_slots=2)()

    def with_prefix():
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            _, lm_startup, _, _ = gpt2.gpt2_logits_program(
                TinyHP, seq_len=T_MAX)
            exe = fluid.Executor(fluid.CPUPlace())
            lm_startup.random_seed = 7
            exe.run(lm_startup)
            eng = ServingEngine(exe, TinyHP, n_slots=2, width=4,
                                t_max=T_MAX, prefix_rows=2)
        return eng, scope

    router = FabricRouter(plain, n_pools=1, queue_depth=8)
    router.pool_factory = with_prefix
    pfx_pid = router.add_pool()
    plain_pid = [p for p in router.pools if p != pfx_pid][0]
    tmpl = np.arange(2, 10).astype("int64")  # 8 tokens = 2 chunks
    rows = router.register_prefix(tmpl)
    assert list(rows) == [pfx_pid]  # the plain pool has no cache
    assert router.stats()["prefixes_registered"] == 1
    hit = Request(rid="hit", prompt=np.concatenate(
        [tmpl, np.array([11, 12, 13], "int64")]), max_new_tokens=4,
        arrival=0.0)
    cold = Request(rid="cold", prompt=np.arange(20, 26).astype("int64"),
                   max_new_tokens=4, arrival=0.0)
    router.submit(hit)
    router.submit(cold)
    router.step()
    placed = {pid: {s.req.rid for _, s in h.engine.pool.active_slots()}
              for pid, h in router.pools.items()}
    # the template request followed its prefix; the cold one kept the
    # old pid tie-break (equal est_work everywhere)
    assert "hit" in placed[pfx_pid], placed
    assert "cold" in placed[plain_pid], placed
    results, stats = router.run([])
    assert {r["status"] for r in results.values()} == {"OK"}
    # the stats verb surfaces the per-pool fast-path counters
    pp = stats["pools"][str(pfx_pid)]
    assert pp["prefix_hits"] == 1 and pp["prefix_tokens_reused"] == 8
    assert "accept_rate" in pp and "spec_proposed" in pp
    # a pool added AFTER registration gets the prefix replayed
    router.pool_factory = with_prefix
    late_pid = router.add_pool()
    late = router.pools[late_pid]
    with fluid.scope_guard(late.scope):
        assert any(np.array_equal(t, tmpl) for t in
                   late.engine.prefix.registered().values())


def test_call_policy_bounded_retry_and_verb_deadlines():
    """CallPolicy: per-verb deadlines override the default; transport
    failures retry up to `attempts` within the deadline and surface as
    ONE ConnectionError naming the policy; remote application errors
    (RuntimeError from {"__error__": ...}) are NEVER retried."""
    from paddle_tpu.distributed.rpc import CallPolicy

    pol = CallPolicy(timeout_s=1.0, deadline_s=0.5, attempts=3,
                     backoff_base=0.01, backoff_cap=0.02,
                     verb_deadlines={"submit": 0.1})
    assert pol.deadline_for("submit") == 0.1
    assert pol.deadline_for("step") == 0.5
    calls = []

    class _Down:
        endpoint = "10.0.0.1:9"

        def call(self, verb, timeout_s=None, deadline_s=None, **kw):
            calls.append(verb)
            raise ConnectionError("refused")

    with pytest.raises(ConnectionError, match="policy deadline"):
        pol.call(_Down(), "step")
    assert len(calls) == 3  # bounded: exactly `attempts`, then done

    class _Remote:
        endpoint = "10.0.0.1:9"

        def call(self, verb, timeout_s=None, deadline_s=None, **kw):
            calls.append("remote")
            raise RuntimeError("unknown verb")

    calls.clear()
    with pytest.raises(RuntimeError, match="unknown verb"):
        pol.call(_Remote(), "step")
    assert calls == ["remote"]  # retrying a bug only hides it


def test_request_wire_round_trip_preserves_schedule_and_sampling():
    """Request.to_wire/from_wire: the ProcessPool submit boundary must
    preserve every schedule AND sampling key bit-exact, or the
    cross-process exactness contract breaks at serialization."""
    r = Request(rid="w1", prompt=np.arange(1, 6), max_new_tokens=4,
                temperature=0.9, top_k=8, top_p=0.9, seed=11,
                eos_id=2, arrival=1.5, deadline=9, sample_step_base=3)
    r2 = Request.from_wire(r.to_wire())
    np.testing.assert_array_equal(r2.prompt, r.prompt)
    for k in ("rid", "max_new_tokens", "temperature", "top_k", "top_p",
              "seed", "eos_id", "arrival", "deadline",
              "sample_step_base"):
        assert getattr(r2, k) == getattr(r, k), k
    g = Request(rid="w2", prompt=np.arange(1, 3), max_new_tokens=2)
    assert Request.from_wire(g.to_wire()).greedy


# ---------------------------------------------------------------------------
# process-pool mode: REAL worker processes over RPC (docs/SERVING.md §7)
# ---------------------------------------------------------------------------
_HP_WIRE = {"vocab_size": 61, "n_ctx": 32, "d_model": 32, "n_layer": 2,
            "n_head": 4, "dropout": 0.0}


def _proc_policy():
    from paddle_tpu.distributed.rpc import CallPolicy

    return CallPolicy(timeout_s=2.0, deadline_s=4.0, attempts=2,
                      verb_deadlines={"submit": 2.0, "shutdown": 1.0})


def _worker_factory(n_slots=2):
    from paddle_tpu.serving import spawn_pool_worker

    def factory():
        return spawn_pool_worker(hp_overrides=_HP_WIRE, n_slots=n_slots,
                                 width=4, t_max=T_MAX, seed=7)

    return factory


def _close_procs(router):
    """Retire every remaining worker (shutdown verb, not SIGKILL) and
    return the Popen handles so tests can assert clean exits."""
    procs = [h.engine.proc for h in router.pools.values()
             if getattr(h.engine, "proc", None) is not None]
    for h in list(router.pools.values()):
        h.engine.close(kill=False)
    return procs


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["greedy", "sampled"])
def test_process_pool_sigkill_midstream_stream_stays_solo_exact(mode):
    """ACCEPTANCE: a request in flight on a REAL worker process when
    that worker is SIGKILL'd finishes token-identical to its solo run —
    greedy and seeded-sampled.  Death is detected by the bounded RPC
    policy (never a hang); the emitted prefix replays on a survivor."""
    rng = np.random.RandomState(5 if mode == "greedy" else 6)
    reqs = [Request(rid=i,
                    prompt=rng.randint(1, 61, 5).astype("int64"),
                    max_new_tokens=10,
                    temperature=1.0 if mode == "greedy" else 0.9,
                    top_k=0 if mode == "greedy" else 8,
                    seed=None if mode == "greedy" else 1000 + i,
                    arrival=0.0)
            for i in range(4)]
    faults = FaultSchedule(schedule={"fabric": {4: "pool_proc_kill"}},
                           seed=5)
    router = FabricRouter(_worker_factory(), n_pools=2, queue_depth=16,
                          pool_mode="process",
                          rpc_policy=_proc_policy(),
                          fault_schedule=faults, miss_beats=2)
    try:
        results, stats = router.run(list(reqs))
    finally:
        _close_procs(router)
    assert stats["pools_died"] == 1 and stats["replaced"] >= 1
    assert stats["finished"] == 4 and stats["rejected"] == 0
    eng, scope = _pool_factory(n_slots=4)()
    with fluid.scope_guard(scope):
        for r in reqs:
            ref, _ = eng.run_solo(r)
            got = np.asarray(results[r.rid]["tokens"])
            assert np.array_equal(np.asarray(ref), got), (
                "rid %r (%s) diverged from solo after SIGKILL failover"
                % (r.rid, mode))


@pytest.mark.slow
def test_process_pool_drain_and_retire_no_orphan_worker():
    """drain_pool on a REAL worker: in-flight requests finish on their
    slots, retirement sends the shutdown verb, and the worker process
    EXITS cleanly — no orphan to leak past the test run."""
    router = FabricRouter(_worker_factory(), n_pools=2, queue_depth=32,
                          pool_mode="process",
                          rpc_policy=_proc_policy())
    procs = {h.pid: h.engine.proc for h in router.pools.values()}
    args = (8, 1.0, 7)
    for r in _trace(*args):
        router.submit(r)
    drained = None
    while router.queue or any(h.engine.queue
                              or h.engine.pool.active_slots()
                              for h in router.pools.values()):
        router.step()
        if router.now == 3:
            drained = sorted(router.pools)[0]
            router.drain_pool(drained)
        assert router.now < 3000
    assert drained is not None and drained not in router.pools
    assert procs[drained].wait(timeout=30) == 0, \
        "retired worker did not exit cleanly"
    results = dict(router._results)
    assert {r["status"] for r in results.values()} == {"OK"}
    _assert_solo_exact(results, args)
    for p in _close_procs(router):
        assert p.wait(timeout=30) == 0


@pytest.mark.slow
def test_process_pool_backpressure_rejects_loudly_over_rpc(capsys):
    """Fabric backpressure in process mode: overflow past queue_depth
    is a loud REJECTED_QUEUE_FULL even though admission now crosses an
    RPC hop — the router's queue is still THE fabric queue, and the
    worker's own queue never buffers past known-free slots."""
    router = FabricRouter(_worker_factory(n_slots=2), n_pools=1,
                          queue_depth=2, pool_mode="process",
                          rpc_policy=_proc_policy())
    burst = [Request(rid=i, prompt=np.arange(1, 5), max_new_tokens=6,
                     arrival=0.0) for i in range(8)]
    try:
        results, stats = router.run(burst)
    finally:
        _close_procs(router)
    st = [results[i]["status"] for i in range(8)]
    assert st.count("REJECTED_QUEUE_FULL") == 4  # 2 slots + 2 waiting
    assert st.count("OK") == 4
    assert stats["rejected"] == 4
    for i in range(8):
        if results[i]["status"] == "OK":
            assert len(results[i]["tokens"]) == 6
    assert "REJECTED_QUEUE_FULL" in capsys.readouterr().out


@pytest.mark.slow
def test_process_pool_supervisor_respawn_within_budget():
    """The supervisor loop in miniature over the REAL control plane: a
    worker SIGKILL'd from outside is death-reported over RPC (beating
    the detection deadline), ONE respawn is drawn from the
    _RestartPolicy budget, and the replacement attaches via the
    attach_worker verb — every stream still finishes solo-exact."""
    import os
    import signal

    from paddle_tpu.distributed.rpc import RPCClient

    factory = _worker_factory()
    router = FabricRouter(factory, n_pools=2, queue_depth=32,
                          pool_mode="process",
                          rpc_policy=_proc_policy())
    srv = router.serve_control("127.0.0.1:0")
    budget = _RestartPolicy(max_restarts=2, window_s=60.0,
                            backoff_s=0.0)
    args = (10, 1.0, 9)
    for r in _trace(*args):
        router.submit(r)
    cli = RPCClient(srv.endpoint, timeout=5, retries=2)
    respawned = False
    try:
        while router.queue or any(h.engine.queue
                                  or h.engine.pool.active_slots()
                                  for h in router.pools.values()):
            router.step()
            if router.now == 3 and not respawned:
                victim = sorted(router.pools)[0]
                h = router.pools[victim]
                os.kill(h.engine.worker_pid, signal.SIGKILL)
                assert budget.next_delay() is not None  # draw 1 of 2
                r = cli.call("report_pool_death",
                             endpoint=h.engine.endpoint)
                assert r["ok"] and r["found"]
                new_ep, proc = factory()
                r2 = cli.call("attach_worker", endpoint=new_ep)
                assert r2["ok"]
                # launch.py holds the child Popen itself; tests park it
                # on the handle so cleanup can assert a clean exit
                router.pools[r2["pid"]].engine.proc = proc
                respawned = True
            assert router.now < 3000
    finally:
        cli.close()
        srv.shutdown()
        _close_procs(router)
    assert respawned
    stats = router.stats()
    assert stats["pools_died"] == 1
    results = dict(router._results)
    assert {r["status"] for r in results.values()} == {"OK"}
    _assert_solo_exact(results, args)
    assert budget.next_delay() is not None  # draw 2 of 2...
    assert budget.next_delay() is None      # ...budget exhausted


@pytest.mark.slow
def test_process_pool_sigkill_with_spec_and_prefix_stays_solo_exact():
    """ACCEPTANCE (docs/SERVING.md §8): the fast path survives chaos —
    REAL worker processes with self-draft speculation AND a prefix
    cache armed (registered fabric-wide over the register_prefix verb),
    one worker SIGKILL'd mid-stream.  Every stream — greedy and seeded
    sampled, template-opening and cold — finishes token-identical to
    its solo run on a spec engine, greedy streams also identical to the
    plain non-spec engine, and the surviving pool's stats report the
    acceptance/prefix counters through the stats verb."""

    def factory():
        from paddle_tpu.serving import spawn_pool_worker

        return spawn_pool_worker(hp_overrides=_HP_WIRE, n_slots=2,
                                 width=4, t_max=T_MAX, seed=7,
                                 spec_k=3, prefix_rows=2)

    rng = np.random.RandomState(13)
    tmpl = rng.randint(1, 61, 8).astype("int64")
    reqs = []
    for i in range(4):
        tail = rng.randint(1, 61, 3).astype("int64")
        prompt = (np.concatenate([tmpl, tail]) if i < 3
                  else rng.randint(1, 61, 6).astype("int64"))
        reqs.append(Request(
            rid=i, prompt=prompt, max_new_tokens=8,
            temperature=1.0 if i % 2 == 0 else 0.9,
            top_k=0 if i % 2 == 0 else 8,
            seed=None if i % 2 == 0 else 1000 + i, arrival=0.0))
    faults = FaultSchedule(schedule={"fabric": {1: "pool_proc_kill"}},
                           seed=5)
    router = FabricRouter(factory, n_pools=2, queue_depth=16,
                          pool_mode="process",
                          rpc_policy=_proc_policy(),
                          fault_schedule=faults, miss_beats=2)
    rows = router.register_prefix(tmpl)
    assert sorted(rows) == sorted(router.pools)  # both workers took it
    try:
        results, stats = router.run(list(reqs))
    finally:
        _close_procs(router)
    assert stats["pools_died"] == 1 and stats["replaced"] >= 1
    assert stats["finished"] == 4 and stats["rejected"] == 0
    assert stats["prefixes_registered"] == 1
    # the survivor's fast-path counters flow through the stats verb
    # (mirrored from the worker's step replies)
    (survivor,) = stats["pools"].values()
    assert survivor["prefix_hits"] >= 1
    assert survivor["spec_proposed"] > 0
    assert 0.0 < survivor["accept_rate"] <= 1.0
    # solo reference: an in-process engine with the SAME spec config
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        _, lm_startup, _, _ = gpt2.gpt2_logits_program(
            TinyHP, seq_len=T_MAX)
        exe = fluid.Executor(fluid.CPUPlace())
        lm_startup.random_seed = 7
        exe.run(lm_startup)
        eng = ServingEngine(exe, TinyHP, n_slots=4, width=4,
                            t_max=T_MAX, draft="self", spec_k=3)
        for r in reqs:
            ref, _ = eng.run_solo(r)
            got = np.asarray(results[r.rid]["tokens"])
            assert np.array_equal(np.asarray(ref), got), (
                "rid %r diverged from spec solo after SIGKILL failover"
                % (r.rid,))
        # greedy spec == the plain engine too (argmax is prefix-pure)
        plain = ServingEngine(exe, TinyHP, n_slots=4, width=4,
                              t_max=T_MAX)
        for r in reqs:
            if not r.greedy:
                continue
            ref, _ = plain.run_solo(r)
            assert np.array_equal(
                np.asarray(ref),
                np.asarray(results[r.rid]["tokens"])), (
                "rid %r: greedy spec diverged from non-spec" % (r.rid,))
