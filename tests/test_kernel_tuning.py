"""Per-(kernel, shape-bucket) tuning cache (ops/kernel_tuning.py): seed/
hit/search semantics, JSON persistence + reload, pinned consult-only
mode, shape bucketing, corrupt-file tolerance, and the attribution
counters bench.py reads."""

import json
import os

import numpy as np
import pytest

from paddle_tpu import flags
from paddle_tpu.ops import kernel_tuning as kt


@pytest.fixture(autouse=True)
def _fresh_cache():
    """Every test starts with an empty in-memory cache and default
    flags; restores both afterwards."""
    kt.clear_cache(forget_path=True)
    kt.reset_attribution()
    old = {k: flags.get_flag(k) for k in ("kernel_tune_cache",
                                          "kernel_autotune")}
    yield
    flags.set_flags(old)
    kt.clear_cache(forget_path=True)
    kt.reset_attribution()


def test_miss_seeds_default_then_hits():
    flags.set_flags({"kernel_tune_cache": ""})
    default = {"block_rows": 256}
    got = kt.tuned_params("ln", [(64, 128)], "float32", [], default)
    assert got == default
    got2 = kt.tuned_params("ln", [(64, 128)], "float32", [],
                           {"block_rows": 999})
    # second consult is a HIT on the seeded entry, not the new default
    assert got2 == default
    stats = kt.attribution()["tuning"]
    assert stats["misses"] == 1 and stats["hits"] == 1
    assert stats["searches"] == 0  # interpret mode never searches


def test_injected_measure_searches_picks_best_and_persists(tmp_path):
    path = str(tmp_path / "tune.json")
    flags.set_flags({"kernel_tune_cache": path, "kernel_autotune": True})
    costs = {8: 3.0, 16: 1.0, 32: 2.0}
    cands = [{"block_rows": b} for b in (8, 16, 32)]
    got = kt.tuned_params(
        "ln", [(64, 128)], "float32", cands, {"block_rows": 8},
        measure=lambda p: costs[p["block_rows"]])
    assert got == {"block_rows": 16}
    stats = kt.attribution()["tuning"]
    assert stats["searches"] == 1 and stats["search_ms"] >= 0.0

    # persisted: a fresh process (simulated by dropping the in-memory
    # cache) reloads the searched decision from disk
    assert os.path.exists(path)
    raw = json.load(open(path))
    assert any(v.get("searched") for v in raw["entries"].values())
    kt.clear_cache(forget_path=True)
    got2 = kt.tuned_params(
        "ln", [(64, 128)], "float32", cands, {"block_rows": 8},
        measure=lambda p: (_ for _ in ()).throw(AssertionError(
            "a reloaded entry must not re-search")))
    assert got2 == {"block_rows": 16}


def test_autotune_off_is_consult_only(tmp_path):
    """The CI regime: a pinned cache + FLAGS_kernel_autotune=0 — misses
    seed the default and NEVER search, and the pinned file stays
    untouched (only searched decisions persist)."""
    path = str(tmp_path / "pinned.json")
    json.dump({"version": 1, "entries": {}}, open(path, "w"))
    before = open(path).read()
    flags.set_flags({"kernel_tune_cache": path, "kernel_autotune": False})
    got = kt.tuned_params(
        "flash", [(4, 64, 16)], "float32",
        [{"block_q": 128}], {"block_q": 64},
        measure=lambda p: (_ for _ in ()).throw(AssertionError(
            "autotune off must not measure")))
    assert got == {"block_q": 64}
    assert open(path).read() == before


def test_candidate_errors_are_skipped():
    """A candidate whose measurement raises (illegal block shapes
    surface as compile errors) is skipped, not fatal."""
    flags.set_flags({"kernel_tune_cache": "", "kernel_autotune": True})

    def measure(p):
        if p["b"] == 1:
            raise RuntimeError("mosaic says no")
        return float(p["b"])

    got = kt.tuned_params("k", [(8, 8)], "float32",
                          [{"b": 1}, {"b": 3}, {"b": 2}], {"b": 9},
                          measure=measure)
    assert got == {"b": 2}


def test_shape_bucket_rounds_leading_dims_only():
    # leading (row/batch) dims bucket to the next pow2; last dim exact
    assert kt.shape_bucket([(100, 768)]) == "128x768"
    assert kt.shape_bucket([(128, 768)]) == "128x768"
    assert kt.shape_bucket([(3, 5, 96)]) == "4x8x96"
    assert kt.shape_bucket([(7,)]) == "7"
    # multiple operands join deterministically
    assert kt.shape_bucket([(100, 64), (64, 50)]) == "128x64,64x50"
    # same bucket -> same key -> one search serves the whole bucket
    flags.set_flags({"kernel_tune_cache": ""})
    kt.tuned_params("mm", [(100, 64)], "float32", [], {"bm": 1})
    kt.tuned_params("mm", [(128, 64)], "float32", [], {"bm": 2})
    stats = kt.attribution()["tuning"]
    assert stats["misses"] == 1 and stats["hits"] == 1


def test_corrupt_cache_file_starts_empty(tmp_path, capsys):
    path = str(tmp_path / "broken.json")
    open(path, "w").write("{not json")
    flags.set_flags({"kernel_tune_cache": path})
    got = kt.tuned_params("ln", [(8, 8)], "float32", [], {"b": 5})
    assert got == {"b": 5}
    assert "unreadable" in capsys.readouterr().err


def test_attribution_counters_and_reset():
    kt.note_kernel("attention")
    kt.note_kernel("attention")
    kt.note_kernel("xent")
    att = kt.attribution()
    assert att["pallas_hits"] == {"attention": 2, "xent": 1}
    kt.reset_attribution()
    att = kt.attribution()
    assert att["pallas_hits"] == {} and att["tuning"]["hits"] == 0


def test_device_kind_isolates_interpret_entries():
    """Interpret-mode (CPU) cache keys carry their own device universe,
    so a CI cache can never leak block sizes onto a real chip."""
    assert kt._device_kind().startswith("interpret-")


def test_measure_candidate_builds_and_times():
    """The real-device measurement helper runs a jitted candidate over
    synthetic operands and returns seconds."""
    import jax.numpy as jnp

    bench = kt.measure_candidate(
        lambda p: (lambda x: x * p["s"]), [((8, 8), "float32")],
        warmup=1, iters=3)
    t = bench({"s": 2.0})
    assert t >= 0.0


def test_search_candidate_traces_do_not_tick_hit_counters():
    """Regression (review finding): candidate timing re-traces kernel
    bodies; those traces must not inflate the per-family pallas-hit
    attribution bench.py reports."""
    flags.set_flags({"kernel_tune_cache": "", "kernel_autotune": True})

    def measure(p):
        kt.note_kernel("attention")  # what a candidate trace would do
        return float(p["b"])

    kt.tuned_params("flash", [(8, 8)], "float32",
                    [{"b": 1}, {"b": 2}, {"b": 3}], {"b": 1},
                    measure=measure)
    assert kt.attribution()["pallas_hits"].get("attention", 0) == 0
    # outside a search the counter ticks normally again
    kt.note_kernel("attention")
    assert kt.attribution()["pallas_hits"]["attention"] == 1


def test_seeded_entries_never_persist_alongside_searched(tmp_path):
    """Regression (review finding): a later search's save must not drag
    in-memory SEEDED entries onto disk — a seeded default frozen into
    the persisted cache would pin its kernel to the unmeasured
    heuristic forever (the next process hits instead of re-searching)."""
    path = str(tmp_path / "tune.json")
    flags.set_flags({"kernel_tune_cache": path, "kernel_autotune": True})
    # a search whose candidates ALL fail -> seeded fallback entry
    kt.tuned_params("broken", [(8, 8)], "float32", [{"b": 1}], {"b": 7},
                    measure=lambda p: (_ for _ in ()).throw(
                        RuntimeError("transient")))
    # a successful search elsewhere triggers the save
    kt.tuned_params("fine", [(8, 8)], "float32", [{"b": 2}], {"b": 9},
                    measure=lambda p: 1.0)
    raw = json.load(open(path))
    assert all(v.get("searched") for v in raw["entries"].values())
    assert not any("broken" in k for k in raw["entries"])
    # a fresh process re-searches the failed kernel (now healthy)
    kt.clear_cache(forget_path=True)
    got = kt.tuned_params("broken", [(8, 8)], "float32", [{"b": 1}],
                          {"b": 7}, measure=lambda p: 1.0)
    assert got == {"b": 1}
